"""Deep end-to-end flow stories over real HTTP (reference analogue: the
e2e/ Playwright suites — provider-flows, setup-flow, API CRUD smoke).
Each test drives one subsystem through its full user-visible arc rather
than a single endpoint."""

import os
import stat
import time

import pytest

from tests.conftest import http_req as req


@pytest.fixture()
def server(http_server):
    return http_server


# ---- provider login session over REST (reference: provider-auth.ts
# session lifecycle driven from the dashboard) ----

MOCK_LOGIN = """#!/usr/bin/env -S python3 -E -S
import sys, time
print("Opening browser to https://auth.example.com/device?code=XYZ-123")
print("enter code ABCD-9876 to continue")
sys.stdout.flush()
time.sleep(0.3)
print("Login successful")
"""


def test_provider_login_flow_rest(server, tmp_path, monkeypatch):
    cli = tmp_path / "mock_claude_login.py"
    cli.write_text(MOCK_LOGIN)
    cli.chmod(cli.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("ROOM_TPU_CLAUDE_CLI", str(cli))

    status, out = req(server, "POST", "/api/providers/claude/auth/start")
    assert status == 201, out
    sid = out["data"]["sessionId"]
    assert out["data"]["provider"] == "claude"

    deadline = time.time() + 15
    view = out["data"]
    while time.time() < deadline:
        status, out = req(
            server, "GET", f"/api/providers/auth/sessions/{sid}"
        )
        assert status == 200
        view = out["data"]
        if not view["active"]:
            break
        time.sleep(0.2)
    assert view["status"] == "completed", view
    text = "\n".join(l["text"] for l in view["lines"])
    assert "Login successful" in text
    assert view["verificationUrl"] and "auth.example.com" in \
        view["verificationUrl"]


def test_provider_login_cancel_rest(server, tmp_path, monkeypatch):
    cli = tmp_path / "mock_slow_login.py"
    cli.write_text(
        "#!/usr/bin/env -S python3 -E -S\nimport time\ntime.sleep(60)\n"
    )
    cli.chmod(cli.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("ROOM_TPU_CLAUDE_CLI", str(cli))

    _, out = req(server, "POST", "/api/providers/claude/auth/start")
    sid = out["data"]["sessionId"]
    status, out = req(
        server, "POST", f"/api/providers/auth/sessions/{sid}/cancel"
    )
    assert status == 200
    deadline = time.time() + 10
    while time.time() < deadline:
        _, out = req(server, "GET",
                     f"/api/providers/auth/sessions/{sid}")
        if not out["data"]["active"]:
            break
        time.sleep(0.2)
    assert out["data"]["status"] == "canceled"


# ---- worker prompt sync round-trip (reference:
# worker-prompt-sync.ts newest-mtime-wins policy) ----

def test_prompt_sync_roundtrip_rest(server, tmp_path):
    _, out = req(server, "POST", "/api/rooms",
                 {"name": "syncroom", "workerModel": "echo"})
    rid = out["data"]["id"]

    status, out = req(server, "POST", f"/api/rooms/{rid}/prompts/export")
    assert status == 200
    paths = out["data"]["paths"]
    assert paths and all(os.path.exists(p) for p in paths)

    # edit the exported file; bump mtime into the future so file wins
    path = paths[0]
    content = open(path).read()
    assert "---" in content  # YAML frontmatter envelope
    edited = content.replace(
        content.split("---")[-1],
        "\nYou are the EDITED queen prompt from disk.\n",
    )
    open(path, "w").write(edited)
    future = time.time() + 60
    os.utime(path, (future, future))

    status, out = req(server, "POST",
                      f"/api/rooms/{rid}/prompts/import", {})
    assert status == 200

    _, out = req(server, "GET", f"/api/rooms/{rid}/workers")
    prompts = [w.get("system_prompt") or "" for w in out["data"]]
    assert any("EDITED queen prompt" in p for p in prompts), prompts


# ---- wallet withdraw fails closed offline and records nothing ----

def test_wallet_withdraw_fails_closed_offline(server):
    _, out = req(server, "POST", "/api/rooms",
                 {"name": "walletroom", "workerModel": "echo"})
    rid = out["data"]["id"]
    _, out = req(server, "GET", f"/api/rooms/{rid}/wallet")
    assert out["data"]["address"].startswith("0x")

    status, out = req(
        server, "POST", f"/api/rooms/{rid}/wallet/withdraw",
        {"to": "0x" + "22" * 20, "amount": "7"},
    )
    assert status == 503, out  # no chain RPC: refuse, don't pretend
    _, out = req(server, "GET",
                 f"/api/rooms/{rid}/wallet/transactions")
    sent = [t for t in out["data"] if t.get("status") == "confirmed"]
    assert not sent


# ---- self-modification audit + revert through the API ----

def test_selfmod_flow_rest(server):
    from room_tpu.core import selfmod, skills as skills_mod

    db = server.db
    _, out = req(server, "POST", "/api/rooms",
                 {"name": "modroom", "workerModel": "echo"})
    rid = out["data"]["id"]
    _, out = req(server, "GET", f"/api/rooms/{rid}/workers")
    wid = out["data"][0]["id"]

    sid = skills_mod.create_skill(db, "greeting", "say hello")
    audit_id = selfmod.perform_modification(
        db, room_id=rid, worker_id=wid, target_type="skill",
        target_id=sid, path=f"skills/{sid}",
        old_content="say hello", new_content="say goodbye",
        reason="flow test",
    )
    assert skills_mod.get_skill(db, sid)["content"] == "say goodbye"

    status, out = req(server, "GET", f"/api/rooms/{rid}/self-mod")
    assert status == 200
    entries = out["data"]
    assert any(e["id"] == audit_id for e in entries)

    status, out = req(server, "POST",
                      f"/api/self-mod/{audit_id}/revert", {})
    assert status == 200
    assert skills_mod.get_skill(db, sid)["content"] == "say hello"


# ---- inter-room mail arc: send → unread → reply → read ----

def test_room_messaging_flow(server):
    _, out = req(server, "POST", "/api/rooms",
                 {"name": "alpha", "workerModel": "echo"})
    a = out["data"]["id"]
    _, out = req(server, "POST", "/api/rooms",
                 {"name": "beta", "workerModel": "echo"})
    b = out["data"]["id"]

    status, out = req(server, "POST", f"/api/rooms/{a}/messages",
                      {"toRoomId": b, "subject": "hi beta",
                       "body": "shall we collaborate?"})
    assert status in (200, 201), out

    _, out = req(server, "GET", f"/api/rooms/{b}/messages")
    inbox = [m for m in out["data"] if m["subject"] == "hi beta"]
    assert inbox and inbox[0]["status"] == "unread"
    mid = inbox[0]["id"]

    status, out = req(server, "GET", f"/api/messages/{mid}")
    assert status == 200 and out["data"]["body"].startswith("shall we")

    status, out = req(server, "POST", f"/api/messages/{mid}/reply",
                      {"body": "yes, let's."})
    assert status in (200, 201), out
    status, out = req(server, "POST", f"/api/messages/{mid}/read", {})
    assert status == 200

    _, out = req(server, "GET", f"/api/rooms/{b}/messages")
    assert all(m["status"] != "unread" or m["id"] != mid
               for m in out["data"])
    # the reply landed back in alpha's inbox
    _, out = req(server, "GET", f"/api/rooms/{a}/messages")
    assert any("yes, let's." in (m.get("body") or "")
               for m in out["data"])
