"""Provider-layer tests: registry grammar, echo fake, rate-limit
detection, TPU provider tool loop (stub engine), HTTP providers (stubbed
network)."""

import threading

import pytest

from room_tpu.core import rate_limit
from room_tpu.providers import (
    ExecutionRequest, ProviderError, RateLimitExceeded,
    get_model_auth_status, get_model_provider, model_name, provider_kind,
    reset_provider_cache,
)
from room_tpu.providers.echo import EchoProvider
from room_tpu.serving.tokenizer import ByteTokenizer


# ---- registry grammar ----

def test_model_string_grammar():
    assert provider_kind(None) == "tpu"
    assert provider_kind("tpu") == "tpu"
    assert provider_kind("tpu:qwen3-coder-30b") == "tpu"
    assert provider_kind("openai:gpt-4o-mini") == "openai"
    assert provider_kind("anthropic:claude-3-5-haiku") == "anthropic"
    assert provider_kind("ollama:qwen3-coder:30b") == "ollama"
    assert provider_kind("echo") == "echo"
    assert provider_kind("qwen3-coder-30b") == "tpu"  # bare name

    assert model_name("tpu") == "qwen3-coder-30b"
    assert model_name("openai:gpt-4o-mini") == "gpt-4o-mini"
    assert model_name("ollama:qwen3-coder:30b") == "qwen3-coder:30b"


def test_registry_returns_cached_instances():
    reset_provider_cache()
    a = get_model_provider("echo")
    b = get_model_provider("echo")
    assert a is b


def test_auth_status_tpu_fail_closed(monkeypatch):
    monkeypatch.delenv("ROOM_TPU_CKPT_DIR", raising=False)
    monkeypatch.delenv("ROOM_TPU_ALLOW_RANDOM_INIT", raising=False)
    st = get_model_auth_status("tpu:qwen3-coder-30b")
    assert st["provider"] == "tpu" and not st["ready"]
    assert "checkpoint" in st["detail"]
    st2 = get_model_auth_status("tpu:tiny-moe")
    assert st2["ready"]


def test_auth_status_openai_requires_key(monkeypatch):
    monkeypatch.delenv("OPENAI_API_KEY", raising=False)
    reset_provider_cache()
    st = get_model_auth_status("openai:gpt-4o-mini")
    assert not st["ready"]
    monkeypatch.setenv("OPENAI_API_KEY", "sk-test")
    st = get_model_auth_status("openai:gpt-4o-mini")
    assert st["ready"]


# ---- echo provider ----

def test_echo_scripted_tool_calls():
    p = EchoProvider(tool_script=[("ls", {"dir": "."})],
                     responses=["done"])
    seen = []

    def on_tool(name, args):
        seen.append((name, args))
        return "file1\nfile2"

    r = p.execute(ExecutionRequest(prompt="list", on_tool_call=on_tool))
    assert r.success and r.text == "done"
    assert seen == [("ls", {"dir": "."})]
    assert r.tool_calls[0]["result"] == "file1\nfile2"


def test_echo_failure_mode():
    p = EchoProvider(fail_with="rate limit reached, try again in 5 minutes")
    r = p.execute(ExecutionRequest(prompt="x"))
    assert not r.success
    assert rate_limit.detect_rate_limit(r.error) == 300.0


# ---- rate limit parsing ----

def test_rate_limit_patterns():
    assert rate_limit.detect_rate_limit("Error 429 Too Many Requests") \
        is not None
    assert rate_limit.detect_rate_limit("all good") is None
    assert rate_limit.detect_rate_limit("usage limit reached, resets at "
                                        "2:30 PM") is not None
    # "in N minutes" parses exactly
    assert rate_limit.detect_rate_limit(
        "rate limited: retry in 10 minutes"
    ) == 600.0
    # clamped to [30s, 60min]
    assert rate_limit.detect_rate_limit(
        "rate limit: retry in 2 seconds"
    ) == 30.0
    assert rate_limit.detect_rate_limit(
        "rate limit: retry in 5 hours"
    ) == 3600.0


def test_abortable_sleep():
    ev = threading.Event()
    ev.set()
    assert rate_limit.abortable_sleep(60, ev)  # returns immediately


# ---- TPU provider tool loop over a stub engine ----

class _StubTurn:
    def __init__(self, tokens, reason):
        self.new_tokens = tokens
        self.finish_reason = reason
        self.error = None
        self.done = threading.Event()
        self.done.set()


class _StubEngine:
    """Scripted stand-in for ServingEngine: each submit() pops the next
    (text, finish_reason) pair."""

    def __init__(self, script):
        self.tokenizer = ByteTokenizer()
        self.script = list(script)
        self.sessions = {}
        self.submits = []
        self.released = []

    def release_session(self, session_id):
        self.released.append(session_id)
        self.sessions.pop(session_id, None)

    def submit(self, tokens, *, session_id=None, sampling=None,
               on_token=None, turn_class=None):
        self.submits.append((list(tokens), session_id))
        self.sessions.setdefault(session_id, object())
        text, reason = self.script.pop(0)
        return _StubTurn(self.tokenizer.encode(text), reason)

    def text_of(self, turn):
        return self.tokenizer.decode(turn.new_tokens)


@pytest.fixture()
def tpu_provider_with_stub(monkeypatch):
    from room_tpu.providers import tpu as tpu_mod

    tpu_mod.reset_model_hosts()
    host = tpu_mod.get_model_host("tiny-moe")

    def install(script):
        host._engine = _StubEngine(script)
        return host._engine

    yield tpu_mod.TpuProvider("tiny-moe"), install
    tpu_mod.reset_model_hosts()


def test_tpu_tool_loop_parks_and_resumes(tpu_provider_with_stub):
    provider, install = tpu_provider_with_stub
    eng = install([
        ('<tool_call>{"name": "search", "arguments": {"q": "tpu"}}'
         "</tool_call>", "tool_call"),
        ("The answer is 42.<|im_end|>", "stop"),
    ])

    calls = []

    def on_tool(name, args):
        calls.append((name, args))
        return "search results: 42"

    r = provider.execute(ExecutionRequest(
        prompt="find the answer",
        system_prompt="be helpful",
        on_tool_call=on_tool,
        session_id="worker-1",
    ))
    assert r.success, r.error
    assert calls == [("search", {"q": "tpu"})]
    assert r.text.endswith("The answer is 42.")
    assert r.turns_used == 2
    # second submit must be the tool response only, same session
    resume_tokens, resume_session = eng.submits[1]
    assert resume_session == "worker-1"
    resumed_text = eng.tokenizer.decode(resume_tokens)
    assert "<tool_response>" in resumed_text
    assert "search results: 42" in resumed_text
    assert "find the answer" not in resumed_text  # no re-prefill


def test_tpu_malformed_tool_call_gets_corrective_resume(
    tpu_provider_with_stub,
):
    provider, install = tpu_provider_with_stub
    eng = install([
        ("<tool_call>not json</tool_call>", "tool_call"),
        ("recovered<|im_end|>", "stop"),
    ])
    r = provider.execute(ExecutionRequest(
        prompt="x", on_tool_call=lambda n, a: "nope",
    ))
    assert r.success
    assert "recovered" in r.text
    corrective = eng.tokenizer.decode(eng.submits[1][0])
    assert "malformed tool call" in corrective


def test_tpu_max_turns_guard(tpu_provider_with_stub):
    provider, install = tpu_provider_with_stub
    install([
        ('<tool_call>{"name": "loop", "arguments": {}}</tool_call>',
         "tool_call"),
    ] * 3)
    r = provider.execute(ExecutionRequest(
        prompt="x", on_tool_call=lambda n, a: "again", max_turns=3,
    ))
    assert not r.success and "max_turns" in r.error


def test_tpu_end_to_end_tiny_model(monkeypatch):
    """Real engine, tiny model: a turn completes and a session is
    resumable (content is random; structure is what's asserted)."""
    from room_tpu.providers import tpu as tpu_mod

    tpu_mod.reset_model_hosts()
    monkeypatch.setenv("ROOM_TPU_MAX_BATCH", "2")
    monkeypatch.setenv("ROOM_TPU_N_PAGES", "64")
    provider = tpu_mod.TpuProvider("tiny-moe")
    r = provider.execute(ExecutionRequest(
        prompt="hello", session_id="w1", max_new_tokens=8,
        max_turns=1, timeout_s=300,
    ))
    assert r.success, r.error
    assert r.output_tokens > 0
    r2 = provider.execute(ExecutionRequest(
        prompt="again", session_id="w1", max_new_tokens=8,
        max_turns=1, timeout_s=300,
    ))
    assert r2.success, r2.error
    tpu_mod.reset_model_hosts()


# ---- HTTP providers with stubbed transport ----

def test_openai_compat_tool_loop(monkeypatch):
    from room_tpu.providers import http_api

    responses = [
        {
            "choices": [{
                "message": {
                    "role": "assistant",
                    "tool_calls": [{
                        "id": "c1",
                        "function": {"name": "add",
                                     "arguments": '{"a": 1, "b": 2}'},
                    }],
                },
            }],
            "usage": {"prompt_tokens": 10, "completion_tokens": 5},
        },
        {
            "choices": [{
                "message": {"role": "assistant", "content": "sum is 3"},
            }],
            "usage": {"prompt_tokens": 20, "completion_tokens": 4},
        },
    ]
    bodies = []

    def fake_post(url, body, headers, timeout):
        bodies.append(body)
        return responses.pop(0)

    monkeypatch.setattr(http_api, "_post_json", fake_post)
    monkeypatch.setenv("OPENAI_API_KEY", "sk-test")
    p = http_api.OpenAICompatProvider("openai", "gpt-4o-mini")
    r = p.execute(ExecutionRequest(
        prompt="add 1 and 2",
        tools=[{"name": "add", "parameters": {}}],
        on_tool_call=lambda n, a: str(a["a"] + a["b"]),
    ))
    assert r.success and r.text == "sum is 3"
    assert r.tool_calls[0]["result"] == "3"
    assert r.input_tokens == 30 and r.output_tokens == 9
    # second request must carry the tool result message
    roles = [m.get("role") for m in bodies[1]["messages"]]
    assert "tool" in roles


def test_openai_rate_limit_raises(monkeypatch):
    from room_tpu.providers import http_api

    def fake_post(url, body, headers, timeout):
        raise RateLimitExceeded("429 too many requests", 120.0)

    monkeypatch.setattr(http_api, "_post_json", fake_post)
    monkeypatch.setenv("OPENAI_API_KEY", "sk-test")
    p = http_api.OpenAICompatProvider("openai", "gpt-4o-mini")
    with pytest.raises(RateLimitExceeded) as e:
        p.execute(ExecutionRequest(prompt="x"))
    assert e.value.wait_s == 120.0


def test_anthropic_tool_loop(monkeypatch):
    from room_tpu.providers import http_api

    responses = [
        {
            "content": [{"type": "tool_use", "id": "t1", "name": "get",
                         "input": {"k": "v"}}],
            "usage": {"input_tokens": 5, "output_tokens": 3},
        },
        {
            "content": [{"type": "text", "text": "done"}],
            "usage": {"input_tokens": 9, "output_tokens": 2},
        },
    ]

    def fake_post(url, body, headers, timeout):
        return responses.pop(0)

    monkeypatch.setattr(http_api, "_post_json", fake_post)
    monkeypatch.setenv("ANTHROPIC_API_KEY", "sk-ant")
    p = http_api.AnthropicProvider("claude-3-5-haiku")
    r = p.execute(ExecutionRequest(
        prompt="fetch", tools=[{"name": "get", "parameters": {}}],
        on_tool_call=lambda n, a: "value",
    ))
    assert r.success and r.text == "done"
    assert r.tool_calls[0]["name"] == "get"


def test_network_unreachable_fails_closed(monkeypatch):
    from room_tpu.providers import http_api

    monkeypatch.setenv("OPENAI_API_KEY", "sk-test")
    monkeypatch.setenv("ROOM_TPU_OPENAI_BASE", "http://127.0.0.1:1")
    p = http_api.OpenAICompatProvider("openai", "gpt-4o-mini")
    r = p.execute(ExecutionRequest(prompt="x", timeout_s=2))
    assert not r.success and "unreachable" in r.error


def test_tpu_ephemeral_sessions_release_pages(tpu_provider_with_stub):
    from room_tpu.providers import tpu as tpu_mod

    provider, install = tpu_provider_with_stub
    eng = install([("one-shot<|im_end|>", "stop")])
    released = []
    eng.release_session = lambda sid: released.append(sid)
    r = provider.execute(ExecutionRequest(prompt="x"))  # no session_id
    assert r.success and released, "ephemeral session must be released"
    assert r.session_id is None


def test_system_prompt_survives_message_history(monkeypatch):
    from room_tpu.providers import http_api

    bodies = []

    def fake_post(url, body, headers, timeout):
        bodies.append(body)
        return {"choices": [{"message": {"role": "assistant",
                                        "content": "ok"}}], "usage": {}}

    monkeypatch.setattr(http_api, "_post_json", fake_post)
    monkeypatch.setenv("OPENAI_API_KEY", "sk-test")
    p = http_api.OpenAICompatProvider("openai", "gpt-4o-mini")
    p.execute(ExecutionRequest(
        prompt="next turn",
        system_prompt="you are the clerk",
        messages=[{"role": "user", "content": "old"},
                  {"role": "assistant", "content": "old reply"}],
    ))
    roles = [m["role"] for m in bodies[0]["messages"]]
    assert roles[0] == "system"
    assert bodies[0]["max_tokens"] == 1024
