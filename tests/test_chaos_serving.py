"""Chaos suite for the serving stack (docs/chaos.md).

Every fault point in room_tpu/serving/faults.py gets a targeted test
proving its recovery path (requeue, retry, degrade, or clean failure),
plus multi-threaded stress tiers that hammer submit/park/resume/release/
evict under active fault injection while asserting the two core
invariants:

  1. KV page accounting balances — after releasing every session the
     pool is back to exactly (n_pages - 1 scratch page) free, no leaks;
  2. per-session token streams stay deterministic for unfaulted
     sessions — greedy canary turns that the engine never disrupted
     (no requeue/eviction) emit exactly the clean-run stream.

The quick tier is CI-bounded (ci.yml chaos job, <=3 min); the >=30 s
soak tier runs behind the `slow` marker.
"""

import threading
import time

import jax
import pytest

from room_tpu.models import qwen3, tiny_moe
from room_tpu.serving import SamplingParams, ServingEngine, faults
from room_tpu.serving.faults import FaultError


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture(scope="module")
def model():
    cfg = tiny_moe()
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


@pytest.fixture()
def make_engine(model, monkeypatch):
    """Engine factory with the prefix cache off, so page-balance
    assertions reduce to 'every session released -> pool full'."""
    monkeypatch.setenv("ROOM_TPU_PREFIX_CACHE_PAGES", "0")
    cfg, params = model

    def build(**kw):
        kw.setdefault("max_batch", 4)
        kw.setdefault("page_size", 8)
        kw.setdefault("n_pages", 96)
        return ServingEngine(cfg, params, **kw)

    return build


def _greedy(n=8, **kw):
    return SamplingParams(temperature=0.0, max_new_tokens=n, **kw)


def _release_all(eng):
    for sid in list(eng.sessions):
        eng.release_session(sid)


def _assert_pages_balanced(eng):
    assert eng.page_table.free_pages == eng.n_pages - 1, (
        "KV page leak: only the __null__ scratch page may stay "
        f"allocated, free={eng.page_table.free_pages}/{eng.n_pages}"
    )


# ---- fault registry ----

def test_fault_env_config_and_registry():
    faults.configure_from_env(
        "kv_alloc:p=0.5;decode_stall:latency=0.1,times=3"
    )
    snap = faults.snapshot()
    assert snap["kv_alloc"]["probability"] == 0.5
    assert snap["decode_stall"]["times_remaining"] == 3
    faults.clear("kv_alloc")
    assert "kv_alloc" not in faults.snapshot()
    with pytest.raises(ValueError, match="unknown fault point"):
        faults.inject("no_such_point")
    with pytest.raises(ValueError, match="unknown fault arg"):
        faults.configure_from_env("kv_alloc:bogus=1")


def test_one_shot_budget_consumed():
    faults.inject("kv_alloc", times=2)
    fired = sum(
        1 for _ in range(10) if faults.should_fire("kv_alloc")
    )
    assert fired == 2
    assert faults.fired("kv_alloc") == 2


# ---- per-fault-point recovery paths ----

def test_kv_alloc_fault_recovers(make_engine):
    """An injected allocation failure takes the same recovery path as a
    genuinely exhausted pool (evict/requeue); the turn still ends and
    nothing leaks."""
    eng = make_engine()
    faults.inject("kv_alloc", times=1)
    turn = eng.submit([1, 2, 3], sampling=_greedy())
    eng.run_until_idle()
    assert turn.done.is_set()
    assert faults.fired("kv_alloc") == 1
    _release_all(eng)
    _assert_pages_balanced(eng)


def test_prefill_fault_retried_transparently(make_engine):
    """A transient prefill fault within the retry budget is invisible:
    same tokens as a clean run, only the retry counter moves."""
    eng = make_engine()
    clean = eng.submit([5, 6, 7], sampling=_greedy())
    eng.run_until_idle()

    faults.inject("prefill_oom", times=2)
    faulted = eng.submit([5, 6, 7], sampling=_greedy())
    eng.run_until_idle()
    assert faulted.new_tokens == clean.new_tokens
    assert eng.stats()["fault_retries"] >= 2
    _release_all(eng)
    _assert_pages_balanced(eng)


def test_prefill_fault_exhaustion_requeues(make_engine):
    """A prefill fault outliving its retry budget rolls the session
    back and requeues the turn — the next admission completes it."""
    eng = make_engine()
    clean = eng.submit([5, 6, 7], sampling=_greedy())
    eng.run_until_idle()

    # fires initial + all retries of the first admission, then clears
    faults.inject("prefill_oom", times=eng.fault_retries + 1)
    turn = eng.submit([5, 6, 7], sampling=_greedy())
    eng.run_until_idle()
    assert turn.finish_reason in ("stop", "length")
    assert turn.disrupted
    assert eng.stats()["requeues"] >= 1
    # requeued admission re-prepares from scratch: stream unchanged
    assert turn.new_tokens == clean.new_tokens
    _release_all(eng)
    _assert_pages_balanced(eng)


def test_prefill_chunk_fault_requeues_at_boundary(make_engine,
                                                  monkeypatch):
    """prefill_chunk (docs/scheduler.md): a failed interleaved chunk
    write re-queues the turn at its last DURABLE chunk boundary —
    committed chunks stay (the retry does not rewrite them), the
    stream matches the clean run, and no KV page leaks. A burst that
    outlives the requeue budget fails the turn cleanly and rolls the
    session back so a full-prompt retry is safe."""
    monkeypatch.setenv("ROOM_TPU_PREFILL_CHUNK_PAGES", "1")
    long = [1 + (i % 31) for i in range(80)]
    eng = make_engine()
    clean = eng.submit(long, sampling=_greedy())
    eng.run_until_idle()

    faults.inject("prefill_chunk", times=2)
    turn = eng.submit(long, session_id="pc", sampling=_greedy())
    eng.run_until_idle()
    faults.clear()
    assert turn.finish_reason in ("stop", "length")
    assert turn.requeues >= 1 and turn.disrupted
    assert turn.new_tokens == clean.new_tokens
    st = eng.stats()
    assert st["prefill_chunk_faults"] >= 1
    # boundary resume, not from-scratch: committed chunks were not
    # rewritten, so total chunk writes stay below 2x the chunk count
    assert st["prefill_chunks_interleaved"] < 2 * (len(long) // 8 + 1)

    # exhaustion: every chunk faults -> clean failure + rollback,
    # then an unfaulted full retry streams the clean tokens
    faults.inject("prefill_chunk")
    eng.max_requeues = 1
    dead = eng.submit(long, session_id="pc2", sampling=_greedy())
    eng.run_until_idle()
    faults.clear()
    eng.max_requeues = 3
    assert dead.finish_reason == "error"
    retry = eng.submit(long, session_id="pc2", sampling=_greedy())
    eng.run_until_idle()
    assert retry.new_tokens == clean.new_tokens
    _release_all(eng)
    _assert_pages_balanced(eng)


def test_decode_stall_watchdog_parks_and_requeues(make_engine):
    """A stalled decode step parks its sessions (KV retained) and
    requeues the turns instead of dropping them."""
    eng = make_engine()
    clean = eng.submit([9, 8, 7], sampling=_greedy(12))
    eng.run_until_idle()

    eng.step_stall_s = 0.05
    faults.inject("decode_stall", latency_s=0.2, times=2)
    turn = eng.submit([9, 8, 7], sampling=_greedy(12))
    eng.run_until_idle()
    assert turn.finish_reason in ("stop", "length")
    st = eng.stats()
    assert st["stall_events"] >= 1 and st["requeues"] >= 1
    assert turn.requeues >= 1 and turn.disrupted
    # park+requeue resumes from the pending token: stream identical
    assert turn.new_tokens == clean.new_tokens
    _release_all(eng)
    _assert_pages_balanced(eng)


def test_decode_step_fault_retried(make_engine):
    eng = make_engine()
    faults.inject("decode_step", times=1)
    turn = eng.submit([1, 2, 3], sampling=_greedy())
    eng.run_until_idle()
    assert turn.finish_reason in ("stop", "length")
    assert eng.stats()["fault_retries"] >= 1


def test_decode_window_fault_scoped_recovery(make_engine):
    """Chaos-tier coverage for the decode_window point (previously
    only in test_decode_pipeline.py; roomlint's fault-coverage
    cross-check keeps the full mapping honest). Transient window
    faults inside the retry budget are invisible to the stream; a
    non-transient one fails ONLY the faulted window's turns, and a
    canary submitted AFTER the fault decodes the clean-run stream
    with the pool balanced."""
    eng = make_engine()
    clean = eng.submit([5, 6, 7], sampling=_greedy(10))
    eng.run_until_idle()

    # within the retry budget: the stream must not notice
    faults.inject("decode_window", times=1)
    retried = eng.submit([5, 6, 7], session_id="rw",
                         sampling=_greedy(10))
    eng.run_until_idle()
    assert retried.new_tokens == clean.new_tokens
    assert eng.stats()["fault_retries"] >= 1

    # past the budget (non-transient): window-scoped failure only
    faults.inject("decode_window", times=1, transient=False)
    failed = eng.submit([5, 6, 7], session_id="fw",
                        sampling=_greedy(10))
    eng.run_until_idle()
    assert failed.finish_reason == "error"
    st = eng.stats()
    assert st["window_faults"] >= 1
    assert st["healthy"] is True and st["engine_crashes"] == 0

    # recovery canary: the engine serves identically after the fault
    canary = eng.submit([5, 6, 7], session_id="cw",
                        sampling=_greedy(10))
    eng.run_until_idle()
    assert canary.new_tokens == clean.new_tokens
    _release_all(eng)
    _assert_pages_balanced(eng)


def test_shutdown_io_drain_fails_soft(make_engine, tmp_path):
    """Chaos-tier coverage for the shutdown_io point (previously only
    in test_lifecycle.py): with EVERY lifecycle write failing, a drain
    must neither raise nor hang — warmth is lost, and the next boot
    cold-starts into a healthy serving engine whose streams match the
    clean run."""
    eng = make_engine()
    clean = eng.submit([3, 1, 4], session_id="s",
                       sampling=_greedy(10))
    eng.run_until_idle()

    faults.inject("shutdown_io")            # every write fails
    eng.drain(str(tmp_path / "d"))          # must not raise
    faults.clear("shutdown_io")

    eng2 = make_engine()
    eng2.restore_from_manifest(str(tmp_path / "d"))
    assert "s" not in eng2.sessions          # cold start, not a crash
    assert eng2.lifecycle_phase == "serving" and eng2.healthy
    turn = eng2.submit([3, 1, 4], sampling=_greedy(10))
    eng2.run_until_idle()
    assert turn.new_tokens == clean.new_tokens
    _release_all(eng2)
    _assert_pages_balanced(eng2)


def test_decode_step_nontransient_escapes_to_supervisor(make_engine):
    """A non-transient device fault is NOT retried — it propagates (the
    crash path) so the supervisor owns recovery."""
    eng = make_engine()
    faults.inject("decode_step", times=1, transient=False)
    eng.submit([1, 2, 3], sampling=_greedy())
    with pytest.raises(FaultError):
        eng.run_until_idle()


def test_deadline_exceeded_fails_cleanly(make_engine):
    eng = make_engine()
    # queued past its deadline: failed at admission, not decoded
    turn = eng.submit([1, 2, 3], sampling=_greedy(), deadline_s=0.01)
    time.sleep(0.05)
    eng.run_until_idle()
    assert turn.finish_reason == "error"
    assert "deadline" in turn.error
    assert eng.stats()["deadline_timeouts"] == 1

    # active turn crossing its deadline mid-generation: clean error,
    # the engine keeps serving others
    slow = eng.submit([4, 5, 6], sampling=_greedy(400), deadline_s=0.2)
    ok = eng.submit([7, 8, 9], sampling=_greedy())
    deadline = time.monotonic() + 30
    while not (slow.done.is_set() and ok.done.is_set()):
        eng.step()
        assert time.monotonic() < deadline
    assert ok.finish_reason in ("stop", "length")
    if slow.finish_reason == "error":       # didn't finish in 0.2 s
        assert "deadline" in slow.error
    _release_all(eng)
    _assert_pages_balanced(eng)


def test_engine_crash_supervision_restarts(make_engine):
    """serve_forever survives an injected scheduler crash: pending
    requests fail cleanly, state resets leak-free, the next submit
    serves."""
    eng = make_engine()
    stop = threading.Event()
    th = threading.Thread(
        target=eng.serve_forever, args=(stop,), daemon=True
    )
    th.start()
    try:
        faults.inject("engine_crash", times=1, transient=False)
        t1 = eng.submit([1, 2, 3], sampling=_greedy())
        assert t1.done.wait(30)
        assert t1.finish_reason == "error"
        assert "engine crashed" in t1.error
        assert eng.stats()["engine_crashes"] == 1
        assert eng.healthy

        t2 = eng.submit([4, 5, 6], sampling=_greedy())
        assert t2.done.wait(30)
        assert t2.finish_reason in ("stop", "length")
        _release_all(eng)
        time.sleep(0.2)
    finally:
        stop.set()
        th.join(5)
    _assert_pages_balanced(eng)


def test_crash_mid_admission_fails_popped_turns_cleanly(make_engine):
    """A crash AFTER a turn is popped from the queue but BEFORE it
    reaches a slot must still fail it cleanly — mid-admission turns
    are in neither _active nor _queue, and losing them would hang
    their callers on done.wait() forever."""
    eng = make_engine()
    orig = eng._prefill_group

    def boom(*a, **k):
        raise RuntimeError("injected mid-admission crash")

    eng._prefill_group = boom
    stop = threading.Event()
    th = threading.Thread(
        target=eng.serve_forever, args=(stop,), daemon=True
    )
    th.start()
    try:
        t = eng.submit([1, 2, 3], sampling=_greedy())
        assert t.done.wait(30), "mid-admission turn leaked on crash"
        assert t.finish_reason == "error"
        assert "engine crashed" in t.error
        # the supervisor recovered: admission works again
        eng._prefill_group = orig
        t2 = eng.submit([4, 5, 6], sampling=_greedy())
        assert t2.done.wait(30)
        assert t2.finish_reason in ("stop", "length")
        _release_all(eng)
        time.sleep(0.2)
    finally:
        eng._prefill_group = orig
        stop.set()
        th.join(5)
    _assert_pages_balanced(eng)


def test_engine_crash_loop_marks_unhealthy(make_engine):
    """Crashes past the restart budget mark the engine unhealthy and
    end the loop — the fail-closed signal the provider registry keys
    its fallback on."""
    eng = make_engine()
    eng.max_crash_restarts = 2
    stop = threading.Event()
    th = threading.Thread(
        target=eng.serve_forever, args=(stop,), daemon=True
    )
    th.start()
    try:
        faults.inject("engine_crash")   # every iteration crashes
        turns = [
            eng.submit([i], sampling=_greedy()) for i in range(3)
        ]
        deadline = time.monotonic() + 30
        while eng.healthy and time.monotonic() < deadline:
            time.sleep(0.05)
        assert not eng.healthy
        th.join(10)
        assert not th.is_alive()
        for t in turns:
            assert t.done.is_set() and t.finish_reason == "error"
    finally:
        faults.clear()
        stop.set()
        th.join(5)


# ---- degradation ladder ----

def test_degradation_level_from_pressure_window(make_engine):
    eng = make_engine()
    eng.degrade_window_s = 0.3
    assert eng.degradation_level() == 0
    for _ in range(eng.degrade_thresholds[0]):
        eng._note_pressure()
    assert eng.degradation_level() == 1
    for _ in range(eng.degrade_thresholds[3]):
        eng._note_pressure()
    assert eng.degradation_level() == 4
    time.sleep(0.35)                   # window drains -> recovery
    assert eng.degradation_level() == 0


def test_degradation_rung1_disables_spec(make_engine):
    """Ladder rung 1 is per-class spec-off (scheduler.SpecTuner):
    worker/background drafting stops at rung 1, queens keep theirs
    one rung longer (CLASS_GRACE)."""
    eng = make_engine(spec_tokens=4)
    # repeated prompt guarantees prompt-lookup drafts exist
    prompt = list(range(10, 20)) * 3
    eng.submit(prompt, sampling=_greedy())
    eng.run_until_idle()
    rounds0 = eng.stats()["spec_rounds"]
    assert rounds0 > 0, "sanity: spec path engages when healthy"

    eng.set_degradation(1)
    assert eng.spec_tuner.gamma_for("worker", 1) == 0
    assert eng.spec_tuner.gamma_for("background", 1) == 0
    assert eng.spec_tuner.gamma_for("queen", 1) > 0, \
        "queens keep drafting until rung 2"
    assert eng.spec_tuner.gamma_for("queen", 2) == 0
    eng.submit(prompt, sampling=_greedy())   # default class: worker
    eng.run_until_idle()
    assert eng.stats()["spec_rounds"] == rounds0, \
        "rung 1 must bypass speculation for worker turns"
    eng.set_degradation(None)


def test_degradation_rung3_halves_admission(make_engine):
    eng = make_engine()
    eng.set_degradation(3)
    for i in range(4):
        eng.submit([i + 1], sampling=_greedy())
    eng.step()
    assert eng.stats()["active_slots"] <= eng.max_batch // 2
    eng.set_degradation(None)
    eng.run_until_idle()


def test_degradation_rung4_sheds_lowest_priority(make_engine):
    eng = make_engine()
    eng.set_degradation(4)
    keep_n = eng.max_batch * 2
    low = [
        eng.submit([i + 1], sampling=_greedy(), priority=0)
        for i in range(3)
    ]
    high = [
        eng.submit([i + 1], sampling=_greedy(), priority=5)
        for i in range(keep_n)
    ]
    eng.step()
    assert all(t.shed and t.finish_reason == "error" for t in low)
    assert all("retry later" in t.error for t in low)
    assert not any(t.shed for t in high)
    assert eng.stats()["shed_turns"] == len(low)
    eng.set_degradation(None)
    eng.run_until_idle()
    _release_all(eng)
    _assert_pages_balanced(eng)


# ---- chip-aware speculation floor (ADVICE r5 satellite) ----

def test_spec_floor_uses_detected_chip(make_engine, monkeypatch):
    """The per-class tuner's default spec-off floor is the roofline
    acceptance breakeven for this model/batch/gamma shape on the chip
    the engine actually landed on (ROOM_TPU_SPEC_MIN_ACCEPT
    overrides it)."""
    from room_tpu.perf.roofline import (
        V5E, detect_chip_spec, spec_accept_floor,
    )

    # CPU test runs resolve to the documented V5E default
    assert detect_chip_spec() is V5E
    eng = make_engine(spec_tokens=4)
    assert eng.spec_tuner.floor == pytest.approx(spec_accept_floor(
        eng.cfg, eng.max_batch, 4, chip=V5E
    ))
    monkeypatch.setenv("ROOM_TPU_SPEC_MIN_ACCEPT", "0.66")
    eng2 = make_engine(spec_tokens=4)
    assert eng2.spec_tuner.floor == pytest.approx(0.66)
    assert eng2._spec_floor_fn is None, \
        "an explicit floor override must never be recalibrated"


def test_spec_floor_recalibrates_to_live_context(make_engine):
    """The roofline-derived spec-off floor is re-solved at drains
    against the batch's live mean context: at long context KV reads
    dominate verify and plain decode alike, so a floor frozen at the
    1024-token init default would throttle drafting exactly where it
    is still profitable."""
    eng = make_engine(spec_tokens=4)
    seen = []
    real = eng._spec_floor_fn
    eng._spec_floor_fn = lambda ctx: seen.append(ctx) or real(ctx)
    t = eng.submit([1, 2, 3, 4] * 8, sampling=_greedy(8))
    eng.run_until_idle()
    eng.release_session(t.session_id)
    assert seen, "no floor recalibration happened at drains"
    assert all(ctx >= 32 for ctx in seen), seen
    assert eng.spec_tuner.floor == pytest.approx(real(seen[-1]))


# ---- provider stack ----

@pytest.fixture(scope="module")
def tpu_host(model):
    import os

    os.environ.setdefault("ROOM_TPU_MAX_BATCH", "4")
    from room_tpu.providers.tpu import get_model_host

    host = get_model_host("tiny-moe")
    yield host


def test_tokenizer_fault_fails_cleanly(tpu_host):
    from room_tpu.providers.base import ExecutionRequest, ProviderError

    provider_req = ExecutionRequest(
        prompt="hi", model="tpu:tiny-moe", max_new_tokens=8,
        timeout_s=60,
    )
    from room_tpu.providers.tpu import TpuProvider

    provider = TpuProvider("tiny-moe")
    engine = tpu_host.engine()
    before = len(engine.sessions)

    # within the retry budget: transparent
    faults.inject("tokenizer", times=1)
    result = provider.execute(provider_req)
    assert result.success

    # past the budget: clean ProviderError, no session leaked
    faults.inject("tokenizer")
    with pytest.raises(ProviderError, match="tokenizer failed"):
        provider.execute(provider_req)
    faults.clear()
    time.sleep(0.3)   # deferred releases drain on the engine thread
    assert len(engine.sessions) <= before


def test_provider_timeout_fault_releases_session(tpu_host):
    from room_tpu.providers.base import ExecutionRequest
    from room_tpu.providers.tpu import TpuProvider

    provider = TpuProvider("tiny-moe")
    faults.inject("provider_timeout", times=1)
    result = provider.execute(ExecutionRequest(
        prompt="hi", model="tpu:tiny-moe", max_new_tokens=8,
        timeout_s=60,
    ))
    assert not result.success
    assert "timeout" in result.error
    # the (possibly still queued) turn finishes on the engine thread,
    # then the deferred release frees its pages — queued turns must
    # hold the release open, not let admission recreate the session
    engine = tpu_host.engine()
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if not any(
            sid.startswith("tpu-") for sid in engine.sessions
        ):
            break
        time.sleep(0.05)
    assert not any(
        sid.startswith("tpu-") for sid in engine.sessions
    ), "ephemeral provider session leaked"


def test_registry_fallback_when_engine_unhealthy(tpu_host, monkeypatch):
    from room_tpu.providers.base import ExecutionRequest, ProviderError
    from room_tpu.providers.registry import (
        get_model_provider, reset_provider_cache,
    )

    engine = tpu_host.engine()
    monkeypatch.setenv("ROOM_TPU_FALLBACK_MODELS", "echo:chaos-fb")
    reset_provider_cache()
    provider = get_model_provider("tpu:tiny-moe")
    assert provider.name == "tpu+fallback"

    monkeypatch.setattr(engine, "healthy", False)
    try:
        # unhealthy primary -> echo fallback serves the request
        result = provider.execute(ExecutionRequest(
            prompt="who", model="tpu:tiny-moe", max_new_tokens=8,
        ))
        assert result.success and result.text   # echo digest reply

        ready, detail = provider.is_ready()
        assert ready and "falling back" in detail

        # fail closed: a chain with nothing ready (openai with no API
        # key) surfaces the real primary failure, never a silent skip
        monkeypatch.setenv("ROOM_TPU_FALLBACK_MODELS",
                           "openai:gpt-nonexistent")
        reset_provider_cache()
        broken = get_model_provider("tpu:tiny-moe")
        assert broken.name == "tpu+fallback"
        with pytest.raises(ProviderError, match="unhealthy"):
            broken.execute(ExecutionRequest(
                prompt="who", model="tpu:tiny-moe",
                max_new_tokens=8,
            ))
    finally:
        monkeypatch.setattr(engine, "healthy", True)
        monkeypatch.delenv("ROOM_TPU_FALLBACK_MODELS")
        reset_provider_cache()


def test_client_disconnect_mid_stream_releases_pages(tpu_host):
    """The /v1 SSE generator must return a disconnected client's pages
    to the pool (fault point fires inside the stream loop)."""
    from room_tpu.server.router import RequestContext, Router
    from room_tpu.server.routes import register_openai_routes

    engine = tpu_host.engine()
    router = Router()
    register_openai_routes(router)
    handler, params = router.match("POST", "/v1/chat/completions")
    ctx = RequestContext(
        method="POST", path="/v1/chat/completions", params=params,
        query={}, body={
            "model": "tpu:tiny-moe", "stream": True,
            "messages": [{"role": "user", "content": "hello"}],
            "max_tokens": 64,
        },
    )
    sessions_before = set(engine.sessions)
    faults.inject("client_disconnect", times=1)
    out = handler(ctx)
    assert "sse" in out
    chunks = list(out["sse"])      # generator ends at the fault point
    assert faults.fired("client_disconnect") == 1
    assert "[DONE]" not in chunks  # stream really was cut short
    # the turn finishes on the engine thread; the deferred release
    # then returns the one-shot session's pages to the pool
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        leaked = set(engine.sessions) - sessions_before
        if not engine.stats()["active_slots"] and not leaked:
            break
        time.sleep(0.05)
    assert not (set(engine.sessions) - sessions_before), (
        "disconnected stream leaked its session"
    )


def test_tpu_health_route(tpu_host):
    from room_tpu.server.router import RequestContext, Router
    from room_tpu.server.routes import register_all_routes

    router = Router()
    register_all_routes(router)
    handler, params = router.match("GET", "/api/tpu/health")
    faults.inject("decode_stall", latency_s=0.1, times=0)
    out = handler(RequestContext(
        method="GET", path="/api/tpu/health", params=params, query={},
        body=None,
    ))
    data = out["data"]
    assert "degraded" in data and "engines" in data
    assert "decode_stall" in data["faults"]
    eng_row = data["engines"].get("tiny-moe")
    assert eng_row is not None
    for key in ("degradation_level", "engine_crashes", "stall_events",
                "requeues", "shed_turns", "healthy",
                # multi-step decode pipeline (docs/serving.md)
                "steps_per_dispatch", "host_stall_ms",
                "decode_windows", "window_faults"):
        assert key in eng_row


def test_shed_turn_maps_to_503_with_retry_after(tpu_host):
    from room_tpu.server.router import RequestContext, Router
    from room_tpu.server.routes import register_openai_routes

    engine = tpu_host.engine()
    engine.set_degradation(4)
    try:
        # saturate the queue well past keep_n (max_batch*2) so the
        # ladder is guaranteed to shed the priority-0 turn below; the
        # injected stall keeps the engine from draining the fillers
        # before the probe submit lands (in-window spec makes these
        # one-token prompts finish in very few windows otherwise)
        faults.inject("decode_stall", latency_s=0.2, times=8)
        filler = [
            engine.submit([1], sampling=_greedy(), priority=9)
            for _ in range(engine.max_batch * 4)
        ]
        router = Router()
        register_openai_routes(router)
        handler, params = router.match("POST", "/v1/chat/completions")
        out = handler(RequestContext(
            method="POST", path="/v1/chat/completions", params=params,
            query={}, body={
                "model": "tpu:tiny-moe",
                "messages": [{"role": "user", "content": "hi"}],
                "max_tokens": 4,
            },
        ))
        assert out["status"] == 503
        assert out["headers"]["Retry-After"]
        for t in filler:
            t.done.wait(60)
    finally:
        faults.clear("decode_stall")
        engine.set_degradation(None)
        deadline = time.monotonic() + 30
        while engine.stats()["active_slots"] and \
                time.monotonic() < deadline:
            time.sleep(0.05)


# ---- dependency gating (soft AES-GCM fallback) ----

def test_soft_aesgcm_nist_vector():
    """The pure-Python AES-GCM the secret store falls back to (when the
    cryptography wheel is absent) matches the NIST AES-256-GCM
    known-answer test, so enc:v1 envelopes stay wire-compatible."""
    from room_tpu.core.aesgcm import InvalidTag, SoftAESGCM

    k = bytes.fromhex(
        "feffe9928665731c6d6a8f9467308308"
        "feffe9928665731c6d6a8f9467308308"
    )
    iv = bytes.fromhex("cafebabefacedbaddecaf888")
    p = bytes.fromhex(
        "d9313225f88406e5a55909c5aff5269a86a7a9531534f7da2e4c303d"
        "8a318a721c3c0c95956809532fcf0e2449a6b525b16aedf5aa0de657"
        "ba637b39"
    )
    a = bytes.fromhex("feedfacedeadbeeffeedfacedeadbeefabaddad2")
    want_ct = bytes.fromhex(
        "522dc1f099567d07f47f37a32a84427d643a8cdcbfe5c0c97598a2bd"
        "2555d1aa8cb08e48590dbb3da7b08b1056828838c5f61e6393ba7a0a"
        "bcc9f662"
    )
    want_tag = bytes.fromhex("76fc6ece0f4e1768cddf8853bb2d551b")
    g = SoftAESGCM(k)
    assert g.encrypt(iv, p, a) == want_ct + want_tag
    assert g.decrypt(iv, want_ct + want_tag, a) == p
    with pytest.raises(InvalidTag):
        g.decrypt(iv, want_ct + want_tag[:-1] + b"\x00", a)


def test_soft_aesgcm_matches_cryptography_if_present():
    pytest.importorskip("cryptography")
    import os

    from cryptography.hazmat.primitives.ciphers.aead import AESGCM

    from room_tpu.core.aesgcm import SoftAESGCM

    key, nonce = os.urandom(32), os.urandom(12)
    msg, aad = b"parity check", b"ctx"
    assert SoftAESGCM(key).encrypt(nonce, msg, aad) == \
        AESGCM(key).encrypt(nonce, msg, aad)


# ---- HTTP error-scoping satellite (ADVICE r5) ----

def test_handler_bugs_are_500_param_errors_are_400(http_server):
    from tests.conftest import http_req

    def buggy(ctx):
        raise TypeError("real handler bug")

    http_server.router.get("/api/chaos-boom", buggy)
    status, out = http_req(http_server, "GET", "/api/chaos-boom")
    assert status == 500, (
        "a handler TypeError must surface as 500, not a client 400"
    )
    # param coercion failures stay client errors
    status, out = http_req(http_server, "GET", "/api/rooms/NaN")
    assert status == 400
    assert "integer" in out["error"]


# ---- stress tiers ----

def _stress(eng, duration_s, n_threads, crash_faults=False):
    """Drive submit/park(resume)/release/evict traffic from many
    threads against armed faults; returns (all_turns, canaries)."""
    canary_prompt = [11, 12, 13, 14]
    canary_len = 10

    # clean baseline stream before any fault arms
    baseline = eng.submit(canary_prompt, sampling=_greedy(canary_len))
    eng.run_until_idle()
    expected = list(baseline.new_tokens)
    eng.release_session(baseline.session_id)

    # warm the jit cache with every traffic shape the stress drives
    # (prefill buckets x batch paddings x continuation variants):
    # otherwise the bounded window measures compiles, not chaos
    warm = []
    for batch in ([4, 1], [2]):
        for n in batch:
            warm += [
                eng.submit([w + 1, 2, 3], sampling=_greedy(4))
                for w in range(n)
            ]
            eng.run_until_idle()
            warm += [
                eng.submit(list(range(1, 30)), sampling=_greedy(8))
                for _ in range(n)
            ]
            eng.run_until_idle()
    for w in range(3):
        sid = f"chaos-w{w}"
        eng.submit([w + 1, 5], session_id=sid, sampling=_greedy(4))
        eng.run_until_idle()
        eng.submit([w + 1, 6], session_id=sid, sampling=_greedy(4))
        eng.run_until_idle()
        eng.release_session(sid)
    for t in warm:
        eng.release_session(t.session_id)
    eng.run_until_idle()

    stop = threading.Event()
    loop = threading.Thread(
        target=eng.serve_forever, args=(stop,), daemon=True
    )
    loop.start()

    # rates tuned so faults fire constantly across the run but a solid
    # fraction of canary turns still completes undisrupted (the
    # determinism invariant needs clean specimens). kv_alloc is rolled
    # per ensure_capacity — several times per decode step — so its
    # probability must stay lowest; a stall parks the WHOLE batch.
    eng.step_stall_s = 0.05
    faults.inject("kv_alloc", probability=0.004, seed=1)
    faults.inject("prefill_oom", probability=0.02, seed=2)
    faults.inject("decode_stall", probability=0.008, latency_s=0.1,
                  seed=3)
    if eng.offload_store is not None:
        # tiered-offload chaos: copy-out/restore I/O faults exercise
        # both fallbacks (fail-back-to-resident, history re-prefill)
        faults.inject("offload_io", probability=0.05, seed=5)
    if crash_faults:
        faults.inject("engine_crash", probability=0.002, seed=4)

    turns: list = []
    errors: list = []
    turns_lock = threading.Lock()
    deadline = time.monotonic() + duration_s

    def worker(widx):
        session = f"chaos-w{widx}"
        i = 0
        while time.monotonic() < deadline:
            i += 1
            kind = i % 4
            if kind == 0:
                # park/resume traffic: reuse one session so pending
                # tokens and retained KV get exercised
                t = eng.submit([widx + 1, i % 50 + 1],
                               session_id=session,
                               sampling=_greedy(4))
            elif kind == 1:
                # eviction pressure: longer fresh turns
                t = eng.submit(list(range(1, 30)),
                               sampling=_greedy(8))
            else:
                t = eng.submit([widx + 1, 2, 3], sampling=_greedy(4))
            ok = t.done.wait(60)
            with turns_lock:
                turns.append(t)
                if not ok:
                    errors.append(f"worker {widx}: turn hung")
                    return
            if kind == 2:
                eng.release_session(t.session_id)
            if i % 7 == 0:
                eng.release_session(session)

    def canary():
        while time.monotonic() < deadline:
            t = eng.submit(canary_prompt, sampling=_greedy(canary_len))
            ok = t.done.wait(60)
            with turns_lock:
                turns.append(("canary", t))
                if not ok:
                    errors.append("canary hung")
                    return
            eng.release_session(t.session_id)
            time.sleep(0.01)

    threads = [
        threading.Thread(target=worker, args=(w,), daemon=True)
        for w in range(n_threads)
    ] + [threading.Thread(target=canary, daemon=True)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(duration_s + 120)
        assert not th.is_alive(), "stress thread wedged"
    assert not errors, errors

    faults.clear()
    # drain: let in-flight work finish, then release everything
    deadline2 = time.monotonic() + 60
    while (eng.stats()["active_slots"] or eng.stats()["queued"]) and \
            time.monotonic() < deadline2:
        time.sleep(0.05)
    _release_all(eng)
    time.sleep(0.3)
    _release_all(eng)
    stop.set()
    loop.join(10)
    return turns, expected


def _assert_stress_invariants(eng, turns, expected, crash_faults=False):
    # every turn terminated (no hangs, no drops)
    flat = [t[1] if isinstance(t, tuple) else t for t in turns]
    assert flat and all(t.done.is_set() for t in flat)
    # invariant 1: zero KV page leaks — and with offload on, the
    # host/disk tiers drained too (every release dropped its copy)
    _assert_pages_balanced(eng)
    if eng.offload_store is not None:
        assert len(eng.offload_store) == 0, (
            "offload store leaked hibernated sessions"
        )
    # invariant 2: unfaulted canaries are token-deterministic
    canaries = [t for t in turns if isinstance(t, tuple)]
    undisrupted = [
        t for _, t in canaries
        if not t.disrupted and t.finish_reason in ("stop", "length")
    ]
    if not crash_faults:
        assert undisrupted, "chaos disrupted every canary; tune rates"
    for t in undisrupted:
        assert t.new_tokens == expected, (
            "unfaulted canary stream diverged from the clean run"
        )
    # faults really were exercised
    st = eng.stats()
    assert st["requeues"] + st["fault_retries"] + st["evictions"] > 0


def test_chaos_stress_quick(make_engine):
    """Bounded quick tier (CI): ~8 s of 3-thread chaos."""
    eng = make_engine(n_pages=64)
    turns, expected = _stress(eng, duration_s=8, n_threads=3)
    _assert_stress_invariants(eng, turns, expected)


@pytest.mark.slow
def test_chaos_stress_soak(make_engine):
    """Soak tier (>=30 s, more threads, occasional engine crashes,
    tiered KV offload live with offload_io armed) — the
    acceptance-criteria stress run: zero page leaks, no dropped turns,
    hibernation round trips under fire."""
    eng = make_engine(n_pages=128, max_batch=8, offload=True)
    turns, expected = _stress(
        eng, duration_s=35, n_threads=6, crash_faults=True
    )
    _assert_stress_invariants(
        eng, turns, expected, crash_faults=True
    )
