"""roomlint (room_tpu.analysis) — the static-analysis suite's own
tests: each checker fires on its seeded fixture violations, the clean
fixture stays clean, the generated docs/knobs.md round-trips against
the registry, suppressions work both ways, and the real tree passes
the same gate CI enforces (docs/static_analysis.md)."""

from __future__ import annotations

import os
from pathlib import Path

import pytest

from room_tpu import analysis
from room_tpu.analysis import (
    dispatch_checker, fault_checker, knob_checker, knobs_doc,
    lock_checker,
)
from room_tpu.analysis.common import (
    SourceFile, apply_suppressions, load_suppressions,
)
from room_tpu.utils import knobs

REPO = Path(__file__).resolve().parents[1]
FIXTURES = REPO / "tests" / "fixtures" / "roomlint"
FAULT_POINTS = fault_checker.load_fault_points(str(REPO))


def _src(name: str) -> SourceFile:
    path = FIXTURES / name
    return SourceFile(str(path), rel=os.path.relpath(path, REPO))


def _rules(violations) -> list[str]:
    return sorted(v.rule for v in violations)


# ---- checker 1: knob discipline ---------------------------------------

def test_knob_checker_flags_every_raw_read_spelling():
    out = knob_checker.check_source(_src("bad_knob_read.py"))
    raw = [v for v in out if v.rule == "knob-raw-env-read"]
    # .get / subscript / getenv / contains / aliased-os / f-string
    assert len(raw) == 6, [v.render() for v in out]
    lines = {v.line for v in raw}
    assert len(lines) == 6  # six distinct seeded sites


def test_knob_checker_flags_unregistered_names():
    out = knob_checker.check_source(_src("bad_knob_read.py"))
    unreg = [v for v in out if v.rule == "knob-unregistered"]
    msgs = " ".join(v.message for v in unreg)
    assert len(unreg) == 2
    assert "ROOM_TPU_NOT_A_REAL_KNOB" in msgs
    assert "ROOM_TPU_{NOPE}_FAKE" in msgs


def test_inline_allow_is_honored():
    out = knob_checker.check_source(_src("bad_knob_read.py"))
    flagged_lines = {v.line for v in out}
    src = _src("bad_knob_read.py")
    allow_line = next(
        i + 1 for i, ln in enumerate(src.lines)
        if "allow[knob-raw-env-read]" in ln
    )
    assert allow_line not in flagged_lines


def test_registry_module_itself_is_exempt():
    src = SourceFile(str(REPO / "room_tpu" / "utils" / "knobs.py"),
                     rel=os.path.join("room_tpu", "utils", "knobs.py"))
    assert knob_checker.check_source(src) == []


# ---- checker 2: lock/stats + host-sync discipline ---------------------

def test_lock_checker_flags_seeded_violations():
    out = lock_checker.check_source(_src("bad_stats_mutation.py"))
    by_rule = {}
    for v in out:
        by_rule.setdefault(v.rule, []).append(v)
    assert len(by_rule["stats-outside-bump"]) == 2
    assert len(by_rule["sync-under-lock"]) == 3
    assert len(by_rule["sync-in-dispatch-window"]) == 1
    # _bump itself is sanctioned
    assert all("_bump" not in v.qualname.split(".")[-1]
               for v in by_rule["stats-outside-bump"])


# ---- checkers 3+4: fault coverage and dispatch ------------------------

def test_dispatch_checker_flags_substring_matching():
    out = dispatch_checker.check_dispatch(
        _src("bad_fault_dispatch.py"), FAULT_POINTS
    )
    assert len(out) == 2
    assert all(v.rule == "fault-substring-dispatch" for v in out)


def test_fault_checker_flags_unknown_point_arms():
    out = fault_checker.check_arm_sites(
        _src("bad_fault_dispatch.py"), FAULT_POINTS
    )
    assert _rules(out) == ["fault-point-unknown"]
    assert "decode_widnow" in out[0].message


def test_fault_coverage_cross_check_on_real_tree():
    """Every FAULT_POINTS entry is armed by some test file (the FULL
    test mapping — decode_window lives in test_decode_pipeline.py,
    shutdown_io in test_lifecycle.py, both also in the chaos suite
    now) and documented in docs/chaos.md."""
    out = fault_checker.check_coverage(str(REPO))
    assert out == [], [v.render() for v in out]


def test_fault_coverage_detects_untested_points(tmp_path):
    empty_tests = tmp_path / "tests"
    empty_tests.mkdir()
    out = fault_checker.check_coverage(
        str(REPO), tests_dir=str(empty_tests)
    )
    untested = {v.message.split("'")[1] for v in out
                if v.rule == "fault-point-untested"}
    assert untested == set(FAULT_POINTS)


def test_chaos_doc_drift_detected(tmp_path):
    doc = tmp_path / "chaos.md"
    doc.write_text("| `kv_alloc` | x | y |\n| `not_a_point` | x | y |\n")
    out = fault_checker.check_coverage(
        str(REPO), tests_dir="tests", doc_path=str(doc)
    )
    rules = _rules(out)
    assert "fault-point-undocumented" in rules  # 15 missing rows
    assert "fault-point-unknown" in rules       # not_a_point


# ---- clean fixture: no false positives --------------------------------

def test_clean_fixture_has_zero_violations():
    src = _src("clean_module.py")
    out = analysis.check_file(src, FAULT_POINTS)
    assert out == [], [v.render() for v in out]


# ---- knobs registry + generated docs round trip -----------------------

def test_generated_knobs_doc_is_fresh():
    """docs/knobs.md must be exactly what the registry generates —
    the same check CI runs (--check-docs)."""
    assert knobs_doc.is_fresh(str(REPO / "docs" / "knobs.md")), (
        "docs/knobs.md is stale: run "
        "`python -m room_tpu.analysis --write-docs`"
    )


def test_doc_drift_detected(tmp_path):
    stale = tmp_path / "knobs.md"
    text = knobs_doc.render().replace(
        "| `ROOM_TPU_MAX_BATCH` | int | `8` |",
        "| `ROOM_TPU_MAX_BATCH` | int | `32` |",
    )
    stale.write_text(text)
    out = knob_checker.check_docs(str(stale))
    assert any(v.rule == "knob-doc-drift"
               and "ROOM_TPU_MAX_BATCH" in v.message for v in out)


def test_missing_and_unknown_doc_rows(tmp_path):
    doc = tmp_path / "knobs.md"
    doc.write_text("| `ROOM_TPU_BOGUS` | str | `x` | | made up |\n")
    out = knob_checker.check_docs(str(doc))
    rules = set(_rules(out))
    assert "knob-undocumented" in rules
    assert "knob-unknown-doc" in rules


def test_every_registered_knob_has_doc_and_valid_shape():
    for knob in knobs.all_knobs().values():
        assert knob.doc.strip(), knob.name
        assert knob.name.startswith("ROOM_TPU_")
        if knob.provider_default is not None:
            assert knob.provider_default != knob.default, (
                f"{knob.name}: provider_default equal to default is "
                "redundant — drop it"
            )


# ---- suppression mechanics --------------------------------------------

def test_suppression_file_round_trip(tmp_path):
    sup = tmp_path / ".roomlint.suppress"
    sup.write_text(
        "stats-outside-bump  tests/fixtures/roomlint/"
        "bad_stats_mutation.py  *  # fixture\n"
        "knob-raw-env-read  room_tpu/never/matches.py  *  # stale\n"
    )
    entries = load_suppressions(str(sup))
    violations = lock_checker.check_source(_src("bad_stats_mutation.py"))
    active, suppressed = apply_suppressions(
        violations, entries, ".roomlint.suppress"
    )
    assert all(v.rule != "stats-outside-bump" for v in active)
    assert len(suppressed) == 2
    # the never-matching entry surfaces as suppression-unused
    assert any(v.rule == "suppression-unused" for v in active)


def test_suppression_without_reason_rejected(tmp_path):
    sup = tmp_path / "s"
    sup.write_text("knob-raw-env-read  a.py  *\n")
    with pytest.raises(ValueError, match="reason"):
        load_suppressions(str(sup))


# ---- the real gate ----------------------------------------------------

def test_tree_is_clean_under_roomlint():
    """The acceptance gate: zero unsuppressed violations on the tree,
    exactly what `python -m room_tpu.analysis` / CI enforces."""
    active, suppressed = analysis.run_checks(str(REPO))
    assert active == [], [v.render() for v in active]
    # the suppression file is small and every entry earns its keep
    assert 0 < len(suppressed) < 20


def test_cli_exits_nonzero_on_fixture_violations():
    from room_tpu.analysis.__main__ import main

    rc = main([
        str(FIXTURES / "bad_knob_read.py"),
        "--repo-root", str(REPO), "--no-cross-checks",
        "--suppress", os.devnull,
    ])
    assert rc == 1
    rc_clean = main([
        str(FIXTURES / "clean_module.py"),
        "--repo-root", str(REPO), "--no-cross-checks",
        "--suppress", os.devnull,
    ])
    assert rc_clean == 0


# ---- ROOM_TPU_SPEC_TOKENS drift regression (ISSUE 8 satellite) --------

class TestSpecTokensSplit:
    """The provider-on / library-off split for speculative decoding is
    now DECLARED in the registry (default=0, provider_default=4) —
    the drift between providers/tpu.py ("4") and serving/engine.py
    ("0") inline defaults cannot recur because neither file carries an
    inline default anymore."""

    def test_registry_declares_the_split(self):
        knob = knobs.REGISTRY["ROOM_TPU_SPEC_TOKENS"]
        assert knob.default == "0"
        assert knob.provider_default == "4"
        assert knob.scope == "provider"

    def test_library_scope_defaults_off(self, monkeypatch):
        monkeypatch.delenv("ROOM_TPU_SPEC_TOKENS", raising=False)
        assert knobs.get_int("ROOM_TPU_SPEC_TOKENS") == 0

    def test_provider_scope_defaults_on(self, monkeypatch):
        monkeypatch.delenv("ROOM_TPU_SPEC_TOKENS", raising=False)
        assert knobs.get_int("ROOM_TPU_SPEC_TOKENS",
                             scope="provider") == 4

    def test_env_override_wins_in_both_scopes(self, monkeypatch):
        monkeypatch.setenv("ROOM_TPU_SPEC_TOKENS", "7")
        assert knobs.get_int("ROOM_TPU_SPEC_TOKENS") == 7
        assert knobs.get_int("ROOM_TPU_SPEC_TOKENS",
                             scope="provider") == 7

    def test_call_sites_pin_their_scopes(self):
        """engine.py reads library scope, providers/tpu.py provider
        scope — the regression pin for the exact files that drifted."""
        engine = (REPO / "room_tpu" / "serving" / "engine.py").read_text()
        tpu = (REPO / "room_tpu" / "providers" / "tpu.py").read_text()
        assert 'knobs.get_int("ROOM_TPU_SPEC_TOKENS")' in engine
        assert '"ROOM_TPU_SPEC_TOKENS", scope="provider"' in tpu
        # neither carries an inline default anymore
        assert 'SPEC_TOKENS", "0"' not in engine
        assert 'SPEC_TOKENS", "4"' not in tpu


# ---- knobs accessor semantics -----------------------------------------

def test_unregistered_name_raises():
    with pytest.raises(KeyError, match="unregistered knob"):
        knobs.get_str("ROOM_TPU_TOTALLY_FAKE")
    with pytest.raises(KeyError, match="dynamic"):
        knobs.get_dynamic("ROOM_TPU_{X}_FAKE", "A")


def test_bool_semantics(monkeypatch):
    for falsey in ("", "0", "off", "FALSE", "no"):
        monkeypatch.setenv("ROOM_TPU_OFFLOAD", falsey)
        assert knobs.get_bool("ROOM_TPU_OFFLOAD") is False
    for truthy in ("1", "true", "on", "yes"):
        monkeypatch.setenv("ROOM_TPU_OFFLOAD", truthy)
        assert knobs.get_bool("ROOM_TPU_OFFLOAD") is True
    monkeypatch.delenv("ROOM_TPU_OFFLOAD", raising=False)
    assert knobs.get_bool("ROOM_TPU_OFFLOAD") is False
    assert knobs.get_bool("ROOM_TPU_OFFLOAD", scope="provider") is True


def test_dynamic_family_resolution(monkeypatch):
    monkeypatch.setenv("ROOM_TPU_MESH_TINY_LLAMA", "1,1,4@0")
    got = knobs.get_dynamic("ROOM_TPU_MESH_{MODEL}", "TINY_LLAMA")
    assert got == "1,1,4@0"
    assert knobs.get_dynamic("ROOM_TPU_MESH_{MODEL}", "OTHER") is None
    assert knobs.get_dynamic(
        "ROOM_TPU_{KIND}_BASE", "OPENAI", default="https://x"
    ) == "https://x"
