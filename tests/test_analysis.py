"""roomlint (room_tpu.analysis) — the static-analysis suite's own
tests: each checker fires on its seeded fixture violations, the clean
fixture stays clean, the generated docs/knobs.md round-trips against
the registry, suppressions work both ways, and the real tree passes
the same gate CI enforces (docs/static_analysis.md)."""

from __future__ import annotations

import os
from pathlib import Path

import pytest

from room_tpu import analysis
from room_tpu.analysis import (
    dispatch_checker, fault_checker, knob_checker, knobs_doc,
    lock_checker,
)
from room_tpu.analysis.common import (
    SourceFile, apply_suppressions, load_suppressions,
)
from room_tpu.utils import knobs

REPO = Path(__file__).resolve().parents[1]
FIXTURES = REPO / "tests" / "fixtures" / "roomlint"
FAULT_POINTS = fault_checker.load_fault_points(str(REPO))


def _src(name: str) -> SourceFile:
    path = FIXTURES / name
    return SourceFile(str(path), rel=os.path.relpath(path, REPO))


def _rules(violations) -> list[str]:
    return sorted(v.rule for v in violations)


# ---- checker 1: knob discipline ---------------------------------------

def test_knob_checker_flags_every_raw_read_spelling():
    out = knob_checker.check_source(_src("bad_knob_read.py"))
    raw = [v for v in out if v.rule == "knob-raw-env-read"]
    # .get / subscript / getenv / contains / aliased-os / f-string
    assert len(raw) == 6, [v.render() for v in out]
    lines = {v.line for v in raw}
    assert len(lines) == 6  # six distinct seeded sites


def test_knob_checker_flags_unregistered_names():
    out = knob_checker.check_source(_src("bad_knob_read.py"))
    unreg = [v for v in out if v.rule == "knob-unregistered"]
    msgs = " ".join(v.message for v in unreg)
    assert len(unreg) == 2
    assert "ROOM_TPU_NOT_A_REAL_KNOB" in msgs
    assert "ROOM_TPU_{NOPE}_FAKE" in msgs


def test_inline_allow_is_honored():
    out = knob_checker.check_source(_src("bad_knob_read.py"))
    flagged_lines = {v.line for v in out}
    src = _src("bad_knob_read.py")
    allow_line = next(
        i + 1 for i, ln in enumerate(src.lines)
        if "allow[knob-raw-env-read]" in ln
    )
    assert allow_line not in flagged_lines


def test_registry_module_itself_is_exempt():
    src = SourceFile(str(REPO / "room_tpu" / "utils" / "knobs.py"),
                     rel=os.path.join("room_tpu", "utils", "knobs.py"))
    assert knob_checker.check_source(src) == []


# ---- checker 2: lock/stats + host-sync discipline ---------------------

def test_lock_checker_flags_seeded_violations():
    out = lock_checker.check_source(_src("bad_stats_mutation.py"))
    by_rule = {}
    for v in out:
        by_rule.setdefault(v.rule, []).append(v)
    assert len(by_rule["stats-outside-bump"]) == 2
    assert len(by_rule["sync-under-lock"]) == 3
    assert len(by_rule["sync-in-dispatch-window"]) == 1
    # _bump itself is sanctioned
    assert all("_bump" not in v.qualname.split(".")[-1]
               for v in by_rule["stats-outside-bump"])


# ---- checkers 3+4: fault coverage and dispatch ------------------------

def test_dispatch_checker_flags_substring_matching():
    out = dispatch_checker.check_dispatch(
        _src("bad_fault_dispatch.py"), FAULT_POINTS
    )
    assert len(out) == 2
    assert all(v.rule == "fault-substring-dispatch" for v in out)


def test_fault_checker_flags_unknown_point_arms():
    out = fault_checker.check_arm_sites(
        _src("bad_fault_dispatch.py"), FAULT_POINTS
    )
    assert _rules(out) == ["fault-point-unknown"]
    assert "decode_widnow" in out[0].message


def test_fault_coverage_cross_check_on_real_tree():
    """Every FAULT_POINTS entry is armed by some test file (the FULL
    test mapping — decode_window lives in test_decode_pipeline.py,
    shutdown_io in test_lifecycle.py, both also in the chaos suite
    now) and documented in docs/chaos.md."""
    out = fault_checker.check_coverage(str(REPO))
    assert out == [], [v.render() for v in out]


def test_fault_coverage_detects_untested_points(tmp_path):
    empty_tests = tmp_path / "tests"
    empty_tests.mkdir()
    out = fault_checker.check_coverage(
        str(REPO), tests_dir=str(empty_tests)
    )
    untested = {v.message.split("'")[1] for v in out
                if v.rule == "fault-point-untested"}
    assert untested == set(FAULT_POINTS)


def test_chaos_doc_drift_detected(tmp_path):
    doc = tmp_path / "chaos.md"
    doc.write_text("| `kv_alloc` | x | y |\n| `not_a_point` | x | y |\n")
    out = fault_checker.check_coverage(
        str(REPO), tests_dir="tests", doc_path=str(doc)
    )
    rules = _rules(out)
    assert "fault-point-undocumented" in rules  # 15 missing rows
    assert "fault-point-unknown" in rules       # not_a_point


# ---- clean fixture: no false positives --------------------------------

def test_clean_fixture_has_zero_violations():
    src = _src("clean_module.py")
    out = analysis.check_file(src, FAULT_POINTS)
    assert out == [], [v.render() for v in out]


# ---- knobs registry + generated docs round trip -----------------------

def test_generated_knobs_doc_is_fresh():
    """docs/knobs.md must be exactly what the registry generates —
    the same check CI runs (--check-docs)."""
    assert knobs_doc.is_fresh(str(REPO / "docs" / "knobs.md")), (
        "docs/knobs.md is stale: run "
        "`python -m room_tpu.analysis --write-docs`"
    )


def test_doc_drift_detected(tmp_path):
    stale = tmp_path / "knobs.md"
    text = knobs_doc.render().replace(
        "| `ROOM_TPU_MAX_BATCH` | int | `8` |",
        "| `ROOM_TPU_MAX_BATCH` | int | `32` |",
    )
    stale.write_text(text)
    out = knob_checker.check_docs(str(stale))
    assert any(v.rule == "knob-doc-drift"
               and "ROOM_TPU_MAX_BATCH" in v.message for v in out)


def test_missing_and_unknown_doc_rows(tmp_path):
    doc = tmp_path / "knobs.md"
    doc.write_text("| `ROOM_TPU_BOGUS` | str | `x` | | made up |\n")
    out = knob_checker.check_docs(str(doc))
    rules = set(_rules(out))
    assert "knob-undocumented" in rules
    assert "knob-unknown-doc" in rules


def test_every_registered_knob_has_doc_and_valid_shape():
    for knob in knobs.all_knobs().values():
        assert knob.doc.strip(), knob.name
        assert knob.name.startswith("ROOM_TPU_")
        if knob.provider_default is not None:
            assert knob.provider_default != knob.default, (
                f"{knob.name}: provider_default equal to default is "
                "redundant — drop it"
            )


# ---- suppression mechanics --------------------------------------------

def test_suppression_file_round_trip(tmp_path):
    sup = tmp_path / ".roomlint.suppress"
    sup.write_text(
        "stats-outside-bump  tests/fixtures/roomlint/"
        "bad_stats_mutation.py  *  # fixture\n"
        "knob-raw-env-read  room_tpu/never/matches.py  *  # stale\n"
    )
    entries = load_suppressions(str(sup))
    violations = lock_checker.check_source(_src("bad_stats_mutation.py"))
    active, suppressed = apply_suppressions(
        violations, entries, ".roomlint.suppress"
    )
    assert all(v.rule != "stats-outside-bump" for v in active)
    assert len(suppressed) == 2
    # the never-matching entry surfaces as suppression-unused
    assert any(v.rule == "suppression-unused" for v in active)


def test_suppression_without_reason_rejected(tmp_path):
    sup = tmp_path / "s"
    sup.write_text("knob-raw-env-read  a.py  *\n")
    with pytest.raises(ValueError, match="reason"):
        load_suppressions(str(sup))


# ---- the real gate ----------------------------------------------------

def test_tree_is_clean_under_roomlint():
    """The acceptance gate: zero unsuppressed violations on the tree,
    exactly what `python -m room_tpu.analysis` / CI enforces."""
    active, suppressed = analysis.run_checks(str(REPO))
    assert active == [], [v.render() for v in active]
    # the suppression file ships EMPTY (the last entry was retired by
    # the kv_offload _bump refactor); inline allows carry the few
    # sanctioned exceptions, so anything suppressed here is suspect
    assert len(suppressed) == 0, suppressed


def test_cli_exits_nonzero_on_fixture_violations():
    from room_tpu.analysis.__main__ import main

    rc = main([
        str(FIXTURES / "bad_knob_read.py"),
        "--repo-root", str(REPO), "--no-cross-checks",
        "--suppress", os.devnull,
    ])
    assert rc == 1
    rc_clean = main([
        str(FIXTURES / "clean_module.py"),
        "--repo-root", str(REPO), "--no-cross-checks",
        "--suppress", os.devnull,
    ])
    assert rc_clean == 0


# ---- ROOM_TPU_SPEC_TOKENS drift regression (ISSUE 8 satellite) --------

class TestSpecTokensSplit:
    """The provider-on / library-off split for speculative decoding is
    now DECLARED in the registry (default=0, provider_default=4) —
    the drift between providers/tpu.py ("4") and serving/engine.py
    ("0") inline defaults cannot recur because neither file carries an
    inline default anymore."""

    def test_registry_declares_the_split(self):
        knob = knobs.REGISTRY["ROOM_TPU_SPEC_TOKENS"]
        assert knob.default == "0"
        assert knob.provider_default == "4"
        assert knob.scope == "provider"

    def test_library_scope_defaults_off(self, monkeypatch):
        monkeypatch.delenv("ROOM_TPU_SPEC_TOKENS", raising=False)
        assert knobs.get_int("ROOM_TPU_SPEC_TOKENS") == 0

    def test_provider_scope_defaults_on(self, monkeypatch):
        monkeypatch.delenv("ROOM_TPU_SPEC_TOKENS", raising=False)
        assert knobs.get_int("ROOM_TPU_SPEC_TOKENS",
                             scope="provider") == 4

    def test_env_override_wins_in_both_scopes(self, monkeypatch):
        monkeypatch.setenv("ROOM_TPU_SPEC_TOKENS", "7")
        assert knobs.get_int("ROOM_TPU_SPEC_TOKENS") == 7
        assert knobs.get_int("ROOM_TPU_SPEC_TOKENS",
                             scope="provider") == 7

    def test_call_sites_pin_their_scopes(self):
        """engine.py reads library scope, providers/tpu.py provider
        scope — the regression pin for the exact files that drifted."""
        engine = (REPO / "room_tpu" / "serving" / "engine.py").read_text()
        tpu = (REPO / "room_tpu" / "providers" / "tpu.py").read_text()
        assert 'knobs.get_int("ROOM_TPU_SPEC_TOKENS")' in engine
        assert '"ROOM_TPU_SPEC_TOKENS", scope="provider"' in tpu
        # neither carries an inline default anymore
        assert 'SPEC_TOKENS", "0"' not in engine
        assert 'SPEC_TOKENS", "4"' not in tpu


# ---- knobs accessor semantics -----------------------------------------

def test_unregistered_name_raises():
    with pytest.raises(KeyError, match="unregistered knob"):
        knobs.get_str("ROOM_TPU_TOTALLY_FAKE")
    with pytest.raises(KeyError, match="dynamic"):
        knobs.get_dynamic("ROOM_TPU_{X}_FAKE", "A")


def test_bool_semantics(monkeypatch):
    for falsey in ("", "0", "off", "FALSE", "no"):
        monkeypatch.setenv("ROOM_TPU_OFFLOAD", falsey)
        assert knobs.get_bool("ROOM_TPU_OFFLOAD") is False
    for truthy in ("1", "true", "on", "yes"):
        monkeypatch.setenv("ROOM_TPU_OFFLOAD", truthy)
        assert knobs.get_bool("ROOM_TPU_OFFLOAD") is True
    monkeypatch.delenv("ROOM_TPU_OFFLOAD", raising=False)
    assert knobs.get_bool("ROOM_TPU_OFFLOAD") is False
    assert knobs.get_bool("ROOM_TPU_OFFLOAD", scope="provider") is True


def test_dynamic_family_resolution(monkeypatch):
    monkeypatch.setenv("ROOM_TPU_MESH_TINY_LLAMA", "1,1,4@0")
    got = knobs.get_dynamic("ROOM_TPU_MESH_{MODEL}", "TINY_LLAMA")
    assert got == "1,1,4@0"
    assert knobs.get_dynamic("ROOM_TPU_MESH_{MODEL}", "OTHER") is None
    assert knobs.get_dynamic(
        "ROOM_TPU_{KIND}_BASE", "OPENAI", default="https://x"
    ) == "https://x"


# ---- checker 6: lockmap — whole-program concurrency (ISSUE 14) --------

import contextlib  # noqa: E402
import threading  # noqa: E402

from room_tpu.analysis import lockmap  # noqa: E402
from room_tpu.utils import lockdep, locks  # noqa: E402

_FX = "tests/fixtures/roomlint"


@contextlib.contextmanager
def _fixture_locks():
    """Temporarily register the fixture files' lock bindings (the
    real registry only knows real locks)."""
    added = []

    def add(name, **kw):
        locks.register_lock(name, "fixture binding", **kw)
        added.append(name)

    add("fx_alpha", module=f"{_FX}/bad_lock_cycle.py",
        attr="_alpha_lock")
    add("fx_beta", module=f"{_FX}/bad_lock_cycle.py",
        attr="_beta_lock")
    add("fx_gamma", module=f"{_FX}/bad_lock_self_nest.py",
        attr="_gamma_lock")
    add("fx_worker", module=f"{_FX}/bad_lock_self_nest.py",
        cls="Worker", attr="_lock", multi_instance=True)
    add("fx_tracker", module=f"{_FX}/bad_guarded_field.py",
        cls="Tracker", attr="_lock")
    add("fx_io", module=f"{_FX}/bad_blocking_under_lock.py",
        attr="_io_lock")
    add("fx_clean_outer", module=f"{_FX}/clean_locks.py",
        attr="_clean_outer_lock")
    add("fx_clean_inner", module=f"{_FX}/clean_locks.py",
        attr="_clean_inner_lock")
    add("fx_ledger", module=f"{_FX}/clean_locks.py",
        cls="Ledger", attr="_lock")
    try:
        yield
    finally:
        for name in added:
            locks.LOCK_REGISTRY.pop(name, None)


def _lockmap_findings(*names):
    facts = lockmap.collect_facts([_src(n) for n in names])
    return (
        lockmap.check_lock_graph(facts)
        + lockmap.check_guarded_state(facts)
        + lockmap.check_blocking(facts)
    )


def test_lockmap_detects_ab_ba_cycle():
    with _fixture_locks():
        out = _lockmap_findings("bad_lock_cycle.py")
    cycles = [v for v in out if v.rule == "lock-order-cycle"]
    assert len(cycles) == 1, [v.render() for v in out]
    assert "fx_alpha" in cycles[0].message
    assert "fx_beta" in cycles[0].message


def test_lockmap_detects_same_instance_self_nest():
    with _fixture_locks():
        out = _lockmap_findings("bad_lock_self_nest.py")
    nests = {v.message.split("'")[1] for v in out
             if v.rule == "lock-self-nest"}
    # the lexical global re-acquire AND the self.method() call-path
    # re-acquire — multi_instance does not exempt same-instance
    # evidence
    assert nests == {"fx_gamma", "fx_worker"}, \
        [v.render() for v in out]


def test_lockmap_guard_inference_flags_unlocked_access():
    with _fixture_locks():
        out = _lockmap_findings("bad_guarded_field.py")
    by_rule = {}
    for v in out:
        by_rule.setdefault(v.rule, []).append(v)
    writes = by_rule.get("lock-guarded-write", [])
    iters = by_rule.get("lock-guarded-iter", [])
    assert len(writes) == 1 and "_items" in writes[0].message
    assert "racy_write" in writes[0].qualname
    assert len(iters) == 1 and "racy_iter" in iters[0].qualname
    # __init__ writes, the *_locked helper, and the plain load are
    # exempt: nothing else fires
    assert len(out) == 2, [v.render() for v in out]


def test_lockmap_blocking_taxonomy_per_class():
    with _fixture_locks():
        out = _lockmap_findings("bad_blocking_under_lock.py")
    blocking = [v for v in out if v.rule == "blocking-under-lock"]
    msgs = " ".join(v.message for v in blocking)
    for needle in ("open()", "os.replace()", "shutil.copyfile()",
                   "sendall()", "recv()", "Thread.join()",
                   "Queue.get()", ".wait()"):
        assert needle in msgs, (needle, msgs)
    # 8 bare-call sites + 4 timeout=None/block=True spellings
    assert len(blocking) == 12, [v.render() for v in blocking]
    assert sum("timeout_none_spellings" in v.qualname
               for v in blocking) == 4
    # bounded calls and dict.get stay clean
    assert all("bounded_ok" not in v.qualname for v in blocking)


def test_lockmap_unresolved_lock_is_flagged():
    out = _lockmap_findings("bad_lock_unresolved.py")
    unres = [v for v in out if v.rule == "lock-unresolved"]
    assert len(unres) == 1 and "_mystery_lock" in unres[0].message


def test_lockmap_clean_fixture_zero_false_positives():
    with _fixture_locks():
        out = _lockmap_findings("clean_locks.py")
    assert out == [], [v.render() for v in out]


def test_lockmap_inline_pin_resolves_aliased_spelling():
    """Without its pin the aliased acquisition in clean_locks.py would
    be lock-unresolved: strip the pin comment and assert exactly that
    finding appears."""
    path = FIXTURES / "clean_locks.py"
    text = path.read_text().replace("  # lockmap: name=fx_clean_inner",
                                    "")
    src = SourceFile(str(path), text=text,
                     rel=os.path.relpath(path, REPO))
    with _fixture_locks():
        facts = lockmap.collect_facts([src])
        out = lockmap.check_lock_graph(facts)
    assert [v.rule for v in out] == ["lock-unresolved"], \
        [v.render() for v in out]


def test_lock_registry_drift_detected():
    with _fixture_locks():
        # fixture locks are created via bare threading.Lock(), so the
        # drift rule fires for each binding when their module is in
        # the scanned set
        facts = lockmap.collect_facts([_src("bad_lock_cycle.py")])
        out = lockmap.check_registry_drift(facts)
    names = {v.message.split("'")[1] for v in out}
    assert names == {"fx_alpha", "fx_beta"}


def test_lock_registry_bindings_match_real_tree():
    """Every real registry entry's module creates its lock through the
    factory (the gate's lock-registry-drift rule stays empty)."""
    from room_tpu.analysis.common import SourceCache, iter_py_paths

    cache = SourceCache(str(REPO))
    sources = [s for s in (cache.source(p) for p in iter_py_paths(
        ("room_tpu",), str(REPO))) if s is not None]
    facts = lockmap.collect_facts(sources)
    out = lockmap.check_registry_drift(facts)
    assert out == [], [v.render() for v in out]
    # and every decl's module is actually part of the tree
    for decl in locks.LOCK_REGISTRY.values():
        assert (REPO / decl.module).exists(), decl.name


def test_lock_graph_dot_export():
    from room_tpu.analysis.common import SourceCache, iter_py_paths

    cache = SourceCache(str(REPO))
    sources = [s for s in (cache.source(p) for p in iter_py_paths(
        ("room_tpu",), str(REPO))) if s is not None]
    facts = lockmap.collect_facts(sources)
    dot = lockmap.render_dot(facts)
    assert dot.startswith("digraph lockmap")
    # the engine->kv edges PR 14 made the graph's first citizens
    assert '"engine" -> "kv_page_table"' in dot
    assert '"engine" -> "kv_offload"' in dot
    # the alias-typed edge the runtime witness surfaced first:
    # engine._queue = engine.scheduler, so _queue_put's enqueue under
    # the engine lock takes the scheduler lock one call deep
    assert '"engine" -> "scheduler"' in dot


# ---- single-parse AST cache (ISSUE 14 satellite) ----------------------

def test_run_checks_parses_each_file_exactly_once(monkeypatch):
    """The measurable `make lint` speedup: one ast.parse per file per
    run across ALL passes (per-file checkers, the lockmap
    whole-program pass, the fault/trace cross-checks that used to
    re-parse faults.py three times)."""
    import ast as ast_mod
    from collections import Counter

    calls = Counter()
    real_parse = ast_mod.parse

    def counting(source, filename="<unknown>", *a, **kw):
        calls[str(filename)] += 1
        return real_parse(source, filename, *a, **kw)

    monkeypatch.setattr(ast_mod, "parse", counting)
    active, _ = analysis.run_checks(str(REPO))
    assert active == [], [v.render() for v in active]
    repeated = {f: n for f, n in calls.items() if n > 1}
    assert repeated == {}, repeated
    # the historically thrice-parsed files are parsed exactly once
    faults_path = str(REPO / "room_tpu" / "serving" / "faults.py")
    trace_path = str(REPO / "room_tpu" / "serving" / "trace.py")
    assert calls[faults_path] == 1
    assert calls[trace_path] == 1


# ---- lockdep: the runtime witness (ISSUE 14) --------------------------

@pytest.fixture()
def _lockdep_armed(monkeypatch):
    monkeypatch.setenv("ROOM_TPU_LOCKDEP", "1")
    monkeypatch.setenv("ROOM_TPU_LOCKDEP_STRICT", "1")
    lockdep.reset()
    yield
    lockdep.reset()


def test_lockdep_clean_pass_records_edges(_lockdep_armed):
    a = lockdep.LockdepLock("wa", threading.Lock(), "lock")
    b = lockdep.LockdepLock("wb", threading.Lock(), "lock")
    for _ in range(3):
        with a:
            with b:
                pass
    snap = lockdep.snapshot()
    assert snap["inversions"] == 0
    assert ("wa", "wb") in lockdep.observed_edges()


def test_lockdep_inversion_raises_in_strict_mode(_lockdep_armed):
    a = lockdep.LockdepLock("wa", threading.Lock(), "lock")
    b = lockdep.LockdepLock("wb", threading.Lock(), "lock")
    with a:
        with b:
            pass
    errors = []

    def reversed_order():
        try:
            with b:
                with a:
                    pass
        except lockdep.LockOrderError as e:
            errors.append(str(e))

    t = threading.Thread(target=reversed_order)
    t.start()
    t.join()
    assert errors and "inversion" in errors[0]
    assert lockdep.snapshot()["inversions"] == 1


def test_lockdep_inversion_counts_when_not_strict(
    _lockdep_armed, monkeypatch,
):
    monkeypatch.setenv("ROOM_TPU_LOCKDEP_STRICT", "0")
    a = lockdep.LockdepLock("wa", threading.Lock(), "lock")
    b = lockdep.LockdepLock("wb", threading.Lock(), "lock")
    with a:
        with b:
            pass
    with b:
        with a:   # inversion: recorded, not raised
            pass
    snap = lockdep.snapshot()
    assert snap["inversions"] == 1
    assert snap["evidence"][0]["acquired"] == "wa"
    assert snap["evidence"][0]["held"] == "wb"
    # review regression: the counted inversion proceeds to acquire,
    # but must NOT record the reverse edge — acquisitions in the
    # original sanctioned order stay clean afterwards (one real ABBA
    # must not inflate the counter on every later normal nesting)
    with a:
        with b:
            pass
    assert lockdep.snapshot()["inversions"] == 1
    assert lockdep.observed_edges() == {("wa", "wb")}


def test_lockdep_inversion_with_telemetry_loaded_never_hangs(
    _lockdep_armed, monkeypatch,
):
    """Review regression: the telemetry counter lock is itself a
    LockdepLock, so counting an inversion from inside the meta-locked
    section re-entered _precheck and self-deadlocked on the meta lock
    — the witness hung the exact thread it was protecting. The count
    now happens after the meta lock is released, under the
    reentrancy guard: an inversion with telemetry live must resolve
    promptly in both modes."""
    import room_tpu.core.telemetry as telemetry

    # telemetry may have been imported before arming: force its
    # counter lock onto the instrumented path like an armed boot
    monkeypatch.setattr(
        telemetry, "_counters_lock",
        lockdep.LockdepLock("telemetry", threading.Lock(), "lock"),
    )
    a = lockdep.LockdepLock("wa", threading.Lock(), "lock")
    b = lockdep.LockdepLock("wb", threading.Lock(), "lock")
    with a:
        with b:
            pass
    # strict: raises (never hangs)
    with pytest.raises(lockdep.LockOrderError, match="inversion"):
        with b:
            with a:
                pass
    # non-strict: counts through the live telemetry lock (never hangs)
    monkeypatch.setenv("ROOM_TPU_LOCKDEP_STRICT", "0")
    before = telemetry.counters_snapshot().get("lockdep_inversions", 0)
    with b:
        with a:
            pass
    after = telemetry.counters_snapshot().get("lockdep_inversions", 0)
    assert after > before
    assert lockdep.snapshot()["inversions"] >= 2


def test_lockdep_same_instance_reacquire_raises_even_lenient(
    _lockdep_armed, monkeypatch,
):
    monkeypatch.setenv("ROOM_TPU_LOCKDEP_STRICT", "0")
    a = lockdep.LockdepLock("wa", threading.Lock(), "lock")
    with pytest.raises(lockdep.LockOrderError, match="same-instance"):
        with a:
            with a:
                pass


def test_lockdep_rlock_reentry_is_clean(_lockdep_armed):
    r = lockdep.LockdepLock("wr", threading.RLock(), "rlock")
    with r:
        with r:
            pass
    assert lockdep.snapshot()["inversions"] == 0
    assert lockdep.observed_edges() == set()


def test_make_lock_plain_by_default_instrumented_when_armed(
    monkeypatch,
):
    monkeypatch.delenv("ROOM_TPU_LOCKDEP", raising=False)
    plain = locks.make_lock("engine")
    assert type(plain).__name__ != "LockdepLock"
    monkeypatch.setenv("ROOM_TPU_LOCKDEP", "1")
    inst = locks.make_lock("engine")
    assert isinstance(inst, lockdep.LockdepLock)
    assert inst.name == "engine"
    # bounded/non-blocking acquire surface survives wrapping
    assert inst.acquire(timeout=0.5)
    inst.release()
    assert inst.acquire(blocking=False)
    inst.release()
    with pytest.raises(ValueError, match="registered as"):
        locks.make_rlock("engine")
    with pytest.raises(KeyError, match="unregistered lock"):
        locks.make_lock("nope")


def test_lockdep_observed_order_consistent_with_static_graph(
    _lockdep_armed,
):
    """The witness contract: acquiring registered locks in the static
    graph's direction records no inversion, and the combined
    static+observed edge set stays acyclic."""
    static = lockmap.graph_edges(str(REPO), ("room_tpu",))
    assert ("engine", "kv_page_table") in static
    eng = locks.make_lock("engine")
    pt = locks.make_lock("kv_page_table")
    with eng:
        with pt:
            pass
    assert lockdep.snapshot()["inversions"] == 0
    combined = static | lockdep.observed_edges()

    def acyclic(edges):
        adj = {}
        for x, y in edges:
            adj.setdefault(x, set()).add(y)
        seen, done = set(), set()

        def dfs(n):
            if n in done:
                return True
            if n in seen:
                return False
            seen.add(n)
            ok = all(dfs(m) for m in adj.get(n, ()))
            done.add(n)
            return ok

        return all(dfs(n) for n in list(adj))

    assert acyclic(combined)
