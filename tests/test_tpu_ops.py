"""TPU-path ops: Pallas paged-attention kernel (interpret mode), ring
attention over an 8-device mesh, embed service + device index +
background indexer."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from room_tpu.core import memory
from room_tpu.core.embedding_indexer import EmbeddingIndexer
from room_tpu.models import qwen3, tiny_moe
from room_tpu.ops import attention_ref
from room_tpu.ops.paged_attention import paged_attention_decode
from room_tpu.parallel.ring import ring_attention, sequence_sharded
from room_tpu.serving import init_page_cache, make_paged_kv_hook
from room_tpu.serving.embed_service import (
    DeviceEmbedIndex, embed_texts, reset_embed_host,
)


# ---- pallas kernel ----

def _pallas_case(lengths_list, B=3, Hq=8, Hkv=2, D=32, page=8, P=16,
                 maxp=4, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.array(rng.standard_normal((B, Hq, D)), jnp.float32)
    k_pages = jnp.array(rng.standard_normal((P, page, Hkv, D)),
                        jnp.float32)
    v_pages = jnp.array(rng.standard_normal((P, page, Hkv, D)),
                        jnp.float32)
    tables = jnp.array(
        [[(b * maxp + i) % (P - 1) + 1 for i in range(maxp)]
         for b in range(B)],
        jnp.int32,
    )
    lengths = jnp.array(lengths_list, jnp.int32)
    got = paged_attention_decode(
        q, k_pages, v_pages, tables, lengths, page_size=page,
        interpret=True,
    )
    kv_len = maxp * page
    k_all = k_pages[tables].reshape(B, kv_len, Hkv, D)
    v_all = v_pages[tables].reshape(B, kv_len, Hkv, D)
    kv_pos = jnp.broadcast_to(jnp.arange(kv_len)[None], (B, kv_len))
    want = attention_ref(
        q[:, None], k_all, v_all, causal=False,
        kv_mask=kv_pos < lengths[:, None],
    )[:, 0]
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_pallas_paged_decode_matches_reference():
    _pallas_case([20, 11, 3])


def test_pallas_paged_decode_page_boundaries():
    _pallas_case([8, 16, 32])     # exact page multiples
    _pallas_case([1, 9, 17])      # one past each boundary


def _pallas_prefill_case(prefix_list, S=16, B=3, Hq=8, Hkv=2, D=32,
                         page=8, P=32, maxp=8, seed=0):
    """Chunk of S queries on top of per-row paged prefixes: kernel vs
    dense reference with a causal-within-chunk mask."""
    from room_tpu.ops.paged_attention import paged_attention_prefill

    rng = np.random.default_rng(seed)
    q = jnp.array(rng.standard_normal((B, S, Hq, D)), jnp.float32)
    k_pages = jnp.array(rng.standard_normal((P, page, Hkv, D)),
                        jnp.float32)
    v_pages = jnp.array(rng.standard_normal((P, page, Hkv, D)),
                        jnp.float32)
    tables = jnp.array(
        [[(b * maxp + i) % (P - 1) + 1 for i in range(maxp)]
         for b in range(B)],
        jnp.int32,
    )
    lengths = jnp.array(prefix_list, jnp.int32)
    got = paged_attention_prefill(
        q, k_pages, v_pages, tables, lengths, page_size=page,
        interpret=True,
    )
    kv_len = maxp * page
    k_all = k_pages[tables].reshape(B, kv_len, Hkv, D)
    v_all = v_pages[tables].reshape(B, kv_len, Hkv, D)
    kv_pos = jnp.broadcast_to(jnp.arange(kv_len)[None], (B, kv_len))
    q_pos = lengths[:, None] + jnp.arange(S)[None]
    want = attention_ref(
        q, k_all, v_all, causal=True,
        q_positions=q_pos, kv_positions=kv_pos,
        kv_mask=kv_pos < (lengths + S)[:, None],
    )
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_pallas_paged_prefill_matches_reference():
    _pallas_prefill_case([20, 11, 0])       # incl. a fresh row


def test_pallas_paged_prefill_page_boundaries():
    _pallas_prefill_case([8, 16, 32])       # exact page multiples
    _pallas_prefill_case([1, 9, 17])        # one past each boundary


def test_pallas_paged_prefill_gqa_30b_shape():
    # the qwen3-coder 32/4-head 128-dim shape, S=32 chunk
    _pallas_prefill_case([5, 40, 64], S=32, B=3, Hq=32, Hkv=4, D=128,
                         page=32, P=16, maxp=8)


def test_pallas_prefill_rejects_ragged_block():
    from room_tpu.ops.paged_attention import paged_attention_prefill

    q = jnp.zeros((1, 5, 8, 32), jnp.float32)   # S=5 not / 8
    kp = jnp.zeros((4, 8, 2, 32), jnp.float32)
    with pytest.raises(ValueError):
        paged_attention_prefill(
            q, kp, kp, jnp.zeros((1, 4), jnp.int32),
            jnp.zeros((1,), jnp.int32), page_size=8, interpret=True,
        )


def test_pallas_prefill_in_engine_hook():
    """The S>1 hook path with the prefill kernel must equal the XLA
    gather path (a continuation chunk on a non-empty session)."""
    cfg = tiny_moe()
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    b, s, page = 2, 8, 4
    tokens = jax.random.randint(jax.random.PRNGKey(1), (b, s), 0,
                                cfg.vocab_size)
    tables = jnp.array([[1, 2, 5, 6, 0], [3, 4, 7, 8, 0]], jnp.int32)

    def run(pallas):
        cache = init_page_cache(cfg, 16, page)
        hook = make_paged_kv_hook(
            tables, jnp.zeros((b,), jnp.int32), page,
            pallas_decode=False,
        )
        pos = jnp.broadcast_to(jnp.arange(s)[None], (b, s))
        _, cache = qwen3.forward(params, cfg, tokens, pos, cache,
                                 kv_hook=hook)
        # continuation chunk of 8 at length s (s>1 → prefill kernel)
        hook2 = make_paged_kv_hook(
            tables, jnp.full((b,), s, jnp.int32), page,
            pallas_decode=pallas,
        )
        cont = jax.random.randint(jax.random.PRNGKey(2), (b, 8), 0,
                                  cfg.vocab_size)
        logits, _ = qwen3.forward(
            params, cfg, cont,
            s + jnp.broadcast_to(jnp.arange(8)[None], (b, 8)),
            cache, kv_hook=hook2,
        )
        return logits

    import functools

    import room_tpu.ops.paged_attention as pa

    orig = pa.paged_attention_prefill
    pa.paged_attention_prefill = functools.partial(orig, interpret=True)
    try:
        got = run(pallas=True)
    finally:
        pa.paged_attention_prefill = orig
    want = run(pallas=False)
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


def test_pallas_kernel_in_engine_hook():
    """The engine hook with pallas_decode=True must equal the XLA path."""
    cfg = tiny_moe()
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    b, s, page = 2, 6, 4
    tokens = jax.random.randint(jax.random.PRNGKey(1), (b, s), 0,
                                cfg.vocab_size)
    tables = jnp.array([[1, 2, 0, 0], [3, 4, 0, 0]], jnp.int32)

    def run(pallas):
        cache = init_page_cache(cfg, 16, page)
        hook = make_paged_kv_hook(
            tables, jnp.zeros((b,), jnp.int32), page,
            pallas_decode=False,
        )
        pos = jnp.broadcast_to(jnp.arange(s)[None], (b, s))
        _, cache = qwen3.forward(params, cfg, tokens, pos, cache,
                                 kv_hook=hook)
        hook2 = make_paged_kv_hook(
            tables, jnp.full((b,), s, jnp.int32), page,
            pallas_decode=pallas,
        )
        logits, _ = qwen3.forward(
            params, cfg, jnp.array([[7], [9]], jnp.int32),
            jnp.full((b, 1), s, jnp.int32), cache, kv_hook=hook2,
        )
        return logits

    import room_tpu.ops.paged_attention as pa
    import functools

    orig = pa.paged_attention_decode
    # interpret mode on CPU
    pa.paged_attention_decode = functools.partial(orig, interpret=True)
    try:
        got = run(pallas=True)
    finally:
        pa.paged_attention_decode = orig
    want = run(pallas=False)
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


# ---- ring attention ----

@pytest.fixture(scope="module")
def sp_mesh():
    return Mesh(np.array(jax.devices()[:8]).reshape(8), ("sp",))


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_dense(sp_mesh, causal):
    rng = np.random.default_rng(1)
    B, S, Hq, Hkv, D = 2, 64, 4, 2, 16
    q = jnp.array(rng.standard_normal((B, S, Hq, D)), jnp.float32)
    k = jnp.array(rng.standard_normal((B, S, Hkv, D)), jnp.float32)
    v = jnp.array(rng.standard_normal((B, S, Hkv, D)), jnp.float32)
    want = attention_ref(q, k, v, causal=causal)
    sh = sequence_sharded(sp_mesh)
    got = ring_attention(
        jax.device_put(q, sh), jax.device_put(k, sh),
        jax.device_put(v, sh), mesh=sp_mesh, causal=causal,
    )
    np.testing.assert_allclose(got, want, rtol=3e-4, atol=3e-4)
    # output stays sequence-sharded over the ring
    assert "sp" in str(got.sharding.spec)


# ---- embed service + indexer ----

def test_embed_texts_deterministic_and_normalized():
    reset_embed_host()
    a = embed_texts(["hello world", "hello world", "other thing"])
    assert a.shape[1] >= 32
    np.testing.assert_allclose(a[0], a[1], rtol=1e-5)
    np.testing.assert_allclose(
        np.linalg.norm(a, axis=1), np.ones(3), rtol=1e-4
    )
    assert not np.allclose(a[0], a[2])


def test_device_index_top_k():
    idx = DeviceEmbedIndex(dim=8)
    vecs = np.eye(8, dtype=np.float32)[:4]
    idx.rebuild(vecs, [10, 11, 12, 13])
    hits = idx.top_k(np.eye(8, dtype=np.float32)[2], k=2)
    assert hits[0][0] == 12 and hits[0][1] == pytest.approx(1.0)
    assert len(idx) == 4
    idx.rebuild(np.zeros((0, 8), np.float32), [])
    assert idx.top_k(np.ones(8), k=2) == []


def test_indexer_pass_embeds_dirty_entities(db):
    reset_embed_host()
    e1 = memory.remember(db, "alpha fact", "first observation")
    e2 = memory.remember(db, "beta fact", "second observation")
    indexer = EmbeddingIndexer(db)
    n = indexer.index_pass()
    assert n == 2
    assert memory.entities_needing_embedding(db) == []
    assert len(indexer.device_index) == 2
    # unchanged content on re-dirty -> hash dedupe, no re-embed
    db.execute("UPDATE entities SET embedded_at=NULL WHERE id=?", (e1,))
    assert indexer.index_pass() == 0
    # new observation -> re-embed
    memory.add_observation(db, e1, "newer observation")
    assert indexer.index_pass() == 1
    # semantic recall through the stored vectors
    from room_tpu.serving.embed_service import embed_texts as et

    hits = memory.semantic_search(
        db, et(["alpha fact first observation"])[0]
    )
    assert hits


# ---- shard_map expert parallelism ----

def _moe_weights(e=8, d=32, f=64, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda *s: jnp.array(rng.standard_normal(s) * 0.05, jnp.float32)
    return (mk(d, e), mk(e, d, f), mk(e, d, f), mk(e, f, d))


@pytest.mark.parametrize("t,top_k", [(16, 2), (8, 1), (64, 4)])
def test_moe_shardmap_matches_ragged(t, top_k):
    """shard_map all-to-all EP == single-device sort+ragged_dot MoE
    (capacity sized so nothing drops)."""
    from room_tpu.ops import moe_ffn
    from room_tpu.ops.moe_shardmap import moe_ffn_shardmap

    router, wg, wu, wd = _moe_weights()
    rng = np.random.default_rng(1)
    x = jnp.array(rng.standard_normal((t, 32)), jnp.float32)

    want = moe_ffn(x, router, wg, wu, wd, top_k=top_k,
                   precision=jax.lax.Precision.HIGHEST)

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("ep",))
    got = moe_ffn_shardmap(
        x, router, wg, wu, wd, top_k=top_k, mesh=mesh,
        capacity_factor=64.0,  # no drops: equivalence must be exact
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
    )


def test_moe_shardmap_under_jit_with_sharded_weights():
    """The op composes with jit + actually-sharded expert weights."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from room_tpu.ops import moe_ffn
    from room_tpu.ops.moe_shardmap import moe_ffn_shardmap

    router, wg, wu, wd = _moe_weights()
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("ep",))
    shard = lambda a: jax.device_put(
        a, NamedSharding(mesh, P("ep", None, None)))
    wg_s, wu_s, wd_s = shard(wg), shard(wu), shard(wd)
    rng = np.random.default_rng(2)
    x = jnp.array(rng.standard_normal((32, 32)), jnp.float32)

    f = jax.jit(lambda x, r, a, b, c: moe_ffn_shardmap(
        x, r, a, b, c, top_k=2, mesh=mesh, capacity_factor=64.0))
    got = f(x, router, wg_s, wu_s, wd_s)
    want = moe_ffn(x, router, wg, wu, wd, top_k=2,
                   precision=jax.lax.Precision.HIGHEST)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
    )


def test_moe_shardmap_capacity_drops_are_bounded():
    """Under tight capacity the op still runs and drops at most the
    overflow (no NaNs, no wrong-token mixing)."""
    from room_tpu.ops.moe_shardmap import moe_ffn_shardmap

    router, wg, wu, wd = _moe_weights()
    rng = np.random.default_rng(3)
    x = jnp.array(rng.standard_normal((64, 32)), jnp.float32)
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("ep",))
    out = moe_ffn_shardmap(
        x, router, wg, wu, wd, top_k=2, mesh=mesh, capacity_factor=0.5,
    )
    assert np.isfinite(np.asarray(out)).all()


def test_moe_shardmap_validates_divisibility():
    from room_tpu.ops.moe_shardmap import moe_ffn_shardmap

    router, wg, wu, wd = _moe_weights()
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("ep",))
    with pytest.raises(ValueError, match="divisible"):
        moe_ffn_shardmap(
            jnp.ones((9, 32)), router, wg, wu, wd, top_k=2, mesh=mesh,
        )


def test_model_forward_shardmap_matches_ragged():
    """Full decoder forward with moe_impl=shardmap == the ragged path
    (same weights, ep mesh installed)."""
    import dataclasses

    from room_tpu.ops.moe_shardmap import set_ep_mesh

    cfg = tiny_moe()
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(
        jax.random.PRNGKey(5), (2, 6), 0, cfg.vocab_size
    )
    want, _ = qwen3.forward(params, cfg, tokens)

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("ep",))
    set_ep_mesh(mesh)
    try:
        cfg_sm = dataclasses.replace(cfg, moe_impl="shardmap")
        got, _ = qwen3.forward(params, cfg_sm, tokens)
    finally:
        set_ep_mesh(None)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=5e-3, atol=5e-3
    )


def test_shardmap_mesh_registry_per_model():
    """Hetero hosts on disjoint submeshes must each trace shard_map over
    THEIR mesh: the registry is keyed by cfg.name (a lazy retrace after
    another host registered would otherwise pick up the wrong mesh)."""
    import dataclasses

    from room_tpu.ops.moe_shardmap import get_ep_mesh, set_ep_mesh
    from room_tpu.parallel import MeshSpec, make_submesh

    cfg = tiny_moe()
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(
        jax.random.PRNGKey(5), (2, 6), 0, cfg.vocab_size
    )
    want, _ = qwen3.forward(params, cfg, tokens)

    cfg_a = dataclasses.replace(cfg, name="hetero-a", moe_impl="shardmap")
    cfg_b = dataclasses.replace(cfg, name="hetero-b", moe_impl="shardmap")
    mesh_a = make_submesh(MeshSpec(1, 2, 1), 0)   # devices 0-1
    mesh_b = make_submesh(MeshSpec(1, 4, 1), 4)   # devices 4-7
    set_ep_mesh(mesh_a, key="hetero-a")
    set_ep_mesh(mesh_b, key="hetero-b")
    try:
        assert get_ep_mesh("hetero-a") is mesh_a
        assert get_ep_mesh("hetero-b") is mesh_b
        # unknown key without a default entry must refuse
        with pytest.raises(RuntimeError):
            get_ep_mesh("hetero-c")
        got_a, _ = qwen3.forward(params, cfg_a, tokens)
        got_b, _ = qwen3.forward(params, cfg_b, tokens)
    finally:
        set_ep_mesh(None, key="hetero-a")
        set_ep_mesh(None, key="hetero-b")
    np.testing.assert_allclose(
        np.asarray(got_a), np.asarray(want), rtol=5e-3, atol=5e-3
    )
    np.testing.assert_allclose(
        np.asarray(got_b), np.asarray(want), rtol=5e-3, atol=5e-3
    )


# ---- pipeline parallelism ----

def test_pipeline_forward_matches_dense():
    """GPipe trunk over a 2-stage pp mesh == plain forward (tiny_moe
    has 2 layers -> 1 per stage), across microbatch counts."""
    import dataclasses

    from room_tpu.parallel.pipeline import (
        pipeline_forward, pipeline_spec, shard_params_for_pipeline,
    )

    cfg = tiny_moe()
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(
        jax.random.PRNGKey(7), (8, 5), 0, cfg.vocab_size
    )
    want, _ = qwen3.forward(params, cfg, tokens)

    mesh = pipeline_spec(2)
    sharded = shard_params_for_pipeline(params, cfg, mesh)
    for m in (1, 2, 4, 8):
        got = pipeline_forward(
            sharded, cfg, tokens, mesh=mesh, n_microbatches=m
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=5e-3, atol=5e-3,
            err_msg=f"microbatches={m}",
        )


def test_pipeline_forward_deeper_model_4_stages():
    """4 stages x 2 layers each on a deeper config."""
    import dataclasses

    from room_tpu.models.config import tiny_moe as tiny_cfg
    from room_tpu.parallel.pipeline import (
        pipeline_forward, pipeline_spec, shard_params_for_pipeline,
    )

    cfg = dataclasses.replace(tiny_cfg(), n_layers=8)
    params = qwen3.init_params(cfg, jax.random.PRNGKey(1))
    tokens = jax.random.randint(
        jax.random.PRNGKey(8), (4, 6), 0, cfg.vocab_size
    )
    want, _ = qwen3.forward(params, cfg, tokens)

    mesh = pipeline_spec(4)
    sharded = shard_params_for_pipeline(params, cfg, mesh)
    got = pipeline_forward(
        sharded, cfg, tokens, mesh=mesh, n_microbatches=4
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=5e-3, atol=5e-3
    )


def test_pipeline_validation():
    import dataclasses

    from room_tpu.models.config import tiny_moe as tiny_cfg
    from room_tpu.parallel.pipeline import (
        pipeline_forward, pipeline_spec, shard_params_for_pipeline,
    )

    cfg = dataclasses.replace(tiny_cfg(), n_layers=3)
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    mesh = pipeline_spec(2)
    with pytest.raises(ValueError, match="divisible"):
        shard_params_for_pipeline(params, cfg, mesh)
    cfg2 = tiny_moe()
    params2 = qwen3.init_params(cfg2, jax.random.PRNGKey(0))
    sharded = shard_params_for_pipeline(params2, cfg2, mesh)
    with pytest.raises(ValueError, match="divisible"):
        pipeline_forward(
            sharded, cfg2, jnp.ones((5, 4), jnp.int32), mesh=mesh,
            n_microbatches=2,
        )


@pytest.mark.parametrize("shape", [
    # (B, Hq, Hkv, D, page, P, maxp) — TPU-realistic ratios: wide GQA
    # groups, 128-dim heads, larger pages; first-real-chip de-risk
    dict(B=1, Hq=8, Hkv=1, D=32, page=8, P=16, maxp=4),     # Hq/Hkv=8
    dict(B=4, Hq=16, Hkv=2, D=64, page=16, P=24, maxp=4),
    dict(B=2, Hq=8, Hkv=8, D=32, page=8, P=16, maxp=4),     # MHA (no GQA)
    dict(B=8, Hq=32, Hkv=4, D=128, page=32, P=12, maxp=3),  # 30B shape
    dict(B=5, Hq=4, Hkv=2, D=16, page=4, P=40, maxp=8),     # many pages
])
def test_pallas_paged_decode_shape_sweep(shape):
    maxp, page = shape["maxp"], shape["page"]
    caps = maxp * page
    # lengths hugging every boundary class: 1, mid-page, page-1, page,
    # page+1, full capacity
    lengths = [1, page // 2 + 1, page - 1, page, page + 1, caps]
    B = shape["B"]
    _pallas_case((lengths * ((B // len(lengths)) + 1))[:B], **shape)


def test_pallas_paged_decode_single_token_context():
    """length=1 everywhere (first decode step after a 1-token prompt)."""
    _pallas_case([1, 1, 1])


def test_pallas_paged_decode_full_capacity():
    """every sequence at exactly max_pages*page tokens."""
    _pallas_case([32, 32, 32])


def test_pallas_paged_decode_30b_shape_big_table():
    """Production decode geometry (VERDICT r2 weak #3: validate at the
    table sizes the engine actually builds): 32/4-head 128-dim qwen3
    shape, page 32, a 256-entry block table (8k-token reach), lengths
    straddling page boundaries."""
    _pallas_case([40, 1, 33], B=3, Hq=32, Hkv=4, D=128, page=32,
                 P=24, maxp=256, seed=7)


def test_pallas_paged_decode_int8_30b_shape(monkeypatch):
    """int8 decode kernel at the production shape + big table."""
    from room_tpu.serving import kv_pages
    from room_tpu.ops import paged_attention as pa

    real = pa.paged_attention_decode_int8
    monkeypatch.setattr(
        pa, "paged_attention_decode_int8",
        lambda *a, **k: real(*a, **{**k, "interpret": True}),
    )
    kv_pages._DECODE_INT8_PROBE.clear()
    try:
        assert kv_pages._probe_decode_int8_kernel(32, 4, 128, 32)
    finally:
        kv_pages._DECODE_INT8_PROBE.clear()
