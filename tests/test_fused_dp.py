"""dp-sharded fused spec-window (docs/serving.md).

The fused dispatch window — interleaved prefill chunks + decode lanes +
in-window speculation in ONE device call — used to auto-disable under
dp sharding. The sharded variant keeps it on: the ragged token stream
becomes per-dp-shard sub-batches ``[ndp, B_local + C_local*cw]``
(decode lanes contiguous per shard, chunk rows dealt round-robin,
shard-major), dispatched once with no cross-shard collectives on the
token path. Pinned here: the greedy token-identity matrix dp {1,2,4}
x steps_per_dispatch {1,4} x spec on/off x prefix-hit x
offload-restore on the 8-device virtual mesh; a decode_window fault
shot through the dp-sharded dispatch (staged rollback durable, no KV
leak, accepted drafts survive); the legacy ROOM_TPU_FUSED_WINDOW_DP=0
auto-off; the shard-layout map's n_shards=1 degeneracy; and the
persistent draft-KV rewrite's equivalence to stateless re-forwarding.
Quick tier: runs in the ci.yml chaos job.
"""

import jax
import numpy as np
import pytest

from room_tpu.models import qwen3, tiny_moe
from room_tpu.ops.paged_attention import ragged_shard_layout
from room_tpu.parallel import (
    MeshSpec, decoder_param_specs, make_mesh, shard_pytree,
)
from room_tpu.serving import SamplingParams, ServingEngine, faults

LONG = [1 + (i % 53) for i in range(100)]   # 13 pages at page_size 8
DPS = (2, 4)
STEPS = (1, 4)
SPEC = (0, 4)


@pytest.fixture(scope="module")
def model():
    cfg = tiny_moe()
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture(scope="module")
def meshes(model):
    """One mesh + sharded param set per dp degree (module-scoped: the
    shard placement is static, only engines vary per test)."""
    cfg, params = model
    out = {}
    for dp in DPS:
        mesh = make_mesh(MeshSpec(dp, 1, 1))
        out[dp] = (mesh, shard_pytree(
            params, decoder_param_specs(cfg), mesh
        ))
    return out


@pytest.fixture()
def build(model, meshes, monkeypatch):
    cfg, params = model

    def make(dp=1, steps=4, spec=0, chunk_pages=1, fused_dp=True, **kw):
        monkeypatch.setenv(
            "ROOM_TPU_PREFILL_CHUNK_PAGES", str(chunk_pages)
        )
        monkeypatch.setenv(
            "ROOM_TPU_DECODE_STEPS_PER_DISPATCH", str(steps)
        )
        monkeypatch.setenv("ROOM_TPU_FUSED_WINDOW", "1")
        monkeypatch.setenv(
            "ROOM_TPU_FUSED_WINDOW_DP", "1" if fused_dp else "0"
        )
        kw.setdefault("max_batch", 4)
        kw.setdefault("page_size", 8)
        kw.setdefault("n_pages", 128)
        kw.setdefault("spec_tokens", spec)
        if dp > 1:
            mesh, sharded = meshes[dp]
            return ServingEngine(cfg, sharded, mesh=mesh, **kw)
        return ServingEngine(cfg, params, **kw)

    return make


def _greedy(n=6):
    return SamplingParams(temperature=0.0, max_new_tokens=n)


def _run_streams(eng):
    """Canonical mixed traffic: a short decode turn, a long (chunked)
    prompt, and a continuation on the chunked session."""
    a = eng.submit([5, 6, 7], session_id="dec", sampling=_greedy(10))
    b = eng.submit(LONG, session_id="long", sampling=_greedy())
    eng.run_until_idle()
    c = eng.submit([7, 8, 9], session_id="long", sampling=_greedy())
    eng.run_until_idle()
    return (a.new_tokens, b.new_tokens, c.new_tokens)


# ---- shard layout: static maps ----

def test_ragged_shard_layout_degenerates_at_one_shard():
    """n_shards=1 must reproduce the flat decode-first layout exactly
    (identity inverse permutation) — the structural reason the sharded
    stream is bit-identical at dp=1."""
    lay = ragged_shard_layout(4, 2, 8, 1)
    n = 4 + 2 * 8
    assert lay["inv_perm"].tolist() == list(range(n))
    assert lay["dec_toks"].tolist() == [0, 1, 2, 3]
    assert lay["dec_rows"].tolist() == [0, 1, 2, 3]
    assert lay["ch_rows"].tolist() == [4, 5]
    assert lay["row_of_token"].tolist() == \
        [0, 1, 2, 3] + [4] * 8 + [5] * 8


def test_ragged_shard_layout_round_trip():
    """Sharded maps stay a permutation: every global token lands in
    exactly one (row, offset) slot and inv_perm undoes the dealing."""
    for ndp in (2, 4):
        lay = ragged_shard_layout(4, 4, 8, ndp)
        n = 4 + 4 * 8
        # decode tokens then chunk tokens, shard-major concatenation,
        # pulled back through inv_perm == original order
        seg = np.concatenate([lay["dec_toks"], lay["ch_toks"]])
        assert seg[lay["inv_perm"]].tolist() == list(range(n))
        # decode lanes contiguous per shard: slot i -> shard i // bl
        bl = 4 // ndp
        assert lay["dec_rows"].tolist() == [
            s * (bl + 4 // ndp) + i for s in range(ndp)
            for i in range(bl)
        ]
    with pytest.raises(ValueError):
        ragged_shard_layout(3, 2, 8, 2)
    with pytest.raises(ValueError):
        ragged_shard_layout(4, 3, 8, 2)


# ---- the identity matrix ----

@pytest.mark.parametrize("spec", SPEC)
@pytest.mark.parametrize("steps", STEPS)
def test_identity_matrix_dp_sharded_vs_fused(build, steps, spec):
    """The acceptance matrix: dp-sharded fused window (dp {2,4}) is
    greedy-token-identical to the dp=1 fused engine, with the window
    actually engaged (mode fused-dp, sharded windows counted, chunks
    riding the window instead of per-chunk dispatches)."""
    base = _run_streams(build(dp=1, steps=steps, spec=spec))
    for dp in DPS:
        eng = build(dp=dp, steps=steps, spec=spec)
        assert eng.fused_window_mode == "fused-dp"
        assert eng.fused_window_disabled_reason == \
            f"sharded variant active (dp={dp})"
        got = _run_streams(eng)
        assert got == base, f"dp={dp} steps={steps} spec={spec}"
        st = eng.stats()
        assert st["fused_window_mode"] == "fused-dp"
        assert st["fused_dp_windows"] > 0
        assert st["prefill_chunks_interleaved"] > 0
        # chunks rode the sharded window, never per-chunk device calls
        assert st["chunk_dispatches"] < \
            st["prefill_chunks_interleaved"]
        # per-shard chunk-row placement is surfaced and accounts for
        # every interleaved chunk
        dpb = st["fused_dp"]
        assert dpb["dp"] == dp and len(dpb["chunks_per_shard"]) == dp
        assert sum(dpb["chunks_per_shard"]) == \
            st["prefill_chunks_interleaved"]


def test_identity_dp_prefix_hit(build):
    """Prefix-hit axis: a second session hitting the first's cached
    prefix streams identically through the dp-sharded window."""
    prefix = list(range(1, 41))             # 5 aligned pages
    base = None
    for dp in (1, 2):
        eng = build(dp=dp)
        t1 = eng.submit(prefix + [61, 62, 63], sampling=_greedy())
        eng.run_until_idle()
        t2 = eng.submit(prefix + [71, 72], sampling=_greedy())
        eng.run_until_idle()
        assert eng.stats()["prefix_hits"] >= 1
        got = (t1.new_tokens, t2.new_tokens)
        if base is None:
            base = got
        assert got == base, f"dp={dp}"


def test_identity_dp_offload_restore(build):
    """Offload-restore axis: hibernate a session, resume it with a
    long chunked continuation through the dp-sharded dispatch."""
    base = None
    for dp in (1, 2):
        eng = build(dp=dp, offload=True)
        t1 = eng.submit(list(range(1, 20)), session_id="h",
                        sampling=_greedy())
        eng.run_until_idle()
        assert eng.offload_session("h")
        t2 = eng.submit(LONG, session_id="h", sampling=_greedy())
        eng.run_until_idle()
        got = (t1.new_tokens, t2.new_tokens)
        if base is None:
            base = got
        assert got == base, f"dp={dp}"
        assert eng.stats()["offload_restores"] >= 1


def test_dp_knob_off_restores_legacy_auto_off(build):
    """ROOM_TPU_FUSED_WINDOW_DP=0 restores the legacy behavior — the
    fused window auto-disables under dp with the old split per-chunk
    dispatches — and the disabled_reason says WHY (the knob), so a
    mixed-mesh fleet can see which replica opted out."""
    base = _run_streams(build(dp=1))
    eng = build(dp=2, fused_dp=False)
    assert eng.fused_window is False
    assert eng.fused_window_mode == "off"
    assert "ROOM_TPU_FUSED_WINDOW_DP=0" in \
        eng.fused_window_disabled_reason
    got = _run_streams(eng)
    assert got == base
    st = eng.stats()
    assert st["fused_windows"] == 0 and st["fused_dp_windows"] == 0
    # legacy path: one device call per interleaved chunk
    assert st["chunk_dispatches"] == st["prefill_chunks_interleaved"]


def test_dp_scheduler_budget_scales_with_shards(build):
    """The per-step chunk budget multiplies by the shard count (each
    dp shard carries its own chunk rows), and the scaling is visible
    in the scheduler snapshot."""
    eng = build(dp=2)
    assert eng.scheduler.chunk_shards == 2
    assert eng.stats()["scheduler"]["chunk_shards"] == 2
    eng1 = build(dp=1)
    assert eng1.scheduler.chunk_shards == 1


# ---- chaos: decode_window fault through the dp-sharded dispatch ----

def test_decode_window_fault_dp_sharded(build, monkeypatch):
    """A non-transient decode_window fault on a dp-sharded fused
    window fails only the window's decode turns; the chunked turn
    rolls back to its last durable chunk boundary, re-prepares, and
    completes with the clean stream. No KV leaks."""
    monkeypatch.setenv("ROOM_TPU_PREFIX_CACHE_PAGES", "0")
    eng0 = build(dp=2)
    d0 = eng0.submit([5, 6, 7], sampling=_greedy(10))
    b0 = eng0.submit(LONG, sampling=_greedy())
    eng0.run_until_idle()

    eng = build(dp=2)
    dec = eng.submit([5, 6, 7], session_id="dec",
                     sampling=_greedy(10))
    for _ in range(2):
        eng.step()
    chunked = eng.submit(LONG, session_id="long", sampling=_greedy())
    faults.inject("decode_window", times=1, transient=False)
    eng.run_until_idle()
    faults.clear()

    st = eng.stats()
    assert st["window_faults"] >= 1
    assert st["healthy"] is True and st["engine_crashes"] == 0
    assert dec.finish_reason == "error"
    assert chunked.finish_reason is not None
    assert chunked.new_tokens == b0.new_tokens
    assert d0.new_tokens

    # canary after the fault: clean stream, balanced pool
    canary = eng.submit([5, 6, 7], sampling=_greedy(10))
    eng.run_until_idle()
    assert canary.new_tokens == d0.new_tokens
    for sid in list(eng.sessions):
        eng.release_session(sid)
    eng.step()
    assert eng.page_table.free_pages == eng.n_pages - 1, (
        "KV page leak after dp-sharded fused-window fault"
    )


def test_decode_window_fault_dp_spec_accepted_drafts_survive(build):
    """Spec-on variant: after the faulted window rolls back, the
    retried stream still rides speculation (accepted drafts survive
    the fault) and stays token-identical to the clean spec engine."""
    cfg = tiny_moe(vocab_size=8)            # forces repetition
    params = qwen3.init_params(cfg, jax.random.PRNGKey(3))
    mesh = make_mesh(MeshSpec(2, 1, 1))
    sharded = shard_pytree(params, decoder_param_specs(cfg), mesh)
    sp = SamplingParams(temperature=0.0, max_new_tokens=24)
    prompt = [1, 2, 3, 1, 2, 3]

    def make():
        return ServingEngine(cfg, sharded, mesh=mesh, max_batch=4,
                             page_size=8, n_pages=128, spec_tokens=4)

    eng0 = make()
    want = eng0.submit(prompt, sampling=sp)
    eng0.run_until_idle()
    assert eng0.stats()["spec_accepted"] > 0

    eng = make()
    faults.inject("decode_window", times=1, transient=True)
    turn = eng.submit(prompt, sampling=sp)
    eng.run_until_idle()
    faults.clear()
    st = eng.stats()
    assert st["fault_retries"] >= 1
    assert turn.new_tokens == want.new_tokens
    assert st["spec_accepted"] > 0, "drafting never re-engaged"
    assert st["fused_window_mode"] == "fused-dp"


# ---- draft tier: persistent KV rewrite ----

def test_draft_propose_incremental_matches_stateless():
    """The persistent-draft-KV rewrite (one window prefill + gamma-1
    single-token advances) proposes the same greedy tokens as the
    stateless reference that re-forwards the whole growing sequence
    every step — the cache is a cost optimization, not a behavior
    change."""
    from room_tpu.models.config import tiny_draft
    from room_tpu.ops.spec import TAIL_PAD, draft_propose
    from room_tpu.serving.sampler import greedy_argmax

    import jax.numpy as jnp

    dcfg = tiny_draft(vocab_size=64)
    dparams = qwen3.init_params(dcfg, jax.random.PRNGKey(11))
    gamma, window = 4, 8
    tail = np.full((3, 12), TAIL_PAD, np.int32)
    tail[0, -8:] = [5, 6, 7, 5, 6, 7, 5, 6]
    tail[1, -4:] = [1, 2, 3, 4]
    tail[2, -12:] = np.arange(12) % 64

    got = np.asarray(draft_propose(
        dparams, dcfg, jnp.asarray(tail), gamma, window
    ))

    # stateless reference: full re-forward of window + drafts-so-far
    seq = np.maximum(tail[:, -window:], 0)
    want = []
    for _ in range(gamma):
        logits, _ = qwen3.forward(
            dparams, dcfg, jnp.asarray(seq)
        )
        nxt = np.asarray(greedy_argmax(
            logits[:, -1].astype(jnp.float32)
        ), np.int32)
        want.append(nxt)
        seq = np.concatenate([seq, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(got, np.stack(want, axis=1))


def test_fused_window_dp_knob_registered():
    from room_tpu.utils.knobs import REGISTRY

    assert "ROOM_TPU_FUSED_WINDOW_DP" in REGISTRY
    assert REGISTRY["ROOM_TPU_FUSED_WINDOW_DP"].default == "1"
