"""Tokenizer/chat-template fidelity: golden-pinned rendering + token
ids under the committed mini-BPE fixture (same shape as the upstream
Qwen3 tokenizer: byte-level BPE, chat/tool specials as single-id added
tokens, eos = <|im_end|>). Reference pinned model behavior via Ollama
(src/shared/local-model.ts:3-5); here the contract is pinned in-tree.
"""

import json
import os

import pytest

from room_tpu.serving import SamplingParams, ServingEngine, render_chat
from room_tpu.serving.tokenizer import HFTokenizer, load_tokenizer

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")
TOK_DIR = os.path.join(FIXTURES, "qwen_mini_tokenizer")
GOLDEN = os.path.join(FIXTURES, "chat_template", "golden.json")


@pytest.fixture(scope="module")
def hf_tok():
    return HFTokenizer(TOK_DIR)


@pytest.fixture(scope="module")
def goldens():
    with open(GOLDEN) as f:
        return json.load(f)


def test_goldens_cover_the_contract(goldens):
    names = {g["name"] for g in goldens}
    assert {
        "system_user", "tools_section", "tool_call_roundtrip",
        "no_system_no_genprompt",
    } <= names


def test_render_chat_matches_goldens(goldens):
    for g in goldens:
        got = render_chat(
            g["messages"], g["tools"],
            add_generation_prompt=g["add_generation_prompt"],
        )
        assert got == g["rendered"], f"template drift in {g['name']}"


def test_token_ids_match_goldens(hf_tok, goldens):
    for g in goldens:
        assert hf_tok.encode(g["rendered"]) == g["ids"], (
            f"token-id drift in {g['name']}"
        )


def test_specials_are_single_ids(hf_tok):
    seen = {}
    for s in ("<|im_start|>", "<|im_end|>", "<tool_call>",
              "</tool_call>", "<tool_response>", "</tool_response>",
              "<|endoftext|>"):
        ids = hf_tok.encode(s)
        assert len(ids) == 1, f"{s} tokenized to {ids}"
        seen[s] = ids[0]
    assert len(set(seen.values())) == len(seen)  # distinct ids
    assert hf_tok.eos_id == seen["<|im_end|>"]
    # pad id 0 must not fall back to eos (`or` bug regression)
    assert hf_tok.pad_id == seen["<|endoftext|>"]


def test_specials_survive_adjacent_text(hf_tok):
    """A special embedded mid-text still maps to its single id — the
    property the engine's id-compare stop/tool detection relies on."""
    text = 'x{"a":1}</tool_call>y'
    ids = hf_tok.encode(text)
    tool_end = hf_tok.encode("</tool_call>")[0]
    assert ids.count(tool_end) == 1
    assert hf_tok.decode(ids) == text


def test_roundtrip(hf_tok, goldens):
    for g in goldens:
        assert hf_tok.decode(g["ids"]) == g["rendered"]


def test_load_tokenizer_env(monkeypatch):
    monkeypatch.setenv("ROOM_TPU_TOKENIZER_PATH", TOK_DIR)
    tok = load_tokenizer()
    assert isinstance(tok, HFTokenizer)


def test_engine_tool_detection_is_token_aware(hf_tok):
    """With a BPE vocab the engine detects </tool_call> by id compare,
    and stops on <|im_end|> by id — no decoded-substring scanning."""
    import jax

    from room_tpu.models import qwen3, tiny_moe

    cfg = tiny_moe(vocab_size=max(512, hf_tok.vocab_size))
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    eng = ServingEngine(
        cfg, params, tokenizer=hf_tok, max_batch=2, page_size=8,
        n_pages=32,
    )
    assert eng._tool_end_id == hf_tok.encode("</tool_call>")[0]
    assert hf_tok.eos_id in eng.stop_token_ids

    # force the model to emit the tool-end id first: pin sampling via a
    # turn whose max_new_tokens=1 then feed the id through the stop path
    t = eng.submit(
        hf_tok.encode("hello world"),
        sampling=SamplingParams(temperature=0.0, max_new_tokens=3),
    )
    eng.run_until_idle()
    assert t.finish_reason in ("stop", "length", "tool_call")
    # direct unit check of the detection branch
    slot_turn = type(t)(
        session_id="probe", prompt_tokens=[1],
        sampling=SamplingParams(max_new_tokens=8),
    )
    from room_tpu.serving.engine import _Session

    eng.sessions["probe"] = _Session(id="probe")
    eng._active[0] = slot_turn
    eng._append_token(0, slot_turn, eng._tool_end_id)
    assert slot_turn.finish_reason == "tool_call"
