"""End-to-end story (the reference's e2e suite equivalent, over real
HTTP): a keeper stands up a templated room, the swarm runs cycles with
tool calls, governance and escalation flow through the API, the keeper
answers, notifications digest, and the room winds down."""

import json
import time
import urllib.request
import urllib.error

import pytest

from room_tpu.db import Database
from room_tpu.providers import get_model_provider, reset_provider_cache
from room_tpu.server.http import ApiServer
from room_tpu.server.runtime import ServerRuntime
from room_tpu.server.notifications import relay_pending


@pytest.fixture()
def server(tmp_path, monkeypatch):
    monkeypatch.setenv("ROOM_TPU_DATA_DIR", str(tmp_path))
    db = Database(":memory:")
    runtime = ServerRuntime(db=db)
    api = ApiServer(db, runtime=runtime, port=0)
    api.start()
    yield api
    api.stop()
    db.close()


def req(server, method, path, body=None):
    headers = {"Authorization": f"Bearer {server.tokens['agent']}"}
    data = json.dumps(body).encode() if body is not None else None
    if data:
        headers["Content-Type"] = "application/json"
    r = urllib.request.Request(
        f"http://127.0.0.1:{server.port}{path}",
        data=data, headers=headers, method=method,
    )
    try:
        with urllib.request.urlopen(r, timeout=10) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def test_full_swarm_story(server):
    reset_provider_cache()
    echo = get_model_provider("echo")
    echo.responses.clear()
    echo.tool_script.clear()
    db = server.db

    # 1. keeper instantiates a templated room on the echo model
    _, out = req(server, "POST", "/api/templates/instantiate",
                 {"template": "research-desk", "workerModel": "echo"})
    room_id = out["data"]["id"]
    _, team = req(server, "GET", f"/api/rooms/{room_id}/workers")
    assert len(team["data"]) == 4  # queen + 2 scouts + scribe
    req(server, "PUT", f"/api/rooms/{room_id}",
        {"workerModel": "echo"})

    # 2. queen's first cycle: the scripted model delegates + remembers +
    # escalates through real tool dispatch
    scout_id = next(w["id"] for w in team["data"]
                    if w["role"] == "researcher")
    echo.tool_script.extend([
        ("set_goal", {"description": "map the competitive landscape"}),
        ("delegate", {"description": "collect competitor list",
                      "worker_id": scout_id}),
        ("remember", {"name": "research scope",
                      "content": "focus on open-source rivals"}),
        ("announce_decision", {"proposal": "publish weekly brief",
                               "decision_type": "high_impact"}),
        ("escalate_to_keeper", {"question": "budget for data access?"}),
        ("save_wip", {"note": "kicked off landscape mapping"}),
    ])
    _, started = req(server, "POST", f"/api/rooms/{room_id}/start")
    assert started["data"]["started"] == room_id

    deadline = time.time() + 15
    while time.time() < deadline:
        _, cycles = req(server, "GET", f"/api/rooms/{room_id}/cycles")
        done = [c for c in cycles["data"] if c["status"] == "success"]
        if done:
            break
        time.sleep(0.1)
    assert done, "queen cycle never completed"
    echo.tool_script.clear()

    # 3. the tool calls took real effect
    _, goals = req(server, "GET", f"/api/rooms/{room_id}/goals")
    descs = json.dumps(goals["data"])
    assert "competitive landscape" in descs
    assert "competitor list" in descs
    _, mem = req(server, "GET",
                 f"/api/memory/search?q=research+scope&roomId={room_id}")
    assert mem["data"]
    _, dec = req(server, "GET", f"/api/rooms/{room_id}/decisions")
    assert any(d["status"] == "announced" for d in dec["data"])
    _, esc = req(server, "GET", "/api/escalations")
    esc_id = next(e["id"] for e in esc["data"]
                  if "budget" in e["question"])

    # 4. a worker objects to the announced decision via the API
    d_id = next(d["id"] for d in dec["data"]
                if d["status"] == "announced")
    _, obj = req(server, "POST", f"/api/decisions/{d_id}/object",
                 {"workerId": scout_id, "reason": "too early"})
    assert obj["data"]["status"] == "objected"

    # 5. keeper answers the escalation; digest includes it beforehand
    digest = relay_pending(db)
    assert digest and "budget" in digest
    _, ans = req(server, "POST", f"/api/escalations/{esc_id}/answer",
                 {"answer": "yes, $50/month"})
    assert ans["data"]["status"] == "answered"

    # 6. activity + usage audit trails exist; wind down
    _, act = req(server, "GET", f"/api/rooms/{room_id}/activity")
    types = {a["event_type"] for a in act["data"]}
    assert "delegate" in types and "decision" in types
    _, usage = req(server, "GET", f"/api/rooms/{room_id}/usage")
    assert usage["data"]["cycles"] >= 1
    _, stopped = req(server, "POST", f"/api/rooms/{room_id}/stop")
    assert stopped["status"] == 200


def test_swarm_cycle_on_real_engine(server, monkeypatch):
    """The agent loop driving the ACTUAL serving engine (tiny-moe,
    random weights): room starts, a queen cycle prefills + decodes on
    the engine, the cycle is recorded, engine stats advance. This is
    the SURVEY §7 integration the echo provider can't cover."""
    from room_tpu.providers.tpu import get_model_host, reset_model_hosts

    reset_provider_cache()
    reset_model_hosts()
    # keep the turn small so CPU decode stays fast
    monkeypatch.setenv("ROOM_TPU_MAX_BATCH", "2")
    monkeypatch.setenv("ROOM_TPU_N_PAGES", "1024")
    try:
        _, out = req(server, "POST", "/api/rooms",
                     {"name": "on-engine", "goal": "exercise the tpu",
                      "workerModel": "tpu:tiny-moe",
                      "createWallet": False})
        room_id = out["data"]["id"]
        status, _ = req(server, "POST", f"/api/rooms/{room_id}/start")
        assert status == 200

        cycles = []
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            _, out = req(server, "GET", f"/api/rooms/{room_id}/cycles")
            cycles = [c for c in out["data"]
                      if c["status"] != "running"]
            if cycles:
                break
            time.sleep(0.5)
        req(server, "POST", f"/api/rooms/{room_id}/stop")
        assert cycles, "no cycle finished on the engine"
        assert cycles[0]["status"] == "success", cycles[0]
        assert cycles[0]["model"] == "tpu:tiny-moe"
        assert cycles[0]["output_tokens"] > 0

        engine = get_model_host("tiny-moe")._engine
        assert engine is not None
        st = engine.stats()
        assert st["prefill_tokens"] > 0 and st["tokens_decoded"] > 0

        # the cycle's prompt + response went through the engine's
        # tokenizer round-trip into the log buffer
        _, logs = req(server, "GET",
                      f"/api/cycles/{cycles[0]['id']}/logs")
        kinds = {l["entry_type"] for l in logs["data"]}
        assert "prompt" in kinds and "assistant" in kinds
    finally:
        req(server, "POST", f"/api/rooms/{room_id}/stop")
        reset_model_hosts()


def test_round5_feature_story(server):
    """Round-5 capstone over real HTTP + WS: keeper configures the
    room via the validated settings PUT (min voters included), the
    queen opens an explicit ballot with her new tool, the open
    decision reaches a live WS subscriber as decision:announced (the
    desktop-notification feed), votes tally against the configured
    electorate floor, and the clerk guide's model setting round-trips
    through the settings route."""
    from room_tpu.core.queen_tools import execute_queen_tool
    from tests.test_server import WsClient

    db = server.db
    _, room = req(server, "POST", "/api/rooms",
                  {"name": "r5-story", "workerModel": "echo"})
    rid = room["data"]["id"]

    ws = WsClient(server.port, server.tokens["user"])
    ws.send_json({"type": "subscribe", "channel": f"room:{rid}"})
    assert ws.recv_json()["type"] == "subscribed"

    # settings PUT exactly as the dashboard's roomConfigSave sends it
    st, out = req(server, "PUT", f"/api/rooms/{rid}", {
        "name": "r5-story-renamed",
        "queenMaxTurns": 40,
        "config": {"voteThreshold": "majority", "minVoters": 2,
                   "voteTimeoutMinutes": 10},
    })
    assert st == 200 and out["data"]["name"] == "r5-story-renamed"

    # queen opens an explicit ballot; the configured floor binds
    queen = db.query_one(
        "SELECT id FROM workers WHERE room_id=? AND is_default=1",
        (rid,),
    )["id"]
    msg = execute_queen_tool(db, rid, queen, "open_ballot",
                             {"proposal": "ship round 5"})
    assert "min voters 2" in msg

    evt = ws.recv_json()
    assert evt["type"] == "decision:announced"
    assert evt["data"]["proposal"] == "ship round 5"
    did = evt["data"]["id"]

    # one yes from the queen cannot clear majority-of-2
    st, _ = req(server, "POST", f"/api/decisions/{did}/vote",
                {"vote": "approve", "workerId": queen})
    assert st == 200
    st, d = req(server, "GET", f"/api/rooms/{rid}/decisions")
    ballot = next(x for x in d["data"] if x["id"] == did)
    assert ballot["status"] == "voting"

    # clerk guide's model pick round-trips
    st, _ = req(server, "PUT", "/api/settings/clerk_model",
                {"value": "echo:test"})
    assert st == 200
    st, got = req(server, "GET", "/api/settings/clerk_model")
    assert got["data"]["value"] == "echo:test"
    ws.close()
