"""Multi-host distributed backend: 2 and 3 real OS processes
initialize jax.distributed against a local coordinator, form one
global 8- or 12-device mesh, run a cross-process psum and a full
dp-sharded training step (SURVEY §2.7 — the reference family's
NCCL/MPI multi-host role, exercised for real, not simulated; the odd
world catches rank arithmetic a world of two cannot)."""

import os
import socket
import subprocess
import sys

WORKER = os.path.join(
    os.path.dirname(__file__), "fixtures", "multihost_worker.py"
)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


import pytest


@pytest.mark.parametrize("n_procs", [2, 3])
def test_multi_process_collectives_and_train_step(n_procs):
    """2- and 3-process topologies (VERDICT r4 #8 asked for a
    3-process case: odd worlds catch rank arithmetic that a world of
    two cannot)."""
    port = _free_port()
    procs = []
    for rank in range(n_procs):
        env = dict(os.environ)
        env.update(
            PALLAS_AXON_POOL_IPS="",
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            ROOM_TPU_COORDINATOR=f"127.0.0.1:{port}",
            ROOM_TPU_NUM_PROCESSES=str(n_procs),
            ROOM_TPU_PROCESS_ID=str(rank),
        )
        procs.append(subprocess.Popen(
            [sys.executable, WORKER],
            env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True,
        ))
    outs = []
    for rank, proc in enumerate(procs):
        try:
            out, _ = proc.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            raise
        outs.append(out)
        assert proc.returncode == 0, f"rank {rank}:\n{out[-2000:]}"
    for rank, out in enumerate(outs):
        assert f"RANK{rank} psum OK" in out
        assert f"RANK{rank} train OK" in out
    # both ranks computed the same loss on the shared global batch
    losses = {
        line.split("loss=")[1].strip()
        for out in outs for line in out.splitlines()
        if "train OK" in line
    }
    assert len(losses) == 1, losses


# ---- failure paths (VERDICT r2 #10: multihost failure coverage) ----

def test_single_process_is_noop(monkeypatch):
    from room_tpu.parallel.multihost import initialize_multihost

    for k in ("ROOM_TPU_COORDINATOR", "ROOM_TPU_NUM_PROCESSES",
              "ROOM_TPU_PROCESS_ID"):
        monkeypatch.delenv(k, raising=False)
    assert initialize_multihost() is False
    # world size 1 is also single-process, whatever else is set
    assert initialize_multihost("127.0.0.1:1", 1, 0) is False


def test_rank_outside_world_size_rejected():
    import pytest as _pytest

    from room_tpu.parallel.multihost import initialize_multihost

    with _pytest.raises(ValueError, match="outside world size"):
        initialize_multihost("127.0.0.1:1", 2, 5)
    with _pytest.raises(ValueError, match="outside world size"):
        initialize_multihost("127.0.0.1:1", 2, -1)


def test_unreachable_coordinator_fails_fast():
    """A worker pointed at a coordinator that never comes up must exit
    with a clear error within ROOM_TPU_DCN_TIMEOUT_S — not hang for
    JAX's five-minute default (pod-launch failure detection)."""
    import time

    port = _free_port()   # nothing listening on it
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "ROOM_TPU_COORDINATOR": f"127.0.0.1:{port}",
        "ROOM_TPU_NUM_PROCESSES": "2",
        "ROOM_TPU_PROCESS_ID": "1",   # not 0: rank 0 hosts the service
        "ROOM_TPU_DCN_TIMEOUT_S": "5",
    }
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, "-c",
         "from room_tpu.parallel.multihost import initialize_multihost;"
         "initialize_multihost()"],
        env=env, capture_output=True, text=True, timeout=120,
        cwd=os.path.join(os.path.dirname(__file__), ".."),
    )
    elapsed = time.monotonic() - t0
    assert proc.returncode != 0
    assert elapsed < 60, f"init hung {elapsed:.0f}s despite timeout"
