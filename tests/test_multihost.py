"""Multi-host distributed backend: TWO real OS processes initialize
jax.distributed against a local coordinator, form one global 8-device
mesh, run a cross-process psum and a full dp-sharded training step
(SURVEY §2.7 — the reference family's NCCL/MPI multi-host role,
exercised for real, not simulated)."""

import os
import socket
import subprocess
import sys

WORKER = os.path.join(
    os.path.dirname(__file__), "fixtures", "multihost_worker.py"
)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_collectives_and_train_step():
    port = _free_port()
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update(
            PALLAS_AXON_POOL_IPS="",
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            ROOM_TPU_COORDINATOR=f"127.0.0.1:{port}",
            ROOM_TPU_NUM_PROCESSES="2",
            ROOM_TPU_PROCESS_ID=str(rank),
        )
        procs.append(subprocess.Popen(
            [sys.executable, WORKER],
            env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True,
        ))
    outs = []
    for rank, proc in enumerate(procs):
        try:
            out, _ = proc.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            raise
        outs.append(out)
        assert proc.returncode == 0, f"rank {rank}:\n{out[-2000:]}"
    for rank, out in enumerate(outs):
        assert f"RANK{rank} psum OK" in out
        assert f"RANK{rank} train OK" in out
    # both ranks computed the same loss on the shared global batch
    losses = {
        line.split("loss=")[1].strip()
        for out in outs for line in out.splitlines()
        if "train OK" in line
    }
    assert len(losses) == 1, losses
