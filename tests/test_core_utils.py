"""Core utility subsystems: rate-limit detection/parsing, secret
envelope crypto, activity feed, cycle log buffer, eth primitives reuse
(density push)."""

import threading
import time

import pytest

from room_tpu.core import activity, rate_limit, secrets
from room_tpu.core.cycle_logs import CycleLogBuffer, get_cycle_logs
from room_tpu.core import rooms


# ---- rate limit ----

@pytest.mark.parametrize("text,expect_hit", [
    ("Error: rate limit exceeded, retry later", True),
    ("429 Too Many Requests", True),
    ("quota exceeded for this minute", True),
    ("everything is fine", False),
    ("the word ratel is an animal", False),
])
def test_detect_rate_limit(text, expect_hit):
    hit = rate_limit.detect_rate_limit(text)
    assert (hit is not None) == expect_hit


def test_parse_reset_wait_formats():
    assert rate_limit.parse_reset_wait("retry after 90 seconds") == 90
    assert rate_limit.parse_reset_wait("try again in 2 minutes") == 120
    assert rate_limit.parse_reset_wait("back in 1 hour") == 3600
    # unitless / missing hints fall back to the default wait
    assert rate_limit.parse_reset_wait("retry-after: 120") == \
        rate_limit.parse_reset_wait("rate limited") > 0


def test_clamp_wait_bounds():
    assert rate_limit.clamp_wait(0.001) >= 1
    assert rate_limit.clamp_wait(10**9) <= 3600 * 6


def test_abortable_sleep_wakes_on_event():
    stop = threading.Event()
    t0 = time.monotonic()
    threading.Timer(0.1, stop.set).start()
    rate_limit.abortable_sleep(30, stop)
    assert time.monotonic() - t0 < 5


# ---- secrets ----

def test_secret_roundtrip_and_envelope(tmp_path, monkeypatch):
    monkeypatch.setenv("ROOM_TPU_DATA_DIR", str(tmp_path))
    env = secrets.encrypt_secret("hunter2", context="cred:1")
    assert env.startswith("enc:v1:")
    assert secrets.is_encrypted(env)
    assert not secrets.is_encrypted("hunter2")
    assert secrets.decrypt_secret(env, context="cred:1") == "hunter2"


def test_secret_context_binding(tmp_path, monkeypatch):
    monkeypatch.setenv("ROOM_TPU_DATA_DIR", str(tmp_path))
    env = secrets.encrypt_secret("s", context="wallet:1")
    with pytest.raises(Exception):
        secrets.decrypt_secret(env, context="wallet:2")


def test_secret_ciphertext_is_nondeterministic(tmp_path, monkeypatch):
    monkeypatch.setenv("ROOM_TPU_DATA_DIR", str(tmp_path))
    a = secrets.encrypt_secret("same", context="c")
    b = secrets.encrypt_secret("same", context="c")
    assert a != b  # fresh nonce per envelope


# ---- activity feed ----

def test_activity_log_and_feed(db):
    rooms.create_room(db, "pub", worker_model="echo")
    db.execute("UPDATE rooms SET visibility='public' WHERE id=1")
    activity.log_room_activity(db, 1, "milestone", "shipped v1")
    rows = activity.recent_activity(db, 1)
    assert any("shipped v1" in (r.get("detail") or "")
               or "shipped v1" in str(r) for r in rows)
    feed = activity.get_public_feed(db)
    assert feed and any("shipped v1" in str(f) for f in feed)


def test_private_room_excluded_from_feed(db):
    rooms.create_room(db, "priv", worker_model="echo")
    activity.log_room_activity(db, 1, "milestone", "secret stuff")
    assert all("secret stuff" not in str(f)
               for f in activity.get_public_feed(db))


# ---- cycle logs ----

def test_cycle_log_buffer_flush_and_read(db):
    rooms.create_room(db, "r", worker_model="echo")
    cycle_id = db.insert(
        "INSERT INTO worker_cycles(worker_id, room_id, model) "
        "VALUES (1, 1, 'echo')"
    )
    buf = CycleLogBuffer(db, cycle_id, flush_interval_s=999)
    buf.append("prompt", "the prompt text")
    buf.append("response", "the model said things")
    buf.flush()
    logs = get_cycle_logs(db, cycle_id)
    assert [l["entry_type"] for l in logs] == ["prompt", "response"]
    assert logs[0]["seq"] < logs[1]["seq"]


def test_cycle_log_buffer_emits_live_events(db):
    from room_tpu.core.events import event_bus

    rooms.create_room(db, "r", worker_model="echo")
    cycle_id = db.insert(
        "INSERT INTO worker_cycles(worker_id, room_id, model) "
        "VALUES (1, 1, 'echo')"
    )
    seen = []
    unsub = event_bus.subscribe(
        f"cycle:{cycle_id}", lambda e: seen.append(e.data)
    )
    try:
        buf = CycleLogBuffer(db, cycle_id, flush_interval_s=999)
        buf.append("tool_call", "ls -la")
        assert seen and seen[0]["entry_type"] == "tool_call"
    finally:
        if callable(unsub):
            unsub()
