"""WS channel-protocol tests (VERDICT r1 #9): wildcard subscription,
per-channel isolation across clients, malformed/unknown frames
tolerated, sequential events in order, clean close (reference:
src/server/ws.ts channel protocol)."""

import socket
import time

import pytest

from room_tpu.core.events import event_bus
from room_tpu.db import Database
from room_tpu.server.http import ApiServer
from tests.test_server import WsClient


@pytest.fixture()
def server(tmp_path, monkeypatch):
    monkeypatch.setenv("ROOM_TPU_DATA_DIR", str(tmp_path))
    db = Database(":memory:")
    api = ApiServer(db, port=0)
    api.start()
    yield api
    api.stop()
    db.close()


def test_wildcard_receives_everything(server):
    ws = WsClient(server.port, server.tokens["user"])
    ws.send_json({"type": "subscribe", "channel": "*"})
    assert ws.recv_json()["type"] == "subscribed"
    event_bus.emit("cycle:started", "room:7", {"cycle_id": 1})
    event_bus.emit("run:created", "tasks", {"run_id": 9})
    first = ws.recv_json()
    second = ws.recv_json()
    assert [first["channel"], second["channel"]] == ["room:7", "tasks"]
    assert first["type"] == "cycle:started"
    ws.close()


def test_channel_isolation_between_clients(server):
    a = WsClient(server.port, server.tokens["user"])
    b = WsClient(server.port, server.tokens["user"])
    a.send_json({"type": "subscribe", "channel": "room:1"})
    b.send_json({"type": "subscribe", "channel": "room:2"})
    assert a.recv_json()["type"] == "subscribed"
    assert b.recv_json()["type"] == "subscribed"

    event_bus.emit("cycle:started", "room:1", {"cycle_id": 11})
    msg = a.recv_json()
    assert msg["data"]["cycle_id"] == 11
    with pytest.raises((TimeoutError, socket.timeout)):
        b.recv_json(timeout=0.4)
    a.close()
    b.close()


def test_events_arrive_in_order(server):
    ws = WsClient(server.port, server.tokens["user"])
    ws.send_json({"type": "subscribe", "channel": "cycle:5"})
    ws.recv_json()
    for seq in range(6):
        event_bus.emit("cycle:log", "cycle:5", {"seq": seq})
    got = [ws.recv_json()["data"]["seq"] for _ in range(6)]
    assert got == list(range(6))
    ws.close()


def test_malformed_and_unknown_messages_tolerated(server):
    ws = WsClient(server.port, server.tokens["user"])
    # raw non-JSON text frame
    import json as _json
    import os
    import struct

    payload = b"this is not json"
    mask = os.urandom(4)
    masked = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
    ws.sock.sendall(
        bytes([0x81, 0x80 | len(payload)]) + mask + masked
    )
    # unknown type
    ws.send_json({"type": "dance"})
    # connection still works afterwards
    ws.send_json({"type": "subscribe", "channel": "tasks"})
    assert ws.recv_json()["type"] == "subscribed"
    event_bus.emit("run:created", "tasks", {"run_id": 3})
    assert ws.recv_json()["data"] == {"run_id": 3}
    assert _json  # imported for symmetry with WsClient internals
    ws.close()


def test_subscribe_is_idempotent_no_duplicate_fanout(server):
    ws = WsClient(server.port, server.tokens["user"])
    ws.send_json({"type": "subscribe", "channel": "tasks"})
    ws.recv_json()
    ws.send_json({"type": "subscribe", "channel": "tasks"})
    ws.recv_json()
    event_bus.emit("run:created", "tasks", {"run_id": 1})
    assert ws.recv_json()["data"] == {"run_id": 1}
    # a second copy must NOT arrive
    with pytest.raises((TimeoutError, socket.timeout)):
        ws.recv_json(timeout=0.4)
    ws.close()


def test_client_disconnect_does_not_break_fanout(server):
    a = WsClient(server.port, server.tokens["user"])
    b = WsClient(server.port, server.tokens["user"])
    for ws in (a, b):
        ws.send_json({"type": "subscribe", "channel": "tasks"})
        ws.recv_json()
    a.sock.close()  # abrupt, no close frame
    time.sleep(0.1)
    event_bus.emit("run:created", "tasks", {"run_id": 4})
    assert b.recv_json()["data"] == {"run_id": 4}
    b.close()


def test_reconnect_resubscribes_and_receives(server):
    """A dropped client that reconnects (the dashboard's 3s retry)
    gets a clean slate: new subscribe, events flow again."""
    ws1 = WsClient(server.port, server.tokens["user"])
    ws1.send_json({"type": "subscribe", "channel": "tasks"})
    assert ws1.recv_json()["type"] == "subscribed"
    ws1.close()
    time.sleep(0.1)

    ws2 = WsClient(server.port, server.tokens["user"])
    ws2.send_json({"type": "subscribe", "channel": "tasks"})
    assert ws2.recv_json()["type"] == "subscribed"
    event_bus.emit("run:created", "tasks", {"run_id": 1})
    got = ws2.recv_json()
    assert got["type"] == "run:created"
    ws2.close()


def test_slow_consumer_never_blocks_emitters(server):
    """Backpressure contract: a client that stops reading must not
    stall the event bus (fan-out runs on agent-loop/runtime threads).
    The hub queues a bounded number of frames, then drops the client;
    emitting thousands of events stays fast throughout."""
    ws = WsClient(server.port, server.tokens["user"])
    ws.send_json({"type": "subscribe", "channel": "*"})
    assert ws.recv_json()["type"] == "subscribed"
    # stop reading entirely; flood with frames big enough to fill the
    # socket buffer plus the bounded queue
    blob = "x" * 4096
    t0 = time.monotonic()
    for i in range(2000):
        event_bus.emit("cycle:log", "flood", {"seq": i, "blob": blob})
    elapsed = time.monotonic() - t0
    # sendall on a full TCP buffer would hang for the whole default
    # socket timeout; the queue bound must keep emit() near-instant
    assert elapsed < 10.0, f"emitters blocked for {elapsed:.1f}s"
    # the stalled client was disconnected rather than serviced forever
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and server.ws_hub.client_count:
        event_bus.emit("cycle:log", "flood", {"seq": -1, "blob": blob})
        time.sleep(0.05)
    assert server.ws_hub.client_count == 0
