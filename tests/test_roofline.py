"""Roofline model arithmetic (VERDICT r4 #2): the predicted-perf table
must come from tested math, not prose."""

import math

import pytest

from room_tpu.models.config import (
    qwen2_72b,
    qwen3_coder_30b,
    tiny_dense,
)
from room_tpu.perf.roofline import (
    V5E,
    VARIANTS,
    ChipSpec,
    decode_flops_per_token,
    expected_experts_touched,
    format_markdown,
    kv_bytes_per_row,
    predict_decode,
    roofline_table,
    spec_expected_tokens,
    step_weight_bytes,
)


def test_bench_shares_the_flops_model():
    # bench delegates lazily (its import must not precede main()'s
    # try/except), so compare values, not identity
    import bench

    cfg = qwen3_coder_30b()
    assert bench.decode_flops_per_token(cfg, 777.0) == \
        decode_flops_per_token(cfg, 777.0)


def test_dense_flops_closed_form():
    cfg = tiny_dense()
    d, dh = cfg.hidden, cfg.head_dim
    attn = d * cfg.n_heads * dh + 2 * d * cfg.n_kv_heads * dh \
        + cfg.n_heads * dh * d
    ffn = 3 * d * cfg.intermediate
    ctx = 100.0
    per_layer = 2 * (attn + ffn) + 4 * ctx * cfg.n_heads * dh
    want = cfg.n_layers * per_layer + 2 * d * cfg.vocab_size
    assert decode_flops_per_token(cfg, ctx) == want


def test_moe_flops_count_only_topk_experts():
    cfg = qwen3_coder_30b()
    f = decode_flops_per_token(cfg, 0.0)
    # all-expert dense equivalent would be ~16x the FFN term; active
    # params of the 30B-A3B are ~3B => ~6 GFLOPs/token + head
    assert 5e9 < f < 9e9


def test_spec_expected_tokens_limits():
    assert spec_expected_tokens(4, 0.0) == 1.0
    assert spec_expected_tokens(4, 1.0) == 5.0
    assert spec_expected_tokens(0, 0.7) == 1.0
    seq = [spec_expected_tokens(4, a) for a in (0.2, 0.5, 0.8)]
    assert seq == sorted(seq)
    with pytest.raises(ValueError):
        spec_expected_tokens(4, 1.5)


def test_expected_experts_touched_limits():
    cfg = qwen3_coder_30b()
    # one row touches exactly top_k experts in expectation
    assert expected_experts_touched(cfg, 1) == pytest.approx(cfg.top_k)
    # a huge batch touches (nearly) all experts
    assert expected_experts_touched(cfg, 4096) == pytest.approx(
        cfg.n_experts, rel=1e-6
    )
    assert expected_experts_touched(tiny_dense(), 8) == 0.0


def test_decode_is_hbm_bound_at_serving_batches():
    cfg = qwen3_coder_30b()
    for batch in (1, 8, 32):
        p = predict_decode(cfg, V5E, batch=batch, mean_ctx=2048.0)
        assert p["bound"] == "hbm"
        assert 0.0 < p["mfu"] < 0.1  # bandwidth-bound decode: low MFU


def test_int8_weights_lift_bw_bound_throughput():
    cfg = qwen3_coder_30b()
    bf16 = predict_decode(cfg, V5E, batch=8, weight_bytes=2.0)
    int8 = predict_decode(cfg, V5E, batch=8, weight_bytes=1.0)
    assert bf16["bound"] == "hbm"
    assert 1.0 < int8["tok_s"] / bf16["tok_s"] <= 2.0


def test_batching_amortizes_weight_reads():
    cfg = qwen3_coder_30b()
    t1 = predict_decode(cfg, V5E, batch=1)["tok_s"]
    t8 = predict_decode(cfg, V5E, batch=8)["tok_s"]
    t32 = predict_decode(cfg, V5E, batch=32)["tok_s"]
    assert t1 < t8 < t32


def test_kv_bytes_scale_with_context_and_dtype():
    cfg = qwen2_72b()
    b2 = kv_bytes_per_row(cfg, 1000.0, 2.0)
    assert b2 == cfg.n_layers * 1000.0 * 2 * cfg.kv_dim * 2.0
    assert kv_bytes_per_row(cfg, 1000.0, 1.0) == b2 / 2


def test_spec_uplift_monotone_and_bounded():
    cfg = qwen3_coder_30b()
    base = predict_decode(cfg, V5E, batch=8)["tok_s"]
    prev = 0.0
    for a in (0.0, 0.5, 0.9, 1.0):
        s = predict_decode(cfg, V5E, batch=8, spec_gamma=4,
                           spec_acceptance=a)["tok_s"]
        assert s > prev
        prev = s
    # acceptance 1.0 with near-free verify cannot exceed (gamma+1)x
    assert prev / base <= 5.0
    # zero acceptance emits only the bonus token per round while the
    # verify round routes 5x the tokens (touching ~2x the experts on
    # the 128-expert MoE) — the model must predict a real slowdown
    # (why the engine's no-draft fallback exists), but bounded by the
    # extra expert bytes, not a collapse
    worst = predict_decode(cfg, V5E, batch=8, spec_gamma=4,
                           spec_acceptance=0.0)
    assert 0.25 * base < worst["tok_s"] < base


def test_step_weight_bytes_int8_halves():
    cfg = qwen3_coder_30b()
    assert step_weight_bytes(cfg, 8, 1.0) == pytest.approx(
        step_weight_bytes(cfg, 8, 2.0) / 2
    )


def test_table_covers_the_grid_and_formats():
    cfg = qwen3_coder_30b()
    rows = roofline_table(cfg, V5E, batches=(8, 32))
    assert len(rows) == len(VARIANTS) * 2 * 2
    labels = {r["variant"] for r in rows}
    assert labels == {v[0] for v in VARIANTS}
    md = format_markdown(rows, V5E, cfg, 2048.0)
    assert "| variant | batch | spec |" in md
    assert md.count("\n") == len(rows) + 4  # header block + one per row


def test_prediction_brackets_the_baseline_target():
    """BASELINE.md:34 asks >=800 decode tok/s/chip on the 30B-A3B.
    The roofline says bf16@bs=8 cannot reach it on v5e bandwidth, and
    the shipped levers (int8 weights + KV, batch 32, spec) clear it —
    i.e. the target is reachable exactly via the engine's defaults."""
    cfg = qwen3_coder_30b()
    bf16_8 = predict_decode(cfg, V5E, batch=8)["tok_s"]
    assert bf16_8 < 800.0
    best = predict_decode(cfg, V5E, batch=32, weight_bytes=1.0,
                          kv_bytes=1.0, spec_gamma=4,
                          spec_acceptance=0.8)["tok_s"]
    assert best > 800.0


def test_custom_chip_spec_scales_linearly():
    cfg = qwen3_coder_30b()
    fast = ChipSpec("2x", V5E.peak_bf16_tflops * 2, V5E.hbm_gbps * 2,
                    V5E.hbm_gib)
    a = predict_decode(cfg, V5E, batch=8)
    b = predict_decode(cfg, fast, batch=8)
    assert b["tok_s"] == pytest.approx(a["tok_s"] * 2)
    assert math.isclose(a["mfu"], b["mfu"])
