"""Test configuration.

Forces JAX onto a virtual 8-device CPU mesh so multi-chip sharding logic
is exercised hermetically (the real-TPU path is covered by bench.py and
__graft_entry__.py on hardware).
"""

import os

# XLA_FLAGS must be in the env before the CPU client is created.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

# The ambient environment points JAX at the real TPU tunnel (axon): its
# PJRT plugin is registered from sitecustomize at interpreter start, which
# also imports jax — so jax's config has already snapshotted
# JAX_PLATFORMS=axon and mutating os.environ above is not sufficient.
# Backends are not initialized yet at conftest time, though, so
# config.update still redirects everything to the virtual CPU platform.
# Tests must never touch the chip.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

from room_tpu.db import Database  # noqa: E402


@pytest.fixture()
def db():
    """Fresh in-memory database with the production schema (the reference
    tests never mock the data layer; neither do we)."""
    d = Database(":memory:")
    yield d
    d.close()
