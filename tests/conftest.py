"""Test configuration.

Forces JAX onto a virtual 8-device CPU mesh so multi-chip sharding logic
is exercised hermetically (the real-TPU path is covered by bench.py and
__graft_entry__.py on hardware).
"""

import os

# XLA_FLAGS must be in the env before the CPU client is created.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

# The ambient environment points JAX at the real TPU tunnel (axon): its
# PJRT plugin is registered from sitecustomize at interpreter start, which
# also imports jax — so jax's config has already snapshotted
# JAX_PLATFORMS=axon and mutating os.environ above is not sufficient.
# Backends are not initialized yet at conftest time, though, so
# config.update still redirects everything to the virtual CPU platform.
# Tests must never touch the chip.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compile cache (same dir bench.py uses): the suite
# builds hundreds of ServingEngine/jit instances over the SAME tiny
# model shapes, and each engine's private jit cache recompiles them
# from scratch — the disk cache dedupes identical programs both within
# one run and across runs, keeping tier-1 inside its timeout window.
try:
    _cache_dir = os.environ.get(
        "JAX_COMPILATION_CACHE_DIR", "/tmp/room_tpu_jax_cache"
    )
    os.makedirs(_cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
except Exception:
    pass

# Hermetic lifecycle state (docs/lifecycle.md): the durable drain
# manifest / clean-shutdown marker root defaults to a STABLE path under
# the system tempdir — exactly right in production (state survives the
# restart), exactly wrong in tests (one run's drain would warm-restore
# into the next). Pin it to a fresh per-run dir unless a test (or the
# caller) chose its own.
if "ROOM_TPU_LIFECYCLE_DIR" not in os.environ:
    import atexit as _atexit
    import shutil as _shutil
    import tempfile as _tempfile

    _lc_tmp = _tempfile.mkdtemp(prefix="room_tpu_test_lifecycle_")
    os.environ["ROOM_TPU_LIFECYCLE_DIR"] = _lc_tmp
    _atexit.register(_shutil.rmtree, _lc_tmp, ignore_errors=True)

# The fleet-global shared prefix store (docs/disagg.md) is ON by
# default on the provider path, and its dir is shared for the whole
# run — so an engine built in one test FILE would pull prefix KV
# another file's engine published, changing which jit variants and
# prefill paths later suites compile mid-test (a 30 s release-wait in
# the chaos suite flaked exactly that way). Suites that test the store
# opt in explicitly (ctor arg / env); everything else runs store-off
# unless the caller chose otherwise.
os.environ.setdefault("ROOM_TPU_PREFIX_STORE", "0")

import pytest  # noqa: E402

from room_tpu.db import Database  # noqa: E402


@pytest.fixture()
def db():
    """Fresh in-memory database with the production schema (the reference
    tests never mock the data layer; neither do we)."""
    d = Database(":memory:")
    yield d
    d.close()


@pytest.fixture()
def http_server(tmp_path, monkeypatch):
    """Real ApiServer on port 0 with a runtime attached — the shared
    harness for HTTP flow suites (reference: helpers/test-server.ts).
    Chain RPC is pinned to a dead socket so wallet paths fail closed
    instead of calling public endpoints from tests."""
    monkeypatch.setenv("ROOM_TPU_DATA_DIR", str(tmp_path))
    monkeypatch.setenv("ROOM_TPU_EMAIL_OUTBOX", str(tmp_path / "outbox"))
    for chain in ("BASE", "ETHEREUM", "ARBITRUM", "OPTIMISM", "POLYGON"):
        monkeypatch.setenv(f"ROOM_TPU_RPC_{chain}", "http://127.0.0.1:1")
    from room_tpu.server.http import ApiServer
    from room_tpu.server.runtime import ServerRuntime

    d = Database(":memory:")
    runtime = ServerRuntime(db=d)
    api = ApiServer(d, runtime=runtime, port=0)
    api.start()
    yield api
    api.stop()
    d.close()


def http_req(server, method, path, body=None, token="agent"):
    """Drive the shared server over real HTTP; returns (status, json)."""
    import json as _json
    import urllib.error
    import urllib.request

    headers = {"Authorization": f"Bearer {server.tokens[token]}"}
    data = _json.dumps(body).encode() if body is not None else None
    if data:
        headers["Content-Type"] = "application/json"
    r = urllib.request.Request(
        f"http://127.0.0.1:{server.port}{path}",
        data=data, headers=headers, method=method,
    )
    try:
        with urllib.request.urlopen(r, timeout=10) as resp:
            return resp.status, _json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, _json.loads(e.read() or b"{}")
