"""Test configuration.

Forces JAX onto a virtual 8-device CPU mesh *before* any jax import so
multi-chip sharding logic is exercised hermetically (the real-TPU path is
covered by bench.py and __graft_entry__.py on hardware).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402

from room_tpu.db import Database  # noqa: E402


@pytest.fixture()
def db():
    """Fresh in-memory database with the production schema (the reference
    tests never mock the data layer; neither do we)."""
    d = Database(":memory:")
    yield d
    d.close()
