"""Task scheduler tests: cron parsing, task runner semantics (slots,
retry, sessions, learned context, auto-pause), runtime ticks."""

import threading
import time
from datetime import datetime

import pytest

from room_tpu.core import task_runner, rooms, workers, messages, memory
from room_tpu.core.cron import CronError, cron_matches, validate_cron
from room_tpu.providers import get_model_provider, reset_provider_cache
from room_tpu.server.runtime import ServerRuntime


# ---- cron ----

def test_cron_basics():
    t = datetime(2026, 7, 28, 14, 30)  # Tuesday
    assert cron_matches("30 14 * * *", t)
    assert cron_matches("*/15 * * * *", t)
    assert not cron_matches("0 14 * * *", t)
    assert cron_matches("30 14 28 7 *", t)
    assert cron_matches("30 14 * * 2", t)      # Tuesday = 2
    assert not cron_matches("30 14 * * 0", t)  # Sunday
    assert cron_matches("30 8-16 * * 1-5", t)
    assert cron_matches("0,30 14 * * *", t)


def test_cron_validation():
    assert validate_cron("* * * * *") is None
    assert validate_cron("bad") is not None
    assert validate_cron("61 * * * *") is not None
    assert validate_cron("*/0 * * * *") is not None
    with pytest.raises(CronError):
        cron_matches("1 2 3", datetime.now())


def test_cron_sunday_seven():
    sunday = datetime(2026, 7, 26, 9, 0)
    assert cron_matches("0 9 * * 0", sunday)
    assert cron_matches("0 9 * * 7", sunday)


# ---- task runner ----

@pytest.fixture()
def echo(db):
    reset_provider_cache()
    p = get_model_provider("echo")
    p.responses.clear()
    p.calls.clear()
    p.fail_with = None
    return p


@pytest.fixture()
def room(db):
    return rooms.create_room(db, "ops", worker_model="echo",
                             create_wallet=False)


def test_create_task_validates_cron(db):
    with pytest.raises(ValueError):
        task_runner.create_task(db, "bad", "p", cron_expression="nope")
    tid = task_runner.create_task(db, "ok", "p",
                                  cron_expression="*/5 * * * *")
    assert task_runner.get_task(db, tid)["webhook_token"]


def test_execute_task_end_to_end(db, room, echo):
    echo.responses.append("task output here")
    tid = task_runner.create_task(
        db, "report", "write the report", trigger_type="once",
        room_id=room["id"],
    )
    run = task_runner.execute_task(db, tid)
    assert run["status"] == "success"
    assert run["result"] == "task output here"
    task = task_runner.get_task(db, tid)
    assert task["run_count"] == 1 and task["error_count"] == 0
    # result stored in room memory
    assert memory.fts_search(db, "task output", room_id=room["id"])
    # result file written
    assert run["result_file"] and run["result_file"].endswith(".md")


def test_task_model_resolution_chain(db, room, echo):
    wid = workers.create_worker(db, "w", "p", room_id=room["id"],
                                model="echo:special")
    tid = task_runner.create_task(db, "t", "p", trigger_type="once",
                                  room_id=room["id"], worker_id=wid)
    task_runner.execute_task(db, tid)
    # worker model wins over room model
    assert get_model_provider("echo:special").calls


def test_learned_context_injection_and_distillation(db, room, echo):
    tid = task_runner.create_task(db, "recurring", "do the thing",
                                  trigger_type="once", room_id=room["id"])
    db.execute("UPDATE tasks SET learned_context='USE THE SIDE DOOR' "
               "WHERE id=?", (tid,))
    echo.responses.append("done")
    task_runner.execute_task(db, tid)
    assert "USE THE SIDE DOOR" in echo.calls[-1].prompt

    # run #3 triggers distillation (background thread)
    db.execute("UPDATE tasks SET run_count=2, status='active' WHERE id=?",
               (tid,))
    echo.responses.extend(["run3 output", "DISTILLED MEMO"])
    task_runner.execute_task(db, tid)
    for _ in range(100):
        t = task_runner.get_task(db, tid)
        if t["learned_context"] == "DISTILLED MEMO":
            break
        time.sleep(0.05)
    assert task_runner.get_task(db, tid)["learned_context"] == \
        "DISTILLED MEMO"


def test_task_failure_counts_and_auto_pause(db, room, echo):
    tid = task_runner.create_task(db, "flaky", "p",
                                  trigger_type="webhook",
                                  room_id=room["id"])
    echo.fail_with = "boom"
    for i in range(task_runner.AUTO_PAUSE_ERROR_COUNT):
        db.execute("UPDATE tasks SET status='active' WHERE id=?", (tid,))
        task_runner.execute_task(db, tid)
    task = task_runner.get_task(db, tid)
    assert task["status"] == "paused"
    assert task["error_count"] == task_runner.AUTO_PAUSE_ERROR_COUNT


def test_max_runs_archives(db, room, echo):
    tid = task_runner.create_task(db, "limited", "p", trigger_type="once",
                                  room_id=room["id"], max_runs=1)
    task_runner.execute_task(db, tid)
    assert task_runner.get_task(db, tid)["status"] == "archived"


def test_concurrency_slots(db, room):
    rooms.update_room(db, room["id"], max_concurrent_tasks=1)
    assert task_runner.slots.acquire(room["id"], 1)
    assert not task_runner.slots.acquire(room["id"], 1)
    task_runner.slots.release(room["id"])
    assert task_runner.slots.acquire(room["id"], 1)
    task_runner.slots.release(room["id"])


def test_duplicate_running_guard(db, room, echo):
    tid = task_runner.create_task(db, "t", "p", trigger_type="once",
                                  room_id=room["id"])
    db.insert("INSERT INTO task_runs(task_id, status) VALUES (?, "
              "'running')", (tid,))
    assert task_runner.execute_task(db, tid) is None


def test_builtin_keeper_reminder(db, room):
    tid = task_runner.create_task(db, "remind", "drink water",
                                  trigger_type="once", room_id=room["id"])
    db.execute("UPDATE tasks SET executor='keeper_reminder' WHERE id=?",
               (tid,))
    run = task_runner.execute_task(db, tid)
    assert run["status"] == "success"
    hist = messages.chat_history(db, room["id"])
    assert "drink water" in hist[-1]["content"]


# ---- runtime ----

def test_task_session_rotation_after_20_runs(db, room, echo):
    tid = task_runner.create_task(
        db, "steady", "keep going", trigger_type="manual",
        room_id=room["id"], session_continuity=True,
    )
    # run_count 19 with a live session: next run keeps it (the echo
    # provider echoes the session id it was resumed with)
    db.execute("UPDATE tasks SET run_count=19, session_id='sess-old' "
               "WHERE id=?", (tid,))
    echo.responses.append("ok")
    task_runner.execute_task(db, tid)
    assert task_runner.get_task(db, tid)["session_id"] == "sess-old"
    # run 20 (run_count now 20): next execute rotates the session away
    db.execute("UPDATE tasks SET run_count=20, session_id='sess-old' "
               "WHERE id=?", (tid,))
    echo.responses.append("ok again")
    task_runner.execute_task(db, tid)
    t = task_runner.get_task(db, tid)
    assert t["session_id"] != "sess-old"


def test_task_error_result_has_no_file_but_counts(db, room, echo):
    echo.fail_with = "boom"
    tid = task_runner.create_task(db, "fragile", "p",
                                  trigger_type="manual",
                                  room_id=room["id"])
    run = task_runner.execute_task(db, tid)
    assert run["status"] == "error"
    assert run["result_file"] is None
    t = task_runner.get_task(db, tid)
    assert t["error_count"] == 1
    # a subsequent success resets the error streak
    echo.fail_with = None
    echo.responses.append("recovered")
    task_runner.execute_task(db, tid)
    assert task_runner.get_task(db, tid)["error_count"] == 0


def test_cancel_running_tasks_for_room(db, room):
    tid = task_runner.create_task(db, "t", "p", trigger_type="manual",
                                  room_id=room["id"])
    rid = db.insert(
        "INSERT INTO task_runs(task_id, status) VALUES (?, 'running')",
        (tid,),
    )
    n = task_runner.cancel_running_tasks_for_room(db, room["id"])
    assert n == 1
    run = db.query_one("SELECT * FROM task_runs WHERE id=?", (rid,))
    assert run["status"] == "cancelled"


def test_builtin_contact_check_notes_clerk(db, room):
    tid = task_runner.create_task(
        db, "contact check", "check in", trigger_type="once",
        room_id=room["id"], executor="keeper_contact_check",
    )
    run = task_runner.execute_task(db, tid)
    assert run["status"] == "success"
    msg = db.query_one(
        "SELECT * FROM clerk_messages WHERE source='contact_check'"
    )
    assert msg and "keeper" in msg["content"].lower()
    # with a configured channel the note names it instead
    from room_tpu.core.messages import set_setting

    set_setting(db, "keeper_email", "k@example.com")
    tid2 = task_runner.create_task(
        db, "contact check 2", "check in", trigger_type="once",
        room_id=room["id"], executor="keeper_contact_check",
    )
    run2 = task_runner.execute_task(db, tid2)
    assert "keeper_email" in run2["result"]


def test_runtime_cron_fires_due_tasks(db, room, echo):
    rt = ServerRuntime(db=db)
    echo.responses.append("cron ran")
    tid = task_runner.create_task(db, "every-minute", "p",
                                  cron_expression="* * * * *",
                                  room_id=room["id"])
    rt.scheduler_tick()
    for _ in range(100):
        run = db.query_one("SELECT * FROM task_runs WHERE task_id=?",
                           (tid,))
        if run and run["status"] != "running":
            break
        time.sleep(0.05)
    assert run and run["status"] == "success"
    # same minute: no duplicate fire
    rt.scheduler_tick()
    time.sleep(0.2)
    assert len(db.query("SELECT * FROM task_runs WHERE task_id=?",
                        (tid,))) == 1


def test_runtime_due_once_task(db, room, echo):
    rt = ServerRuntime(db=db)
    echo.responses.append("once ran")
    tid = task_runner.create_task(
        db, "soon", "p", trigger_type="once",
        scheduled_at="2020-01-01T00:00:00.000Z", room_id=room["id"],
    )
    rt.scheduler_tick()
    for _ in range(100):
        run = db.query_one("SELECT * FROM task_runs WHERE task_id=?",
                           (tid,))
        if run and run["status"] != "running":
            break
        time.sleep(0.05)
    assert run["status"] == "success"
    assert task_runner.get_task(db, tid)["status"] == "archived"


def test_runtime_stale_cleanup(db, room):
    rid = db.insert(
        "INSERT INTO task_runs(task_id, status, started_at) "
        "SELECT id, 'running', '2020-01-01T00:00:00.000Z' FROM tasks "
        "LIMIT 1"
    )
    tid = task_runner.create_task(db, "t", "p", trigger_type="once")
    db.insert(
        "INSERT INTO task_runs(task_id, status, started_at) VALUES "
        "(?, 'running', '2020-01-01T00:00:00.000Z')",
        (tid,),
    )
    rt = ServerRuntime(db=db)
    n = rt.cleanup_stale()
    assert n >= 1
    stale = db.query("SELECT * FROM task_runs WHERE status='error'")
    assert stale and "stale" in stale[0]["error_message"]


def test_runtime_restart_still_starts_loops(db, tmp_path, monkeypatch):
    """Regression: a second boot on a persisted DB (contact checks
    already scheduled) must still spawn the runtime loop threads."""
    monkeypatch.setenv("ROOM_TPU_DATA_DIR", str(tmp_path))
    rt1 = ServerRuntime(db=db)
    rt1.start()
    n1 = len(rt1.threads)
    rt1.stop()
    rt2 = ServerRuntime(db=db)  # same DB: settings flag already set
    rt2.start()
    try:
        # scheduler + maintenance + inbox + supervision
        assert len(rt2.threads) == n1 == 4
        # contact checks were not duplicated
        n_checks = db.query_one(
            "SELECT COUNT(*) AS n FROM tasks WHERE "
            "executor='keeper_contact_check'"
        )["n"]
        assert n_checks == 2
    finally:
        rt2.stop()


def test_runtime_inbox_poll_wakes_queen(db, room, echo):
    """Unanswered keeper chat triggers the queen on the next inbox poll
    (reference: runtime.ts:47-61)."""
    from room_tpu.core import agent_loop, messages
    from room_tpu.server.runtime import ServerRuntime

    rt = ServerRuntime(db=db)
    assert rt.start_room(room["id"])
    try:
        messages.add_chat_message(db, room["id"], "user",
                                  "queen, are you there?")
        rt.inbox_poll()
        # the trigger materializes as an immediate-cycle request on the
        # queen's loop
        deadline = time.monotonic() + 20
        woke = False
        while time.monotonic() < deadline:
            cycles = db.query(
                "SELECT * FROM worker_cycles WHERE room_id=? AND "
                "status != 'running'",
                (room["id"],),
            )
            if cycles:
                woke = True
                break
            time.sleep(0.05)
        assert woke, "inbox poll did not wake the queen"
    finally:
        rt.stop_room(room["id"])
        rt.stop()
        # let any in-flight cycle thread (memory embed etc.) finish
        # before interpreter teardown
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and db.query(
            "SELECT * FROM worker_cycles WHERE status='running'"
        ):
            time.sleep(0.1)


def test_runtime_inbox_poll_quiet_when_answered(db, room, echo):
    from room_tpu.core import messages
    from room_tpu.server.runtime import ServerRuntime

    messages.add_chat_message(db, room["id"], "user", "hello")
    messages.add_chat_message(db, room["id"], "assistant", "hi keeper")
    rt = ServerRuntime(db=db)
    # room not launched: poll must be a no-op either way
    rt.inbox_poll()
    assert db.query("SELECT * FROM worker_cycles") == []
    rt.stop()


def test_queue_task_execution_dedupes_pending(db, room, echo):
    """A task already queued in the runtime is not double-queued
    (reference: queueTaskExecution dedupe)."""
    import threading

    from room_tpu.server.runtime import ServerRuntime

    rt = ServerRuntime(db=db)
    tid = task_runner.create_task(
        db, "slow", "work", trigger_type="manual",
        room_id=room["id"],
    )
    # hold the pending set occupied without running a real thread race
    with rt._pending_lock:
        rt._pending_tasks.add(tid)
    assert rt.queue_task_execution(tid) is False
    with rt._pending_lock:
        rt._pending_tasks.discard(tid)
    assert rt.queue_task_execution(tid) is True
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        runs = db.query("SELECT * FROM task_runs WHERE task_id=?",
                        (tid,))
        if runs and runs[0]["status"] != "running":
            break
        time.sleep(0.05)
    rt.stop()
