"""turnscope — end-to-end turn tracing, flight recorder, /metrics
(docs/observability.md).

Pins the observability layer's three hard contracts:

1. **Token identity**: tracing on vs off changes NO greedy stream,
   across dispatch-window depths {1,4} x fused/split chunk dispatch x
   offload restore — the span recorder reads host state only, never
   touches the device.
2. **Honest spans**: a turn's contiguous top-level spans (queue +
   prefill + decode) sum to its wall latency; window
   dispatch/drain/host components live inside decode; a faulted or
   shedded turn's trace survives in the flight recorder's evidence
   ring with the fault point recorded.
3. **Strict exposition**: /metrics parses with a strict Prometheus
   text-format 0.0.4 parser (typed contiguous families, cumulative
   histogram buckets closed by _count/_sum), and the
   telemetry.observe_ms bucket math is le-cumulative.

Quick tier: runs in the ci.yml chaos job.
"""

import threading
import time

import jax
import pytest

from room_tpu.models import qwen3, tiny_moe
from room_tpu.serving import (
    SamplingParams, ServingEngine, faults, trace,
)


@pytest.fixture(scope="module")
def model():
    cfg = tiny_moe()
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    faults.clear()
    trace.set_enabled(None)
    trace.recorder.reset()
    yield
    faults.clear()
    trace.set_enabled(None)
    trace.recorder.reset()


@pytest.fixture()
def build(model, monkeypatch):
    cfg, params = model

    def make(steps=4, **kw):
        monkeypatch.setenv(
            "ROOM_TPU_DECODE_STEPS_PER_DISPATCH", str(steps)
        )
        kw.setdefault("max_batch", 4)
        kw.setdefault("page_size", 8)
        kw.setdefault("n_pages", 96)
        return ServingEngine(cfg, params, **kw)

    return make


def _greedy(n=8):
    return SamplingParams(temperature=0.0, max_new_tokens=n)


# ---- 1. token identity: tracing must be a pure observer -------------

def test_identity_trace_on_vs_off(build, monkeypatch):
    """Greedy streams are byte-identical with tracing enabled vs
    disabled, across steps {1,4} x fused/split x an offload
    hibernate/restore round trip."""
    base = None
    # prefetch off: the restore happens BLOCKING at admission, which
    # is the path the trace attributes to the turn (a prefetch restore
    # overlaps decode and is a global event instead)
    monkeypatch.setenv("ROOM_TPU_OFFLOAD_PREFETCH", "0")
    for steps in (1, 4):
        for fused in ("0", "1"):
            monkeypatch.setenv("ROOM_TPU_FUSED_WINDOW", fused)
            # narrow interleaved chunks so the long prompt actually
            # chunk-prefills (and fuses when fused=1)
            monkeypatch.setenv("ROOM_TPU_PREFILL_CHUNK_PAGES", "2")
            for arm in (False, True):
                trace.set_enabled(arm)
                eng = build(steps, offload=True, n_pages=128)
                t1 = eng.submit(list(range(1, 40)), session_id="h",
                                sampling=_greedy(6))
                eng.run_until_idle()
                assert eng.offload_session("h")
                t2 = eng.submit([5, 6, 7], session_id="h",
                                sampling=_greedy(6))
                eng.run_until_idle()
                assert eng.stats()["offload_restores"] >= 1
                got = (t1.new_tokens, t2.new_tokens)
                if base is None:
                    base = got
                assert got == base, \
                    f"steps={steps} fused={fused} trace={arm}"
                if arm:
                    assert t1.trace is not None
                    assert t1.trace.to_dict()["prefill"]["chunks"] > 0
                    assert t2.trace.to_dict()["prefill"][
                        "offload_restores"] >= 1
                else:
                    assert t1.trace is None


# ---- 2. span honesty ------------------------------------------------

def test_span_components_sum_to_wall(build):
    """Contiguous spans: queue + prefill + decode == wall (to within
    10%, per the acceptance criterion; in-process they are exact to
    rounding), and the decode sub-spans stay inside decode."""
    eng = build(4)
    t = eng.submit([4, 8, 15, 16], session_id="s",
                   sampling=_greedy(10), turn_class="queen")
    eng.run_until_idle()
    d = t.trace.to_dict()
    s = d["spans"]
    covered = s["queue_ms"] + s["prefill_ms"] + s["decode_ms"]
    assert covered == pytest.approx(s["wall_ms"], rel=0.10)
    assert s["unattributed_ms"] <= s["wall_ms"] * 0.10 + 1.0
    assert d["decode"]["windows"] >= 1
    assert s["dispatch_ms"] + s["drain_ms"] <= s["decode_ms"] + 1.0
    assert d["ttft_ms"] is not None and d["ttft_ms"] > 0
    assert d["tokens"] == len(t.new_tokens)
    assert d["cid"].startswith("s#")
    assert d["class"] == "queen" and d["generation"] == 1
    # the recorder booked it
    snap = trace.recorder.snapshot()
    assert any(r["cid"] == d["cid"] for r in snap["recent"])
    attr = trace.recorder.attribution()
    assert attr["classes"]["queen"]["turns"] == 1
    assert attr["classes"]["queen"]["wall_ms"] > 0


def test_ttft_tpot_derivation(build):
    """TTFT/TPOT derive from host token-booking timestamps and carry
    the class targets captured at finish."""
    eng = build(1)
    t = eng.submit([1, 2, 3], sampling=_greedy(8),
                   turn_class="background")
    eng.run_until_idle()
    d = t.trace.to_dict()
    assert d["ttft_target_s"] == eng.scheduler.targets[
        "background"].ttft_s
    assert d["tpot_ms"] is not None
    # 8 tokens booked over a tiny CPU run: tpot is (last-first)/7
    assert 0 <= d["tpot_ms"] < 60_000


def test_chunked_prefill_attribution(build, monkeypatch):
    """A long prompt's interleaved chunk writes land in the trace
    (chunk count + tokens), and the prefill span covers them."""
    monkeypatch.setenv("ROOM_TPU_PREFILL_CHUNK_PAGES", "2")
    eng = build(4, n_pages=128)
    t = eng.submit(list(range(1, 60)), sampling=_greedy(4))
    eng.run_until_idle()
    d = t.trace.to_dict()
    assert d["prefill"]["chunks"] >= 2
    assert d["prefill"]["chunk_tokens"] >= 32
    assert d["spans"]["prefill_ms"] > 0
    names = [e[0] for e in d["events"]]
    assert "chunk_landed" in names


def test_queue_span_under_load(build):
    """A turn submitted behind a full batch spends real time queued —
    the queue span must show it."""
    eng = build(1, max_batch=1)
    a = eng.submit([1, 2, 3], sampling=_greedy(12))
    b = eng.submit([4, 5, 6], sampling=_greedy(4))
    eng.run_until_idle()
    assert a.finish_reason and b.finish_reason
    db = b.trace.to_dict()
    # b waited for a's slot: queue span is a real fraction of wall
    assert db["spans"]["queue_ms"] > 0


# ---- 3. flight recorder ---------------------------------------------

def test_faulted_turn_survives_in_evidence_ring(build, monkeypatch):
    """A decode_window fault fails the window's turns; their traces
    must land in the violations ring with the fault point recorded —
    and survive a burst of healthy traffic that overflows the recent
    ring."""
    monkeypatch.setenv("ROOM_TPU_TRACE_RING", "4")
    trace.recorder.reset()
    eng = build(4, max_batch=2)
    faults.inject("decode_window", times=1, transient=False)
    victim = eng.submit([7, 7, 7], sampling=_greedy(6))
    eng.run_until_idle()
    faults.clear()
    assert victim.finish_reason == "error"
    d = victim.trace.to_dict()
    assert "decode_window" in d["faults"]
    # healthy burst overflows the 4-deep recent ring
    for i in range(8):
        t = eng.submit([1, 2, i + 1], sampling=_greedy(3))
        eng.run_until_idle()
        eng.release_session(t.session_id)
    snap = trace.recorder.snapshot()
    assert len(snap["recent"]) <= 4
    assert not any(r["cid"] == d["cid"] for r in snap["recent"])
    viol = [r for r in snap["violations"] if r["cid"] == d["cid"]]
    assert viol and "decode_window" in viol[0]["faults"]
    # the firing also landed in the global event ring
    assert any(
        e["kind"] == "fault.decode_window" for e in snap["events"]
    )
    # attribution counted the faulted turn
    attr = snap["attribution"]["classes"]["worker"]
    assert attr["faulted"] >= 1 and attr["errors"] >= 1


def test_shedded_turn_retained(build):
    """A ladder-shed turn (503 contract) is evidence: its trace lands
    in the violations ring with shed=True."""
    eng = build(1, max_batch=2)
    eng.set_degradation(4)
    turns = [
        eng.submit([1, 2, i + 1], sampling=_greedy(2),
                   turn_class="background")
        for i in range(8)
    ]
    eng.step()
    eng.set_degradation(None)
    eng.run_until_idle()
    shed = [t for t in turns if t.shed]
    assert shed, "rung-4 shedding never fired"
    snap = trace.recorder.snapshot()
    shed_cids = {t.trace.cid for t in shed if t.trace is not None}
    viol_cids = {r["cid"] for r in snap["violations"]}
    assert shed_cids & viol_cids
    rec = next(r for r in snap["violations"]
               if r["cid"] in shed_cids)
    assert rec["shed"] is True


def test_disabled_tracing_records_nothing(build):
    trace.set_enabled(False)
    eng = build(1)
    t = eng.submit([1, 2, 3], sampling=_greedy(3))
    eng.run_until_idle()
    assert t.trace is None
    snap = trace.recorder.snapshot()
    assert snap["recent"] == [] and snap["enabled"] is False


def test_event_cap_bounds_turn_events(build, monkeypatch):
    monkeypatch.setenv("ROOM_TPU_TRACE_EVENTS", "8")
    monkeypatch.setenv("ROOM_TPU_PREFILL_CHUNK_PAGES", "1")
    eng = build(4, n_pages=128)
    t = eng.submit(list(range(1, 80)), sampling=_greedy(6))
    eng.run_until_idle()
    assert len(t.trace.events) <= 8
    # accumulators keep counting past the event cap
    assert t.trace.chunks >= 4


# ---- 4. telemetry histogram bucket math -----------------------------

def test_observe_ms_cumulative_buckets():
    from room_tpu.core import telemetry

    telemetry.reset_counters()
    # edges (1, 5, 20, 100, 500): one obs per region + one overflow
    for ms in (0.5, 3, 15, 60, 300, 900):
        telemetry.observe_ms("t.hist", ms)
    h = telemetry.histograms_snapshot()["t.hist"]
    assert h["buckets"] == [1.0, 5.0, 20.0, 100.0, 500.0]
    # cumulative le semantics: each bucket counts everything <= edge
    assert h["cumulative"] == [1, 2, 3, 4, 5]
    assert h["count"] == 6          # +Inf bucket == count
    assert h["sum"] == pytest.approx(0.5 + 3 + 15 + 60 + 300 + 900)
    # histograms no longer pollute the counter map with .le_ keys
    assert not any(
        ".le_" in k or ".gt_" in k
        for k in telemetry.counters_snapshot()
    )
    # monotonic: a second observation only grows the counts
    telemetry.observe_ms("t.hist", 2)
    h2 = telemetry.histograms_snapshot()["t.hist"]
    assert h2["cumulative"] == [1, 3, 4, 5, 6] and h2["count"] == 7
    # mixed buckets against one name are a bug, not silent corruption
    with pytest.raises(ValueError):
        telemetry.observe_ms("t.hist", 1, buckets=(1, 2))
    telemetry.reset_counters()


def test_observe_ms_boundary_is_le():
    from room_tpu.core import telemetry

    telemetry.reset_counters()
    telemetry.observe_ms("t.edge", 5)     # exactly on an edge: le
    h = telemetry.histograms_snapshot()["t.edge"]
    assert h["cumulative"] == [0, 1, 1, 1, 1]
    telemetry.reset_counters()


# ---- 5. /metrics strict text-format parse ---------------------------

def _strict_parse(text: str) -> dict:
    """Minimal strict Prometheus text-format 0.0.4 parser: families
    must be typed before samples, contiguous, with escaped labels and
    float-parseable values. Returns {family: {"type", "samples"}}."""
    import re

    families: dict = {}
    current = None
    seen_done = set()
    sample_re = re.compile(
        r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"
        r"(?:\{((?:[a-zA-Z_][a-zA-Z0-9_]*="
        r'"(?:[^"\\]|\\.)*",?)*)\})?'
        r" (NaN|[-+]?[0-9.eE+-]+)$"
    )
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            name = line.split(" ", 3)[2]
            assert name not in seen_done, \
                f"family {name} not contiguous"
            current = name
            families[name] = {"type": None, "samples": []}
            continue
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(" ", 3)
            assert name == current, "TYPE without preceding HELP"
            assert kind in ("counter", "gauge", "histogram",
                            "summary", "untyped")
            families[name]["type"] = kind
            continue
        assert not line.startswith("#"), f"bad comment: {line}"
        m = sample_re.match(line)
        assert m, f"malformed sample line: {line!r}"
        base = m.group(1)
        assert current is not None, "sample before any family"
        stripped = base
        for suffix in ("_bucket", "_sum", "_count", "_total"):
            if base == current + suffix:
                stripped = current
        assert stripped == current or base == current, \
            f"sample {base} outside family {current}"
        labels = {}
        if m.group(2):
            for pair in re.findall(
                r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"',
                m.group(2),
            ):
                labels[pair[0]] = pair[1]
        value = float(m.group(3)) if m.group(3) != "NaN" else None
        seen_done.add(current)
        families[current]["samples"].append((base, labels, value))
    return families


def _histogram_consistent(samples, name):
    """Cumulative buckets monotonic per label-set, +Inf == _count."""
    series: dict = {}
    for base, labels, value in samples:
        key = labels.get("name", "")
        series.setdefault(key, {"buckets": [], "count": None,
                                "sum": None})
        if base.endswith("_bucket"):
            series[key]["buckets"].append(
                (labels["le"], value)
            )
        elif base.endswith("_count"):
            series[key]["count"] = value
        elif base.endswith("_sum"):
            series[key]["sum"] = value
    for key, s in series.items():
        assert s["count"] is not None and s["sum"] is not None, \
            (name, key)
        prev = -1.0
        for le, v in s["buckets"]:
            assert v >= prev, f"{name}{{{key}}} not cumulative"
            prev = v
        assert s["buckets"][-1][0] == "+Inf"
        assert s["buckets"][-1][1] == s["count"]


def test_metrics_exposition_strict_parse(build):
    from room_tpu.core import telemetry
    from room_tpu.server import metrics

    telemetry.reset_counters()
    telemetry.incr_counter("fault.decode_window")
    telemetry.incr_counter('weird"name\nwith\\escapes')
    telemetry.observe_ms("offload.restore", 12.5)
    telemetry.observe_ms("offload.restore", 700.0)
    eng = build(1)
    t = eng.submit([1, 2, 3], sampling=_greedy(4),
                   turn_class="queen")
    eng.run_until_idle()
    text = metrics.render_metrics()
    fams = _strict_parse(text)
    assert fams["room_tpu_events_total"]["type"] == "counter"
    events = {
        s[1]["event"]: s[2]
        for s in fams["room_tpu_events_total"]["samples"]
    }
    assert events["fault.decode_window"] == 1
    hist = fams["room_tpu_latency_ms"]
    assert hist["type"] == "histogram"
    _histogram_consistent(hist["samples"], "room_tpu_latency_ms")
    restore = [s for s in hist["samples"]
               if s[1].get("name") == "offload.restore"]
    assert restore, "offload.restore histogram missing"
    # turnscope attribution families
    attr = fams["room_tpu_slo_attribution_ms_total"]
    assert attr["type"] == "counter"
    comps = {(s[1]["class"], s[1]["component"]) for s in
             attr["samples"]}
    assert ("queen", "queue") in comps
    assert ("queen", "wall") in comps
    turns = {(s[1]["class"], s[1]["outcome"]): s[2]
             for s in fams["room_tpu_turns_total"]["samples"]}
    assert turns[("queen", "all")] >= 1
    telemetry.reset_counters()


def test_metrics_disabled_knob(monkeypatch):
    from room_tpu.server import metrics

    monkeypatch.setenv("ROOM_TPU_METRICS", "0")
    assert not metrics.metrics_enabled()
    monkeypatch.setenv("ROOM_TPU_METRICS", "1")
    assert metrics.metrics_enabled()


# ---- 6. routes ------------------------------------------------------

def _route(method, path, body=None, query=None):
    from room_tpu.server.router import RequestContext, Router
    from room_tpu.server.routes import register_aux_routes

    router = Router()
    register_aux_routes(router)
    matched = router.match(method, path)
    assert matched is not None, f"{method} {path} unrouted"
    handler, params = matched
    return handler(RequestContext(
        method=method, path=path, params=params,
        query=query or {}, body=body, principal={"role": "user"},
        db=None,
    ))


def test_trace_route(build):
    eng = build(1)
    t = eng.submit([9, 9], sampling=_greedy(3), turn_class="queen")
    eng.run_until_idle()
    out = _route("GET", "/api/tpu/trace", query={"limit": "5"})
    assert out["status"] == 200
    data = out["data"]
    assert data["enabled"] is True
    assert data["attribution"]["classes"]["queen"]["turns"] >= 1
    assert len(data["recent"]) >= 1
    rec = data["recent"][-1]
    assert {"cid", "spans", "events", "class"} <= set(rec)


def test_metrics_route_wrapper():
    out = _route("GET", "/api/tpu/metrics")
    assert out["status"] == 200
    assert "# TYPE room_tpu_events_total counter" in \
        out["data"]["exposition"]


def test_profile_route(tmp_path, monkeypatch):
    """POST /api/tpu/profile runs a bounded jax.profiler capture
    against the live process and 409s a concurrent start."""
    monkeypatch.setenv("ROOM_TPU_TRACE_DIR", str(tmp_path))
    out = _route("POST", "/api/tpu/profile",
                 body={"duration_s": 0.2})
    assert out["status"] == 202
    assert out["data"]["dir"].startswith(str(tmp_path))
    # a second capture while one runs is a 409, not a corrupted trace
    dup = _route("POST", "/api/tpu/profile",
                 body={"duration_s": 0.2})
    assert dup["status"] == 409
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        st = _route("GET", "/api/tpu/profile")["data"]
        if not st["running"]:
            break
        time.sleep(0.05)
    assert not st["running"]
    assert st.get("error") is None, st
    import os

    assert os.path.isdir(out["data"]["dir"])
    # the capture itself landed in the flight recorder's event ring
    snap = trace.recorder.snapshot()
    assert any(e["kind"] == "profile_capture" for e in snap["events"])


def test_profile_duration_clamped(tmp_path, monkeypatch):
    monkeypatch.setenv("ROOM_TPU_TRACE_DIR", str(tmp_path))
    monkeypatch.setenv("ROOM_TPU_PROFILE_MAX_S", "0.1")
    out = _route("POST", "/api/tpu/profile",
                 body={"duration_s": 9999})
    assert out["status"] == 202
    assert out["data"]["duration_s"] <= 0.1
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if not _route("GET", "/api/tpu/profile")["data"]["running"]:
            break
        time.sleep(0.05)


def test_health_route_carries_trace_block(build):
    from room_tpu.server.router import RequestContext, Router
    from room_tpu.server.routes import register_aux_routes

    eng = build(1)
    t = eng.submit([1, 2], sampling=_greedy(3), turn_class="worker")
    eng.run_until_idle()
    router = Router()
    register_aux_routes(router)
    handler, params = router.match("GET", "/api/tpu/health")
    out = handler(RequestContext(
        method="GET", path="/api/tpu/health", params=params,
        query={}, body=None, principal={"role": "user"}, db=None,
    ))
    assert out["status"] == 200
    data = out["data"]
    assert "trace" in data and "histograms" in data
    assert data["trace"]["classes"]["worker"]["turns"] >= 1


def test_metrics_http_endpoint(tmp_path, monkeypatch):
    """GET /metrics over real HTTP: served pre-auth (scraper
    contract) with the Prometheus content type, 404 when disabled."""
    import urllib.error
    import urllib.request

    from room_tpu.db import Database
    from room_tpu.server.http import ApiServer

    monkeypatch.setenv("ROOM_TPU_DATA_DIR", str(tmp_path / "data"))
    db = Database(":memory:")
    srv = ApiServer(db)
    srv.start()
    try:
        url = f"http://127.0.0.1:{srv.port}/metrics"
        with urllib.request.urlopen(url, timeout=10) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith(
                "text/plain; version=0.0.4"
            )
            body = resp.read().decode()
        fams = _strict_parse(body)
        assert "room_tpu_events_total" in fams
        monkeypatch.setenv("ROOM_TPU_METRICS", "0")
        try:
            with urllib.request.urlopen(url, timeout=10) as resp:
                status = resp.status
        except urllib.error.HTTPError as e:
            status = e.code
        assert status == 404
    finally:
        srv.stop()


# ---- 7. fleet + crash-report integration ----------------------------

def test_router_shed_turn_traced():
    """A fleet-router shed (no healthy replica) books an evidence-ring
    trace even though no engine ever saw the turn."""
    from room_tpu.serving.fleet import EngineFleet

    fleet = EngineFleet.__new__(EngineFleet)
    fleet._stats = {"router_shed": 0}
    fleet._lock = threading.Lock()
    turn = EngineFleet._shed_turn(
        fleet, "lost-session", [1, 2, 3], None, "queen",
        "no healthy replica available; retry shortly",
    )
    assert turn.shed and turn.done.is_set()
    snap = trace.recorder.snapshot()
    viol = [r for r in snap["violations"]
            if r["session"] == "lost-session"]
    assert viol and viol[0]["shed"] is True
    assert viol[0]["class"] == "queen"


def test_crash_report_attaches_evidence(build, monkeypatch, tmp_path):
    """telemetry.submit_crash_report attaches the flight recorder's
    violation traces (resolved through sys.modules, no serving
    import)."""
    from room_tpu.core import telemetry

    eng = build(4, max_batch=2)
    faults.inject("decode_window", times=1, transient=False)
    victim = eng.submit([7, 7], sampling=_greedy(4))
    eng.run_until_idle()
    faults.clear()
    assert victim.finish_reason == "error"
    ev = telemetry._flight_recorder_evidence()
    assert ev and any("decode_window" in r["faults"] for r in ev)


# ---- 8. roomlint fault-trace coverage cross-check -------------------

def test_trace_checker_clean_on_real_tree():
    from room_tpu.analysis.trace_checker import (
        check_fault_trace_coverage,
    )

    import os

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    assert check_fault_trace_coverage(root) == []


def test_trace_checker_flags_missing_and_unknown(tmp_path):
    """A FAULT_POINTS entry missing from FAULT_EVENTS (or a mapping
    for an unknown point, or an unwired should_fire) fails lint."""
    from room_tpu.analysis.trace_checker import (
        check_fault_trace_coverage,
    )

    serving = tmp_path / "room_tpu" / "serving"
    serving.mkdir(parents=True)
    (serving / "faults.py").write_text(
        'FAULT_POINTS = ("kv_alloc", "new_point")\n'
        "def should_fire(name):\n"
        "    _telemetry_count(name)\n"
    )
    (serving / "trace.py").write_text(
        'FAULT_EVENTS = {\n'
        '    "kv_alloc": "fault.kv_alloc",\n'
        '    "typo_point": "fault.typo_point",\n'
        "}\n"
    )
    out = check_fault_trace_coverage(str(tmp_path))
    rules = sorted(v.rule for v in out)
    assert "fault-point-untraced" in rules      # new_point unmapped
    assert "fault-trace-unknown" in rules       # typo_point unknown
    assert "fault-point-unwired" in rules       # _trace_event missing


def test_fault_events_match_registry():
    """Belt-and-braces runtime twin of the static check."""
    assert set(trace.FAULT_EVENTS) == set(faults.FAULT_POINTS)
    for point, event in trace.FAULT_EVENTS.items():
        assert event == f"fault.{point}"
