"""Swarm shard suite (docs/swarmshard.md).

CI quick tier (lockdep-armed in the chaos job) for the room-partitioned
swarm runtime: placement + ID striding, cross-shard dispatch
exactly-once, shard crash + journal adoption, N→M re-placement, and the
``shard_crash`` chaos fault point:

- ID striding: every shard file mints from its own
  billion-wide band, so ids (and their placement hashes) never collide
  across files.
- Cross-shard message_send / escalation ride journaled effect intents
  keyed by content-derived idempotency keys: a duplicate dispatch (the
  retry after a crash) returns the SAME row ids and writes nothing.
- Killing a shard sheds its rooms (ShardDownError) for the swarm
  lease; the least-loaded sibling then reopens the file, runs journal
  recovery, and takes ownership under a NEW placement epoch — after
  which a redelivery of the pre-crash dispatch still dedups (zero
  double-fired effects).
- resize_swarm N→M moves every re-homed room's whole row-set with zero
  journal rows lost and ids preserved.
- faults.inject("shard_crash") kills the busiest serving shard at the
  next supervise; adoption heals it.
- The runtime ticks iterate every shard when the default router is
  armed (ROOM_TPU_SWARM_SHARDS).
"""

import threading

import pytest

from room_tpu.core import journal as journal_mod
from room_tpu.core.events import event_bus
from room_tpu.db import Database
from room_tpu.serving import faults
from room_tpu.swarm import (
    ShardDownError, SwarmRouter, maybe_default_router,
    reset_default_router, resize_swarm, shard_db_path,
)
from room_tpu.swarm.shard import ID_STRIDE


@pytest.fixture(autouse=True)
def _clean_state():
    faults.clear()
    reset_default_router()
    yield
    faults.clear()
    reset_default_router()


@pytest.fixture()
def router(tmp_path):
    r = SwarmRouter(n_shards=4, db_dir=str(tmp_path), lease_s=0.0)
    yield r
    r.close()


def _room_on_shard(router, shard_id):
    """Create rooms until one lands on ``shard_id`` (data home)."""
    for i in range(64):
        room = router.create_room(f"probe-{i}")
        if router.base_home(room["id"]) == shard_id:
            return room
    raise AssertionError("allocator never hit the shard")


def _two_rooms_on_distinct_shards(router):
    a = router.create_room("alpha")
    for _ in range(64):
        b = router.create_room("beta")
        if router.base_home(b["id"]) != router.base_home(a["id"]):
            return a, b
    raise AssertionError("allocator never split shards")


# ---- placement + striding ----

def test_id_striding_and_placement(router):
    """Each shard file mints from its own billion-wide band; the
    swarm-global room counter keeps room ids unique; db_for routes by
    the placement map."""
    rooms = [router.create_room(f"room-{i}") for i in range(8)]
    ids = [r["id"] for r in rooms]
    assert len(set(ids)) == 8
    homes = {router.base_home(i) for i in ids}
    assert len(homes) > 1           # 8 rooms spread over 4 shards
    for rid in ids:
        home = router.base_home(rid)
        db = router.db_for(rid)
        assert db is router.shards[home].db
        row = db.query_one("SELECT id FROM rooms WHERE id=?", (rid,))
        assert row is not None
        # the queen worker's id came from the shard's strided band
        w = db.query_one(
            "SELECT id FROM workers WHERE room_id=?", (rid,)
        )
        if home > 0:
            assert w["id"] >= home * ID_STRIDE
        else:
            assert w["id"] < ID_STRIDE


def test_shard_db_paths(tmp_path):
    assert shard_db_path(2, str(tmp_path)).endswith("shard2.db")
    assert shard_db_path(0, str(tmp_path)).endswith("shard0.db")


def test_meta_db_and_single_shard_back_compat(tmp_path):
    """n_shards=1 is the classic runtime: one file, no striding, no
    cross-shard seam — send_message stays a same-DB insert pair."""
    r = SwarmRouter(n_shards=1, db_dir=str(tmp_path))
    try:
        a = r.create_room("a")
        b = r.create_room("b")
        assert a["id"] < ID_STRIDE and b["id"] < ID_STRIDE
        out_id, in_id = r.send_message(a["id"], b["id"], "s", "hello")
        assert out_id and in_id
        assert r.stats["cross_shard_messages"] == 0
    finally:
        r.close()


def test_maybe_default_router_gated_by_knob(monkeypatch, tmp_path):
    monkeypatch.setenv("ROOM_TPU_SWARM_DB_DIR", str(tmp_path))
    monkeypatch.setenv("ROOM_TPU_SWARM_SHARDS", "1")
    assert maybe_default_router() is None
    monkeypatch.setenv("ROOM_TPU_SWARM_SHARDS", "3")
    r = maybe_default_router()
    assert r is not None and r.n_shards == 3
    assert maybe_default_router() is r    # cached singleton
    reset_default_router()
    assert r._closed


# ---- cross-shard dispatch: exactly-once ----

def test_cross_shard_message_exactly_once(router):
    a, b = _two_rooms_on_distinct_shards(router)
    out_id, in_id = router.send_message(
        a["id"], b["id"], "subj", "body-1"
    )
    assert out_id and in_id
    # the duplicate dispatch (a retry) returns the SAME ids and
    # writes nothing
    again = router.send_message(a["id"], b["id"], "subj", "body-1")
    assert again == (out_id, in_id)
    assert router.stats["dedup_skips"] >= 1
    inbound = router.db_for(b["id"]).query(
        "SELECT * FROM room_messages WHERE to_room_id=?", (b["id"],)
    )
    assert len(inbound) == 1
    # a DIFFERENT body is a different effect: new row
    router.send_message(a["id"], b["id"], "subj", "body-2")
    inbound = router.db_for(b["id"]).query(
        "SELECT * FROM room_messages WHERE to_room_id=?", (b["id"],)
    )
    assert len(inbound) == 2
    assert router.stats["cross_shard_messages"] >= 2


def test_cross_shard_escalation_exactly_once(router):
    a, b = _two_rooms_on_distinct_shards(router)
    del a
    eid = router.escalate(b["id"], "need a keeper?")
    assert router.escalate(b["id"], "need a keeper?") == eid
    rows = router.db_for(b["id"]).query(
        "SELECT * FROM escalations WHERE room_id=?", (b["id"],)
    )
    assert len(rows) == 1
    assert router.stats["cross_shard_escalations"] >= 1


def test_xshard_journal_rows_survive_recovery(router):
    """journal.recover() must not flag committed xshard effect rows:
    they are the dedup evidence, not abandoned work."""
    a, b = _two_rooms_on_distinct_shards(router)
    ids = router.send_message(a["id"], b["id"], "s", "m")
    db = router.db_for(b["id"])
    journal_mod.recover(db)
    assert router.send_message(a["id"], b["id"], "s", "m") == ids


# ---- shard crash + adoption ----

def test_shard_crash_sheds_then_adoption_redelivers_exactly_once(
    router,
):
    a, b = _two_rooms_on_distinct_shards(router)
    victim = router.base_home(b["id"])
    pre_epoch = router.placement.epoch
    ids = router.send_message(a["id"], b["id"], "s", "pre-crash")
    router.kill_shard(victim, reason="test")
    assert router.shards[victim].state == "dead"
    # dead window (lease_s=0 still sheds until adopt runs): the
    # victim's rooms shed with the transient-error contract
    with pytest.raises(ShardDownError) as ei:
        router.db_for(b["id"])
    assert ei.value.shard_id == victim and ei.value.transient
    assert router.stats["sheds"] >= 1
    adopted = router.adopt_dead_shards()
    assert len(adopted) == 1
    assert adopted[0]["shard"] == victim
    assert router.placement.epoch == pre_epoch + 1
    assert router.shards[victim].state == "retired"
    # ownership moved: the adopter serves the victim's rooms over the
    # reopened origin file
    adopter = adopted[0]["adopter"]
    assert router.owner_of(b["id"]) == adopter
    db = router.db_for(b["id"])
    assert db.query_one(
        "SELECT id FROM rooms WHERE id=?", (b["id"],)
    )
    # the pre-crash dispatch REDELIVERED post-adoption dedups: zero
    # double-fired effects across the failover
    assert router.send_message(a["id"], b["id"], "s", "pre-crash") \
        == ids
    inbound = db.query(
        "SELECT * FROM room_messages WHERE to_room_id=?", (b["id"],)
    )
    assert len(inbound) == 1


def test_kill_last_serving_shard_refused(tmp_path):
    r = SwarmRouter(n_shards=2, db_dir=str(tmp_path), lease_s=0.0)
    try:
        assert r.kill_shard(0) is True
        assert r.kill_shard(1) is False   # nobody left to adopt
        assert r.shards[1].state == "serving"
    finally:
        r.close()


def test_shard_crash_fault_point_heals(router):
    """faults.inject("shard_crash") kills the busiest serving shard at
    the next supervise; the same pass adopts it (lease 0)."""
    _room_on_shard(router, 2)
    faults.inject("shard_crash", times=1)
    router.supervise()
    assert faults.fired("shard_crash") == 1
    assert router.stats["shard_crashes"] == 1
    assert router.stats["adoptions"] == 1
    states = [s.state for s in router.shards]
    assert states.count("retired") == 1
    assert states.count("serving") == 3


# ---- event-bus segments ----

def test_room_events_land_on_owning_shard_segment(router):
    a, b = _two_rooms_on_distinct_shards(router)
    sa = router.shards[router.base_home(a["id"])]
    sb = router.shards[router.base_home(b["id"])]
    got = []
    unsub = sb.bus.subscribe(None, got.append)
    try:
        event_bus.emit("x:ping", f"room:{a['id']}", {})
        event_bus.emit("x:ping", f"room:{b['id']}", {})
        event_bus.emit("x:ping", "runtime", {})   # non-room: untouched
    finally:
        unsub()
    assert [e.channel for e in got] == [f"room:{b['id']}"]
    assert sa.stats["events"] >= 1 and sb.stats["events"] >= 1


# ---- N→M re-placement ----

def test_resize_moves_rooms_zero_journal_loss(tmp_path):
    r = SwarmRouter(n_shards=4, db_dir=str(tmp_path), lease_s=0.0)
    rooms = [
        r.create_room(f"room-{i}", goal=f"goal {i}") for i in range(6)
    ]
    a, b = rooms[0], next(
        x for x in rooms[1:]
        if r.base_home(x["id"]) != r.base_home(rooms[0]["id"])
    )
    ids = r.send_message(a["id"], b["id"], "s", "survives resize")
    r2, summary = resize_swarm(r, 2, db_dir=str(tmp_path))
    try:
        assert summary["old_shards"] == 4
        assert summary["new_shards"] == 2
        assert summary["journal_rows_lost"] == 0
        assert summary["rooms_moved"] + summary["rooms_kept"] == 6
        for room in rooms:
            db = r2.db_for(room["id"])
            row = db.query_one(
                "SELECT id, name FROM rooms WHERE id=?", (room["id"],)
            )
            assert row is not None and row["name"] == room["name"]
            # the whole row-set moved with it
            assert db.query_one(
                "SELECT id FROM workers WHERE room_id=?",
                (room["id"],),
            ) is not None
            assert db.query_one(
                "SELECT id FROM goals WHERE room_id=?", (room["id"],)
            ) is not None
        # dedup evidence moved too: the pre-resize dispatch still
        # dedups on the new topology
        assert r2.send_message(a["id"], b["id"], "s",
                               "survives resize") == ids
        # new rooms keep minting unique ids
        extra = r2.create_room("post-resize")
        assert extra["id"] not in {x["id"] for x in rooms}
    finally:
        r2.close()


def test_resize_scale_up(tmp_path):
    r = SwarmRouter(n_shards=2, db_dir=str(tmp_path), lease_s=0.0)
    rooms = [r.create_room(f"room-{i}") for i in range(5)]
    r2, summary = resize_swarm(r, 5, db_dir=str(tmp_path))
    try:
        assert summary["new_shards"] == 5
        assert summary["journal_rows_lost"] == 0
        for room in rooms:
            assert r2.db_for(room["id"]).query_one(
                "SELECT id FROM rooms WHERE id=?", (room["id"],)
            ) is not None
    finally:
        r2.close()


# ---- schema v3 migration ----

def test_v2_journal_migrates_to_xshard_check(tmp_path):
    """A pre-v3 file (kind CHECK without 'xshard') is rebuilt in place:
    old rows survive, new xshard intents insert."""
    from room_tpu.db.schema import SCHEMA

    path = str(tmp_path / "old.db")
    import sqlite3

    conn = sqlite3.connect(path)
    conn.executescript(
        SCHEMA.replace("'cycle','task_run','xshard'",
                       "'cycle','task_run'")
    )
    conn.execute(
        "INSERT INTO cycle_journal(kind, ref_id, room_id, worker_id, "
        "entry, status) VALUES ('cycle', 7, 1, 1, 'started', 'open')"
    )
    # stamp the ledger at v2 so the next open runs the v3 rebuild
    # (an EMPTY ledger means a fresh file: migrations stamp-only)
    conn.execute("INSERT INTO schema_migrations(version) VALUES (1)")
    conn.execute("INSERT INTO schema_migrations(version) VALUES (2)")
    conn.commit()
    conn.close()
    db = Database(path)
    try:
        rows = db.query("SELECT * FROM cycle_journal")
        assert len(rows) == 1 and rows[0]["kind"] == "cycle"
        assert rows[0]["ref_id"] == 7
        db.execute(
            "INSERT INTO cycle_journal(kind, ref_id, room_id, "
            "worker_id, entry, status, idem_key) VALUES "
            "('xshard', 0, 1, 1, 'effect', 'intent', 'k1')"
        )
        assert db.query_one(
            "SELECT kind FROM cycle_journal WHERE idem_key='k1'"
        )["kind"] == "xshard"
    finally:
        db.close()


# ---- runtime integration ----

def test_runtime_ticks_iterate_all_shards(monkeypatch, tmp_path):
    """With the default router armed, ServerRuntime's ticks cover
    every shard: a stale run on shard N is swept without the runtime
    holding that shard's handle."""
    from room_tpu.server.runtime import ServerRuntime

    monkeypatch.setenv("ROOM_TPU_SWARM_DB_DIR", str(tmp_path))
    monkeypatch.setenv("ROOM_TPU_SWARM_SHARDS", "3")
    router = maybe_default_router()
    assert router is not None
    rooms = [router.create_room(f"room-{i}") for i in range(4)]
    rt = ServerRuntime(db=router.db_for())
    # a crash-stranded worker on every shard
    for room in rooms:
        db = router.db_for(room["id"])
        db.execute(
            "UPDATE workers SET agent_state='running' WHERE room_id=?",
            (room["id"],),
        )
    n = rt.cleanup_stale(startup=True)
    assert n >= len(rooms)
    for room in rooms:
        w = router.db_for(room["id"]).query_one(
            "SELECT agent_state FROM workers WHERE room_id=?",
            (room["id"],),
        )
        assert w["agent_state"] == "idle"
    # per-shard supervision domains are distinct objects
    doms = {id(s.domain) for s in router.shards}
    assert len(doms) == len(router.shards)
    rt.supervision_tick()   # covers router.supervise() + every domain


def test_concurrent_cross_shard_sends_stay_exactly_once(router):
    """The storm seam in miniature: many threads redeliver the same
    logical message; exactly one inbound row lands."""
    a, b = _two_rooms_on_distinct_shards(router)
    results, errs = [], []

    def fire():
        try:
            results.append(
                router.send_message(a["id"], b["id"], "s", "dup")
            )
        except Exception as e:       # pragma: no cover - diagnostics
            errs.append(e)

    threads = [threading.Thread(target=fire) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    assert len(set(results)) == 1
    inbound = router.db_for(b["id"]).query(
        "SELECT * FROM room_messages WHERE to_room_id=?", (b["id"],)
    )
    assert len(inbound) == 1
