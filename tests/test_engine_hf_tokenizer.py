"""End-to-end: the serving engine running on the committed BPE fixture
tokenizer — chat render → encode → generate → decode, with id-based
stop/tool detection on a real (mini) vocabulary. Closes the loop the
round-1 verdict flagged: tokenizer fidelity exercised THROUGH the
engine, not just beside it."""

import os

import jax
import pytest

from room_tpu.models import qwen3, tiny_moe
from room_tpu.serving import SamplingParams, ServingEngine, render_chat
from room_tpu.serving.tokenizer import HFTokenizer

TOK_DIR = os.path.join(
    os.path.dirname(__file__), "fixtures", "qwen_mini_tokenizer"
)


@pytest.fixture(scope="module")
def hf_engine():
    tok = HFTokenizer(TOK_DIR)
    cfg = tiny_moe(vocab_size=max(512, tok.vocab_size))
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    return ServingEngine(
        cfg, params, tokenizer=tok, max_batch=2, page_size=8,
        n_pages=64,
    ), tok


def test_engine_uses_hf_ids_for_stops(hf_engine):
    eng, tok = hf_engine
    assert eng._tool_end_id == tok.encode("</tool_call>")[0]
    assert tok.eos_id in eng.stop_token_ids
    im_end = tok.encode("<|im_end|>")
    assert len(im_end) == 1 and im_end[0] in eng.stop_token_ids


def test_chat_render_generate_decode_roundtrip(hf_engine):
    eng, tok = hf_engine
    prompt = render_chat([
        {"role": "system", "content": "You are a helpful assistant."},
        {"role": "user", "content": "What is the weather in Paris?"},
    ])
    ids = tok.encode(prompt)
    turn = eng.submit(
        ids, sampling=SamplingParams(temperature=0.0, max_new_tokens=8)
    )
    eng.run_until_idle()
    assert turn.finish_reason in ("stop", "length", "tool_call")
    text = eng.text_of(turn)
    assert isinstance(text, str)
    # decoded output re-encodes into the same ids when no stop-token
    # boundary was crossed mid-merge (BPE roundtrip on generated ids)
    assert tok.decode(turn.new_tokens) == text


def test_two_hf_turns_batch_identically(hf_engine):
    eng, tok = hf_engine
    sp = SamplingParams(temperature=0.0, max_new_tokens=5)
    a = eng.submit(tok.encode("hello world"), sampling=sp)
    b = eng.submit(tok.encode("the quick brown fox"), sampling=sp)
    eng.run_until_idle()

    eng2_tok = HFTokenizer(TOK_DIR)
    cfg = tiny_moe(vocab_size=max(512, eng2_tok.vocab_size))
    params = eng.params
    eng2 = ServingEngine(
        cfg, params, tokenizer=eng2_tok, max_batch=2, page_size=8,
        n_pages=64,
    )
    a2 = eng2.submit(eng2_tok.encode("hello world"), sampling=sp)
    eng2.run_until_idle()
    b2 = eng2.submit(eng2_tok.encode("the quick brown fox"),
                     sampling=sp)
    eng2.run_until_idle()
    assert a.new_tokens == a2.new_tokens
    assert b.new_tokens == b2.new_tokens


def test_fts_query_sanitized(db):
    """User-supplied MATCH strings with FTS operators must not raise
    (gotcha recorded in the verify skill)."""
    from room_tpu.core import memory

    memory.remember(db, "note", "parentheses (everywhere)")
    for evil in ('"unbalanced', "a AND OR b", "x NEAR/ y", "col:val",
                 "-minus", "wild*card"):
        out = memory.hybrid_search(db, evil)
        assert isinstance(out, list)
