"""Serving-engine edge behaviors + profiling subsystem: parking/resume
token identity, eviction interplay with parked tool-call sessions,
stats surface, StepTimer/HttpProfiler (density push, VERDICT r1 #9)."""

import time

import jax
import numpy as np
import pytest

from room_tpu.models import qwen3, tiny_moe
from room_tpu.serving import SamplingParams, ServingEngine


@pytest.fixture(scope="module")
def setup():
    cfg = tiny_moe()
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def make_engine(cfg, params, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("page_size", 4)
    kw.setdefault("n_pages", 32)
    return ServingEngine(cfg, params, **kw)


def test_parked_session_resume_is_token_identical(setup):
    """Park via tool-call stop, resume with the tool response: the
    generated continuation must equal a run where the whole context was
    prefilled fresh (KV-resident resume is exact, not approximate)."""
    cfg, params = setup
    tok_end = None
    eng = make_engine(cfg, params)
    tok_end = eng.tokenizer.encode("</tool_call>")[0]
    sp = SamplingParams(temperature=0.0, max_new_tokens=6)

    # drive a session to a parked state by feeding the tool-end token
    # through the prompt (engine parks on the sampled token, so instead
    # emulate: turn 1 normally, then resume turn with extra tokens)
    t1 = eng.submit([5, 6, 7], session_id="park", sampling=sp)
    eng.run_until_idle()
    resume_prompt = [9, 9, tok_end, 8]
    t2 = eng.submit(resume_prompt, session_id="park", sampling=sp)
    eng.run_until_idle()
    assert t2.finish_reason in ("stop", "length", "tool_call")

    # fresh engine, one flat prefill of the full equivalent context
    eng2 = make_engine(cfg, params)
    full = ([5, 6, 7] + t1.new_tokens[:-1] + [t1.new_tokens[-1]]
            + resume_prompt)
    t3 = eng2.submit(full, session_id="flat", sampling=sp)
    eng2.run_until_idle()
    assert t2.new_tokens == t3.new_tokens


def test_eviction_prefers_idle_over_parked_recency(setup):
    """LRU considers last_used: the most recently parked session
    survives longer than one idle for ages."""
    cfg, params = setup
    eng = make_engine(cfg, params, max_batch=1, n_pages=9)
    sp = SamplingParams(temperature=0.0, max_new_tokens=3)
    eng.submit([1, 2], session_id="old", sampling=sp)
    eng.run_until_idle()
    eng.submit([3, 4], session_id="new", sampling=sp)
    eng.run_until_idle()
    eng.sessions["old"].last_used -= 1000
    # a third session forces one eviction: "old" must be the victim
    eng.submit([5, 6], session_id="third", sampling=sp)
    eng.run_until_idle()
    assert eng.stats()["evictions"] >= 1
    assert eng.sessions["old"].length == 0      # evicted
    assert eng.sessions["new"].length > 0       # survived


def test_stats_surface(setup):
    cfg, params = setup
    eng = make_engine(cfg, params)
    st = eng.stats()
    for key in ("tokens_decoded", "turns_completed", "prefill_tokens",
                "decode_steps", "evictions", "queued", "active_slots",
                "phases"):
        assert key in st
    eng.submit([1], sampling=SamplingParams(temperature=0.0,
                                            max_new_tokens=2))
    eng.run_until_idle()
    st = eng.stats()
    assert st["turns_completed"] == 1
    assert st["tokens_decoded"] >= 1
    assert st["phases"]  # StepTimer recorded prefill/decode


def test_max_new_tokens_zero_finishes_immediately(setup):
    cfg, params = setup
    eng = make_engine(cfg, params)
    t = eng.submit([1, 2], sampling=SamplingParams(max_new_tokens=0))
    eng.run_until_idle()
    assert t.finish_reason == "length" and t.new_tokens == []


def test_on_token_callback_streams(setup):
    cfg, params = setup
    eng = make_engine(cfg, params)
    seen = []
    t = eng.submit(
        [1, 2, 3],
        sampling=SamplingParams(temperature=0.0, max_new_tokens=4),
        on_token=seen.append,
    )
    eng.run_until_idle()
    assert seen == t.new_tokens


def test_on_token_exception_does_not_kill_turn(setup):
    cfg, params = setup
    eng = make_engine(cfg, params)

    def boom(tok):
        raise RuntimeError("subscriber bug")

    t = eng.submit(
        [1, 2], sampling=SamplingParams(temperature=0.0,
                                        max_new_tokens=3),
        on_token=boom,
    )
    eng.run_until_idle()
    assert t.finish_reason in ("stop", "length")


# ---- profiling ----

def test_step_timer_phases():
    from room_tpu.utils.profiling import StepTimer

    t = StepTimer()
    with t.phase("prefill"):
        pass
    with t.phase("prefill"):
        pass
    with t.phase("decode"):
        pass
    snap = t.snapshot()
    assert snap["prefill"]["count"] == 2
    assert snap["decode"]["count"] == 1
    assert snap["prefill"]["total_s"] >= 0


def test_http_profiler_aggregates():
    from room_tpu.utils.profiling import HttpProfiler

    p = HttpProfiler()
    p.record("GET", "/api/rooms", 12.0)
    p.record("GET", "/api/rooms", 8.0)
    p.record("POST", "/api/tasks", 5.0)
    snap = p.snapshot()
    rooms_key = [k for k in snap if "rooms" in k][0]
    assert snap[rooms_key]["count"] == 2
    assert snap[rooms_key]["mean_ms"] == pytest.approx(10.0)
    assert snap[rooms_key]["p95_ms"] in (8.0, 12.0)


def test_http_profiling_endpoint(tmp_path, monkeypatch):
    from tests.test_server import req

    from room_tpu.db import Database
    from room_tpu.server.http import ApiServer

    monkeypatch.setenv("ROOM_TPU_DATA_DIR", str(tmp_path))
    monkeypatch.setenv("ROOM_TPU_PROFILE_HTTP", "1")
    db = Database(":memory:")
    srv = ApiServer(db)
    srv.start()
    try:
        req(srv, "GET", "/api/rooms")
        # the sample is recorded in the handler's finally block AFTER
        # the response flushes, so poll briefly instead of racing it
        deadline = time.time() + 5
        while True:
            status, out = req(srv, "GET", "/api/profiling/http")
            assert status == 200
            if any("rooms" in k for k in out["data"]):
                break
            assert time.time() < deadline, out["data"]
            time.sleep(0.05)
    finally:
        srv.stop()


# ---- small accounting/spec edges ----

def test_page_table_capacity_accounting():
    from room_tpu.serving import PageTable

    pt = PageTable(n_pages=8, page_size=4)
    pt.ensure_capacity("s", 9)  # 3 pages
    assert pt.tokens_capacity("s") == 12
    assert pt.pages_of("s") != pt.pages_of("missing") == []


def test_make_mesh_rejects_oversized_spec():
    from room_tpu.parallel import MeshSpec, make_mesh

    spec = MeshSpec(dp=64, ep=64, tp=64)
    assert spec.n_devices == 64 ** 3
    with pytest.raises(ValueError, match="needs"):
        make_mesh(spec)


def test_page_cache_specs_tp_fallback():
    """KV-head axis shards over tp only when divisible; otherwise the
    heads stay replicated rather than erroring."""
    import jax
    from jax.sharding import Mesh
    from room_tpu.models.config import tiny_moe as tiny_cfg
    from room_tpu.parallel import page_cache_specs

    cfg = tiny_cfg()  # 2 kv heads
    devs = np.array(jax.devices()[:8])
    mesh2 = Mesh(devs.reshape(4, 2), ("dp", "tp"))   # tp=2 divides
    spec = page_cache_specs(cfg, mesh2)
    assert spec["k_pages"][3] == "tp"
    mesh8 = Mesh(devs.reshape(1, 8), ("dp", "tp"))   # tp=8 doesn't
    spec = page_cache_specs(cfg, mesh8)
    assert spec["k_pages"][3] is None


def test_sampling_params_defaults():
    assert SamplingParams().top_k == 0          # full vocab
    assert SamplingParams().top_p == 1.0        # off
    assert SamplingParams().max_new_tokens > 0


def test_release_unknown_session_is_noop(setup):
    cfg, params = setup
    eng = make_engine(cfg, params)
    eng.release_session("never-existed")  # must not raise
    assert eng.stats()["turns_completed"] == 0


# ---- sampler fast path ----

def test_sampler_fast_path_matches_full_sort_oracle():
    """Peaked distributions ride the top-K fast path; the token chosen
    must equal the full-sort reference bit-for-bit."""
    import jax.numpy as jnp

    from room_tpu.serving.sampler import (
        _sample_batched_sorted, sample_batched,
    )

    rng = np.random.default_rng(0)
    vocab = 4096
    for trial in range(6):
        # peaked: a few dominant logits per row
        logits = rng.standard_normal((4, vocab)).astype(np.float32)
        logits[:, :8] += 12.0
        logits = jnp.asarray(logits)
        key = jax.random.PRNGKey(trial)
        temps = jnp.asarray(rng.uniform(0.2, 1.2, 4), jnp.float32)
        tops = jnp.asarray([0.9, 0.95, 1.0, 0.8][: 4], jnp.float32)
        ks = jnp.asarray([0, 40, 5, 0], jnp.int32)
        fast = sample_batched(logits, key, temps, tops, ks)
        want = _sample_batched_sorted(logits, key, temps, tops, ks)
        assert fast.tolist() == want.tolist(), trial


def test_sampler_flat_distribution_falls_back_exactly():
    """Near-uniform logits can't cover top_p in the prefix: the cond
    fallback must produce the same tokens as the reference."""
    import jax.numpy as jnp

    from room_tpu.serving.sampler import (
        _sample_batched_sorted, sample_batched,
    )

    vocab = 4096
    logits = jnp.zeros((3, vocab), jnp.float32) + \
        jax.random.normal(jax.random.PRNGKey(9), (3, vocab)) * 0.01
    key = jax.random.PRNGKey(1)
    temps = jnp.asarray([1.0, 0.7, 1.0], jnp.float32)
    tops = jnp.asarray([0.99, 0.95, 0.9], jnp.float32)
    ks = jnp.asarray([0, 0, 200], jnp.int32)  # k=200 > K forces slow
    fast = sample_batched(logits, key, temps, tops, ks)
    want = _sample_batched_sorted(logits, key, temps, tops, ks)
    assert fast.tolist() == want.tolist()


def test_sampler_greedy_rows_unaffected_by_fast_path():
    import jax.numpy as jnp

    from room_tpu.serving.sampler import sample_batched

    vocab = 4096
    logits = jax.random.normal(jax.random.PRNGKey(3), (2, vocab))
    toks = sample_batched(
        logits, jax.random.PRNGKey(0),
        jnp.asarray([0.0, 0.0]), jnp.asarray([0.9, 1.0]),
        jnp.asarray([40, 0], jnp.int32),
    )
    assert toks.tolist() == jnp.argmax(logits, axis=-1).tolist()


def test_admission_batch_never_evicts_its_own_preps(setup):
    """Two sessions prepped in ONE _admit pass with a pool that only
    fits one: the second must requeue (not evict the first, whose
    prefill is imminent). Both eventually complete token-identically to
    sequential runs on a roomy pool."""
    cfg, params = setup
    sp = SamplingParams(temperature=0.0, max_new_tokens=4)

    eng = make_engine(cfg, params, max_batch=2, n_pages=7)
    a = eng.submit([1, 2, 3], session_id="A", sampling=sp)
    b = eng.submit([9, 8, 7], session_id="B", sampling=sp)
    eng.run_until_idle()
    assert a.finish_reason in ("stop", "length")
    assert b.finish_reason in ("stop", "length"), b.error

    big = make_engine(cfg, params, max_batch=2, n_pages=64)
    a2 = big.submit([1, 2, 3], session_id="A", sampling=sp)
    big.run_until_idle()
    b2 = big.submit([9, 8, 7], session_id="B", sampling=sp)
    big.run_until_idle()
    assert a.new_tokens == a2.new_tokens
    assert b.new_tokens == b2.new_tokens


def test_prefix_covers_exempts_disabled_top_p():
    """top_p=1 rows (incl. idle slot padding) must not force the
    full-sort fallback."""
    import jax.numpy as jnp

    from room_tpu.serving.sampler import SAMPLE_FAST_K, _prefix_covers

    vocab = 4096
    flat = jax.random.normal(jax.random.PRNGKey(0), (2, vocab)) * 0.01
    top_vals = jax.lax.top_k(flat, SAMPLE_FAST_K)[0]
    assert bool(_prefix_covers(
        flat, top_vals, jnp.asarray([1.0, 1.0]),
        jnp.asarray([0, 0], jnp.int32), SAMPLE_FAST_K,
    ))
    # but a row genuinely needing mass coverage still falls back
    assert not bool(_prefix_covers(
        flat, top_vals, jnp.asarray([0.95, 1.0]),
        jnp.asarray([0, 0], jnp.int32), SAMPLE_FAST_K,
    ))


def test_chunked_long_prefill_token_identical(setup):
    """A prompt longer than prefill_chunk streams through KV-write-only
    chunks, then the tail samples — tokens identical to one-shot
    prefill."""
    cfg, params = setup
    sp = SamplingParams(temperature=0.0, max_new_tokens=5)
    prompt = [(i * 7) % 100 + 1 for i in range(45)]

    one_shot = make_engine(cfg, params, n_pages=64)
    one_shot.prefill_chunk = 0
    a = one_shot.submit(prompt, sampling=sp)
    one_shot.run_until_idle()

    chunked = make_engine(cfg, params, n_pages=64)
    chunked.prefill_chunk = 16   # 45 tokens -> 2 chunks + 13-token tail
    b = chunked.submit(prompt, sampling=sp)
    chunked.run_until_idle()

    assert a.finish_reason in ("stop", "length")
    assert b.new_tokens == a.new_tokens
    # chunk writes showed up as their own timer phases
    assert any(k.startswith("prefill_write_16")
               for k in chunked.timer.snapshot())


def test_chunked_prefill_resume_continuation(setup):
    """Chunked prefill composes with session continuation (the resume
    prompt itself gets chunked against existing KV)."""
    cfg, params = setup
    sp = SamplingParams(temperature=0.0, max_new_tokens=4)

    def run(chunk):
        eng = make_engine(cfg, params, n_pages=64)
        eng.prefill_chunk = chunk
        t1 = eng.submit([1, 2, 3], session_id="s", sampling=sp)
        eng.run_until_idle()
        t2 = eng.submit(list(range(5, 45)), session_id="s",
                        sampling=sp)
        eng.run_until_idle()
        return t1.new_tokens, t2.new_tokens

    assert run(0) == run(12)


# ---- automatic prefix caching ----

def _shared_prompts():
    shared = [(i * 3) % 90 + 1 for i in range(17)]   # aligned 16 @ page 4
    return shared + [7, 8], shared + [70, 71, 72]


def test_prefix_cache_hit_token_identical(setup):
    """Second session sharing a long prompt prefix reuses the cached
    pages: same tokens as an uncached engine, fewer prefill tokens."""
    cfg, params = setup
    sp = SamplingParams(temperature=0.0, max_new_tokens=4)
    p1, p2 = _shared_prompts()

    off = make_engine(cfg, params, n_pages=64)
    off.prefix_cache_min_pages = 0
    a_off = off.submit(p1, session_id="a", sampling=sp)
    off.run_until_idle()
    b_off = off.submit(p2, session_id="b", sampling=sp)
    off.run_until_idle()

    on = make_engine(cfg, params, n_pages=64)
    on.prefix_cache_min_pages = 2
    a_on = on.submit(p1, session_id="a", sampling=sp)
    on.run_until_idle()
    b_on = on.submit(p2, session_id="b", sampling=sp)
    on.run_until_idle()

    assert a_on.new_tokens == a_off.new_tokens
    assert b_on.new_tokens == b_off.new_tokens
    st = on.stats()
    assert st["prefix_hits"] == 1
    assert st["prefix_tokens_reused"] == 16
    # the hit prefilled only the unshared tail
    assert st["prefill_tokens"] < off.stats()["prefill_tokens"]


def test_prefix_cache_share_pages_accounting(setup):
    """Cached prefix pages are owned once: two sessions referencing
    them hold fewer pool pages than two full copies."""
    cfg, params = setup
    sp = SamplingParams(temperature=0.0, max_new_tokens=3)
    p1, p2 = _shared_prompts()

    on = make_engine(cfg, params, n_pages=64)
    on.prefix_cache_min_pages = 2
    on.submit(p1, session_id="a", sampling=sp)
    on.run_until_idle()
    free_after_first = on.page_table.free_pages
    on.submit(p2, session_id="b", sampling=sp)
    on.run_until_idle()
    # session b added only bucket-padded tail pages (4 @ page_size 4),
    # NOT another copy of the 4-page prefix + tail (the uncached cost:
    # 19 tokens -> bucket 32 -> 8 pages)
    assert free_after_first - on.page_table.free_pages <= 4

    # released sessions return their own pages; the prefix entry stays
    # cached (refcount 0) until pool pressure evicts it
    on.release_session("a")
    on.release_session("b")
    assert len(on._prefix_cache) == 1
    entry = next(iter(on._prefix_cache.values()))
    assert not entry.sessions and entry.ready


def test_prefix_cache_evicted_under_pressure(setup):
    cfg, params = setup
    sp = SamplingParams(temperature=0.0, max_new_tokens=3)
    p1, _ = _shared_prompts()
    eng = make_engine(cfg, params, max_batch=1, n_pages=14)
    eng.prefix_cache_min_pages = 2
    eng.submit(p1, session_id="a", sampling=sp)
    eng.run_until_idle()
    eng.release_session("a")
    assert len(eng._prefix_cache) == 1
    # one big new session needs more pages than remain free, and no
    # idle session exists to evict: the orphaned prefix must go
    t = eng.submit([300] * 33, session_id="big", sampling=sp)
    eng.run_until_idle()
    assert t.finish_reason in ("stop", "length"), t.error
    assert eng.stats()["prefix_evictions"] >= 1


def test_prefix_hit_session_survives_own_eviction(setup):
    """A session that used a cached prefix, got evicted, and resumes:
    restore re-hits the cache and stays token-identical."""
    cfg, params = setup
    sp = SamplingParams(temperature=0.0, max_new_tokens=3)
    p1, p2 = _shared_prompts()

    def run(n_pages):
        eng = make_engine(cfg, params, max_batch=1, n_pages=n_pages)
        eng.prefix_cache_min_pages = 2
        eng.submit(p1, session_id="keep", sampling=sp)
        eng.run_until_idle()
        for i in range(2):
            eng.submit([150 + i] * 9, session_id=f"fill{i}",
                       sampling=sp)
            eng.run_until_idle()
        t = eng.submit([5, 6], session_id="keep", sampling=sp)
        eng.run_until_idle()
        assert t.finish_reason in ("stop", "length"), t.error
        return t.new_tokens

    assert run(n_pages=15) == run(n_pages=64)


# ---- eviction x prefix-cache x spec-decode interaction matrix
# (VERDICT r2 #10): the three features compose — a pressure-cooker
# engine with every combination must stay token-identical to a
# pressure-free run and close its page accounting to zero leaks ----

@pytest.mark.parametrize("spec", [0, 4])
@pytest.mark.parametrize("prefix_cache", [False, True])
def test_eviction_prefix_spec_matrix(
    setup, spec, prefix_cache, monkeypatch
):
    cfg, params = setup
    monkeypatch.setenv(
        "ROOM_TPU_PREFIX_CACHE_PAGES", "2" if prefix_cache else "0"
    )
    # shared long-ish prefix so the prefix cache engages; repetitive
    # body so spec drafts engage; greedy so identity is exact
    prefix = [5, 6, 7, 5, 6, 7, 5, 6]
    prompts = [prefix + [10 + i] for i in range(6)]
    sp = SamplingParams(temperature=0.0, max_new_tokens=8)

    def run(n_pages):
        eng = ServingEngine(
            cfg, params, max_batch=2, page_size=4, n_pages=n_pages,
            spec_tokens=spec,
        )
        turns = [
            eng.submit(list(p), session_id=f"s{i}", sampling=sp)
            for i, p in enumerate(prompts)
        ]
        eng.run_until_idle()
        for i in range(len(prompts)):
            eng.release_session(f"s{i}")
        st = eng.stats()
        free = eng.page_table.free_pages
        return [t.new_tokens for t in turns], st, free, eng

    # roomy pool: no eviction pressure
    want, _, _, _ = run(n_pages=256)
    # tight pool: evictions forced (6 sessions x ~4 pages on 25 usable)
    got, st, free, eng = run(n_pages=26)

    assert got == want
    # all sessions released: every page is either free, the scratch
    # page, or retained by a live prefix-cache entry (that's the
    # cache working, not a leak)
    held_by_prefix = sum(
        len(e.pages) for e in eng._prefix_cache.values()
    )
    assert free == eng.page_table.n_pages - 1 - held_by_prefix, (
        free, eng.page_table.n_pages, held_by_prefix, st,
    )
    if not prefix_cache:
        assert held_by_prefix == 0
