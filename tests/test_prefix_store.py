"""Fleet-global shared prefix store suite (docs/disagg.md).

Pins the content-addressed prefix tier on the CPU backend:

- Store unit contract: publish/fetch round trip (bf16-safe spool
  bytes, sha256 verified on read), content addressing (same tokens +
  same config fingerprint -> same key; different fingerprint -> no
  cross-hit), longest-prefix probing, byte-cap LRU eviction, corrupt
  entries degrading to a miss and self-healing.
- Cross-process adoption: a second store instance (fresh process
  emulation) over the same dir serves entries its dead donor
  published — prefix KV carries no owner PID, and the `.kvspool`
  orphan sweeps never touch `.pfxspool` files.
- Engine integration: greedy streams TOKEN-IDENTICAL across all
  three prefill paths — monolithic (prefix cache off), local
  prefix-cache miss-then-register, and the prefix-store pull
  (copy-on-adopt scatter) — plus the store actually removing prefill
  work (prefill_tokens delta) on the pulling engine.
- The `prefix_io` fault point: a failed pull is an ordinary miss, a
  failed publish skips; correctness never depends on the store.
"""

import os

import jax
import numpy as np
import pytest

from room_tpu.models import qwen3, tiny_moe
from room_tpu.serving import SamplingParams, ServingEngine, faults
from room_tpu.serving import lifecycle
from room_tpu.serving.prefix_store import SharedPrefixStore


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture(scope="module")
def model():
    cfg = tiny_moe()
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _arrays(n_pages=2, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "k": rng.standard_normal((2, n_pages, 8, 4)).astype(
            np.float32
        ),
        "v": rng.standard_normal((2, n_pages, 8, 4)).astype(
            np.float32
        ),
    }


FP = {"model": "t", "page_size": 8, "kv_quant": None}


# ---- store unit contract ----

def test_publish_fetch_round_trip(tmp_path):
    store = SharedPrefixStore(FP, str(tmp_path), page_size=8)
    toks = list(range(16))
    arrays = _arrays()
    assert store.publish(toks, arrays, n_pages=2)
    got = store.fetch_longest(toks + [99, 98], max_len=16)
    assert got is not None
    length, meta, back = got
    assert length == 16 and meta["n_pages"] == 2
    for k in arrays:
        np.testing.assert_array_equal(arrays[k], back[k])
    st = store.stats()
    assert st["publishes"] == 1 and st["hits"] == 1


def test_longest_prefix_wins(tmp_path):
    store = SharedPrefixStore(FP, str(tmp_path), page_size=8)
    toks = list(range(32))
    store.publish(toks[:8], _arrays(1), n_pages=1)
    store.publish(toks[:24], _arrays(3, seed=1), n_pages=3)
    got = store.fetch_longest(toks, max_len=32)
    assert got is not None and got[0] == 24
    # max_len clamps below the longer entry
    got = store.fetch_longest(toks, max_len=16)
    assert got is not None and got[0] == 8


def test_fingerprint_separates_keys(tmp_path):
    a = SharedPrefixStore(FP, str(tmp_path), page_size=8)
    b = SharedPrefixStore({**FP, "kv_quant": "int8"}, str(tmp_path),
                          page_size=8)
    toks = list(range(8))
    assert a.key_of(toks) != b.key_of(toks)
    a.publish(toks, _arrays(1), n_pages=1)
    assert b.fetch_longest(toks, max_len=8) is None, \
        "a differently-configured engine must never hit another " \
        "config's KV bytes"


def test_publish_idempotent_and_unaligned_refused(tmp_path):
    store = SharedPrefixStore(FP, str(tmp_path), page_size=8)
    toks = list(range(8))
    assert store.publish(toks, _arrays(1), n_pages=1)
    assert store.publish(toks, _arrays(1), n_pages=1)   # skip, True
    assert store.stats()["publish_skips"] == 1
    assert not store.publish(list(range(5)), _arrays(1), n_pages=1), \
        "a non-page-aligned prefix must be refused"


def test_byte_cap_evicts_lru(tmp_path):
    one = sum(a.nbytes for a in _arrays(1).values())
    store = SharedPrefixStore(
        FP, str(tmp_path), bytes_cap=int(one * 2.5), page_size=8,
    )
    for i in range(4):
        store.publish([i * 100 + j for j in range(8)], _arrays(1, i),
                      n_pages=1)
    st = store.stats()
    assert st["evictions"] >= 1
    assert st["entries"] <= 2


def test_corrupt_spool_degrades_to_miss_and_heals(tmp_path):
    store = SharedPrefixStore(FP, str(tmp_path), page_size=8)
    toks = list(range(8))
    store.publish(toks, _arrays(1), n_pages=1)
    spool, _meta = store._paths(store.key_of(toks))
    with open(spool, "r+b") as f:
        f.seek(40)
        f.write(b"\xff\xff\xff\xff")
    assert store.fetch_longest(toks, max_len=8) is None
    assert store.stats()["pull_errors"] == 1
    assert not os.path.exists(spool), \
        "a corrupt entry is dropped so the next publisher can repair"
    assert store.publish(toks, _arrays(1), n_pages=1)
    assert store.fetch_longest(toks, max_len=8) is not None


def test_prefix_io_fault_degrades(tmp_path):
    store = SharedPrefixStore(FP, str(tmp_path), page_size=8)
    toks = list(range(8))
    faults.inject("prefix_io", times=1)
    assert not store.publish(toks, _arrays(1), n_pages=1)
    assert store.stats()["publish_errors"] == 1
    store.publish(toks, _arrays(1), n_pages=1)
    faults.inject("prefix_io", times=1)
    assert store.fetch_longest(toks, max_len=8) is None
    assert store.stats()["pull_errors"] == 1
    assert store.fetch_longest(toks, max_len=8) is not None


# ---- cross-process adoption ----

def test_cross_process_adoption_dead_pid_donor(tmp_path):
    """Two stores share a dir; the donor 'process' is gone. The
    adopting store (a fresh instance = fresh process) must still
    serve the entries, and the lifecycle orphan sweeps — which DO
    delete dead-PID `.kvspool` files — must leave prefix entries
    alone: shared prefix KV is immortal content, not process state."""
    donor = SharedPrefixStore(FP, str(tmp_path), page_size=8)
    toks = list(range(16))
    arrays = _arrays()
    donor.publish(toks, arrays, n_pages=2)
    del donor   # donor process dead; files carry no live-PID tag

    # a dead-PID .kvspool sibling IS swept by the same dir's hygiene
    dead = tmp_path / "pid999999-deadbeef.kvspool"
    dead.write_bytes(b"leftover")
    os.utime(dead, (1, 1))
    removed = lifecycle.sweep_orphans(str(tmp_path), max_age_s=0.0)
    assert removed == 1 and not dead.exists()

    adopter = SharedPrefixStore(FP, str(tmp_path), page_size=8)
    got = adopter.fetch_longest(toks, max_len=16)
    assert got is not None, \
        "a fresh store over the shared dir must adopt the dead " \
        "donor's entries"
    for k in arrays:
        np.testing.assert_array_equal(arrays[k], got[2][k])


# ---- engine integration: three-path token identity ----

@pytest.fixture()
def engines(model, monkeypatch, tmp_path):
    monkeypatch.setenv("ROOM_TPU_PREFIX_STORE_DIR",
                       str(tmp_path / "pfx"))
    monkeypatch.setenv("ROOM_TPU_LIFECYCLE_DIR", str(tmp_path / "lc"))
    cfg, params = model

    def build(prefix_pages="2", store=True, **kw):
        monkeypatch.setenv("ROOM_TPU_PREFIX_CACHE_PAGES",
                           prefix_pages)
        kw.setdefault("max_batch", 4)
        kw.setdefault("page_size", 8)
        kw.setdefault("n_pages", 96)
        kw.setdefault("stop_token_ids", [])
        return ServingEngine(cfg, params, prefix_store=store, **kw)

    return build


SYS = list(range(3, 40))          # 32-token aligned shared prefix
PROMPT = SYS + [9, 9, 5]


def _greedy(n=8):
    return SamplingParams(temperature=0.0, max_new_tokens=n)


def test_three_path_token_identity_and_prefill_delta(engines):
    # path 1: monolithic (prefix caching off entirely)
    mono = engines(prefix_pages="0", store=False)
    c = mono.submit(PROMPT, session_id="a", sampling=_greedy())
    mono.run_until_idle()
    control = list(c.new_tokens)

    # path 2: local prefix cache, no store (miss -> register)
    local = engines(store=False)
    t2 = local.submit(PROMPT, session_id="a", sampling=_greedy())
    local.run_until_idle()
    assert t2.new_tokens == control
    assert local.prefix_store is None

    # path 3: store-enabled publisher, then a FRESH engine pulling
    pub = engines()
    t3 = pub.submit(PROMPT, session_id="a", sampling=_greedy())
    pub.run_until_idle()
    assert t3.new_tokens == control
    assert pub.stats()["prefix_store_publishes"] == 1

    puller = engines()
    t4 = puller.submit(PROMPT, session_id="b", sampling=_greedy())
    puller.run_until_idle()
    st = puller.stats()
    assert t4.new_tokens == control, \
        "a prefix-store pull must be token-identical to the " \
        "monolithic prefill"
    assert st["prefix_store_hits"] == 1
    assert st["prefix_store_tokens_reused"] == 32
    assert st["prefill_tokens"] == pub.stats()["prefill_tokens"] - 32, \
        "the pull must actually remove the prefix from prefill work"
    assert st["prefix_store"]["hits"] == 1


def test_pull_materializes_shareable_local_entry(engines):
    pub = engines()
    pub.submit(PROMPT, session_id="a", sampling=_greedy())
    pub.run_until_idle()
    puller = engines()
    puller.submit(PROMPT, session_id="b", sampling=_greedy())
    puller.run_until_idle()
    # a SECOND session on the pulling engine hits the local entry the
    # pull materialized — no second store read
    bytes_before = puller.prefix_store.stats()["bytes_pulled"]
    puller.submit(SYS + [1, 2], session_id="c", sampling=_greedy())
    puller.run_until_idle()
    st = puller.stats()
    assert st["prefix_hits"] >= 2     # pull-hit + local hit
    assert st["prefix_store_hits"] == 1
    assert puller.prefix_store.stats()["bytes_pulled"] == bytes_before


def test_prefix_io_fault_on_pull_is_plain_miss(engines):
    pub = engines()
    pub.submit(PROMPT, session_id="a", sampling=_greedy())
    pub.run_until_idle()
    control = None
    mono = engines(prefix_pages="0", store=False)
    c = mono.submit(PROMPT, session_id="a", sampling=_greedy())
    mono.run_until_idle()
    control = list(c.new_tokens)

    faults.inject("prefix_io")
    eng = engines()
    t = eng.submit(PROMPT, session_id="d", sampling=_greedy())
    eng.run_until_idle()
    assert t.new_tokens == control
    assert eng.stats()["prefix_store_hits"] == 0
    faults.clear()


def test_session_resume_reprefill_pulls_prefix(engines, model):
    """The disagg synergy: a re-homed/re-prefilling session (history
    re-enters as a fresh prefill) pulls the shared prefix instead of
    recomputing it — the engine adoption seam + store together."""
    pub = engines()
    pub.submit(PROMPT, session_id="a", sampling=_greedy())
    pub.run_until_idle()

    target = engines()
    # adopt a history-only entry (the mirror re-prefill path)
    entry = {
        "id": "moved", "history": list(PROMPT), "pending": 11,
        "length": len(PROMPT), "generation": 1, "kv": None,
    }
    target.adopt_parked_session(entry, fingerprint=None)
    t = target.submit([4, 4], session_id="moved",
                      sampling=_greedy())
    target.run_until_idle()
    assert t.finish_reason == "length"
    st = target.stats()
    assert st["prefix_store_hits"] == 1, \
        "the resume re-prefill must pull the shared prefix"
    assert st["prefix_store_tokens_reused"] == 32
