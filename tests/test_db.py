"""Persistence-layer tests: schema integrity, FTS sync triggers, WAL-style
concurrency basics, migration ledger."""

import threading

from room_tpu.db import Database, SCHEMA_VERSION, utc_now


def test_schema_creates_all_tables(db):
    tables = {
        r["name"]
        for r in db.query(
            "SELECT name FROM sqlite_master WHERE type='table'"
        )
    }
    expected = {
        "settings", "workers", "rooms", "entities", "observations",
        "relations", "embeddings", "tasks", "task_runs", "console_logs",
        "watches", "chat_messages", "room_activity", "quorum_decisions",
        "quorum_votes", "goals", "goal_updates", "skills", "self_mod_audit",
        "self_mod_snapshots", "escalations", "credentials", "wallets",
        "wallet_transactions", "room_messages", "worker_cycles",
        "cycle_logs", "agent_sessions", "clerk_messages", "clerk_usage",
        "schema_migrations",
    }
    missing = expected - tables
    assert not missing, f"missing tables: {missing}"


def test_schema_is_idempotent(db):
    from room_tpu.db import SCHEMA
    db._conn.executescript(SCHEMA)  # second run must not raise


def test_migration_ledger(db):
    assert db.schema_version == SCHEMA_VERSION


def test_fts_triggers_track_entities(db):
    eid = db.insert(
        "INSERT INTO entities(name, type, category) VALUES (?,?,?)",
        ("deploy pipeline", "fact", "ops"),
    )
    hits = db.query(
        "SELECT entity_id FROM memory_fts WHERE memory_fts MATCH ?", ("deploy",)
    )
    assert [h["entity_id"] for h in hits] == [eid]

    db.execute("UPDATE entities SET name='release train' WHERE id=?", (eid,))
    assert db.query(
        "SELECT entity_id FROM memory_fts WHERE memory_fts MATCH ?", ("deploy",)
    ) == []
    assert [
        h["entity_id"]
        for h in db.query(
            "SELECT entity_id FROM memory_fts WHERE memory_fts MATCH ?",
            ("release",),
        )
    ] == [eid]

    db.execute("DELETE FROM entities WHERE id=?", (eid,))
    assert db.query(
        "SELECT entity_id FROM memory_fts WHERE memory_fts MATCH ?", ("release",)
    ) == []


def test_foreign_keys_cascade(db):
    rid = db.insert("INSERT INTO rooms(name) VALUES (?)", ("r",))
    gid = db.insert(
        "INSERT INTO goals(room_id, description) VALUES (?,?)", (rid, "g")
    )
    db.insert(
        "INSERT INTO goal_updates(goal_id, observation) VALUES (?,?)",
        (gid, "obs"),
    )
    db.execute("DELETE FROM rooms WHERE id=?", (rid,))
    assert db.query("SELECT * FROM goals") == []
    assert db.query("SELECT * FROM goal_updates") == []


def test_transaction_rollback(db):
    try:
        with db.transaction():
            db.insert("INSERT INTO rooms(name) VALUES (?)", ("a",))
            raise RuntimeError("boom")
    except RuntimeError:
        pass
    assert db.query("SELECT * FROM rooms") == []


def test_threaded_access(db):
    errors = []

    def worker(n):
        try:
            for i in range(25):
                db.insert(
                    "INSERT INTO settings(key, value) VALUES (?,?) "
                    "ON CONFLICT(key) DO UPDATE SET value=excluded.value",
                    (f"k{n}-{i}", str(i)),
                )
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(n,)) for n in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert len(db.query("SELECT * FROM settings")) == 100


def test_utc_now_format():
    ts = utc_now()
    assert ts.endswith("Z") and "T" in ts and len(ts) == 24


def test_nested_transaction_savepoints(db):
    with db.transaction():
        db.insert("INSERT INTO rooms(name) VALUES ('outer')")
        try:
            with db.transaction():
                db.insert("INSERT INTO rooms(name) VALUES ('inner')")
                raise RuntimeError("inner fails")
        except RuntimeError:
            pass
    names = [r["name"] for r in db.query("SELECT name FROM rooms")]
    assert names == ["outer"]


def test_room_delete_cascades_worker_cycles(db):
    rid = db.insert("INSERT INTO rooms(name) VALUES ('r')")
    wid = db.insert(
        "INSERT INTO workers(name, system_prompt, room_id) VALUES ('w','p',?)",
        (rid,),
    )
    db.insert(
        "INSERT INTO worker_cycles(worker_id, room_id) VALUES (?,?)",
        (wid, rid),
    )
    db.execute("DELETE FROM rooms WHERE id=?", (rid,))
    assert db.query("SELECT * FROM worker_cycles") == []


def test_fresh_db_stamps_future_migrations(tmp_path):
    from room_tpu.db import database as dbmod
    dbmod.MIGRATIONS.append((999, "THIS WOULD FAIL IF EXECUTED;"))
    try:
        d = Database(str(tmp_path / "fresh.db"))
        assert d.schema_version == 999  # stamped, never executed
        d.close()
    finally:
        dbmod.MIGRATIONS.pop()
