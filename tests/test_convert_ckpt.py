"""Checkpoint round-trip (orbax) and HF-weights converter mapping."""

import os

import jax
import numpy as np
import pytest

from room_tpu.models import qwen3, tiny_dense, tiny_moe
from room_tpu.utils.checkpoint import load_params, save_params


def test_orbax_checkpoint_roundtrip(tmp_path):
    cfg = tiny_moe()
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    path = str(tmp_path / "ckpt")
    save_params(path, params)
    like = qwen3.init_params(cfg, jax.random.PRNGKey(1))  # different values
    restored = load_params(path, like=like)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def _write_hf_safetensors(tmp_path, cfg, params):
    """Reverse-map our param tree into HF tensor names/orientations."""
    from safetensors.numpy import save_file

    def T(x):  # safetensors writes raw buffers: transposes must be materialized
        return np.ascontiguousarray(np.asarray(x, np.float32).T)

    tensors = {}
    tensors["model.embed_tokens.weight"] = np.asarray(
        params["embed"], np.float32
    )
    tensors["model.norm.weight"] = np.asarray(
        params["final_norm"], np.float32
    )
    tensors["lm_head.weight"] = T(params["lm_head"])
    lp = params["layers"]
    for li in range(cfg.n_layers):
        p = f"model.layers.{li}"
        tensors[f"{p}.self_attn.q_proj.weight"] = T(lp["wq"][li])
        tensors[f"{p}.self_attn.k_proj.weight"] = T(lp["wk"][li])
        tensors[f"{p}.self_attn.v_proj.weight"] = T(lp["wv"][li])
        tensors[f"{p}.self_attn.o_proj.weight"] = T(lp["wo"][li])
        tensors[f"{p}.input_layernorm.weight"] = np.asarray(
            lp["ln1"][li], np.float32)
        tensors[f"{p}.post_attention_layernorm.weight"] = np.asarray(
            lp["ln2"][li], np.float32)
        if cfg.qkv_bias:
            tensors[f"{p}.self_attn.q_proj.bias"] = np.asarray(
                lp["bq"][li], np.float32)
            tensors[f"{p}.self_attn.k_proj.bias"] = np.asarray(
                lp["bk"][li], np.float32)
            tensors[f"{p}.self_attn.v_proj.bias"] = np.asarray(
                lp["bv"][li], np.float32)
        if cfg.qk_norm:
            tensors[f"{p}.self_attn.q_norm.weight"] = np.asarray(
                lp["q_norm"][li], np.float32)
            tensors[f"{p}.self_attn.k_norm.weight"] = np.asarray(
                lp["k_norm"][li], np.float32)
        if cfg.is_moe:
            tensors[f"{p}.mlp.gate.weight"] = T(lp["router"][li])
            for ei in range(cfg.n_experts):
                for hf, ours in (("gate_proj", "w_gate"),
                                 ("up_proj", "w_up"),
                                 ("down_proj", "w_down")):
                    tensors[f"{p}.mlp.experts.{ei}.{hf}.weight"] = \
                        T(lp[ours][li, ei])
        else:
            for hf, ours in (("gate_proj", "w_gate"), ("up_proj", "w_up"),
                             ("down_proj", "w_down")):
                tensors[f"{p}.mlp.{hf}.weight"] = T(lp[ours][li])
    save_file(tensors, str(tmp_path / "model.safetensors"))


@pytest.mark.parametrize("cfg_fn", [tiny_moe, tiny_dense])
def test_hf_converter_roundtrip(tmp_path, cfg_fn):
    """Our params -> HF layout -> converter -> identical logits."""
    from room_tpu.utils.convert import convert_hf_decoder

    cfg = cfg_fn()
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    _write_hf_safetensors(tmp_path, cfg, params)
    converted = convert_hf_decoder(str(tmp_path), cfg, dtype="float32")

    tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 5), 0,
                                cfg.vocab_size)
    want, _ = qwen3.forward(params, cfg, tokens)
    got, _ = qwen3.forward(
        jax.tree.map(lambda x: np.asarray(x, np.float32), converted),
        cfg, tokens,
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
    )


def test_hf_encoder_converter_roundtrip(tmp_path):
    """Encoder params -> BERT-style HF layout -> converter -> identical
    embeddings (the all-MiniLM-class path the memory system loads)."""
    from safetensors.numpy import save_file

    from room_tpu.models import embedder
    from room_tpu.models.config import tiny_encoder
    from room_tpu.utils.convert import convert_hf_encoder

    cfg = tiny_encoder()
    params = embedder.init_params(cfg, jax.random.PRNGKey(3))

    def T(x):
        return np.ascontiguousarray(np.asarray(x, np.float32).T)

    def A(x):
        return np.asarray(x, np.float32)

    tensors = {
        "embeddings.word_embeddings.weight": A(params["word_embed"]),
        "embeddings.position_embeddings.weight": A(params["pos_embed"]),
        "embeddings.token_type_embeddings.weight": A(params["type_embed"]),
        "embeddings.LayerNorm.weight": A(params["embed_ln_scale"]),
        "embeddings.LayerNorm.bias": A(params["embed_ln_bias"]),
    }
    lp = params["layers"]
    hf_map = [
        ("attention.self.query.weight", "wq", True),
        ("attention.self.query.bias", "bq", False),
        ("attention.self.key.weight", "wk", True),
        ("attention.self.key.bias", "bk", False),
        ("attention.self.value.weight", "wv", True),
        ("attention.self.value.bias", "bv", False),
        ("attention.output.dense.weight", "wo", True),
        ("attention.output.dense.bias", "bo", False),
        ("attention.output.LayerNorm.weight", "attn_ln_scale", False),
        ("attention.output.LayerNorm.bias", "attn_ln_bias", False),
        ("intermediate.dense.weight", "w_in", True),
        ("intermediate.dense.bias", "b_in", False),
        ("output.dense.weight", "w_out", True),
        ("output.dense.bias", "b_out", False),
        ("output.LayerNorm.weight", "ffn_ln_scale", False),
        ("output.LayerNorm.bias", "ffn_ln_bias", False),
    ]
    for li in range(cfg.n_layers):
        for hf, ours, transpose in hf_map:
            # use the "bert." prefix variant to exercise prefix stripping
            tensors[f"bert.encoder.layer.{li}.{hf}"] = (
                T(lp[ours][li]) if transpose else A(lp[ours][li])
            )
    save_file(tensors, str(tmp_path / "model.safetensors"))

    import dataclasses

    converted = convert_hf_encoder(str(tmp_path), cfg)
    tokens = np.array([[5, 6, 7, 8]], np.int32)
    mask = np.ones((1, 4), np.float32)
    cfg32 = dataclasses.replace(cfg, dtype="float32")
    want = embedder.encode(
        jax.tree.map(lambda x: np.asarray(x, np.float32), params),
        cfg32, tokens, mask,
    )
    got = embedder.encode(converted, cfg32, tokens, mask)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
    )


def test_experiment_harness_runs():
    import subprocess
    import sys

    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, "scripts/experiment.py", "--models", "echo",
         "--workers", "2", "--cycles", "2"],
        capture_output=True, text=True, timeout=120, cwd="/root/repo",
        env=env,
    )
    assert out.returncode == 0, out.stderr[-500:]
    import json

    summary = json.loads(out.stdout.strip().splitlines()[-1])
    r = summary["results"][0]
    assert r["model"] == "echo"
    assert r["cycles_run"] == 6 and r["errors"] == 0  # 3 agents x 2
