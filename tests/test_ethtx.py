"""EVM signing tests: RFC 6979 published vectors, cross-verification
against the independent `cryptography` ECDSA implementation, RLP
goldens from the Ethereum spec, EIP-1559 encode/recover round-trip,
and the wallet transfer path (reference: src/shared/wallet.ts:19-37)."""

import hashlib

import pytest

from room_tpu.core.ethtx import (
    N, ecdsa_recover, ecdsa_sign, encode_eip1559_unsigned,
    erc20_transfer_data, point_to_address, pubkey_point, rlp_encode,
    sign_eip1559, _rfc6979_k,
)
from room_tpu.core.keccak import keccak256


# ---- RFC 6979 deterministic nonces (published secp256k1 vectors,
# SHA-256; the classic set circulated by the Bitcoin implementations) --

def test_rfc6979_vector_satoshi():
    priv = (1).to_bytes(32, "big")
    h = hashlib.sha256(b"Satoshi Nakamoto").digest()
    k = _rfc6979_k(h, priv)
    assert k == int(
        "8F8A276C19F4149656B280621E358CCE24F5F52542772691EE69063B74F15D15",
        16,
    )
    r, s, _ = ecdsa_sign(h, priv)
    assert r == int(
        "934b1ea10a4b3c1757e2b0c017d0b6143ce3c9a7e6a4a49860d7a6ab210ee3d8",
        16,
    )
    assert s == int(
        "2442ce9d2b916064108014783e923ec36b49743e2ffa1c4496f01a512aafd9e5",
        16,
    )


def test_rfc6979_vector_tears_in_rain():
    priv = (1).to_bytes(32, "big")
    h = hashlib.sha256(
        b"All those moments will be lost in time, like tears in rain. "
        b"Time to die..."
    ).digest()
    k = _rfc6979_k(h, priv)
    assert k == int(
        "38AA22D72376B4DBC472E06C3BA403EE0A394DA63FC58D88686C611ABA98D6B3",
        16,
    )


# ---- cross-check against the independent library ----

def test_signature_verifies_under_cryptography():
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.hazmat.primitives.asymmetric.utils import (
        Prehashed, encode_dss_signature,
    )

    priv = bytes.fromhex(
        "4c0883a69102937d6231471b5dbb6204fe5129617082792ae468d01a3f362318"
    )
    digest = keccak256(b"room_tpu signing cross-check")
    r, s, _ = ecdsa_sign(digest, priv)

    pub_nums = ec.EllipticCurvePublicNumbers(
        *pubkey_point(priv), ec.SECP256K1()
    )
    pub = pub_nums.public_key()
    # raises InvalidSignature on mismatch
    pub.verify(
        encode_dss_signature(r, s), digest,
        ec.ECDSA(Prehashed(hashes_sha256_like(digest))),
    )


def hashes_sha256_like(digest: bytes):
    """Prehashed needs an algorithm whose digest_size matches."""
    from cryptography.hazmat.primitives import hashes

    assert len(digest) == 32
    return hashes.SHA256()


def test_pubkey_matches_cryptography_derivation():
    from cryptography.hazmat.primitives.asymmetric import ec

    priv = bytes.fromhex("01" * 32)
    sk = ec.derive_private_key(
        int.from_bytes(priv, "big"), ec.SECP256K1()
    )
    nums = sk.public_key().public_numbers()
    assert pubkey_point(priv) == (nums.x, nums.y)


# ---- recovery ----

def test_ecrecover_roundtrip():
    priv = bytes.fromhex("aa" * 32)
    digest = keccak256(b"recover me")
    r, s, y = ecdsa_sign(digest, priv)
    assert s <= N // 2  # EIP-2 low-s
    pt = ecdsa_recover(digest, r, s, y)
    assert pt == pubkey_point(priv)
    assert point_to_address(pt) == point_to_address(pubkey_point(priv))


# ---- RLP goldens (Ethereum spec examples) ----

@pytest.mark.parametrize("value,expected", [
    ("dog", "83646f67"),
    (["cat", "dog"], "c88363617483646f67"),
    ("", "80"),
    (0, "80"),
    (15, "0f"),
    (1024, "820400"),
    ([], "c0"),
    ([[], [[]], [[], [[]]]], "c7c0c1c0c3c0c1c0"),
])
def test_rlp_goldens(value, expected):
    assert rlp_encode(value).hex() == expected


def test_rlp_long_string():
    s = "Lorem ipsum dolor sit amet, consectetur adipisicing elit"
    assert rlp_encode(s).hex() == "b8" + "38" + s.encode().hex()


# ---- EIP-1559 ----

def _rlp_decode(data: bytes):
    """Minimal decoder for the round-trip test."""
    def dec(b, i):
        x = b[i]
        if x <= 0x7F:
            return b[i:i + 1], i + 1
        if x <= 0xB7:
            ln = x - 0x80
            return b[i + 1:i + 1 + ln], i + 1 + ln
        if x <= 0xBF:
            lln = x - 0xB7
            ln = int.from_bytes(b[i + 1:i + 1 + lln], "big")
            st = i + 1 + lln
            return b[st:st + ln], st + ln
        if x <= 0xF7:
            ln = x - 0xC0
            end = i + 1 + ln
            out, j = [], i + 1
            while j < end:
                item, j = dec(b, j)
                out.append(item)
            return out, end
        lln = x - 0xF7
        ln = int.from_bytes(b[i + 1:i + 1 + lln], "big")
        st = i + 1 + lln
        end = st + ln
        out, j = [], st
        while j < end:
            item, j = dec(b, j)
            out.append(item)
        return out, end

    out, end = dec(data, 0)
    assert end == len(data)
    return out


def test_sign_eip1559_structure_and_sender():
    priv = bytes.fromhex("bb" * 32)
    signed = sign_eip1559(
        priv,
        chain_id=8453,           # base
        nonce=7,
        max_priority_fee_per_gas=1_000_000,
        max_fee_per_gas=30_000_000_000,
        gas_limit=21_000,
        to="0x833589fCD6eDb6E08f4c7C32D4f71b54bdA02913",
        value=0,
        data=b"\x01\x02",
    )
    raw = bytes.fromhex(signed["raw"][2:])
    assert raw[0] == 0x02
    fields = _rlp_decode(raw[1:])
    assert len(fields) == 12
    assert int.from_bytes(fields[0], "big") == 8453
    assert int.from_bytes(fields[1], "big") == 7
    assert fields[5].hex() == "833589fcd6edb6e08f4c7c32d4f71b54bda02913"
    assert fields[7] == b"\x01\x02"
    # recover the sender from the signature over the unsigned payload
    unsigned = encode_eip1559_unsigned(
        chain_id=8453, nonce=7, max_priority_fee_per_gas=1_000_000,
        max_fee_per_gas=30_000_000_000, gas_limit=21_000,
        to="0x833589fCD6eDb6E08f4c7C32D4f71b54bdA02913", value=0,
        data=b"\x01\x02",
    )
    digest = keccak256(unsigned)
    y = int.from_bytes(fields[9], "big") if fields[9] else 0
    r = int.from_bytes(fields[10], "big")
    s = int.from_bytes(fields[11], "big")
    sender = point_to_address(ecdsa_recover(digest, r, s, y))
    assert sender == point_to_address(pubkey_point(priv))
    assert signed["hash"] == "0x" + keccak256(raw).hex()


def test_deterministic_signing():
    priv = bytes.fromhex("cc" * 32)
    kwargs = dict(
        chain_id=1, nonce=0, max_priority_fee_per_gas=1,
        max_fee_per_gas=2, gas_limit=21_000, to="0x" + "11" * 20,
        value=10**18,
    )
    assert sign_eip1559(priv, **kwargs) == sign_eip1559(priv, **kwargs)


def test_erc20_transfer_data():
    data = erc20_transfer_data("0x" + "ab" * 20, 123456)
    assert data[:4].hex() == "a9059cbb"
    assert data[4:36].hex() == "00" * 12 + "ab" * 20
    assert int.from_bytes(data[36:], "big") == 123456
    assert len(data) == 68


# ---- wallet integration ----

def test_wallet_build_signed_transfer(tmp_path, monkeypatch):
    from room_tpu.core.ethtx import ecdsa_recover as rec
    from room_tpu.core.wallet import (
        WalletError, build_signed_transfer, create_room_wallet,
        to_checksum_address, transfer_token,
    )
    from room_tpu.db import Database

    monkeypatch.setenv("ROOM_TPU_DATA_DIR", str(tmp_path))
    db = Database(":memory:")
    rid = db.insert("INSERT INTO rooms(name) VALUES ('w')")
    wallet = create_room_wallet(db, rid)

    signed = build_signed_transfer(
        db, rid, "0x" + "22" * 20, 1_000_000,
        nonce=0, max_fee_per_gas=10**9, max_priority_fee_per_gas=10**6,
    )
    raw = bytes.fromhex(signed["raw"][2:])
    assert raw[0] == 0x02
    fields = _rlp_decode(raw[1:])
    digest = keccak256(b"\x02" + rlp_encode(fields[:9]))
    y = int.from_bytes(fields[9], "big") if fields[9] else 0
    sender = point_to_address(rec(
        digest, int.from_bytes(fields[10], "big"),
        int.from_bytes(fields[11], "big"), y,
    ))
    assert to_checksum_address(sender) == wallet["address"]

    # validation + offline broadcast fails closed
    with pytest.raises(WalletError):
        build_signed_transfer(
            db, rid, "not-an-address", 1, nonce=0,
            max_fee_per_gas=1, max_priority_fee_per_gas=1,
        )
    with pytest.raises(WalletError):
        build_signed_transfer(
            db, rid, "0x" + "22" * 20, 0, nonce=0,
            max_fee_per_gas=1, max_priority_fee_per_gas=1,
        )
    monkeypatch.setenv("ROOM_TPU_RPC_BASE", "http://127.0.0.1:1")
    with pytest.raises(WalletError, match="unreachable"):
        transfer_token(db, rid, "0x" + "22" * 20, 1)
