"""Unified ragged paged-attention kernel + fused dispatch window.

One dispatch for the mixed [prefill-chunks + decode-lanes] batch
(PAPERS.md lead citation "Ragged Paged Attention"): the Pallas kernel
is pinned against attention_ref in interpret mode (bf16 AND the
in-kernel-dequant int8 variant), the XLA gather+einsum reference stays
the CPU/tier-1 fallback, and the engine's FUSED window — the step's
interleaved prefill chunks riding the decode dispatch — must be
greedy-token-identical to the split path across steps_per_dispatch
{1, 4} x chunk sizes {1 page, 4 pages, full} x bf16/int8 x prefix-hit
x offload-restore, including a decode_window fault shot through the
fused dispatch (no KV leak, chunk boundaries durable). Quick tier:
runs in the ci.yml chaos job.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from room_tpu.models import qwen3, tiny_moe
from room_tpu.ops import attention_ref
from room_tpu.ops.paged_attention import (
    paged_attention_ragged, paged_attention_ragged_int8,
    ragged_block_layout,
)
from room_tpu.serving import SamplingParams, ServingEngine, faults
from room_tpu.serving.kv_pages import (
    _quantize_kv, make_paged_kv_hook, make_ragged_kv_hook,
)

QB = 8
LONG = [1 + (i % 53) for i in range(100)]   # 13 pages at page_size 8
STEPS = (1, 4)
CHUNK_PAGES = (1, 4, 0)                     # 0 = full/monolithic


@pytest.fixture(scope="module")
def model():
    cfg = tiny_moe()
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture()
def build(model, monkeypatch):
    cfg, params = model

    def make(chunk_pages, steps=4, fused=True, kv_quant=None, **kw):
        monkeypatch.setenv(
            "ROOM_TPU_PREFILL_CHUNK_PAGES", str(chunk_pages)
        )
        monkeypatch.setenv(
            "ROOM_TPU_DECODE_STEPS_PER_DISPATCH", str(steps)
        )
        monkeypatch.setenv(
            "ROOM_TPU_FUSED_WINDOW", "1" if fused else "0"
        )
        if kv_quant:
            monkeypatch.setenv("ROOM_TPU_KV_QUANT", kv_quant)
        else:
            monkeypatch.delenv("ROOM_TPU_KV_QUANT", raising=False)
        kw.setdefault("max_batch", 4)
        kw.setdefault("page_size", 8)
        kw.setdefault("n_pages", 128)
        return ServingEngine(cfg, params, **kw)

    return make


def _greedy(n=6):
    return SamplingParams(temperature=0.0, max_new_tokens=n)


# ---- kernel numerics (interpret mode) ----

def _build_ragged_case(rows, page, hkv, hq, d, seed=0):
    """Pack per-row (q_len, prefix) sequences into one shared pool and
    return everything the ragged kernel and the per-row reference
    need."""
    rng = np.random.default_rng(seed)
    maxp = 10
    total_pages = 1 + sum(-(-(ql + pl) // page) for ql, pl in rows)
    kpool = np.zeros((total_pages, page, hkv, d), np.float32)
    vpool = np.zeros_like(kpool)
    tables = np.zeros((len(rows), maxp), np.int32)
    refs, qs = [], []
    nxt = 1
    for r, (ql, pl) in enumerate(rows):
        total = ql + pl
        npg = -(-total // page)
        k = rng.standard_normal((total, hkv, d)).astype(np.float32) * .5
        v = rng.standard_normal((total, hkv, d)).astype(np.float32) * .5
        pad = npg * page - total
        kpool[nxt:nxt + npg] = np.concatenate(
            [k, np.zeros((pad, hkv, d), np.float32)]
        ).reshape(npg, page, hkv, d)
        vpool[nxt:nxt + npg] = np.concatenate(
            [v, np.zeros((pad, hkv, d), np.float32)]
        ).reshape(npg, page, hkv, d)
        tables[r, :npg] = np.arange(nxt, nxt + npg)
        nxt += npg
        refs.append((k, v))
        qs.append(
            rng.standard_normal((ql, hq, d)).astype(np.float32) * .5
        )
    return kpool, vpool, tables, refs, qs


def test_ragged_kernel_mixed_batch_matches_reference():
    """The headline shape: decode lanes (q_len 1) and multi-block
    prefill chunks (q_len 16/24) with ragged prefixes, one kernel
    call, each row checked against attention_ref."""
    page, hkv, hq, d = 8, 2, 4, 32
    rows = [(1, 11), (16, 9), (1, 3), (24, 0)]
    kpool, vpool, tables, refs, qs = _build_ragged_case(
        rows, page, hkv, hq, d
    )
    q_lens = [r[0] for r in rows]
    prefixes = [r[1] for r in rows]
    rowmap, blkmap, gather, scatter = ragged_block_layout(q_lens, QB)
    q_flat = np.concatenate(qs, axis=0)
    q_pad = q_flat[gather].reshape(len(rowmap), QB, hq, d)

    out = paged_attention_ragged(
        jnp.asarray(q_pad, jnp.bfloat16),
        jnp.asarray(kpool, jnp.bfloat16),
        jnp.asarray(vpool, jnp.bfloat16),
        jnp.asarray(tables), jnp.asarray(prefixes, jnp.int32),
        jnp.asarray(q_lens, jnp.int32),
        jnp.asarray(rowmap), jnp.asarray(blkmap),
        page_size=page, q_block=QB, interpret=True,
    )
    out_flat = np.asarray(out.reshape(-1, hq, d), np.float32)[scatter]
    off = 0
    for (ql, pl), (k, v), q in zip(rows, refs, qs):
        total = ql + pl
        exp = attention_ref(
            jnp.asarray(q[None], jnp.bfloat16),
            jnp.asarray(k[None], jnp.bfloat16),
            jnp.asarray(v[None], jnp.bfloat16),
            causal=True,
            q_positions=pl + jnp.arange(ql)[None],
            kv_positions=jnp.arange(total)[None],
        )[0]
        np.testing.assert_allclose(
            out_flat[off:off + ql], np.asarray(exp, np.float32),
            atol=6e-2,
        )
        off += ql


def test_ragged_kernel_int8_in_kernel_dequant():
    """The int8 variant dequantizes pages IN-KERNEL: the only error vs
    the bf16 reference over dequantized values is quantization noise
    already in the cache, not the kernel's."""
    page, hkv, hq, d = 8, 2, 4, 32
    rows = [(1, page + 3), (16, page)]
    kpool, vpool, tables, refs, qs = _build_ragged_case(
        rows, page, hkv, hq, d, seed=1
    )
    qk, sk = _quantize_kv(jnp.asarray(kpool))
    qv, sv = _quantize_kv(jnp.asarray(vpool))
    q_lens = [r[0] for r in rows]
    prefixes = [r[1] for r in rows]
    rowmap, blkmap, gather, scatter = ragged_block_layout(q_lens, QB)
    q_flat = np.concatenate(qs, axis=0)
    q_pad = q_flat[gather].reshape(len(rowmap), QB, hq, d)

    out = paged_attention_ragged_int8(
        jnp.asarray(q_pad, jnp.bfloat16), qk, qv, sk, sv,
        jnp.asarray(tables), jnp.asarray(prefixes, jnp.int32),
        jnp.asarray(q_lens, jnp.int32),
        jnp.asarray(rowmap), jnp.asarray(blkmap),
        page_size=page, q_block=QB, interpret=True,
    )
    out_flat = np.asarray(out.reshape(-1, hq, d), np.float32)[scatter]
    kdq = np.asarray(qk, np.float32) * np.asarray(sk)[..., None]
    vdq = np.asarray(qv, np.float32) * np.asarray(sv)[..., None]
    off = 0
    for r, (ql, pl) in enumerate(rows):
        total = ql + pl
        npg = -(-total // page)
        pids = tables[r, :npg]
        kd = kdq[pids].reshape(-1, hkv, d)[:total]
        vd = vdq[pids].reshape(-1, hkv, d)[:total]
        exp = attention_ref(
            jnp.asarray(qs[r][None], jnp.bfloat16),
            jnp.asarray(kd[None], jnp.bfloat16),
            jnp.asarray(vd[None], jnp.bfloat16),
            causal=True,
            q_positions=pl + jnp.arange(ql)[None],
            kv_positions=jnp.arange(total)[None],
        )[0]
        np.testing.assert_allclose(
            out_flat[off:off + ql], np.asarray(exp, np.float32),
            atol=6e-2,
        )
        off += ql


def test_ragged_block_layout_shapes():
    rowmap, blkmap, gather, scatter = ragged_block_layout(
        (1, 16, 1, 3), 8
    )
    # 1 + 2 + 1 + 1 blocks; every row starts a fresh block
    assert rowmap.tolist() == [0, 1, 1, 2, 3]
    assert blkmap.tolist() == [0, 0, 1, 0, 0]
    assert len(gather) == 5 * 8
    assert len(scatter) == 1 + 16 + 1 + 3
    # round trip: scatter pulls each flat token back out of the padded
    # layout gather built
    assert gather[scatter].tolist() == list(range(21))
    with pytest.raises(ValueError):
        ragged_block_layout((1, 0), 8)


def test_ragged_probes_cpu_fallback_and_interpret(monkeypatch):
    """On CPU the real-Pallas probe must fail soft (fallback, no
    crash); under interpret patching the same probe passes — the gate
    is the numerics, not the platform."""
    from room_tpu.serving import kv_pages as kvp

    assert kvp._probe_ragged_kernel(4, 2, 32, 8, 8) is False
    import room_tpu.ops.paged_attention as pa

    monkeypatch.setattr(
        pa, "paged_attention_ragged",
        functools.partial(paged_attention_ragged, interpret=True),
    )
    monkeypatch.setattr(
        pa, "paged_attention_ragged_int8",
        functools.partial(paged_attention_ragged_int8, interpret=True),
    )
    assert kvp._probe_ragged_kernel(4, 2, 32, 8, 8) is True
    assert kvp._probe_ragged_int8_kernel(4, 2, 32, 8, 8) is True


# ---- ragged hook: fused == split, bit-identical on the XLA path ----

@pytest.mark.parametrize("quant", [False, True])
def test_ragged_hook_matches_split_hooks_bitwise(quant):
    """The fused hook's XLA fallback computes each segment with the
    exact gather+einsum the split dispatches use, so attention outputs
    AND cache writes are bit-identical — the structural guarantee
    behind the engine-level token-identity matrix."""
    page, hkv, hq, d, maxp, pool = 4, 2, 4, 8, 6, 16
    rng = np.random.default_rng(3)
    if quant:
        cache = {
            "k_pages": jnp.zeros((pool, page, hkv, d), jnp.int8),
            "v_pages": jnp.zeros((pool, page, hkv, d), jnp.int8),
            "k_scale": jnp.zeros((pool, page, hkv), jnp.float32),
            "v_scale": jnp.zeros((pool, page, hkv), jnp.float32),
        }
    else:
        cache = {
            "k_pages": jnp.zeros((pool, page, hkv, d), jnp.bfloat16),
            "v_pages": jnp.zeros((pool, page, hkv, d), jnp.bfloat16),
        }
    B, C, cw = 2, 1, 8
    dec_tables = np.array(
        [[1, 2, 0, 0, 0, 0], [3, 0, 0, 0, 0, 0]], np.int32
    )
    dec_lens = np.array([5, 2], np.int32)
    ch_tables = np.array([[4, 5, 6, 0, 0, 0]], np.int32)
    ch_lens = np.array([3], np.int32)

    def prefill_row(cache, table, toks_n):
        hook = make_paged_kv_hook(
            jnp.asarray(table[None]), jnp.asarray([0], jnp.int32),
            page, pallas_decode=False, fresh_prefill=True,
        )
        k = jnp.asarray(
            rng.standard_normal((1, toks_n, hkv, d)) * .5, jnp.bfloat16
        )
        v = jnp.asarray(
            rng.standard_normal((1, toks_n, hkv, d)) * .5, jnp.bfloat16
        )
        q = jnp.asarray(
            rng.standard_normal((1, toks_n, hq, d)) * .5, jnp.bfloat16
        )
        _, cache = hook(q, k, v, cache)
        return cache

    cache = prefill_row(cache, dec_tables[0], 5)
    cache = prefill_row(cache, dec_tables[1], 2)
    cache = prefill_row(cache, ch_tables[0], 3)

    def rand(shape):
        return jnp.asarray(
            rng.standard_normal(shape) * .5, jnp.bfloat16
        )

    qd, kd, vd = rand((B, 1, hq, d)), rand((B, 1, hkv, d)), \
        rand((B, 1, hkv, d))
    qc, kc, vc = rand((C, cw, hq, d)), rand((C, cw, hkv, d)), \
        rand((C, cw, hkv, d))

    dhook = make_paged_kv_hook(
        jnp.asarray(dec_tables), jnp.asarray(dec_lens), page,
        pallas_decode=False, active_pages=4,
    )
    attn_d, cache_s = dhook(qd, kd, vd, dict(cache))
    chook = make_paged_kv_hook(
        jnp.asarray(ch_tables), jnp.asarray(ch_lens), page,
        pallas_decode=False, pallas_prefill=False, active_pages=4,
    )
    attn_c, cache_split = chook(qc, kc, vc, cache_s)

    rhook = make_ragged_kv_hook(
        jnp.asarray(np.concatenate([dec_tables, ch_tables])),
        jnp.asarray(np.concatenate([dec_lens, ch_lens])),
        page, n_decode=B, n_chunks=C, chunk_width=cw,
        active_pages=4, pallas_ragged=False,
    )
    q_all = jnp.concatenate([qd[:, 0], qc.reshape(C * cw, hq, d)])[None]
    k_all = jnp.concatenate([kd[:, 0], kc.reshape(C * cw, hkv, d)])[None]
    v_all = jnp.concatenate([vd[:, 0], vc.reshape(C * cw, hkv, d)])[None]
    attn_r, cache_fused = rhook(q_all, k_all, v_all, dict(cache))

    np.testing.assert_array_equal(
        np.asarray(attn_r[0, :B], np.float32),
        np.asarray(attn_d[:, 0], np.float32),
    )
    np.testing.assert_array_equal(
        np.asarray(attn_r[0, B:], np.float32),
        np.asarray(attn_c.reshape(C * cw, hq, d), np.float32),
    )
    for key in cache:
        np.testing.assert_array_equal(
            np.asarray(cache_fused[key].astype(jnp.float32)),
            np.asarray(cache_split[key].astype(jnp.float32)),
        )


# ---- engine: fused window token identity ----

def _run_streams(eng):
    """Canonical traffic: a short decode turn, a long (chunked) prompt,
    and a continuation on the chunked session."""
    a = eng.submit([5, 6, 7], session_id="dec", sampling=_greedy(10))
    b = eng.submit(LONG, session_id="long", sampling=_greedy())
    eng.run_until_idle()
    c = eng.submit([7, 8, 9], session_id="long", sampling=_greedy())
    eng.run_until_idle()
    return (a.new_tokens, b.new_tokens, c.new_tokens)


def test_identity_fused_vs_split_matrix(build):
    """The acceptance matrix: the fused window (unified dispatch) is
    greedy-token-identical to the split path across steps {1,4} x
    chunk sizes {1 page, 4 pages, full}."""
    base = _run_streams(build(4, steps=4, fused=False))
    for steps in STEPS:
        for pages in CHUNK_PAGES:
            eng = build(pages, steps=steps, fused=True)
            got = _run_streams(eng)
            assert got == base, f"pages={pages} steps={steps}"
            st = eng.stats()
            if pages:
                assert st["prefill_chunks_interleaved"] > 0
                # chunks rode the fused dispatch (or an idle-batch
                # flush), never per-chunk device calls
                assert st["chunk_dispatches"] < \
                    st["prefill_chunks_interleaved"]


def test_identity_fused_int8(build):
    """bf16/int8 axis: the fused dispatch through the quantized pool
    (in-kernel dequant on TPU, dequant gather on CPU) matches the
    split int8 path."""
    base = _run_streams(build(1, steps=4, fused=False,
                              kv_quant="int8"))
    for steps in STEPS:
        eng = build(1, steps=steps, fused=True, kv_quant="int8")
        assert _run_streams(eng) == base, f"steps={steps}"
        assert eng.stats()["fused_windows"] > 0


def test_identity_fused_prefix_hit(build):
    """Prefix-hit axis: a second session hitting the first's cached
    prefix must stream identically through the fused path."""
    prefix = list(range(1, 41))             # 5 aligned pages
    base = None
    for fused in (False, True):
        eng = build(1, fused=fused)
        t1 = eng.submit(prefix + [61, 62, 63], sampling=_greedy())
        eng.run_until_idle()
        t2 = eng.submit(prefix + [71, 72], sampling=_greedy())
        eng.run_until_idle()
        assert eng.stats()["prefix_hits"] >= 1
        got = (t1.new_tokens, t2.new_tokens)
        if base is None:
            base = got
        assert got == base, f"fused={fused}"


def test_identity_fused_offload_restore(build):
    """Offload-restore axis: hibernate a session, resume it with a
    long chunked continuation through the fused dispatch."""
    base = None
    for fused in (False, True):
        eng = build(1, fused=fused, offload=True)
        t1 = eng.submit(list(range(1, 20)), session_id="h",
                        sampling=_greedy())
        eng.run_until_idle()
        assert eng.offload_session("h")
        t2 = eng.submit(LONG, session_id="h", sampling=_greedy())
        eng.run_until_idle()
        got = (t1.new_tokens, t2.new_tokens)
        if base is None:
            base = got
        assert got == base, f"fused={fused}"
        assert eng.stats()["offload_restores"] >= 1


def test_chunk_only_flush_idle_batch(build):
    """No decode lanes to fuse with: staged chunks land in ONE batched
    flush dispatch per step, still token-identical to split."""
    eng0 = build(1, fused=False)
    b0 = eng0.submit(LONG, sampling=_greedy())
    eng0.run_until_idle()

    eng = build(1, fused=True)
    b1 = eng.submit(LONG, sampling=_greedy())
    eng.run_until_idle()
    assert b1.new_tokens == b0.new_tokens
    st = eng.stats()
    assert st["prefill_chunks_interleaved"] > 0
    # one flush dispatch can carry the whole step's budget of chunks
    assert st["chunk_dispatches"] <= st["prefill_chunks_interleaved"]


def test_fused_dispatch_count_delta(build):
    """The measurable claim: fused mode collapses per-chunk device
    dispatches into the window dispatch — the split engine pays one
    device call PER chunk, the fused engine near zero."""
    results = {}
    for fused in (False, True):
        eng = build(1, steps=4, fused=fused)
        eng.submit([5, 6, 7], sampling=_greedy(12))
        eng.submit(LONG, sampling=_greedy())
        eng.run_until_idle()
        results[fused] = eng.stats()
    split, unified = results[False], results[True]
    assert split["chunk_dispatches"] == \
        split["prefill_chunks_interleaved"]
    assert unified["fused_windows"] > 0
    assert unified["chunk_dispatches"] < split["chunk_dispatches"]


# ---- chaos: decode_window fault through the fused dispatch ----

def test_decode_window_fault_through_fused_dispatch(build, monkeypatch):
    """A non-transient decode_window fault on a FUSED window (decode
    lanes + staged chunks) fails only the window's decode turns; the
    chunked turn rolls back to its last durable chunk boundary,
    re-prepares, and completes with the clean stream. No KV leaks."""
    monkeypatch.setenv("ROOM_TPU_PREFIX_CACHE_PAGES", "0")
    # clean baseline streams
    eng0 = build(1, fused=True)
    d0 = eng0.submit([5, 6, 7], sampling=_greedy(10))
    b0 = eng0.submit(LONG, sampling=_greedy())
    eng0.run_until_idle()

    eng = build(1, fused=True)
    dec = eng.submit([5, 6, 7], session_id="dec", sampling=_greedy(10))
    # get the decode turn into a slot first
    for _ in range(2):
        eng.step()
    chunked = eng.submit(LONG, session_id="long", sampling=_greedy())
    faults.inject("decode_window", times=1, transient=False)
    eng.run_until_idle()
    faults.clear()

    st = eng.stats()
    assert st["window_faults"] >= 1
    assert st["healthy"] is True and st["engine_crashes"] == 0
    # the decode turn was in the faulted window: window-scoped failure
    assert dec.finish_reason == "error"
    # the chunked turn re-prepared from its durable boundary and
    # streams the clean tokens (disrupted, but token-identical greedy)
    assert chunked.finish_reason is not None
    assert chunked.new_tokens == b0.new_tokens
    assert d0.new_tokens  # baseline decode stream existed

    # canary after the fault: clean stream, balanced pool
    canary = eng.submit([5, 6, 7], sampling=_greedy(10))
    eng.run_until_idle()
    assert canary.new_tokens == d0.new_tokens
    for sid in list(eng.sessions):
        eng.release_session(sid)
    eng.step()
    assert eng.page_table.free_pages == eng.n_pages - 1, (
        "KV page leak after fused-window fault"
    )


def test_staged_chunks_never_survive_a_step(build):
    """Invariant behind the fused window's durability story: every
    step's staged chunks land on device within THAT step's
    _decode_once — even when the step has no active decode slots but a
    window still in flight (the hazard: the NEXT step's admission runs
    before its _decode_once and could tail-admit on top of unwritten
    chunk KV). Pinned by driving steps manually around a window that
    finishes its turns while in flight."""
    eng0 = build(1, steps=4, fused=False)
    d0 = eng0.submit([5, 6, 7], sampling=SamplingParams(
        temperature=0.0, max_new_tokens=3))
    b0 = eng0.submit(LONG, sampling=_greedy())
    eng0.run_until_idle()

    eng = build(1, steps=4, fused=True)
    dec = eng.submit([5, 6, 7], sampling=SamplingParams(
        temperature=0.0, max_new_tokens=3))
    # two steps: the short turn finishes at a drain while the next
    # window is still in flight, emptying the active slots
    eng.step()
    eng.step()
    long_turn = eng.submit(LONG, sampling=_greedy())
    for _ in range(400):
        eng.step()
        assert not eng._staged_chunks, (
            "staged chunks survived a scheduler step"
        )
        if long_turn.done.is_set() and dec.done.is_set():
            break
    assert dec.new_tokens == d0.new_tokens
    assert long_turn.new_tokens == b0.new_tokens


def test_staged_rollback_restores_boundary(build):
    """Arm the fault BEFORE any window lands: the first fused dispatch
    (carrying the first staged chunks) faults — the turn must roll
    back to its pre-stage state (no phantom committed chunks) and
    still produce the clean stream on retry."""
    eng0 = build(1, fused=True)
    b0 = eng0.submit(LONG, sampling=_greedy())
    eng0.run_until_idle()

    eng = build(1, fused=True)
    faults.inject("decode_window", times=1, transient=False)
    dec = eng.submit([5, 6, 7], sampling=_greedy(4))
    turn = eng.submit(LONG, sampling=_greedy())
    eng.run_until_idle()
    faults.clear()
    assert turn.new_tokens == b0.new_tokens
    assert dec.finish_reason is not None
    st = eng.stats()
    # landed chunk count stays honest: exactly the chunks the prompt
    # needs (rolled-back staging never double-counts)
    assert st["prefill_chunks_interleaved"] >= 1
