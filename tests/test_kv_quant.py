"""int8 KV-cache quantization (ROOM_TPU_KV_QUANT=int8): pages stored
as int8 + per-(token, head) f32 scales — ~49% of the bf16 pool's HBM
bytes and decode read traffic. No reference counterpart (the
reference's decoding lives inside Ollama); vLLM-style KV quantization
re-designed for the TPU paged layout."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from room_tpu.models import qwen3, tiny_moe
from room_tpu.serving import SamplingParams, ServingEngine
from room_tpu.serving.kv_pages import (
    _quantize_kv, init_page_cache, make_paged_kv_hook,
)


@pytest.fixture(scope="module")
def setup():
    cfg = tiny_moe()
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def test_quantize_roundtrip_error_bounded():
    rng = np.random.default_rng(0)
    x = jnp.asarray(
        rng.standard_normal((64, 4, 32)).astype(np.float32) * 3.0
    )
    q, s = _quantize_kv(x)
    assert q.dtype == jnp.int8 and s.dtype == jnp.float32
    deq = q.astype(jnp.float32) * s[..., None]
    # symmetric int8: max error is half a quantization step per row
    step = np.asarray(s)[..., None]
    assert np.all(np.abs(np.asarray(deq - x)) <= step * 0.5 + 1e-6)


def test_quantized_hook_attention_close_to_dense(setup):
    """The dequant-gather attention path must track the unquantized
    path within int8 tolerance for a decode step over a real prefix."""
    cfg, _ = setup
    hkv, d, page = cfg.n_kv_heads, cfg.head_dim, 8
    rng = np.random.default_rng(2)
    b, prefix = 2, 13

    def run(quant):
        cache = init_page_cache(cfg, n_pages=16, page_size=page,
                                quant=quant)
        layer = {k: v[0] for k, v in cache.items()}
        tables = jnp.asarray([[1, 2, 0, 0], [3, 4, 0, 0]], jnp.int32)
        # write the prefix through the hook itself (fresh prefill)
        kpre = jnp.asarray(rng.standard_normal(
            (b, prefix, hkv, d)).astype(np.float32))
        vpre = jnp.asarray(rng.standard_normal(
            (b, prefix, hkv, d)).astype(np.float32))
        qpre = jnp.asarray(rng.standard_normal(
            (b, prefix, cfg.n_heads, d)).astype(np.float32))
        hook = make_paged_kv_hook(
            tables, jnp.zeros((b,), jnp.int32), page,
            pallas_decode=False, fresh_prefill=True,
        )
        _, layer = hook(qpre, kpre, vpre, layer)
        # one decode token on top
        hook2 = make_paged_kv_hook(
            tables, jnp.full((b,), prefix, jnp.int32), page,
            pallas_decode=False,
        )
        q1 = jnp.asarray(rng.standard_normal(
            (b, 1, cfg.n_heads, d)).astype(np.float32))
        k1 = jnp.asarray(rng.standard_normal(
            (b, 1, hkv, d)).astype(np.float32))
        v1 = jnp.asarray(rng.standard_normal(
            (b, 1, hkv, d)).astype(np.float32))
        out, _ = hook2(q1, k1, v1, layer)
        return np.asarray(out, np.float32)

    rng = np.random.default_rng(2)
    dense = run(None)
    rng = np.random.default_rng(2)
    quant = run("int8")
    assert np.allclose(dense, quant, atol=8e-2), (
        np.abs(dense - quant).max()
    )


def test_int8_decode_kernel_interpret_matches_dequant():
    """Kernel logic vs the dequantized dense reference (interpret mode;
    the hardware lowering is probe-gated at engine startup)."""
    from room_tpu.ops import paged_attention as pa
    from room_tpu.serving import kv_pages

    real = pa.paged_attention_decode_int8
    try:
        pa.paged_attention_decode_int8 = (
            lambda *a, **k: real(*a, **{**k, "interpret": True})
        )
        kv_pages._DECODE_INT8_PROBE.clear()
        assert kv_pages.pallas_decode_int8_ok(8, 2, 64, 16) is True
        # page-boundary sweep: ragged lengths across page edges
        assert kv_pages._probe_decode_int8_kernel(4, 4, 32, 8) is True
    finally:
        pa.paged_attention_decode_int8 = real
        kv_pages._DECODE_INT8_PROBE.clear()


def test_engine_serves_with_int8_kv(setup, monkeypatch):
    """End-to-end: quantized engine completes turns; the first sampled
    token is exact (fresh prefill never reads the cache), the rest
    stays plausible under int8 noise; session continuation (dequant
    gather over a real prefix) works."""
    cfg, params = setup
    monkeypatch.setenv("ROOM_TPU_KV_QUANT", "int8")
    eng = ServingEngine(cfg, params, max_batch=2, page_size=8,
                        n_pages=64)
    assert eng.kv_quant == "int8"
    assert "k_scale" in eng.cache
    sp = SamplingParams(temperature=0.0, max_new_tokens=6)
    t1 = eng.submit([5, 6, 7, 8], session_id="a", sampling=sp)
    eng.run_until_idle()
    assert t1.finish_reason in ("stop", "length")
    assert len(t1.new_tokens) >= 1

    monkeypatch.delenv("ROOM_TPU_KV_QUANT")
    base = ServingEngine(cfg, params, max_batch=2, page_size=8,
                         n_pages=64)
    b1 = base.submit([5, 6, 7, 8], session_id="a", sampling=sp)
    base.run_until_idle()
    assert t1.new_tokens[0] == b1.new_tokens[0]

    # continuation on the quantized engine (delta submission): prefix
    # KV is read back through the dequant gather
    monkeypatch.setenv("ROOM_TPU_KV_QUANT", "int8")
    t2 = eng.submit([9, 9], session_id="a", sampling=sp)
    eng.run_until_idle()
    assert t2.finish_reason in ("stop", "length")


def test_quantized_cache_sharding_specs(setup):
    from room_tpu.parallel import MeshSpec, make_mesh, page_cache_specs
    from room_tpu.parallel.mesh import shard_pytree

    cfg, _ = setup
    mesh = make_mesh(MeshSpec(2, 2, 2))
    specs = page_cache_specs(cfg, mesh, quant="int8")
    assert set(specs) == {"k_pages", "v_pages", "k_scale", "v_scale"}
    cache = init_page_cache(cfg, n_pages=16, page_size=8, quant="int8")
    sharded = shard_pytree(cache, specs, mesh)
    assert sharded["k_scale"].shape == cache["k_scale"].shape


def test_int8_prefill_kernel_interpret_matches_dequant():
    """int8 chunked-prefill kernel vs the dequantized dense reference
    (interpret mode; probe-gated on hardware like its siblings)."""
    from room_tpu.ops import paged_attention as pa
    from room_tpu.serving import kv_pages

    real = pa.paged_attention_prefill_int8
    try:
        pa.paged_attention_prefill_int8 = (
            lambda *a, **k: real(*a, **{**k, "interpret": True})
        )
        kv_pages._PREFILL_INT8_PROBE.clear()
        assert kv_pages.pallas_prefill_int8_ok(8, 2, 64, 16) is True
        assert kv_pages._probe_prefill_int8_kernel(4, 4, 32, 8) is True
    finally:
        pa.paged_attention_prefill_int8 = real
        kv_pages._PREFILL_INT8_PROBE.clear()
