"""Unit tests for the mini-JS interpreter (tests/jsdom/mini_js.py) —
the render harness's engine. Panel renders cover it end-to-end; these
pin the JS semantics corners a refactor could silently break."""

import math

import pytest

from tests.jsdom.mini_js import (
    UNDEFINED,
    JSInterpreter,
    JSObject,
    JSThrow,
    to_js_string,
)


def run(src, want_global=None):
    i = JSInterpreter()
    i.run(src)
    if want_global is not None:
        return i.get_global(want_global)
    return i


def test_truthiness_and_coercion():
    i = run("""
      const checks = [
        !!"", !!0, !!null, !!undefined, !!NaN, !![], !!{}, !!"x",
      ];
      const plus = 1 + "2";
      const num = "3" * "4";
      const arrstr = [1, null, 2] + "";
    """)
    assert i.get_global("checks") == [
        False, False, False, False, False, True, True, True,
    ]
    assert i.get_global("plus") == "12"
    assert i.get_global("num") == 12
    assert i.get_global("arrstr") == "1,,2"


def test_equality_semantics():
    i = run("""
      const a = null == undefined;    // true (loose)
      const b = null === undefined;   // false
      const c = 0 == "0";             // true
      const d = 0 === "";             // false
      const e = NaN === NaN;          // false
    """)
    assert i.get_global("a") is True
    assert i.get_global("b") is False
    assert i.get_global("c") is True
    assert i.get_global("d") is False
    assert i.get_global("e") is False


def test_nullish_vs_or():
    i = run("""
      const zero = 0 || 5;        // 5 (falsy)
      const zkeep = 0 ?? 5;       // 0 (not nullish)
      const u = undefined ?? "d";
      const chain = ({}).a?.b?.c; // undefined, no throw
    """)
    assert i.get_global("zero") == 5
    assert i.get_global("zkeep") == 0
    assert i.get_global("u") == "d"
    assert i.get_global("chain") is UNDEFINED


def test_closures_and_hoisting():
    assert run("""
      const out = before();      // function decls hoist
      function before() { return make(3)(4); }
      function make(x) { return (y) => x + y; }
    """, "out") == 7


def test_destructuring_defaults_and_rest():
    i = run("""
      const {a, b = 9, ...rest} = {a: 1, c: 3, d: 4};
      const [x, , z = 7] = [10, 20];
      function f({k} = {}, ...args) { return [k, args.length]; }
      const fr = f({k: "v"}, 1, 2, 3);
    """)
    assert i.get_global("a") == 1
    assert i.get_global("b") == 9
    assert dict(i.get_global("rest")) == {"c": 3, "d": 4}
    assert i.get_global("x") == 10
    assert i.get_global("z") == 7
    assert i.get_global("fr") == ["v", 3]


def test_template_literals_nested():
    assert run("""
      const xs = [{n: "a"}, {n: "b"}];
      const out = `<ul>${xs.map(x => `<li>${x.n.toUpperCase()}` +
        `${x.missing ?? ""}</li>`).join("")}</ul>`;
    """, "out") == "<ul><li>A</li><li>B</li></ul>"


def test_regex_exec_and_groups():
    i = run("""
      const m = /^room:(\\d+)$/.exec("room:42");
      const none = /^x$/.exec("y");
      const t = /ab+/.test("slabby");
    """)
    assert i.get_global("m") == ["room:42", "42"]
    assert i.get_global("none") is None
    assert i.get_global("t") is True


def test_sort_is_stable_and_comparator_driven():
    assert run("""
      const xs = [{k: 2, t: "a"}, {k: 1, t: "b"}, {k: 2, t: "c"}];
      const out = xs.sort((p, q) => p.k - q.k).map(x => x.t).join("");
    """, "out") == "bac"


def test_array_methods():
    i = run("""
      const r = [1, 2, 3, 4].reduce((acc, x) => acc + x, 10);
      const f = [[1, 2], [3]].flat().filter(x => x > 1);
      const fm = [1, 2].flatMap(x => [x, x * 10]);
      const sl = [0, 1, 2, 3, 4].slice(-2);
      const found = [5, 6, 7].findIndex(x => x === 6);
    """)
    assert i.get_global("r") == 20
    assert i.get_global("f") == [2, 3]
    assert i.get_global("fm") == [1, 10, 2, 20]
    assert i.get_global("sl") == [3, 4]
    assert i.get_global("found") == 1


def test_try_catch_finally_and_throw():
    i = run("""
      let order = [];
      function f() {
        try { throw {message: "boom"}; }
        catch (e) { order.push("caught:" + e.message); return 1; }
        finally { order.push("fin"); }
      }
      const r = f();
      let bare = 0;
      try { JSON.parse("{bad"); } catch { bare = 1; }
    """)
    assert i.get_global("r") == 1
    assert i.get_global("order") == ["caught:boom", "fin"]
    assert i.get_global("bare") == 1


def test_for_of_entries_and_for_classic():
    i = run("""
      let s = 0;
      for (let i = 0; i < 5; i = i + 1) { if (i === 3) continue; s += i; }
      let keys = [];
      for (const [k, v] of Object.entries({a: 1, b: 2})) {
        keys.push(k + v);
      }
    """)
    assert i.get_global("s") == 0 + 1 + 2 + 4
    assert i.get_global("keys") == ["a1", "b2"]


def test_number_formatting_matches_js():
    assert to_js_string(3.0) == "3"
    assert to_js_string(3.5) == "3.5"
    assert to_js_string(math.nan) == "NaN"
    assert run("const s = (0.1 + 0.2).toFixed(2);", "s") == "0.30"
    assert run("const s = Math.round(2.5);", "s") == 3  # not banker's
    assert run("const s = Math.round(-2.5);", "s") == -2


def test_async_await_runs_synchronously():
    assert run("""
      async function a() { return 5; }
      async function b() { return (await a()) + (await Promise.resolve(2)); }
      let out = 0;
      b().then ? 0 : 0;   // result is a plain value, not a thenable
      async function top() { out = await b(); }
      top();
    """, "out") == 7


def test_spread_in_calls_arrays_objects():
    i = run("""
      const arr = [...[1, 2], 3];
      const obj = {...{a: 1, b: 2}, b: 9};
      const mx = Math.max(...[4, 8, 2]);
    """)
    assert i.get_global("arr") == [1, 2, 3]
    assert dict(i.get_global("obj")) == {"a": 1, "b": 9}
    assert i.get_global("mx") == 8


def test_delete_and_in_operator():
    i = run("""
      const o = {a: 1, b: 2};
      delete o.a;
      const hasA = "a" in o;
      const hasB = "b" in o;
    """)
    assert i.get_global("hasA") is False
    assert i.get_global("hasB") is True


def test_strings_methods():
    i = run("""
      const p = "7".padStart(3, "0");
      const r = "a-b-c".replaceAll("-", "+");
      const sp = "x,y,,z".split(",");
      const inc = "hello".includes("ell");
    """)
    assert i.get_global("p") == "007"
    assert i.get_global("r") == "a+b+c"
    assert i.get_global("sp") == ["x", "y", "", "z"]
    assert i.get_global("inc") is True


def test_undefined_member_read_throws():
    with pytest.raises(JSThrow):
        run("const x = undefined.anything;")


def test_new_is_rejected_loudly():
    # `new` is outside the subset: a panel drifting into it must fail
    # at parse time, not render garbage
    with pytest.raises(SyntaxError):
        run("const d = new Date();")


def test_json_round_trip():
    i = run("""
      const o = JSON.parse('{"a": [1, 2], "b": null}');
      const s = JSON.stringify({x: o.a, y: undefined});
    """)
    assert i.get_global("s") == '{"x": [1, 2]}'


def test_global_assignment_without_declaration():
    # classic-script behavior panels rely on (provPollTimer etc.)
    assert run("""
      function set() { implicitGlobal = 42; }
      set();
      const out = implicitGlobal;
    """, "out") == 42


def test_js_object_prop_default():
    o = JSObject({"a": 1})
    assert o.get_prop("a") == 1
    assert o.get_prop("missing") is UNDEFINED


def test_increment_decrement():
    i = run("""
      let n = 5;
      const post = n++;   // 5, n=6
      const pre = ++n;    // 7
      const o = {c: 3};
      o.c--;
      let loopSum = 0;
      for (let j = 0; j < 3; j++) loopSum += j;
    """)
    assert i.get_global("post") == 5
    assert i.get_global("pre") == 7
    assert i.get_global("n") == 7
    assert dict(i.get_global("o")) == {"c": 2}
    assert i.get_global("loopSum") == 3


def test_increment_single_evaluation_and_asi():
    i = run("""
      let calls = 0;
      function f() { calls++; return 0; }
      const a = [10];
      a[f()]++;
      let x = 1;
      let y = 2;
      const c = x
      ++y;
    """)
    assert i.get_global("calls") == 1      # operand evaluated once
    assert i.get_global("a") == [11]
    assert i.get_global("x") == 1          # ASI: x stays untouched
    assert i.get_global("y") == 3          # ++y on the next line
    assert i.get_global("c") == 1
