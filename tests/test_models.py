"""Model + ops tests on the virtual CPU mesh: numerics vs numpy references,
decode-vs-prefill consistency, MoE dispatch, sharded-vs-unsharded parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from room_tpu.models import embedder, qwen3, tiny_dense, tiny_encoder, tiny_moe
from room_tpu.ops import attention_ref, moe_ffn, rms_norm
from room_tpu.parallel import (
    MeshSpec, decoder_param_specs, kv_cache_specs, make_mesh, shard_pytree,
)


def test_rms_norm_matches_numpy():
    x = np.random.randn(3, 8).astype(np.float32)
    scale = np.random.randn(8).astype(np.float32)
    got = rms_norm(jnp.array(x), jnp.array(scale))
    want = x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-6) * scale
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_attention_ref_causality():
    b, s, h, d = 1, 6, 2, 8
    q = jnp.array(np.random.randn(b, s, h, d), jnp.float32)
    k = jnp.array(np.random.randn(b, s, h, d), jnp.float32)
    v = jnp.array(np.random.randn(b, s, h, d), jnp.float32)
    out1 = attention_ref(q, k, v)
    # changing the future must not change the past
    k2 = k.at[:, -1].set(99.0)
    v2 = v.at[:, -1].set(99.0)
    out2 = attention_ref(q, k2, v2)
    np.testing.assert_allclose(out1[:, :-1], out2[:, :-1], rtol=1e-5)
    assert not np.allclose(out1[:, -1], out2[:, -1])


def test_attention_gqa_equals_repeated_heads():
    b, s, d = 2, 5, 8
    q = jnp.array(np.random.randn(b, s, 4, d), jnp.float32)
    kv = np.random.randn(b, s, 2, d).astype(np.float32)
    out_gqa = attention_ref(q, jnp.array(kv), jnp.array(kv))
    kv_rep = np.repeat(kv, 2, axis=2)  # expand each kv head to its group
    out_full = attention_ref(q, jnp.array(kv_rep), jnp.array(kv_rep))
    np.testing.assert_allclose(out_gqa, out_full, rtol=1e-5)


def test_moe_matches_dense_loop():
    t, d, e, f, k = 12, 8, 4, 16, 2
    rng = np.random.default_rng(0)
    x = rng.standard_normal((t, d)).astype(np.float32)
    router = rng.standard_normal((d, e)).astype(np.float32)
    wg = rng.standard_normal((e, d, f)).astype(np.float32) * 0.1
    wu = rng.standard_normal((e, d, f)).astype(np.float32) * 0.1
    wd = rng.standard_normal((e, f, d)).astype(np.float32) * 0.1

    got = moe_ffn(
        jnp.array(x), jnp.array(router), jnp.array(wg), jnp.array(wu),
        jnp.array(wd), top_k=k, precision=jax.lax.Precision.HIGHEST,
    )

    # dense numpy reference: every expert on every token, masked combine
    logits = x @ router
    top = np.argsort(-logits, axis=-1)[:, :k]
    want = np.zeros_like(x)
    for ti in range(t):
        sel = logits[ti, top[ti]]
        w = np.exp(sel - sel.max())
        w = w / w.sum()
        for j, ei in enumerate(top[ti]):
            def silu(z):
                return z / (1 + np.exp(-z))
            h = silu(x[ti] @ wg[ei]) * (x[ti] @ wu[ei])
            want[ti] += w[j] * (h @ wd[ei])
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)


@pytest.mark.parametrize("cfg_fn", [tiny_moe, tiny_dense])
def test_decode_matches_prefill(cfg_fn):
    cfg = cfg_fn()
    key = jax.random.PRNGKey(0)
    params = qwen3.init_params(cfg, key)
    b, s = 2, 7
    tokens = jax.random.randint(jax.random.PRNGKey(1), (b, s), 0,
                                cfg.vocab_size)

    full_logits, _ = qwen3.forward(params, cfg, tokens)

    # same sequence fed through the cache path: prefill s-1 then step
    cache = qwen3.init_kv_cache(cfg, b, 16)
    _, cache = qwen3.forward(
        params, cfg, tokens[:, :-1], None, cache
    )
    step_logits, cache = qwen3.decode_step(
        params, cfg, tokens[:, -1], cache
    )
    np.testing.assert_allclose(
        step_logits, full_logits[:, -1], rtol=2e-4, atol=2e-4
    )
    assert int(cache["lengths"][0]) == s


def test_forward_is_jittable_and_deterministic():
    cfg = tiny_moe()
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.ones((1, 4), jnp.int32)
    f = jax.jit(lambda p, t: qwen3.forward(p, cfg, t)[0])
    a, b = f(params, tokens), f(params, tokens)
    np.testing.assert_array_equal(a, b)


def test_param_count_tiny():
    cfg = tiny_moe()
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    assert qwen3.param_count(params) > 0


def test_embedder_normalized_and_mask_sensitive():
    cfg = tiny_encoder()
    params = embedder.init_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.array([[5, 6, 7, 0], [5, 6, 7, 9]], jnp.int32)
    mask = jnp.array([[1, 1, 1, 0], [1, 1, 1, 1]], jnp.float32)
    out = embedder.encode(params, cfg, tokens, mask)
    np.testing.assert_allclose(
        np.linalg.norm(out, axis=-1), np.ones(2), rtol=1e-5
    )
    # padded token must not affect row 0, but row 1 sees token 9
    out2 = embedder.encode(
        params, cfg, tokens.at[0, 3].set(99), mask
    )
    np.testing.assert_allclose(out[0], out2[0], rtol=1e-5)
    assert not np.allclose(out[0], out[1])


def test_sharded_forward_matches_single_device():
    cfg = tiny_moe()
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(2), (4, 6), 0,
                                cfg.vocab_size)
    want, _ = qwen3.forward(params, cfg, tokens)

    mesh = make_mesh(MeshSpec(dp=2, ep=2, tp=2))
    specs = decoder_param_specs(cfg)
    sharded = shard_pytree(params, specs, mesh)
    f = jax.jit(lambda p, t: qwen3.forward(p, cfg, t)[0])
    got = f(sharded, tokens)
    np.testing.assert_allclose(got, want, rtol=5e-3, atol=5e-3)


def test_sharded_decode_with_cache():
    cfg = tiny_moe()
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    mesh = make_mesh(MeshSpec(dp=2, ep=2, tp=2))
    sharded = shard_pytree(params, decoder_param_specs(cfg), mesh)
    cache = qwen3.init_kv_cache(cfg, 4, 16)
    cache = shard_pytree(cache, kv_cache_specs(cfg), mesh)
    tokens = jax.random.randint(jax.random.PRNGKey(3), (4, 5), 0,
                                cfg.vocab_size)
    _, cache = qwen3.forward(sharded, cfg, tokens, None, cache)
    logits, cache = qwen3.decode_step(
        sharded, cfg, jnp.ones((4,), jnp.int32), cache
    )
    assert logits.shape == (4, cfg.vocab_size)
    assert int(cache["lengths"][0]) == 6


def test_gshard_moe_matches_ragged_in_model():
    """Full model forward with moe_impl=gshard equals the ragged path
    (generous capacity; same weights)."""
    import dataclasses

    cfg = tiny_moe()
    cfg_g = dataclasses.replace(cfg, moe_impl="gshard")
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(4), (2, 6), 0,
                                cfg.vocab_size)
    want, _ = qwen3.forward(params, cfg, tokens)
    got, _ = qwen3.forward(params, cfg_g, tokens)
    np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


def test_gshard_model_shards_over_ep():
    import dataclasses

    cfg = dataclasses.replace(tiny_moe(), moe_impl="gshard")
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    mesh = make_mesh(MeshSpec(dp=1, ep=4, tp=2))
    sharded = shard_pytree(params, decoder_param_specs(cfg), mesh)
    tokens = jax.random.randint(jax.random.PRNGKey(5), (2, 6), 0,
                                cfg.vocab_size)
    want, _ = qwen3.forward(params, cfg, tokens)
    got = jax.jit(lambda p, t: qwen3.forward(p, cfg, t)[0])(
        sharded, tokens
    )
    np.testing.assert_allclose(got, want, rtol=5e-3, atol=5e-3)


def test_remat_grads_match():
    """cfg.remat recomputes activations in backward; loss and grads
    must be identical to the non-remat path."""
    import dataclasses

    from room_tpu.train import init_train_state, make_train_step

    cfg = tiny_moe()
    tokens = jax.random.randint(jax.random.PRNGKey(0), (2, 8), 0,
                                cfg.vocab_size)
    mask = jnp.ones((2, 8), jnp.float32)

    def loss_and_grads(cfg):
        params = qwen3.init_params(cfg, jax.random.PRNGKey(0))

        def loss_fn(p):
            logits, _ = qwen3.forward(p, cfg, tokens)
            targets = jnp.roll(tokens, -1, axis=1)
            ll = jax.nn.log_softmax(logits.astype(jnp.float32))
            nll = -jnp.take_along_axis(
                ll, targets[..., None], axis=-1
            )[..., 0]
            return (nll * mask).sum() / mask.sum()

        return jax.value_and_grad(loss_fn)(params)

    base_loss, base_grads = loss_and_grads(cfg)
    r_loss, r_grads = loss_and_grads(
        dataclasses.replace(cfg, remat=True)
    )
    np.testing.assert_allclose(float(base_loss), float(r_loss),
                               rtol=1e-5)
    for a, b in zip(jax.tree.leaves(base_grads),
                    jax.tree.leaves(r_grads)):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=1e-4, atol=1e-5,
        )


def test_llama_family_forward_and_decode():
    """Third model family (llama shape: GQA, no qk-norm/bias, 500k
    theta) runs the shared decoder + cache path."""
    from room_tpu.models.config import tiny_llama

    cfg = tiny_llama()
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    assert "bq" not in params["layers"] and \
        "q_norm" not in params["layers"]
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 6), 0,
                                cfg.vocab_size)
    logits, _ = qwen3.forward(params, cfg, tokens)
    assert logits.shape == (2, 6, cfg.vocab_size)
    cache = qwen3.init_kv_cache(cfg, 2, 16)
    _, cache = qwen3.forward(params, cfg, tokens, None, cache)
    step, _ = qwen3.decode_step(
        params, cfg, jnp.ones((2,), jnp.int32), cache
    )
    assert np.isfinite(np.asarray(step)).all()


def test_llama_converter_roundtrip(tmp_path):
    """The HF converter covers the llama tensor layout (same names,
    no bias/qk-norm tensors present)."""
    from room_tpu.models.config import tiny_llama
    from room_tpu.utils.convert import convert_hf_decoder
    from tests.test_convert_ckpt import _write_hf_safetensors

    cfg = tiny_llama()
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    _write_hf_safetensors(tmp_path, cfg, params)
    converted = convert_hf_decoder(str(tmp_path), cfg, dtype="float32")
    tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 5), 0,
                                cfg.vocab_size)
    want, _ = qwen3.forward(params, cfg, tokens)
    got, _ = qwen3.forward(
        jax.tree.map(lambda x: np.asarray(x, np.float32), converted),
        cfg, tokens,
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_llama_serves_in_engine():
    from room_tpu.models.config import tiny_llama
    from room_tpu.serving import SamplingParams, ServingEngine

    cfg = tiny_llama()
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    eng = ServingEngine(cfg, params, max_batch=2, page_size=8,
                        n_pages=32)
    t = eng.submit([1, 2, 3], sampling=SamplingParams(
        temperature=0.0, max_new_tokens=4))
    eng.run_until_idle()
    assert t.finish_reason in ("stop", "length")
    assert len(t.new_tokens) >= 1
