"""Clerk tool dispatch, commentary engine behaviors, notification
delivery paths, and cloud-sync token/heartbeat handling — the
subsystems VERDICT r1 flagged as test-thin (shared 15 cases in
test_aux)."""

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from room_tpu.core import escalations, quorum, rooms, task_runner
from room_tpu.core.clerk import execute_clerk_tool


# ---- clerk tools ----

def test_clerk_list_rooms_and_status(db):
    rooms.create_room(db, "alpha", worker_model="echo")
    out = execute_clerk_tool(db, "list_rooms", {}, None)
    data = json.loads(out)
    assert data[0]["name"] == "alpha"
    out = execute_clerk_tool(db, "room_status", {"room_id": 1}, None)
    assert "alpha" in out


def test_clerk_create_room_and_task(db):
    out = execute_clerk_tool(
        db, "create_room",
        {"name": "made-by-clerk", "goal": "help"}, None,
    )
    assert "created" in out.lower() or "room" in out.lower()
    assert rooms.get_room(db, 1)["name"] == "made-by-clerk"

    out = execute_clerk_tool(
        db, "create_task",
        {"name": "tidy", "prompt": "clean up",
         "cron_expression": "0 8 * * *"}, None,
    )
    task = db.query_one("SELECT * FROM tasks WHERE name='tidy'")
    assert task is not None and task["cron_expression"] == "0 8 * * *"


def test_clerk_reminder_is_a_once_task(db):
    out = execute_clerk_tool(
        db, "create_reminder",
        {"text": "call the accountant",
         "at": "2099-01-01T09:00:00Z"}, None,
    )
    assert out
    task = db.query_one(
        "SELECT * FROM tasks ORDER BY id DESC LIMIT 1"
    )
    assert task is not None
    assert "accountant" in (task.get("prompt") or task["name"])
    assert task["trigger_type"] == "once"


def test_clerk_answer_escalation(db):
    rooms.create_room(db, "a", worker_model="echo")
    eid = escalations.create_escalation(db, 1, "budget?")
    out = execute_clerk_tool(
        db, "answer_escalation",
        {"escalation_id": eid, "answer": "500"}, None,
    )
    row = db.query_one(
        "SELECT status, answer FROM escalations WHERE id=?", (eid,)
    )
    assert row["status"] == "answered" and row["answer"] == "500"


def test_clerk_keeper_vote(db):
    rooms.create_room(db, "a", worker_model="echo")
    quorum.announce(db, 1, None, "buy a domain",
                    decision_type="high_impact")
    decision = db.query_one(
        "SELECT id FROM quorum_decisions WHERE proposal='buy a domain'"
    )
    out = execute_clerk_tool(
        db, "keeper_vote",
        {"decision_id": decision["id"], "vote": "no"}, None,
    )
    row = db.query_one(
        "SELECT status FROM quorum_decisions WHERE id=?",
        (decision["id"],),
    )
    assert row["status"] == "objected"


def test_clerk_run_task_now_requires_runtime(db):
    task_runner.create_task(db, "t", "p", trigger_type="manual")
    out = execute_clerk_tool(db, "run_task_now", {"task_id": 1}, None)
    assert "runtime" in out.lower() or "not running" in out.lower()


def test_clerk_unknown_tool(db):
    out = execute_clerk_tool(db, "juggle", {}, None)
    assert "unknown" in out.lower()


def test_clerk_tool_error_is_contained(db):
    out = execute_clerk_tool(
        db, "room_status", {"room_id": "NaN"}, None
    )
    assert "error" in out.lower() or "not found" in out.lower()


# ---- notifications ----

def test_digest_delivers_to_verified_email(db, tmp_path, monkeypatch):
    from room_tpu.server.contacts import (
        issue_email_verification, verify_email_code,
    )
    from room_tpu.server.notifications import relay_pending

    monkeypatch.setenv("ROOM_TPU_EMAIL_OUTBOX", str(tmp_path / "box"))
    rooms.create_room(db, "a", worker_model="echo")
    escalations.create_escalation(db, 1, "urgent: need funds")

    # no verified email yet: digest lands only in clerk messages
    digest = relay_pending(db)
    assert digest and "need funds" in digest
    assert not list((tmp_path / "box").glob("*")) if \
        (tmp_path / "box").exists() else True

    issue_email_verification(db, "keeper@example.com")
    import re

    mail = json.loads(
        sorted((tmp_path / "box").iterdir())[-1].read_text()
    )
    code = re.search(r"\b(\d{6})\b", mail["body"]).group(1)
    verify_email_code(db, code)

    escalations.create_escalation(db, 1, "second question")
    digest = relay_pending(db)
    assert digest and "second question" in digest
    mails = [json.loads(p.read_text())
             for p in sorted((tmp_path / "box").iterdir())]
    assert any("Keeper digest" == m["subject"] for m in mails)


def test_digest_cursor_prevents_resend(db, tmp_path, monkeypatch):
    from room_tpu.server.notifications import relay_pending

    monkeypatch.setenv("ROOM_TPU_EMAIL_OUTBOX", str(tmp_path / "box"))
    rooms.create_room(db, "a", worker_model="echo")
    escalations.create_escalation(db, 1, "only once")
    assert "only once" in relay_pending(db)
    assert relay_pending(db) is None  # nothing new


# ---- cloud sync ----

@pytest.fixture
def cloud_stub(monkeypatch, tmp_path):
    calls = []

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_POST(self):
            length = int(self.headers.get("Content-Length") or 0)
            body = json.loads(self.rfile.read(length) or b"{}")
            calls.append((self.path, body,
                          self.headers.get("Authorization")))
            if self.path.endswith("/rooms/register"):
                out = {"token": "room-token-1"}
            else:
                out = {"ok": True, "messages": []}
            data = json.dumps(out).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

    srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    monkeypatch.setenv("ROOM_TPU_DATA_DIR", str(tmp_path))
    monkeypatch.setenv(
        "ROOM_TPU_CLOUD_API",
        f"http://127.0.0.1:{srv.server_address[1]}",
    )
    yield calls
    srv.shutdown()
    srv.server_close()


def test_cloud_token_registration_and_persistence(db, cloud_stub,
                                                  tmp_path):
    from room_tpu.server.cloud_sync import ensure_cloud_room_token

    rooms.create_room(db, "a", worker_model="echo")
    token = ensure_cloud_room_token(db, 1)
    assert token == "room-token-1"
    assert any("/rooms/register" in path for path, _b, _a in cloud_stub)
    # persisted on disk with owner-only mode
    tok_file = [p for p in tmp_path.rglob("*") if "token" in p.name]
    assert tok_file, "tokens not persisted"
    assert oct(tok_file[0].stat().st_mode & 0o777) == "0o600"
    # second call reuses the stored token (no extra register)
    n = len(cloud_stub)
    assert ensure_cloud_room_token(db, 1) == "room-token-1"
    assert len(cloud_stub) == n


def test_cloud_heartbeat(db, cloud_stub):
    from room_tpu.server.cloud_sync import (
        ensure_cloud_room_token, send_heartbeat,
    )

    rooms.create_room(db, "a", worker_model="echo")
    ensure_cloud_room_token(db, 1)
    assert send_heartbeat(db, 1) is True
    assert any("heartbeat" in path for path, _b, _a in cloud_stub)


def test_cloud_sync_silent_when_unconfigured(db, monkeypatch):
    from room_tpu.server.cloud_sync import (
        ensure_cloud_room_token, send_heartbeat,
    )

    monkeypatch.delenv("ROOM_TPU_CLOUD_API", raising=False)
    rooms.create_room(db, "a", worker_model="echo")
    assert ensure_cloud_room_token(db, 1) is None
    assert send_heartbeat(db, 1) is False


def test_cloud_sync_survives_unreachable_api(db, monkeypatch,
                                             tmp_path):
    from room_tpu.server.cloud_sync import ensure_cloud_room_token

    monkeypatch.setenv("ROOM_TPU_DATA_DIR", str(tmp_path))
    monkeypatch.setenv("ROOM_TPU_CLOUD_API", "http://127.0.0.1:1")
    rooms.create_room(db, "a", worker_model="echo")
    assert ensure_cloud_room_token(db, 1) is None  # silent failure
