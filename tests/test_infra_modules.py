"""Coverage for the quiet infra modules: telemetry (dedupe, gating),
process supervisor (tree walk + kill sweep), learned-context distill
triggers, secrets envelope edges, rate-limit reset parsing corners.
(Reference analogues: telemetry.test.ts, process-supervisor tests,
learned-context.test.ts.)"""

import os
import signal
import subprocess
import time

import pytest

from room_tpu.core import learned_context, rate_limit, supervisor, telemetry
from room_tpu.core.secrets import decrypt_secret, encrypt_secret


# ---- telemetry ----

def test_telemetry_machine_id_stable_and_anonymous():
    a = telemetry.get_machine_id()
    b = telemetry.get_machine_id()
    assert a == b and len(a) == 12
    assert os.uname().nodename not in a


def test_crash_report_dedupes(db, monkeypatch):
    sent = []
    monkeypatch.setenv("ROOM_TPU_TELEMETRY_TOKEN", "t0k")
    monkeypatch.setenv("ROOM_TPU_TELEMETRY_URL", "http://127.0.0.1:1")
    monkeypatch.setattr(
        telemetry, "_post", lambda payload: sent.append(payload) or True
    )
    err = RuntimeError("boom")
    assert telemetry.submit_crash_report(db, err, "ctx") is True
    assert telemetry.submit_crash_report(db, err, "ctx") is False
    assert len(sent) == 1  # same signature sent once per day
    assert sent[0]["error"].startswith("RuntimeError")


# ---- process supervisor ----

def test_tree_kill_reaps_every_descendant():
    # spawn a parent that spawns a child, then kill the tree
    proc = subprocess.Popen(
        ["/bin/sh", "-c", "sleep 30 & wait"],
    )
    supervisor.register_managed_process(proc.pid, "test-tree")
    try:
        deadline = time.time() + 5
        kids = []
        while time.time() < deadline:
            kids = supervisor._descendants(proc.pid)
            if kids:
                break
            time.sleep(0.05)
        assert kids, "child sleep never appeared"
        killed = supervisor.kill_pid_tree(proc.pid)
        assert killed >= 1
        proc.wait(timeout=5)
        # SIGKILL delivery to the orphaned child is async: poll
        deadline = time.time() + 5
        for pid in kids:
            while time.time() < deadline:
                try:
                    os.kill(pid, 0)
                    time.sleep(0.05)
                except OSError:
                    break
            else:
                pytest.fail(f"descendant {pid} survived tree kill")
    finally:
        supervisor.unregister_managed_process(proc.pid)
        if proc.poll() is None:
            proc.kill()


def test_terminate_managed_sweep():
    proc = subprocess.Popen(["sleep", "30"])
    supervisor.register_managed_process(proc.pid, "sweep-me")
    n = supervisor.terminate_managed_processes(grace_s=0.5)
    assert n >= 1
    proc.wait(timeout=5)
    assert proc.pid not in supervisor.managed_processes()


def test_spawn_managed_registers_and_cleans():
    proc = supervisor.spawn_managed(["sleep", "0.1"], label="quick")
    assert proc.pid in supervisor.managed_processes()
    proc.wait(timeout=5)
    supervisor.unregister_managed_process(proc.pid)


def test_descendants_ps_fallback(monkeypatch):
    """With /proc unreadable, _descendants must find the same children
    via the `ps` fallback path."""
    proc = subprocess.Popen(["/bin/sh", "-c", "sleep 30 & wait"])
    try:
        deadline = time.time() + 5
        kids = []
        while time.time() < deadline:
            kids = supervisor._descendants(proc.pid)
            if kids:
                break
            time.sleep(0.05)
        assert kids, "child sleep never appeared via /proc"

        real_listdir = os.listdir

        def no_proc(path, *a, **kw):
            if str(path) == "/proc":
                raise OSError("proc unavailable")
            return real_listdir(path, *a, **kw)

        monkeypatch.setattr(os, "listdir", no_proc)
        via_ps = supervisor._descendants(proc.pid)
        assert set(kids) <= set(via_ps), (
            f"/proc saw {kids}, ps fallback saw {via_ps}"
        )
    finally:
        supervisor.kill_pid_tree(proc.pid, signal.SIGKILL)
        proc.wait(timeout=5)


def test_terminate_sweep_forces_sigterm_ignorer():
    """The graceful-then-forced sweep: a child that traps SIGTERM must
    still die (SIGKILL) and leave the registry empty."""
    proc = subprocess.Popen(
        ["/bin/sh", "-c", "trap '' TERM; sleep 30"],
    )
    # wait until the trap is installed (sh execs the trap immediately,
    # but give the process a moment to start)
    time.sleep(0.2)
    supervisor.register_managed_process(proc.pid, "stubborn")
    t0 = time.time()
    n = supervisor.terminate_managed_processes(grace_s=0.5)
    assert n >= 1
    proc.wait(timeout=5)
    assert proc.returncode == -signal.SIGKILL
    # the sweep waited for the grace window before forcing
    assert time.time() - t0 >= 0.5
    assert proc.pid not in supervisor.managed_processes()


# ---- learned context ----

def test_should_distill_every_three_runs():
    fire = [learned_context.should_distill(
        {"run_count": n, "learned_context": "x" if n else None}
    ) for n in range(1, 10)]
    # fires at 3, 6, 9 (refresh cadence), never below 3
    assert fire == [False, False, True, False, False, True,
                    False, False, True]


def test_distill_persists_memo(db):
    from room_tpu.core import task_runner
    from room_tpu.providers import reset_provider_cache

    reset_provider_cache()
    tid = task_runner.create_task(db, "t", "do", trigger_type="manual")
    for _ in range(3):
        db.insert(
            "INSERT INTO task_runs(task_id, status, result) "
            "VALUES (?, 'success', 'built the thing')", (tid,),
        )
    db.execute("UPDATE tasks SET run_count=3 WHERE id=?", (tid,))

    memo = learned_context.distill_learned_context(
        db, task_runner.get_task(db, tid), "echo"
    )
    assert memo
    assert task_runner.get_task(db, tid)["learned_context"] == memo


class _LongProvider:
    def execute(self, req):
        from room_tpu.providers.base import ExecutionResult

        return ExecutionResult(success=True, text="y" * 9000)


def test_distill_caps_length(db, monkeypatch):
    from room_tpu.core import task_runner

    tid = task_runner.create_task(db, "t2", "do", trigger_type="manual")
    db.insert(
        "INSERT INTO task_runs(task_id, status, result) "
        "VALUES (?, 'success', 'r')", (tid,),
    )
    db.execute("UPDATE tasks SET run_count=3 WHERE id=?", (tid,))
    monkeypatch.setattr(
        learned_context, "get_model_provider",
        lambda model, db=None: _LongProvider(),
    )
    memo = learned_context.distill_learned_context(
        db, task_runner.get_task(db, tid), "echo"
    )
    assert memo is not None and len(memo) <= 1500


# ---- secrets envelope edges ----

def test_secret_envelope_roundtrip_and_tamper():
    enc = encrypt_secret("hunter2")
    assert enc.startswith("enc:v1:")
    assert decrypt_secret(enc) == "hunter2"
    # bit-flip the ciphertext: must raise, not return garbage
    tampered = enc[:-4] + ("AAAA" if not enc.endswith("AAAA") else "BBBB")
    with pytest.raises(Exception):
        decrypt_secret(tampered)


def test_decrypt_rejects_plaintext():
    # a non-envelope value must be rejected loudly, not decrypted
    with pytest.raises(ValueError, match="envelope"):
        decrypt_secret("plain-old-value")


# ---- rate limit parsing corners ----

@pytest.mark.parametrize("msg", [
    "usage limit reached|please wait",
    "429 Too Many Requests",
    "rate limit exceeded, try again later",
])
def test_detect_rate_limit_patterns(msg):
    assert rate_limit.detect_rate_limit(msg) is not None


def test_rate_limit_wait_clamped():
    w = rate_limit.detect_rate_limit(
        "rate limit exceeded. resets in 9 hours"
    )
    assert w is not None and w <= 60 * 60  # seconds, 60-min clamp


def test_non_rate_limit_errors_pass():
    assert rate_limit.detect_rate_limit("file not found") is None
    assert rate_limit.detect_rate_limit("") is None
