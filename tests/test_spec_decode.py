"""Prompt-lookup speculative decoding: drafting from the session's own
history, one-forward verification, exact greedy equivalence.

No reference counterpart (the reference's decoding lives inside Ollama);
TPU-first new work — decode streams the full weight set per device call,
so every accepted draft token divides the HBM-bandwidth bill.
"""

import jax
import numpy as np
import pytest

from room_tpu.models import qwen3, tiny_moe
from room_tpu.serving import SamplingParams, ServingEngine
from room_tpu.serving.engine import propose_ngram


@pytest.fixture(scope="module")
def setup():
    cfg = tiny_moe()
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def make_engine(cfg, params, **kw):
    kw.setdefault("max_batch", 4)
    kw.setdefault("page_size", 8)
    kw.setdefault("n_pages", 64)
    return ServingEngine(cfg, params, **kw)


def test_propose_ngram():
    # trailing 3-gram [5,6,7] occurred earlier; propose what followed
    seq = [1, 5, 6, 7, 9, 9, 2, 5, 6, 7]
    assert propose_ngram(seq, 3) == [9, 9, 2]
    assert propose_ngram(seq, 1) == [9]
    # 2-gram fallback
    assert propose_ngram([4, 4, 1, 2, 8, 1, 2], 2) == [8, 1]
    # no repeat -> no proposal
    assert propose_ngram([1, 2, 3, 4, 5], 4) == []
    # too short
    assert propose_ngram([1, 2], 4) == []


@pytest.mark.parametrize("prompt", [
    # repetitive: drafts should be accepted
    [5, 6, 7, 5, 6, 7, 5, 6, 7, 5, 6],
    # arbitrary: speculation must not change anything
    [1, 2, 3, 4],
])
def test_spec_greedy_token_identity(setup, prompt):
    cfg, params = setup
    sp = SamplingParams(temperature=0.0, max_new_tokens=12)

    base_eng = make_engine(cfg, params, spec_tokens=0)
    want = base_eng.submit(prompt, sampling=sp)
    base_eng.run_until_idle()

    spec_eng = make_engine(cfg, params, spec_tokens=4)
    got = spec_eng.submit(prompt, sampling=sp)
    spec_eng.run_until_idle()

    assert got.new_tokens == want.new_tokens
    # speculation must save device calls whenever drafts are accepted:
    # rounds + accepted tokens must cover all decoded tokens. (With a
    # random-weight model the generation may never repeat; the no-draft
    # rounds then fall back to the chunked path, which is the point.)
    assert spec_eng.stats()["decode_steps"] <= len(got.new_tokens)


def test_spec_accepts_on_repetitive_generation():
    """A model generating a repeating pattern must actually accept
    drafts (the whole point): fewer device rounds than decoded tokens.
    An 8-token vocabulary forces greedy generation into a cycle within
    a few steps, so drafting engages deterministically."""
    cfg = tiny_moe(vocab_size=8)
    params = qwen3.init_params(cfg, jax.random.PRNGKey(3))
    sp = SamplingParams(temperature=0.0, max_new_tokens=32)
    prompt = [1, 2, 3, 1, 2, 3]

    base_eng = make_engine(cfg, params, spec_tokens=0)
    want = base_eng.submit(prompt, sampling=sp)
    base_eng.run_until_idle()

    eng = make_engine(cfg, params, spec_tokens=4)
    turn = eng.submit(prompt, sampling=sp)
    eng.run_until_idle()
    assert turn.new_tokens == want.new_tokens
    st = eng.stats()
    assert st["spec_rounds"] > 0 and st["spec_accepted"] > 0, st
    assert st["decode_steps"] < len(turn.new_tokens), st


def test_spec_batched_sessions_match_non_spec(setup):
    cfg, params = setup
    sp = SamplingParams(temperature=0.0, max_new_tokens=8)
    prompts = [
        [5, 6, 7, 5, 6, 7, 5, 6],
        [1, 2, 3, 4],
        [9, 9, 9, 9, 9],
    ]

    base_eng = make_engine(cfg, params, spec_tokens=0)
    base = [base_eng.submit(p, sampling=sp) for p in prompts]
    base_eng.run_until_idle()

    spec_eng = make_engine(cfg, params, spec_tokens=3)
    got = [spec_eng.submit(p, sampling=sp) for p in prompts]
    spec_eng.run_until_idle()

    assert [t.new_tokens for t in got] == [t.new_tokens for t in base]


def test_spec_session_continuation(setup):
    """Two turns on one session (resume on retained KV) must be
    token-identical with and without speculation."""
    cfg, params = setup
    sp = SamplingParams(temperature=0.0, max_new_tokens=6)

    def two_turns(eng):
        t1 = eng.submit([5, 6, 7, 5, 6, 7], session_id="s",
                        sampling=sp)
        eng.run_until_idle()
        t2 = eng.submit([5, 6, 7], session_id="s", sampling=sp)
        eng.run_until_idle()
        return t1.new_tokens, t2.new_tokens

    base = two_turns(make_engine(cfg, params, spec_tokens=0))
    spec = two_turns(make_engine(cfg, params, spec_tokens=4))
    assert spec == base


def test_spec_verify_greedy_reduction():
    """temperature=0 rows reduce to argmax verification: accept iff the
    draft IS the argmax, and the rejection emission is the argmax."""
    import jax.numpy as jnp

    from room_tpu.serving.sampler import spec_verify

    logits = jnp.asarray([[[0.0, 3.0, 1.0, 2.0],
                           [5.0, 0.0, 1.0, 2.0],
                           [0.0, 0.0, 9.0, 2.0]]])   # argmax: 1, 0, 2
    drafts = jnp.asarray([[1, 3]])   # draft0 == argmax, draft1 != argmax
    accept, residual, plain = spec_verify(
        logits, drafts, jax.random.PRNGKey(0),
        jnp.zeros((1,)), jnp.ones((1,)), jnp.zeros((1,), jnp.int32),
    )
    assert accept[0, 0] and not accept[0, 1]
    assert int(residual[0, 1]) == 0      # argmax at the rejected slot
    assert plain[0].tolist() == [1, 0, 2]


def test_spec_verify_preserves_distribution():
    """The accept/residual scheme must exactly preserve the target
    sampling distribution: P(emit = x) = p(x) regardless of the draft
    (Leviathan et al. with a deterministic draft)."""
    import jax.numpy as jnp

    from room_tpu.serving.sampler import spec_verify

    base = jnp.asarray([2.0, 1.0, 0.5, 0.0])
    target = np.asarray(jax.nn.softmax(base / 0.8))
    n = 20_000

    def one(key):
        accept, residual, _ = spec_verify(
            base[None, None, :].repeat(2, axis=1),  # W=2: draft pos + bonus
            jnp.asarray([[2]]),                     # draft a mid-prob token
            key,
            jnp.asarray([0.8]), jnp.ones((1,)),
            jnp.zeros((1,), jnp.int32),
        )
        return jnp.where(accept[0, 0], 2, residual[0, 0])

    toks = np.asarray(jax.vmap(one)(
        jax.random.split(jax.random.PRNGKey(1), n)
    ))
    freq = np.bincount(toks, minlength=4) / n
    np.testing.assert_allclose(freq, target, atol=0.015)


def test_spec_top_k1_matches_greedy(setup):
    """top_k=1 sampling is a delta distribution, so a stochastic spec
    run must emit exactly the greedy sequence (drafting included)."""
    cfg, params = setup
    prompt = [5, 6, 7, 5, 6, 7, 5, 6]

    base_eng = make_engine(cfg, params, spec_tokens=0)
    want = base_eng.submit(prompt, sampling=SamplingParams(
        temperature=0.0, max_new_tokens=10))
    base_eng.run_until_idle()

    eng = make_engine(cfg, params, spec_tokens=4)
    got = eng.submit(prompt, sampling=SamplingParams(
        temperature=0.9, top_k=1, max_new_tokens=10))
    eng.run_until_idle()
    assert got.new_tokens == want.new_tokens


def test_spec_stochastic_rows_complete(setup):
    """Sampling rows draft too (speculative sampling keeps their exact
    distribution) and finish alongside greedy batchmates."""
    cfg, params = setup
    eng = make_engine(cfg, params, spec_tokens=4)
    greedy = eng.submit(
        [5, 6, 7, 5, 6, 7],
        sampling=SamplingParams(temperature=0.0, max_new_tokens=6),
    )
    stoch = eng.submit(
        [1, 2, 3],
        sampling=SamplingParams(temperature=0.8, max_new_tokens=6),
    )
    eng.run_until_idle()
    assert greedy.finish_reason in ("stop", "length")
    assert stoch.finish_reason in ("stop", "length")
    assert 1 <= len(stoch.new_tokens) <= 6
    assert all(0 <= t < cfg.vocab_size for t in stoch.new_tokens)


def test_spec_respects_max_new_tokens(setup):
    cfg, params = setup
    eng = make_engine(cfg, params, spec_tokens=4)
    turn = eng.submit(
        [5, 6, 7, 5, 6, 7, 5, 6],
        sampling=SamplingParams(temperature=0.0, max_new_tokens=3),
    )
    eng.run_until_idle()
    assert len(turn.new_tokens) <= 3


def test_spec_on_mesh_token_identity(setup):
    """Speculation composes with multi-chip serving: spec engine on the
    8-device mesh == non-spec single-device engine."""
    from room_tpu.parallel import (
        MeshSpec, decoder_param_specs, make_mesh, shard_pytree,
    )

    cfg, params = setup
    sp = SamplingParams(temperature=0.0, max_new_tokens=8)
    prompts = [[5, 6, 7, 5, 6, 7, 5, 6], [1, 2, 3, 4]]

    base_eng = make_engine(cfg, params, spec_tokens=0)
    base = [base_eng.submit(p, sampling=sp) for p in prompts]
    base_eng.run_until_idle()

    mesh = make_mesh(MeshSpec(2, 2, 2))
    sharded = shard_pytree(params, decoder_param_specs(cfg), mesh)
    eng = make_engine(cfg, sharded, mesh=mesh, spec_tokens=4)
    got = [eng.submit(p, sampling=sp) for p in prompts]
    eng.run_until_idle()
    assert [t.new_tokens for t in got] == [t.new_tokens for t in base]


def test_spec_oversubscribed_pool_completes(setup):
    """Speculation under pool pressure: eviction degrades the round,
    never corrupts or deadlocks."""
    cfg, params = setup
    eng = make_engine(cfg, params, max_batch=2, page_size=4, n_pages=17,
                      spec_tokens=4)
    sp = SamplingParams(temperature=0.0, max_new_tokens=4)
    turns = [
        eng.submit([5, 6, 7, 5, 6, 7], session_id=f"s{i}", sampling=sp)
        for i in range(8)
    ]
    eng.run_until_idle()
    assert all(t.finish_reason in ("stop", "length") for t in turns)


def test_spec_mixed_penalized_batch_rides_spec_per_row():
    """One penalized tenant must not pull the whole batch off spec
    (ADVICE r3): non-penalized rows still ride spec (token-identical to
    the non-spec engine), the penalized row takes the sequential scan
    in the same round, and the split is visible in stats."""
    # an 8-token vocabulary forces greedy generation into a cycle, so
    # the plain row's drafts engage deterministically
    cfg = tiny_moe(vocab_size=8)
    params = qwen3.init_params(cfg, jax.random.PRNGKey(3))
    rep = [1, 2, 3, 1, 2, 3]
    plain_sp = SamplingParams(temperature=0.0, max_new_tokens=32)
    pen_sp = SamplingParams(
        temperature=0.0, max_new_tokens=32,
        presence_penalty=0.6, frequency_penalty=0.2,
    )

    base = make_engine(cfg, params, spec_tokens=0)
    b1 = base.submit(rep, sampling=plain_sp, session_id="p1")
    b2 = base.submit(list(rep), sampling=pen_sp, session_id="p2")
    base.run_until_idle()

    eng = make_engine(cfg, params, spec_tokens=4)
    g1 = eng.submit(rep, sampling=plain_sp, session_id="p1")
    g2 = eng.submit(list(rep), sampling=pen_sp, session_id="p2")
    eng.run_until_idle()

    assert g1.new_tokens == b1.new_tokens
    assert g2.new_tokens == b2.new_tokens
    st = eng.stats()
    assert st["spec_rounds"] > 0
    assert st["spec_rows_sequential"] > 0
