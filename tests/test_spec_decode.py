"""On-mesh speculative decoding fused into the dispatch window
(docs/serving.md): device-tail prompt-lookup drafting, in-scan
verification, exact greedy equivalence — and the pinned identity
matrix across window depth x fused dispatch x prefix-hit x
offload-restore.

No reference counterpart (the reference's decoding lives inside Ollama);
TPU-first new work — decode streams the full weight set per device call,
so every accepted draft token divides the HBM-bandwidth bill.
"""

import jax
import numpy as np
import pytest

from room_tpu.models import qwen3, tiny_moe
from room_tpu.serving import SamplingParams, ServingEngine, faults
from room_tpu.serving.engine import propose_ngram


@pytest.fixture(scope="module")
def setup():
    cfg = tiny_moe()
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def make_engine(cfg, params, **kw):
    kw.setdefault("max_batch", 4)
    kw.setdefault("page_size", 8)
    kw.setdefault("n_pages", 64)
    return ServingEngine(cfg, params, **kw)


def test_propose_ngram():
    # trailing 3-gram [5,6,7] occurred earlier; propose what followed
    seq = [1, 5, 6, 7, 9, 9, 2, 5, 6, 7]
    assert propose_ngram(seq, 3) == [9, 9, 2]
    assert propose_ngram(seq, 1) == [9]
    # 2-gram fallback
    assert propose_ngram([4, 4, 1, 2, 8, 1, 2], 2) == [8, 1]
    # no repeat -> no proposal
    assert propose_ngram([1, 2, 3, 4, 5], 4) == []
    # too short
    assert propose_ngram([1, 2], 4) == []


@pytest.mark.parametrize("prompt", [
    # repetitive: drafts should be accepted
    [5, 6, 7, 5, 6, 7, 5, 6, 7, 5, 6],
    # arbitrary: speculation must not change anything
    [1, 2, 3, 4],
])
def test_spec_greedy_token_identity(setup, prompt):
    cfg, params = setup
    sp = SamplingParams(temperature=0.0, max_new_tokens=12)

    base_eng = make_engine(cfg, params, spec_tokens=0)
    want = base_eng.submit(prompt, sampling=sp)
    base_eng.run_until_idle()

    spec_eng = make_engine(cfg, params, spec_tokens=4)
    got = spec_eng.submit(prompt, sampling=sp)
    spec_eng.run_until_idle()

    assert got.new_tokens == want.new_tokens
    # speculation must save device calls whenever drafts are accepted:
    # rounds + accepted tokens must cover all decoded tokens. (With a
    # random-weight model the generation may never repeat; the no-draft
    # rounds then fall back to the chunked path, which is the point.)
    assert spec_eng.stats()["decode_steps"] <= len(got.new_tokens)


def test_spec_accepts_on_repetitive_generation():
    """A model generating a repeating pattern must actually accept
    drafts (the whole point): fewer device rounds than decoded tokens.
    An 8-token vocabulary forces greedy generation into a cycle within
    a few steps, so drafting engages deterministically."""
    cfg = tiny_moe(vocab_size=8)
    params = qwen3.init_params(cfg, jax.random.PRNGKey(3))
    sp = SamplingParams(temperature=0.0, max_new_tokens=32)
    prompt = [1, 2, 3, 1, 2, 3]

    base_eng = make_engine(cfg, params, spec_tokens=0)
    want = base_eng.submit(prompt, sampling=sp)
    base_eng.run_until_idle()

    eng = make_engine(cfg, params, spec_tokens=4)
    turn = eng.submit(prompt, sampling=sp)
    eng.run_until_idle()
    assert turn.new_tokens == want.new_tokens
    st = eng.stats()
    assert st["spec_rounds"] > 0 and st["spec_accepted"] > 0, st
    assert st["decode_steps"] < len(turn.new_tokens), st


def test_spec_batched_sessions_match_non_spec(setup):
    cfg, params = setup
    sp = SamplingParams(temperature=0.0, max_new_tokens=8)
    prompts = [
        [5, 6, 7, 5, 6, 7, 5, 6],
        [1, 2, 3, 4],
        [9, 9, 9, 9, 9],
    ]

    base_eng = make_engine(cfg, params, spec_tokens=0)
    base = [base_eng.submit(p, sampling=sp) for p in prompts]
    base_eng.run_until_idle()

    spec_eng = make_engine(cfg, params, spec_tokens=3)
    got = [spec_eng.submit(p, sampling=sp) for p in prompts]
    spec_eng.run_until_idle()

    assert [t.new_tokens for t in got] == [t.new_tokens for t in base]


def test_spec_session_continuation(setup):
    """Two turns on one session (resume on retained KV) must be
    token-identical with and without speculation."""
    cfg, params = setup
    sp = SamplingParams(temperature=0.0, max_new_tokens=6)

    def two_turns(eng):
        t1 = eng.submit([5, 6, 7, 5, 6, 7], session_id="s",
                        sampling=sp)
        eng.run_until_idle()
        t2 = eng.submit([5, 6, 7], session_id="s", sampling=sp)
        eng.run_until_idle()
        return t1.new_tokens, t2.new_tokens

    base = two_turns(make_engine(cfg, params, spec_tokens=0))
    spec = two_turns(make_engine(cfg, params, spec_tokens=4))
    assert spec == base


def test_spec_verify_greedy_reduction():
    """temperature=0 rows reduce to argmax verification: accept iff the
    draft IS the argmax, and the rejection emission is the argmax."""
    import jax.numpy as jnp

    from room_tpu.serving.sampler import spec_verify

    logits = jnp.asarray([[[0.0, 3.0, 1.0, 2.0],
                           [5.0, 0.0, 1.0, 2.0],
                           [0.0, 0.0, 9.0, 2.0]]])   # argmax: 1, 0, 2
    drafts = jnp.asarray([[1, 3]])   # draft0 == argmax, draft1 != argmax
    accept, residual, plain = spec_verify(
        logits, drafts, jax.random.PRNGKey(0),
        jnp.zeros((1,)), jnp.ones((1,)), jnp.zeros((1,), jnp.int32),
    )
    assert accept[0, 0] and not accept[0, 1]
    assert int(residual[0, 1]) == 0      # argmax at the rejected slot
    assert plain[0].tolist() == [1, 0, 2]


def test_spec_verify_preserves_distribution():
    """The accept/residual scheme must exactly preserve the target
    sampling distribution: P(emit = x) = p(x) regardless of the draft
    (Leviathan et al. with a deterministic draft)."""
    import jax.numpy as jnp

    from room_tpu.serving.sampler import spec_verify

    base = jnp.asarray([2.0, 1.0, 0.5, 0.0])
    target = np.asarray(jax.nn.softmax(base / 0.8))
    n = 20_000

    def one(key):
        accept, residual, _ = spec_verify(
            base[None, None, :].repeat(2, axis=1),  # W=2: draft pos + bonus
            jnp.asarray([[2]]),                     # draft a mid-prob token
            key,
            jnp.asarray([0.8]), jnp.ones((1,)),
            jnp.zeros((1,), jnp.int32),
        )
        return jnp.where(accept[0, 0], 2, residual[0, 0])

    toks = np.asarray(jax.vmap(one)(
        jax.random.split(jax.random.PRNGKey(1), n)
    ))
    freq = np.bincount(toks, minlength=4) / n
    np.testing.assert_allclose(freq, target, atol=0.015)


def test_spec_top_k1_matches_greedy(setup):
    """top_k=1 sampling is a delta distribution, so a stochastic spec
    run must emit exactly the greedy sequence (drafting included)."""
    cfg, params = setup
    prompt = [5, 6, 7, 5, 6, 7, 5, 6]

    base_eng = make_engine(cfg, params, spec_tokens=0)
    want = base_eng.submit(prompt, sampling=SamplingParams(
        temperature=0.0, max_new_tokens=10))
    base_eng.run_until_idle()

    eng = make_engine(cfg, params, spec_tokens=4)
    got = eng.submit(prompt, sampling=SamplingParams(
        temperature=0.9, top_k=1, max_new_tokens=10))
    eng.run_until_idle()
    assert got.new_tokens == want.new_tokens


def test_spec_stochastic_rows_complete(setup):
    """Sampling rows draft too (speculative sampling keeps their exact
    distribution) and finish alongside greedy batchmates."""
    cfg, params = setup
    eng = make_engine(cfg, params, spec_tokens=4)
    greedy = eng.submit(
        [5, 6, 7, 5, 6, 7],
        sampling=SamplingParams(temperature=0.0, max_new_tokens=6),
    )
    stoch = eng.submit(
        [1, 2, 3],
        sampling=SamplingParams(temperature=0.8, max_new_tokens=6),
    )
    eng.run_until_idle()
    assert greedy.finish_reason in ("stop", "length")
    assert stoch.finish_reason in ("stop", "length")
    assert 1 <= len(stoch.new_tokens) <= 6
    assert all(0 <= t < cfg.vocab_size for t in stoch.new_tokens)


def test_spec_respects_max_new_tokens(setup):
    cfg, params = setup
    eng = make_engine(cfg, params, spec_tokens=4)
    turn = eng.submit(
        [5, 6, 7, 5, 6, 7, 5, 6],
        sampling=SamplingParams(temperature=0.0, max_new_tokens=3),
    )
    eng.run_until_idle()
    assert len(turn.new_tokens) <= 3


def test_spec_on_mesh_token_identity(setup):
    """Speculation composes with multi-chip serving: spec engine on the
    8-device mesh == non-spec single-device engine."""
    from room_tpu.parallel import (
        MeshSpec, decoder_param_specs, make_mesh, shard_pytree,
    )

    cfg, params = setup
    sp = SamplingParams(temperature=0.0, max_new_tokens=8)
    prompts = [[5, 6, 7, 5, 6, 7, 5, 6], [1, 2, 3, 4]]

    base_eng = make_engine(cfg, params, spec_tokens=0)
    base = [base_eng.submit(p, sampling=sp) for p in prompts]
    base_eng.run_until_idle()

    mesh = make_mesh(MeshSpec(2, 2, 2))
    sharded = shard_pytree(params, decoder_param_specs(cfg), mesh)
    eng = make_engine(cfg, sharded, mesh=mesh, spec_tokens=4)
    got = [eng.submit(p, sampling=sp) for p in prompts]
    eng.run_until_idle()
    assert [t.new_tokens for t in got] == [t.new_tokens for t in base]


def test_spec_oversubscribed_pool_completes(setup):
    """Speculation under pool pressure: eviction degrades the round,
    never corrupts or deadlocks."""
    cfg, params = setup
    eng = make_engine(cfg, params, max_batch=2, page_size=4, n_pages=17,
                      spec_tokens=4)
    sp = SamplingParams(temperature=0.0, max_new_tokens=4)
    turns = [
        eng.submit([5, 6, 7, 5, 6, 7], session_id=f"s{i}", sampling=sp)
        for i in range(8)
    ]
    eng.run_until_idle()
    assert all(t.finish_reason in ("stop", "length") for t in turns)


def test_spec_mixed_penalized_batch_rides_spec_per_row():
    """One penalized tenant must not pull the whole batch off spec
    (ADVICE r3): non-penalized rows still ride spec (token-identical to
    the non-spec engine), the penalized row takes the sequential scan
    in the same round, and the split is visible in stats."""
    # an 8-token vocabulary forces greedy generation into a cycle, so
    # the plain row's drafts engage deterministically
    cfg = tiny_moe(vocab_size=8)
    params = qwen3.init_params(cfg, jax.random.PRNGKey(3))
    rep = [1, 2, 3, 1, 2, 3]
    plain_sp = SamplingParams(temperature=0.0, max_new_tokens=32)
    pen_sp = SamplingParams(
        temperature=0.0, max_new_tokens=32,
        presence_penalty=0.6, frequency_penalty=0.2,
    )

    base = make_engine(cfg, params, spec_tokens=0)
    b1 = base.submit(rep, sampling=plain_sp, session_id="p1")
    b2 = base.submit(list(rep), sampling=pen_sp, session_id="p2")
    base.run_until_idle()

    eng = make_engine(cfg, params, spec_tokens=4)
    g1 = eng.submit(rep, sampling=plain_sp, session_id="p1")
    g2 = eng.submit(list(rep), sampling=pen_sp, session_id="p2")
    eng.run_until_idle()

    assert g1.new_tokens == b1.new_tokens
    assert g2.new_tokens == b2.new_tokens
    st = eng.stats()
    assert st["spec_rounds"] > 0
    assert st["spec_rows_sequential"] > 0


# ---- the pinned identity matrix (docs/serving.md) ----
# An 8-token vocabulary forces greedy generation into a cycle within a
# few steps, so in-window drafting engages (and accepts) determinist-
# ically on every cell of the matrix.

REP = [1, 2, 3, 1, 2, 3]
LONG = ([1, 2, 3, 4, 5, 6, 7, 0] * 5)[:37]   # 5 pages -> chunked
PREFIX = [2, 4, 6, 1, 3, 5, 7, 2] * 3        # 24 tokens = 3 aligned pages


@pytest.fixture(scope="module")
def model8():
    cfg = tiny_moe(vocab_size=8)
    params = qwen3.init_params(cfg, jax.random.PRNGKey(3))
    return cfg, params


@pytest.fixture()
def build8(model8, monkeypatch):
    cfg, params = model8

    def make(steps, fused=True, chunk_pages=1, **kw):
        monkeypatch.setenv(
            "ROOM_TPU_DECODE_STEPS_PER_DISPATCH", str(steps)
        )
        monkeypatch.setenv(
            "ROOM_TPU_FUSED_WINDOW", "1" if fused else "0"
        )
        monkeypatch.setenv(
            "ROOM_TPU_PREFILL_CHUNK_PAGES", str(chunk_pages)
        )
        kw.setdefault("max_batch", 4)
        kw.setdefault("page_size", 8)
        kw.setdefault("n_pages", 128)
        return ServingEngine(cfg, params, **kw)

    return make


def _g(n=8):
    return SamplingParams(temperature=0.0, max_new_tokens=n)


def _matrix_streams(eng):
    """Canonical matrix traffic: a repetitive decode turn (drafting
    engages), a long chunked prompt (rides the fused window when
    enabled), a prefix-cache hit pair, and an offload-hibernate/
    restore continuation."""
    a = eng.submit(REP, session_id="rep", sampling=_g(24))
    b = eng.submit(LONG, session_id="long", sampling=_g(8))
    eng.run_until_idle()
    c = eng.submit(PREFIX + [1, 2], session_id="pfx1", sampling=_g(6))
    eng.run_until_idle()                     # registers the prefix
    d = eng.submit(PREFIX + [4, 5], session_id="pfx2", sampling=_g(6))
    eng.run_until_idle()
    assert eng.offload_session("rep")
    e = eng.submit([1, 2, 3], session_id="rep", sampling=_g(8))
    eng.run_until_idle()
    return [t.new_tokens for t in (a, b, c, d, e)]


def test_spec_identity_full_matrix(build8):
    """The acceptance matrix: greedy streams are token-identical
    spec-on vs spec-off across steps {1,4} x fused/split window x
    prefix-hit x offload-restore — and spec rounds no longer flush
    the dispatch window (the engine keeps running at the configured
    multi-step depth while drafting)."""
    base = _matrix_streams(build8(4, fused=True, spec_tokens=0,
                                  offload=True))
    for steps in (1, 4):
        for fused in (True, False):
            eng = build8(steps, fused=fused, spec_tokens=4,
                         offload=True)
            got = _matrix_streams(eng)
            assert got == base, f"steps={steps} fused={fused}"
            st = eng.stats()
            tag = f"steps={steps} fused={fused}: {st}"
            # drafting engaged and accepted on every cell...
            assert st["spec_rounds"] > 0, tag
            assert st["spec_accepted"] > 0, tag
            # ...without composing the window down to steps=1: the
            # engine still dispatched at the configured depth (the old
            # path flushed the pipeline at every spec-round boundary)
            assert st["steps_per_dispatch"] == steps, tag
            # the matrix legs actually exercised their paths
            assert st["prefix_hits"] >= 1, tag
            assert st["offload_restores"] >= 1, tag
            assert st["prefill_chunks_interleaved"] > 0, tag
            if fused:
                assert st["fused_windows"] > 0, tag


def test_decode_window_fault_mid_spec_round(build8, monkeypatch):
    """Chaos: decode_window armed while speculative windows are in
    flight. Accepted-draft tokens up to the last durable boundary
    (the previous window's drain) survive to the stream; the faulted
    window's turn fails cleanly and releases every KV page."""
    monkeypatch.setenv("ROOM_TPU_PREFIX_CACHE_PAGES", "0")
    eng = build8(4, spec_tokens=4)
    got = []
    turn = eng.submit(REP, sampling=_g(256), on_token=got.append)
    # run windows until accepted drafts are riding the pipeline
    for _ in range(8):
        eng.step()
        if eng.stats()["spec_accepted"] > 0:
            break
    assert eng.stats()["spec_accepted"] > 0, \
        "sanity: drafts accepted before the fault"
    n_before = len(turn.new_tokens)
    faults.inject("decode_window", times=1, transient=False)
    eng.step()       # next dispatch faults; in-flight window drains
    eng.run_until_idle()
    assert turn.finish_reason == "error"
    assert "decode_window" in (turn.error or "")
    # the undrained spec window's tokens (accepted drafts included)
    # were NOT discarded by the fault one window later
    assert len(turn.new_tokens) >= n_before
    assert got == turn.new_tokens
    eng.release_session(turn.session_id)
    assert eng.page_table.free_pages == eng.page_table.n_pages - 1, \
        "KV page leak after mid-spec-round window fault"


def test_spec_off_class_runs_gamma_zero_in_mixed_batch(
        build8, monkeypatch):
    """Per-class spec-off is a LANE decision, not a batch one: an
    acceptance-starved class rides the same window at gamma 0 while
    its batchmates keep drafting — tokens identical to spec-off, the
    starved class stays off, the healthy class keeps its gamma."""
    monkeypatch.setenv("ROOM_TPU_SPEC_MIN_ACCEPT", "0.5")
    # park the starved class well past this test's traffic so a
    # probe round can't re-arm it mid-run
    monkeypatch.setenv("ROOM_TPU_SPEC_COOLDOWN", "100000")
    base = build8(4, spec_tokens=0)
    b1 = base.submit(REP, sampling=_g(24), turn_class="queen",
                     session_id="q")
    b2 = base.submit([3, 2, 1, 3, 2, 1], sampling=_g(24),
                     turn_class="worker", session_id="w")
    base.run_until_idle()

    eng = build8(4, spec_tokens=4)
    # starve the worker class through the tuner's own accounting: a
    # full tune window of rejected proposals drives it spec-off
    assert eng.spec_tuner.observe("worker", 16, 0, 16) == 1
    assert eng.spec_tuner.gamma_for("worker", 0) == 0
    assert eng.spec_tuner.gamma_for("queen", 0) == 4
    g1 = eng.submit(REP, sampling=_g(24), turn_class="queen",
                    session_id="q")
    g2 = eng.submit([3, 2, 1, 3, 2, 1], sampling=_g(24),
                    turn_class="worker", session_id="w")
    eng.run_until_idle()
    assert g1.new_tokens == b1.new_tokens
    assert g2.new_tokens == b2.new_tokens
    st = eng.stats()
    snap = st["spec"]["classes"]
    assert st["spec_rounds"] > 0, "queen lanes kept drafting"
    assert snap["worker"]["off"] is True
    assert snap["queen"]["off"] is False
    assert snap["queen"]["gamma"] == 4
    # the worker's lanes decoded sequentially inside drafting windows
    assert st["spec_rows_sequential"] > 0
    # and its tuner state never gained a proposal (no probe fired)
    assert snap["worker"]["proposed"] == 16


def test_draft_model_tier_proposes_and_stays_identical(build8):
    """Tier-2 drafting (ROOM_TPU_DRAFT_MODEL, docs/serving.md): the
    tiny on-mesh draft decoder proposes where prompt-lookup finds no
    repeating n-gram, behind the SAME in-window verify — so its
    quality is a throughput knob, never a correctness one. Greedy
    streams stay token-identical to spec-off, and the draft tier is
    attributed differentially: both engines emit the same stream, so
    the lookup-only engine's proposal count is exactly the lookup
    share — the draft engine proposing strictly more is the tier-2
    path firing on the lookup-empty steps."""
    from room_tpu.models.config import tiny_draft

    arb = [4, 1, 6, 2, 7, 0, 5, 3]           # no repeating 2-gram
    base = build8(4, spec_tokens=0)
    b = base.submit(arb, sampling=_g(8))
    base.run_until_idle()

    lookup_only = build8(4, spec_tokens=4)
    l = lookup_only.submit(arb, sampling=_g(8))
    lookup_only.run_until_idle()
    assert l.new_tokens == b.new_tokens
    lookup_proposed = lookup_only.stats()["spec_proposed"]

    dcfg = tiny_draft(vocab_size=8)
    dparams = qwen3.init_params(dcfg, jax.random.PRNGKey(11))
    eng = build8(4, spec_tokens=4, draft=(dcfg, dparams))
    g = eng.submit(arb, sampling=_g(8))
    eng.run_until_idle()
    assert g.new_tokens == b.new_tokens
    st = eng.stats()
    assert st["spec_proposed"] > lookup_proposed, \
        "draft tier never proposed on lookup-empty steps"
    assert st["spec"]["draft_model"] == "tiny-draft"


def test_draft_model_vocab_mismatch_raises(model8):
    """A draft whose vocabulary differs from the target's would
    propose token ids the verify gather can't index — refused loudly
    at engine build, not silently at serve time."""
    from room_tpu.models.config import tiny_draft

    cfg, params = model8
    dcfg = tiny_draft(vocab_size=16)
    dparams = qwen3.init_params(dcfg, jax.random.PRNGKey(11))
    with pytest.raises(ValueError, match="vocab"):
        ServingEngine(cfg, params, max_batch=4, page_size=8,
                      n_pages=128, spec_tokens=4,
                      draft=(dcfg, dparams))


def test_resolve_draft_config_unknown_name_raises():
    """ROOM_TPU_DRAFT_MODEL typos fail loudly at host build."""
    from room_tpu.models.config import resolve_draft_config

    with pytest.raises(ValueError, match="unknown draft model"):
        resolve_draft_config("qwen3-drafty", 512)
    cfg = resolve_draft_config("qwen3-draft", 1234)
    assert cfg.vocab_size == 1234
