"""WebSocket hub per-channel subscription suite (docs/swarmshard.md).

Regression for the firehose removal: the hub holds ONE ref-counted
event-bus subscription per channel some client asked for, so an event
on a channel nobody watches never reaches the hub's fan-out handler at
all — with swarm shards emitting every room's traffic onto the global
bus, the old subscribe-everything handler made every hub pay O(events)
for O(subscribed) interest.
"""

import pytest

from room_tpu.core.events import event_bus
from room_tpu.server.ws import WebSocketHub, _Client


class _FakeSock:
    def __init__(self):
        self.sent = []

    def sendall(self, data):
        self.sent.append(data)

    def close(self):
        pass

    def shutdown(self, how):
        pass


@pytest.fixture()
def hub():
    h = WebSocketHub(server=object())
    yield h
    h.stop()


def _attach(hub, channels=()):
    """A connected client the way handle_upgrade + the reader loop
    build one: registered, then per-channel acquire."""
    client = _Client(_FakeSock())
    client.frames = []
    client.send_text = lambda t: client.frames.append(t) or True
    with hub._lock:
        hub._clients.append(client)
    for ch in channels:
        client.channels.add(ch)
        hub._acquire_channel(ch)
    return client


def test_no_subscription_no_bus_handler(hub, monkeypatch):
    """THE regression: before any subscribe the hub holds zero bus
    subscriptions, and an event on an unwatched channel never invokes
    the fan-out handler."""
    calls = []
    monkeypatch.setattr(
        hub, "_fanout",
        lambda ev, ch: calls.append((ev.channel, ch)),
    )
    client = _attach(hub)
    assert hub.subscribed_channels == []
    event_bus.emit("x", "room:1", {})
    event_bus.emit("x", "runtime", {})
    assert calls == []
    assert client.frames == []
    # subscribing arms exactly that channel — other channels still
    # never reach the handler
    client.channels.add("room:1")
    hub._acquire_channel("room:1")
    event_bus.emit("x", "room:1", {})
    event_bus.emit("x", "room:2", {})
    assert calls == [("room:1", "room:1")]


def test_subscribed_channel_delivers_and_unsubscribe_stops(hub):
    client = _attach(hub, ["room:7"])
    event_bus.emit("x", "room:7", {"k": 1})
    assert len(client.frames) == 1
    assert '"channel": "room:7"' in client.frames[0]
    # unsubscribe (the reader-loop path): bus subscription released
    client.channels.discard("room:7")
    hub._release_channel("room:7")
    assert hub.subscribed_channels == []
    event_bus.emit("x", "room:7", {"k": 2})
    assert len(client.frames) == 1


def test_wildcard_subscription_and_exact_dedup(hub):
    """A client on both "*" and an exact channel sees each event
    exactly once."""
    client = _attach(hub, ["*", "room:3"])
    event_bus.emit("x", "room:3", {})
    event_bus.emit("x", "room:9", {})
    assert len(client.frames) == 2
    channels = [f for f in client.frames]
    assert sum('"room:3"' in f for f in channels) == 1
    assert sum('"room:9"' in f for f in channels) == 1


def test_channel_refcount_across_clients(hub):
    a = _attach(hub, ["room:5"])
    b = _attach(hub, ["room:5"])
    assert hub.subscribed_channels == ["room:5"]
    hub._drop_client(a)
    # still subscribed for b
    assert hub.subscribed_channels == ["room:5"]
    event_bus.emit("x", "room:5", {})
    assert len(b.frames) == 1 and a.frames == []
    hub._drop_client(b)
    assert hub.subscribed_channels == []
    # double-drop is a no-op
    hub._drop_client(b)


def test_dead_client_releases_its_channels(hub):
    """A send failure (slow consumer) drops the client and releases
    its subscriptions."""
    client = _attach(hub, ["room:2"])
    client.send_text = lambda t: False   # writer queue full / dead
    event_bus.emit("x", "room:2", {})
    assert hub.client_count == 0
    assert hub.subscribed_channels == []


def test_stop_releases_everything(hub):
    _attach(hub, ["room:1", "*"])
    hub.stop()
    assert hub.subscribed_channels == []
    assert hub.client_count == 0
