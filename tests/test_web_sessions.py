"""Persistent web sessions: cookie continuity, outline snapshots, link
clicking, form submit, history, registry eviction, and the web_browse
tool dispatch — against a local stub site (reference behaviors:
src/shared/web-tools.ts persistent browser sessions)."""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import pytest

from room_tpu.core.queen_tools import execute_queen_tool
from room_tpu.core.web_tools import (
    get_web_session, open_web_session, reset_web_sessions,
)

PAGES = {
    "/": """
      <html><head><title>Stub Site</title></head><body>
      <h1>Welcome</h1>
      <a href="/about">About us</a>
      <a href="/login">Log in</a>
      <h2>News</h2>
      <p>Nothing happened today.</p>
      <script>ignored()</script>
      </body></html>""",
    "/about": """
      <html><head><title>About</title></head><body>
      <h1>About</h1><p>We are a stub.</p>
      <a href="/">Home</a>
      </body></html>""",
    "/login": """
      <html><head><title>Login</title></head><body>
      <form action="/do-login" method="post">
        <input type="hidden" name="csrf" value="tok123">
        <input type="text" name="user" placeholder="username">
        <input type="password" name="pass">
        <button type="submit">Sign in</button>
      </form>
      <form action="/search" method="get">
        <input type="text" name="q">
      </form>
      </body></html>""",
    # SPA shell: script-heavy, no static content — what a React/Vue
    # bundle page looks like to a non-JS client
    "/app": ("""
      <html><head><title>App</title>
      <script src="/static/runtime.js"></script>
      <script src="/static/vendors.js"></script>
      <script src="/static/main.js"></script>
      </head><body><div id="root"></div>
      <script>window.__BOOT__ = {};</script>
      </body></html>""" + "<!-- bundle padding -->" * 128),
    "/noscript": """
      <html><head><title>NS</title></head><body>
      <noscript>Please enable JavaScript to use this site.</noscript>
      <div id="app"></div>
      </body></html>""",
}


class _Site(BaseHTTPRequestHandler):
    def log_message(self, *a):
        pass

    def _send(self, body: str, cookie: str | None = None):
        data = body.encode()
        self.send_response(200)
        self.send_header("Content-Type", "text/html")
        self.send_header("Content-Length", str(len(data)))
        if cookie:
            self.send_header("Set-Cookie", cookie)
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):
        parsed = urlparse(self.path)
        if parsed.path == "/jump":
            self.send_response(302)
            self.send_header("Location", "/about")
            self.send_header("Content-Length", "0")
            self.end_headers()
        elif parsed.path == "/data.json":
            data = b'{"answer": 42}'
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
        elif parsed.path == "/search":
            q = parse_qs(parsed.query).get("q", [""])[0]
            self._send(f"<html><body><h1>Results for {q}</h1>"
                       "</body></html>")
        elif parsed.path == "/private":
            cookies = self.headers.get("Cookie", "")
            if "auth=yes" in cookies:
                self._send("<html><body><h1>Secret page</h1>"
                           "</body></html>")
            else:
                self._send("<html><body><h1>Please log in</h1>"
                           "</body></html>")
        elif parsed.path in PAGES:
            self._send(PAGES[parsed.path])
        else:
            self.send_response(404)
            self.send_header("Content-Length", "0")
            self.end_headers()

    def do_POST(self):
        length = int(self.headers.get("Content-Length") or 0)
        body = parse_qs(self.rfile.read(length).decode())
        if self.path == "/do-login":
            assert body.get("csrf") == ["tok123"]  # hidden field kept
            user = body.get("user", [""])[0]
            self._send(
                f"<html><body><h1>Hello {user}</h1>"
                '<a href="/private">private area</a></body></html>',
                cookie="auth=yes",
            )
        else:
            self._send("<html><body>posted</body></html>")


@pytest.fixture(scope="module")
def site():
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _Site)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()
    srv.server_close()


@pytest.fixture(autouse=True)
def clean_sessions():
    reset_web_sessions()
    yield
    reset_web_sessions()


def test_snapshot_outline_links_forms(site):
    sess = open_web_session()
    snap = sess.goto(site + "/")
    assert snap["title"] == "Stub Site"
    assert "# Welcome" in snap["outline"]
    assert "## News" in snap["outline"]
    assert [l["text"] for l in snap["links"]] == ["About us", "Log in"]

    snap = sess.goto(site + "/login")
    assert snap["forms"][0]["method"] == "post"
    names = [f["name"] for f in snap["forms"][0]["fields"]]
    assert names == ["user", "pass"]  # hidden csrf not shown
    assert snap["buttons"] == ["Sign in"]


def test_click_and_back(site):
    sess = open_web_session()
    sess.goto(site + "/")
    snap = sess.click(0)
    assert snap["title"] == "About"
    assert sess.url.endswith("/about")
    snap = sess.back()
    assert snap["title"] == "Stub Site"
    out = sess.click(99)
    assert "out of range" in out["error"]


def test_form_login_sets_cookie_and_persists(site):
    """The whole point of sessions: the login cookie carries into the
    next navigation."""
    sess = open_web_session()
    sess.goto(site + "/login")
    snap = sess.submit_form(0, {"user": "keeper", "pass": "pw"})
    assert "# Hello keeper" in snap["outline"]
    snap = sess.goto(site + "/private")
    assert "# Secret page" in snap["outline"]
    # a FRESH session has no cookie
    other = open_web_session()
    snap = other.goto(site + "/private")
    assert "# Please log in" in snap["outline"]


def test_get_form_builds_query(site):
    sess = open_web_session()
    sess.goto(site + "/login")
    snap = sess.submit_form(1, {"q": "tpu kernels"})
    assert "# Results for tpu kernels" in snap["outline"]


def test_text_find(site):
    sess = open_web_session()
    sess.goto(site + "/")
    assert "Nothing happened" in sess.text()
    assert "Nothing happened today." == sess.text(find="nothing")
    assert "not found" in sess.text(find="absent-string")


def test_registry_eviction():
    from room_tpu.core import web_tools

    sessions = [open_web_session() for _ in range(web_tools.MAX_SESSIONS)]
    sessions[0].last_used -= 10  # oldest
    extra = open_web_session()
    assert get_web_session(sessions[0].id) is None  # evicted
    assert get_web_session(extra.id) is extra


def test_web_browse_tool_dispatch(site):
    out = json.loads(execute_queen_tool(
        None, None, None, "web_browse",
        {"action": "open", "url": site + "/"},
    ))
    sid = out["session_id"]
    assert out["title"] == "Stub Site"
    out = json.loads(execute_queen_tool(
        None, None, None, "web_browse",
        {"action": "click", "session_id": sid, "index": 0},
    ))
    assert out["title"] == "About"
    text = execute_queen_tool(
        None, None, None, "web_browse",
        {"action": "text", "session_id": sid},
    )
    assert "We are a stub." in text
    assert execute_queen_tool(
        None, None, None, "web_browse",
        {"action": "close", "session_id": sid},
    ) == "session closed"
    assert "unknown web session" in execute_queen_tool(
        None, None, None, "web_browse",
        {"action": "click", "session_id": sid, "index": 0},
    )


def test_redirect_followed_and_recorded(site):
    """302 is followed transparently; the session records the FINAL
    url (what the agent acts on next)."""
    s = open_web_session()
    out = s.goto(site + "/jump")
    assert "error" not in out
    assert s.url.endswith("/about")
    assert "About" in s.text()


def test_goto_rejects_non_http_schemes():
    s = open_web_session()
    for bad in ("file:///etc/passwd", "ftp://x", "javascript:alert(1)"):
        out = s.goto(bad)
        assert "error" in out, bad


def test_404_is_reported_not_raised(site):
    s = open_web_session()
    out = s.goto(site + "/definitely-missing")
    assert "error" in out and "404" in out["error"]


def test_non_html_body_served_as_text(site):
    s = open_web_session()
    out = s.goto(site + "/data.json")
    assert "error" not in out
    # non-HTML gets the plain {url, text} snapshot, not an outline
    assert set(out) == {"url", "text"}
    assert s.text().strip().startswith("{")


def test_click_before_any_page_errors(site):
    s = open_web_session()
    assert "error" in s.click(0)          # nothing loaded yet


def test_back_without_history_errors(site):
    s = open_web_session()
    s.goto(site + "/")
    assert "error" in s.back()


def test_close_session_removes_it():
    from room_tpu.core.web_tools import close_web_session

    s = open_web_session()
    assert get_web_session(s.id) is s
    assert close_web_session(s.id) is True
    assert get_web_session(s.id) is None
    assert close_web_session(s.id) is False


def test_js_rendered_spa_shell_flagged(site):
    """A script-heavy page with no static text must carry an explicit
    js_rendered signal (VERDICT r4 #7) instead of a silently empty
    outline."""
    s = open_web_session()
    out = s.goto(site + "/app")
    assert out.get("js_rendered") is True
    assert "JS-rendered" in out["warning"]
    # navigating to a real content page clears the flag
    out2 = s.goto(site + "/about")
    assert "js_rendered" not in out2


def test_noscript_plea_flagged(site):
    s = open_web_session()
    out = s.goto(site + "/noscript")
    assert out.get("js_rendered") is True


def test_content_pages_not_flagged(site):
    s = open_web_session()
    for path in ("/", "/about", "/login"):
        out = s.goto(site + path)
        assert "js_rendered" not in out, path


def test_web_fetch_marks_js_rendered(site):
    from room_tpu.core.web_tools import web_fetch

    body = web_fetch(site + "/app")
    assert body.startswith("[page appears to be JS-rendered")
    body2 = web_fetch(site + "/about")
    assert "JS-rendered" not in body2


def test_detect_js_rendered_unit():
    from room_tpu.core.web_tools import detect_js_rendered

    spa = ("<html><head><script src=a.js></script>"
           "<script src=b.js></script><script>boot()</script></head>"
           "<body><div id=root></div></body></html>" + "<!-- -->" * 400)
    assert detect_js_rendered(spa, "")
    # long static text wins even with many scripts
    assert not detect_js_rendered(spa, "real words " * 50)
    # noscript plea with thin text
    assert detect_js_rendered(
        "<noscript>please enable JavaScript</noscript>", "")
    # small plain page: not flagged
    assert not detect_js_rendered("<html><body>hi</body></html>", "hi")
