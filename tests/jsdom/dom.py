"""Minimal DOM shim for the panels render harness (VERDICT r4 #3).

Elements are JSObjects (so `el.innerHTML = ...` rides the
interpreter's normal member assignment); the document keeps an id
registry so `$("x")` resolves, auto-creating stubs for ids that the
panels themselves create via innerHTML (the harness asserts on the
HTML strings, it does not build a layout tree).
"""

from __future__ import annotations

from tests.jsdom.mini_js import JSObject, UNDEFINED, to_js_string


class Element(JSObject):
    def __init__(self, tag: str = "div", elt_id: str = ""):
        super().__init__()
        self["tagName"] = tag.upper()
        self["id"] = elt_id
        self["innerHTML"] = ""
        self["textContent"] = ""
        self["value"] = ""
        self["checked"] = False
        self["style"] = JSObject({"cssText": "", "display": ""})
        self["dataset"] = JSObject()
        classes: set = set()
        self["classList"] = JSObject({
            "add": lambda *cs: [classes.add(to_js_string(c))
                                for c in cs] and None,
            "remove": lambda *cs: [classes.discard(to_js_string(c))
                                   for c in cs] and None,
            "contains": lambda c="": to_js_string(c) in classes,
            "toggle": lambda c, force=UNDEFINED: _toggle(
                classes, to_js_string(c), force),
        })
        self["remove"] = lambda: None
        self["focus"] = lambda: None
        self["appendChild"] = lambda child: child
        self["addEventListener"] = lambda *a: None
        self["querySelector"] = lambda sel="": None
        self["querySelectorAll"] = lambda sel="": []
        self["getContext"] = lambda *a: None


def _toggle(classes, c, force):
    if force is not UNDEFINED:
        (classes.add if force else classes.discard)(c)
        return bool(force)
    if c in classes:
        classes.discard(c)
        return False
    classes.add(c)
    return True


class Document(JSObject):
    def __init__(self):
        super().__init__()
        self._by_id: dict[str, Element] = {}
        self["body"] = Element("body")
        self["createElement"] = self.create_element
        self["getElementById"] = self.get_element_by_id

    def create_element(self, tag="div"):
        return Element(to_js_string(tag))

    def get_element_by_id(self, elt_id=""):
        """Auto-create: panels write ids via innerHTML then $() them;
        the harness asserts on HTML strings, so a fresh stub is the
        right answer for any id."""
        key = to_js_string(elt_id)
        if key not in self._by_id:
            self._by_id[key] = Element("div", key)
        return self._by_id[key]
