"""Panel render harness: executes ui/panels.js with native stand-ins
for the app.js helper surface ($, esc, when, api, toast, dialogs, ws
plumbing) and a pluggable payload source, so tests render every panel
against REAL route payloads and assert on the produced HTML
(VERDICT r4 #3).
"""

from __future__ import annotations

import os

from tests.jsdom.dom import Document, Element
from tests.jsdom.mini_js import (
    UNDEFINED,
    JSInterpreter,
    JSObject,
    js_to_py,
    py_to_js,
    to_js_string,
)

PANELS_JS = os.path.join(os.path.dirname(__file__), "..", "..",
                         "ui", "panels.js")


def _esc(v=UNDEFINED, *rest):
    s = "" if v is None or v is UNDEFINED else to_js_string(v)
    return (s.replace("&", "&amp;").replace("<", "&lt;")
            .replace(">", "&gt;").replace('"', "&quot;"))


def _when(ts=UNDEFINED, *rest):
    if ts is None or ts is UNDEFINED or ts == 0 or ts == "":
        return ""
    if isinstance(ts, (int, float)):
        import datetime

        return datetime.datetime.fromtimestamp(
            float(ts), datetime.timezone.utc
        ).strftime("%Y-%m-%d %H:%M:%S")
    return to_js_string(ts)


class PanelHarness:
    """api_fn(method: str, path: str, body: dict|None) -> dict —
    typically backed by a live test server so field drift between
    routes and panels is caught, not fixtured away."""

    def __init__(self, api_fn, confirm_answer=True,
                 prompt_answer="harness-input"):
        self.api_fn = api_fn
        self.api_calls: list[tuple] = []
        self.toasts: list[str] = []
        self.subscriptions: list[str] = []
        self.timeouts: list = []       # recorded, never fired
        self.confirm_answer = confirm_answer
        self.prompt_answer = prompt_answer

        self.interp = JSInterpreter()
        self.document = Document()
        g = self.interp.set_global
        g("document", self.document)
        g("$", self.document.get_element_by_id)
        g("esc", _esc)
        g("when", _when)
        g("api", self._api)
        g("toast", lambda text=UNDEFINED, *r: self.toasts.append(
            to_js_string(text)))
        g("subscribe", lambda ch=UNDEFINED, *r:
          self.subscriptions.append(to_js_string(ch)))
        g("unsubscribe", lambda ch=UNDEFINED, *r: None)
        g("wsHandlers", JSObject())
        g("wsLog", [])
        g("currentView", "swarm")
        g("selectedRoom", None)
        g("confirmDialog", lambda text=UNDEFINED, ok=UNDEFINED, *r:
          self.confirm_answer)
        g("promptDialog", lambda text=UNDEFINED, ph=UNDEFINED, *r:
          self.prompt_answer)
        g("refreshView", lambda *r: UNDEFINED)
        g("showView", lambda *r: UNDEFINED)
        g("setTimeout", self._set_timeout)
        g("clearTimeout", lambda *r: None)
        g("setInterval", self._set_timeout)
        g("clearInterval", lambda *r: None)
        g("TOKEN", "harness-token")

        with open(PANELS_JS) as f:
            self.interp.run(f.read())

    # -- shims --

    def _api(self, method=UNDEFINED, path=UNDEFINED, body=UNDEFINED):
        m = to_js_string(method)
        p = to_js_string(path)
        b = js_to_py(body) if body is not UNDEFINED else None
        self.api_calls.append((m, p, b))
        return py_to_js(self.api_fn(m, p, b))

    def _set_timeout(self, fn=UNDEFINED, delay=0, *rest):
        # recorded but never run: poll loops must not spin the harness
        self.timeouts.append((fn, delay))
        return len(self.timeouts)

    # -- drive --

    def panels(self) -> dict:
        return self.interp.get_global("PANELS")

    def panel_keys(self) -> list[str]:
        return list(self.panels().keys())

    def render(self, key: str) -> str:
        """Run PANELS[key].render(el); return the element's HTML."""
        panel = self.panels().get_prop(key)
        if panel is UNDEFINED:
            raise KeyError(f"no panel {key!r}")
        el = Element("div", f"view-{key}")
        self.document._by_id[f"view-{key}"] = el
        self.interp.call(panel.get_prop("render"), el)
        return to_js_string(el.get_prop("innerHTML"))

    def call_global(self, name: str, *args):
        return self.interp.call(self.interp.get_global(name), *args)

    def element_html(self, elt_id: str) -> str:
        return to_js_string(
            self.document.get_element_by_id(elt_id)
            .get_prop("innerHTML"))

    def ws_dispatch(self, msg: dict):
        """Deliver one WS message to every registered handler (the
        app.js onmessage loop)."""
        handlers = self.interp.get_global("wsHandlers")
        for fn in list(handlers.values()):
            self.interp.call(fn, py_to_js(msg))
