"""A small tree-walking interpreter for the JS subset used by
ui/panels.js (VERDICT r4 #3: execute the dashboard's render functions
in CI with no JS engine in the image).

Supported: const/let/var with object/array destructuring (+defaults,
rest), function declarations/expressions/arrows (async treated as
synchronous — the harness's fetch substitute resolves immediately),
template literals (nested), regex literals, spread in
calls/arrays/objects, optional chaining, nullish coalescing, ternary,
for / for-of / while, try/catch(/finally) with optional binding,
throw, JS truthiness and string coercion, === semantics, undefined vs
null, and the built-ins the dashboard uses (Object, Math, JSON,
Date.now, parseInt/Float, encodeURIComponent, Array/String methods).

Deliberately NOT a general engine: no classes, generators, labels,
getters, prototypes, `new`, or event loop — panels.js uses none of
them, and the full-panel render sweep in tests/test_ui_render.py keeps
it inside this subset (a construct the interpreter lacks fails the
sweep with a SyntaxError at parse time).
"""

from __future__ import annotations

import functools
import json
import math
import re
import urllib.parse

# ---------------------------------------------------------------- values


class _Undefined:
    _inst = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "undefined"

    def __bool__(self):
        return False


UNDEFINED = _Undefined()


class JSObject(dict):
    """A JS object: plain dict with attribute-ish get returning
    UNDEFINED for missing keys."""

    def get_prop(self, name):
        return self[name] if name in self else UNDEFINED


class JSRegex:
    def __init__(self, pattern: str, flags: str):
        f = 0
        if "i" in flags:
            f |= re.IGNORECASE
        if "m" in flags:
            f |= re.MULTILINE
        if "s" in flags:
            f |= re.DOTALL
        self.global_ = "g" in flags
        self.re = re.compile(_js_regex_to_py(pattern), f)

    def exec(self, s):
        m = self.re.search(to_js_string(s))
        if not m:
            return None
        out = [m.group(0)] + [
            g if g is not None else UNDEFINED for g in m.groups()
        ]
        return out

    def test(self, s):
        return self.re.search(to_js_string(s)) is not None


def _js_regex_to_py(pat: str) -> str:
    # \d \w etc. are shared; JS's `\/` escape is meaningless to Python
    return pat.replace(r"\/", "/")


class JSFunction:
    def __init__(self, name, params, body, env, interp,
                 is_expr_body=False):
        self.name = name or "<anonymous>"
        self.params = params          # list of patterns
        self.body = body
        self.env = env
        self.interp = interp
        self.is_expr_body = is_expr_body

    def call(self, this, args):
        env = Env(parent=self.env)
        self.interp.bind_params(env, self.params, args)
        env.declare("this", this if this is not None else UNDEFINED)
        if self.is_expr_body:
            return self.interp.eval_expr(self.body, env)
        try:
            self.interp.exec_block(self.body, env)
        except _Return as r:
            return r.value
        return UNDEFINED


class _Return(Exception):
    def __init__(self, value):
        self.value = value


class _Break(Exception):
    pass


class _Continue(Exception):
    pass


class JSThrow(Exception):
    def __init__(self, value):
        self.value = value
        super().__init__(to_js_string(
            value.get_prop("message") if isinstance(value, JSObject)
            else value
        ))


# ------------------------------------------------------------- coercions


def truthy(v) -> bool:
    if v is UNDEFINED or v is None or v is False:
        return False
    if v is True:
        return True
    if isinstance(v, (int, float)):
        return v != 0 and not (isinstance(v, float) and math.isnan(v))
    if isinstance(v, str):
        return len(v) > 0
    return True  # objects, arrays, functions


def to_js_string(v) -> str:
    if v is UNDEFINED:
        return "undefined"
    if v is None:
        return "null"
    if v is True:
        return "true"
    if v is False:
        return "false"
    if isinstance(v, float):
        if math.isnan(v):
            return "NaN"
        if v == math.inf:
            return "Infinity"
        if v == -math.inf:
            return "-Infinity"
        if v.is_integer() and abs(v) < 1e21:
            return str(int(v))
        return repr(v)
    if isinstance(v, int):
        return str(v)
    if isinstance(v, str):
        return v
    if isinstance(v, list):
        return ",".join(
            "" if x is None or x is UNDEFINED else to_js_string(x)
            for x in v
        )
    if isinstance(v, JSObject):
        return "[object Object]"
    if isinstance(v, (JSFunction,)) or callable(v):
        return f"function {getattr(v, 'name', '')}() {{ ... }}"
    return str(v)


def to_number(v):
    if v is True:
        return 1
    if v is False or v is None:
        return 0
    if v is UNDEFINED:
        return math.nan
    if isinstance(v, (int, float)):
        return v
    if isinstance(v, str):
        s = v.strip()
        if not s:
            return 0
        try:
            return int(s)
        except ValueError:
            try:
                return float(s)
            except ValueError:
                return math.nan
    return math.nan


def js_equals_strict(a, b) -> bool:
    if a is UNDEFINED or b is UNDEFINED:
        return a is b
    if a is None or b is None:
        return a is b
    if isinstance(a, bool) or isinstance(b, bool):
        return a is b
    if isinstance(a, (int, float)) and isinstance(b, (int, float)):
        return float(a) == float(b)
    if isinstance(a, str) and isinstance(b, str):
        return a == b
    return a is b


def js_equals_loose(a, b) -> bool:
    nullish_a = a is None or a is UNDEFINED
    nullish_b = b is None or b is UNDEFINED
    if nullish_a or nullish_b:
        return nullish_a and nullish_b
    if isinstance(a, str) and isinstance(b, (int, float)) or \
            isinstance(b, str) and isinstance(a, (int, float)):
        return to_number(a) == to_number(b)
    return js_equals_strict(a, b)


# ------------------------------------------------------------- tokenizer

KEYWORDS = {
    "const", "let", "var", "function", "return", "if", "else", "for",
    "while", "of", "in", "break", "continue", "try", "catch",
    "finally", "throw", "true", "false", "null", "undefined", "async",
    "await", "typeof", "delete", "new", "this", "do",
}

PUNCT3 = ("===", "!==", "**=", "...", "??=", "&&=", "||=")
PUNCT2 = ("=>", "==", "!=", "<=", ">=", "&&", "||", "??", "?.", "+=",
          "-=", "*=", "/=", "%=", "++", "--", "**")


class Token:
    __slots__ = ("type", "value", "pos")

    def __init__(self, type_, value, pos):
        self.type = type_
        self.value = value
        self.pos = pos

    def __repr__(self):
        return f"{self.type}:{self.value!r}"


class Tokenizer:
    def __init__(self, src: str):
        self.src = src
        self.i = 0
        self.n = len(src)
        self.tokens: list[Token] = []

    def error(self, msg):
        line = self.src.count("\n", 0, self.i) + 1
        raise SyntaxError(f"js tokenize: {msg} at line {line}")

    def run(self) -> list[Token]:
        while self.i < self.n:
            c = self.src[self.i]
            if c in " \t\r\n":
                self.i += 1
            elif self.src.startswith("//", self.i):
                j = self.src.find("\n", self.i)
                self.i = self.n if j < 0 else j
            elif self.src.startswith("/*", self.i):
                j = self.src.find("*/", self.i + 2)
                if j < 0:
                    self.error("unterminated block comment")
                self.i = j + 2
            elif c in "'\"":
                self.tokens.append(self.read_string(c))
            elif c == "`":
                self.tokens.append(self.read_template())
            elif c.isdigit() or (c == "." and self.i + 1 < self.n
                                 and self.src[self.i + 1].isdigit()):
                self.tokens.append(self.read_number())
            elif c.isalpha() or c in "_$":
                self.tokens.append(self.read_ident())
            elif c == "/" and self.regex_allowed():
                self.tokens.append(self.read_regex())
            else:
                self.tokens.append(self.read_punct())
        self.tokens.append(Token("eof", None, self.i))
        return self.tokens

    def regex_allowed(self) -> bool:
        """A `/` starts a regex unless the previous token can end an
        expression."""
        for t in reversed(self.tokens):
            if t.type in ("num", "str", "tmpl", "regex"):
                return False
            if t.type == "ident":
                return t.value in KEYWORDS and t.value not in (
                    "this", "true", "false", "null", "undefined",
                )
            if t.type == "punct":
                return t.value not in (")", "]", "}")
            return True
        return True

    def read_string(self, quote) -> Token:
        start = self.i
        self.i += 1
        out = []
        while self.i < self.n:
            c = self.src[self.i]
            if c == "\\":
                out.append(self.read_escape())
            elif c == quote:
                self.i += 1
                return Token("str", "".join(out), start)
            elif c == "\n":
                self.error("newline in string")
            else:
                out.append(c)
                self.i += 1
        self.error("unterminated string")

    def read_escape(self) -> str:
        self.i += 1  # backslash
        c = self.src[self.i]
        self.i += 1
        table = {"n": "\n", "t": "\t", "r": "\r", "b": "\b", "f": "\f",
                 "v": "\v", "0": "\0"}
        if c == "u":
            if self.src[self.i] == "{":
                j = self.src.index("}", self.i)
                code = int(self.src[self.i + 1:j], 16)
                self.i = j + 1
            else:
                code = int(self.src[self.i:self.i + 4], 16)
                self.i += 4
            return chr(code)
        if c == "x":
            code = int(self.src[self.i:self.i + 2], 16)
            self.i += 2
            return chr(code)
        if c == "\n":
            return ""
        return table.get(c, c)

    def read_template(self) -> Token:
        """Parts: ("str", text) | ("expr", [tokens])."""
        start = self.i
        self.i += 1
        parts = []
        buf = []
        while True:
            if self.i >= self.n:
                self.error("unterminated template literal")
            c = self.src[self.i]
            if c == "\\":
                buf.append(self.read_escape())
            elif c == "`":
                self.i += 1
                if buf:
                    parts.append(("str", "".join(buf)))
                return Token("tmpl", parts, start)
            elif self.src.startswith("${", self.i):
                if buf:
                    parts.append(("str", "".join(buf)))
                    buf = []
                j = self.find_matching_brace(self.i + 2)
                inner = self.src[self.i + 2:j]
                parts.append(("expr", Tokenizer(inner).run()))
                self.i = j + 1
            else:
                buf.append(c)
                self.i += 1

    def find_matching_brace(self, start: int) -> int:
        """Index of the `}` closing the `${` whose body starts at
        `start`, skipping strings / templates / comments."""
        depth = 1
        i = start
        while i < self.n:
            c = self.src[i]
            if c in "'\"":
                i = self.skip_string(i, c)
            elif c == "`":
                i = self.skip_template(i)
            elif self.src.startswith("//", i):
                j = self.src.find("\n", i)
                i = self.n if j < 0 else j
            elif self.src.startswith("/*", i):
                i = self.src.index("*/", i) + 2
            elif c == "{":
                depth += 1
                i += 1
            elif c == "}":
                depth -= 1
                if depth == 0:
                    return i
                i += 1
            else:
                i += 1
        self.error("unterminated ${...}")

    def skip_string(self, i: int, quote: str) -> int:
        i += 1
        while i < self.n:
            if self.src[i] == "\\":
                i += 2
            elif self.src[i] == quote:
                return i + 1
            else:
                i += 1
        self.error("unterminated string")

    def skip_template(self, i: int) -> int:
        i += 1
        while i < self.n:
            c = self.src[i]
            if c == "\\":
                i += 2
            elif c == "`":
                return i + 1
            elif self.src.startswith("${", i):
                i = self.find_matching_brace(i + 2) + 1
            else:
                i += 1
        self.error("unterminated template")

    def read_number(self) -> Token:
        start = self.i
        if self.src.startswith(("0x", "0X"), self.i):
            self.i += 2
            while self.i < self.n and self.src[self.i] in \
                    "0123456789abcdefABCDEF_":
                self.i += 1
            return Token(
                "num",
                int(self.src[start + 2:self.i].replace("_", ""), 16),
                start,
            )
        seen_dot = seen_e = False
        while self.i < self.n:
            c = self.src[self.i]
            if c.isdigit() or c == "_":
                self.i += 1
            elif c == "." and not seen_dot and not seen_e:
                seen_dot = True
                self.i += 1
            elif c in "eE" and not seen_e:
                seen_e = True
                self.i += 1
                if self.i < self.n and self.src[self.i] in "+-":
                    self.i += 1
            else:
                break
        text = self.src[start:self.i].replace("_", "")
        value = float(text) if (seen_dot or seen_e) else int(text)
        return Token("num", value, start)

    def read_ident(self) -> Token:
        start = self.i
        while self.i < self.n and (self.src[self.i].isalnum()
                                   or self.src[self.i] in "_$"):
            self.i += 1
        return Token("ident", self.src[start:self.i], start)

    def read_regex(self) -> Token:
        start = self.i
        self.i += 1
        in_class = False
        pat = []
        while self.i < self.n:
            c = self.src[self.i]
            if c == "\\":
                pat.append(self.src[self.i:self.i + 2])
                self.i += 2
            elif c == "[":
                in_class = True
                pat.append(c)
                self.i += 1
            elif c == "]":
                in_class = False
                pat.append(c)
                self.i += 1
            elif c == "/" and not in_class:
                self.i += 1
                fstart = self.i
                while self.i < self.n and self.src[self.i].isalpha():
                    self.i += 1
                return Token(
                    "regex",
                    ("".join(pat), self.src[fstart:self.i]),
                    start,
                )
            elif c == "\n":
                self.error("newline in regex")
            else:
                pat.append(c)
                self.i += 1
        self.error("unterminated regex")

    def read_punct(self) -> Token:
        for group in (PUNCT3, PUNCT2):
            for p in group:
                if self.src.startswith(p, self.i):
                    t = Token("punct", p, self.i)
                    self.i += len(p)
                    return t
        t = Token("punct", self.src[self.i], self.i)
        self.i += 1
        return t


# ---------------------------------------------------------------- parser


class Parser:
    def __init__(self, tokens: list[Token], src: str = ""):
        self.toks = tokens
        self.i = 0
        self.src = src
        self._newlines = [i for i, c in enumerate(src) if c == "\n"]

    def _line(self, pos: int) -> int:
        import bisect

        return bisect.bisect_right(self._newlines, pos)

    # -- token helpers --

    def peek(self, k=0) -> Token:
        return self.toks[min(self.i + k, len(self.toks) - 1)]

    def next(self) -> Token:
        t = self.toks[self.i]
        if t.type != "eof":
            self.i += 1
        return t

    def at_punct(self, *vals) -> bool:
        t = self.peek()
        return t.type == "punct" and t.value in vals

    def at_kw(self, *vals) -> bool:
        t = self.peek()
        return t.type == "ident" and t.value in vals

    def expect(self, value):
        t = self.next()
        ok = (t.type == "punct" and t.value == value) or \
             (t.type == "ident" and t.value == value)
        if not ok:
            self.error(f"expected {value!r}, got {t!r}")
        return t

    def error(self, msg):
        t = self.peek()
        line = self.src.count("\n", 0, t.pos) + 1 if self.src else "?"
        raise SyntaxError(f"js parse: {msg} (line {line})")

    def eat_semi(self):
        if self.at_punct(";"):
            self.next()

    # -- program / statements --

    def parse_program(self) -> list:
        stmts = []
        while self.peek().type != "eof":
            stmts.append(self.parse_statement())
        return stmts

    def parse_statement(self):
        t = self.peek()
        if t.type == "punct" and t.value == "{":
            return ("block", self.parse_block())
        if t.type == "punct" and t.value == ";":
            self.next()
            return ("empty",)
        if t.type == "str" and self.peek(1).type == "punct" and \
                self.peek(1).value == ";":
            self.next()  # "use strict" etc.
            self.next()
            return ("empty",)
        if t.type != "ident":
            e = self.parse_expression()
            self.eat_semi()
            return ("expr", e)
        kw = t.value
        if kw in ("const", "let", "var"):
            return self.parse_var()
        if kw == "function" or (kw == "async"
                                and self.peek(1).type == "ident"
                                and self.peek(1).value == "function"):
            return self.parse_function_decl()
        if kw == "if":
            return self.parse_if()
        if kw == "for":
            return self.parse_for()
        if kw == "while":
            return self.parse_while()
        if kw == "return":
            self.next()
            if self.at_punct(";") or self.at_punct("}"):
                self.eat_semi()
                return ("ret", ("undef",))
            e = self.parse_expression()
            self.eat_semi()
            return ("ret", e)
        if kw == "break":
            self.next()
            self.eat_semi()
            return ("brk",)
        if kw == "continue":
            self.next()
            self.eat_semi()
            return ("cont",)
        if kw == "throw":
            self.next()
            e = self.parse_expression()
            self.eat_semi()
            return ("throw", e)
        if kw == "try":
            return self.parse_try()
        e = self.parse_expression()
        self.eat_semi()
        return ("expr", e)

    def parse_block(self) -> list:
        self.expect("{")
        out = []
        while not self.at_punct("}"):
            out.append(self.parse_statement())
        self.expect("}")
        return out

    def parse_var(self):
        kind = self.next().value
        decls = []
        while True:
            pattern = self.parse_binding_pattern()
            init = ("undef",)
            if self.at_punct("="):
                self.next()
                init = self.parse_assignment()
            decls.append((pattern, init))
            if self.at_punct(","):
                self.next()
                continue
            break
        self.eat_semi()
        return ("var", kind, decls)

    def parse_binding_pattern(self):
        if self.at_punct("{"):
            self.next()
            props = []
            rest = None
            while not self.at_punct("}"):
                if self.at_punct("..."):
                    self.next()
                    rest = self.next().value
                else:
                    key = self.next().value
                    sub = ("pid", key, None)
                    if self.at_punct(":"):
                        self.next()
                        sub = self.parse_binding_pattern()
                    if self.at_punct("="):
                        self.next()
                        default = self.parse_assignment()
                        if sub[0] == "pid":
                            sub = ("pid", sub[1], default)
                        else:
                            sub = ("pdefault", sub, default)
                    props.append((key, sub))
                if self.at_punct(","):
                    self.next()
            self.expect("}")
            return ("pobj", props, rest)
        if self.at_punct("["):
            self.next()
            elts = []
            while not self.at_punct("]"):
                if self.at_punct(","):
                    elts.append(None)
                    self.next()
                    continue
                sub = self.parse_binding_pattern()
                if self.at_punct("="):
                    self.next()
                    sub = ("pdefault", sub, self.parse_assignment())
                elts.append(sub)
                if self.at_punct(","):
                    self.next()
            self.expect("]")
            return ("parr", elts)
        name = self.next()
        if name.type != "ident":
            self.error(f"bad binding target {name!r}")
        return ("pid", name.value, None)

    def parse_function_decl(self):
        is_async = False
        if self.at_kw("async"):
            self.next()
            is_async = True
        self.expect("function")
        name = self.next().value
        params = self.parse_params()
        body = self.parse_block()
        return ("fndecl", name, params, body, is_async)

    def parse_params(self) -> list:
        self.expect("(")
        params = []
        while not self.at_punct(")"):
            if self.at_punct("..."):
                self.next()
                params.append(("prest", self.next().value))
            else:
                p = self.parse_binding_pattern()
                if self.at_punct("="):
                    self.next()
                    p = ("pdefault", p, self.parse_assignment())
                params.append(p)
            if self.at_punct(","):
                self.next()
        self.expect(")")
        return params

    def parse_if(self):
        self.expect("if")
        self.expect("(")
        cond = self.parse_expression()
        self.expect(")")
        then = self.parse_statement()
        other = None
        if self.at_kw("else"):
            self.next()
            other = self.parse_statement()
        return ("if", cond, then, other)

    def parse_for(self):
        self.expect("for")
        self.expect("(")
        init = None
        if self.at_punct(";"):
            self.next()
        elif self.at_kw("const", "let", "var"):
            kind = self.next().value
            pattern = self.parse_binding_pattern()
            if self.at_kw("of", "in"):
                mode = self.next().value
                it = self.parse_expression()
                self.expect(")")
                body = self.parse_statement()
                return ("forof" if mode == "of" else "forin",
                        kind, pattern, it, body)
            init_expr = ("undef",)
            if self.at_punct("="):
                self.next()
                init_expr = self.parse_assignment()
            init = ("var", kind, [(pattern, init_expr)])
            self.expect(";")
        else:
            init = ("expr", self.parse_expression())
            self.expect(";")
        cond = None
        if not self.at_punct(";"):
            cond = self.parse_expression()
        self.expect(";")
        update = None
        if not self.at_punct(")"):
            update = self.parse_expression()
        self.expect(")")
        body = self.parse_statement()
        return ("for", init, cond, update, body)

    def parse_while(self):
        self.expect("while")
        self.expect("(")
        cond = self.parse_expression()
        self.expect(")")
        return ("while", cond, self.parse_statement())

    def parse_try(self):
        self.expect("try")
        block = self.parse_block()
        param = None
        handler = None
        final = None
        if self.at_kw("catch"):
            self.next()
            if self.at_punct("("):
                self.next()
                param = self.parse_binding_pattern()
                self.expect(")")
            handler = self.parse_block()
        if self.at_kw("finally"):
            self.next()
            final = self.parse_block()
        return ("try", block, param, handler, final)

    # -- expressions (precedence climbing) --

    def parse_expression(self):
        e = self.parse_assignment()
        while self.at_punct(","):
            self.next()
            e = ("seq", e, self.parse_assignment())
        return e

    def parse_assignment(self):
        if self.is_arrow_ahead():
            return self.parse_arrow()
        left = self.parse_conditional()
        if self.at_punct("=", "+=", "-=", "*=", "/=", "%=", "??="):
            op = self.next().value
            right = self.parse_assignment()
            return ("assign", op, left, right)
        return left

    def is_arrow_ahead(self) -> bool:
        """Lookahead for `ident =>`, `async ident =>`, `( ... ) =>`,
        `async ( ... ) =>`."""
        j = self.i
        toks = self.toks
        if toks[j].type == "ident" and toks[j].value == "async":
            j += 1
        t = toks[j]
        if t.type == "ident" and t.value not in KEYWORDS:
            nxt = toks[j + 1]
            return nxt.type == "punct" and nxt.value == "=>"
        if t.type == "punct" and t.value == "(":
            depth = 0
            while j < len(toks):
                tj = toks[j]
                if tj.type == "punct" and tj.value == "(":
                    depth += 1
                elif tj.type == "punct" and tj.value == ")":
                    depth -= 1
                    if depth == 0:
                        nxt = toks[j + 1]
                        return nxt.type == "punct" and \
                            nxt.value == "=>"
                elif tj.type == "eof":
                    return False
                j += 1
        return False

    def parse_arrow(self):
        if self.at_kw("async"):
            self.next()
        if self.at_punct("("):
            params = self.parse_params()
        else:
            params = [("pid", self.next().value, None)]
        self.expect("=>")
        if self.at_punct("{"):
            body = self.parse_block()
            return ("arrow", params, body, False)
        return ("arrow", params, self.parse_assignment(), True)

    def parse_conditional(self):
        cond = self.parse_nullish()
        if self.at_punct("?"):
            self.next()
            a = self.parse_assignment()
            self.expect(":")
            b = self.parse_assignment()
            return ("cond", cond, a, b)
        return cond

    def _binary(self, sub, *ops):
        e = sub()
        while self.at_punct(*ops):
            op = self.next().value
            e = ("bin", op, e, sub())
        return e

    def parse_nullish(self):
        e = self.parse_or()
        while self.at_punct("??"):
            self.next()
            e = ("nullish", e, self.parse_or())
        return e

    def parse_or(self):
        e = self.parse_and()
        while self.at_punct("||"):
            self.next()
            e = ("or", e, self.parse_and())
        return e

    def parse_and(self):
        e = self.parse_equality()
        while self.at_punct("&&"):
            self.next()
            e = ("and", e, self.parse_equality())
        return e

    def parse_equality(self):
        return self._binary(self.parse_relational,
                            "===", "!==", "==", "!=")

    def parse_relational(self):
        e = self.parse_additive()
        while self.at_punct("<", ">", "<=", ">=") or self.at_kw("in"):
            if self.at_kw("in"):
                self.next()
                e = ("bin", "in", e, self.parse_additive())
            else:
                op = self.next().value
                e = ("bin", op, e, self.parse_additive())
        return e

    def parse_additive(self):
        return self._binary(self.parse_multiplicative, "+", "-")

    def parse_multiplicative(self):
        return self._binary(self.parse_unary, "*", "/", "%")

    def parse_unary(self):
        if self.at_punct("++", "--"):
            op = self.next().value
            return ("predec", op, self.parse_unary())
        if self.at_punct("!", "-", "+"):
            op = self.next().value
            return ("un", op, self.parse_unary())
        if self.at_kw("typeof"):
            self.next()
            return ("typeof", self.parse_unary())
        if self.at_kw("delete"):
            self.next()
            return ("delete", self.parse_unary())
        if self.at_kw("await"):
            self.next()
            return ("await", self.parse_unary())
        return self.parse_postfix()

    def parse_postfix(self):
        e = self.parse_primary()
        while True:
            if self.at_punct("."):
                self.next()
                e = ("member", e, self.next().value, False)
            elif self.at_punct("?."):
                self.next()
                if self.at_punct("("):
                    e = ("call", e, self.parse_args(), True)
                elif self.at_punct("["):
                    self.next()
                    idx = self.parse_expression()
                    self.expect("]")
                    e = ("index", e, idx, True)
                else:
                    e = ("member", e, self.next().value, True)
            elif self.at_punct("["):
                self.next()
                idx = self.parse_expression()
                self.expect("]")
                e = ("index", e, idx, False)
            elif self.at_punct("("):
                e = ("call", e, self.parse_args(), False)
            elif self.at_punct("++", "--") and self._line(
                    self.peek().pos) == self._line(
                    self.toks[self.i - 1].pos):
                # ASI: postfix ++/-- must sit on the operand's line
                op = self.next().value
                e = ("postdec", op, e)
            else:
                return e

    def parse_args(self) -> list:
        self.expect("(")
        args = []
        while not self.at_punct(")"):
            if self.at_punct("..."):
                self.next()
                args.append(("spread", self.parse_assignment()))
            else:
                args.append(self.parse_assignment())
            if self.at_punct(","):
                self.next()
        self.expect(")")
        return args

    def parse_primary(self):
        t = self.peek()
        if t.type == "num":
            self.next()
            return ("num", t.value)
        if t.type == "str":
            self.next()
            return ("strlit", t.value)
        if t.type == "tmpl":
            self.next()
            parts = []
            for kind, payload in t.value:
                if kind == "str":
                    parts.append(("str", payload))
                else:
                    parts.append(
                        ("expr",
                         Parser(payload, self.src).parse_expression())
                    )
            return ("tmpl", parts)
        if t.type == "regex":
            self.next()
            return ("regex", t.value[0], t.value[1])
        if t.type == "punct":
            if t.value == "(":
                self.next()
                e = self.parse_expression()
                self.expect(")")
                return e
            if t.value == "[":
                return self.parse_array()
            if t.value == "{":
                return self.parse_object()
            self.error(f"unexpected token {t!r}")
        kw = t.value
        if kw == "function" or (
                kw == "async" and self.peek(1).type == "ident"
                and self.peek(1).value == "function"):
            if kw == "async":
                self.next()
            self.next()
            name = None
            if self.peek().type == "ident" and not self.at_punct("("):
                name = self.next().value
            params = self.parse_params()
            body = self.parse_block()
            return ("funcexpr", name, params, body)
        if kw == "true":
            self.next()
            return ("bool", True)
        if kw == "false":
            self.next()
            return ("bool", False)
        if kw == "null":
            self.next()
            return ("null",)
        if kw == "undefined":
            self.next()
            return ("undef",)
        if kw == "this":
            self.next()
            return ("ident", "this")
        if kw == "new":
            self.error("`new` is outside the supported subset")
        self.next()
        return ("ident", kw)

    def parse_array(self):
        self.expect("[")
        elts = []
        while not self.at_punct("]"):
            if self.at_punct("..."):
                self.next()
                elts.append(("spread", self.parse_assignment()))
            else:
                elts.append(self.parse_assignment())
            if self.at_punct(","):
                self.next()
        self.expect("]")
        return ("arr", elts)

    def parse_object(self):
        self.expect("{")
        props = []
        while not self.at_punct("}"):
            if self.at_punct("..."):
                self.next()
                props.append(("spread", self.parse_assignment()))
            else:
                t = self.next()
                if t.type in ("str", "num"):
                    key = to_js_string(t.value)
                elif t.type == "punct" and t.value == "[":
                    key_expr = self.parse_assignment()
                    self.expect("]")
                    self.expect(":")
                    props.append(("computed", key_expr,
                                  self.parse_assignment()))
                    if self.at_punct(","):
                        self.next()
                    continue
                else:
                    key = t.value
                if self.at_punct(":"):
                    self.next()
                    props.append(("kv", key, self.parse_assignment()))
                elif self.at_punct("("):
                    params = self.parse_params()
                    body = self.parse_block()
                    props.append(
                        ("kv", key, ("funcexpr", key, params, body)))
                else:
                    props.append(("kv", key, ("ident", key)))
            if self.at_punct(","):
                self.next()
        self.expect("}")
        return ("obj", props)


# ------------------------------------------------------------ evaluator


class Env:
    __slots__ = ("vars", "parent")

    def __init__(self, parent=None):
        self.vars: dict = {}
        self.parent = parent

    def declare(self, name, value):
        self.vars[name] = value

    def lookup_env(self, name):
        e = self
        while e is not None:
            if name in e.vars:
                return e
            e = e.parent
        return None

    def get(self, name):
        e = self.lookup_env(name)
        if e is None:
            raise JSThrow(f"ReferenceError: {name} is not defined")
        return e.vars[name]

    def set(self, name, value):
        e = self.lookup_env(name)
        if e is None:
            # non-declared assignment lands on the global env (panels
            # run as classic scripts, not modules)
            e = self
            while e.parent is not None:
                e = e.parent
        e.vars[name] = value


class JSInterpreter:
    def __init__(self):
        self.global_env = Env()
        self._install_builtins()

    # -- public API --

    def run(self, src: str):
        toks = Tokenizer(src).run()
        prog = Parser(toks, src).parse_program()
        # hoist function declarations (panels call across definition
        # order)
        for st in prog:
            if st[0] == "fndecl":
                _, name, params, body, _async = st
                self.global_env.declare(
                    name,
                    JSFunction(name, params, body, self.global_env,
                               self),
                )
        for st in prog:
            if st[0] != "fndecl":
                self.exec_stmt(st, self.global_env)

    def call(self, fn, *args):
        if isinstance(fn, JSFunction):
            return fn.call(UNDEFINED, list(args))
        return fn(*args)

    def get_global(self, name):
        return self.global_env.get(name)

    def set_global(self, name, value):
        self.global_env.declare(name, value)

    # -- statements --

    def exec_block(self, stmts, env):
        for st in stmts:
            if st[0] == "fndecl":
                _, name, params, body, _async = st
                env.declare(
                    name, JSFunction(name, params, body, env, self))
        for st in stmts:
            if st[0] != "fndecl":
                self.exec_stmt(st, env)

    def exec_stmt(self, st, env):
        tag = st[0]
        if tag == "expr":
            self.eval_expr(st[1], env)
        elif tag == "var":
            for pattern, init in st[2]:
                self.bind_pattern(env, pattern,
                                  self.eval_expr(init, env),
                                  declare=True)
        elif tag == "if":
            if truthy(self.eval_expr(st[1], env)):
                self.exec_stmt(st[2], env)
            elif st[3] is not None:
                self.exec_stmt(st[3], env)
        elif tag == "block":
            self.exec_block(st[1], Env(env))
        elif tag == "ret":
            raise _Return(self.eval_expr(st[1], env))
        elif tag == "brk":
            raise _Break()
        elif tag == "cont":
            raise _Continue()
        elif tag == "throw":
            raise JSThrow(self.eval_expr(st[1], env))
        elif tag == "for":
            self.exec_for(st, env)
        elif tag == "forof":
            self.exec_forof(st, env)
        elif tag == "forin":
            self.exec_forin(st, env)
        elif tag == "while":
            while truthy(self.eval_expr(st[1], env)):
                try:
                    self.exec_stmt(st[2], Env(env))
                except _Break:
                    break
                except _Continue:
                    continue
        elif tag == "try":
            self.exec_try(st, env)
        elif tag == "empty":
            pass
        elif tag == "fndecl":
            _, name, params, body, _async = st
            env.declare(name,
                        JSFunction(name, params, body, env, self))
        else:
            raise JSThrow(f"unsupported statement {tag}")

    def exec_for(self, st, env):
        _, init, cond, update, body = st
        loop_env = Env(env)
        if init is not None:
            self.exec_stmt(init, loop_env)
        while cond is None or truthy(self.eval_expr(cond, loop_env)):
            try:
                self.exec_stmt(body, Env(loop_env))
            except _Break:
                break
            except _Continue:
                pass
            if update is not None:
                self.eval_expr(update, loop_env)

    def _iterate(self, value):
        if isinstance(value, list):
            return list(value)
        if isinstance(value, str):
            return list(value)
        if isinstance(value, JSObject):
            raise JSThrow("object is not iterable (no Symbol.iterator)")
        raise JSThrow(f"{to_js_string(value)} is not iterable")

    def exec_forof(self, st, env):
        _, _kind, pattern, it, body = st
        for item in self._iterate(self.eval_expr(it, env)):
            iter_env = Env(env)
            self.bind_pattern(iter_env, pattern, item, declare=True)
            try:
                self.exec_stmt(body, iter_env)
            except _Break:
                break
            except _Continue:
                continue

    def exec_forin(self, st, env):
        _, _kind, pattern, it, body = st
        obj = self.eval_expr(it, env)
        keys = list(obj.keys()) if isinstance(obj, JSObject) else \
            [to_js_string(i) for i in range(len(obj))] \
            if isinstance(obj, list) else []
        for key in keys:
            iter_env = Env(env)
            self.bind_pattern(iter_env, pattern, key, declare=True)
            try:
                self.exec_stmt(body, iter_env)
            except _Break:
                break
            except _Continue:
                continue

    def exec_try(self, st, env):
        _, block, param, handler, final = st
        try:
            self.exec_block(block, Env(env))
        except JSThrow as e:
            if handler is not None:
                h_env = Env(env)
                if param is not None:
                    val = e.value
                    if isinstance(val, str):
                        val = JSObject(
                            {"message": val, "name": "Error"})
                    self.bind_pattern(h_env, param, val, declare=True)
                self.exec_block(handler, h_env)
            elif final is None:
                raise
        finally:
            if final is not None:
                self.exec_block(final, Env(env))

    # -- binding --

    def bind_params(self, env, params, args):
        ai = 0
        for p in params:
            if p[0] == "prest":
                env.declare(p[1], list(args[ai:]))
                ai = len(args)
                continue
            val = args[ai] if ai < len(args) else UNDEFINED
            ai += 1
            self.bind_pattern(env, p, val, declare=True)

    def bind_pattern(self, env, pattern, value, declare=False):
        tag = pattern[0]
        if tag == "pid":
            _, name, default = pattern
            if value is UNDEFINED and default is not None:
                value = self.eval_expr(default, env)
            if declare:
                env.declare(name, value)
            else:
                env.set(name, value)
        elif tag == "pdefault":
            _, sub, default = pattern
            if value is UNDEFINED:
                value = self.eval_expr(default, env)
            self.bind_pattern(env, sub, value, declare)
        elif tag == "pobj":
            _, props, rest = pattern
            taken = set()
            for key, sub in props:
                taken.add(key)
                v = value.get_prop(key) \
                    if isinstance(value, JSObject) else UNDEFINED
                self.bind_pattern(env, sub, v, declare)
            if rest is not None:
                leftover = JSObject({
                    k: v for k, v in value.items() if k not in taken
                }) if isinstance(value, JSObject) else JSObject()
                if declare:
                    env.declare(rest, leftover)
                else:
                    env.set(rest, leftover)
        elif tag == "parr":
            _, elts = pattern
            seq = self._iterate(value)
            for idx, sub in enumerate(elts):
                if sub is None:
                    continue
                v = seq[idx] if idx < len(seq) else UNDEFINED
                self.bind_pattern(env, sub, v, declare)
        else:
            raise JSThrow(f"unsupported pattern {tag}")

    # -- expressions --

    def eval_expr(self, e, env):
        tag = e[0]
        if tag == "num":
            return e[1]
        if tag == "strlit":
            return e[1]
        if tag == "bool":
            return e[1]
        if tag == "null":
            return None
        if tag == "undef":
            return UNDEFINED
        if tag == "ident":
            return env.get(e[1])
        if tag == "tmpl":
            out = []
            for kind, payload in e[1]:
                if kind == "str":
                    out.append(payload)
                else:
                    out.append(
                        to_js_string(self.eval_expr(payload, env)))
            return "".join(out)
        if tag == "regex":
            return JSRegex(e[1], e[2])
        if tag == "arr":
            out = []
            for elt in e[1]:
                if elt[0] == "spread":
                    out.extend(
                        self._iterate(self.eval_expr(elt[1], env)))
                else:
                    out.append(self.eval_expr(elt, env))
            return out
        if tag == "obj":
            obj = JSObject()
            for prop in e[1]:
                if prop[0] == "spread":
                    src = self.eval_expr(prop[1], env)
                    if isinstance(src, JSObject):
                        obj.update(src)
                elif prop[0] == "computed":
                    obj[to_js_string(self.eval_expr(prop[1], env))] = \
                        self.eval_expr(prop[2], env)
                else:
                    obj[prop[1]] = self.eval_expr(prop[2], env)
            return obj
        if tag == "arrow":
            _, params, body, is_expr = e
            return JSFunction(None, params, body, env, self,
                              is_expr_body=is_expr)
        if tag == "funcexpr":
            _, name, params, body = e
            return JSFunction(name, params, body, env, self)
        if tag == "member":
            obj = self.eval_expr(e[1], env)
            if e[3] and (obj is None or obj is UNDEFINED):
                return UNDEFINED
            return self.get_member(obj, e[2])
        if tag == "index":
            obj = self.eval_expr(e[1], env)
            if e[3] and (obj is None or obj is UNDEFINED):
                return UNDEFINED
            return self.get_index(obj, self.eval_expr(e[2], env))
        if tag == "call":
            return self.eval_call(e, env)
        if tag == "assign":
            return self.eval_assign(e, env)
        if tag == "cond":
            return self.eval_expr(
                e[2] if truthy(self.eval_expr(e[1], env)) else e[3],
                env,
            )
        if tag == "and":
            left = self.eval_expr(e[1], env)
            return self.eval_expr(e[2], env) if truthy(left) else left
        if tag == "or":
            left = self.eval_expr(e[1], env)
            return left if truthy(left) else self.eval_expr(e[2], env)
        if tag == "nullish":
            left = self.eval_expr(e[1], env)
            if left is None or left is UNDEFINED:
                return self.eval_expr(e[2], env)
            return left
        if tag == "bin":
            return self.eval_binary(
                e[1],
                self.eval_expr(e[2], env),
                self.eval_expr(e[3], env),
            )
        if tag == "un":
            op = e[1]
            v = self.eval_expr(e[2], env)
            if op == "!":
                return not truthy(v)
            if op == "-":
                return -to_number(v)
            if op == "+":
                return to_number(v)
        if tag == "typeof":
            try:
                v = self.eval_expr(e[1], env)
            except JSThrow:
                return "undefined"
            if v is UNDEFINED:
                return "undefined"
            if v is None:
                return "object"
            if isinstance(v, bool):
                return "boolean"
            if isinstance(v, (int, float)):
                return "number"
            if isinstance(v, str):
                return "string"
            if isinstance(v, JSFunction) or callable(v):
                return "function"
            return "object"
        if tag == "delete":
            target = e[1]
            if target[0] == "member":
                obj = self.eval_expr(target[1], env)
                if isinstance(obj, JSObject):
                    obj.pop(target[2], None)
                return True
            if target[0] == "index":
                obj = self.eval_expr(target[1], env)
                key = self.eval_expr(target[2], env)
                if isinstance(obj, JSObject):
                    obj.pop(to_js_string(key), None)
                return True
            return True
        if tag == "await":
            return self.eval_expr(e[1], env)
        if tag in ("predec", "postdec"):
            # resolve the reference ONCE: a side-effecting operand
            # (a[f()]++) must read and write the same slot
            op, target = e[1], e[2]
            ttag = target[0]
            if ttag == "ident":
                old = to_number(env.get(target[1]))
                new = old + (1 if op == "++" else -1)
                env.set(target[1], new)
            elif ttag == "member":
                obj = self.eval_expr(target[1], env)
                old = to_number(self.get_member(obj, target[2]))
                new = old + (1 if op == "++" else -1)
                self.set_member(obj, target[2], new)
            elif ttag == "index":
                obj = self.eval_expr(target[1], env)
                key = self.eval_expr(target[2], env)
                old = to_number(self.get_index(obj, key))
                new = old + (1 if op == "++" else -1)
                self.set_index(obj, key, new)
            else:
                raise JSThrow(f"invalid ++/-- target {ttag}")
            return new if tag == "predec" else old
        if tag == "seq":
            self.eval_expr(e[1], env)
            return self.eval_expr(e[2], env)
        raise JSThrow(f"unsupported expression {tag}")

    def eval_binary(self, op, left, right):
        if op == "+":
            if isinstance(left, str) or isinstance(right, str):
                return to_js_string(left) + to_js_string(right)
            return to_number(left) + to_number(right)
        if op == "-":
            return to_number(left) - to_number(right)
        if op == "*":
            return to_number(left) * to_number(right)
        if op == "/":
            rn = to_number(right)
            ln = to_number(left)
            if rn == 0:
                if ln == 0 or math.isnan(ln):
                    return math.nan
                return math.inf if ln > 0 else -math.inf
            return ln / rn
        if op == "%":
            rn = to_number(right)
            if rn == 0:
                return math.nan
            return math.fmod(to_number(left), rn)
        if op == "===":
            return js_equals_strict(left, right)
        if op == "!==":
            return not js_equals_strict(left, right)
        if op == "==":
            return js_equals_loose(left, right)
        if op == "!=":
            return not js_equals_loose(left, right)
        if op in ("<", ">", "<=", ">="):
            if isinstance(left, str) and isinstance(right, str):
                pass
            else:
                left, right = to_number(left), to_number(right)
                if isinstance(left, float) and math.isnan(left) or \
                        isinstance(right, float) and math.isnan(right):
                    return False
            if op == "<":
                return left < right
            if op == ">":
                return left > right
            if op == "<=":
                return left <= right
            return left >= right
        if op == "in":
            if isinstance(right, JSObject):
                return to_js_string(left) in right
            if isinstance(right, list):
                idx = to_number(left)
                return 0 <= idx < len(right)
            return False
        raise JSThrow(f"unsupported operator {op}")

    def eval_call(self, e, env):
        _, callee, arg_exprs, optional = e
        args = []
        for a in arg_exprs:
            if a[0] == "spread":
                args.extend(self._iterate(self.eval_expr(a[1], env)))
            else:
                args.append(self.eval_expr(a, env))
        # method call: evaluate receiver once, dispatch on it
        if callee[0] == "member":
            obj = self.eval_expr(callee[1], env)
            if callee[3] and (obj is None or obj is UNDEFINED):
                return UNDEFINED
            fn = self.get_member(obj, callee[2])
            if fn is UNDEFINED or fn is None:
                if optional:
                    return UNDEFINED
                raise JSThrow(
                    f"TypeError: {to_js_string(obj)[:40]}."
                    f"{callee[2]} is not a function")
            return self.invoke(fn, obj, args)
        fn = self.eval_expr(callee, env)
        if (fn is UNDEFINED or fn is None) and optional:
            return UNDEFINED
        return self.invoke(fn, UNDEFINED, args)

    def invoke(self, fn, this, args):
        if isinstance(fn, JSFunction):
            return fn.call(this, args)
        if callable(fn):
            return fn(*args)
        raise JSThrow(
            f"TypeError: {to_js_string(fn)[:40]} is not a function")

    def eval_assign(self, e, env):
        _, op, target, value_expr = e
        if op == "??=":
            current = self.eval_expr(target, env)
            if not (current is None or current is UNDEFINED):
                return current
            value = self.eval_expr(value_expr, env)
        else:
            value = self.eval_expr(value_expr, env)
            if op != "=":
                current = self.eval_expr(target, env)
                value = self.eval_binary(op[0], current, value)
        tag = target[0]
        if tag == "ident":
            env.set(target[1], value)
        elif tag == "member":
            obj = self.eval_expr(target[1], env)
            self.set_member(obj, target[2], value)
        elif tag == "index":
            obj = self.eval_expr(target[1], env)
            key = self.eval_expr(target[2], env)
            self.set_index(obj, key, value)
        else:
            raise JSThrow(f"invalid assignment target {tag}")
        return value

    # -- member access / built-in methods --

    def get_member(self, obj, name):
        if obj is None or obj is UNDEFINED:
            raise JSThrow(
                f"TypeError: cannot read properties of "
                f"{to_js_string(obj)} (reading '{name}')")
        if isinstance(obj, JSObject):
            if name in obj:
                return obj[name]
            return UNDEFINED
        if isinstance(obj, str):
            return self.string_member(obj, name)
        if isinstance(obj, list):
            return self.array_member(obj, name)
        if isinstance(obj, (int, float)) and not isinstance(obj, bool):
            return self.number_member(obj, name)
        if isinstance(obj, JSRegex):
            if name == "exec":
                return obj.exec
            if name == "test":
                return obj.test
        if isinstance(obj, JSFunction):
            if name == "name":
                return obj.name
            if name == "call":
                return lambda this=UNDEFINED, *a: obj.call(
                    this, list(a))
        return UNDEFINED

    def set_member(self, obj, name, value):
        if isinstance(obj, JSObject):
            obj[name] = value
        elif isinstance(obj, list) and name == "length":
            n = int(to_number(value))
            del obj[n:]
        else:
            raise JSThrow(
                f"TypeError: cannot set {name} on "
                f"{to_js_string(obj)[:40]}")

    def get_index(self, obj, key):
        if isinstance(obj, list):
            if isinstance(key, (int, float)) and \
                    not isinstance(key, bool):
                idx = int(key)
                if 0 <= idx < len(obj):
                    return obj[idx]
                return UNDEFINED
            return self.get_member(obj, to_js_string(key))
        if isinstance(obj, str):
            if isinstance(key, (int, float)) and \
                    not isinstance(key, bool):
                idx = int(key)
                if 0 <= idx < len(obj):
                    return obj[idx]
                return UNDEFINED
            return self.get_member(obj, to_js_string(key))
        if isinstance(obj, JSObject):
            return obj.get_prop(to_js_string(key))
        return self.get_member(obj, to_js_string(key))

    def set_index(self, obj, key, value):
        if isinstance(obj, list):
            idx = int(to_number(key))
            while len(obj) <= idx:
                obj.append(UNDEFINED)
            obj[idx] = value
        elif isinstance(obj, JSObject):
            obj[to_js_string(key)] = value
        else:
            raise JSThrow("TypeError: cannot index-assign on "
                          f"{to_js_string(obj)[:40]}")

    # -- built-in method tables --

    def string_member(self, s: str, name):
        i = self  # noqa: F841

        def method(fn):
            return fn

        table = {
            "length": len(s),
            "slice": lambda a=0, b=None: _slice(s, a, b),
            "substring": lambda a=0, b=None: _substring(s, a, b),
            "toUpperCase": lambda: s.upper(),
            "toLowerCase": lambda: s.lower(),
            "trim": lambda: s.strip(),
            "split": lambda sep=UNDEFINED, n=None: _split(s, sep),
            "includes": lambda sub="": to_js_string(sub) in s,
            "startsWith": lambda sub="": s.startswith(
                to_js_string(sub)),
            "endsWith": lambda sub="": s.endswith(to_js_string(sub)),
            "indexOf": lambda sub="": s.find(to_js_string(sub)),
            "lastIndexOf": lambda sub="": s.rfind(to_js_string(sub)),
            "charAt": lambda idx=0: s[int(to_number(idx))]
            if 0 <= int(to_number(idx)) < len(s) else "",
            "charCodeAt": lambda idx=0: ord(s[int(to_number(idx))])
            if 0 <= int(to_number(idx)) < len(s) else math.nan,
            "padStart": lambda n=0, fill=" ": _pad(s, n, fill, True),
            "padEnd": lambda n=0, fill=" ": _pad(s, n, fill, False),
            "repeat": lambda n=0: s * int(to_number(n)),
            "replace": lambda pat, rep="": _replace(s, pat, rep,
                                                    all_=False),
            "replaceAll": lambda pat, rep="": _replace(s, pat, rep,
                                                       all_=True),
            "match": lambda pat: pat.exec(s)
            if isinstance(pat, JSRegex) else None,
            "concat": lambda *a: s + "".join(to_js_string(x)
                                             for x in a),
            "toString": lambda: s,
            "localeCompare": lambda o="": (s > to_js_string(o))
            - (s < to_js_string(o)),
        }
        v = table.get(name, UNDEFINED)
        return method(v) if callable(v) else v

    def array_member(self, arr: list, name):
        interp = self

        def as_fn(f):
            return lambda *cb_args: interp.invoke(
                f, UNDEFINED, list(cb_args))

        table = {
            "length": len(arr),
            "map": lambda f: [
                interp.invoke(f, UNDEFINED, [x, i, arr])
                for i, x in enumerate(list(arr))
            ],
            "filter": lambda f: [
                x for i, x in enumerate(list(arr))
                if truthy(interp.invoke(f, UNDEFINED, [x, i, arr]))
            ],
            "forEach": lambda f: _foreach(interp, arr, f),
            "join": lambda sep=",": to_js_string(sep).join(
                "" if x is None or x is UNDEFINED else to_js_string(x)
                for x in arr
            ),
            "slice": lambda a=0, b=None: _slice(arr, a, b),
            "concat": lambda *others: _concat(arr, others),
            "includes": lambda v=UNDEFINED: any(
                js_equals_strict(x, v) for x in arr),
            "indexOf": lambda v=UNDEFINED: next(
                (i for i, x in enumerate(arr)
                 if js_equals_strict(x, v)), -1),
            "find": lambda f: next(
                (x for i, x in enumerate(list(arr))
                 if truthy(interp.invoke(f, UNDEFINED, [x, i, arr]))),
                UNDEFINED,
            ),
            "findIndex": lambda f: next(
                (i for i, x in enumerate(list(arr))
                 if truthy(interp.invoke(f, UNDEFINED, [x, i, arr]))),
                -1,
            ),
            "some": lambda f: any(
                truthy(interp.invoke(f, UNDEFINED, [x, i, arr]))
                for i, x in enumerate(list(arr))
            ),
            "every": lambda f: all(
                truthy(interp.invoke(f, UNDEFINED, [x, i, arr]))
                for i, x in enumerate(list(arr))
            ),
            "push": lambda *v: (arr.extend(v), len(arr))[1],
            "pop": lambda: arr.pop() if arr else UNDEFINED,
            "shift": lambda: arr.pop(0) if arr else UNDEFINED,
            "unshift": lambda *v: (arr.__setitem__(
                slice(0, 0), list(v)), len(arr))[1],
            "reverse": lambda: (arr.reverse(), arr)[1],
            "flat": lambda depth=1: _flat(arr, int(to_number(depth))),
            "flatMap": lambda f: _flat(
                [interp.invoke(f, UNDEFINED, [x, i, arr])
                 for i, x in enumerate(list(arr))], 1),
            "reduce": lambda f, *init: _reduce(interp, arr, f, init),
            "sort": lambda f=None: _sort(interp, arr, f),
            "keys": lambda: list(range(len(arr))),
            "entries": lambda: [[i, x] for i, x in enumerate(arr)],
        }
        v = table.get(name, UNDEFINED)
        return v

    def number_member(self, num, name):
        if name == "toFixed":
            return lambda digits=0: (
                f"{float(num):.{int(to_number(digits))}f}")
        if name == "toString":
            return lambda: to_js_string(num)
        if name == "toLocaleString":
            return lambda: f"{num:,}"
        return UNDEFINED

    # -- global built-ins --

    def _install_builtins(self):
        g = self.global_env
        interp = self

        g.declare("undefined", UNDEFINED)
        g.declare("NaN", math.nan)
        g.declare("Infinity", math.inf)
        g.declare("globalThis", JSObject())

        g.declare("Object", JSObject({
            "keys": lambda o: list(o.keys())
            if isinstance(o, JSObject) else [],
            "values": lambda o: list(o.values())
            if isinstance(o, JSObject) else [],
            "entries": lambda o: [[k, v] for k, v in o.items()]
            if isinstance(o, JSObject) else [],
            "assign": lambda target, *rest: _assign(target, rest),
            "fromEntries": lambda pairs: JSObject({
                to_js_string(p[0]): p[1] for p in pairs
            }),
        }))
        g.declare("Array", JSObject({
            "isArray": lambda v=UNDEFINED: isinstance(v, list),
            "from": lambda v=UNDEFINED, f=None: [
                interp.invoke(f, UNDEFINED, [x, i]) if f else x
                for i, x in enumerate(interp._iterate(v))
            ] if not (v is UNDEFINED or v is None) else [],
        }))
        g.declare("Math", JSObject({
            "round": lambda x=math.nan: _js_round(to_number(x)),
            "floor": lambda x=math.nan: math.floor(to_number(x)),
            "ceil": lambda x=math.nan: math.ceil(to_number(x)),
            "abs": lambda x=math.nan: abs(to_number(x)),
            "max": lambda *a: max((to_number(x) for x in a),
                                  default=-math.inf),
            "min": lambda *a: min((to_number(x) for x in a),
                                  default=math.inf),
            "cos": lambda x=math.nan: math.cos(to_number(x)),
            "sin": lambda x=math.nan: math.sin(to_number(x)),
            "sqrt": lambda x=math.nan: math.sqrt(to_number(x)),
            "pow": lambda a=math.nan, b=math.nan: to_number(a)
            ** to_number(b),
            "random": lambda: 0.5,  # deterministic for tests
            "PI": math.pi,
        }))
        g.declare("JSON", JSObject({
            "parse": _json_parse,
            "stringify": _json_stringify,
        }))
        g.declare("Date", JSObject({
            "now": lambda: 1_785_400_000_000,  # fixed test clock (ms)
        }))
        # async runs synchronously in this interpreter, so promises
        # are already-resolved plain values
        g.declare("Promise", JSObject({
            "all": lambda arr=UNDEFINED: list(arr)
            if isinstance(arr, list) else [],
            "resolve": lambda v=UNDEFINED: v,
            "reject": lambda v=UNDEFINED: _promise_reject(v),
        }))
        g.declare("console", JSObject({
            "log": lambda *a: None,
            "warn": lambda *a: None,
            "error": lambda *a: None,
        }))
        # *rest swallows the (value, index, array) triple Array.map
        # passes when these are used as callbacks (`.map(String)`)
        g.declare("parseInt",
                  lambda s=UNDEFINED, base=10, *rest: _parse_int(
                      s, base if not rest else 10))
        g.declare("parseFloat",
                  lambda s=UNDEFINED, *rest: _parse_float(s))
        g.declare("isNaN", lambda v=UNDEFINED, *rest: isinstance(
            to_number(v), float) and math.isnan(to_number(v)))
        g.declare("String",
                  lambda v=UNDEFINED, *rest: to_js_string(v))
        g.declare("Number", lambda v=UNDEFINED, *rest: to_number(v))
        g.declare("Boolean", lambda v=UNDEFINED, *rest: truthy(v))
        g.declare("encodeURIComponent",
                  lambda s="": urllib.parse.quote(
                      to_js_string(s), safe="!'()*-._~"))
        g.declare("decodeURIComponent",
                  lambda s="": urllib.parse.unquote(to_js_string(s)))


# ----------------------------------------------------- builtin helpers


def _promise_reject(v):
    raise JSThrow(v)


def _js_round(x):
    if math.isnan(x) or math.isinf(x):
        return x
    return math.floor(x + 0.5)  # JS rounds .5 up, not banker's


def _slice(seq, a=0, b=None):
    n = len(seq)
    a = int(to_number(a)) if a is not None and a is not UNDEFINED else 0
    if a < 0:
        a = max(0, n + a)
    if b is None or b is UNDEFINED:
        b = n
    else:
        b = int(to_number(b))
        if b < 0:
            b = max(0, n + b)
    return seq[a:b]


def _substring(s, a=0, b=None):
    n = len(s)
    a = max(0, min(n, int(to_number(a))))
    b = n if (b is None or b is UNDEFINED) else \
        max(0, min(n, int(to_number(b))))
    if a > b:
        a, b = b, a
    return s[a:b]


def _split(s, sep):
    if sep is UNDEFINED:
        return [s]
    sep = to_js_string(sep)
    if sep == "":
        return list(s)
    return s.split(sep)


def _pad(s, n, fill, start):
    n = int(to_number(n))
    fill = to_js_string(fill) or " "
    while len(s) < n:
        add = fill[: n - len(s)]
        s = add + s if start else s + add
    return s


def _replace(s, pat, rep, all_):
    rep_s = to_js_string(rep) if not callable(rep) and \
        not isinstance(rep, JSFunction) else rep
    if isinstance(pat, JSRegex):
        count = 0 if (pat.global_ or all_) else 1
        if isinstance(rep_s, str):
            py_rep = re.sub(r"\$(\d)", r"\\\1", rep_s)
            return pat.re.sub(py_rep, s, count=count)
        return pat.re.sub(lambda m: to_js_string(rep_s(m.group(0))),
                          s, count=count)
    pat_s = to_js_string(pat)
    if all_:
        return s.replace(pat_s, to_js_string(rep_s))
    return s.replace(pat_s, to_js_string(rep_s), 1)


def _concat(arr, others):
    out = list(arr)
    for o in others:
        if isinstance(o, list):
            out.extend(o)
        else:
            out.append(o)
    return out


def _flat(arr, depth):
    out = []
    for x in arr:
        if isinstance(x, list) and depth > 0:
            out.extend(_flat(x, depth - 1))
        else:
            out.append(x)
    return out


def _foreach(interp, arr, f):
    for i, x in enumerate(list(arr)):
        interp.invoke(f, UNDEFINED, [x, i, arr])
    return UNDEFINED


def _reduce(interp, arr, f, init):
    items = list(arr)
    if init:
        acc = init[0]
        start = 0
    else:
        if not items:
            raise JSThrow("TypeError: reduce of empty array "
                          "with no initial value")
        acc = items[0]
        start = 1
    for i in range(start, len(items)):
        acc = interp.invoke(f, UNDEFINED, [acc, items[i], i, arr])
    return acc


def _sort(interp, arr, f):
    if f is None or f is UNDEFINED:
        arr.sort(key=to_js_string)
    else:
        def cmp(a, b):
            r = to_number(interp.invoke(f, UNDEFINED, [a, b]))
            if math.isnan(r):
                return 0
            return -1 if r < 0 else (1 if r > 0 else 0)

        arr.sort(key=functools.cmp_to_key(cmp))
    return arr


def _assign(target, rest):
    for o in rest:
        if isinstance(o, JSObject):
            target.update(o)
    return target


def py_to_js(v):
    """Convert parsed-JSON Python values into interpreter values."""
    if isinstance(v, dict):
        return JSObject({k: py_to_js(x) for k, x in v.items()})
    if isinstance(v, list):
        return [py_to_js(x) for x in v]
    return v


def js_to_py(v):
    if v is UNDEFINED:
        return None
    if isinstance(v, JSObject):
        return {k: js_to_py(x) for k, x in v.items()
                if x is not UNDEFINED}
    if isinstance(v, list):
        return [js_to_py(x) for x in v]
    return v


def _json_parse(s="null"):
    try:
        return py_to_js(json.loads(to_js_string(s)))
    except (ValueError, TypeError) as e:
        raise JSThrow(f"SyntaxError: {e}") from None


def _json_stringify(v=UNDEFINED, _replacer=None, indent=None):
    if v is UNDEFINED:
        return UNDEFINED
    kw = {}
    if indent is not None and indent is not UNDEFINED:
        kw["indent"] = int(to_number(indent))
    return json.dumps(js_to_py(v), **kw)


def _parse_int(s, base=10):
    base = int(to_number(base)) or 10
    m = re.match(r"\s*[+-]?(0[xX][0-9a-fA-F]+|\d+)",
                 to_js_string(s))
    if not m:
        return math.nan
    text = m.group(0).strip()
    try:
        if text.lower().startswith(("0x", "+0x", "-0x")):
            return int(text, 16)
        return int(text, base)
    except ValueError:
        return math.nan


def _parse_float(s=UNDEFINED):
    m = re.match(r"\s*[+-]?(\d+\.?\d*|\.\d+)([eE][+-]?\d+)?",
                 to_js_string(s))
    if not m:
        return math.nan
    return float(m.group(0))
