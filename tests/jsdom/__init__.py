"""Stdlib-only JS execution harness for the dashboard (VERDICT r4 #3).

No JS engine exists in this image (no node/quickjs/duktape), so the
render harness ships its own: a tree-walking interpreter for the
bounded modern-JS subset ui/panels.js is written in (template
literals, arrow functions, async/await, destructuring, spread,
optional chaining, nullish coalescing — no classes, no generators),
plus a minimal DOM shim. tests/test_ui_render.py executes every
panel's real render function against payloads served by the real HTTP
routes and asserts on the produced HTML — the field-drift class of bug
(round 4 found two) can no longer hide in a render path.
"""

from tests.jsdom.dom import Document, Element  # noqa: F401
from tests.jsdom.mini_js import JSInterpreter, JSObject, UNDEFINED  # noqa: F401
