"""Weight-only int8 quantization (ops/quant.py): tensor roundtrip,
forward fidelity, cross-impl agreement, mesh sharding, engine serving,
and the ROOM_TPU_QUANT provider knob.

No reference counterpart (quantization lived inside Ollama's GGUF files,
local-model.ts:3-5); this is TPU-first new work — decode streams every
weight byte from HBM each step, so int8 halves the bandwidth bill.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from room_tpu.models import qwen3, tiny_dense, tiny_moe
from room_tpu.ops.quant import (
    QTensor, dequantize, quantize_decoder_params, quantize_tensor,
    quantized_decoder_param_specs,
)
from room_tpu.serving import SamplingParams, ServingEngine


def test_quantize_tensor_roundtrip():
    w = jax.random.normal(jax.random.PRNGKey(0), (16, 32), jnp.float32)
    qt = quantize_tensor(w, (0,))
    assert qt.q.dtype == jnp.int8 and qt.q.shape == w.shape
    assert qt.s.shape == (1, 32)
    back = dequantize(qt, jnp.float32)
    # absmax int8 per column: worst-case error is s/2 per element
    err = np.abs(np.asarray(back) - np.asarray(w))
    bound = np.asarray(qt.s) / 2 + 1e-6
    assert (err <= bound).all()


def test_quantize_tensor_multi_axis():
    w = jax.random.normal(jax.random.PRNGKey(1), (3, 4, 8, 5))
    qt = quantize_tensor(w, (2,))
    assert qt.s.shape == (3, 4, 1, 5)


@pytest.mark.parametrize("cfg_fn", [tiny_moe, tiny_dense])
def test_forward_quantized_close(cfg_fn):
    """Quantized logits must stay close to full precision in relative
    norm — int8 per-channel on randn weights keeps a few % error."""
    cfg = cfg_fn()
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    # quantization consumes its input tree (leaf donation), so
    # quantize a fresh identically-seeded init
    qparams = quantize_decoder_params(
        qwen3.init_params(cfg, jax.random.PRNGKey(0)), cfg
    )
    assert qparams["layers"]["wq"].q.dtype == jnp.int8
    # norms and router stay unquantized
    assert not isinstance(qparams["layers"]["ln1"], QTensor)
    if cfg.is_moe:
        assert not isinstance(qparams["layers"]["router"], QTensor)

    tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 9), 0,
                                cfg.vocab_size)
    want, _ = qwen3.forward(params, cfg, tokens)
    got, _ = qwen3.forward(qparams, cfg, tokens)
    w = np.asarray(want, np.float32)
    g = np.asarray(got, np.float32)
    rel = np.linalg.norm(g - w) / (np.linalg.norm(w) + 1e-9)
    assert rel < 0.15, f"quantized logits diverged: rel={rel:.3f}"


def test_quantized_weights_halve_bytes():
    cfg = tiny_moe()
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    # quantization consumes its input tree (leaf donation), so
    # quantize a fresh identically-seeded init
    qparams = quantize_decoder_params(
        qwen3.init_params(cfg, jax.random.PRNGKey(0)), cfg
    )

    def nbytes(tree):
        return sum(x.nbytes for x in jax.tree.leaves(tree))

    # bf16 -> int8 (+ small f32 scales): comfortably under 60%
    assert nbytes(qparams) < 0.6 * nbytes(params)


def test_quant_moe_impls_agree():
    """ragged, gshard, and shardmap MoE must agree on the SAME
    quantized weights (scale application is per-expert-channel in all
    three)."""
    import dataclasses

    from room_tpu.ops.moe_shardmap import set_ep_mesh
    from room_tpu.parallel import MeshSpec, make_mesh

    cfg = tiny_moe()
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    # quantization consumes its input tree (leaf donation), so
    # quantize a fresh identically-seeded init
    qparams = quantize_decoder_params(
        qwen3.init_params(cfg, jax.random.PRNGKey(0)), cfg
    )
    tokens = jax.random.randint(jax.random.PRNGKey(3), (2, 8), 0,
                                cfg.vocab_size)

    outs = {}
    for impl in ("ragged", "shardmap"):
        c = dataclasses.replace(cfg, moe_impl=impl)
        if impl == "shardmap":
            set_ep_mesh(make_mesh(MeshSpec(1, 2, 1)))
        try:
            outs[impl], _ = qwen3.forward(qparams, c, tokens)
        finally:
            if impl == "shardmap":
                set_ep_mesh(None)
    np.testing.assert_allclose(
        np.asarray(outs["shardmap"]), np.asarray(outs["ragged"]),
        rtol=2e-3, atol=2e-3,
    )

    # gshard has its own (capacity-drop) semantics, so compare its
    # quantized output against its own full-precision output instead
    c = dataclasses.replace(cfg, moe_impl="gshard")
    want, _ = qwen3.forward(params, c, tokens)
    got, _ = qwen3.forward(qparams, c, tokens)
    w = np.asarray(want, np.float32)
    g = np.asarray(got, np.float32)
    rel = np.linalg.norm(g - w) / (np.linalg.norm(w) + 1e-9)
    assert rel < 0.15, f"gshard quantized diverged: rel={rel:.3f}"


def test_quantized_sharded_token_identity():
    """A quantized engine on the 8-device mesh must generate the same
    tokens as the quantized single-device engine (QTensor leaves shard
    per quantized_decoder_param_specs)."""
    from room_tpu.parallel import MeshSpec, make_mesh, shard_pytree

    cfg = tiny_moe()
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    # quantization consumes its input tree (leaf donation), so
    # quantize a fresh identically-seeded init
    qparams = quantize_decoder_params(
        qwen3.init_params(cfg, jax.random.PRNGKey(0)), cfg
    )
    sp = SamplingParams(temperature=0.0, max_new_tokens=5)
    prompts = [[1, 2, 3], [9, 8, 7, 6]]

    def serve(p, mesh):
        eng = ServingEngine(cfg, p, max_batch=2, page_size=8,
                            n_pages=64, mesh=mesh)
        turns = [eng.submit(pr, sampling=sp) for pr in prompts]
        eng.run_until_idle()
        assert all(t.finish_reason in ("stop", "length") for t in turns)
        return [t.new_tokens for t in turns]

    base = serve(qparams, None)
    mesh = make_mesh(MeshSpec(2, 2, 2))
    sharded = shard_pytree(
        qparams, quantized_decoder_param_specs(cfg), mesh
    )
    assert serve(sharded, mesh) == base


def test_provider_quant_env(monkeypatch):
    """ROOM_TPU_QUANT=int8 makes the model host serve quantized weights
    end-to-end through the provider tool loop."""
    from room_tpu.providers import ExecutionRequest
    from room_tpu.providers.tpu import (
        TpuProvider, get_model_host, quant_env_for, reset_model_hosts,
    )

    monkeypatch.setenv("ROOM_TPU_QUANT", "int8")
    assert quant_env_for("tiny-moe") == "int8"
    monkeypatch.setenv("ROOM_TPU_QUANT_TINY_MOE", "int8")
    assert quant_env_for("tiny-moe") == "int8"

    reset_model_hosts()
    try:
        prov = TpuProvider("tiny-moe")
        res = prov.execute(ExecutionRequest(
            prompt="quantized turn", max_new_tokens=4, max_turns=1,
            timeout_s=300,
        ))
        assert res.success and res.output_tokens > 0
        host = get_model_host("tiny-moe")
        assert isinstance(host._engine.params["layers"]["wq"], QTensor)
    finally:
        reset_model_hosts()


def test_provider_quant_env_rejects_unknown(monkeypatch):
    from room_tpu.providers.base import ProviderError
    from room_tpu.providers.tpu import ModelHost, reset_model_hosts

    monkeypatch.setenv("ROOM_TPU_QUANT", "int4")
    reset_model_hosts()
    try:
        with pytest.raises(ProviderError, match="int4"):
            ModelHost("tiny-moe").engine()
    finally:
        reset_model_hosts()
