"""DOM-level render tests (VERDICT r4 #3): every dashboard panel's
real render function executes (tests/jsdom mini-JS interpreter +
DOM shim) against payloads served by the live HTTP routes, and the
produced HTML is asserted on. The round-4 field-drift class
(`t.instructions` vs `prompt`, `m.content` vs `observations`) now
fails CI in the render path itself: a missing field interpolates as
the literal string "undefined", which the sweep rejects in every
panel."""

import json
import os
import urllib.error
import urllib.request

import pytest

from room_tpu.db import Database
from room_tpu.server.http import ApiServer
from tests.jsdom.harness import PanelHarness

UI_DIR = os.path.join(os.path.dirname(__file__), "..", "ui")


def _seed(db):
    from room_tpu.core import (
        escalations as esc_mod, goals as goals_mod,
        memory as memory_mod, messages as messages_mod,
        quorum as quorum_mod, rooms as rooms_mod,
        skills as skills_mod, task_runner,
    )

    room = rooms_mod.create_room(db, "render-room",
                                 worker_model="echo")
    rid = room["id"]
    task_runner.create_task(db, "render-task", "do the thing",
                            trigger_type="manual")
    goals_mod.create_goal(db, rid, "render-goal")
    # high-impact stays open for votes (low-impact auto-approves)
    quorum_mod.announce(db, rid, None, "render-proposal",
                        decision_type="high_impact")
    esc_mod.create_escalation(db, rid, "render-question")
    messages_mod.send_room_message(db, rid, rid, "render-subject",
                                   "render-body")
    memory_mod.remember(db, "render-fact", "render-content")
    skills_mod.create_skill(db, "render-skill", "render-how")
    db.insert("INSERT INTO task_runs(task_id, status) VALUES (1, 'ok')")
    return rid


@pytest.fixture(scope="module")
def harness(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("ui-render")
    os.environ["ROOM_TPU_DATA_DIR"] = str(tmp / "data")
    db = Database(":memory:")
    srv = ApiServer(db, static_dir=UI_DIR)
    srv.start()
    _seed(db)
    token = srv.tokens["user"]

    def api(method, path, body):
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}{path}",
            method=method,
            headers={
                "Authorization": f"Bearer {token}",
                **({"Content-Type": "application/json"}
                   if body is not None else {}),
            },
            data=json.dumps(body).encode()
            if body is not None else None,
        )
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                return json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            try:
                return json.loads(e.read() or b"{}")
            except ValueError:
                return {"error": f"http {e.code}"}

    h = PanelHarness(api)
    yield h
    srv.stop()


ALL_PANELS = [
    "swarm", "rooms", "setup", "workers", "goals", "tasks", "runs",
    "inbox", "messages", "votes", "memory", "skills", "wallet",
    "transactions", "tpu", "cycles", "usage", "providers", "clerk",
    "status", "feed", "system", "settings", "help",
]


def test_panel_registry_complete(harness):
    assert harness.panel_keys() == ALL_PANELS


@pytest.mark.parametrize("key", ALL_PANELS)
def test_panel_renders_clean(harness, key):
    """Every panel: renders against live payloads, produces real
    markup, and never interpolates a missing field ("undefined"),
    a numeric hole ("NaN"), or an unstringified object."""
    html = harness.render(key)
    assert len(html) > 40, f"{key}: near-empty render"
    for poison in ("undefined", "NaN", "[object Object]"):
        assert poison not in html, f"{key}: {poison!r} in HTML"


def test_rooms_panel_shows_seeded_room(harness):
    harness.render("rooms")
    # the room list loads into its own element (loadRoomList)
    assert "render-room" in harness.element_html("roomList")


def test_tasks_panel_shows_seeded_task(harness):
    html = harness.render("tasks")
    assert "render-task" in html
    assert "do the thing" in html     # prompt column (r4 drift bug)


def test_goals_panel_shows_seeded_goal(harness):
    assert "render-goal" in harness.render("goals")


def test_memory_panel_shows_observations(harness):
    harness.render("memory")
    html = harness.element_html("memResults")  # memSearch target
    assert "render-fact" in html
    assert "render-content" in html   # observation body (r4 drift bug)


def test_skills_panel_shows_seeded_skill(harness):
    assert "render-skill" in harness.render("skills")


def test_inbox_panel_shows_escalation(harness):
    assert "render-question" in harness.render("inbox")


def test_messages_panel_shows_message(harness):
    harness.render("messages")
    assert "render-subject" in harness.element_html("msgTable")


def test_votes_panel_shows_proposal(harness):
    assert "render-proposal" in harness.render("votes")


def test_runs_panel_shows_run_status(harness):
    html = harness.render("runs")
    assert "render-task" in html or "#1" in html


def test_workers_panel_shows_queen(harness):
    # every room auto-creates its queen
    html = harness.render("workers")
    assert "queen" in html.lower()


def test_status_panel_shows_version(harness):
    import room_tpu

    assert room_tpu.__version__ in harness.render("status")


def test_tasks_panel_run_link_calls_runs_route(harness):
    """Panel-driven interaction: showRuns(1) must hit the runs route
    and render the run row into the taskRuns element."""
    harness.render("tasks")
    harness.call_global("showRuns", 1)
    assert ("GET", "/api/tasks/1/runs", None) in harness.api_calls
    assert "pill" in harness.element_html("taskRuns")


def test_swarm_ws_cycle_events_render_cards(harness):
    """The swarm panel's WS path: a cycle:started event for a seeded
    worker produces a cycling card."""
    harness.render("swarm")
    harness.interp.set_global("currentView", "swarm")
    harness.ws_dispatch({
        "channel": "room:1", "type": "cycle:started",
        "data": {"worker_id": 1, "cycle_id": 7},
    })
    assert "cycling" in harness.element_html("swarmRooms")


def test_help_panel_static_sections(harness):
    html = harness.render("help")
    assert html.count("<h2>") >= 4


def test_render_panel_error_boundary(harness):
    """A throwing panel renders an inline error card with retry, not a
    blank view (renderPanel is the app-wide boundary)."""
    harness.interp.run(
        'PANELS.broken = {title: "broken", '
        'render: async () => { throw {message: "boom-123"}; }};'
    )
    from tests.jsdom.dom import Element

    el = Element("div", "view-broken")
    harness.call_global("renderPanel", "broken", el)
    from tests.jsdom.mini_js import to_js_string

    html = to_js_string(el.get_prop("innerHTML"))
    assert "failed to render" in html
    assert "boom-123" in html
    assert "retry" in html


def test_room_settings_validation_blocks_bad_save(harness):
    harness.render("rooms")
    harness.call_global("selectRoom", 1)
    doc = harness.document
    doc.get_element_by_id("roomMaxTurns")["value"] = "0"
    n_calls = len(harness.api_calls)
    harness.call_global("roomConfigSave", 1)
    assert "max turns" in harness.element_html("roomCfgError") \
        or "max turns" in harness.document.get_element_by_id(
            "roomCfgError").get_prop("textContent")
    # no PUT fired
    assert not any(m == "PUT" for m, p, b in harness.api_calls[n_calls:])


def test_room_settings_valid_save_puts_all_knobs(harness):
    harness.render("rooms")
    harness.call_global("selectRoom", 1)
    doc = harness.document
    # element stubs don't inherit rendered values: set every
    # validated field explicitly
    for elt_id, val in (
        ("roomNameEdit", "renamed-room"), ("roomMaxTurns", "40"),
        ("roomMaxTasks", "3"), ("cfgVoteTimeout", "10"),
        ("cfgMinVoters", "2"), ("roomCycleGap", "30"),
    ):
        doc.get_element_by_id(elt_id)["value"] = val
    harness.call_global("roomConfigSave", 1)
    puts = [(m, p, b) for m, p, b in harness.api_calls
            if m == "PUT" and p == "/api/rooms/1"]
    assert puts, harness.api_calls[-5:]
    body = puts[-1][2]
    assert body["name"] == "renamed-room"
    assert body["queenMaxTurns"] == 40
    assert body["config"]["minVoters"] == 2
    # unknown config keys survive the save (spread of loaded config)
    assert "voteThreshold" in body["config"]


def test_room_archive_needs_confirmation(harness):
    harness.render("rooms")
    harness.call_global("selectRoom", 1)
    harness.confirm_answer = False
    n = len(harness.api_calls)
    harness.call_global("roomArchive", 1)
    assert not any(m == "DELETE" for m, p, b in harness.api_calls[n:])
    harness.confirm_answer = True  # restore for other tests


def test_clerk_setup_guide_steps(harness):
    harness.interp.set_global("clerkGuideStep", 1)
    html = harness.render("clerk")
    assert "clerk setup guide" in html
    assert "backend" in html
    harness.interp.set_global("clerkGuideStep", 3)
    harness.render("clerk")
    harness.document.get_element_by_id(
        "clerkModelPick")["value"] = "tpu:qwen3-coder-30b"
    harness.call_global("clerkGuideSaveModel")
    assert ("PUT", "/api/settings/clerk_model",
            {"value": "tpu:qwen3-coder-30b"}) in harness.api_calls
    harness.interp.set_global("clerkGuideStep", 0)


def test_memory_graph_view_renders_entities(harness):
    """The memory panel's graph tab: stats line + entity table with
    observation drill-down, driven through the live memory routes."""
    harness.interp.set_global("memTab", "graph")
    try:
        harness.render("memory")
        graph = harness.element_html("memGraph")
        assert "entities" in graph
        assert "render-fact" in graph
        # drill into entity 1's observations
        harness.call_global("entObservations", 1)
        obs = harness.element_html("entObs-1")
        assert "render-content" in obs
        for poison in ("undefined", "NaN", "[object Object]"):
            assert poison not in graph + obs
    finally:
        harness.interp.set_global("memTab", "search")


def test_swarm_graph_view_draws_queen_hub(harness):
    """The swarm graph view (SVG queen hub + worker ring) renders from
    worker state: the seeded room's queen must appear as the hub."""
    harness.render("swarm")
    state = harness.interp.get_global("swarmState")
    # mirror what the swarm loader stores (workers + rooms), saving
    # the loader-populated values for restore (module-scoped harness)
    from tests.jsdom.mini_js import UNDEFINED, py_to_js

    saved = {k: state.get(k, UNDEFINED)
             for k in ("rooms", "workers", "tab")}
    state["rooms"] = py_to_js([{"id": 1, "name": "render-room"}])
    state["workers"] = py_to_js([
        {"id": 1, "room_id": 1, "name": "queen", "is_default": True},
        {"id": 2, "room_id": 1, "name": "scout", "is_default": False},
    ])
    state["tab"] = "graph"
    try:
        harness.call_global("renderSwarmCards")
        html = harness.element_html("swarmRooms")
        assert "<svg" in html
        assert "queen" in html
        assert "scout" in html
    finally:
        for k, v in saved.items():
            if v is UNDEFINED:
                state.pop(k, None)
            else:
                state[k] = v


def test_setup_create_room_round_trip(harness):
    """Setup panel drives the real create-room route; the result line
    reports the new room id."""
    harness.render("setup")
    harness.document.get_element_by_id(
        "setupName")["value"] = "made-in-setup"
    harness.document.get_element_by_id("setupTemplate")["value"] = ""
    harness.document.get_element_by_id("setupModel")["value"] = "echo"
    harness.call_global("setupCreate")
    posts = [b for m, p, b in harness.api_calls
             if m == "POST" and p == "/api/rooms"]
    assert {"name": "made-in-setup", "workerModel": "echo"} in posts
    out = harness.document.get_element_by_id(
        "setupResult").get_prop("textContent")
    assert "created" in out


def test_settings_notifications_row(harness):
    """Desktop-notification UX (reference useNotifications): the
    settings row degrades when the Notification API is absent, shows
    the enable button when unpermitted, and the verified pill when
    granted."""
    html = harness.render("settings")
    assert "desktop notifications" in html
    assert "not supported here" in html  # no Notification API in shim

    harness.interp.set_global("notifySupported", lambda *a: True)
    harness.interp.set_global("notifyPermitted", lambda *a: False)
    html = harness.render("settings")
    assert "notifyRequest()" in html     # enable button wired

    harness.interp.set_global("notifyPermitted", lambda *a: True)
    html = harness.render("settings")
    assert "enabled" in html


def test_votes_panel_buttons_ride_keeper_route(harness):
    """The human at the dashboard is the keeper: approve/reject must
    hit /keeper-vote (posting to /vote without a workerId was an FK
    500 before)."""
    harness.render("votes")
    harness.call_global("vote", 1, "approve")
    assert ("POST", "/api/decisions/1/keeper-vote",
            {"vote": "approve"}) in harness.api_calls
    assert not any(
        p == "/api/decisions/1/vote" for _, p, _ in harness.api_calls
    )


def test_onclick_sweep_no_server_errors():
    """Generalizes the voting-flow bug class: render every panel
    against the live server, extract every onclick handler from the
    produced HTML, execute each through the interpreter (dialogs
    auto-confirm, timeouts never fire), and assert NO handler ever
    produced a 5xx — a panel button that crashes the server must fail
    CI even when a data-dependent 4xx would be acceptable."""
    import re
    import threading

    from room_tpu.core import rooms as rooms_mod
    from room_tpu.db import Database as Db
    from room_tpu.server.http import ApiServer as Api
    from tests.jsdom.mini_js import JSThrow

    # dedicated server: the sweep mutates state (deletes, archives)
    db = Db(":memory:")
    srv = Api(db, static_dir=UI_DIR)
    srv.start()
    try:
        _seed(db)
        rooms_mod.create_room(db, "sweep-spare", worker_model="echo")
        token = srv.tokens["user"]
        statuses: list[tuple] = []

        def api(method, path, body):
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}{path}", method=method,
                headers={
                    "Authorization": f"Bearer {token}",
                    **({"Content-Type": "application/json"}
                       if body is not None else {}),
                },
                data=json.dumps(body).encode()
                if body is not None else None,
            )
            try:
                with urllib.request.urlopen(req, timeout=10) as resp:
                    statuses.append((method, path, resp.status))
                    return json.loads(resp.read() or b"{}")
            except urllib.error.HTTPError as e:
                statuses.append((method, path, e.code))
                try:
                    return json.loads(e.read() or b"{}")
                except ValueError:
                    return {"error": f"http {e.code}"}

        h = PanelHarness(api)
        onclick_re = re.compile(r'onclick="([^"]+)"')
        ran = 0
        for key in ALL_PANELS:
            html = h.render(key)
            if key == "rooms":
                h.call_global("selectRoom", 1)
                html += h.element_html("roomDetail")
            handlers = set(onclick_re.findall(html))
            for code in handlers:
                code = code.replace("&quot;", '"').replace("&amp;", "&")
                if "event" in code or "this" in code:
                    continue
                # browser inline-handler idiom: top-level `return
                # false` has no meaning outside an element context
                code = re.sub(r";?\s*return false;?\s*$", "", code)
                try:
                    h.interp.run(code)
                    ran += 1
                except (JSThrow, SyntaxError):
                    # a handler may legitimately throw on sweep state
                    # (e.g. missing element values); the assertion
                    # below is about SERVER health
                    pass
        assert ran >= 40, f"sweep only executed {ran} handlers"
        # 503 = service honestly unavailable in this hermetic env (no
        # runtime thread / chain RPC / JWT secret); anything else in
        # the 5xx range is a server crash a button must never cause
        fives = [s for s in statuses if s[2] >= 500 and s[2] != 503]
        assert not fives, f"panel buttons caused 5xx: {fives}"
    finally:
        srv.stop()


# ---- real-engine syntax gate (node-dependent; docs/lifecycle.md CI) ----

def test_ui_js_parses_under_real_node():
    """The mini-JS interpreter accepts a bounded JS *subset* — syntax
    it happens to tolerate could still be invalid JS in a browser. When
    a real node binary exists, `node --check` every UI source; on bare
    containers (this image ships no JS engine) skip cleanly instead of
    reporting a spurious failure."""
    import shutil
    import subprocess

    node = shutil.which("node")
    if node is None:
        pytest.skip("node not installed; jsdom shim covers the render "
                    "path without it")
    for fname in sorted(os.listdir(UI_DIR)):
        if not fname.endswith(".js"):
            continue
        proc = subprocess.run(
            [node, "--check", os.path.join(UI_DIR, fname)],
            capture_output=True, text=True, timeout=60,
        )
        assert proc.returncode == 0, (
            f"{fname} failed node --check:\n{proc.stderr}"
        )
