"""Tiered KV offload suite (docs/kv_offload.md).

Pins the subsystem's core contract on the CPU backend: hibernating a
parked session measurably releases its HBM pages (PageTable free count
rises) and the resumed turn is token-identical to a never-offloaded
control — through the host-RAM tier, the disk spool, watermark-driven
demotion, prefetch, and every offload_io fault fallback. The quick
chaos burst runs in the CI chaos job; page accounting and store
drainage are asserted after every scenario.
"""

import threading
import time

import jax
import numpy as np
import pytest

from room_tpu.models import qwen3, tiny_moe
from room_tpu.serving import SamplingParams, ServingEngine, faults
from room_tpu.serving.kv_offload import (
    TieredKVStore, _read_spool, _write_spool,
)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture(scope="module")
def model():
    cfg = tiny_moe()
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


@pytest.fixture()
def make_engine(model, monkeypatch, tmp_path):
    """Offload-enabled engine factory: prefix cache off so page-balance
    checks reduce to 'every session released -> pool full', spool under
    tmp_path so nothing leaks across tests."""
    monkeypatch.setenv("ROOM_TPU_PREFIX_CACHE_PAGES", "0")
    monkeypatch.setenv("ROOM_TPU_OFFLOAD_DIR", str(tmp_path / "spool"))
    cfg, params = model

    def build(**kw):
        kw.setdefault("max_batch", 4)
        kw.setdefault("page_size", 8)
        kw.setdefault("n_pages", 96)
        kw.setdefault("offload", True)
        return ServingEngine(cfg, params, **kw)

    return build


def _greedy(n=8, **kw):
    return SamplingParams(temperature=0.0, max_new_tokens=n, **kw)


def _drain(eng):
    for sid in list(eng.sessions):
        eng.release_session(sid)
    assert eng.page_table.free_pages == eng.n_pages - 1, (
        "KV page leak after releasing every session"
    )
    if eng.offload_store is not None:
        assert len(eng.offload_store) == 0, "offload store leaked"


# ---- store unit tier ----

def _arrays(nbytes=1024):
    return {"k": np.arange(nbytes, dtype=np.uint8).reshape(1, -1),
            "v": np.zeros((1, nbytes), np.uint8)}


def test_store_put_get_discard(tmp_path):
    st = TieredKVStore(host_bytes_cap=1 << 20, disk_bytes_cap=1 << 20,
                       spool_dir=str(tmp_path))
    st.put("a", _arrays(), own_tokens=16, n_pages=2)
    assert st.has("a") and st.tier_of("a") == "host"
    entry, arrays = st.get("a")
    assert entry.own_tokens == 16 and entry.n_pages == 2
    assert (arrays["k"] == _arrays()["k"]).all()
    assert st.discard("a") and not st.has("a")
    assert st.stats()["host_hits"] == 1


def test_store_lru_demotes_to_disk_and_drops(tmp_path):
    """Tier caps: host overflow demotes OLDEST-first to the spool;
    disk overflow drops oldest-first — strict LRU at both edges."""
    st = TieredKVStore(host_bytes_cap=5000, disk_bytes_cap=5000,
                       spool_dir=str(tmp_path))
    for i, sid in enumerate(("old", "mid", "new")):
        st.put(sid, _arrays(), own_tokens=8, n_pages=1)   # 2048 B each
        time.sleep(0.01)
    # 3 * 2048 > 5000: the oldest went to disk
    assert st.tier_of("old") == "disk"
    assert st.tier_of("mid") == "host" and st.tier_of("new") == "host"
    # disk read round-trips bytes exactly
    _, arrays = st.get("old")
    assert (arrays["k"] == _arrays()["k"]).all()
    assert st.stats()["disk_hits"] == 1
    # overflow the disk tier too: oldest disk entry is dropped outright
    for i in range(4):
        st.put(f"x{i}", _arrays(), own_tokens=8, n_pages=1)
        time.sleep(0.01)
    stats = st.stats()
    assert stats["disk_drops"] >= 1
    assert not st.has("old"), "oldest entry should have dropped"
    st.clear()
    assert len(st) == 0


def test_spool_roundtrip_preserves_bfloat16(tmp_path):
    """The raw spool format (json header + buffers) must round-trip
    bfloat16 byte-exactly — np.savez can't, which is why it exists."""
    import ml_dtypes

    path = str(tmp_path / "s.kvspool")
    arrays = {
        "k_pages": np.arange(24, dtype=np.float32).astype(
            ml_dtypes.bfloat16).reshape(2, 3, 4),
        "v_pages": np.ones((2, 2), np.int8),
        "k_scale": np.full((2, 2), 0.5, np.float32),
    }
    _write_spool(path, arrays)
    got = _read_spool(path)
    for k, a in arrays.items():
        assert got[k].dtype == a.dtype and got[k].shape == a.shape
        assert got[k].tobytes() == a.tobytes()


def test_store_spool_read_error_degrades_to_miss(tmp_path):
    st = TieredKVStore(host_bytes_cap=0, disk_bytes_cap=1 << 20,
                       spool_dir=str(tmp_path))
    st.put("a", _arrays(), own_tokens=8, n_pages=1)   # demoted at once
    assert st.tier_of("a") == "disk"
    entry = st._entries["a"]
    with open(entry.path, "wb") as f:
        f.write(b"\x10")                               # truncate/corrupt
    assert st.get("a") is None                         # miss, not raise
    assert not st.has("a")
    assert st.stats()["spool_errors"] == 1


# ---- engine round trip (acceptance criteria) ----

def test_offload_releases_pages_and_resume_is_token_identical(
    make_engine,
):
    """THE acceptance canary: a parked session's non-prefix HBM pages
    are measurably released (free-page count rises) and the resumed
    greedy turn matches a never-offloaded control token for token."""
    prompt = list(range(1, 20))
    cont = [7, 7, 7]

    ctrl = make_engine(offload=False)
    c1 = ctrl.submit(prompt, session_id="s", sampling=_greedy())
    ctrl.run_until_idle()
    c2 = ctrl.submit(cont, session_id="s", sampling=_greedy())
    ctrl.run_until_idle()
    _drain(ctrl)

    eng = make_engine()
    t1 = eng.submit(prompt, session_id="s", sampling=_greedy())
    eng.run_until_idle()
    # the tool-call park semantics: session is cold, pages resident
    assert eng.page_table.pages_of("s")
    free_before = eng.page_table.free_pages
    assert eng.offload_session("s")
    assert eng.page_table.free_pages > free_before, (
        "offload must measurably release HBM pages"
    )
    assert not eng.page_table.pages_of("s")
    assert eng.offload_store.tier_of("s") == "host"

    t2 = eng.submit(cont, session_id="s", sampling=_greedy())
    eng.run_until_idle()
    st = eng.stats()
    assert st["offload_restores"] == 1, "resume must restore, not re-prefill"
    assert st["offload_reprefills"] == 0
    assert t1.new_tokens == c1.new_tokens
    assert t2.new_tokens == c2.new_tokens, (
        "offload round trip changed the greedy stream"
    )
    _drain(eng)


def test_resume_from_disk_tier_is_token_identical(
    make_engine, monkeypatch,
):
    monkeypatch.setenv("ROOM_TPU_OFFLOAD_HOST_MB", "0.001")
    prompt = list(range(1, 20))

    ctrl = make_engine(offload=False)
    c1 = ctrl.submit(prompt, session_id="s", sampling=_greedy())
    ctrl.run_until_idle()
    c2 = ctrl.submit([9, 9], session_id="s", sampling=_greedy())
    ctrl.run_until_idle()
    _drain(ctrl)

    eng = make_engine()
    t1 = eng.submit(prompt, session_id="s", sampling=_greedy())
    eng.run_until_idle()
    assert eng.offload_session("s")
    # ~1 KB host cap: the entry demoted straight to the disk spool
    assert eng.offload_store.tier_of("s") == "disk"
    t2 = eng.submit([9, 9], session_id="s", sampling=_greedy())
    eng.run_until_idle()
    assert eng.offload_store.stats()["disk_hits"] == 1
    assert t1.new_tokens == c1.new_tokens
    assert t2.new_tokens == c2.new_tokens
    _drain(eng)


def test_prefetch_restores_queued_session_before_admission(make_engine):
    eng = make_engine()
    eng.submit(list(range(1, 20)), session_id="s", sampling=_greedy())
    eng.run_until_idle()
    assert eng.offload_session("s")
    eng.submit([5, 5], session_id="s", sampling=_greedy())
    # a scheduler step prefetches the queued session's pages back
    # (overlapping restore with decode) before admission prefills
    eng.run_until_idle()
    st = eng.stats()
    assert st["offload_prefetches"] == 1
    assert st["offload_restores"] == 1
    _drain(eng)


def test_watermark_sweep_offloads_coldest_first(make_engine):
    """Pool pressure under the low watermark hibernates cold sessions
    in strict last_used order (coldest first) until the high watermark
    is restored."""
    eng = make_engine(n_pages=96)
    sids = ["cold", "cool", "warm"]
    for i, sid in enumerate(sids):
        eng.submit(list(range(1, 20)), session_id=sid,
                   sampling=_greedy(4))
        eng.run_until_idle()
    # age the sessions explicitly (submission order isn't enough: the
    # engine bumps last_used at finish time too)
    now = time.monotonic()
    eng.sessions["cold"].last_used = now - 30
    eng.sessions["cool"].last_used = now - 20
    eng.sessions["warm"].last_used = now - 10
    # force pressure: pretend the pool is nearly exhausted
    eng.offload_low_wm = 1.1       # always under the low watermark
    eng.offload_high_wm = eng.page_table.free_fraction + \
        len(eng.page_table.pages_of("cold")) / eng.n_pages
    eng._offload_sweep()
    assert eng.offload_store.has("cold"), "coldest must offload first"
    assert not eng.offload_store.has("warm")
    # aggressive rung (ladder level 2) hibernates every cold session
    eng.set_degradation(2)
    eng._offload_sweep()
    assert eng.offload_store.has("cool") and eng.offload_store.has("warm")
    eng.set_degradation(None)
    _drain(eng)


def test_pool_exhaustion_prefers_offload_over_eviction(make_engine):
    """_ensure_capacity_evicting tries hibernation (KV kept, memcpy
    resume) before LRU eviction (KV dropped, re-prefill resume)."""
    eng = make_engine(n_pages=24)       # 23 usable pages
    eng.submit(list(range(1, 40)), session_id="a", sampling=_greedy(4))
    eng.run_until_idle()
    # a second long session can't fit alongside: admission pressure
    # must hibernate "a" rather than evict it
    eng.submit(list(range(1, 80)), session_id="b", sampling=_greedy(4))
    eng.run_until_idle()
    st = eng.stats()
    assert st["offloads"] >= 1
    assert st["evictions"] == 0, (
        "offload must satisfy pressure before eviction drops KV"
    )
    assert eng.offload_store.has("a")
    _drain(eng)


# ---- offload_io fault fallbacks ----

def test_offload_io_fault_fails_back_to_resident(make_engine):
    eng = make_engine()
    eng.submit(list(range(1, 20)), session_id="s", sampling=_greedy())
    eng.run_until_idle()
    free_before = eng.page_table.free_pages
    faults.inject("offload_io", times=eng.fault_retries + 1)
    assert not eng.offload_session("s")
    # fail-back-to-resident: pages untouched, no half-written entry
    assert eng.page_table.free_pages == free_before
    assert eng.page_table.pages_of("s")
    assert not eng.offload_store.has("s")
    assert eng.stats()["offload_resident_fallbacks"] == 1
    faults.clear()
    # the session is still fully serviceable
    t = eng.submit([5], session_id="s", sampling=_greedy())
    eng.run_until_idle()
    assert t.finish_reason in ("stop", "length")
    _drain(eng)


def test_offload_io_transient_fault_is_retried_transparently(
    make_engine,
):
    eng = make_engine()
    eng.submit(list(range(1, 20)), session_id="s", sampling=_greedy())
    eng.run_until_idle()
    faults.inject("offload_io", times=1)     # within the retry budget
    assert eng.offload_session("s")
    assert eng.stats()["fault_retries"] >= 1
    assert eng.offload_store.has("s")
    _drain(eng)


def test_offload_io_restore_fault_falls_back_to_reprefill(make_engine):
    """A restore that outlives its retry budget re-prefills from the
    history mirror — slower, but the greedy stream is unchanged and
    nothing leaks."""
    prompt = list(range(1, 20))
    ctrl = make_engine(offload=False)
    ctrl.submit(prompt, session_id="s", sampling=_greedy())
    ctrl.run_until_idle()
    c2 = ctrl.submit([9, 9], session_id="s", sampling=_greedy())
    ctrl.run_until_idle()
    _drain(ctrl)

    eng = make_engine()
    eng.submit(prompt, session_id="s", sampling=_greedy())
    eng.run_until_idle()
    assert eng.offload_session("s")
    faults.inject("offload_io", times=eng.fault_retries + 1)
    t2 = eng.submit([9, 9], session_id="s", sampling=_greedy())
    eng.run_until_idle()
    faults.clear()
    st = eng.stats()
    assert st["offload_reprefills"] >= 1
    assert not eng.offload_store.has("s")
    assert t2.new_tokens == c2.new_tokens, (
        "re-prefill fallback changed the greedy stream"
    )
    _drain(eng)


def test_dropped_entry_reprefills_from_history(make_engine):
    """A session whose copy was dropped (disk-cap pressure) silently
    rebuilds via re-prefill at its next turn — drops cost compute,
    never correctness or liveness."""
    eng = make_engine()
    eng.submit(list(range(1, 20)), session_id="s", sampling=_greedy())
    eng.run_until_idle()
    assert eng.offload_session("s")
    eng.offload_store.discard("s")       # simulate a cap drop
    t = eng.submit([9, 9], session_id="s", sampling=_greedy())
    eng.run_until_idle()
    assert t.finish_reason in ("stop", "length")
    assert eng.stats()["offload_reprefills"] >= 1
    _drain(eng)


def test_tool_call_park_offloads_and_release_drops_copy(make_engine):
    """Tool-call park semantics drive offload directly; releasing a
    hibernated session drops its host/disk copy with its pages."""
    eng = make_engine()
    eng.submit(list(range(1, 20)), session_id="s", sampling=_greedy())
    eng.run_until_idle()
    sess = eng.sessions["s"]
    sess.parked = True                   # as a </tool_call> stop does
    assert eng.offload_session("s")
    assert eng.offload_store.has("s")
    eng.release_session("s")
    assert not eng.offload_store.has("s")
    assert "s" not in eng.sessions
    _drain(eng)


# ---- health surface ----

def test_health_route_reports_offload_tiers(make_engine, monkeypatch):
    import room_tpu.providers.tpu as tpu_mod
    from room_tpu.server.router import RequestContext, Router
    from room_tpu.server.routes import register_all_routes

    eng = make_engine()
    eng.submit(list(range(1, 20)), session_id="s", sampling=_greedy())
    eng.run_until_idle()
    assert eng.offload_session("s")

    class FakeHost:
        _engine = eng

        @staticmethod
        def is_healthy():
            return True

    monkeypatch.setattr(tpu_mod, "_hosts", {"tiny-moe-off": FakeHost()})
    router = Router()
    register_all_routes(router)
    handler, params = router.match("GET", "/api/tpu/health")
    out = handler(RequestContext(
        method="GET", path="/api/tpu/health", params=params, query={},
        body=None,
    ))
    row = out["data"]["engines"]["tiny-moe-off"]
    assert row["offloads"] == 1
    off = row["offload"]
    assert off["host_entries"] + off["disk_entries"] == 1
    assert "restore_ms_hist" in off and "host_bytes" in off
    _drain(eng)


# ---- quick chaos burst (CI chaos job) ----

def test_offload_chaos_quick(make_engine):
    """~6 s of multi-threaded park/offload/restore churn with
    offload_io armed: no dropped turns, zero page leaks, store
    drained. The >=35 s soak (offload + crashes) lives behind the
    slow marker in test_chaos_serving.py."""
    eng = make_engine(n_pages=64)
    eng.offload_low_wm = 1.1             # every step sweeps...
    eng.offload_high_wm = 1.2            # ...and never stops early
    # warm the jit cache (CPU compiles would eat the whole window)
    warm = []
    for sid in ("w0", "w1", "w2"):
        warm.append(eng.submit([1, 2, 3], session_id=sid,
                               sampling=_greedy(4)))
        eng.run_until_idle()
        warm.append(eng.submit([4, 5], session_id=sid,
                               sampling=_greedy(4)))
        eng.run_until_idle()
        eng.release_session(sid)

    faults.inject("offload_io", probability=0.15, seed=11)
    stop = threading.Event()
    loop = threading.Thread(
        target=eng.serve_forever, args=(stop,), daemon=True
    )
    loop.start()
    errors: list[str] = []
    deadline = time.monotonic() + 6

    def worker(widx):
        sid = f"off-w{widx}"
        i = 0
        while time.monotonic() < deadline:
            i += 1
            t = eng.submit([widx + 1, i % 40 + 1], session_id=sid,
                           sampling=_greedy(4))
            if not t.done.wait(60):
                errors.append(f"worker {widx} hung")
                return
            if i % 5 == 0:
                eng.release_session(sid)

    threads = [
        threading.Thread(target=worker, args=(w,), daemon=True)
        for w in range(3)
    ]
    for th in threads:
        th.start()
    for th in threads:
        th.join(120)
        assert not th.is_alive(), "offload chaos thread wedged"
    assert not errors, errors
    faults.clear()
    drain_deadline = time.monotonic() + 60
    while (eng.stats()["active_slots"] or eng.stats()["queued"]) and \
            time.monotonic() < drain_deadline:
        time.sleep(0.05)
    stop.set()
    loop.join(10)
    st = eng.stats()
    assert st["offloads"] > 0, "chaos burst never exercised offload"
    _drain(eng)
