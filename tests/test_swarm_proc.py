"""Multi-process swarm shard suite (docs/swarmshard.md "Process
mode").

Lockdep-armed suite for the OS-process shard isolation layer: every
shard a supervised child process with its own interpreter/SQLite
handle, cross-shard dispatch riding framed-RTKW control frames under
the journaled exactly-once contract, and the PodMembership-mold
supervisor.  The process-lifecycle scenarios (child spawns, SIGKILLs,
restarts — seconds each) sit behind ``-m slow`` to keep the tier-1
window lean; CI's dedicated swarm-proc step runs the FULL file, no
marker filter.  Covered:

- kill-between-halves: a child SIGKILLed after the outbound half
  committed — the post-restart redelivery dedups the committed half
  and fires ONLY the missing one.
- duplicate-frame redelivery after restart: a byte-identical resend
  lands on the replacement child and both halves dedup against the
  journal rows on disk.
- restart-budget exhaustion degrades to sibling adoption (placement
  rehome + epoch bump) with the shard unhealthy.
- PID-tagged shard lockfiles refuse a double-open while the holder
  lives; a crashed parent's orphans are reaped at the next parent's
  boot before any child re-opens their files.
- graceful drain: SIGTERM commits in-flight halves then exits; a
  SIGTERM-ignoring child is escalated to SIGKILL after the drain
  deadline; ServerRuntime.stop() sweeps every child.
- ``shard_proc_kill`` / ``shard_wire_io`` chaos points recover with
  zero message loss and zero double-fire (docs/chaos.md).
- a process-mode mini swarm_storm with a SIGKILL mid-storm loses
  nothing and double-fires nothing.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from room_tpu.db import Database
from room_tpu.serving import faults, podnet
from room_tpu.swarm import (
    ProcSupervisor, ShardDownError, ShardLockHeld, merge_attributions,
    reset_default_proc, reset_default_router, shard_db_path,
)
from room_tpu.swarm.procshard import (
    acquire_shard_lock, read_shard_lock, release_shard_lock,
)

# tight-but-safe supervisor timings: child boot is ~0.5s, a full
# dead->lease->restart cycle ~1.5s
FAST = dict(suspect_s=0.6, dead_s=1.2, lease_s=0.4,
            backoff_s=0.05, hb_s=0.15)


@pytest.fixture(autouse=True)
def _clean_state():
    faults.clear()
    podnet.reset_breakers()
    reset_default_router()
    reset_default_proc()
    yield
    faults.clear()
    podnet.reset_breakers()
    reset_default_router()
    reset_default_proc()


def _wait_serving(sup, timeout=15.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        snap = sup.snapshot()
        if all(c["state"] == "serving" for c in snap["children"]):
            return snap
        time.sleep(0.1)
    raise AssertionError(
        f"children never all served: {sup.snapshot()['children']}"
    )


def _wait_restarted(sup, shard, old_pid, timeout=25.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        sup.supervise()
        c = sup.snapshot()["children"][shard]
        if c["state"] == "serving" and c["pid"] != old_pid:
            return c
        time.sleep(0.1)
    raise AssertionError(
        f"shard {shard} never restarted: {sup.snapshot()['children']}"
    )


def _send_retrying(sup, *args, timeout=20.0, **kwargs):
    """send_message with ShardDownError retries — the shed window
    while a child restarts is the contract, not a failure."""
    deadline = time.monotonic() + timeout
    while True:
        try:
            return sup.send_message(*args, **kwargs)
        except ShardDownError:
            if time.monotonic() >= deadline:
                raise
            time.sleep(0.1)


def _room_on_home(sup, home):
    """Create rooms until one's id hashes to ``home`` (the
    message/escalation FKs want real rooms)."""
    for i in range(128):
        room = sup.create_room(f"probe-{home}-{i}")
        if sup.base_home(room["id"]) == home:
            return room["id"]
    raise AssertionError("allocator never hit the home")


def _msg_rows(db_path, direction, subject):
    db = Database(db_path)
    try:
        return db.query(
            "SELECT id FROM room_messages WHERE direction=? AND "
            "subject=?", (direction, subject),
        )
    finally:
        db.close()


@pytest.fixture()
def sup(tmp_path):
    s = ProcSupervisor(n_shards=2, db_dir=str(tmp_path), **FAST)
    yield s
    s.stop()


# ---- exactly-once over the wire ----

def test_cross_shard_message_exactly_once_over_wire(sup):
    _wait_serving(sup)
    a = sup.create_room("alpha")
    b = sup.create_room("beta")
    out1, in1 = sup.send_message(a["id"], b["id"], "s1", "b1")
    out2, in2 = sup.send_message(a["id"], b["id"], "s1", "b1")
    assert (out1, in1) == (out2, in2)
    assert sup.stats["dedup_skips"] == 2
    eid1 = sup.escalate(a["id"], "why?")
    eid2 = sup.escalate(a["id"], "why?")
    assert eid1 == eid2


@pytest.mark.slow
def test_kill_between_halves_fires_only_missing_half(sup, tmp_path):
    """The out-half commits, the DESTINATION child dies before the
    in-half: after the restart, the full resend dedups the committed
    half and fires exactly the missing one."""
    _wait_serving(sup)
    src_rid = _room_on_home(sup, 0)
    dst_rid = _room_on_home(sup, 1)
    args = {"from": src_rid, "to": dst_rid,
            "subject": "half", "body": "payload"}
    # the first half, exactly as send_message would fire it
    out1, dup = sup._xshard(0, "xshard_msg_out", args, src_rid, None)
    assert not dup
    victim = sup.snapshot()["children"][1]
    os.kill(victim["pid"], signal.SIGKILL)
    _wait_restarted(sup, 1, victim["pid"])
    out2, in2 = _send_retrying(
        sup, src_rid, dst_rid, "half", "payload"
    )
    assert out2 == int(out1)          # committed half deduped
    assert len(_msg_rows(shard_db_path(0, str(tmp_path)),
                         "outbound", "half")) == 1
    assert len(_msg_rows(shard_db_path(1, str(tmp_path)),
                         "inbound", "half")) == 1


@pytest.mark.slow
def test_duplicate_redelivery_after_restart_dedups(sup, tmp_path):
    """A byte-identical resend after the child restarted dedups BOTH
    halves against the journal rows on disk (the replacement process
    reads the same file)."""
    _wait_serving(sup)
    src_rid = _room_on_home(sup, 0)
    dst_rid = _room_on_home(sup, 1)
    first = sup.send_message(src_rid, dst_rid, "dup", "again")
    victim = sup.snapshot()["children"][1]
    os.kill(victim["pid"], signal.SIGKILL)
    _wait_restarted(sup, 1, victim["pid"])
    second = _send_retrying(sup, src_rid, dst_rid, "dup", "again")
    assert first == second
    assert len(_msg_rows(shard_db_path(1, str(tmp_path)),
                         "inbound", "dup")) == 1


@pytest.mark.slow
def test_restart_rearms_membership_and_counts(sup):
    _wait_serving(sup)
    victim = sup.snapshot()["children"][1]
    os.kill(victim["pid"], signal.SIGKILL)
    c = _wait_restarted(sup, 1, victim["pid"])
    assert sup.stats["restarts"] == 1
    assert c["restarts_in_window"] == 1
    # the replacement's heartbeats keep the member alive
    time.sleep(0.5)
    sup.supervise()
    assert sup.snapshot()["children"][1]["state"] == "serving"


# ---- budget exhaustion -> sibling adoption ----

@pytest.mark.slow
def test_budget_exhaustion_degrades_to_adoption(tmp_path):
    sup = ProcSupervisor(n_shards=2, db_dir=str(tmp_path),
                         restart_budget=0, **FAST)
    try:
        _wait_serving(sup)
        epoch0 = sup.placement.epoch
        victim = sup.snapshot()["children"][1]
        os.kill(victim["pid"], signal.SIGKILL)
        deadline = time.monotonic() + 25
        adoptions = []
        while time.monotonic() < deadline and not adoptions:
            adoptions = sup.supervise()
            time.sleep(0.1)
        assert adoptions and adoptions[0]["shard"] == 1
        assert adoptions[0]["adopter"] == 0
        assert sup.placement.epoch > epoch0
        assert sup.unhealthy_shards() == [1]
        assert sup.snapshot()["children"][1]["state"] == "failed"
        # dispatch to the dead shard's homes lands on the adopter
        rid = _room_on_home(sup, 1)
        out, dup = sup._xshard(
            1, "xshard_msg_in",
            {"from": 1, "to": rid, "subject": "x", "body": "y"},
            rid, None,
        )
        assert out and not dup
        # the row landed in the DEAD shard's file, written by the
        # adopter child — visible both on disk and over the wire
        assert len(_msg_rows(shard_db_path(1, str(tmp_path)),
                             "inbound", "x")) == 1
        got = sup.query(
            1, "SELECT COUNT(*) AS n FROM room_messages WHERE "
            "direction='inbound' AND subject='x'",
        )
        assert got[0]["n"] == 1
    finally:
        sup.stop()


# ---- lockfiles + orphan reap ----

def test_lockfile_refuses_live_holder_and_heals_stale(tmp_path):
    db_path = shard_db_path(0, str(tmp_path))
    acquire_shard_lock(db_path, 0)
    assert read_shard_lock(db_path)["pid"] == os.getpid()
    # a stale lock (dead pid) is silently replaced
    with open(db_path + ".lock", "w") as f:
        json.dump({"pid": 2 ** 22 + 12345, "shard": 0, "ts": 0}, f)
    acquire_shard_lock(db_path, 0)
    assert read_shard_lock(db_path)["pid"] == os.getpid()
    release_shard_lock(db_path)
    assert read_shard_lock(db_path) is None


@pytest.mark.slow
def test_child_process_refuses_held_lockfile(sup, tmp_path):
    """A second child for a LIVE shard exits 3 without touching the
    file — the restarted-parent double-open guard."""
    _wait_serving(sup)
    with pytest.raises(ShardLockHeld):
        acquire_shard_lock(shard_db_path(1, str(tmp_path)), 1)
    proc = subprocess.run(
        [sys.executable, "-m", "room_tpu.swarm.procshard",
         "--shard", "1", "--db-dir", str(tmp_path)],
        capture_output=True, text=True, timeout=60,
        cwd=os.path.dirname(os.path.dirname(__file__)),
    )
    assert proc.returncode == 3
    assert "refusing to start" in proc.stderr


def test_parent_crash_orphans_reaped_at_next_boot(tmp_path):
    """A lockfile naming a live PID from a crashed parent's child is
    killed + cleared before the new parent spawns anything."""
    sleeper = subprocess.Popen(["sleep", "300"])
    try:
        db_path = shard_db_path(0, str(tmp_path))
        with open(db_path + ".lock", "w") as f:
            json.dump({"pid": sleeper.pid, "shard": 0, "ts": 0}, f)
        sup = ProcSupervisor(n_shards=2, db_dir=str(tmp_path),
                             spawn=False, **FAST)
        try:
            assert sup.stats["orphans_reaped"] == 1
            assert read_shard_lock(db_path) is None
            sleeper.wait(timeout=5)
            assert sleeper.returncode is not None
        finally:
            sup.stop()
    finally:
        if sleeper.poll() is None:
            sleeper.kill()
            sleeper.wait()


# ---- drain + forced kill ----

@pytest.mark.slow
def test_graceful_stop_drains_children(sup):
    snap = _wait_serving(sup)
    pids = [c["pid"] for c in snap["children"]]
    out = sup.stop()
    assert out["stopped"] == 2 and out["forced_kills"] == 0
    for pid in pids:
        with pytest.raises(ProcessLookupError):
            os.kill(pid, 0)


@pytest.mark.slow
def test_sigterm_ignoring_child_gets_forced_kill(tmp_path):
    """A wedged child — deaf to the drain frame AND SIGTERM (the
    ``ROOM_TPU_SWARM_PROC_IGNORE_TERM`` seam) — is SIGKILLed after
    the drain deadline instead of hanging the parent."""
    sup = ProcSupervisor(
        n_shards=2, db_dir=str(tmp_path), drain_s=1.0,
        child_env={"ROOM_TPU_SWARM_PROC_IGNORE_TERM": "1"}, **FAST,
    )
    stopped = False
    try:
        snap = _wait_serving(sup)
        pids = [c["pid"] for c in snap["children"]]
        t0 = time.monotonic()
        out = sup.stop()
        stopped = True
        assert out["forced_kills"] == 2
        assert time.monotonic() - t0 < 10
        for pid in pids:
            with pytest.raises(ProcessLookupError):
                os.kill(pid, 0)
    finally:
        if not stopped:
            sup.stop()


@pytest.mark.slow
def test_runtime_stop_terminates_shard_children(monkeypatch, tmp_path):
    """ServerRuntime.stop() sweeps the shard children BEFORE the
    generic managed-process pass — the parent's clean shutdown
    terminates every shard process."""
    import room_tpu.swarm.procshard as procshard_mod
    from room_tpu.server.runtime import ServerRuntime

    monkeypatch.setenv("ROOM_TPU_SWARM_PROC", "1")
    monkeypatch.setenv("ROOM_TPU_SWARM_SHARDS", "2")
    sup = ProcSupervisor(n_shards=2, db_dir=str(tmp_path / "sw"),
                         **FAST)
    procshard_mod._default_proc = sup
    snap = _wait_serving(sup)
    pids = [c["pid"] for c in snap["children"]]
    rt = ServerRuntime(db=Database(str(tmp_path / "main.db")))
    rt.supervision_tick()       # proc.supervise() on the tick path
    rt.stop()
    for pid in pids:
        with pytest.raises(ProcessLookupError):
            os.kill(pid, 0)


# ---- chaos fault points ----

@pytest.mark.slow
def test_shard_proc_kill_fault_recovers_exactly_once(sup, tmp_path):
    """faults.inject('shard_proc_kill') SIGKILLs a live child at the
    next supervise; restart + journal replay keep the traffic
    exactly-once."""
    _wait_serving(sup)
    src_rid = _room_on_home(sup, 0)
    dst_rid = _room_on_home(sup, 1)
    sup.send_message(src_rid, dst_rid, "chaos", "one")
    before = {c["shard"]: c["pid"]
              for c in sup.snapshot()["children"]}
    faults.inject("shard_proc_kill", times=1)
    sup.supervise()
    assert faults.fired("shard_proc_kill") == 1
    assert sup.stats["proc_kills"] == 1
    # some child died; wait until every shard serves again (restart)
    deadline = time.monotonic() + 25
    while time.monotonic() < deadline:
        sup.supervise()
        snap = sup.snapshot()
        if all(c["state"] == "serving" for c in snap["children"]) \
                and any(c["pid"] != before[c["shard"]]
                        for c in snap["children"]):
            break
        time.sleep(0.1)
    else:
        raise AssertionError(snap["children"])
    second = _send_retrying(sup, src_rid, dst_rid, "chaos", "one")
    assert second == _send_retrying(
        sup, src_rid, dst_rid, "chaos", "one"
    )
    assert len(_msg_rows(shard_db_path(1, str(tmp_path)),
                         "inbound", "chaos")) == 1


@pytest.mark.slow
def test_shard_wire_io_fault_retries_without_double_fire(sup,
                                                         tmp_path):
    """A failed dispatch frame is retried — safe because the frame's
    journal key dedups a half that actually landed."""
    _wait_serving(sup)
    src_rid = _room_on_home(sup, 0)
    dst_rid = _room_on_home(sup, 1)
    faults.inject("shard_wire_io", times=1)
    out, inn = sup.send_message(src_rid, dst_rid, "wio", "b")
    assert faults.fired("shard_wire_io") == 1
    assert sup.stats["wire_retries"] >= 1
    assert out and inn
    assert len(_msg_rows(shard_db_path(0, str(tmp_path)),
                         "outbound", "wio")) == 1
    assert len(_msg_rows(shard_db_path(1, str(tmp_path)),
                         "inbound", "wio")) == 1


# ---- process-mode storm: zero loss, zero double-fire ----

@pytest.mark.slow
def test_proc_storm_with_midstorm_kill_zero_loss(tmp_path):
    """A mini process-mode swarm_storm: concurrent cross-shard sends
    with a SIGKILL mid-storm and a supervise loop running; every
    message lands exactly once on both sides."""
    sup = ProcSupervisor(n_shards=2, db_dir=str(tmp_path), **FAST)
    try:
        _wait_serving(sup)
        src_rid = _room_on_home(sup, 0)
        dst_rid = _room_on_home(sup, 1)
        stop = threading.Event()

        def supervise_loop():
            while not stop.is_set():
                sup.supervise()
                time.sleep(0.05)

        sup_thread = threading.Thread(target=supervise_loop,
                                      daemon=True)
        sup_thread.start()
        n_msgs, errors = 24, []

        def storm(start, count):
            for i in range(start, start + count):
                try:
                    _send_retrying(
                        sup, src_rid, dst_rid, f"storm-{i}", "b",
                        timeout=30,
                    )
                except Exception as e:   # noqa: BLE001
                    errors.append((i, repr(e)))

        threads = [
            threading.Thread(target=storm, args=(s, 8))
            for s in range(0, n_msgs, 8)
        ]
        for t in threads:
            t.start()
        time.sleep(0.3)
        victim = sup.snapshot()["children"][1]
        if victim["pid"] is not None:
            try:
                os.kill(victim["pid"], signal.SIGKILL)
            except ProcessLookupError:
                pass
        for t in threads:
            t.join(timeout=60)
        stop.set()
        sup_thread.join(timeout=5)
        assert not errors, errors
    finally:
        sup.stop()
    # accounting straight off the files: zero lost, zero double-fired
    out_db = Database(shard_db_path(0, str(tmp_path)))
    in_db = Database(shard_db_path(1, str(tmp_path)))
    try:
        for i in range(24):
            outs = out_db.query(
                "SELECT id FROM room_messages WHERE direction="
                "'outbound' AND subject=?", (f"storm-{i}",),
            )
            ins = in_db.query(
                "SELECT id FROM room_messages WHERE direction="
                "'inbound' AND subject=?", (f"storm-{i}",),
            )
            assert len(outs) == 1, (i, len(outs))   # no loss, no dup
            assert len(ins) == 1, (i, len(ins))
    finally:
        out_db.close()
        in_db.close()


# ---- SLO merge + surfaces ----

def test_merge_attributions_sums_and_reweights():
    a = {"finished_turns": 2, "classes": {"queen": {
        "turns": 2, "errors": 1, "queue_ms": 10.0,
        "ttft_ms_mean": 100.0, "ttft_violations": 0,
    }}}
    b = {"finished_turns": 6, "classes": {"queen": {
        "turns": 6, "errors": 0, "queue_ms": 30.0,
        "ttft_ms_mean": 200.0, "ttft_violations": 2,
    }, "worker": {"turns": 1, "errors": 0, "queue_ms": 5.0,
                  "ttft_ms_mean": None, "ttft_violations": 0}}}
    m = merge_attributions([a, b, None, "junk"])
    assert m["finished_turns"] == 8
    q = m["classes"]["queen"]
    assert q["turns"] == 8 and q["errors"] == 1
    assert q["queue_ms"] == 40.0 and q["ttft_violations"] == 2
    assert q["ttft_ms_mean"] == 175.0    # (100*2 + 200*6) / 8
    assert m["classes"]["worker"]["turns"] == 1
    assert "ttft_ms_mean" not in m["classes"]["worker"]


def test_snapshot_and_metrics_surface(sup):
    import room_tpu.swarm.procshard as procshard_mod
    from room_tpu.server.metrics import render_metrics

    _wait_serving(sup)
    a = sup.create_room("alpha")
    b = sup.create_room("beta")
    sup.send_message(a["id"], b["id"], "m", "b")
    snap = sup.snapshot()
    assert snap["mode"] == "proc" and snap["n_shards"] == 2
    assert {c["shard"] for c in snap["children"]} == {0, 1}
    assert "slo" in snap and "classes" in snap["slo"]
    procshard_mod._default_proc = sup
    try:
        text = render_metrics()
        assert "room_tpu_swarm_proc{" in text
        assert 'stat="serving"' in text
    finally:
        procshard_mod._default_proc = None


def test_maybe_default_router_gated_off_in_proc_mode(monkeypatch):
    from room_tpu.swarm import maybe_default_router

    monkeypatch.setenv("ROOM_TPU_SWARM_PROC", "1")
    monkeypatch.setenv("ROOM_TPU_SWARM_SHARDS", "4")
    assert maybe_default_router() is None


# ---- external mode: shard children as separate containers ----

def _launch_external(shard, db_dir, parent_port):
    return subprocess.Popen(
        [sys.executable, "-m", "room_tpu.swarm.procshard",
         "--shard", str(shard), "--db-dir", db_dir,
         "--parent", f"127.0.0.1:{parent_port}", "--hb-s", "0.15"],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )


@pytest.mark.slow
def test_external_mode_supervises_foreign_children(tmp_path):
    """ROOM_TPU_SWARM_PROC_EXTERNAL deployment shape (compose/k8s
    shard containers): children the parent never spawned register by
    heartbeat, dispatch exactly-once works unchanged, a killed child
    opens its slot after the budgeted backoff for the container
    runtime's replacement, and stop() drains over the wire without
    ever signalling a foreign PID."""
    sup = ProcSupervisor(n_shards=2, db_dir=str(tmp_path),
                         external=True, **FAST)
    kids = {}
    try:
        assert sup.external and sup.snapshot()["external"]
        port = sup.server.address[1]
        kids = {k: _launch_external(k, str(tmp_path), port)
                for k in (0, 1)}
        _wait_serving(sup)
        a = sup.create_room("ext-a")
        b = sup.create_room("ext-b")
        out1, in1 = sup.send_message(a["id"], b["id"], "e1", "b1")
        out2, in2 = sup.send_message(a["id"], b["id"], "e1", "b1")
        assert (out1, in1) == (out2, in2)

        # 'the container runtime' relaunches what the supervisor
        # cannot: the kill is ours, the respawn is the test's
        old_pid = sup.snapshot()["children"][1]["pid"]
        kids[1].send_signal(signal.SIGKILL)
        kids[1].wait()
        relaunched = False
        deadline = time.monotonic() + 25.0
        while time.monotonic() < deadline:
            sup.supervise()
            snap = sup.snapshot()
            c1 = snap["children"][1]
            if not relaunched and snap["restarts"] >= 1 and \
                    c1["state"] == "starting":
                assert c1["pid"] is None   # slot opened, not killed
                kids[1] = _launch_external(1, str(tmp_path), port)
                relaunched = True
            if relaunched and c1["state"] == "serving" and \
                    c1["pid"] != old_pid:
                break
            time.sleep(0.05)
        c1 = sup.snapshot()["children"][1]
        assert c1["state"] == "serving" and c1["pid"] != old_pid, c1
        _send_retrying(sup, a["id"], b["id"], "e2", "b2")

        res = sup.stop()
        assert res["forced_kills"] == 0
        for k, p in kids.items():
            p.wait(timeout=10)   # drain frame alone stopped them
    finally:
        sup.stop()
        for p in kids.values():
            if p.poll() is None:
                p.kill()
