"""Spec-drafting acceptance on realistic traffic (VERDICT r4 #5):
the offline replay must mirror the engine's acceptance rule, and the
per-class numbers behind the gamma default must be reproducible."""

import os

import jax
import pytest

from room_tpu.models import qwen3, tiny_moe
from room_tpu.serving import SamplingParams, ServingEngine
from room_tpu.serving.spec_replay import ReplayStats, replay_acceptance
from room_tpu.serving.tokenizer import ByteTokenizer

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "traffic")


def load_class(name: str, split: float = 0.5):
    toks = ByteTokenizer().encode(
        open(os.path.join(FIXTURES, name + ".txt")).read()
    )
    cut = int(len(toks) * split)
    return toks[:cut], toks[cut:]


def test_pure_repetition_accepts_everything():
    hist = [1, 2, 3, 4] * 8
    cont = [1, 2, 3, 4] * 16
    st = replay_acceptance(hist, cont, gamma=4)
    assert st.acceptance == 1.0
    assert st.plain_steps == 0
    assert st.tokens_per_forward == pytest.approx(5.0, rel=0.1)
    # first continuation token is prefill-emitted, not decode-emitted
    assert st.emitted == len(cont) - 1


def test_no_repetition_never_drafts():
    # strictly increasing tokens: no trailing n-gram ever recurs
    hist = list(range(100))
    cont = list(range(100, 164))
    st = replay_acceptance(hist, cont, gamma=4)
    assert st.rounds == 0
    assert st.proposed == 0
    assert st.tokens_per_forward == 1.0  # degrades to sequential
    assert st.emitted == len(cont) - 1


def test_emitted_always_equals_continuation():
    hist, cont = load_class("toolcalls")
    for gamma in (2, 4, 8):
        st = replay_acceptance(hist, cont, gamma)
        assert st.emitted == len(cont) - 1
        assert 0.0 <= st.acceptance <= 1.0


def test_gamma_must_be_positive():
    with pytest.raises(ValueError):
        replay_acceptance([1, 2], [3], 0)


def test_replay_matches_live_engine_counters():
    """Replay the engine's own greedy output: proposed/accepted must
    equal the engine's spec telemetry exactly — the offline numbers
    are only meaningful if the replay IS the engine's rule."""
    cfg = tiny_moe(vocab_size=8)
    params = qwen3.init_params(cfg, jax.random.PRNGKey(3))
    prompt = [1, 2, 3, 1, 2, 3]
    sp = SamplingParams(temperature=0.0, max_new_tokens=32)

    eng = ServingEngine(cfg, params, max_batch=4, page_size=8,
                        n_pages=64, spec_tokens=4)
    turn = eng.submit(prompt, sampling=sp)
    eng.run_until_idle()
    st = eng.stats()
    assert st["spec_rounds"] > 0  # drafting actually engaged

    rp = replay_acceptance(prompt, turn.new_tokens, gamma=4)
    assert rp.proposed == st["spec_proposed"]
    assert rp.accepted == st["spec_accepted"]
    assert rp.rounds == st["spec_rounds"]


def test_per_class_acceptance_ordering():
    """The claim behind keeping spec on by default: agent tool-call
    traffic accepts drafts at high rate, prose at low rate — and the
    no-draft fallback means low-acceptance classes mostly degrade to
    plain steps rather than paying failed verifies."""
    rates = {}
    engage = {}
    for cls in ("prose", "code", "toolcalls"):
        hist, cont = load_class(cls)
        st = replay_acceptance(hist, cont, gamma=4)
        rates[cls] = st.acceptance
        engage[cls] = st.draft_engage_rate
    assert rates["toolcalls"] > rates["prose"]
    assert rates["code"] > rates["prose"]
    # tool-call traffic must actually speed up end-to-end
    hist, cont = load_class("toolcalls")
    assert replay_acceptance(hist, cont, 4).tokens_per_forward > 1.5


def test_replay_tail_bound_matches_device_tail():
    """The engine drafts from a bounded device tail
    (ROOM_TPU_SPEC_TAIL, default 256): an n-gram whose only earlier
    occurrence lies further back than the tail is invisible to live
    drafting, so replay — the number behind the deployment gamma
    default — must not credit it either. A wide tail still sees it."""
    filler = list(range(1000, 1300))
    hist = [7, 8, 9, 41, 42, 43, 44] + filler + [7, 8, 9]
    cont = [41, 42, 43, 44, 5]
    wide = replay_acceptance(hist, cont, 4, tail=4096)
    assert wide.accepted >= 3
    bounded = replay_acceptance(hist, cont, 4)   # default: engine's 256
    assert bounded.accepted == 0
    assert bounded.plain_steps == len(cont) - 1


def test_tokens_per_forward_bounded_by_gamma_plus_one():
    for cls in ("prose", "code", "toolcalls"):
        hist, cont = load_class(cls)
        for gamma in (2, 4):
            st = replay_acceptance(hist, cont, gamma)
            assert st.tokens_per_forward <= gamma + 1


def test_stats_properties_empty():
    st = ReplayStats()
    assert st.acceptance == 0.0
    assert st.tokens_per_forward == 0.0
    assert st.draft_engage_rate == 0.0


def test_accept_floor_shapes():
    """The roofline throttle floor: high for the 128-expert MoE at
    small batch (verify rounds inflate expert reads), zero for dense
    (verify ~free when bandwidth-bound), falling with batch."""
    from room_tpu.models.config import qwen2_72b, qwen3_coder_30b
    from room_tpu.perf.roofline import spec_accept_floor

    moe = qwen3_coder_30b()
    assert spec_accept_floor(moe, 8, 4) > 0.4
    assert spec_accept_floor(moe, 32, 4) < spec_accept_floor(moe, 8, 4)
    assert spec_accept_floor(qwen2_72b(), 8, 4) == 0.0


def test_replay_throttle_reduces_rounds():
    hist, cont = load_class("prose")
    free = replay_acceptance(hist, cont, 4)
    throttled = replay_acceptance(hist, cont, 4, min_accept=0.56)
    assert throttled.throttles > 0
    assert throttled.rounds < free.rounds
    assert throttled.emitted == free.emitted  # output unchanged


def test_engine_throttle_engages_and_preserves_tokens(monkeypatch):
    """With an impossible acceptance floor the class tuner drives the
    turn's class to spec-off; generated tokens must be identical to
    the unthrottled engine (the throttle changes cost, never
    content). The off decision lands at a window drain (one window
    after the acceptance evidence, the pipelined-tuner lag), so the
    run must be long enough to decode several windows past it."""
    cfg = tiny_moe(vocab_size=8)
    params = qwen3.init_params(cfg, jax.random.PRNGKey(3))
    prompt = [1, 2, 3, 1, 2, 3]
    sp = SamplingParams(temperature=0.0, max_new_tokens=128)

    base = ServingEngine(cfg, params, max_batch=4, page_size=8,
                         n_pages=64, spec_tokens=4)
    want = base.submit(prompt, sampling=sp)
    base.run_until_idle()
    assert base.stats()["spec_throttles"] == 0

    monkeypatch.setenv("ROOM_TPU_SPEC_MIN_ACCEPT", "1.1")
    monkeypatch.setenv("ROOM_TPU_SPEC_COOLDOWN", "16")
    monkeypatch.setenv("ROOM_TPU_SPEC_TUNE_EVERY", "8")
    eng = ServingEngine(cfg, params, max_batch=4, page_size=8,
                        n_pages=64, spec_tokens=4)
    turn = eng.submit(prompt, sampling=sp)
    eng.run_until_idle()
    st = eng.stats()
    assert st["spec_throttles"] > 0
    assert eng.spec_tuner.snapshot()["worker"]["off"] is True
    assert turn.new_tokens == want.new_tokens
    # throttled windows decode plainly: fewer verify rounds than free
    assert st["spec_rounds"] < base.stats()["spec_rounds"]


def test_bpe_tokenizer_preserves_class_ordering():
    """The gamma default's evidence must not be a byte-tokenizer
    artifact: under the qwen-style mini-BPE the per-class acceptance
    ordering (prose < toolcalls/code) and the code-class uplift
    survive."""
    from room_tpu.serving.tokenizer import HFTokenizer

    tok = HFTokenizer(os.path.join(FIXTURES, "..",
                                   "qwen_mini_tokenizer"))
    rates = {}
    tpf = {}
    for cls in ("prose", "code", "toolcalls"):
        toks = tok.encode(
            open(os.path.join(FIXTURES, cls + ".txt")).read()
        )
        cut = len(toks) // 2
        st = replay_acceptance(toks[:cut], toks[cut:], 4)
        rates[cls] = st.acceptance
        tpf[cls] = st.tokens_per_forward
    assert rates["prose"] < rates["toolcalls"]
    assert rates["prose"] < rates["code"]
    assert tpf["code"] > 1.5
