"""MCP server tests: the JSON-RPC surface in-process (reference pattern:
a fake McpServer harness capturing handlers, src/mcp/tools/__tests__/)
plus one真 stdio round-trip via subprocess."""

import json
import os
import subprocess
import sys

import pytest

from room_tpu.core import rooms, task_runner
from room_tpu.mcp.server import McpServer, tools_list_payload
from room_tpu.mcp.tools import TOOLS


@pytest.fixture()
def mcp(db):
    return McpServer(db=db)


def call(mcp, name, args=None, msg_id=1):
    resp = mcp.handle({
        "jsonrpc": "2.0", "id": msg_id, "method": "tools/call",
        "params": {"name": name, "arguments": args or {}},
    })
    content = resp["result"]["content"][0]["text"]
    return content, resp["result"].get("isError", False)


def test_initialize_and_list(mcp):
    resp = mcp.handle({"jsonrpc": "2.0", "id": 1,
                       "method": "initialize", "params": {}})
    assert resp["result"]["serverInfo"]["name"] == "room-tpu"
    resp = mcp.handle({"jsonrpc": "2.0", "id": 2, "method": "tools/list"})
    names = {t["name"] for t in resp["result"]["tools"]}
    # the catalog covers the reference's tool families
    for expected in ("room_create", "worker_create", "goal_create",
                     "memory_remember", "memory_recall", "quorum_vote",
                     "schedule_task", "skill_create", "selfmod_audit",
                     "wallet_info", "wip_save", "setting_set",
                     "system_resources", "escalation_answer"):
        assert expected in names, expected
    assert len(names) >= 30


def test_every_tool_has_valid_schema():
    for name, desc, schema, fn in TOOLS:
        assert schema["type"] == "object"
        assert desc
        for req in schema.get("required", []):
            assert req in schema["properties"], (name, req)


def test_room_lifecycle_via_tools(mcp, db):
    out, is_err = call(mcp, "room_create",
                       {"name": "mcp-room", "goal": "test mcp"})
    assert not is_err and "room #1" in out
    out, _ = call(mcp, "room_list")
    assert "mcp-room" in out
    out, _ = call(mcp, "worker_create",
                  {"room_id": 1, "name": "W", "role": "executor"})
    assert "worker #" in out
    out, _ = call(mcp, "goal_create",
                  {"room_id": 1, "description": "subgoal"})
    out, _ = call(mcp, "goal_tree", {"room_id": 1})
    assert "subgoal" in out
    out, _ = call(mcp, "memory_remember",
                  {"name": "fact", "content": "the sky is blue",
                   "room_id": 1})
    out, _ = call(mcp, "memory_recall", {"query": "sky", "room_id": 1})
    assert "fact" in out


def test_scheduler_tools(mcp, db):
    out, _ = call(mcp, "schedule_task",
                  {"name": "daily", "prompt": "do it",
                   "cron_expression": "0 9 * * *"})
    assert "webhook" in out
    out, _ = call(mcp, "cron_validate", {"expression": "0 9 * * *"})
    assert out == "valid"
    out, _ = call(mcp, "cron_validate", {"expression": "nope"})
    assert "cron" in out
    out, _ = call(mcp, "task_list", {})
    assert "daily" in out


def test_missing_required_args(mcp):
    out, is_err = call(mcp, "room_create", {})
    assert is_err and "name" in out


def test_unknown_tool_and_method(mcp):
    resp = mcp.handle({"jsonrpc": "2.0", "id": 1, "method": "tools/call",
                       "params": {"name": "nope"}})
    assert resp["error"]["code"] == -32602
    resp = mcp.handle({"jsonrpc": "2.0", "id": 2, "method": "bogus"})
    assert resp["error"]["code"] == -32601


def test_tool_exception_becomes_is_error(mcp, db):
    # room_status on a non-integer id raises ValueError inside the tool
    out, is_err = call(mcp, "room_status", {"room_id": "not-a-number"})
    assert is_err and "ValueError" in out


def test_stdio_round_trip(tmp_path):
    """Real process: spawn the MCP server over stdio against a temp DB
    and drive initialize -> tools/list -> tools/call."""
    env = dict(os.environ)
    env["ROOM_TPU_DB_PATH"] = str(tmp_path / "mcp.db")
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [sys.executable, "-m", "room_tpu.cli.main", "mcp"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True,
        env=env, cwd="/root/repo",
    )
    msgs = [
        {"jsonrpc": "2.0", "id": 1, "method": "initialize", "params": {}},
        {"jsonrpc": "2.0", "id": 2, "method": "tools/call",
         "params": {"name": "room_create",
                    "arguments": {"name": "stdio-room"}}},
        {"jsonrpc": "2.0", "id": 3, "method": "tools/call",
         "params": {"name": "room_list", "arguments": {}}},
    ]
    input_text = "".join(json.dumps(m) + "\n" for m in msgs)
    out, _ = proc.communicate(input_text, timeout=60)
    lines = [json.loads(l) for l in out.strip().splitlines()]
    assert lines[0]["result"]["protocolVersion"]
    assert "room #1 created" in lines[1]["result"]["content"][0]["text"]
    assert "stdio-room" in lines[2]["result"]["content"][0]["text"]
    assert proc.returncode == 0
