"""SLO-aware request scheduler (docs/scheduler.md).

Chunked prefill must be GREEDY-TOKEN-IDENTICAL to monolithic prefill —
across chunk sizes {1 page, 4 pages, full} × steps_per_dispatch {1, 4}
× prefix-hit × offload-restore × mid-prefill disruption (fault requeue,
pool-pressure deferral, drain) — because chunking only moves WHEN KV is
written, never which values land at which positions. Priority classes
must order admission (a background prefill cannot starve a queen turn),
shedding (background before workers before queens), and per-class chunk
budgets. Quick tier: runs in the ci.yml chaos job.
"""

import threading

import jax
import pytest

from room_tpu.models import qwen3, tiny_moe
from room_tpu.serving import SamplingParams, ServingEngine, faults
from room_tpu.serving.scheduler import (
    RequestScheduler, class_chunks_from_env, class_targets_from_env,
    normalize_class,
)

CHUNK_PAGES = (0, 1, 4)   # 0 = monolithic (pre-scheduler behavior)
STEPS = (1, 4)


@pytest.fixture(scope="module")
def model():
    cfg = tiny_moe()
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture()
def build(model, monkeypatch):
    cfg, params = model

    def make(chunk_pages, steps=4, **kw):
        monkeypatch.setenv(
            "ROOM_TPU_PREFILL_CHUNK_PAGES", str(chunk_pages)
        )
        monkeypatch.setenv(
            "ROOM_TPU_DECODE_STEPS_PER_DISPATCH", str(steps)
        )
        kw.setdefault("max_batch", 4)
        kw.setdefault("page_size", 8)
        kw.setdefault("n_pages", 128)
        return ServingEngine(cfg, params, **kw)

    return make


def _greedy(n=6):
    return SamplingParams(temperature=0.0, max_new_tokens=n)


LONG = [1 + (i % 53) for i in range(100)]   # 13 pages at page_size 8


# ---- token identity: chunk size × pipeline depth matrix ----

def test_identity_chunk_sizes_x_steps(build):
    """The acceptance matrix: greedy output identical across
    {monolithic, 1-page, 4-page} chunking × steps_per_dispatch {1,4},
    including a session continuation on top of the chunked prefill."""
    base = None
    for steps in STEPS:
        for pages in CHUNK_PAGES:
            eng = build(pages, steps=steps)
            a = eng.submit(LONG, session_id="s", sampling=_greedy())
            eng.run_until_idle()
            b = eng.submit([7, 8, 9], session_id="s",
                           sampling=_greedy())
            eng.run_until_idle()
            got = (a.new_tokens, b.new_tokens)
            if base is None:
                base = got
            assert got == base, f"pages={pages} steps={steps}"
            if pages:
                assert eng.stats()["prefill_chunks_interleaved"] > 0


def test_identity_prefix_hit_under_chunking(build):
    """A second session whose prompt starts with the first's cached
    page-aligned prefix must stream identically whether the registering
    prefill was chunked or monolithic."""
    prefix = list(range(1, 41))             # 5 aligned pages
    base = None
    for pages in CHUNK_PAGES:
        eng = build(pages)
        t1 = eng.submit(prefix + [61, 62, 63], sampling=_greedy())
        eng.run_until_idle()
        t2 = eng.submit(prefix + [71, 72], sampling=_greedy())
        eng.run_until_idle()
        assert eng.stats()["prefix_hits"] >= 1
        got = (t1.new_tokens, t2.new_tokens)
        if base is None:
            base = got
        assert got == base, f"pages={pages}"


def test_identity_offload_restore_then_chunked_continuation(build):
    """Hibernate a session, then resume it with a long (chunked)
    continuation prompt: the restored-KV + chunk-written continuation
    must match the monolithic engine exactly."""
    base = None
    for pages in CHUNK_PAGES:
        eng = build(pages, offload=True)
        t1 = eng.submit(list(range(1, 20)), session_id="h",
                        sampling=_greedy())
        eng.run_until_idle()
        assert eng.offload_session("h")
        t2 = eng.submit(LONG, session_id="h", sampling=_greedy())
        eng.run_until_idle()
        assert eng.stats()["offload_restores"] >= 1
        got = (t1.new_tokens, t2.new_tokens)
        if base is None:
            base = got
        assert got == base, f"pages={pages}"


def test_identity_chunk_fault_requeues_at_boundary(build, monkeypatch):
    """An injected prefill_chunk fault re-queues the turn at its last
    durable chunk boundary: the stream still matches the clean run, the
    turn is marked disrupted, and no KV page leaks."""
    monkeypatch.setenv("ROOM_TPU_PREFIX_CACHE_PAGES", "0")
    clean = build(0)
    want = clean.submit(LONG, sampling=_greedy())
    clean.run_until_idle()

    eng = build(1)
    faults.inject("prefill_chunk", times=1)
    t = eng.submit(LONG, session_id="f", sampling=_greedy())
    eng.run_until_idle()
    faults.clear()
    assert t.finish_reason in ("stop", "length")
    assert t.requeues >= 1 and t.disrupted
    assert t.new_tokens == want.new_tokens
    st = eng.stats()
    assert st["prefill_chunk_faults"] == 1
    eng.release_session("f")
    assert eng.page_table.free_pages == eng.page_table.n_pages - 1
    assert not eng.sessions


def test_identity_pool_pressure_defers_chunk(build):
    """A kv_alloc failure mid-chunked-prefill defers the turn to the
    next step (no rollback, no divergence) instead of failing it."""
    clean = build(0)
    want = clean.submit(LONG, sampling=_greedy())
    clean.run_until_idle()

    eng = build(1)
    t = eng.submit(LONG, sampling=_greedy())
    eng.step()                      # first chunk(s) written
    faults.inject("kv_alloc", times=1)
    eng.run_until_idle()
    faults.clear()
    assert t.finish_reason in ("stop", "length")
    assert t.new_tokens == want.new_tokens


def test_identity_prefix_hit_then_defer_before_first_chunk(build):
    """A prefix HIT taken in the same admission as a pre-first-commit
    deferral (class chunk budget already spent by a sibling) must be
    rolled back with the deferral: re-admission re-resolves the hit
    against the FULL prompt instead of chunk-writing the prefix tokens
    a second time on top of the cached pages."""
    prefix = list(range(1, 41))             # 5 aligned pages
    tail2 = [71 + (i % 7) for i in range(20)]   # > 1 chunk after the hit
    base = None
    for pages in (0, 1):
        eng = build(pages)
        t1 = eng.submit(prefix + [61, 62, 63], sampling=_greedy(),
                        turn_class="background")
        eng.run_until_idle()            # registers + readies the prefix
        # sibling background turn burns the class's 1-chunk budget in
        # the same admission pass the hit turn defers in
        t3 = eng.submit([9] * 80, sampling=_greedy(),
                        turn_class="background")
        t2 = eng.submit(prefix + tail2, sampling=_greedy(),
                        turn_class="background")
        eng.run_until_idle()
        for t in (t1, t2, t3):
            assert t.finish_reason in ("stop", "length")
        got = (t1.new_tokens, t2.new_tokens, t3.new_tokens)
        if base is None:
            base = got
        assert got == base, f"pages={pages}"


def test_identity_restoring_session_first_chunk_fault(build):
    """A prefill_chunk fault on the FIRST chunk of an evicted
    (history-mirror re-prefill) session must not lose the mirror: the
    requeue restores it, and the resumed turn streams exactly the
    clean run."""
    clean = build(1)
    c1 = clean.submit(list(range(1, 30)), session_id="v",
                      sampling=_greedy())
    clean.run_until_idle()
    c2 = clean.submit(LONG, session_id="v", sampling=_greedy())
    clean.run_until_idle()

    eng = build(1)
    t1 = eng.submit(list(range(1, 30)), session_id="v",
                    sampling=_greedy())
    eng.run_until_idle()
    assert t1.new_tokens == c1.new_tokens
    # drop the session's pages: its context now lives only in the
    # host-side history mirror (the re-prefill path)
    assert eng._evict_lru(exclude="__none__")
    assert eng.sessions["v"].length == 0
    assert eng.sessions["v"].history
    faults.inject("prefill_chunk", times=1)
    t2 = eng.submit(LONG, session_id="v", sampling=_greedy())
    eng.run_until_idle()
    faults.clear()
    assert t2.finish_reason in ("stop", "length")
    assert t2.requeues >= 1
    assert t2.new_tokens == c2.new_tokens


# ---- priority classes ----

def test_background_prefill_cannot_starve_queen(build):
    """Priority inversion guard: with a background long prefill already
    in progress, a queen turn must admit, stream, and finish before the
    background turn produces its first token — decode windows and
    admission keep running between the background's budgeted chunks."""
    eng = build(1)
    events = []
    bg = eng.submit(
        [2 + (i % 11) for i in range(200)],   # 25 chunks at budget 1
        sampling=_greedy(4), turn_class="background",
        on_token=lambda tok: events.append("bg"),
    )
    eng.step()          # background prefill begins (1 chunk written)
    assert eng.stats()["prefill_chunks_interleaved"] >= 1
    assert bg.done.is_set() is False
    queen = eng.submit(
        [5, 6, 7], sampling=_greedy(4), turn_class="queen",
        on_token=lambda tok: events.append("q"),
    )
    eng.run_until_idle()
    assert queen.finish_reason in ("stop", "length")
    assert bg.finish_reason in ("stop", "length")
    # every queen token preceded the background's first token
    assert "q" in events and "bg" in events
    assert events.index("bg") > max(
        i for i, e in enumerate(events) if e == "q"
    )


def test_queue_orders_by_class_deadline(build):
    """EDF admission: a queen submitted AFTER a background turn pops
    first (tighter TTFT target), same-class stays FIFO."""
    sched = RequestScheduler()

    class T:
        def __init__(self, cls, at):
            self.turn_class = cls
            self.submitted_at = at
            self.admit_by = sched.admit_deadline(cls, at)

    bg = T("background", 0.0)
    w1 = T("worker", 1.0)
    w2 = T("worker", 2.0)
    q = T("queen", 5.0)
    for t in (bg, w1, w2, q):
        sched.put(t)
    assert [sched.get_nowait() for _ in range(4)] == [q, w1, w2, bg]


def test_shed_order_background_before_worker_before_queen(build):
    eng = build(0, max_batch=2)
    eng.set_degradation(4)
    keep_n = eng.max_batch * 2
    queens = [
        eng.submit([i + 1], sampling=_greedy(), turn_class="queen")
        for i in range(keep_n)
    ]
    workers = [
        eng.submit([i + 1], sampling=_greedy(), turn_class="worker")
        for i in range(2)
    ]
    bgs = [
        eng.submit([i + 1], sampling=_greedy(),
                   turn_class="background")
        for i in range(2)
    ]
    eng.step()
    assert all(t.shed for t in bgs), "background sheds first"
    assert all(t.shed for t in workers), "workers shed next"
    assert not any(t.shed for t in queens), "queens kept"
    sched = eng.stats()["scheduler"]["classes"]
    assert sched["background"]["shed"] == 2
    assert sched["worker"]["shed"] == 2
    assert sched["queen"]["shed"] == 0
    eng.set_degradation(None)
    eng.run_until_idle()


def test_class_rung_grace():
    assert RequestScheduler.class_rung("queen", 0) == 0
    assert RequestScheduler.class_rung("queen", 2) == 2
    assert RequestScheduler.class_rung("queen", 3) == 2
    assert RequestScheduler.class_rung("queen", 4) == 3
    assert RequestScheduler.class_rung("worker", 3) == 3
    assert RequestScheduler.class_rung("background", 4) == 4


# ---- drain / warm restart composition ----

def test_drain_mid_chunk_resumes_token_identically(build, tmp_path):
    """SIGTERM mid-chunked-prefill: the dying turn rolls its session
    back to the last pre-turn state, the drain manifest carries that
    state, and a client retry of the SAME prompt against the restored
    engine streams exactly what an undisturbed engine would."""
    control = build(0, offload=True)
    c1 = control.submit([9, 8, 7], session_id="d", sampling=_greedy())
    control.run_until_idle()
    c2 = control.submit(LONG, session_id="d", sampling=_greedy())
    control.run_until_idle()

    lc = str(tmp_path / "lc")
    eng = build(1, offload=True)
    t1 = eng.submit([9, 8, 7], session_id="d", sampling=_greedy())
    eng.run_until_idle()
    assert t1.new_tokens == c1.new_tokens
    t2 = eng.submit(LONG, session_id="d", sampling=_greedy())
    eng.step()
    eng.step()          # a few chunks written, prefill mid-flight
    assert t2.done.is_set() is False
    summary = eng.drain(lc)
    assert summary["manifest_written"]
    assert t2.shed and t2.finish_reason == "error"

    eng2 = build(1, offload=True)
    restored = eng2.restore_from_manifest(lc)
    assert restored["resumed"] + restored["reprefill"] >= 1
    t2b = eng2.submit(LONG, session_id="d", sampling=_greedy())
    eng2.run_until_idle()
    assert t2b.new_tokens == c2.new_tokens


def test_failed_chunked_turn_rolls_session_back(build):
    """A chunked turn that dies while queued must leave the session in
    its pre-turn state: a full-prompt retry produces the undisturbed
    stream (no half-prefilled duplication)."""
    control = build(0)
    c1 = control.submit([4, 5, 6], session_id="r", sampling=_greedy())
    control.run_until_idle()
    c2 = control.submit(LONG, session_id="r", sampling=_greedy())
    control.run_until_idle()

    eng = build(1)
    eng.max_requeues = 0
    t1 = eng.submit([4, 5, 6], session_id="r", sampling=_greedy())
    eng.run_until_idle()
    assert t1.new_tokens == c1.new_tokens
    faults.inject("prefill_chunk", times=1)
    t2 = eng.submit(LONG, session_id="r", sampling=_greedy())
    eng.run_until_idle()
    faults.clear()
    assert t2.finish_reason == "error"
    eng.max_requeues = 3
    retry = eng.submit(LONG, session_id="r", sampling=_greedy())
    eng.run_until_idle()
    assert retry.new_tokens == c2.new_tokens


# ---- surface / config ----

def test_scheduler_stats_surface(build):
    eng = build(1)
    t = eng.submit(LONG, sampling=_greedy(), turn_class="queen")
    eng.run_until_idle()
    assert t.finish_reason in ("stop", "length")
    sched = eng.stats()["scheduler"]
    assert sched["chunk_tokens"] == 8
    q = sched["classes"]["queen"]
    assert q["submitted"] == 1 and q["completed"] == 1
    assert q["ttft_ema_s"] is not None and q["ttft_target_s"] == 2.0
    assert q["tpot_ema_s"] is not None
    assert q["chunks_written"] > 0
    assert 0.0 < q["chunk_budget_util"] <= 1.0
    for cls in ("queen", "worker", "background"):
        row = sched["classes"][cls]
        assert {"queued", "rung", "shed", "ttft_ok", "tpot_ok",
                "chunk_budget"} <= set(row)


def test_class_env_parsers(monkeypatch):
    assert normalize_class(None) == "worker"
    assert normalize_class("nonsense") == "worker"
    assert normalize_class("queen") == "queen"
    t = class_targets_from_env("queen=1.5:0.05;background=60:2")
    assert t["queen"].ttft_s == 1.5 and t["queen"].tpot_s == 0.05
    assert t["background"].ttft_s == 60.0
    assert t["worker"].ttft_s == 8.0, "unset classes keep defaults"
    with pytest.raises(ValueError):
        class_targets_from_env("drone=1:1")
    with pytest.raises(ValueError):
        class_targets_from_env("queen=oops")
    c = class_chunks_from_env("queen=8;background=0")
    assert c["queen"] == 8
    assert c["background"] == 1, "budgets clamp to >= 1"
    with pytest.raises(ValueError):
        class_chunks_from_env("drone=3")


def test_chunk_budget_paces_background(build):
    """One background turn writes at most its per-step budget (default
    1 chunk) per scheduler step."""
    eng = build(1)
    eng.submit([3] * 50, sampling=_greedy(2), turn_class="background")
    before = eng.stats()["prefill_chunks_interleaved"]
    eng.step()
    mid = eng.stats()["prefill_chunks_interleaved"]
    eng.step()
    after = eng.stats()["prefill_chunks_interleaved"]
    assert mid - before == 1
    assert after - mid == 1
    eng.run_until_idle()


# ---- per-class speculative gamma tuner (docs/serving.md) ----

def test_spec_tuner_per_class_independence():
    """One class's spec-off decision must never leak into another:
    a starved class throttles to gamma 0 while its siblings keep
    their full depth, and a good probe brings it back."""
    from room_tpu.serving.scheduler import SpecTuner

    tu = SpecTuner(4, floor=0.5, ema_alpha=1.0, cooldown=8,
                   tune_every=8)
    # starve the worker: a full tune window of rejected proposals
    assert tu.observe("worker", 8, 0, 8) == 1
    assert tu.gamma_for("worker", 0) == 0
    # queen accepts everything in the same drains: unaffected
    assert tu.observe("queen", 8, 8, 8) == 0
    assert tu.gamma_for("queen", 0) == 4
    snap = tu.snapshot()
    assert snap["worker"]["off"] is True
    assert snap["queen"]["off"] is False
    assert snap["background"]["proposed"] == 0
    # the worker decodes plainly through its cooldown, then probes
    tu.observe("worker", 0, 0, 8)        # plain tokens tick the clock
    assert tu.gamma_for("worker", 0) == 1, "post-cooldown probe round"
    # a fully-accepted probe restores the class (alpha=1: ema=rate)
    assert tu.observe("worker", 2, 2, 2) == 0
    assert tu.gamma_for("worker", 0) == 4
    assert tu.snapshot()["worker"]["probes"] == 1


def test_spec_tuner_gamma_tracks_acceptance():
    """At or above the floor, gamma follows ceil(ema * gamma_max):
    a half-accepting class drafts half as deep instead of paying
    full-width verifies."""
    from room_tpu.serving.scheduler import SpecTuner

    tu = SpecTuner(4, floor=0.1, ema_alpha=1.0, tune_every=4)
    tu.observe("worker", 4, 2, 4)        # rate 0.5 -> gamma 2
    assert tu.gamma_for("worker", 0) == 2
    tu.observe("worker", 4, 1, 4)        # rate 0.25 -> gamma 1
    assert tu.gamma_for("worker", 0) == 1
    tu.observe("worker", 4, 4, 4)        # rate 1.0 -> back to 4
    assert tu.gamma_for("worker", 0) == 4


def test_spec_tuner_dry_traffic_ratchets_down():
    """A class whose traffic never matches (zero-proposal windows)
    must not pin gamma at gamma_max paying full-width verifies
    forever: dry emission decays the acceptance EMA so gamma ratchets
    down and the floor's spec-off can engage. Off-state dry windows
    stay inert (riding at gamma 0 is expected to propose nothing)."""
    from room_tpu.serving.scheduler import SpecTuner

    tu = SpecTuner(4, floor=0.0, ema_alpha=0.5, tune_every=4)
    tu.observe("worker", 4, 4, 4)          # ema 1.0 -> gamma 4
    assert tu.gamma_for("worker", 0) == 4
    tu.observe("worker", 0, 0, 4)          # dry tune window: ema 0.5
    assert tu.gamma_for("worker", 0) == 2
    tu.observe("worker", 0, 0, 4)          # ema 0.25 -> gamma 1
    assert tu.gamma_for("worker", 0) == 1
    # with a positive floor, a dry run drives the class spec-off like
    # a below-floor tune; cooldown-period dry windows only tick the
    # clock (no repeated throttle events)
    tu2 = SpecTuner(4, floor=0.3, ema_alpha=1.0, cooldown=8,
                    tune_every=4)
    assert tu2.observe("queen", 0, 0, 4) == 1
    assert tu2.gamma_for("queen", 0) == 0
    assert tu2.observe("queen", 0, 0, 2) == 0
    assert tu2.snapshot()["queen"]["off"] is True
    # past the cooldown a probe round is handed out. The first dry
    # drain past resume_at only marks the probe pending (under
    # pipelining that window predates the probe); the SECOND dry
    # drain is the probe coming back empty — a failed probe that
    # re-arms the cooldown, so an undraftable class never sits at
    # permanent gamma-1 probing. gamma_for stays a pure read
    # (snapshot()/stats() call it from non-engine threads).
    assert tu2.observe("queen", 0, 0, 8) == 0   # marks probe pending
    assert tu2.gamma_for("queen", 0) == 1       # probe handed out
    assert tu2.observe("queen", 0, 0, 4) == 1   # dry probe: re-off
    assert tu2.gamma_for("queen", 0) == 0       # cooling again
    assert tu2.snapshot()["queen"]["probes"] == 1


def test_spec_tuner_ladder_rung_is_per_class():
    """The degradation ladder's spec-off rung honors CLASS_GRACE:
    rung 1 silences worker/background drafting while queens keep
    theirs until rung 2."""
    from room_tpu.serving.scheduler import SpecTuner

    tu = SpecTuner(4, floor=0.0)
    assert tu.gamma_for("worker", 1) == 0
    assert tu.gamma_for("background", 1) == 0
    assert tu.gamma_for("queen", 1) == 4
    assert tu.gamma_for("queen", 2) == 0
