"""Seeded lockmap violations: guarded-state inference.

``Tracker._items`` and ``Tracker.count`` are written under
``self._lock`` at two sites each, so both infer as guarded. The
unlocked subscript write and the unlocked direct iteration must be
flagged; the ``*_locked`` helper, the ``__init__`` writes, and the
plain GIL-atomic load must not.
"""

import threading


class Tracker:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = {}
        self.count = 0

    def add(self, key, val):
        with self._lock:
            self._items[key] = val
            self.count += 1

    def drop(self, key):
        with self._lock:
            self._items.pop(key, None)
            self.count -= 1

    def racy_write(self, key):
        self._items[key] = None          # lock-guarded-write

    def racy_iter(self):
        return [k for k in self._items]  # lock-guarded-iter

    def _sweep_locked(self):
        self._items.clear()              # exempt: caller holds lock

    def snapshot_count(self):
        return self.count                # plain load: sanctioned
