"""Fixture: direct self._stats mutations outside _bump
(stats-outside-bump) and blocking syncs under a lock / inside a
marked dispatch-window region (sync-under-lock,
sync-in-dispatch-window)."""

import threading

import jax
import numpy as np


class FakeEngine:
    def __init__(self):
        self._stats = {"tokens": 0}
        self._lock = threading.Lock()

    def _bump(self, key, n=1):
        with self._lock:
            self._stats[key] += n          # sanctioned: inside _bump

    def bad_direct_increment(self):
        self._stats["tokens"] += 1         # VIOLATION

    def bad_plain_assign(self, n):
        self._stats["tokens"] = n          # VIOLATION

    def bad_sync_under_lock(self, arr):
        with self._lock:
            return np.asarray(arr)         # VIOLATION

    def bad_block_under_lock(self, arr):
        with self._lock:
            arr.block_until_ready()        # VIOLATION
            return jax.device_get(arr)     # VIOLATION

    # roomlint: region=dispatch-window
    def bad_sync_in_window(self, ring):
        host = np.asarray(ring)            # VIOLATION (in region)
        return host

    def ok_sync_outside(self, ring):
        return np.asarray(ring)            # fine: no lock, no region
