"""Clean lockmap fixture — disciplined concurrency that must produce
ZERO findings (the zero-false-positive pass):

- consistent guard discipline on every shared field;
- a ``*_locked`` caller-holds helper;
- bounded waits/joins under the lock;
- a one-way nesting order (outer -> inner only);
- an explicitly pinned acquisition (``# lockmap: name=...``).

The analysis-suite tests register ``fx_clean`` / ``fx_clean_inner``
bindings for this file.
"""

import threading

_clean_outer_lock = threading.Lock()
_clean_inner_lock = threading.Lock()
_totals = {"events": 0}


def account(n):
    with _clean_outer_lock:
        with _clean_inner_lock:
            _totals["events"] += n


_renamed_lock = _clean_inner_lock


def account_pinned(n):
    # an aliased spelling the resolver cannot bind on its own: the
    # inline pin names it
    with _renamed_lock:  # lockmap: name=fx_clean_inner
        _totals["events"] += n


class Ledger:
    def __init__(self):
        self._lock = threading.Lock()
        self._rows = {}

    def put(self, key, val):
        with self._lock:
            self._rows[key] = val

    def drop(self, key):
        with self._lock:
            self._rows.pop(key, None)

    def keys(self):
        with self._lock:
            return list(self._rows)

    def _clear_locked(self):
        self._rows.clear()

    def wait_bounded(self, ev, thread):
        with self._lock:
            ev.wait(0.1)
            thread.join(timeout=0.1)
