"""Seeded lockmap violation: AB/BA lock-order cycle.

Two module-global locks acquired in both nesting orders — two threads
entering from different sides deadlock. The analysis-suite tests
register ``fx_alpha``/``fx_beta`` bindings for this file and expect
one ``lock-order-cycle`` finding.
"""

import threading

_alpha_lock = threading.Lock()
_beta_lock = threading.Lock()
shared = 0


def forward():
    with _alpha_lock:
        with _beta_lock:
            return shared


def backward():
    with _beta_lock:
        with _alpha_lock:
            return shared
