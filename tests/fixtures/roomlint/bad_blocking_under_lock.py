"""Seeded lockmap violations: every blocking-call-under-lock class.

``file_io`` (open / os.replace / shutil copy), ``sockets``
(sendall / recv), ``joins`` (timeout-less Thread.join, Queue.get,
Event.wait). ``bounded_ok`` holds the same lock but bounds every
call — zero findings expected there.
"""

import os
import shutil
import threading

_io_lock = threading.Lock()


def file_io(path, tmp):
    with _io_lock:
        with open(path) as fh:           # blocking-under-lock
            data = fh.read()
        os.replace(tmp, path)            # blocking-under-lock
        shutil.copyfile(path, tmp)       # blocking-under-lock
    return data


def sockets(sock):
    with _io_lock:
        sock.sendall(b"ping")            # blocking-under-lock
        return sock.recv(1024)           # blocking-under-lock


def joins(thread, q, ev):
    with _io_lock:
        thread.join()                    # blocking-under-lock
        item = q.get()                   # blocking-under-lock
        ev.wait()                        # blocking-under-lock
    return item


def timeout_none_spellings(thread, q, ev):
    # every one of these blocks exactly like the bare calls above
    with _io_lock:
        thread.join(timeout=None)        # blocking-under-lock
        item = q.get(block=True)         # blocking-under-lock
        item = q.get(True, None)         # blocking-under-lock
        ev.wait(None)                    # blocking-under-lock
    return item


def bounded_ok(thread, q, ev, d):
    with _io_lock:
        thread.join(timeout=1.0)
        item = q.get(timeout=0.5)
        item = q.get(True, 0.5)
        ev.wait(0.5)
        item = d.get("key")              # dict.get: not queue-like
        item = d.get("key", None)
    return item
