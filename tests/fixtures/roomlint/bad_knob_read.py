"""Fixture: every spelling of a raw ROOM_TPU_* env read roomlint must
flag (knob-raw-env-read), plus an unregistered-knob accessor call
(knob-unregistered). Never imported — parsed by tests/test_analysis.py.
"""

import os
import os as _os

from room_tpu.utils import knobs


def get_reads():
    a = os.environ.get("ROOM_TPU_MAX_BATCH", "8")          # .get
    b = os.environ["ROOM_TPU_PAGE_SIZE"]                   # subscript
    c = os.getenv("ROOM_TPU_N_PAGES")                      # getenv
    d = "ROOM_TPU_JAX_CACHE" in os.environ                 # contains
    e = _os.environ.get("ROOM_TPU_SPEC_TOKENS")            # aliased os
    return a, b, c, d, e


def fstring_family(provider: str):
    return os.environ.get(f"ROOM_TPU_{provider.upper()}_CLI")


def unregistered():
    return knobs.get_int("ROOM_TPU_NOT_A_REAL_KNOB")


def unregistered_family(kind: str):
    return knobs.get_dynamic("ROOM_TPU_{NOPE}_FAKE", kind)


def allowed_read():
    # the inline escape hatch must keep working
    return os.environ.get("ROOM_TPU_FAULTS")  # roomlint: allow[knob-raw-env-read]
