"""Fixture: idiomatic code that must produce ZERO roomlint violations
(the no-false-positive pass)."""

import os
import threading

import numpy as np

from room_tpu.serving import faults
from room_tpu.utils import knobs

# non-ROOM_TPU env reads are out of scope
HOME = os.environ.get("HOME", "/root")
PATH = os.getenv("PATH")


class CleanEngine:
    def __init__(self):
        self.max_batch = knobs.get_int("ROOM_TPU_MAX_BATCH")
        self.offload = knobs.get_bool("ROOM_TPU_OFFLOAD",
                                      scope="provider")
        self.mesh = knobs.get_dynamic("ROOM_TPU_MESH_{MODEL}", "TINY")
        self._stats = {"tokens": 0}        # initialization is fine
        self._lock = threading.Lock()

    def _bump(self, key, n=1):
        with self._lock:
            self._stats[key] += n

    def step(self):
        faults.maybe_fail("decode_step")
        self._bump("tokens")

    def drain(self, ring):
        # host sync outside any lock/region is the sanctioned pattern
        host = np.asarray(ring)
        with self._lock:
            snapshot = dict(self._stats)
        return host, snapshot

    def recover(self, fn):
        try:
            return fn()
        except RuntimeError as e:
            if getattr(e, "point", None) == "decode_window":
                return None
            raise
