"""Seeded lockmap violation: an acquisition site the registry cannot
name (``lock-unresolved``) — the lock was never registered and the
site carries no ``# lockmap: name=...`` pin.
"""

import threading


class Mystery:
    def __init__(self):
        self._mystery_lock = threading.Lock()
        self.state = {}

    def touch(self, key):
        with self._mystery_lock:
            self.state[key] = True
