"""Fixture: recovery code dispatching on fault message substrings
(fault-substring-dispatch) and arming an unknown fault point
(fault-point-unknown)."""

from room_tpu.serving import faults


def bad_substring_dispatch(fn):
    try:
        return fn()
    except RuntimeError as e:
        if "decode_window" in str(e):      # VIOLATION
            return "window"
        if "injected fault" in e.args[0]:  # VIOLATION
            return "injected"
        raise


def good_typed_dispatch(fn):
    try:
        return fn()
    except RuntimeError as e:
        if getattr(e, "point", None) == "decode_window":  # sanctioned
            return "window"
        raise


def bad_unknown_point():
    faults.maybe_fail("decode_widnow")     # VIOLATION (typo'd point)
