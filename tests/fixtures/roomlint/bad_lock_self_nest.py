"""Seeded lockmap violations: same-instance re-acquires of
non-reentrant locks — guaranteed deadlocks.

- ``lexical``: a module-global lock nested inside itself;
- ``Worker.outer``: a ``self.method()`` call under ``self._lock``
  into a method that takes the same lock (the class is registered
  ``multi_instance`` — the ``self.`` call is same-instance evidence
  that overrides that exemption).
"""

import threading

_gamma_lock = threading.Lock()


def lexical():
    with _gamma_lock:
        with _gamma_lock:
            pass


class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self.jobs = []

    def _inner(self):
        with self._lock:
            return len(self.jobs)

    def outer(self):
        with self._lock:
            return self._inner()
