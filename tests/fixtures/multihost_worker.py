"""One rank of the multi-host smoke test (spawned by
tests/test_multihost.py with ROOM_TPU_COORDINATOR / NUM_PROCESSES /
PROCESS_ID set): initializes jax.distributed, checks the global device
view, runs a cross-process psum, then ONE full sharded training step
over the global dp mesh — the multi-host path of SURVEY §2.7's
distributed backend, exercised with REAL separate processes."""

import os
import sys

sys.path.insert(
    0, os.path.join(os.path.dirname(__file__), "..", "..")
)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from room_tpu.models.config import tiny_moe  # noqa: E402
from room_tpu.parallel import (  # noqa: E402
    MeshSpec, decoder_param_specs, shard_pytree,
)
from room_tpu.parallel.multihost import (  # noqa: E402
    initialize_multihost, make_global_mesh,
)
from room_tpu.train import init_train_state, make_train_step  # noqa: E402


def main() -> None:
    assert initialize_multihost(), "env-driven init failed"
    rank = jax.process_index()
    n_local = len(jax.local_devices())
    n_global = len(jax.devices())
    n_procs = int(os.environ["ROOM_TPU_NUM_PROCESSES"])
    assert jax.process_count() == n_procs
    assert n_global == n_procs * n_local

    # 1. cross-process psum: every device contributes global_index + 1
    mesh = make_global_mesh(MeshSpec(dp=n_global, ep=1, tp=1))
    fn = jax.shard_map(
        lambda x: jax.lax.psum(x, ("dp", "ep", "tp")),
        mesh=mesh,
        in_specs=P(("dp", "ep", "tp")),
        out_specs=P(),
    )
    local_vals = np.array(
        [i + 1.0 for i in range(rank * n_local, (rank + 1) * n_local)],
        np.float32,
    )
    garr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P(("dp", "ep", "tp"))),
        local_vals, (n_global,),
    )
    got = float(np.asarray(fn(garr).addressable_data(0)))
    want = n_global * (n_global + 1) / 2
    assert got == want, (got, want)
    print(f"RANK{rank} psum OK ({got})", flush=True)

    # 2. one sharded training step with the batch dp-split ACROSS the
    # processes (grad all-reduce crosses the process boundary)
    cfg = tiny_moe()
    spec = MeshSpec(dp=n_global, ep=1, tp=1)
    tmesh = make_global_mesh(spec)
    state, tx = init_train_state(cfg, jax.random.PRNGKey(0))
    state.params = shard_pytree(
        state.params, decoder_param_specs(cfg), tmesh
    )
    state.opt_state = tx.init(state.params)
    train_step = jax.jit(make_train_step(cfg, tx), donate_argnums=(0,))

    batch, seq = n_global, 16
    rng = np.random.default_rng(0)   # same data on both ranks
    tokens_all = rng.integers(
        0, cfg.vocab_size, (batch, seq)
    ).astype(np.int32)
    mask_all = np.ones((batch, seq), np.float32)
    tok_shard = NamedSharding(tmesh, P("dp", None))
    rows_per = batch // n_procs
    local_rows = slice(rank * rows_per, (rank + 1) * rows_per)
    tokens = jax.make_array_from_process_local_data(
        tok_shard, tokens_all[local_rows], (batch, seq)
    )
    mask = jax.make_array_from_process_local_data(
        tok_shard, mask_all[local_rows], (batch, seq)
    )
    state, loss = train_step(state, tokens, mask)
    loss_val = float(np.asarray(
        jax.device_get(loss)
    ))
    assert np.isfinite(loss_val)
    print(f"RANK{rank} train OK loss={loss_val:.4f}", flush=True)


if __name__ == "__main__":
    main()
