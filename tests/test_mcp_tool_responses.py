"""Per-MCP-tool response assertions (reference pattern:
src/mcp/tools/__tests__/tool-responses.test.ts — every tool gets its
response content checked against seeded state, not just "no error")."""

import json

import pytest

from room_tpu.core import rooms as rooms_mod
from room_tpu.mcp.server import McpServer


@pytest.fixture()
def mcp(db):
    return McpServer(db=db)


def call(mcp, name, args=None):
    resp = mcp.handle({
        "jsonrpc": "2.0", "id": 1, "method": "tools/call",
        "params": {"name": name, "arguments": args or {}},
    })
    content = resp["result"]["content"][0]["text"]
    return content, resp["result"].get("isError", False)


@pytest.fixture()
def seeded(mcp, db):
    """One of everything, built through the tools themselves."""
    call(mcp, "room_create", {"name": "alpha", "goal": "ship it"})
    call(mcp, "worker_create",
         {"room_id": 1, "name": "forge", "role": "executor"})
    call(mcp, "goal_create", {"room_id": 1, "description": "phase 1"})
    call(mcp, "memory_remember",
         {"name": "pricing", "content": "competitor charges $29",
          "room_id": 1})
    call(mcp, "skill_create",
         {"name": "deploy", "content": "use blue-green"})
    call(mcp, "schedule_task",
         {"name": "daily", "prompt": "report",
          "cron_expression": "0 9 * * *"})
    return mcp


# ---- rooms ----

def test_room_list_shows_status_and_goal(seeded):
    out, err = call(seeded, "room_list")
    assert not err
    assert "alpha" in out and "ship it" in out


def test_room_list_empty(mcp):
    out, _ = call(mcp, "room_list")
    assert out.strip() == "[]"


def test_room_status_counts(seeded):
    out, err = call(seeded, "room_status", {"room_id": 1})
    assert not err
    # queen + forge, root goal + phase 1
    assert '"worker_count": 2' in out
    assert '"active_goals"' in out


def test_room_status_unknown_room(seeded):
    out, err = call(seeded, "room_status", {"room_id": 99})
    assert err or "not found" in out.lower()


def test_room_start_without_server_fails_closed(seeded, db):
    # room_start nudges the running HTTP server; with none up the tool
    # reports it instead of pretending
    out, err = call(seeded, "room_start", {"room_id": 1})
    assert "server not reachable" in out


# ---- workers ----

def test_worker_list_roles(seeded):
    out, _ = call(seeded, "worker_list", {"room_id": 1})
    assert "queen" in out and "forge" in out


def test_worker_nudge_without_server_fails_closed(seeded):
    out, err = call(seeded, "worker_nudge", {"worker_id": 2})
    assert "server not reachable" in out


# ---- goals ----

def test_goal_tree_shows_hierarchy(seeded):
    out, _ = call(seeded, "goal_tree", {"room_id": 1})
    assert "phase 1" in out


def test_goal_complete_then_tree_updates(seeded, db):
    goal = db.query_one(
        "SELECT id FROM goals WHERE description='phase 1'"
    )
    out, err = call(seeded, "goal_complete", {"goal_id": goal["id"]})
    assert not err
    row = db.query_one(
        "SELECT status FROM goals WHERE id=?", (goal["id"],)
    )
    assert row["status"] == "completed"


# ---- memory ----

def test_memory_recall_finds_by_content(seeded):
    out, _ = call(seeded, "memory_recall", {"query": "competitor"})
    assert "pricing" in out


def test_memory_forget_removes(seeded, db):
    ent = db.query_one("SELECT id FROM entities WHERE name='pricing'")
    out, err = call(seeded, "memory_forget", {"entity_id": ent["id"]})
    assert not err
    out, _ = call(seeded, "memory_recall", {"query": "competitor"})
    assert "pricing" not in out


# ---- quorum ----

def test_quorum_flow_vote_and_keeper_veto(seeded, db):
    from room_tpu.core import quorum

    # high_impact stays open for votes (low_impact auto-approves)
    quorum.announce(db, 1, 2, "adopt cadence",
                    decision_type="high_impact")
    out, _ = call(seeded, "quorum_decisions", {"room_id": 1})
    assert "adopt cadence" in out
    decision = db.query_one(
        "SELECT id FROM quorum_decisions WHERE proposal='adopt cadence'"
    )
    # keeper "no" on an announced decision objects it outright
    out, err = call(seeded, "quorum_keeper_vote", {
        "decision_id": decision["id"], "vote": "no",
    })
    assert not err
    row = db.query_one(
        "SELECT status FROM quorum_decisions WHERE id=?",
        (decision["id"],),
    )
    assert row["status"] == "objected"

    # a second decision resolves effective through a worker vote
    quorum.announce(db, 1, 2, "second proposal",
                    decision_type="high_impact")
    second = db.query_one(
        "SELECT id FROM quorum_decisions WHERE proposal="
        "'second proposal'"
    )
    out, err = call(seeded, "quorum_vote", {
        "decision_id": second["id"], "worker_id": 2,
        "vote": "approve", "reasoning": "fine",
    })
    assert not err
    row = db.query_one(
        "SELECT status FROM quorum_decisions WHERE id=?",
        (second["id"],),
    )
    assert row["status"] in ("effective", "approved", "voting",
                             "announced")


# ---- tasks ----

def test_task_list_includes_schedule(seeded):
    out, _ = call(seeded, "task_list", {})
    assert "daily" in out and "0 9 * * *" in out


def test_task_pause_resume_roundtrip(seeded, db):
    out, err = call(seeded, "task_pause", {"task_id": 1})
    assert not err
    assert db.query_one(
        "SELECT status FROM tasks WHERE id=1"
    )["status"] == "paused"
    out, err = call(seeded, "task_resume", {"task_id": 1})
    assert not err
    assert db.query_one(
        "SELECT status FROM tasks WHERE id=1"
    )["status"] == "active"


def test_task_history_empty(seeded):
    out, err = call(seeded, "task_history", {"task_id": 1})
    assert not err
    assert "no runs" in out.lower() or out in ("[]", "")


def test_cron_validate_rejects_six_fields(mcp):
    out, _ = call(mcp, "cron_validate",
                  {"expression": "0 9 * * * *"})
    assert "valid" != out


# ---- skills ----

def test_skill_list_names(seeded):
    out, _ = call(seeded, "skill_list", {})
    assert "deploy" in out


# ---- selfmod ----

def test_selfmod_audit_empty_then_revert_unknown(mcp):
    out, err = call(mcp, "selfmod_audit", {})
    assert not err
    out, err = call(mcp, "selfmod_revert", {"audit_id": 999})
    assert "nothing to revert" in out


# ---- messaging ----

def test_message_send_and_inbox_unread(seeded, db):
    out, err = call(seeded, "message_send", {
        "from_room_id": 1, "to_room_id": 1,
        "subject": "st", "body": "phase 1 done",
    })
    assert not err
    out, _ = call(seeded, "inbox_unread", {"room_id": 1})
    assert "phase 1 done" in out


def test_escalation_answer_roundtrip(seeded, db):
    from room_tpu.core import escalations

    eid = escalations.create_escalation(db, 1, "which cloud?")
    out, _ = call(seeded, "escalation_list", {})
    assert "which cloud?" in out
    out, err = call(seeded, "escalation_answer",
                    {"escalation_id": eid, "answer": "use our own"})
    assert not err
    row = db.query_one(
        "SELECT status, answer FROM escalations WHERE id=?", (eid,)
    )
    assert row["status"] == "answered" and row["answer"] == "use our own"


# ---- wallet / identity ----

def test_wallet_info_and_payment_audit(seeded, db):
    from room_tpu.core.wallet import create_room_wallet

    create_room_wallet(db, 1)
    out, err = call(seeded, "wallet_info", {"room_id": 1})
    assert not err and "0x" in out
    out, err = call(seeded, "payment_audit", {"room_id": 1})
    assert not err


def test_identity_info(seeded, db):
    from room_tpu.core.wallet import create_room_wallet

    create_room_wallet(db, 1)
    out, err = call(seeded, "identity_info", {"room_id": 1})
    assert not err and "address" in out


# ---- wip / settings / system ----

def test_wip_save_persists(seeded, db):
    out, err = call(seeded, "wip_save",
                    {"worker_id": 2, "note": "halfway through"})
    assert not err
    assert db.query_one(
        "SELECT wip FROM workers WHERE id=2"
    )["wip"] == "halfway through"


def test_setting_roundtrip(mcp, db):
    out, err = call(mcp, "setting_set",
                    {"key": "tone", "value": "dry"})
    assert not err
    out, _ = call(mcp, "setting_get", {"key": "tone"})
    assert "dry" in out
    out, _ = call(mcp, "setting_get", {"key": "missing-key"})
    assert "(unset)" in out


def test_system_resources_shape(mcp):
    out, err = call(mcp, "system_resources")
    assert not err
    data = json.loads(out)
    assert "platform" in data or "devices" in data or "cpu" in data


# ---- templates / watches ----

def test_template_list_and_instantiate(mcp, db):
    out, _ = call(mcp, "template_list")
    assert "research-desk" in out
    out, err = call(mcp, "template_instantiate",
                    {"template": "research-desk", "name": "desk"})
    assert not err
    room = rooms_mod.get_room(db, 1)
    assert room is not None
    out, err = call(mcp, "template_instantiate", {"template": "nope"})
    assert err or "unknown" in out.lower()


def test_watch_create_and_list(mcp, tmp_path):
    out, err = call(mcp, "watch_create", {
        "path": str(tmp_path), "action_prompt": "summarize changes",
    })
    assert not err
    out, _ = call(mcp, "watch_list", {})
    assert str(tmp_path) in out


def test_watch_create_missing_path(mcp):
    out, err = call(mcp, "watch_create", {
        "path": "/nonexistent/deep/path", "action_prompt": "x",
    })
    assert err or "exist" in out.lower() or "invalid" in out.lower()


# ---- web ----

def test_web_fetch_invalid_url(mcp):
    out, _ = call(mcp, "web_fetch", {"url": "ftp://nope"})
    assert "invalid url" in out


def test_web_fetch_offline_fails_closed(mcp):
    out, _ = call(mcp, "web_fetch", {"url": "http://127.0.0.1:1/x"})
    assert "fetch failed" in out
