"""Disaggregated prefill/decode serving suite (docs/disagg.md).

Pins the disaggregation contract on the CPU backend:

- Role parsing + role-aware placement: fresh long-prompt sessions land
  on prefill replicas, short/continuation traffic prefers decode.
- The prefill->decode KV handoff: greedy continuations are
  token-identical to the monolithic single-replica baseline through
  BOTH ship transports — the same-host detached-spool adopt and the
  loopback-socket wire (length-prefixed, sha256-checksummed frames) —
  and the handoff is warm (adopted spool, no re-prefill) when the KV
  is eligible.
- The `kv_wire` fault point: a chaos burst over every shipment
  degrades to the router-mirror re-prefill with ZERO
  durably-streamed-token loss and identical continuations.
- Ship/turn races: an export is refused (never blocked on) while the
  session has a live turn; routing waits out a mid-flight ship rather
  than forking the session.
- Satellite pins: the bounded router history mirror
  (ROOM_TPU_FLEET_MIRROR_TOKENS cap + eviction stat, warm-only
  failover afterwards) and the scheduler-classifier fix (an untagged
  background-priority turn is NOT promoted to worker class).
"""

import threading
import time

import jax
import pytest

from room_tpu.models import qwen3, tiny_moe
from room_tpu.serving import SamplingParams, ServingEngine, faults
from room_tpu.serving import disagg
from room_tpu.serving.fleet import EngineFleet


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture(scope="module")
def model():
    cfg = tiny_moe()
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


@pytest.fixture()
def make_fleet(model, monkeypatch, tmp_path):
    """Role-split fleet factory: prefix cache off (KV wholly
    session-owned, so ships are warm-eligible), offload on (the ship's
    spool source), a low prefill threshold so the small test prompts
    exercise the role router."""
    monkeypatch.setenv("ROOM_TPU_PREFIX_CACHE_PAGES", "0")
    monkeypatch.setenv("ROOM_TPU_OFFLOAD_DIR", str(tmp_path / "spool"))
    monkeypatch.setenv("ROOM_TPU_LIFECYCLE_DIR", str(tmp_path / "lc"))
    monkeypatch.setenv("ROOM_TPU_DISAGG_PREFILL_TOKENS", "8")
    cfg, params = model

    def build_engine(**kw):
        kw.setdefault("max_batch", 4)
        kw.setdefault("page_size", 8)
        kw.setdefault("n_pages", 96)
        kw.setdefault("offload", True)
        kw.setdefault("stop_token_ids", [])
        return ServingEngine(cfg, params, **kw)

    def build(n=2, roles=("prefill", "decode"), **kw):
        return EngineFleet(
            "tiny-moe", lambda i: build_engine(**kw), n,
            auto_rebuild=False, roles=list(roles),
        )

    build.engine = build_engine
    return build


def _greedy(n=8):
    return SamplingParams(temperature=0.0, max_new_tokens=n)


LONG_PROMPT = list(range(1, 20))     # >= threshold -> prefill replica
SHORT_PROMPT = [5, 6, 7]             # < threshold -> decode replica
CONT = [7, 7, 7]


@pytest.fixture(scope="module")
def control(model):
    """Uninterrupted two-turn reference streams on one engine."""
    cfg, params = model
    eng = ServingEngine(
        cfg, params, max_batch=4, page_size=8, n_pages=96,
        offload=False, stop_token_ids=[],
    )
    c1 = eng.submit(LONG_PROMPT, session_id="s", sampling=_greedy())
    eng.run_until_idle()
    c2 = eng.submit(CONT, session_id="s", sampling=_greedy())
    eng.run_until_idle()
    return c1.new_tokens, c2.new_tokens


# ---- roles + placement ----

def test_roles_from_env_parsing():
    assert disagg.roles_from_env(3, "prefill,decode") == \
        ["prefill", "decode", "mixed"]
    assert disagg.roles_from_env(2, "prefill; decode; decode") == \
        ["prefill", "decode"]
    assert disagg.roles_from_env(2, "") == ["mixed", "mixed"]
    # positions are the contract: empty entries normalize to mixed IN
    # PLACE, never shifting later roles onto earlier replicas
    assert disagg.roles_from_env(3, ",,prefill") == \
        ["mixed", "mixed", "prefill"]
    with pytest.raises(ValueError):
        disagg.roles_from_env(2, "prefill,typo")


def test_explicit_roles_list_is_padded(make_fleet):
    # a ctor roles list shorter than the fleet pads to mixed, exactly
    # like the env path (it must not crash mid-construction)
    fleet = make_fleet(n=3, roles=("prefill",))
    assert [h.role for h in fleet.replicas] == \
        ["prefill", "mixed", "mixed"]
    with pytest.raises(ValueError):
        make_fleet(n=2, roles=("typo",))


def test_release_mid_export_never_adopts_ghost(make_fleet):
    """A session released while its export is in flight must NOT be
    adopted anywhere (an unreleasable ghost) — the coordinator's
    liveness re-check discards the exported entry and its spool."""
    fleet = make_fleet()
    t1 = fleet.submit(LONG_PROMPT, session_id="s", sampling=_greedy())
    for _ in range(2000):   # step WITHOUT supervise: no ship starts
        busy = sum(
            h.engine.step() for h in fleet.replicas
            if h.state == "serving"
        )
        if t1.done.is_set() and busy == 0:
            break
    assert t1.finish_reason == "length"
    rec = fleet._records["s"]
    donor = fleet._handle(rec.rid)
    # stage the export by hand, then release before the collect
    done, holder = donor.engine.export_session("s")
    assert done.is_set() and holder["entry"] is not None
    rec.ship_state = "exporting"
    rec.ship_event = threading.Event()
    rec.ship_export = (done, holder, donor.rid)
    fleet.release_session("s")
    fleet.disagg._collect_export(rec)
    assert rec.ship_state is None
    for h in fleet.replicas:
        assert "s" not in h.engine.sessions, \
            "a released session must not be re-adopted by the ship"
    kv = holder["entry"].get("kv")
    if kv:
        import os as _os

        assert not _os.path.exists(kv["file"]), \
            "the discarded entry's detached spool must be unlinked"


def test_placement_by_role(make_fleet):
    fleet = make_fleet(n=3, roles=("prefill", "decode", "decode"))
    fleet.submit(LONG_PROMPT, session_id="long", sampling=_greedy(2))
    fleet.submit(SHORT_PROMPT, session_id="short", sampling=_greedy(2))
    assert fleet._records["long"].rid == "r0", \
        "fresh long prompt must land on the prefill replica"
    assert fleet._records["short"].rid in ("r1", "r2"), \
        "short prompt must prefer a decode replica"
    st = fleet.disagg.stats()
    assert st["prefill_placements"] == 1
    assert st["decode_placements"] == 1
    fleet.run_until_idle()


def test_placement_degrades_without_role_tier(make_fleet):
    # no decode/mixed sibling: short prompts still get served (on the
    # prefill replica) — specialization degrades, availability doesn't
    fleet = make_fleet(n=1, roles=("prefill",))
    t = fleet.submit(SHORT_PROMPT, session_id="s", sampling=_greedy(2))
    fleet.run_until_idle()
    assert t.finish_reason == "length"
    assert fleet._records["s"].rid == "r0"


# ---- the handoff: token identity through every path ----

def test_same_host_handoff_token_identity(make_fleet, control):
    c1, c2 = control
    fleet = make_fleet()
    t1 = fleet.submit(LONG_PROMPT, session_id="s", sampling=_greedy())
    fleet.run_until_idle()
    assert t1.new_tokens == c1
    st = fleet.disagg.stats()
    assert st["ships"] == 1 and st["ships_warm"] == 1, st
    assert fleet._records["s"].rid == "r1", \
        "after the ship the session must live on the decode replica"
    t2 = fleet.submit(CONT, session_id="s", sampling=_greedy())
    fleet.run_until_idle()
    assert fleet._records["s"].rid == "r1"
    assert t2.new_tokens == c2, \
        "greedy continuation must be token-identical across the " \
        "prefill->decode handoff"


def test_wire_handoff_token_identity(make_fleet, control, monkeypatch):
    monkeypatch.setenv("ROOM_TPU_DISAGG_WIRE", "loopback")
    c1, c2 = control
    fleet = make_fleet()
    try:
        assert fleet.disagg._wire_server is not None
        t1 = fleet.submit(LONG_PROMPT, session_id="s",
                          sampling=_greedy())
        fleet.run_until_idle()
        assert t1.new_tokens == c1
        st = fleet.disagg.stats()
        assert st["ship_wire"] == 1 and st["wire_errors"] == 0, st
        assert st["ships_warm"] == 1, \
            "the wire shipment must adopt the spool bytes warm"
        t2 = fleet.submit(CONT, session_id="s", sampling=_greedy())
        fleet.run_until_idle()
        assert t2.new_tokens == c2, \
            "greedy continuation must be token-identical across the " \
            "loopback-wire handoff"
    finally:
        fleet.disagg.close()


def test_threaded_handoff_token_identity(make_fleet, control):
    c1, c2 = control
    fleet = make_fleet()
    stop = threading.Event()
    th = threading.Thread(
        target=fleet.serve_forever, args=(stop,), daemon=True,
    )
    th.start()
    try:
        t1 = fleet.submit(LONG_PROMPT, session_id="s",
                          sampling=_greedy())
        assert t1.wait(60).new_tokens == c1
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and \
                fleet.disagg.stats()["ships"] < 1:
            time.sleep(0.02)
        assert fleet.disagg.stats()["ships"] == 1
        t2 = fleet.submit(CONT, session_id="s", sampling=_greedy())
        assert t2.wait(60).new_tokens == c2
        assert fleet._records["s"].rid == "r1"
    finally:
        stop.set()
        th.join(30)


# ---- kv_wire chaos ----

def test_kv_wire_chaos_burst_reprefill_fallback_zero_loss(
    make_fleet, control, monkeypatch,
):
    """Every wire shipment fails (send-side and receive-side
    firings): the coordinator adopts history-only, the decode replica
    re-prefills from the router mirror, and the continuation stream
    is token-identical — zero durably-streamed tokens lost."""
    monkeypatch.setenv("ROOM_TPU_DISAGG_WIRE", "loopback")
    c1, c2 = control
    faults.inject("kv_wire", times=8)
    fleet = make_fleet()
    try:
        streams = {}
        for i in range(3):
            sid = f"s{i}"
            t = fleet.submit(LONG_PROMPT, session_id=sid,
                             sampling=_greedy())
            fleet.run_until_idle()
            streams[sid] = list(t.new_tokens)
            assert streams[sid] == c1
        st = fleet.disagg.stats()
        assert st["ships"] == 3, st
        assert st["ships_reprefill"] == 3 and st["ships_warm"] == 0, \
            f"wire faults must degrade every ship to re-prefill: {st}"
        assert st["wire_errors"] == 3
        assert faults.fired("kv_wire") >= 3
        for i in range(3):
            sid = f"s{i}"
            t2 = fleet.submit(CONT, session_id=sid,
                              sampling=_greedy())
            fleet.run_until_idle()
            assert t2.new_tokens == c2, \
                "re-prefill fallback must keep greedy continuations " \
                "token-identical (zero token loss)"
    finally:
        fleet.disagg.close()


def test_kv_wire_checksum_mismatch_degrades(make_fleet, tmp_path):
    """A corrupted payload is refused by the receiver's in-transit
    sha256 — the sender gets a typed error, never a silent adoption
    of bad KV bytes."""
    from room_tpu.parallel.multihost import (
        KVWireError, KVWireServer, kv_wire_send,
    )

    got = []
    srv = KVWireServer(str(tmp_path / "in"), lambda *a: got.append(a))
    try:
        spool = tmp_path / "x.kvspool"
        spool.write_bytes(b"\x08\x00\x00\x00\x00\x00\x00\x00{}bytes!")
        entry = {
            "id": "s", "history": [1, 2], "pending": 3, "length": 2,
            "generation": 1,
            "kv": {"file": str(spool), "own_tokens": 2, "n_pages": 1,
                   "nbytes": spool.stat().st_size,
                   "sha256": "0" * 64},   # wrong digest
        }
        with pytest.raises(KVWireError, match="checksum"):
            kv_wire_send(srv.address, entry)
        assert not got, "a refused shipment must never reach adoption"
        assert not list((tmp_path / "in").glob("*.kvspool")), \
            "the corrupt payload must not be persisted"
    finally:
        srv.close()


def test_donor_death_mid_ship_drains_and_discards(make_fleet):
    """A replica dying with a ship mid-flight must drain the
    coordinator's in-flight tracking (run_until_idle would otherwise
    spin on pending() forever) and discard the completed export's
    detached spool instead of leaking it."""
    import os as _os

    fleet = make_fleet(n=3, roles=("prefill", "decode", "decode"))
    t1 = fleet.submit(LONG_PROMPT, session_id="s", sampling=_greedy())
    for _ in range(2000):   # step WITHOUT supervise: no ship starts
        busy = sum(
            h.engine.step() for h in fleet.replicas
            if h.state == "serving"
        )
        if t1.done.is_set() and busy == 0:
            break
    rec = fleet._records["s"]
    donor = fleet._handle(rec.rid)
    done, holder = donor.engine.export_session("s")
    assert holder["entry"] is not None
    spool = (holder["entry"].get("kv") or {}).get("file")
    rec.ship_state = "exporting"
    rec.ship_event = threading.Event()
    rec.ship_export = (done, holder, donor.rid)
    with fleet._lock:
        fleet.disagg._inflight[rec.sid] = rec
    fleet.kill_replica(donor.rid, "test")
    assert fleet.disagg.pending() == 0, \
        "a dead donor's ship must drain the in-flight tracking"
    if spool:
        assert not _os.path.exists(spool), \
            "the dead ship's detached spool must be discarded"
    fleet.run_until_idle()   # must terminate, not spin on pending()


def test_drain_folds_inflight_ship_into_manifest(
    make_fleet, model, tmp_path,
):
    """A process drain catching a ship mid-flight (export applied, no
    adoption yet) must fold the exported session into SOME replica's
    manifest — the zero-durable-loss drain contract."""
    cfg, params = model
    fleet = make_fleet()
    t1 = fleet.submit(LONG_PROMPT, session_id="s", sampling=_greedy())
    for _ in range(2000):   # step WITHOUT supervise: no ship starts
        busy = sum(
            h.engine.step() for h in fleet.replicas
            if h.state == "serving"
        )
        if t1.done.is_set() and busy == 0:
            break
    rec = fleet._records["s"]
    donor = fleet._handle(rec.rid)
    done, holder = donor.engine.export_session("s")
    assert holder["entry"] is not None
    rec.ship_state = "exporting"
    rec.ship_event = threading.Event()
    rec.ship_export = (done, holder, donor.rid)
    with fleet._lock:
        fleet.disagg._inflight[rec.sid] = rec
    dump = str(tmp_path / "drainfold")
    fleet.drain(dump, deadline_s=20.0)
    eng = ServingEngine(
        cfg, params, max_batch=4, page_size=8, n_pages=96,
        offload=True, stop_token_ids=[],
    )
    eng.restore_from_manifest(dump)
    sess = eng.sessions.get("s")
    assert sess is not None, \
        "the mid-ship session must survive the drain in a manifest"
    full = sess.history + (
        [sess.pending] if sess.pending is not None else []
    )
    assert full[: len(LONG_PROMPT)] == LONG_PROMPT


def test_wire_refusal_drops_persisted_spool(tmp_path):
    """A receiver that refuses a shipment (e.g. named target not
    serving) must not leave the already-persisted payload filling the
    wire-in dir — only an accepted (possibly still-queued) adoption
    keeps its spool."""
    from room_tpu.parallel.multihost import KVWireError, kv_wire_send
    from room_tpu.parallel.multihost import KVWireServer
    import hashlib as _hashlib

    srv = KVWireServer(
        str(tmp_path / "in"),
        lambda *a: {"ok": False, "error": "target not serving"},
    )
    try:
        payload = b"\x02\x00\x00\x00\x00\x00\x00\x00{}kv"
        spool = tmp_path / "x.kvspool"
        spool.write_bytes(payload)
        entry = {
            "id": "s", "history": [1], "pending": 2, "length": 1,
            "generation": 1,
            "kv": {"file": str(spool), "own_tokens": 1, "n_pages": 1,
                   "nbytes": len(payload),
                   "sha256": _hashlib.sha256(payload).hexdigest()},
        }
        with pytest.raises(KVWireError, match="not serving"):
            kv_wire_send(srv.address, entry, target_rid="r9")
        assert not list((tmp_path / "in").glob("*.kvspool")), \
            "a refused shipment's persisted spool must be unlinked"
        assert not list((tmp_path / "in").glob("*.tmp"))
    finally:
        srv.close()


def test_export_refused_while_turn_live(make_fleet):
    """The engine's export seam refuses (never blocks on) a session
    with a live turn — the ship retries at the next boundary."""
    fleet = make_fleet()
    t1 = fleet.submit(LONG_PROMPT, session_id="s", sampling=_greedy())
    eng = fleet._handle("r0").engine
    # the turn is queued, not yet admitted: export must refuse
    done, holder = eng.export_session("s")
    assert done.is_set()
    assert holder["entry"] is None
    assert "busy" in holder["error"]
    fleet.run_until_idle()
    assert t1.finish_reason == "length"
    # quiescent now: the coordinator's ship went through normally
    assert fleet.disagg.stats()["ships"] == 1
    assert fleet._records["s"].rid == "r1"


# ---- satellite: bounded router history mirror ----

def test_mirror_cap_evicts_lru_with_stat(make_fleet, monkeypatch):
    monkeypatch.setenv("ROOM_TPU_FLEET_MIRROR_TOKENS", "40")
    fleet = make_fleet(n=2, roles=("mixed", "mixed"))
    assert fleet.mirror_cap_tokens == 40
    for i in range(3):
        fleet.submit(LONG_PROMPT, session_id=f"m{i}",
                     sampling=_greedy())
        fleet.run_until_idle()
    st = fleet.fleet_stats()["mirror"]
    assert st["evictions"] > 0, "the cap must evict LRU mirrors"
    assert st["tokens_evicted"] > 0
    assert st["tokens"] <= 40 + len(LONG_PROMPT) + 9, \
        "the mirror total must stay near the cap"
    # the COLDEST record lost its mirror; the hottest kept it
    assert fleet._records["m0"].mirror_dropped
    assert not fleet._records["m2"].mirror_dropped
    # a dropped record stops mirroring entirely: no unusable (and
    # unevictable) partial suffix may accumulate after the drop
    dropped = fleet._records["m0"]
    before = len(dropped.tokens)
    fleet.submit(CONT, session_id="m0", sampling=_greedy())
    fleet.run_until_idle()
    assert len(dropped.tokens) == before == 0, \
        "cap-evicted mirrors must not keep growing"


def test_dropped_mirror_never_forks_on_failover(
    make_fleet, monkeypatch,
):
    """A cap-evicted mirror makes failover warm-only for that session:
    with no salvage either, the record is dropped — the session's next
    turn starts FRESH (a visible reset), never a silently forked
    history re-prefilled from a partial mirror."""
    monkeypatch.setenv("ROOM_TPU_FLEET_MIRROR_TOKENS", "10")
    monkeypatch.setenv("ROOM_TPU_OFFLOAD_DISK_MB", "0")
    fleet = make_fleet(n=2, roles=("mixed", "mixed"))
    fleet.submit(LONG_PROMPT, session_id="s", sampling=_greedy())
    fleet.run_until_idle()
    rec = fleet._records["s"]
    assert rec.mirror_dropped    # cap 10 < prompt+stream
    assert fleet._entry_from_mirror(rec) is None, \
        "a dropped mirror's partial suffix must never become a " \
        "re-home entry"
    home = rec.rid
    fleet.kill_replica(home, "test")
    # the router mirror is gone, so the re-home may only come from
    # the dying engine's OWN salvage (full history / exported spool)
    # — or drop the record entirely. A partial-suffix re-prefill
    # (forked history) must be impossible.
    rec = fleet._records.get("s")
    if rec is not None and rec.rid:
        target = fleet._handle(rec.rid)
        sess = target.engine.sessions.get("s")
        assert sess is not None
        full = sess.history + (
            [sess.pending] if sess.pending is not None else []
        )
        assert full[: len(LONG_PROMPT)] == LONG_PROMPT, \
            "re-homed context must contain the FULL prompt (salvage " \
            "history), never the dropped mirror's partial suffix"


# ---- satellite: scheduler classifier ----

def test_untagged_background_priority_not_promoted(make_fleet):
    """fleet.submit with turn_class=None but an explicit background
    priority must classify through the scheduler (background), not
    silently promote to worker."""
    fleet = make_fleet(n=2, roles=("mixed", "mixed"))
    t = fleet.submit(SHORT_PROMPT, session_id="x", sampling=_greedy(2),
                     priority=0)
    assert t.turn_class == "background"
    fleet.run_until_idle()
    assert t.finish_reason == "length"
    # and through the router shed path (no serving replica)
    for h in fleet.replicas:
        fleet.kill_replica(h.rid, "test")
    shed = fleet.submit(SHORT_PROMPT, session_id="y",
                        sampling=_greedy(2), priority=0)
    assert shed.shed and shed.turn_class == "background"
    tagged = fleet.submit(SHORT_PROMPT, session_id="z",
                          sampling=_greedy(2), turn_class="queen")
    assert tagged.turn_class == "queen"


def test_classify_turn_table():
    from room_tpu.serving.scheduler import classify_turn

    assert classify_turn("queen") == "queen"
    assert classify_turn("background", priority=2) == "background"
    assert classify_turn(None, priority=0) == "background"
    assert classify_turn(None, priority=-5) == "background"
    assert classify_turn(None, priority=1) == "worker"
    assert classify_turn(None, priority=7) == "queen"
    assert classify_turn(None) == "worker"
    assert classify_turn("typo") == "worker"


# ---- class budgets on prefill replicas ----

def test_prefill_replica_honors_class_chunk_budgets(
    make_fleet, monkeypatch,
):
    """A prefill replica still runs the SLO scheduler: a background
    long prompt prefills under its per-window chunk budget (deferred
    chunks counted), it does not monopolize the replica."""
    monkeypatch.setenv("ROOM_TPU_PREFILL_CHUNK_PAGES", "1")
    fleet = make_fleet(n=2, roles=("prefill", "decode"))
    big = list(range(1, 70))   # several 8-token pages of chunks
    t = fleet.submit(big, session_id="bg", sampling=_greedy(2),
                     turn_class="background")
    fleet.run_until_idle()
    assert t.finish_reason == "length"
    eng = fleet._handle("r0").engine
    st = eng.stats()
    assert st["prefill_chunks_interleaved"] > 0, \
        "the prefill replica must chunk the long prompt through the " \
        "scheduler budget, not prefill it monolithically"
