"""Agent-loop tests over the echo provider — the reference's pattern of
mocking only the model boundary and asserting on prompt content, DB
side-effects, and state transitions (reference:
src/shared/__tests__/agent-loop.test.ts)."""

import pytest

from room_tpu.core import (
    agent_loop, goals, memory, messages, quorum, rooms, workers,
)
from room_tpu.core.queen_tools import QUEEN_TOOLS, WORKER_TOOLS, execute_queen_tool
from room_tpu.providers import reset_provider_cache, get_model_provider
from room_tpu.providers.echo import EchoProvider


@pytest.fixture()
def room(db):
    r = rooms.create_room(
        db, "hive", goal="grow revenue", worker_model="echo",
        create_wallet=False,
    )
    agent_loop.set_room_launch_enabled(r["id"], True)
    yield r
    agent_loop.set_room_launch_enabled(r["id"], False)


@pytest.fixture()
def echo(room):
    reset_provider_cache()
    provider = get_model_provider("echo")
    provider.responses.clear()
    provider.tool_script.clear()
    provider.calls.clear()
    provider.fail_with = None
    return provider


def queen_of(db, room):
    return workers.get_worker(db, room["queen_worker_id"])


def test_cycle_records_tokens_and_status(db, room, echo):
    cycle = agent_loop.run_cycle(db, room, queen_of(db, room))
    assert cycle["status"] == "success"
    assert cycle["input_tokens"] > 0 and cycle["output_tokens"] > 0
    assert cycle["finished_at"] is not None


def test_prompt_assembly_order_and_content(db, room, echo):
    queen = queen_of(db, room)
    workers.save_wip(db, queen["id"], "halfway through pricing analysis")
    memory.remember(db, "pricing data", "competitor charges $40",
                    room_id=room["id"])
    quorum.announce(db, room["id"], queen["id"], "redo website",
                    "high_impact")
    messages.add_chat_message(db, room["id"], "user", "status update?")

    agent_loop.run_cycle(db, room, queen)
    prompt = echo.calls[-1].prompt

    assert "CONTINUE FORWARD" in prompt
    assert "halfway through pricing analysis" in prompt
    assert "Room objective: grow revenue" in prompt
    assert "pricing data" in prompt
    assert "redo website" in prompt
    assert "status update?" in prompt
    assert prompt.index("CONTINUE FORWARD") < prompt.index("Room objective")
    # queen gets the queen tool set
    tool_names = {t["name"] for t in echo.calls[-1].tools}
    assert "delegate" in tool_names and "announce_decision" in tool_names


def test_worker_gets_worker_tools_and_assignments(db, room, echo):
    wid = workers.create_worker(db, "W", "do things", room_id=room["id"],
                                role="executor", model="echo")
    root = goals.get_root_goal(db, room["id"])
    goals.create_goal(db, room["id"], "ship feature x",
                      parent_goal_id=root["id"], assigned_worker_id=wid)
    w = workers.get_worker(db, wid)
    agent_loop.run_cycle(db, room, w)
    req = echo.calls[-1]
    assert "ship feature x" in req.prompt
    names = {t["name"] for t in req.tools}
    assert "complete_goal" in names and "delegate" not in names


def test_queen_alone_gets_executor(db, room, echo):
    assert len(workers.list_room_workers(db, room["id"])) == 1
    agent_loop.run_cycle(db, room, queen_of(db, room))
    team = workers.list_room_workers(db, room["id"])
    assert len(team) == 2
    assert any(w["role"] == "executor" for w in team)


def test_cycle_checks_expired_decisions(db, room, echo):
    d = quorum.announce(db, room["id"], None, "x", "high_impact",
                        delay_minutes=0)
    agent_loop.run_cycle(db, room, queen_of(db, room))
    assert quorum.get_decision(db, d["id"])["status"] == "effective"


def test_session_rotation_after_20_cycles(db, room, echo):
    queen = queen_of(db, room)
    db.execute(
        "INSERT INTO agent_sessions(worker_id, session_id, model, "
        "turn_count) VALUES (?,?,?,?)",
        (queen["id"], "old-session", "echo", 20),
    )
    agent_loop.run_cycle(db, room, queen)
    # rotated: the request must NOT carry the old session
    assert echo.calls[-1].session_id is None
    row = db.query_one("SELECT * FROM agent_sessions WHERE worker_id=?",
                       (queen["id"],))
    assert row["turn_count"] == 1


def test_session_persists_and_increments(db, room, echo):
    queen = queen_of(db, room)
    agent_loop.run_cycle(db, room, queen)
    agent_loop.run_cycle(db, room, queen)
    row = db.query_one("SELECT * FROM agent_sessions WHERE worker_id=?",
                       (queen["id"],))
    assert row["turn_count"] == 2
    assert row["session_id"] == "echo-session"


def test_history_compression_at_threshold(db, room, echo):
    import json as _json

    queen = queen_of(db, room)
    long_history = [
        {"role": "user", "content": f"msg {i}"} for i in range(32)
    ]
    db.execute(
        "INSERT INTO agent_sessions(worker_id, session_id, messages_json, "
        "model, turn_count) VALUES (?,?,?,?,?)",
        (queen["id"], "s", _json.dumps(long_history), "echo", 3),
    )
    echo.responses.append("SUMMARY-OF-HISTORY")  # compression call
    agent_loop.run_cycle(db, room, queen)
    # the compressed history was handed to the provider
    cycle_req = echo.calls[-1]
    assert cycle_req.messages is not None
    assert len(cycle_req.messages) < 32
    assert "SUMMARY-OF-HISTORY" in cycle_req.messages[0]["content"]
    # and the summary was persisted to room memory
    hits = memory.fts_search(db, "SUMMARY", room_id=room["id"])
    assert hits


def test_auto_wip_fallback(db, room, echo):
    queen = queen_of(db, room)
    echo.responses.append("I analyzed the funnel and found issues.")
    agent_loop.run_cycle(db, room, queen)
    w = workers.get_worker(db, queen["id"])
    assert w["wip"].startswith("[auto]")
    assert "analyzed the funnel" in w["wip"]


def test_rate_limit_raises_typed_error(db, room, echo):
    echo.fail_with = "429 rate limit exceeded, retry in 2 minutes"
    from room_tpu.providers import RateLimitExceeded

    with pytest.raises(RateLimitExceeded) as e:
        agent_loop.run_cycle(db, room, queen_of(db, room))
    assert e.value.wait_s == 120.0
    cycle = db.query_one(
        "SELECT * FROM worker_cycles ORDER BY id DESC LIMIT 1"
    )
    assert cycle["status"] == "error"


def test_stuck_detector_note(db, room, echo):
    queen = queen_of(db, room)
    for _ in range(5):
        db.insert(
            "INSERT INTO worker_cycles(worker_id, room_id, status) "
            "VALUES (?,?,'error')",
            (queen["id"], room["id"]),
        )
    agent_loop.run_cycle(db, room, queen)
    assert "keep failing" in echo.calls[-1].prompt


def test_delegate_tool_creates_goal_and_assigns(db, room, echo):
    queen = queen_of(db, room)
    wid = workers.create_worker(db, "Builder", "p", room_id=room["id"],
                                role="executor")
    out = execute_queen_tool(
        db, room["id"], queen["id"], "delegate",
        {"description": "build the landing page", "worker_id": wid},
    )
    assert "delegated to Builder" in out
    assigned = goals.active_goals_for_worker(db, wid)
    assert len(assigned) == 1


def test_tool_errors_are_returned_not_raised(db, room, echo):
    out = execute_queen_tool(
        db, room["id"], 1, "object_to_decision",
        {"decision_id": 999, "reason": "x"},
    )
    assert out.startswith("tool error:")


def test_announce_dedupe(db, room):
    queen = queen_of(db, room)
    a = execute_queen_tool(
        db, room["id"], queen["id"], "announce_decision",
        {"proposal": "migrate to k8s", "decision_type": "high_impact"},
    )
    b = execute_queen_tool(
        db, room["id"], queen["id"], "announce_decision",
        {"proposal": "migrate to k8s", "decision_type": "high_impact"},
    )
    assert "already announced" in b


def test_send_message_to_keeper_and_room(db, room):
    queen = queen_of(db, room)
    other = rooms.create_room(db, "other", create_wallet=False)
    out1 = execute_queen_tool(
        db, room["id"], queen["id"], "send_message",
        {"to": "keeper", "body": "weekly report ready"},
    )
    assert "keeper" in out1
    hist = messages.chat_history(db, room["id"])
    assert hist[-1]["content"] == "weekly report ready"
    out2 = execute_queen_tool(
        db, room["id"], queen["id"], "send_message",
        {"to": str(other["id"]), "subject": "hi", "body": "collab?"},
    )
    assert f"room #{other['id']}" in out2
    assert len(messages.unread_messages(db, other["id"])) == 1


def test_quiet_hours_window(db, room, monkeypatch):
    from datetime import datetime

    def at(hhmm):
        """Freeze the loop's clock at hh:mm (deterministic at any CI
        wall time, incl. the first hour after midnight)."""
        h, m = (int(x) for x in hhmm.split(":"))

        class Frozen:
            @staticmethod
            def now():
                return datetime(2026, 1, 15, h, m)

        monkeypatch.setattr(agent_loop, "datetime", Frozen)

    r = dict(rooms.get_room(db, room["id"]))
    # no window configured -> never quiet
    r["queen_quiet_from"] = r["queen_quiet_until"] = None
    at("12:00")
    assert not agent_loop._in_quiet_hours(r)
    # same-day window
    r["queen_quiet_from"], r["queen_quiet_until"] = "09:00", "17:00"
    at("12:00")
    assert agent_loop._in_quiet_hours(r)
    at("08:59")
    assert not agent_loop._in_quiet_hours(r)
    at("17:00")   # end is exclusive
    assert not agent_loop._in_quiet_hours(r)
    # midnight-crossing window 22:00-07:00
    r["queen_quiet_from"], r["queen_quiet_until"] = "22:00", "07:00"
    for hhmm, quiet in (("23:30", True), ("00:30", True),
                        ("06:59", True), ("07:00", False),
                        ("12:00", False)):
        at(hhmm)
        assert agent_loop._in_quiet_hours(r) is quiet, hhmm


def test_wip_momentum_shortens_gap(db, room, echo):
    queen = queen_of(db, room)
    rooms.update_room(db, room["id"], queen_cycle_gap_ms=1_800_000)
    r = rooms.get_room(db, room["id"])
    gap = agent_loop._cycle_gap_s(db, r, queen)
    assert gap == 1800.0
    workers.update_worker(db, queen["id"], wip="mid-flight work note")
    gap = agent_loop._cycle_gap_s(db, r, queen)
    assert gap == agent_loop.WIP_MOMENTUM_GAP_S


def test_worker_gap_overrides_room_gap(db, room, echo):
    wid = workers.create_worker(
        db, "fast", "p", room_id=room["id"], cycle_gap_ms=5_000
    )
    w = workers.get_worker(db, wid)
    r = rooms.get_room(db, room["id"])
    assert agent_loop._cycle_gap_s(db, r, w) == 5.0


def test_cycle_prune_keeps_recent(db, room, echo):
    queen = queen_of(db, room)
    for _ in range(5):
        agent_loop.run_cycle(db, room, queen)
    agent_loop._prune_old_cycles(db, room["id"], keep=2)
    left = db.query(
        "SELECT id FROM worker_cycles WHERE room_id=? ORDER BY id",
        (room["id"],),
    )
    assert len(left) == 2
    # newest survive
    all_max = db.query_one(
        "SELECT MAX(id) AS m FROM worker_cycles")["m"]
    assert left[-1]["id"] == all_max


def test_failed_cycle_records_error(db, room, echo):
    echo.fail_with = "provider exploded"
    queen = queen_of(db, room)
    cycle = agent_loop.run_cycle(db, room, queen)
    assert cycle["status"] == "error"
    assert "provider exploded" in (cycle["error_message"] or "")


def test_trigger_agent_cold_start_requires_flag(db, room, echo):
    queen = queen_of(db, room)
    agent_loop.set_room_launch_enabled(room["id"], False)
    assert not agent_loop.trigger_agent(
        db, room["id"], queen["id"], allow_cold_start=False
    )
    assert agent_loop.trigger_agent(
        db, room["id"], queen["id"], allow_cold_start=True
    )
    # loop is now live; clean up
    agent_loop.pause_agent(queen["id"])
    agent_loop.stop_room_loops(db, room["id"], "test done")


def test_loop_thread_lifecycle(db, room, echo):
    queen = queen_of(db, room)
    # long gap so the loop sleeps after one cycle
    rooms.update_room(db, room["id"], queen_cycle_gap_ms=3_600_000)
    room2 = rooms.get_room(db, room["id"])
    handle = agent_loop.start_agent_loop(db, room2["id"], queen["id"])
    import time

    for _ in range(100):
        if db.query_one(
            "SELECT * FROM worker_cycles WHERE worker_id=?",
            (queen["id"],),
        ):
            break
        time.sleep(0.05)
    assert handle.thread.is_alive()
    agent_loop.pause_agent(queen["id"])
    handle.thread.join(timeout=5)
    assert not handle.thread.is_alive()
    assert workers.get_worker(db, queen["id"])["agent_state"] == "stopped"
