"""Systematic serving-feature interaction matrix (VERDICT r4 #8).

Eviction x prefix-cache x speculation x int8-KV x per-row-penalty,
fully crossed: for each cell, a variant engine exercising the features
must emit EXACTLY the tokens of a plain baseline engine that shares
the cell's numeric config (kv-quant changes numerics legitimately, so
the baseline carries it too — the invariant is that the serving
MACHINERY is token-invisible), and its page accounting must close back
to the fresh-engine state after every session is released.
"""

import itertools

import jax
import pytest

from room_tpu.models import qwen3
from room_tpu.models.config import tiny_moe
from room_tpu.serving import SamplingParams, ServingEngine


@pytest.fixture(scope="module")
def setup():
    cfg = tiny_moe()
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


GREEDY = dict(temperature=0.0, max_new_tokens=5)
# penalties force the per-row sequential path next to spec rows
PENALIZED = dict(temperature=0.0, max_new_tokens=5,
                 presence_penalty=0.4, frequency_penalty=0.2)

CELLS = list(itertools.product(
    ("bf16", "int8"),        # KV cache dtype
    (0, 4),                  # spec_tokens
    (False, True),           # tight pool (forces eviction)
    (False, True),           # one penalized row in the batch
    (False, True),           # shared-prefix prompts (prefix cache)
))


def _prompts(shared_prefix: bool):
    if shared_prefix:
        p1 = list(range(1, 21))
        p2 = p1[:16] + [30, 31, 32, 33]
    else:
        p1 = list(range(1, 21))
        p2 = list(range(40, 60))
    return p1, p2


# baselines depend only on (kv, penalized, shared): cache them so the
# 32-cell sweep runs 8 baselines, not 32; same for fresh free counts
_BASELINES: dict = {}
_FRESH_FREE: dict = {}


def _run_scenario(cfg, params, *, n_pages, spec, penalized, prompts):
    """Submit two concurrent sessions, a repeat of prompt 1 (prefix
    path), then a delta continuation of session 1 (park/evict/resume
    path). Returns (tokens per step, engine)."""
    eng = ServingEngine(cfg, params, max_batch=4, page_size=8,
                        n_pages=n_pages, spec_tokens=spec)
    p1, p2 = prompts
    sp1 = SamplingParams(**GREEDY)
    sp2 = SamplingParams(**(PENALIZED if penalized else GREEDY))
    t1 = eng.submit(p1, session_id="s1", sampling=sp1)
    t2 = eng.submit(p2, session_id="s2", sampling=sp2)
    eng.run_until_idle()
    # repeat of p1 as a fresh session: prefix-cache candidate
    t3 = eng.submit(p1, session_id="s3", sampling=sp1)
    eng.run_until_idle()
    # delta continuation of s1 (if s1 was evicted for s3's pages this
    # re-prefills from the host history mirror)
    t4 = eng.submit([7, 7, 7], session_id="s1", sampling=sp1)
    eng.run_until_idle()
    for t in (t1, t2, t3, t4):
        assert t.finish_reason in ("stop", "length"), t.error
    return [t.new_tokens for t in (t1, t2, t3, t4)], eng


@pytest.mark.parametrize(
    "kv,spec,tight,penalized,shared", CELLS,
    ids=[f"kv={k}-spec={s}-tight={t}-pen={p}-prefix={sh}"
         for k, s, t, p, sh in CELLS],
)
def test_machinery_is_token_invisible(setup, monkeypatch, kv, spec,
                                      tight, penalized, shared):
    cfg, params = setup
    if kv == "int8":
        monkeypatch.setenv("ROOM_TPU_KV_QUANT", "int8")
    else:
        monkeypatch.delenv("ROOM_TPU_KV_QUANT", raising=False)
    prompts = _prompts(shared)

    # baseline: same numerics, generous pool, no spec, same penalties
    base_key = (kv, penalized, shared)
    if base_key not in _BASELINES:
        _BASELINES[base_key], _ = _run_scenario(
            cfg, params, n_pages=64, spec=0, penalized=penalized,
            prompts=prompts,
        )
    want = _BASELINES[base_key]

    # tight pool: page 0 is scratch, so 8 usable pages hold exactly
    # the first two sessions (4 pages each) — s3's admission must
    # evict, and the s1 continuation re-prefills from host history
    n_pages = 9 if tight else 64
    got, eng = _run_scenario(
        cfg, params, n_pages=n_pages, spec=spec, penalized=penalized,
        prompts=prompts,
    )
    assert got == want, {
        "cell": (kv, spec, tight, penalized, shared),
        "stats": eng.stats(),
    }

    if tight:
        assert eng.stats()["evictions"] >= 1, eng.stats()

    # page accounting closes: after releasing every session AND
    # draining the prefix cache (cached prefixes own pages by design),
    # the pool returns to its fresh-engine free count
    if n_pages not in _FRESH_FREE:
        _FRESH_FREE[n_pages] = ServingEngine(
            cfg, params, max_batch=4, page_size=8, n_pages=n_pages,
        ).page_table.free_pages
    for sid in list(eng.sessions):
        eng.release_session(sid)
    while eng._evict_prefix():
        pass
    assert eng.page_table.free_pages == _FRESH_FREE[n_pages], \
        eng.stats()


def test_prefix_cache_engages_in_generous_shared_cells(setup,
                                                      monkeypatch):
    """The matrix must not silently never-exercise the prefix cache:
    in the generous shared-prefix cell the repeat submission hits."""
    monkeypatch.delenv("ROOM_TPU_KV_QUANT", raising=False)
    cfg, params = setup
    _, eng = _run_scenario(
        cfg, params, n_pages=64, spec=0, penalized=False,
        prompts=_prompts(True),
    )
    assert eng.stats()["prefix_hits"] >= 1, eng.stats()


def test_eviction_engages_in_tight_cells(setup, monkeypatch):
    monkeypatch.delenv("ROOM_TPU_KV_QUANT", raising=False)
    cfg, params = setup
    _, eng = _run_scenario(
        cfg, params, n_pages=9, spec=0, penalized=False,
        prompts=_prompts(False),
    )
    assert eng.stats()["evictions"] >= 1, eng.stats()


def test_spec_and_penalized_rows_share_a_batch(monkeypatch):
    """Deterministic spec engagement (8-token vocab forces a greedy
    cycle) with a penalized batchmate: verify rounds must actually
    run, the penalized row must take the sequential path, and both
    rows' tokens must match their plain-engine twins."""
    monkeypatch.delenv("ROOM_TPU_KV_QUANT", raising=False)
    cfg = tiny_moe(vocab_size=8)
    params = qwen3.init_params(cfg, jax.random.PRNGKey(3))
    prompt = [1, 2, 3, 1, 2, 3]
    sp_plain = SamplingParams(temperature=0.0, max_new_tokens=24)
    sp_pen = SamplingParams(temperature=0.0, max_new_tokens=24,
                            presence_penalty=0.4)

    base = ServingEngine(cfg, params, max_batch=4, page_size=8,
                         n_pages=64)
    b1 = base.submit(prompt, sampling=sp_plain)
    b2 = base.submit(prompt, sampling=sp_pen)
    base.run_until_idle()

    eng = ServingEngine(cfg, params, max_batch=4, page_size=8,
                        n_pages=64, spec_tokens=4)
    t1 = eng.submit(prompt, sampling=sp_plain)
    t2 = eng.submit(prompt, sampling=sp_pen)
    eng.run_until_idle()
    st = eng.stats()
    assert st["spec_rounds"] >= 1, st
    assert st["spec_rows_sequential"] >= 1, st
    assert t1.new_tokens == b1.new_tokens
    assert t2.new_tokens == b2.new_tokens
