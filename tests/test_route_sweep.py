"""Route sweep: every registered REST endpoint gets hit at least once
over real HTTP against a seeded DB (VERDICT r1 #9 — route-level
coverage for the whole surface). Contract per route: it must resolve
(no 404-from-router), parse its params/body (no 500), and respond with
a sane status for the seeded state."""

import json
import urllib.error
import urllib.request

import pytest

from room_tpu.db import Database
from room_tpu.server.http import ApiServer
from room_tpu.server.router import Router
from room_tpu.server.routes import register_all_routes


def _all_routes() -> list[tuple[str, str]]:
    r = Router()
    register_all_routes(r)
    out = []
    for method, pattern, _fn in r.routes():
        out.append((method, pattern))
    return sorted(set(out))


# bodies that satisfy each write route's required fields
BODIES = {
    ("POST", "/api/rooms"): {"name": "swept", "workerModel": "echo",
                             "createWallet": False},
    ("PUT", "/api/rooms/:id"): {"goal": "updated"},
    ("POST", "/api/rooms/:id/chat"): {"content": "hello queen"},
    ("POST", "/api/rooms/:id/goals"): {"description": "swept goal"},
    ("POST", "/api/rooms/:id/workers"): {"name": "swept-worker"},
    ("POST", "/api/rooms/:id/messages"):
        {"toRoomId": 1, "subject": "s", "body": "b"},
    ("POST", "/api/rooms/:id/credentials"):
        {"name": "api_key", "value": "secret"},
    ("POST", "/api/rooms/:id/wallet/withdraw"):
        {"to": "0x" + "11" * 20, "amount": "5"},
    ("POST", "/api/rooms/:id/prompts/export"): {},
    ("POST", "/api/rooms/:id/prompts/import"): {},
    ("POST", "/api/rooms/:id/identity/register"): {"dryRun": True},
    ("POST", "/api/workers/:id/start"): {},
    ("PUT", "/api/workers/:id"): {"role": "generalist"},
    ("POST", "/api/goals/:id/complete"): {},
    ("POST", "/api/goals/:id/abandon"): {},
    ("POST", "/api/memory"): {"name": "swept", "content": "fact"},
    ("POST", "/api/decisions/:id/vote"):
        {"workerId": 2, "vote": "approve"},
    ("POST", "/api/decisions/:id/object"): {"workerId": 2},
    ("POST", "/api/decisions/:id/keeper-vote"): {"vote": "yes"},
    ("POST", "/api/skills"): {"name": "swept", "content": "how"},
    ("PUT", "/api/skills/:id"): {"content": "how v2"},
    ("POST", "/api/escalations/:id/answer"): {"answer": "42"},
    ("POST", "/api/escalations/:id/dismiss"): {},
    ("POST", "/api/messages/:id/reply"): {"body": "re"},
    ("POST", "/api/messages/:id/read"): {},
    ("POST", "/api/tasks"): {"name": "swept-task", "prompt": "p",
                             "triggerType": "manual"},
    ("POST", "/api/tasks/:id/run"): {},
    ("POST", "/api/tasks/:id/pause"): {},
    ("POST", "/api/tasks/:id/resume"): {},
    ("PUT", "/api/settings"): {"swept": "1"},
    ("POST", "/api/clerk/message"): {"content": "hi"},
    ("POST", "/v1/chat/completions"): {
        "model": "tpu:tiny-moe",
        "messages": [{"role": "user", "content": "swept"}],
        "max_tokens": 2,
    },
    ("POST", "/v1/embeddings"): {"input": "swept"},
    ("POST", "/api/templates/instantiate"):
        {"template": "ops-room", "workerModel": "echo"},
    ("POST", "/api/watches"):
        {"path": "/tmp/route-sweep", "actionPrompt": "check"},
    ("POST", "/api/invites"): {"ttlDays": 1},
    ("POST", "/api/contacts/email/start"): {"email": "k@e.com"},
    ("POST", "/api/contacts/email/verify"): {"code": "123456"},
    ("POST", "/api/tpu/provision"): {"model": "tiny-moe"},
    ("POST", "/api/tpu/apply"): {"model": "tiny-moe"},
    # a bounded capture so the sweep doesn't leave a 5 s profiler
    # running in the test server process
    ("POST", "/api/tpu/profile"): {"duration_s": 0.05},
    ("POST", "/api/tpu/plan"): {
        "placements": [{"model": "qwen3-coder-30b", "chips": 8}],
        "totalChips": 8, "hbmPerChipGb": 16.0,
    },
    ("POST", "/api/self-mod/:id/revert"): {},
    ("POST", "/api/update/check"): {},
    ("POST", "/api/goals/:id/updates"): {"update": "making progress"},
    ("PUT", "/api/goals/:id"): {"progress": 0.5},
    ("POST", "/api/rooms/:id/decisions"): {"proposal": "swept decision"},
    ("POST", "/api/decisions/:id/resolve"): {"approve": False},
    ("POST", "/api/memory/entities/:id/observations"):
        {"content": "observed"},
    ("POST", "/api/memory/relations"):
        {"fromId": 1, "toId": 1, "relationType": "related_to"},
    ("POST", "/api/rooms/:id/messages/read-all"): {},
    ("PUT", "/api/settings/:key"): {"value": "v"},
    ("PUT", "/api/tasks/:id"): {"name": "renamed"},
    ("POST", "/api/tasks/:id/reset-session"): {},
    ("POST", "/api/clerk/reset"): {},
    ("POST", "/api/workers/:id/stop"): {},
    ("POST", "/api/rooms/:id/restart"): {},
}

# routes where a non-2xx is the correct answer for the seeded state
EXPECTED_NON_2XX = {
    ("POST", "/api/rooms/:id/start"),          # tpu ckpt gate
    ("POST", "/api/workers/:id/start"),        # ditto
    ("POST", "/api/rooms/:id/wallet/withdraw"),  # no chain RPC
    ("GET", "/api/rooms/:id/wallet/balance"),  # no chain RPC
    ("POST", "/api/contacts/email/start"),     # no transport configured
    ("POST", "/api/contacts/email/resend"),
    ("POST", "/api/contacts/email/verify"),    # wrong/absent code
    ("POST", "/api/invites"),                  # no JWT secret set
    ("POST", "/api/decisions/:id/vote"),       # auto-approved already
    ("POST", "/api/decisions/:id/keeper-vote"),
    ("POST", "/api/decisions/:id/object"),
    ("POST", "/api/self-mod/:id/revert"),      # no audit entry 1
    ("POST", "/api/tasks/:id/run"),            # no runtime attached
    ("GET", "/api/providers/:provider/auth"),  # no active session
    ("GET", "/api/providers/auth/sessions/:sid"),   # unknown sid
    ("POST", "/api/providers/:provider/auth/start"),  # "1" not a provider
    ("POST", "/api/providers/auth/sessions/:sid/cancel"),
    ("POST", "/api/providers/:provider/install/start"),
    ("GET", "/api/providers/install/sessions/:sid"),
    ("POST", "/api/providers/install/sessions/:sid/cancel"),
    ("GET", "/api/tpu/provision/:sid"),        # unknown session
    ("GET", "/api/runs/:id"),                  # no runs seeded
    ("POST", "/api/update/check"),             # may 200 w/ error diag
    ("GET", "/api/cycles/:cycle_id/logs"),     # no cycles seeded (may 200 [])
    ("DELETE", "/api/workers/:id"),            # worker 1 is the queen (409)
    ("POST", "/api/decisions/:id/resolve"),    # already auto-approved (409)
    ("POST", "/api/rooms/:id/restart"),        # no runtime attached (503)
}


@pytest.fixture(scope="module")
def swept_server(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("sweep")
    import os

    os.environ["ROOM_TPU_DATA_DIR"] = str(tmp / "data")
    db = Database(":memory:")
    srv = ApiServer(db)
    srv.start()

    from room_tpu.core import (
        escalations, goals, memory, messages, quorum, rooms, selfmod,
        skills, task_runner, wallet,
    )

    room = rooms.create_room(db, "swept", worker_model="echo")
    rid = room["id"]
    goals.create_goal(db, rid, "g1")
    quorum.announce(db, rid, None, "p1")
    escalations.create_escalation(db, rid, "q1")
    messages.send_room_message(db, rid, rid, "s", "b")
    memory.remember(db, "swept-fact", "content")
    skills.create_skill(db, "sk", "content")
    task_runner.create_task(db, "t1", "p", trigger_type="manual")
    wallet.create_room_wallet(db, rid)
    assert selfmod  # imported for parity; audit list may be empty
    yield srv
    srv.stop()


def _hit(server, method, pattern) -> tuple[int, str]:
    path = (
        pattern.replace(":cycle_id", "1").replace(":provider", "1")
        .replace(":sid", "1").replace(":name", "api_key")
        .replace(":id", "1")
    )
    body = BODIES.get((method, pattern))
    if body is None and method in ("POST", "PUT"):
        body = {}
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.port}{path}",
        data=data,
        headers={
            "Authorization": f"Bearer {server.tokens['user']}",
            **({"Content-Type": "application/json"} if data else {}),
        },
        method=method,
    )
    try:
        with urllib.request.urlopen(req, timeout=15) as resp:
            return resp.status, path
    except urllib.error.HTTPError as e:
        return e.code, path


def _sweep(server, routes) -> list[str]:
    failures = []
    for method, pattern in routes:
        status, path = _hit(server, method, pattern)
        if status in (500, 405):
            failures.append(f"{method} {pattern} -> {status}")
        elif (method, pattern) not in EXPECTED_NON_2XX and \
                not (200 <= status < 300):
            failures.append(f"{method} {pattern} -> {status}")
    return failures


def test_sweep_reads_then_writes(swept_server):
    """GET + POST/PUT across the whole surface (DELETEs run in their
    own phase so the seed survives the reads)."""
    routes = [(m, p) for m, p in _all_routes() if m in ("GET",)]
    assert not _sweep(swept_server, routes)


def test_sweep_mutations(swept_server):
    routes = [(m, p) for m, p in _all_routes()
              if m in ("POST", "PUT")]
    failures = _sweep(swept_server, routes)
    # the provision POST kicked off a model-loading thread; let it
    # finish before interpreter teardown (mid-XLA-compile threads abort
    # the process on exit)
    import time

    from room_tpu.providers.tpu import reset_model_hosts
    from room_tpu.server import tpu_manager

    for _ in range(300):
        with tpu_manager._lock:
            live = [
                s for s in tpu_manager._sessions.values()
                if s["status"] == "running"
            ]
        if not live:
            break
        time.sleep(0.1)
    reset_model_hosts()
    assert not failures

# ---- payload shapes (VERDICT r2 #10): beyond "resolves with a sane
# status", the important GETs must return the fields their consumers
# (dashboard panels, MCP tools, CLI status) actually read ----

# pattern -> ("list"|"dict", required keys of the item/dict)
GET_SHAPES = {
    "/api/rooms": ("list", {"id", "name", "status", "launched",
                            "worker_model"}),
    "/api/rooms/:id": ("dict", {"id", "name", "goal", "status"}),
    "/api/rooms/:id/workers": ("list", {"id", "name", "role",
                                        "room_id", "is_default"}),
    "/api/rooms/:id/goals": ("list", {"id", "description", "status"}),
    "/api/rooms/:id/decisions": ("list", {"id", "proposal", "status",
                                          "created_at"}),
    "/api/rooms/:id/queen": ("dict", {"id", "name"}),
    "/api/rooms/:id/credentials": ("list", set()),
    "/api/rooms/:id/wallet": ("dict", {"address"}),
    "/api/workers": ("list", {"id", "name", "room_id"}),
    "/api/workers/:id": ("dict", {"id", "name", "system_prompt"}),
    "/api/goals/:id": ("dict", {"id", "description", "status"}),
    "/api/tasks": ("list", {"id", "name", "prompt", "trigger_type",
                            "run_count", "status"}),
    "/api/tasks/:id": ("dict", {"id", "name", "prompt", "status"}),
    "/api/skills": ("list", {"id", "name", "content"}),
    "/api/escalations": ("list", {"id", "question", "status"}),
    "/api/memory/search?q=swept": ("list",
                                   {"entity_id", "name",
                                    "observations", "score"}),
    "/api/memory/entities": ("list", {"id", "name"}),
    "/api/memory/stats": ("dict", {"entities"}),
    "/api/decisions/:id": ("dict", {"id", "proposal", "status"}),
    "/api/settings": ("dict", set()),
    "/api/status": ("dict", {"version", "platform", "devices",
                             "activeRooms"}),
    "/api/templates": ("dict", {"rooms", "workers"}),
    "/api/tpu/status": ("dict", {"model", "ready", "checks"}),
    "/api/tpu/engines": ("dict", set()),
    "/api/update": ("dict", {"currentVersion", "autoUpdate",
                             "diagnostics"}),
    "/api/watches": ("list", set()),
    "/api/feed": ("list", set()),
    "/api/runs": ("list", set()),
    "/api/providers": ("dict", set()),
    "/api/clerk/status": ("dict", set()),
    "/v1/models": ("dict", {"object", "data"}),
}


def test_get_payload_shapes(swept_server):
    # earlier mutation phases resolved the seeded escalation; the list
    # endpoint only shows open ones, so seed a fresh row to shape-check
    from room_tpu.core import escalations as esc_mod

    esc_mod.create_escalation(swept_server.db, 1, "shape probe?")
    failures = []
    for pattern, (kind, keys) in sorted(GET_SHAPES.items()):
        path = pattern.replace(":id", "1")
        req = urllib.request.Request(
            f"http://127.0.0.1:{swept_server.port}{path}",
            headers={"Authorization":
                     f"Bearer {swept_server.tokens['user']}"},
        )
        try:
            with urllib.request.urlopen(req, timeout=15) as resp:
                out = json.loads(resp.read())
        except urllib.error.HTTPError as e:
            failures.append(f"{pattern} -> {e.code}")
            continue
        enveloped = isinstance(out, dict) and "status" in out
        data = out["data"] if enveloped else out
        if kind == "list":
            if not isinstance(data, list):
                failures.append(f"{pattern}: not a list")
                continue
            if keys:
                if not data:
                    failures.append(f"{pattern}: empty (seed missing)")
                    continue
                missing = keys - set(data[0])
                if missing:
                    failures.append(f"{pattern}: missing {missing}")
        else:
            if not isinstance(data, dict):
                failures.append(f"{pattern}: not a dict")
                continue
            missing = keys - set(data)
            if missing:
                failures.append(f"{pattern}: missing {missing}")
    assert not failures, "\n".join(failures)


# pattern -> keys required in the POST/PUT response's data payload
WRITE_SHAPES = {
    ("POST", "/api/rooms"): {"id", "name", "queen_worker_id",
                             "status"},
    ("POST", "/api/rooms/:id/goals"): {"id", "description", "status"},
    ("POST", "/api/rooms/:id/workers"): {"id", "name", "room_id"},
    ("POST", "/api/rooms/:id/chat"): set(),   # clerk/queen reply text
    ("POST", "/api/memory"): {"entityId"},
    ("POST", "/api/skills"): {"id", "name", "content"},
    ("POST", "/api/tasks"): {"id", "name", "status"},
    ("POST", "/api/watches"): {"id"},
    ("POST", "/api/templates/instantiate"): {"id", "name",
                                             "queen_worker_id"},
    ("PUT", "/api/rooms/:id"): {"id", "goal"},
    ("PUT", "/api/settings"): set(),
}

WRITE_BODIES = {
    ("POST", "/api/rooms"): {"name": "shaped", "workerModel": "echo",
                             "createWallet": False},
    ("POST", "/api/rooms/:id/goals"): {"description": "shaped goal"},
    ("POST", "/api/rooms/:id/workers"): {"name": "shaped-worker"},
    ("POST", "/api/rooms/:id/chat"): {"content": "hello"},
    ("POST", "/api/memory"): {"name": "shaped", "content": "c"},
    ("POST", "/api/skills"): {"name": "shaped", "content": "how"},
    ("POST", "/api/tasks"): {"name": "shaped-task", "prompt": "p",
                             "triggerType": "manual"},
    ("POST", "/api/watches"): {"path": "/tmp/shaped-watch",
                               "actionPrompt": "act"},
    ("POST", "/api/templates/instantiate"):
        {"template": "research-desk", "workerModel": "echo"},
    ("PUT", "/api/rooms/:id"): {"goal": "shaped objective"},
    ("PUT", "/api/settings"): {"shaped_key": "1"},
}


def test_write_payload_shapes(swept_server):
    """Write endpoints return the created/updated entity with the
    fields the dashboard immediately re-renders from (VERDICT r2 #10:
    payload assertions beyond sane-status)."""
    failures = []
    for (method, pattern), keys in sorted(WRITE_SHAPES.items()):
        path = pattern.replace(":id", "1")
        body = WRITE_BODIES[(method, pattern)]
        req = urllib.request.Request(
            f"http://127.0.0.1:{swept_server.port}{path}",
            data=json.dumps(body).encode(),
            headers={
                "Authorization":
                    f"Bearer {swept_server.tokens['user']}",
                "Content-Type": "application/json",
            },
            method=method,
        )
        try:
            with urllib.request.urlopen(req, timeout=15) as resp:
                out = json.loads(resp.read())
        except urllib.error.HTTPError as e:
            failures.append(f"{method} {pattern} -> {e.code}")
            continue
        data = out.get("data")
        if keys:
            if not isinstance(data, dict):
                failures.append(f"{method} {pattern}: data not a dict")
                continue
            missing = keys - set(data)
            if missing:
                failures.append(
                    f"{method} {pattern}: missing {missing}"
                )
    assert not failures, "\n".join(failures)



def test_sweep_deletes_last(swept_server):
    # children before their room: DELETE /api/rooms/:id cascades, so it
    # must run after the credential/worker deletes
    routes = sorted(
        ((m, p) for m, p in _all_routes() if m == "DELETE"),
        key=lambda mp: (mp[1] == "/api/rooms/:id", mp[1]),
    )
    assert not _sweep(swept_server, routes)


def test_sweep_covers_everything():
    """The three phases together touch every registered route."""
    all_routes = _all_routes()
    assert len(all_routes) >= 100
    methods = {m for m, _ in all_routes}
    assert methods == {"GET", "POST", "PUT", "DELETE"}
