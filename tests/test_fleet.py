"""Engine replica fleet suite (docs/fleet.md).

Pins the fleet contract on the CPU backend:

- KV-affinity routing: a session's turns always land on the replica
  holding its KV/history; fresh sessions spread by health score.
- Crash failover: killing a replica mid-decode-window re-homes its
  sessions onto siblings with ZERO durably-streamed tokens lost and
  greedy continuations token-identical to an unkilled run — warm via
  adopted spool files where a hibernate landed them, re-prefill from
  the router's history mirror otherwise. The real crash-loop path
  (engine_crash past the restart budget) rides the same re-homing.
- Blue/green: draining one replica lets in-flight turns finish (no
  503s to queen-class turns), absorbs its sessions into siblings
  byte-exact, and `rebuild_replica` re-admits the slot.
- The two fleet fault points: `replica_crash` (supervisor kills the
  busiest replica; recovery is the failover above) and `router_io`
  (bounded retry; exhaustion sheds with the 503 contract — a session
  is never misrouted).
- Satellite pins: /api/tpu/health keyed per replica + fleet aggregate;
  `fused_window_disabled_reason` diagnosability; the PID re-tag on
  adopt protecting a just-handed-off session from the donor's orphan
  sweep through REPEATED failovers.
"""

import os
import threading
import time

import jax
import pytest

from room_tpu.models import qwen3, tiny_moe
from room_tpu.serving import SamplingParams, ServingEngine, faults
from room_tpu.serving import lifecycle
from room_tpu.serving.fleet import EngineFleet


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture(scope="module")
def model():
    cfg = tiny_moe()
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


@pytest.fixture()
def make_fleet(model, monkeypatch, tmp_path):
    """Fleet factory: prefix cache off (every session's KV is
    spoolable), shared offload spool + lifecycle dirs under tmp_path,
    no stop tokens (greedy streams run to budget, so interruption
    points are controllable)."""
    monkeypatch.setenv("ROOM_TPU_PREFIX_CACHE_PAGES", "0")
    monkeypatch.setenv("ROOM_TPU_OFFLOAD_DIR", str(tmp_path / "spool"))
    monkeypatch.setenv("ROOM_TPU_LIFECYCLE_DIR", str(tmp_path / "lc"))
    cfg, params = model

    def build_engine(**kw):
        kw.setdefault("max_batch", 4)
        kw.setdefault("page_size", 8)
        kw.setdefault("n_pages", 96)
        kw.setdefault("offload", True)
        kw.setdefault("stop_token_ids", [])
        return ServingEngine(cfg, params, **kw)

    def build(n=3, auto_rebuild=False, **kw):
        return EngineFleet(
            "tiny-moe", lambda i: build_engine(**kw), n,
            auto_rebuild=auto_rebuild,
        )

    build.engine = build_engine
    return build


def _greedy(n=8):
    return SamplingParams(temperature=0.0, max_new_tokens=n)


PROMPT = list(range(1, 20))
CONT = [7, 7, 7]


@pytest.fixture(scope="module")
def control(model):
    """Uninterrupted two-turn reference streams."""
    cfg, params = model
    eng = ServingEngine(
        cfg, params, max_batch=4, page_size=8, n_pages=96,
        offload=False, stop_token_ids=[],
    )
    c1 = eng.submit(PROMPT, session_id="s", sampling=_greedy())
    eng.run_until_idle()
    c2 = eng.submit(CONT, session_id="s", sampling=_greedy())
    eng.run_until_idle()
    return c1.new_tokens, c2.new_tokens


# ---- routing ----

def test_affinity_keeps_session_on_its_replica(make_fleet, control):
    c1, c2 = control
    fleet = make_fleet()
    t1 = fleet.submit(PROMPT, session_id="s", sampling=_greedy())
    fleet.run_until_idle()
    assert t1.new_tokens == c1
    home = fleet._records["s"].rid
    t2 = fleet.submit(CONT, session_id="s", sampling=_greedy())
    fleet.run_until_idle()
    assert fleet._records["s"].rid == home, \
        "a placed session must stay on its replica (KV affinity)"
    assert t2.new_tokens == c2


def test_fresh_sessions_spread_by_health_score(make_fleet):
    fleet = make_fleet()
    # submit without stepping: each queued turn raises its replica's
    # queue depth, so the router spreads the next session elsewhere
    for i in range(3):
        fleet.submit(PROMPT, session_id=f"s{i}", sampling=_greedy(2))
    homes = {fleet._records[f"s{i}"].rid for i in range(3)}
    assert len(homes) == 3, f"expected 3 distinct homes, got {homes}"
    fleet.run_until_idle()


def test_class_priority_rides_through_to_the_replica(make_fleet):
    fleet = make_fleet(n=2)
    t = fleet.submit(
        PROMPT, session_id="q", sampling=_greedy(2), turn_class="queen",
    )
    fleet.run_until_idle()
    assert t.finish_reason == "length"
    eng = fleet._handle(fleet._records["q"].rid).engine
    assert eng.scheduler.snapshot(0)["classes"]["queen"]["completed"] >= 1


# ---- crash failover (THE acceptance canary) ----

def test_kill_mid_decode_window_zero_streamed_token_loss(
    make_fleet, control,
):
    """Kill a replica while its decode window is in flight: every
    DURABLY-streamed token survives (the mirror carries the streamed
    prefix), the in-flight window's undrained tokens are dropped (they
    never reached a client), and the resumed stream continues exactly
    where the durable stream stopped — token-identical to an unkilled
    run. Sibling replicas' sessions are untouched."""
    cfg_budget = 32
    fleet = make_fleet()
    ctrl = make_fleet(n=1)
    full = ctrl.submit(PROMPT, session_id="s", sampling=_greedy(cfg_budget))
    ctrl.run_until_idle()
    assert len(full.new_tokens) == cfg_budget

    streamed: list[int] = []
    t1 = fleet.submit(
        PROMPT, session_id="s", sampling=_greedy(cfg_budget),
        on_token=streamed.append,
    )
    # a sibling session that must ride through the failover untouched
    bystander = fleet.submit(
        PROMPT, session_id="b", sampling=_greedy(4),
    )
    victim = fleet._handle(fleet._records["s"].rid)
    victim.engine.steps_per_dispatch = 4
    # step the victim until a window is in flight and tokens streamed
    for _ in range(200):
        victim.engine.step()
        if streamed and victim.engine._inflight is not None:
            break
    assert streamed and victim.engine._inflight is not None, \
        "kill point must be mid-decode-window with a streamed prefix"
    n_streamed = len(streamed)
    fleet.kill_replica(victim.rid, "chaos: mid-window kill")
    # the failed turn reports the shed contract; its streamed tokens
    # are exactly the durable prefix
    assert t1.done.is_set() and t1.finish_reason == "error"
    assert t1.new_tokens == streamed
    assert 0 < n_streamed < cfg_budget
    assert streamed == full.new_tokens[:n_streamed]
    assert fleet._records["s"].rid != victim.rid

    fleet.run_until_idle()   # bystander finishes on its own replica
    assert bystander.finish_reason == "length"

    t2 = fleet.submit(
        [], session_id="s", sampling=_greedy(cfg_budget - n_streamed),
    )
    fleet.run_until_idle()
    assert streamed + t2.new_tokens == full.new_tokens, \
        "failover dropped or duplicated streamed tokens"


def test_failover_warm_via_adopted_spool(make_fleet, control):
    """A hibernated session hands its byte-exact spool to a sibling:
    the continuation restores (offload_restores) instead of
    re-prefilling, token-identical."""
    _, c2 = control
    fleet = make_fleet()
    fleet.submit(PROMPT, session_id="s", sampling=_greedy())
    fleet.run_until_idle()
    victim = fleet._handle(fleet._records["s"].rid)
    assert victim.engine.offload_session("s")
    fleet.kill_replica(victim.rid, "test")
    assert fleet._stats["sessions_rehomed_warm"] == 1
    target = fleet._handle(fleet._records["s"].rid)
    assert target.rid != victim.rid
    assert target.engine.offload_store.has("s")
    t2 = fleet.submit(CONT, session_id="s", sampling=_greedy())
    fleet.run_until_idle()
    assert t2.new_tokens == c2
    st = target.engine.stats()
    assert st["offload_restores"] == 1 and st["offload_reprefills"] == 0


def test_crash_storm_across_three_replicas(make_fleet, control):
    """Repeated failovers: kill the session's home replica, rebuild
    it, kill the new home — the session survives every hop with
    greedy continuations token-identical (the mirror + adoption chain
    never loses a streamed token)."""
    c1, c2 = control
    fleet = make_fleet()
    t1 = fleet.submit(PROMPT, session_id="s", sampling=_greedy())
    fleet.run_until_idle()
    assert t1.new_tokens == c1
    for _ in range(2):
        victim = fleet._handle(fleet._records["s"].rid)
        fleet.kill_replica(victim.rid, "storm")
        assert fleet._records["s"].rid != victim.rid
        assert fleet.rebuild_replica(victim.rid)
    assert fleet._stats["failovers"] == 2
    t2 = fleet.submit(CONT, session_id="s", sampling=_greedy())
    fleet.run_until_idle()
    assert t2.new_tokens == c2


def test_engine_crash_loop_past_budget_triggers_failover(
    make_fleet, control, monkeypatch,
):
    """The REAL death path: engine_crash armed permanent crash-loops
    one replica past its restart budget; _recover_from_crash preserves
    crash_salvage, the supervisor buries the replica, and the session
    continues on a sibling token-identical."""
    monkeypatch.setenv("ROOM_TPU_ENGINE_MAX_RESTARTS", "1")
    _, c2 = control
    fleet = make_fleet()
    fleet.submit(PROMPT, session_id="s", sampling=_greedy())
    fleet.run_until_idle()
    victim = fleet._handle(fleet._records["s"].rid)
    faults.inject("engine_crash", transient=False)
    # crash-loop the victim only: drive its steps directly
    for _ in range(8):
        try:
            victim.engine.step()
        except Exception as e:
            if not victim.engine._recover_from_crash(e):
                break
    faults.clear("engine_crash")
    assert not victim.engine.healthy
    assert victim.engine.crash_salvage is not None
    fleet.supervise()
    assert victim.state == "dead"
    new_home = fleet._records["s"].rid
    assert new_home != victim.rid
    t2 = fleet.submit(CONT, session_id="s", sampling=_greedy())
    fleet.run_until_idle()
    assert t2.new_tokens == c2


def test_fatal_crash_warm_salvage_survives_store_clear(
    model, control, monkeypatch,
):
    """Regression (review finding): the fatal crash's
    offload_store.clear() must NOT rmtree a store-OWNED spool dir —
    crash_salvage just detached spool files in that dir for a sibling
    to adopt, and the rmtree deleted the bytes out from under the
    hand-off (silently degrading every 'warm' failover to re-prefill).
    No ROOM_TPU_OFFLOAD_DIR here: the store must own its dir."""
    monkeypatch.setenv("ROOM_TPU_PREFIX_CACHE_PAGES", "0")
    monkeypatch.delenv("ROOM_TPU_OFFLOAD_DIR", raising=False)
    monkeypatch.setenv("ROOM_TPU_ENGINE_MAX_RESTARTS", "0")
    _, c2 = control
    cfg, params = model

    def build(i):
        return ServingEngine(
            cfg, params, max_batch=4, page_size=8, n_pages=96,
            offload=True, stop_token_ids=[],
        )

    fleet = EngineFleet("tiny-moe", build, 2, auto_rebuild=False)
    fleet.submit(PROMPT, session_id="s", sampling=_greedy())
    fleet.run_until_idle()
    victim = fleet._handle(fleet._records["s"].rid)
    assert victim.engine.offload_session("s")
    faults.inject("engine_crash", transient=False, times=1)
    try:
        victim.engine.step()
    except Exception as e:
        assert not victim.engine._recover_from_crash(e)
    assert not victim.engine.healthy
    kv = victim.engine.crash_salvage["s"]["kv"]
    assert kv is not None and os.path.exists(kv["file"]), \
        "clear() deleted the salvaged spool file"
    fleet.supervise()
    assert fleet._stats["sessions_rehomed_warm"] == 1
    target = fleet._handle(fleet._records["s"].rid)
    assert target.engine.offload_store.has("s")
    t2 = fleet.submit(CONT, session_id="s", sampling=_greedy())
    fleet.run_until_idle()
    assert t2.new_tokens == c2
    st = target.engine.stats()
    assert st["offload_restores"] == 1 and st["offload_reprefills"] == 0


def test_drain_applies_queued_adoptions(make_fleet, tmp_path):
    """Regression (review finding): an adoption enqueued while a loop
    thread owned the engine, but not yet applied when the thread
    exited, must ride the drain's manifest — its donor manifest is
    already consumed, so dropping it would lose the session."""
    eng = make_fleet.engine()

    class FakeAliveThread:
        @staticmethod
        def is_alive():
            return True

    eng._loop_thread = FakeAliveThread()
    ev = eng.adopt_parked_session({
        "id": "handed-off", "history": [1, 2, 3], "pending": 4,
        "length": 3, "generation": 0, "kv": None,
    })
    assert not ev.is_set(), "must queue while a loop owns the engine"
    eng._loop_thread = None
    lc_dir = str(tmp_path / "drainlc")
    summary = eng.drain(lc_dir)
    assert ev.is_set(), "drain must apply queued adoptions"
    assert summary["manifest_written"]
    import json

    with open(os.path.join(lc_dir, lifecycle.MANIFEST_NAME)) as f:
        ids = [e["id"] for e in json.load(f)["sessions"]]
    assert "handed-off" in ids


def test_failover_with_no_sibling_defers_then_adopts(
    make_fleet, control,
):
    """Regression (review finding): when a replica dies with NO
    serving sibling to absorb its sessions, the router must keep the
    history (deferred entry on the record) and adopt it into the next
    replica that serves — never silently drop the conversation."""
    _, c2 = control
    fleet = make_fleet(n=2)
    fleet.submit(PROMPT, session_id="s", sampling=_greedy())
    fleet.run_until_idle()
    first = fleet._records["s"].rid
    fleet.kill_replica(first, "kill 1")
    second = fleet._records["s"].rid
    assert second != first
    fleet.kill_replica(second, "kill 2")
    rec = fleet._records["s"]
    assert rec.rid == "" and rec.pending_entry is not None, \
        "no-sibling failover must defer, not drop, the session"
    assert fleet.rebuild_replica(first)
    t2 = fleet.submit(CONT, session_id="s", sampling=_greedy())
    fleet.run_until_idle()
    assert t2.new_tokens == c2, "deferred re-home lost history"


def test_lone_engine_fatal_crash_does_not_detach_spools(
    model, control, monkeypatch,
):
    """Regression (review finding): an UNSUPERVISED engine's fatal
    crash must not detach spool files (nothing will ever adopt them)
    — the store clears fully, spool dir included."""
    monkeypatch.setenv("ROOM_TPU_PREFIX_CACHE_PAGES", "0")
    monkeypatch.delenv("ROOM_TPU_OFFLOAD_DIR", raising=False)
    monkeypatch.setenv("ROOM_TPU_ENGINE_MAX_RESTARTS", "0")
    cfg, params = model
    eng = ServingEngine(cfg, params, max_batch=4, page_size=8,
                        n_pages=96, offload=True, stop_token_ids=[])
    eng.submit(PROMPT, session_id="s", sampling=_greedy())
    eng.run_until_idle()
    assert eng.offload_session("s")
    spool_dir = eng.offload_store._spool_dir
    faults.inject("engine_crash", transient=False, times=1)
    try:
        eng.step()
    except Exception as e:
        assert not eng._recover_from_crash(e)
    assert eng.crash_salvage is None
    assert spool_dir is None or not os.path.isdir(spool_dir), \
        "lone-engine crash must not leak a preserved spool dir"


def test_no_serving_replica_sheds_with_503_contract(make_fleet):
    fleet = make_fleet(n=2)
    for h in list(fleet.replicas):
        fleet.kill_replica(h.rid, "test")
    t = fleet.submit(PROMPT, session_id="x", sampling=_greedy())
    assert t.done.is_set() and t.shed and t.finish_reason == "error"


# ---- fault points ----

def test_replica_crash_fault_point_recovers(make_fleet, control):
    """faults.inject("replica_crash") kills the busiest replica at the
    next supervision pass; recovery is the standard failover — the
    surviving session's continuation is token-identical."""
    c1, c2 = control
    fleet = make_fleet()
    t1 = fleet.submit(PROMPT, session_id="s", sampling=_greedy())
    fleet.run_until_idle()
    assert t1.new_tokens == c1
    faults.inject("replica_crash", times=1)
    fleet.supervise()
    assert faults.fired("replica_crash") == 1
    assert fleet._stats["failovers"] == 1
    assert sum(1 for h in fleet.replicas if h.state == "dead") == 1
    t2 = fleet.submit(CONT, session_id="s", sampling=_greedy())
    fleet.run_until_idle()
    assert t2.new_tokens == c2


def test_router_io_transient_retries_then_routes(make_fleet, control):
    c1, _ = control
    fleet = make_fleet()
    faults.inject("router_io", times=1)
    t = fleet.submit(PROMPT, session_id="s", sampling=_greedy())
    fleet.run_until_idle()
    assert not t.shed and t.new_tokens == c1
    assert fleet._stats["router_retries"] == 1


def test_router_io_exhaustion_sheds_never_misroutes(make_fleet):
    """Past the retry budget the turn sheds cleanly (503 contract);
    the session is NEVER placed on an arbitrary replica."""
    fleet = make_fleet()
    faults.inject("router_io", times=10)
    t = fleet.submit(PROMPT, session_id="s", sampling=_greedy())
    assert t.done.is_set() and t.shed and t.finish_reason == "error"
    assert "s" not in fleet._records, "a shed turn must not place"
    faults.clear("router_io")
    # permanent faults short-circuit the retry loop
    faults.inject("router_io", transient=False)
    t2 = fleet.submit(PROMPT, session_id="s2", sampling=_greedy())
    assert t2.shed and "s2" not in fleet._records


# ---- blue/green ----

def test_bluegreen_drain_absorbs_warm_no_queen_503(
    make_fleet, control,
):
    """The deploy primitive: drain one replica of a busy fleet —
    in-flight turns finish streaming (nothing shed), its sessions
    absorb into siblings byte-exact (spool adoption, not re-prefill),
    queen turns keep flowing with zero 503s, and the drained slot
    re-admits a fresh build."""
    c1, c2 = control
    fleet = make_fleet()
    turns = [
        fleet.submit(PROMPT, session_id=f"s{i}",
                     sampling=_greedy(), turn_class="queen")
        for i in range(3)
    ]
    fleet.run_until_idle()
    assert all(t.new_tokens == c1 for t in turns)
    victim_rid = fleet._records["s0"].rid
    summary = fleet.drain_replica(victim_rid)
    assert summary["manifest_written"]
    assert summary["absorbed"]["resumed"] >= 1, \
        "blue/green handoff must adopt spooled KV, not re-prefill"
    assert summary["absorbed"]["reprefill"] == 0
    # every queen continuation — including the moved sessions — flows
    # with no 503 and token-identical streams
    conts = [
        fleet.submit(CONT, session_id=f"s{i}",
                     sampling=_greedy(), turn_class="queen")
        for i in range(3)
    ]
    fleet.run_until_idle()
    for t in conts:
        assert not t.shed and t.finish_reason == "length"
        assert t.new_tokens == c2
    assert all(
        fleet._records[f"s{i}"].rid != victim_rid for i in range(3)
    )
    # swap in the "new build" and verify the slot serves again
    assert fleet.rebuild_replica(victim_rid)
    assert fleet._handle(victim_rid).is_serving()


def test_drain_refuses_last_serving_replica(make_fleet):
    fleet = make_fleet(n=2)
    fleet.kill_replica("r0", "test")
    out = fleet.drain_replica("r1")
    assert "error" in out


def test_failover_during_in_progress_drain(make_fleet, control):
    """Crash a SIBLING while a blue/green drain is absorbing: the
    drained replica's sessions and the crashed replica's sessions all
    land somewhere serving, token-identical."""
    c1, c2 = control
    fleet = make_fleet()
    for i in range(3):
        fleet.submit(PROMPT, session_id=f"s{i}", sampling=_greedy())
    fleet.run_until_idle()
    homes = {i: fleet._records[f"s{i}"].rid for i in range(3)}
    distinct = sorted(set(homes.values()))
    assert len(distinct) == 3
    drain_rid = homes[0]
    # kill a sibling FIRST so the drain's absorb must route around it
    crash_rid = next(r for r in distinct if r != drain_rid)
    fleet.kill_replica(crash_rid, "mid-drain crash")
    summary = fleet.drain_replica(drain_rid)
    assert summary["manifest_written"]
    survivor = next(
        r for r in distinct if r not in (drain_rid, crash_rid)
    )
    for i in range(3):
        assert fleet._records[f"s{i}"].rid == survivor
        t = fleet.submit(CONT, session_id=f"s{i}", sampling=_greedy())
        fleet.run_until_idle()
        assert t.new_tokens == c2, f"s{i} diverged"


def test_fleet_drain_restore_roundtrip_tolerates_resize(
    make_fleet, control, tmp_path,
):
    """Process-level lifecycle: a 3-replica fleet drains (per-replica
    manifests, manifest_written ANDed), and a DIFFERENT-sized fleet
    restores every session — warm — on the next boot."""
    c1, c2 = control
    lc_dir = str(tmp_path / "lc" / "engines" / "tiny-moe")
    fleet = make_fleet()
    for i in range(3):
        t = fleet.submit(PROMPT, session_id=f"s{i}", sampling=_greedy())
    fleet.run_until_idle()
    summary = fleet.drain(lc_dir)
    assert summary["manifest_written"]
    assert summary["sessions_spooled"] == 3
    assert len(summary["replicas"]) == 3

    fleet2 = make_fleet(n=2)
    restored = fleet2.restore_from_manifest(lc_dir)
    assert restored["manifest"] and restored["resumed"] == 3
    for i in range(3):
        t2 = fleet2.submit(CONT, session_id=f"s{i}", sampling=_greedy())
        fleet2.run_until_idle()
        assert t2.new_tokens == c2, f"s{i} diverged across restart"


def test_fleet_drain_restores_into_single_engine(
    make_fleet, control, tmp_path,
):
    """Regression (review finding): rolling a fleet deployment back to
    ROOM_TPU_FLEET_REPLICAS=1 must not lose the fleet's drained
    sessions — a plain ServingEngine's restore absorbs the
    per-replica sub-manifests too."""
    _, c2 = control
    lc_dir = str(tmp_path / "lc" / "engines" / "tiny-moe")
    fleet = make_fleet()
    for i in range(3):
        fleet.submit(PROMPT, session_id=f"s{i}", sampling=_greedy())
    fleet.run_until_idle()
    assert fleet.drain(lc_dir)["manifest_written"]

    eng = make_fleet.engine()
    restored = eng.restore_from_manifest(lc_dir)
    assert restored["manifest"] and restored["resumed"] == 3
    for i in range(3):
        t = eng.submit(CONT, session_id=f"s{i}", sampling=_greedy())
        eng.run_until_idle()
        assert t.new_tokens == c2, f"s{i} diverged across rollback"


def test_absorb_missing_fingerprint_reprefills_never_vouches(
    make_fleet, control, tmp_path,
):
    """Regression (review finding): a manifest MISSING its fingerprint
    must degrade its KV entries to re-prefill at absorb — None must
    not read as 'caller vouches for config identity'."""
    import glob
    import json

    _, c2 = control
    lc_dir = str(tmp_path / "lc" / "engines" / "tiny-moe")
    fleet = make_fleet()
    fleet.submit(PROMPT, session_id="s", sampling=_greedy())
    fleet.run_until_idle()
    assert fleet.drain(lc_dir)["sessions_spooled"] == 1
    for mf in glob.glob(os.path.join(lc_dir, "replica-*",
                                     "manifest.json")):
        with open(mf) as f:
            m = json.load(f)
        m.pop("fingerprint", None)
        with open(mf, "w") as f:
            json.dump(m, f)

    fleet2 = make_fleet(n=2)
    restored = fleet2.restore_from_manifest(lc_dir)
    assert restored["resumed"] == 0 and restored["reprefill"] == 1
    t = fleet2.submit(CONT, session_id="s", sampling=_greedy())
    fleet2.run_until_idle()
    assert t.new_tokens == c2, "re-prefill fallback diverged"


# ---- satellite: PID re-tag vs the donor's orphan sweep ----

def test_adopt_retag_survives_donor_sweep_through_repeated_failovers(
    make_fleet, control, tmp_path,
):
    """Satellite pin: TieredKVStore.adopt re-tags a handed-off spool
    with the adopting PID, so the donor's (or any third sibling's)
    age-0 orphan sweep can never delete a live engine's adopted
    session — through REPEATED blue/green handoffs of the same
    session."""
    _, c2 = control
    fleet = make_fleet()
    fleet.submit(PROMPT, session_id="s", sampling=_greedy())
    fleet.run_until_idle()
    for hop in range(2):
        rid = fleet._records["s"].rid
        summary = fleet.drain_replica(rid)
        assert summary["absorbed"]["resumed"] == 1, f"hop {hop}"
        handoff = summary["dir"]
        # the donor's own hygiene pass, max-age 0: everything
        # unprotected dies NOW — the adopted spool must survive on
        # its live-PID tag alone (the manifest is already consumed)
        lifecycle.sweep_orphans(handoff, max_age_s=0.0)
        target = fleet._handle(fleet._records["s"].rid)
        assert target.engine.offload_store.has("s"), f"hop {hop}"
        assert fleet.rebuild_replica(rid)
    t2 = fleet.submit(CONT, session_id="s", sampling=_greedy())
    fleet.run_until_idle()
    assert t2.new_tokens == c2, "sweep destroyed adopted KV"
    st = fleet._handle(fleet._records["s"].rid).engine.stats()
    assert st["offload_restores"] == 1 and st["offload_reprefills"] == 0


def test_adopt_retag_protects_against_foreign_pid_sweep(
    tmp_path, monkeypatch,
):
    """Cross-process story, unit-level: a spool file tagged with a
    DEAD donor PID is adopted (re-tagged to the live PID); the dead
    donor's sweep then removes genuinely orphaned files but never the
    adopted one."""
    import numpy as np

    from room_tpu.serving import lifecycle as lc
    from room_tpu.serving.kv_offload import TieredKVStore, _write_spool

    monkeypatch.setenv("ROOM_TPU_OFFLOAD_DIR", str(tmp_path))
    dead_pid = 4100100  # beyond pid_max on any default Linux host
    monkeypatch.setattr(
        lc, "_pid_alive", lambda pid: pid == os.getpid()
    )
    arrays = {"k": np.arange(16, dtype=np.int8).reshape(2, 8)}
    donor_file = str(tmp_path / f"pid{dead_pid}-cafe.kvspool")
    _write_spool(donor_file, arrays)
    orphan_file = str(tmp_path / f"pid{dead_pid}-dead.kvspool")
    _write_spool(orphan_file, arrays)

    store = TieredKVStore(spool_dir=str(tmp_path))
    nbytes = os.path.getsize(donor_file)
    assert store.adopt("s", donor_file, 16, 2, nbytes)
    # the donor's sweep: age 0, no manifest — only the PID tag saves
    # the adopted file
    removed = lc.sweep_orphans(str(tmp_path), max_age_s=0.0)
    assert removed == 1 and not os.path.exists(orphan_file)
    assert store.has("s")
    got = store.get("s")
    assert got is not None
    np.testing.assert_array_equal(got[1]["k"], arrays["k"])
    # second failover: the next store adopts the already-live-tagged
    # file (same PID — no rename needed) and a third sibling's sweep
    # still cannot touch it
    entry = store.export_entry("s")
    assert entry is not None
    store2 = TieredKVStore(spool_dir=str(tmp_path))
    assert store2.adopt("s", entry["file"], 16, 2, entry["nbytes"])
    assert lc.sweep_orphans(str(tmp_path), max_age_s=0.0) == 0
    got2 = store2.get("s")
    np.testing.assert_array_equal(got2[1]["k"], arrays["k"])


# ---- observability ----

def test_health_route_keys_engine_blocks_per_replica(
    make_fleet, monkeypatch,
):
    """Satellite pin: /api/tpu/health must key fleet siblings'
    scheduler/offload/lifecycle blocks by replica id (model#rid) —
    not collapse them under the model name — plus a fleet aggregate
    with router/failover counters."""
    import room_tpu.providers.tpu as tpu_mod
    from room_tpu.server.router import RequestContext, Router
    from room_tpu.server.routes import register_all_routes

    fleet = make_fleet()
    fleet.submit(PROMPT, session_id="s", sampling=_greedy())
    fleet.run_until_idle()
    fleet.kill_replica(fleet._records["s"].rid, "test")

    class FakeHost:
        _engine = fleet

        @staticmethod
        def is_healthy():
            return True

    monkeypatch.setattr(tpu_mod, "_hosts", {"tiny-moe": FakeHost()})
    router = Router()
    register_all_routes(router)
    handler, params = router.match("GET", "/api/tpu/health")
    out = handler(RequestContext(
        method="GET", path="/api/tpu/health", params=params, query={},
        body=None,
    ))
    engines = out["data"]["engines"]
    rows = {k for k in engines if k.startswith("tiny-moe#")}
    assert rows == {"tiny-moe#r0", "tiny-moe#r1", "tiny-moe#r2"}
    agg = engines["tiny-moe"]
    assert agg["fleet"]["replicas"] == 3
    assert agg["fleet"]["failovers"] == 1
    assert agg["fleet"]["sessions_rehomed"] == 1
    # per-replica blocks are the FULL engine surface, not a summary:
    # each sibling keeps its own scheduler/offload/lifecycle blocks
    for rid in rows:
        row = engines[rid]
        assert "scheduler" in row and "offload" in row
        assert "lifecycle" in row and "replica" in row
    dead = [r for r in rows if engines[r]["replica"]["state"] == "dead"]
    assert len(dead) == 1


def test_fleet_stats_aggregate_and_placements(make_fleet):
    fleet = make_fleet()
    for i in range(2):
        fleet.submit(PROMPT, session_id=f"s{i}", sampling=_greedy(2))
    fleet.run_until_idle()
    st = fleet.stats()
    assert st["fleet"]["serving"] == 3
    assert sum(st["fleet"]["placements"].values()) == 2
    assert st["tokens_decoded"] >= 2   # summed across replicas
    assert st["healthy"] is True


# ---- satellite: fused-window diagnosability ----

def test_fused_window_disabled_reason_surfaces(model, monkeypatch):
    cfg, params = model
    eng = ServingEngine(cfg, params, max_batch=4, page_size=8,
                        n_pages=64)
    st = eng.stats()
    assert st["fused_window"] is True
    assert st["fused_window_disabled_reason"] is None

    monkeypatch.setenv("ROOM_TPU_FUSED_WINDOW", "0")
    eng2 = ServingEngine(cfg, params, max_batch=4, page_size=8,
                         n_pages=64)
    st2 = eng2.stats()
    assert st2["fused_window"] is False
    assert "ROOM_TPU_FUSED_WINDOW=0" in \
        st2["fused_window_disabled_reason"]


def test_fused_window_dp_mode_is_logged_and_reported(
    model, monkeypatch, caplog,
):
    """Under a dp mesh the fused window now stays ON as the sharded
    variant (docs/serving.md "dp-sharded fused window"): mode
    `fused-dp`, an INFO mode-marker reason string, and a build log
    line. ROOM_TPU_FUSED_WINDOW_DP=0 restores the legacy auto-off
    with the old warning — either way a mixed-mesh fleet is
    diagnosable from stats()."""
    import logging

    from room_tpu.parallel import (
        MeshSpec, decoder_param_specs, make_mesh, shard_pytree,
    )

    cfg, params = model
    mesh = make_mesh(MeshSpec(dp=2, ep=2, tp=2))
    sharded = shard_pytree(params, decoder_param_specs(cfg), mesh)
    with caplog.at_level(logging.INFO,
                         logger="room_tpu.serving.engine"):
        eng = ServingEngine(cfg, sharded, max_batch=4, page_size=8,
                            n_pages=64, mesh=mesh)
    assert eng._dp_size == 2
    st = eng.stats()
    assert st["fused_window"] is True
    assert st["fused_window_mode"] == "fused-dp"
    assert st["fused_window_disabled_reason"] == \
        "sharded variant active (dp=2)"
    assert any("fused dispatch window" in r.message
               for r in caplog.records)

    monkeypatch.setenv("ROOM_TPU_FUSED_WINDOW_DP", "0")
    caplog.clear()
    with caplog.at_level(logging.WARNING,
                         logger="room_tpu.serving.engine"):
        eng2 = ServingEngine(cfg, sharded, max_batch=4, page_size=8,
                             n_pages=64, mesh=mesh)
    st2 = eng2.stats()
    assert st2["fused_window"] is False
    assert st2["fused_window_mode"] == "off"
    assert "ROOM_TPU_FUSED_WINDOW_DP=0" in \
        st2["fused_window_disabled_reason"]
    assert any("fused dispatch window" in r.message
               for r in caplog.records)


# ---- threaded mode ----

@pytest.mark.parametrize("n", [2])
def test_threaded_fleet_serves_and_fails_over(make_fleet, control, n):
    """The deployment shape: replica serve threads + the supervisor
    loop. A kill mid-traffic re-homes and the continuation is
    token-identical (adoption rides the engine thread's step)."""
    c1, c2 = control
    fleet = make_fleet(n=n)
    stop = threading.Event()
    sup = threading.Thread(
        target=fleet.serve_forever, args=(stop,),
        kwargs={"idle_sleep": 0.02}, daemon=True,
    )
    sup.start()
    try:
        t1 = fleet.submit(PROMPT, session_id="s", sampling=_greedy())
        assert t1.done.wait(60) and t1.new_tokens == c1
        victim = fleet._records["s"].rid
        fleet.kill_replica(victim, "threaded kill")
        assert fleet._records["s"].rid != victim
        t2 = fleet.submit(CONT, session_id="s", sampling=_greedy())
        assert t2.done.wait(60), "adoption must apply before admission"
        assert t2.new_tokens == c2
    finally:
        stop.set()
        sup.join(timeout=30)
    assert not sup.is_alive()
