"""Chaos suite for the swarm runtime (docs/swarm_recovery.md).

The serving chaos suite (test_chaos_serving.py) proves the engine
survives induced failure; this suite proves the swarm layer above it
does too. Each swarm fault point — db_io, cycle_crash, loop_hang,
tool_exec — gets a targeted recovery test, plus a multi-room crash
storm asserting the acceptance invariants:

  1. every started cycle / task run reaches a terminal journal state
     (after journal recovery, nothing is left 'running');
  2. no journaled side effect executes twice — committed effects of
     interrupted work are replay-skipped, never re-fired;
  3. _SlotPool slots never leak, whatever the crash path;
  4. a loop past its restart budget is keeper-escalated, marked
     unhealthy, and visible in /api/tpu/health.

The quick tier is CI-bounded (ci.yml chaos job); the >=30 s soak tier
runs behind the `slow` marker.
"""

import threading
import time

import pytest

from room_tpu.core import (
    agent_loop, journal, rooms, task_runner, workers,
)
from room_tpu.core.telemetry import reset_counters
from room_tpu.providers import get_model_provider, reset_provider_cache
from room_tpu.providers.base import ExecutionRequest, ExecutionResult
from room_tpu.serving import faults
from tests.conftest import http_req


def _drain_loops(timeout=10.0):
    """Stop and JOIN every registered loop thread, then drop the
    handles. Joining matters: a straggler mid-iteration from a previous
    test can consume the next test's one-shot global fault (its exit
    path swallows the injected error), turning deterministic tests
    flaky."""
    with agent_loop._registry_lock:
        handles = list(agent_loop._running_loops.values())
    for h in handles:
        h.stop.set()
        h.wake.set()
    for h in handles:
        if h.thread is not None:
            h.thread.join(timeout=timeout)
    with agent_loop._registry_lock:
        for wid, h in list(agent_loop._running_loops.items()):
            if h.thread is None or not h.thread.is_alive():
                del agent_loop._running_loops[wid]


@pytest.fixture(autouse=True)
def _clean_swarm_state():
    """Faults disarmed, loops drained, and supervision state forgotten
    around every test — module-global state must never leak across
    tests."""
    faults.clear()
    _drain_loops()
    agent_loop.reset_supervision(list(agent_loop._strikes)
                                 + list(agent_loop._unhealthy))
    for k in agent_loop._supervision_counts:
        agent_loop._supervision_counts[k] = 0
    reset_counters()
    yield
    faults.clear()
    _drain_loops()
    agent_loop.reset_supervision(list(agent_loop._strikes)
                                 + list(agent_loop._unhealthy))


@pytest.fixture()
def room(db):
    r = rooms.create_room(
        db, "hive", goal="survive crashes", worker_model="echo",
        create_wallet=False,
    )
    agent_loop.set_room_launch_enabled(r["id"], True)
    yield r
    agent_loop.set_room_launch_enabled(r["id"], False)
    agent_loop.stop_room_loops(db, r["id"], "test done")


@pytest.fixture()
def echo(room):
    reset_provider_cache()
    provider = get_model_provider("echo")
    provider.responses.clear()
    provider.tool_script.clear()
    provider.calls.clear()
    provider.fail_with = None
    return provider


def queen_of(db, room):
    return workers.get_worker(db, room["queen_worker_id"])


def _wait(predicate, timeout=8.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return False


def _start_loop(db, room, worker_id, gap_ms=3_600_000):
    """Start a loop and wait until it finished its first cycle and went
    to sleep (state 'idle'), so fault arming afterwards hits the NEXT
    iteration deterministically — never the in-flight first cycle."""
    rooms.update_room(db, room["id"], queen_cycle_gap_ms=gap_ms)
    handle = agent_loop.start_agent_loop(db, room["id"], worker_id)
    # generous timeout: the very first cycle in a process pays one-off
    # embed/skills warmup
    assert _wait(lambda: handle.state == "idle", timeout=20.0), \
        f"loop never went idle (state={handle.state})"
    return handle


# ---- fault registry ----

def test_swarm_fault_points_registered():
    for point in ("db_io", "cycle_crash", "loop_hang", "tool_exec"):
        assert point in faults.FAULT_POINTS
    faults.configure_from_env("cycle_crash:times=2;db_io:p=0.5")
    snap = faults.snapshot()
    assert snap["cycle_crash"]["times_remaining"] == 2
    assert snap["db_io"]["probability"] == 0.5


# ---- journal lifecycle ----

def test_clean_cycle_closes_its_journal(db, room, echo):
    cycle = agent_loop.run_cycle(db, room, queen_of(db, room))
    assert cycle["status"] == "success"
    rows = db.query(
        "SELECT * FROM cycle_journal WHERE kind='cycle' AND ref_id=?",
        (cycle["id"],),
    )
    entries = {r["entry"]: r["status"] for r in rows}
    assert entries["started"] == "closed"
    assert entries["provider_call"] == "closed"
    assert journal.backlog(db) == 0


def test_clean_failure_closes_its_journal(db, room, echo):
    echo.fail_with = "provider exploded"
    cycle = agent_loop.run_cycle(db, room, queen_of(db, room))
    assert cycle["status"] == "error"
    assert journal.backlog(db) == 0


def test_journaled_tool_commits_effect(db, room, echo):
    echo.tool_script.append(
        ("send_message", {"to": "keeper", "body": "status ok"})
    )
    cycle = agent_loop.run_cycle(db, room, queen_of(db, room))
    row = db.query_one(
        "SELECT * FROM cycle_journal WHERE kind='cycle' AND ref_id=? "
        "AND entry='effect'",
        (cycle["id"],),
    )
    assert row is not None and row["status"] == "committed"
    assert "status ok" in (row["payload"] or "")


def test_clean_task_run_closes_its_journal(db, room, echo):
    tid = task_runner.create_task(
        db, "t", "do it", trigger_type="once", room_id=room["id"]
    )
    run = task_runner.execute_task(db, tid)
    assert run["status"] == "success"
    assert journal.backlog(db) == 0
    assert task_runner.slots.in_use(room["id"]) == 0


# ---- crash recovery ----

def test_recovery_fails_interrupted_cycle_immediately(db, room, echo):
    """A cycle_crash leaves the cycle 'running' with an open journal —
    recovery resolves it to a terminal state NOW, not 120 min later."""
    faults.inject("cycle_crash", times=1)
    with pytest.raises(faults.FaultError):
        agent_loop.run_cycle(db, room, queen_of(db, room))
    stuck = db.query_one(
        "SELECT * FROM worker_cycles ORDER BY id DESC LIMIT 1"
    )
    assert stuck["status"] == "running"
    assert journal.backlog(db) > 0

    summary = journal.recover(db)
    assert summary["cycles"] == 1
    after = db.query_one(
        "SELECT * FROM worker_cycles WHERE id=?", (stuck["id"],)
    )
    assert after["status"] == "error"
    assert "recovered" in after["error_message"]
    assert journal.backlog(db) == 0


def test_recovery_requeues_interrupted_task_run(db, room, echo):
    """An interrupted 'once' task run is failed by recovery but the
    task stays active — the scheduler requeues it, and the retry
    completes."""
    tid = task_runner.create_task(
        db, "t", "do it", trigger_type="once", room_id=room["id"]
    )
    faults.inject("cycle_crash", times=1, transient=False)
    with pytest.raises(faults.FaultError):
        task_runner.execute_task(db, tid)
    assert task_runner.slots.in_use(room["id"]) == 0  # no slot leak
    run = db.query_one("SELECT * FROM task_runs ORDER BY id DESC LIMIT 1")
    assert run["status"] == "running"  # crash model: no cleanup ran

    summary = journal.recover(db)
    assert summary["task_runs"] == 1
    assert db.query_one(
        "SELECT status FROM task_runs WHERE id=?", (run["id"],)
    )["status"] == "error"
    # not archived: still schedulable, and the retry succeeds
    assert task_runner.get_task(db, tid)["status"] == "active"
    retry = task_runner.execute_task(db, tid)
    assert retry["status"] == "success"


def test_recovery_closes_bookkeeping_for_finished_refs(db, room, echo):
    """Crash after the status update but before the journal close:
    recovery must close the entry quietly, not double-fail the ref."""
    cycle = agent_loop.run_cycle(db, room, queen_of(db, room))
    db.execute(
        "UPDATE cycle_journal SET status='open' WHERE kind='cycle' "
        "AND ref_id=? AND entry='started'",
        (cycle["id"],),
    )
    summary = journal.recover(db)
    assert summary["closed"] == 1 and summary["cycles"] == 0
    assert db.query_one(
        "SELECT status FROM worker_cycles WHERE id=?", (cycle["id"],)
    )["status"] == "success"


# ---- side-effect idempotency ----

def test_committed_effect_is_not_double_fired_on_replay(db, room, echo):
    """The core exactly-once guarantee: a message sent before the crash
    is NOT re-sent by the recovered retry."""
    echo.tool_script.append(
        ("send_message", {"to": "keeper", "body": "wire the payment"})
    )
    cycle = agent_loop.run_cycle(db, room, queen_of(db, room))
    sent = db.query(
        "SELECT * FROM chat_messages WHERE room_id=?", (room["id"],)
    )
    assert len(sent) == 1

    # simulate the crash window: cycle died after the tool committed
    # but before finishing — reopen its journal and roll the row back
    db.execute(
        "UPDATE worker_cycles SET status='running', finished_at=NULL "
        "WHERE id=?", (cycle["id"],),
    )
    db.execute(
        "UPDATE cycle_journal SET status='open' WHERE kind='cycle' "
        "AND ref_id=? AND entry='started'",
        (cycle["id"],),
    )
    summary = journal.recover(db)
    assert summary["effects_flagged"] == 1

    # the retry runs the same logical cycle (same tool, same args)
    agent_loop.run_cycle(db, room, queen_of(db, room))
    sent = db.query(
        "SELECT * FROM chat_messages WHERE room_id=?", (room["id"],)
    )
    assert len(sent) == 1, "replay double-fired a committed side effect"
    consumed = db.query_one(
        "SELECT * FROM cycle_journal WHERE entry='effect' AND "
        "status='consumed'"
    )
    assert consumed is not None


def test_replay_protection_chains_through_repeated_crashes(db, room,
                                                           echo):
    """If the RETRY also crashes after its skip point, the third
    attempt must still skip: consuming a marker records a committed
    marker on the consuming ref, so protection survives chained
    crash/retry rounds."""
    echo.tool_script.append(
        ("send_message", {"to": "keeper", "body": "wire it"})
    )

    def crash_after(cycle_id):
        db.execute(
            "UPDATE worker_cycles SET status='running', "
            "finished_at=NULL WHERE id=?", (cycle_id,),
        )
        db.execute(
            "UPDATE cycle_journal SET status='open' WHERE kind='cycle' "
            "AND ref_id=? AND entry='started'", (cycle_id,),
        )
        journal.recover(db)

    for round_no in range(3):
        cycle = agent_loop.run_cycle(db, room, queen_of(db, room))
        sent = db.query(
            "SELECT * FROM chat_messages WHERE room_id=?", (room["id"],)
        )
        assert len(sent) == 1, (
            f"round {round_no}: effect fired {len(sent)} times"
        )
        if round_no < 2:
            crash_after(cycle["id"])


def test_uncommitted_intent_reruns_on_retry(db, room, echo):
    """tool_exec crashes the effect between intent and execution: the
    message was never sent, so the retry must send it — exactly once
    in total."""
    echo.tool_script.append(
        ("send_message", {"to": "keeper", "body": "hello"})
    )
    faults.inject("tool_exec", times=1)
    with pytest.raises(faults.FaultError):
        agent_loop.run_cycle(db, room, queen_of(db, room))
    cycle = db.query_one(
        "SELECT * FROM worker_cycles ORDER BY id DESC LIMIT 1"
    )
    assert cycle["status"] == "error"
    assert not db.query(
        "SELECT * FROM chat_messages WHERE room_id=?", (room["id"],)
    )
    intent = db.query_one(
        "SELECT status FROM cycle_journal WHERE entry='effect'"
    )
    assert intent["status"] == "abandoned"

    agent_loop.run_cycle(db, room, queen_of(db, room))
    sent = db.query(
        "SELECT * FROM chat_messages WHERE room_id=?", (room["id"],)
    )
    assert len(sent) == 1


def test_failed_tool_is_not_committed(db, room, echo):
    """execute_queen_tool returns 'tool error: ...' strings instead of
    raising; a failed effect must be abandoned, not committed —
    otherwise replay protection would suppress the retry of an act
    that never happened."""
    echo.tool_script.append(("send_message", {"to": "keeper"}))  # no body
    agent_loop.run_cycle(db, room, queen_of(db, room))
    row = db.query_one(
        "SELECT status, payload FROM cycle_journal WHERE entry='effect'"
    )
    assert row["status"] == "abandoned"
    assert "tool error" in row["payload"]
    # the corrected retry executes normally
    echo.tool_script.clear()
    echo.tool_script.append(
        ("send_message", {"to": "keeper", "body": "fixed"})
    )
    agent_loop.run_cycle(db, room, queen_of(db, room))
    sent = db.query(
        "SELECT * FROM chat_messages WHERE room_id=?", (room["id"],)
    )
    assert len(sent) == 1


def test_second_legitimate_send_still_executes(db, room, echo):
    """Idempotency must not turn into dedupe of legitimate repeats:
    the same message sent by two SUCCESSFUL cycles goes out twice."""
    echo.tool_script.append(
        ("send_message", {"to": "keeper", "body": "daily report"})
    )
    agent_loop.run_cycle(db, room, queen_of(db, room))
    agent_loop.run_cycle(db, room, queen_of(db, room))
    sent = db.query(
        "SELECT * FROM chat_messages WHERE room_id=?", (room["id"],)
    )
    assert len(sent) == 2


# ---- db_io + loop supervision ----

def test_db_io_fault_kills_loop_and_supervisor_restarts(db, room, echo):
    queen = queen_of(db, room)
    handle = _start_loop(db, room, queen["id"])
    assert handle.thread.is_alive()

    # burst, not one-shot: a lone arm can land mid-spontaneous-cycle
    # (WIP momentum) where the transient cycle-error handler swallows
    # it; the burst leaves an arm for the fatal tail write / next
    # top-of-loop get_worker
    faults.inject("db_io", times=3)
    handle.wake.set()  # next iteration hits the injected OperationalError
    assert _wait(lambda: not handle.thread.is_alive()), \
        "db_io fault did not kill the loop thread"
    assert handle.state == "crashed"
    assert "OperationalError" in (handle.crash_error or "")
    # the corpse stays in the registry for the supervisor to find
    assert agent_loop._running_loops.get(queen["id"]) is handle

    faults.clear()   # unconsumed arms must not hit the restart below
    actions = agent_loop.supervise_loops(db)
    assert queen["id"] in actions["restarted"]
    new = agent_loop._running_loops.get(queen["id"])
    assert new is not None and new is not handle
    assert new.thread.is_alive()
    snap = agent_loop.supervision_snapshot()
    assert snap["restarts"] == 1 and snap["crashes"] == 1


def test_wake_path_routes_crashed_corpse_through_supervision(db, room,
                                                            echo):
    """trigger_agent / start_agent_loop on a crashed corpse must NOT
    silently replace it: supervision (journal recovery + strike
    accounting) runs first, and an unhealthy worker stays locked out
    until the keeper resets it."""
    queen = queen_of(db, room)
    handle = _start_loop(db, room, queen["id"])
    faults.inject("cycle_crash", times=1, transient=False)
    handle.wake.set()
    assert _wait(lambda: not handle.thread.is_alive())
    orphan = db.query_one(
        "SELECT id FROM worker_cycles WHERE status='running'"
    )
    assert orphan is not None

    # the wake path — not supervise_loops — triggers the restart
    new = agent_loop.trigger_agent(db, room["id"], queen["id"])
    assert new is not None and new is not handle
    # ...and supervision bookkeeping still happened
    assert db.query_one(
        "SELECT status FROM worker_cycles WHERE id=?", (orphan["id"],)
    )["status"] == "error"
    assert agent_loop.supervision_snapshot()["restarts"] == 1

    # lockout: an unhealthy worker cannot be resurrected by a wake
    agent_loop.pause_agent(queen["id"])
    _drain_loops()
    with agent_loop._supervision_lock:
        agent_loop._unhealthy[queen["id"]] = {"room_id": room["id"],
                                              "error": "test",
                                              "strikes": 9,
                                              "at": "now"}
    locked = agent_loop.start_agent_loop(db, room["id"], queen["id"])
    assert locked.state == "unhealthy" and locked.thread is None
    assert queen["id"] not in agent_loop._running_loops
    # keeper reset re-enables the worker
    agent_loop.reset_supervision([queen["id"]])
    revived = agent_loop.start_agent_loop(db, room["id"], queen["id"])
    assert revived.thread is not None and revived.thread.is_alive()


def test_live_committed_effect_skipped_without_recovery(db, room, echo):
    """An un-recovered predecessor stuck 'running' (in-process crash
    orphan or hung twin) already committed the effect: the next cycle
    must skip it even though recover() never ran."""
    echo.tool_script.append(
        ("send_message", {"to": "keeper", "body": "ship it"})
    )
    cycle = agent_loop.run_cycle(db, room, queen_of(db, room))
    # freeze the predecessor mid-flight: row back to running, journal
    # open, NO recovery
    db.execute(
        "UPDATE worker_cycles SET status='running', finished_at=NULL "
        "WHERE id=?", (cycle["id"],),
    )
    db.execute(
        "UPDATE cycle_journal SET status='open' WHERE kind='cycle' "
        "AND ref_id=? AND entry='started'", (cycle["id"],),
    )

    agent_loop.run_cycle(db, room, queen_of(db, room))
    sent = db.query(
        "SELECT * FROM chat_messages WHERE room_id=?", (room["id"],)
    )
    assert len(sent) == 1, "live-committed effect was re-fired"


def test_supervised_restart_recovers_interrupted_cycle(db, room, echo):
    """An in-process crash restart must arm the same journal recovery
    as a full process restart: the dead loop's interrupted cycle
    resolves to terminal (and its effects get replay protection)
    BEFORE the replacement loop runs."""
    queen = queen_of(db, room)
    handle = _start_loop(db, room, queen["id"])

    # a non-transient cycle_crash escapes the loop's handler: the
    # thread dies mid-cycle, leaving the cycle row 'running'
    faults.inject("cycle_crash", times=1, transient=False)
    handle.wake.set()
    assert _wait(lambda: not handle.thread.is_alive())
    orphan = db.query_one(
        "SELECT id FROM worker_cycles WHERE status='running'"
    )
    assert orphan is not None

    actions = agent_loop.supervise_loops(db)
    assert queen["id"] in actions["restarted"]
    after = db.query_one(
        "SELECT status, error_message FROM worker_cycles WHERE id=?",
        (orphan["id"],),
    )
    assert after["status"] == "error"
    assert "recovered" in after["error_message"]


def test_prune_expires_stale_replay_skip(db):
    db.insert(
        "INSERT INTO cycle_journal(kind, ref_id, entry, status, "
        "idem_key, updated_at) VALUES "
        "('cycle', 1, 'effect', 'replay_skip', 'k:1', "
        "'2020-01-01T00:00:00.000Z')",
    )
    db.insert(
        "INSERT INTO cycle_journal(kind, ref_id, entry, status, "
        "idem_key) VALUES ('cycle', 2, 'effect', 'replay_skip', 'k:2')",
    )
    n = journal.prune(db)
    assert n == 1  # only the expired one; the fresh skip survives
    left = db.query("SELECT idem_key FROM cycle_journal")
    assert [r["idem_key"] for r in left] == ["k:2"]


def test_restart_budget_exhaustion_escalates(db, room, echo,
                                             monkeypatch):
    monkeypatch.setattr(agent_loop, "LOOP_RESTART_BUDGET", 1)
    queen = queen_of(db, room)
    handle = _start_loop(db, room, queen["id"])

    for strike in range(2):
        # a burst, not a one-shot: the loop runs spontaneous cycles
        # (WIP momentum), and a single arm landing mid-cycle is
        # swallowed by the transient cycle-error handler — the loop
        # survives and the strike never lands. With a burst, the
        # cycle's swallow still leaves an arm for the fatal tail
        # set_agent_state / next top-of-loop get_worker.
        faults.inject("db_io", times=3)
        handle.wake.set()
        assert _wait(lambda: not handle.thread.is_alive())
        # unconsumed arms must not hit supervise/restart or this
        # thread's own queries below
        faults.clear()
        agent_loop.supervise_loops(db)
        handle = agent_loop._running_loops.get(queen["id"])
        if handle is None:
            break
        assert _wait(lambda: handle.state == "idle")

    # past budget: no loop, unhealthy worker, keeper escalation
    assert agent_loop._running_loops.get(queen["id"]) is None
    assert workers.get_worker(db, queen["id"])["agent_state"] == \
        "unhealthy"
    esc = db.query(
        "SELECT * FROM escalations WHERE room_id=?", (room["id"],)
    )
    assert esc and "restart budget" in esc[-1]["question"]
    snap = agent_loop.supervision_snapshot()
    assert str(queen["id"]) in snap["unhealthy_workers"]
    assert snap["budget_exhausted"] == 1

    # keeper restart re-arms the budget
    agent_loop.reset_supervision([queen["id"]])
    assert not agent_loop.supervision_snapshot()["unhealthy_workers"]


def test_hung_loop_is_detected_and_replaced(db, room, echo,
                                            monkeypatch):
    monkeypatch.setattr(agent_loop, "LOOP_HANG_S", 0.2)
    queen = queen_of(db, room)
    handle = _start_loop(db, room, queen["id"])

    faults.inject("loop_hang", latency_s=3.0, times=1)
    handle.wake.set()  # iteration enters the injected stall
    assert _wait(
        lambda: handle.state == "running"
        and time.monotonic() - handle.beat > 0.25
    )
    actions = agent_loop.supervise_loops(db)
    assert queen["id"] in actions["replaced_hung"]
    new = agent_loop._running_loops.get(queen["id"])
    assert new is not None and new is not handle
    assert handle.stop.is_set()  # old thread told to die when it unsticks
    assert agent_loop.supervision_snapshot()["hang_replacements"] == 1
    # the stuck thread exits without clobbering its successor
    assert _wait(lambda: not handle.thread.is_alive(), timeout=6.0)
    assert agent_loop._running_loops.get(queen["id"]) is new


# ---- stranded worker reset (satellite) ----

def test_cleanup_stale_resets_stranded_workers(db, room, echo):
    from room_tpu.server.runtime import ServerRuntime

    queen = queen_of(db, room)
    workers.set_agent_state(db, queen["id"], "running")
    wid = workers.create_worker(db, "w2", "p", room_id=room["id"])
    workers.set_agent_state(db, wid, "rate_limited")

    rt = ServerRuntime(db=db)
    n = rt.cleanup_stale(startup=True)
    assert n >= 2
    assert workers.get_worker(db, queen["id"])["agent_state"] == "idle"
    assert workers.get_worker(db, wid)["agent_state"] == "idle"


def test_cleanup_stale_spares_live_loops(db, room, echo):
    from room_tpu.server.runtime import ServerRuntime

    queen = queen_of(db, room)
    handle = _start_loop(db, room, queen["id"])
    workers.set_agent_state(db, queen["id"], "rate_limited")
    rt = ServerRuntime(db=db)
    rt.cleanup_stale(startup=False)  # periodic sweep, loop is alive
    assert workers.get_worker(db, queen["id"])["agent_state"] == \
        "rate_limited"
    handle.stop.set()
    handle.wake.set()


# ---- provider fallback on crash (satellite) ----

class _CrashingPrimary:
    name = "tpu"
    model_name = ""

    def is_ready(self):
        return True, "ok"

    def execute(self, request):
        return ExecutionResult(
            success=False,
            error="engine crashed: RuntimeError: injected",
        )


def test_crash_failed_result_reroutes_when_opted_in(monkeypatch):
    from room_tpu.providers.registry import FallbackProvider

    reset_provider_cache()
    echo = get_model_provider("echo")
    echo.responses.clear()
    echo.fail_with = None
    fb = FallbackProvider(_CrashingPrimary(), ["echo"])
    monkeypatch.setattr(fb, "_primary_healthy", lambda: True)

    # default: crash-failed result surfaces unchanged (no reroute)
    monkeypatch.delenv("ROOM_TPU_FALLBACK_ON_CRASH", raising=False)
    result = fb.execute(ExecutionRequest(prompt="hi"))
    assert not result.success and "engine crashed" in result.error

    echo.responses.append("fallback answer")
    monkeypatch.setenv("ROOM_TPU_FALLBACK_ON_CRASH", "1")
    result = fb.execute(ExecutionRequest(prompt="hi"))
    assert result.success and result.text == "fallback answer"


def test_crash_reroute_fails_closed_without_ready_fallback(monkeypatch):
    from room_tpu.providers.registry import FallbackProvider

    monkeypatch.setenv("ROOM_TPU_FALLBACK_ON_CRASH", "1")
    fb = FallbackProvider(_CrashingPrimary(), [])  # empty chain
    monkeypatch.setattr(fb, "_primary_healthy", lambda: True)
    result = fb.execute(ExecutionRequest(prompt="hi"))
    # chain exhausted: the original crash-failed result surfaces
    assert not result.success and "engine crashed" in result.error


# ---- health surface ----

def test_health_route_exposes_swarm_state(http_server):
    status, body = http_req(http_server, "GET", "/api/tpu/health")
    assert status == 200
    swarm = body["data"]["swarm"]
    assert "loops_alive" in swarm and "journal" in swarm
    assert set(swarm["journal"]) >= {"backlog", "recovered"}
    assert "unhealthy_workers" in swarm


# ---- multi-room crash storm (acceptance) ----

class _StormProvider:
    """Deterministic provider issuing one uniquely-bodied journaled
    send per cycle — any duplicated body is a double-fired effect."""

    name = "storm"

    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0

    def is_ready(self):
        return True, "ok"

    def execute(self, request):
        with self._lock:
            self.n += 1
            n = self.n
        if request.on_tool_call:
            request.on_tool_call(
                "send_message", {"to": "keeper", "body": f"storm-{n}"}
            )
        return ExecutionResult(
            text=f"cycle {n}", success=True,
            session_id=request.session_id or "storm-session",
            input_tokens=1, output_tokens=1,
        )


def _run_crash_storm(db, monkeypatch, n_rooms, min_cycles, min_run_s,
                     max_s):
    """Drive n_rooms of looping workers under armed swarm faults with
    the supervisor running, then assert the acceptance invariants."""
    provider = _StormProvider()
    monkeypatch.setattr(
        agent_loop, "get_model_provider", lambda m, d=None: provider
    )
    monkeypatch.setattr(agent_loop, "LOOP_RESTART_BUDGET", 10_000)
    monkeypatch.setattr(agent_loop, "CYCLE_ERROR_GAP_S", 0.05)

    room_ids = []
    for i in range(n_rooms):
        r = rooms.create_room(
            db, f"storm-{i}", goal="survive", worker_model="storm",
            create_wallet=False,
        )
        agent_loop.set_room_launch_enabled(r["id"], True)
        room_ids.append(r["id"])
        rooms.update_room(db, r["id"], queen_cycle_gap_ms=30)
        # the queen worker row carries its own gap, which overrides the
        # room's — shrink it too or the storm idles 30 min per cycle
        workers.update_worker(
            db, r["queen_worker_id"], cycle_gap_ms=30
        )
        agent_loop.start_agent_loop(db, r["id"], r["queen_worker_id"])

    faults.inject("cycle_crash", probability=0.25, seed=11)
    faults.inject("db_io", probability=0.003, seed=13)
    faults.inject("tool_exec", probability=0.15, seed=17)
    faults.inject("loop_hang", probability=0.05, latency_s=0.3, seed=19)

    t0 = time.monotonic()
    try:
        while time.monotonic() - t0 < max_s:
            agent_loop.supervise_loops(db)
            time.sleep(0.1)
            if time.monotonic() - t0 < min_run_s:
                continue
            try:
                n = db.query_one(
                    "SELECT COUNT(*) AS n FROM worker_cycles"
                )["n"]
            except Exception:
                continue  # db_io fault hit the driver's own query
            if n >= min_cycles:
                break
    finally:
        faults.clear()
        for rid in room_ids:
            agent_loop.stop_room_loops(db, rid, "storm over")
        storm_rooms = set(room_ids)
        _wait(lambda: not any(
            h.thread is not None and h.thread.is_alive()
            for h in list(agent_loop._running_loops.values())
            if h.room_id in storm_rooms
        ), timeout=10.0)

    started = db.query_one("SELECT COUNT(*) AS n FROM worker_cycles")["n"]
    assert started >= min_cycles, (
        f"storm too quiet: only {started} cycles started"
    )

    # simulated restart: journal recovery resolves interrupted work
    journal.recover(db)

    # 1. every started cycle reached a terminal state
    stuck = db.query(
        "SELECT * FROM worker_cycles WHERE status='running'"
    )
    assert not stuck, f"{len(stuck)} cycles never reached terminal state"
    assert journal.backlog(db) == 0

    # 2. no journaled side effect executed twice (unique bodies)
    sent = db.query(
        "SELECT content FROM chat_messages WHERE role='assistant'"
    )
    bodies = [r["content"] for r in sent]
    assert len(bodies) == len(set(bodies)), "a send was double-fired"

    # 3. no slot leaks anywhere
    for rid in room_ids:
        assert task_runner.slots.in_use(rid) == 0

    return started


def test_crash_storm_quick(db, monkeypatch):
    """Quick tier: >=20 cycles across 2 concurrent rooms under
    cycle_crash + db_io + tool_exec + loop_hang."""
    started = _run_crash_storm(
        db, monkeypatch, n_rooms=2, min_cycles=20, min_run_s=2.0,
        max_s=15.0,
    )
    assert started >= 20


@pytest.mark.slow
def test_crash_storm_soak(db, monkeypatch):
    """Soak tier: 3 rooms, >=30 s of sustained crash pressure."""
    t0 = time.monotonic()
    _run_crash_storm(
        db, monkeypatch, n_rooms=3, min_cycles=150, min_run_s=30.5,
        max_s=45.0,
    )
    assert time.monotonic() - t0 >= 30.0
