"""HTTP/WS integration tests: real server on port 0, real HTTP requests,
real WebSocket client (reference pattern:
src/server/__tests__/helpers/test-server.ts — in-memory DB, ephemeral
port, agent/user/no-auth request helpers)."""

import base64
import hashlib
import json
import os
import socket
import struct
import time
import urllib.error
import urllib.request

import pytest

from room_tpu.db import Database
from room_tpu.core import rooms, workers, task_runner
from room_tpu.core.events import event_bus
from room_tpu.providers import get_model_provider, reset_provider_cache
from room_tpu.server.http import ApiServer
from room_tpu.server.runtime import ServerRuntime
from room_tpu.server.auth import sign_cloud_jwt


@pytest.fixture()
def server(tmp_path, monkeypatch):
    monkeypatch.setenv("ROOM_TPU_DATA_DIR", str(tmp_path))
    db = Database(":memory:")
    runtime = ServerRuntime(db=db)
    api = ApiServer(db, runtime=runtime, port=0)
    api.start()
    yield api
    api.stop()
    db.close()


def req(server, method, path, body=None, token="agent", raw_token=None):
    url = f"http://127.0.0.1:{server.port}{path}"
    headers = {}
    if raw_token is not None:
        headers["Authorization"] = f"Bearer {raw_token}"
    elif token is not None:
        headers["Authorization"] = f"Bearer {server.tokens[token]}"
    data = json.dumps(body).encode() if body is not None else None
    if data:
        headers["Content-Type"] = "application/json"
    r = urllib.request.Request(url, data=data, headers=headers,
                               method=method)
    try:
        with urllib.request.urlopen(r, timeout=10) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def test_auth_required(server):
    status, out = req(server, "GET", "/api/rooms", token=None)
    assert status == 401
    status, out = req(server, "GET", "/api/rooms", raw_token="wrong")
    assert status == 401
    status, out = req(server, "GET", "/api/rooms")
    assert status == 200 and out["data"] == []


def test_handshake_returns_user_token(server):
    status, out = req(server, "GET", "/api/auth/handshake", token=None)
    assert status == 200
    assert out["data"]["userToken"] == server.tokens["user"]


def test_member_jwt_rbac(server, monkeypatch):
    monkeypatch.setenv("ROOM_TPU_CLOUD_JWT_SECRET", "s3cret")
    jwt = sign_cloud_jwt(
        {"iss": "room-tpu-cloud", "aud": "room-tpu-runtime",
         "exp": time.time() + 60, "role": "member", "sub": "m-1"},
        "s3cret",
    )
    status, _ = req(server, "GET", "/api/rooms", raw_token=jwt)
    assert status == 200
    # token without a subject carries no auditable identity
    nosub = sign_cloud_jwt(
        {"iss": "room-tpu-cloud", "aud": "room-tpu-runtime",
         "exp": time.time() + 60, "role": "member"},
        "s3cret",
    )
    status, _ = req(server, "GET", "/api/rooms", raw_token=nosub)
    assert status == 401
    # member cannot write outside the whitelist
    status, _ = req(server, "POST", "/api/rooms", {"name": "x"},
                    raw_token=jwt)
    assert status == 403
    # bad signature rejected
    status, _ = req(server, "GET", "/api/rooms",
                    raw_token=jwt[:-3] + "abc")
    assert status == 401


def test_room_crud_over_http(server):
    status, out = req(server, "POST", "/api/rooms",
                      {"name": "api-room", "goal": "test the API",
                       "workerModel": "echo", "createWallet": False})
    assert status == 201
    room_id = out["data"]["id"]

    status, out = req(server, "GET", f"/api/rooms/{room_id}/status")
    assert status == 200 and out["data"]["worker_count"] == 1

    status, out = req(server, "PUT", f"/api/rooms/{room_id}",
                      {"goal": "new goal"})
    assert out["data"]["goal"] == "new goal"

    status, out = req(server, "GET", f"/api/rooms/{room_id}/workers")
    assert len(out["data"]) == 1
    assert out["data"][0]["role"] == "queen"

    status, out = req(server, "DELETE", f"/api/rooms/{room_id}")
    assert status == 200
    status, _ = req(server, "GET", f"/api/rooms/{room_id}")
    assert status == 404


def test_room_settings_round_trip(server):
    """The dashboard's full settings form: every field the panel PUTs
    must persist and read back (reference: RoomSettingsPanel.tsx)."""
    import json as json_mod

    _, out = req(server, "POST", "/api/rooms",
                 {"name": "cfg-room", "workerModel": "echo",
                  "createWallet": False})
    rid = out["data"]["id"]
    payload = {
        "goal": "tuned", "autonomyMode": "semi",
        "visibility": "public", "workerModel": "echo",
        "queenNickname": "Her Majesty",
        "queenCycleGapMs": 300000, "queenMaxTurns": 75,
        "queenQuietFrom": "22:00", "queenQuietUntil": "07:00",
        "maxConcurrentTasks": 5,
        "config": {
            "voteThreshold": "two_thirds", "voteTimeoutMinutes": 20,
            "queenTieBreaker": False, "sealedBallot": True,
            "autoApprove": [],
        },
    }
    status, out = req(server, "PUT", f"/api/rooms/{rid}", payload)
    assert status == 200
    r = out["data"]
    assert (r["goal"], r["autonomy_mode"], r["visibility"]) == \
        ("tuned", "semi", "public")
    assert r["queen_nickname"] == "Her Majesty"
    assert r["queen_cycle_gap_ms"] == 300000
    assert r["queen_max_turns"] == 75
    assert (r["queen_quiet_from"], r["queen_quiet_until"]) == \
        ("22:00", "07:00")
    assert r["max_concurrent_tasks"] == 5
    cfg = json_mod.loads(r["config"])
    assert cfg["voteThreshold"] == "two_thirds"
    assert cfg["voteTimeoutMinutes"] == 20
    assert cfg["queenTieBreaker"] is False
    assert cfg["sealedBallot"] is True
    assert cfg["autoApprove"] == []


def test_room_start_runs_real_cycle(server):
    reset_provider_cache()
    echo = get_model_provider("echo")
    echo.responses.clear()

    status, out = req(server, "POST", "/api/rooms",
                      {"name": "live", "goal": "g", "workerModel": "echo",
                       "createWallet": False})
    room_id = out["data"]["id"]
    status, out = req(server, "POST", f"/api/rooms/{room_id}/start")
    assert status == 200

    for _ in range(100):
        _, out = req(server, "GET", f"/api/rooms/{room_id}/cycles")
        if out["data"] and out["data"][0]["status"] == "success":
            break
        time.sleep(0.05)
    assert out["data"], "no cycle ran"
    cycle_id = out["data"][0]["id"]
    _, logs = req(server, "GET", f"/api/cycles/{cycle_id}/logs")
    assert any(e["entry_type"] == "prompt" for e in logs["data"])

    req(server, "POST", f"/api/rooms/{room_id}/stop")


def test_task_webhook_no_auth(server):
    reset_provider_cache()
    get_model_provider("echo").responses.append("webhook ran")
    db = server.db
    tid = task_runner.create_task(db, "hooked", "p", trigger_type="webhook")
    token = task_runner.get_task(db, tid)["webhook_token"]

    status, out = req(server, "POST", f"/api/hooks/task/{token}",
                      {"x": 1}, token=None)
    assert status == 200 and out["data"]["queued"]
    status, _ = req(server, "POST", "/api/hooks/task/not-a-token", {},
                    token=None)
    assert status == 404


def test_queen_webhook_files_escalation(server):
    db = server.db
    room = rooms.create_room(db, "hooked", worker_model="echo",
                             create_wallet=False)
    status, out = req(
        server, "POST", f"/api/hooks/queen/{room['webhook_token']}",
        {"message": "deploy finished"}, token=None,
    )
    assert status == 200
    _, esc = req(server, "GET", "/api/escalations")
    assert any("deploy finished" in e["question"] for e in esc["data"])


def test_settings_masks_secrets(server):
    req(server, "PUT", "/api/settings",
        {"keeper_email": "k@x.com", "openai_api_key": "sk-secret"})
    _, out = req(server, "GET", "/api/settings")
    assert out["data"]["keeper_email"] == "k@x.com"
    assert out["data"]["openai_api_key"] == "***"


def test_status_endpoint(server):
    _, out = req(server, "GET", "/api/status")
    assert out["data"]["version"]
    assert out["data"]["runtime"] is True


def test_memory_over_http(server):
    req(server, "POST", "/api/memory",
        {"name": "deploy notes", "content": "use blue-green"})
    _, out = req(server, "GET", "/api/memory/search?q=blue-green")
    assert out["data"] and out["data"][0]["name"] == "deploy notes"


def test_static_traversal_guard(server, tmp_path):
    server.static_dir = str(tmp_path)
    (tmp_path / "index.html").write_text("<html>app</html>")
    url = f"http://127.0.0.1:{server.port}/"
    with urllib.request.urlopen(url, timeout=5) as resp:
        assert b"app" in resp.read()
    # traversal attempt
    conn = socket.create_connection(("127.0.0.1", server.port), timeout=5)
    conn.sendall(
        b"GET /../../etc/passwd HTTP/1.1\r\nHost: x\r\n\r\n"
    )
    data = conn.recv(4096)
    conn.close()
    assert b"passwd" not in data or b"root:" not in data


# ---- WebSocket ----

class WsClient:
    def __init__(self, port: int, token: str) -> None:
        self.sock = socket.create_connection(("127.0.0.1", port),
                                             timeout=10)
        key = base64.b64encode(os.urandom(16)).decode()
        self.sock.sendall(
            f"GET /ws?token={token} HTTP/1.1\r\n"
            f"Host: 127.0.0.1:{port}\r\n"
            "Upgrade: websocket\r\nConnection: Upgrade\r\n"
            f"Sec-WebSocket-Key: {key}\r\n"
            "Sec-WebSocket-Version: 13\r\n\r\n".encode()
        )
        # read HTTP response head
        head = b""
        while b"\r\n\r\n" not in head:
            head += self.sock.recv(1)
        self.status = int(head.split(b" ")[1])

    def send_json(self, obj) -> None:
        payload = json.dumps(obj).encode()
        mask = os.urandom(4)
        masked = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
        header = bytearray([0x81])
        n = len(payload)
        if n < 126:
            header.append(0x80 | n)
        else:
            header.append(0x80 | 126)
            header += struct.pack(">H", n)
        self.sock.sendall(bytes(header) + mask + masked)

    def recv_json(self, timeout=5):
        self.sock.settimeout(timeout)
        while True:
            head = self._read_exact(2)
            opcode = head[0] & 0x0F
            length = head[1] & 0x7F
            if length == 126:
                length = struct.unpack(">H", self._read_exact(2))[0]
            payload = self._read_exact(length)
            if opcode == 0x9:  # server ping: ignore
                continue
            if opcode == 0x1:
                return json.loads(payload)

    def _read_exact(self, n):
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("closed")
            buf += chunk
        return buf

    def close(self):
        self.sock.close()


def test_ws_auth_and_fanout(server):
    bad = WsClient(server.port, "wrong-token")
    assert bad.status == 401
    bad.close()

    ws = WsClient(server.port, server.tokens["user"])
    assert ws.status == 101
    ws.send_json({"type": "subscribe", "channel": "tasks"})
    assert ws.recv_json()["type"] == "subscribed"

    event_bus.emit("run:created", "tasks", {"run_id": 1})
    msg = ws.recv_json()
    assert msg["type"] == "run:created"
    assert msg["data"] == {"run_id": 1}

    # unsubscribed channel events don't arrive
    ws.send_json({"type": "unsubscribe", "channel": "tasks"})
    assert ws.recv_json()["type"] == "unsubscribed"
    event_bus.emit("run:created", "tasks", {"run_id": 2})
    with pytest.raises((TimeoutError, socket.timeout)):
        ws.recv_json(timeout=0.5)
    ws.close()


def test_start_room_twice_keeps_loop_alive(server):
    """Restarting a running room must hand back a LIVE loop, not the
    dying handle of the loop being stopped (regression)."""
    import room_tpu.core.agent_loop as al

    reset_provider_cache()
    _, out = req(server, "POST", "/api/rooms",
                 {"name": "restartable", "workerModel": "echo",
                  "createWallet": False})
    room_id = out["data"]["id"]
    req(server, "POST", f"/api/rooms/{room_id}/start")
    time.sleep(0.2)
    req(server, "POST", f"/api/rooms/{room_id}/start")  # restart
    deadline = time.time() + 5
    alive = False
    while time.time() < deadline:
        handles = [h for h in al._running_loops.values()
                   if h.room_id == room_id and h.thread
                   and h.thread.is_alive() and not h.stop.is_set()]
        if handles:
            alive = True
            break
        time.sleep(0.05)
    assert alive, "no live loop after restart"
    req(server, "POST", f"/api/rooms/{room_id}/stop")


def test_dashboard_served_and_wired(server):
    """The bundled SPA serves at / and only references API routes that
    exist on this server."""
    import re as _re

    ui_dir = os.path.join(os.path.dirname(__file__), os.pardir, "ui")
    server.static_dir = ui_dir
    html = ""
    for page in ("/", "/app.js", "/panels.js"):
        with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}{page}", timeout=5
        ) as resp:
            html += resp.read().decode()
    assert "room_tpu" in html
    # every /api path the bundle references — double-quoted literals AND
    # template literals like `/api/rooms/${id}/chat` — must match a
    # registered route (params substituted with 1)
    refs = set(_re.findall(r'["`](/api/[a-zA-Z\-/${}.]+)', html))
    assert any("${" in m for m in refs), "template-literal routes missed"
    pre_router = {
        "/api/auth/handshake", "/api/server/restart",
        "/api/server/update-restart",
    }
    for m in refs:
        if m in pre_router:
            continue  # handled before the router
        actions = (
            ("start", "stop", "pause", "run", "resume", "complete",
             "abandon", "answer", "dismiss", "auth", "install")
            if "${action}" in m else (None,)
        )
        hits = 0
        for action in actions:
            path = m.replace("${action}", action) if action else m
            path = _re.sub(r"\$\{[^}]+\}", "1", path).rstrip("/")
            found = any(
                server.router.match(method, path)
                for method in ("GET", "POST", "PUT", "DELETE")
            )
            hits += found
            if "${action}" not in m:
                assert found, \
                    f"dashboard references unknown route {path}"
        assert hits, f"no action verb of {m} resolves to a route"


def test_hetero_two_models_serve_concurrently(server):
    """BASELINE config #5 shape: two model hosts (worker MoE + queen
    dense) serving turns side by side."""
    from room_tpu.providers import ExecutionRequest
    from room_tpu.providers.tpu import TpuProvider, reset_model_hosts

    reset_model_hosts()
    try:
        moe = TpuProvider("tiny-moe")
        dense = TpuProvider("tiny-dense")
        r1 = moe.execute(ExecutionRequest(
            prompt="worker turn", max_new_tokens=4, max_turns=1,
            timeout_s=300,
        ))
        r2 = dense.execute(ExecutionRequest(
            prompt="queen turn", max_new_tokens=4, max_turns=1,
            timeout_s=300,
        ))
        assert r1.success and r2.success
        assert r1.output_tokens > 0 and r2.output_tokens > 0
    finally:
        reset_model_hosts()


def test_start_server_defaults_to_bundled_ui(tmp_path, monkeypatch):
    """The serve entry point must resolve the bundled ui/ dir on its
    own (the other dashboard test sets static_dir by hand)."""
    monkeypatch.setenv("ROOM_TPU_DATA_DIR", str(tmp_path))
    monkeypatch.delenv("ROOM_TPU_STATIC_DIR", raising=False)
    from room_tpu.server import runtime as rt_mod
    from room_tpu.server.app import start_server

    rt_mod._runtime = None  # isolate from other tests' singleton
    app = start_server(port=0, db=Database(":memory:"))
    try:
        assert app.api.static_dir and app.api.static_dir.endswith("ui")
        with urllib.request.urlopen(
            f"http://127.0.0.1:{app.port}/", timeout=5
        ) as resp:
            assert b"room_tpu" in resp.read()
    finally:
        app.stop()
        rt_mod._runtime = None


def test_http_profiling_endpoint(server, monkeypatch):
    from room_tpu.utils.profiling import http_profiler

    http_profiler.reset()
    monkeypatch.setenv("ROOM_TPU_PROFILE_HTTP", "1")
    for _ in range(3):
        req(server, "GET", "/api/rooms")
    req(server, "GET", "/api/rooms/123")  # normalized to /:id
    # recording happens in the handler's finally — poll briefly
    stats = {}
    for _ in range(50):
        _, out = req(server, "GET", "/api/profiling/http")
        stats = out["data"]
        if "GET /api/rooms/:id" in stats and \
                stats.get("GET /api/rooms", {}).get("count", 0) >= 3:
            break
        time.sleep(0.05)
    assert stats["GET /api/rooms"]["count"] >= 3
    assert any(k == "GET /api/rooms/:id" for k in stats)
    assert all("p95_ms" in v for v in stats.values())


def test_profiler_redacts_tokens_and_bounds_keys(server, monkeypatch):
    monkeypatch.setenv("ROOM_TPU_PROFILE_HTTP", "1")
    req(server, "POST", "/api/hooks/task/sekrit-webhook-token-value",
        {}, token=None)
    # recording happens in the handler's finally, which can lag the
    # response by a beat — poll briefly
    keys = ""
    for _ in range(50):
        _, out = req(server, "GET", "/api/profiling/http")
        keys = " ".join(out["data"].keys())
        if "/api/hooks/task/:token" in keys:
            break
        time.sleep(0.05)
    assert "sekrit" not in keys
    assert "/api/hooks/task/:token" in keys
    # unbounded-path spray cannot grow keys past the cap
    from room_tpu.utils.profiling import MAX_KEYS, http_profiler
    try:
        for i in range(MAX_KEYS + 50):
            http_profiler.record("GET", f"/x{i}a/{'q'*3}", 1.0)
        assert len(http_profiler.snapshot()) <= MAX_KEYS
    finally:
        http_profiler.reset()  # don't saturate the global for others


def test_invite_minting_and_use(server, monkeypatch):
    monkeypatch.setenv("ROOM_TPU_CLOUD_JWT_SECRET", "invite-secret")
    status, out = req(server, "POST", "/api/invites", {"ttlDays": 1})
    assert status == 201
    invite = out["data"]["token"]
    # the minted token works as a member credential
    status, rooms_out = req(server, "GET", "/api/rooms",
                            raw_token=invite)
    assert status == 200
    # ... but cannot write, nor mint further invites
    status, _ = req(server, "POST", "/api/rooms", {"name": "x"},
                    raw_token=invite)
    assert status == 403
    status, _ = req(server, "POST", "/api/invites", {},
                    raw_token=invite)
    assert status == 403
    # without the secret configured, minting is unavailable
    monkeypatch.delenv("ROOM_TPU_CLOUD_JWT_SECRET")
    status, _ = req(server, "POST", "/api/invites", {})
    assert status == 503


def test_invite_ttl_validation(server, monkeypatch):
    monkeypatch.setenv("ROOM_TPU_CLOUD_JWT_SECRET", "s")
    for bad in ("inf", "nan", "abc", -1, 0, 9999):
        status, _ = req(server, "POST", "/api/invites",
                        {"ttlDays": bad})
        assert status == 400, bad


def test_webhook_rate_limit_and_eviction(server):
    """Pre-auth surface: 30 req/min per token, then 429; the tracker is
    bounded so attacker-supplied tokens can't grow memory unboundedly
    (webhooks.py _rate_ok)."""
    from room_tpu.server import webhooks as wh

    # isolate the module-global tracker
    old = dict(wh._hits)
    wh._hits.clear()
    try:
        for i in range(wh.WEBHOOK_RATE_PER_MIN):
            status, _ = req(
                server, "POST", "/api/hooks/task/not-a-real-token",
                {}, token=None,
            )
            assert status == 404, (i, status)   # unknown token, counted
        status, out = req(
            server, "POST", "/api/hooks/task/not-a-real-token",
            {}, token=None,
        )
        assert status == 429

        # saturation fails closed, stale tokens get evicted
        now = __import__("time").monotonic()
        for i in range(wh.MAX_TRACKED_TOKENS):
            wh._hits[f"tok{i}"] = [now - 120]   # stale
        status, _ = req(
            server, "POST", "/api/hooks/task/fresh-token", {},
            token=None,
        )
        # eviction freed room: the fresh token must be SERVED (404 =
        # unknown token), not fail-closed rate-limited
        assert status == 404
        assert len(wh._hits) < wh.MAX_TRACKED_TOKENS  # evicted stale
    finally:
        wh._hits.clear()
        wh._hits.update(old)


def test_vote_routes_accept_dashboard_vocabulary(server):
    """The votes panel speaks approve/reject; the quorum core speaks
    yes/no. The routes must translate — before the mapping, every
    panel vote 409ed and a keeper 'reject' would have APPROVED."""
    from room_tpu.core import quorum, rooms

    db = server.db
    room = rooms.create_room(db, "vocab", worker_model="echo")
    d = quorum.open_ballot(db, room["id"], None, "map-me")
    st, out = req(server, "POST", f"/api/decisions/{d['id']}/vote",
                  {"vote": "approve",
                   "workerId": room["queen_worker_id"]})
    assert st == 200, out
    votes = db.query(
        "SELECT vote FROM quorum_votes WHERE decision_id=?", (d["id"],)
    )
    assert votes[0]["vote"] == "yes"

    # no workerId: clean 400 pointing at keeper-vote, never an FK 500
    st, out = req(server, "POST", f"/api/decisions/{d['id']}/vote",
                  {"vote": "approve"})
    assert st == 400 and "keeper-vote" in out["error"]
    # non-string vote: clean 4xx, never a TypeError 500
    st, _ = req(server, "POST", f"/api/decisions/{d['id']}/vote",
                {"vote": ["yes"],
                 "workerId": room["queen_worker_id"]})
    assert st == 409

    # on an open ballot the keeper is one voter: "reject" must be
    # recorded as a "no" ballot vote (pre-mapping it would have been
    # stored raw and, on the announced path, treated as approval)
    d2 = quorum.open_ballot(db, room["id"], None, "veto-me")
    st, out = req(server, "POST",
                  f"/api/decisions/{d2['id']}/keeper-vote",
                  {"vote": "reject"})
    assert st == 200, out
    assert quorum.get_decision(db, d2["id"])["keeper_vote"] == "no"

    # on an ANNOUNCED decision the keeper veto is absolute: "reject"
    # must object, never approve
    d3 = quorum.announce(db, room["id"], None, "announced-veto",
                         decision_type="high_impact")
    st, out = req(server, "POST",
                  f"/api/decisions/{d3['id']}/keeper-vote",
                  {"vote": "reject"})
    assert st == 200, out
    assert quorum.get_decision(db, d3["id"])["status"] == "objected"
