"""Pod fault-tolerance suite (docs/podnet.md).

Chaos matrix for the membership / fencing / wire-hardening / durable-
mirror layer on the CPU backend:

- Circuit breaker unit contract: closed -> open after N consecutive
  failures -> half-open single probe after the cooldown -> closed on
  success / re-open on probe failure.
- Membership ladder: alive -> suspect -> dead on silence, heal at any
  rung before the lease fires, `heartbeat_loss` drops beats without
  touching liveness of the detector itself.
- Partition mid-decode: a partitioned replica's sessions are re-homed
  only after its session lease expires, with zero durably-streamed
  token loss and greedy continuations token-identical to the
  unpartitioned control.
- Partition during an in-flight disagg ship: the ship is aborted, the
  session re-homes from the mirror, the continuation is identical.
- Stale-fence refusal: after a partition heals, the old owner's
  replayed export (over the real RTKW wire) is refused — no session
  fork, no double adoption.
- Wire retry/backoff: `wire_partition` on one attempt is absorbed by
  the retry budget; exhaustion degrades to the documented mirror
  re-prefill (token-identical), and the per-peer breaker opens.
- Router restart: a crash (no drain) mid-stream is recovered from the
  journaled mirror — the rebuilt router re-parks the session and the
  resumed stream is token-identical; `mirror_journal_io` drops are
  detected as holes (cold start, never a forked re-prefill).
- Satellites: the wire-in orphan sweep (dead-PID payloads from a
  receiver that crashed between persist and adopt), the acceptor
  surviving a wedged peer, and the reported (never silent) failed
  accept-thread join.
"""

import os
import socket
import threading
import time

import jax
import pytest

from room_tpu.models import qwen3, tiny_moe
from room_tpu.serving import SamplingParams, ServingEngine, faults
from room_tpu.serving import podnet
from room_tpu.serving.fleet import EngineFleet
from room_tpu.parallel import multihost


@pytest.fixture(autouse=True)
def _clean_state():
    faults.clear()
    podnet.reset_breakers()
    yield
    faults.clear()
    podnet.reset_breakers()


@pytest.fixture(scope="module")
def model():
    cfg = tiny_moe()
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


LONG_PROMPT = list(range(1, 20))
CONT = [7, 7, 7]


def _greedy(n=8):
    return SamplingParams(temperature=0.0, max_new_tokens=n)


@pytest.fixture(scope="module")
def control(model):
    """Uninterrupted two-turn reference streams on one engine."""
    cfg, params = model
    eng = ServingEngine(
        cfg, params, max_batch=4, page_size=8, n_pages=96,
        offload=False, stop_token_ids=[],
    )
    c1 = eng.submit(LONG_PROMPT, session_id="s", sampling=_greedy())
    eng.run_until_idle()
    c2 = eng.submit(CONT, session_id="s", sampling=_greedy())
    eng.run_until_idle()
    return list(c1.new_tokens), list(c2.new_tokens)


@pytest.fixture()
def make_fleet(model, monkeypatch, tmp_path):
    """Fleet factory with the pod knobs tuned for test-speed walks of
    the membership ladder and no real backoff sleeps."""
    monkeypatch.setenv("ROOM_TPU_PREFIX_CACHE_PAGES", "0")
    monkeypatch.setenv("ROOM_TPU_OFFLOAD_DIR", str(tmp_path / "spool"))
    monkeypatch.setenv("ROOM_TPU_LIFECYCLE_DIR", str(tmp_path / "lc"))
    monkeypatch.setenv("ROOM_TPU_DISAGG_PREFILL_TOKENS", "8")
    monkeypatch.setenv("ROOM_TPU_WIRE_BACKOFF_S", "0.001")
    monkeypatch.setenv("ROOM_TPU_POD_HEARTBEAT_S", "0.01")
    monkeypatch.setenv("ROOM_TPU_POD_SUSPECT_S", "0.05")
    monkeypatch.setenv("ROOM_TPU_POD_DEAD_S", "0.1")
    monkeypatch.setenv("ROOM_TPU_POD_LEASE_S", "0.05")
    cfg, params = model

    def build_engine(**kw):
        kw.setdefault("max_batch", 4)
        kw.setdefault("page_size", 8)
        kw.setdefault("n_pages", 96)
        kw.setdefault("offload", True)
        kw.setdefault("stop_token_ids", [])
        return ServingEngine(cfg, params, **kw)

    def build(n=2, roles=None, env=None, **kw):
        for k, v in (env or {}).items():
            monkeypatch.setenv(k, v)
        return EngineFleet(
            "tiny-moe", lambda i: build_engine(**kw), n,
            auto_rebuild=False,
            roles=list(roles) if roles is not None else None,
        )

    build.engine = build_engine
    return build


def _stream_partial(fleet, sid, budget, min_tokens):
    """Submit a greedy turn and step its replica until at least
    ``min_tokens`` streamed; returns (streamed_list, handle)."""
    streamed: list = []
    fleet.submit(LONG_PROMPT, session_id=sid, sampling=_greedy(budget),
                 on_token=streamed.append)
    handle = fleet._handle(fleet._records[sid].rid)
    for _ in range(3000):
        handle.engine.step()
        if len(streamed) >= min_tokens:
            break
    assert len(streamed) >= min_tokens
    return streamed, handle


def _supervise_until(fleet, cond, timeout_s=5.0, sleep_s=0.02):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        fleet.supervise()
        if cond():
            return True
        time.sleep(sleep_s)
    return False


# ---- circuit breaker ----

def test_breaker_opens_half_opens_closes():
    t = [0.0]
    b = podnet.CircuitBreaker(
        "peer", threshold=3, cooldown_s=1.0, clock=lambda: t[0]
    )
    for _ in range(2):
        assert b.allow()
        b.record_failure()
    assert b.state == "closed"
    assert b.allow()
    b.record_failure()            # third consecutive failure
    assert b.state == "open"
    assert not b.allow()          # fast refusal while open
    t[0] = 1.5
    assert b.allow()              # cooldown elapsed: half-open probe
    assert b.state == "half_open"
    assert not b.allow()          # only ONE probe outstanding
    b.record_failure()            # probe failed -> re-open
    assert b.state == "open"
    t[0] = 3.0
    assert b.allow()
    b.record_success()            # probe succeeded -> closed
    assert b.state == "closed"
    assert b.allow()
    snap = b.snapshot()
    assert snap["opens"] == 2 and snap["rejections"] >= 2


def test_breaker_threshold_zero_disables():
    b = podnet.CircuitBreaker("p", threshold=0, cooldown_s=0.0)
    for _ in range(10):
        b.record_failure()
        assert b.allow()
    assert b.state == "closed"


def test_backoff_is_bounded_and_jittered(monkeypatch):
    monkeypatch.setenv("ROOM_TPU_WIRE_BACKOFF_S", "0.05")
    monkeypatch.setenv("ROOM_TPU_WIRE_BACKOFF_MAX_S", "0.4")
    import random

    seen = {
        podnet.wire_backoff_s(a, random.Random(seed))
        for a in range(6) for seed in (1, 2, 3)
    }
    assert all(0.0 < v <= 0.4 for v in seen)
    assert len(seen) > 6   # jitter actually varies
    # deep attempts saturate at the cap
    assert podnet.wire_backoff_s(20, random.Random(0)) == 0.4


# ---- membership ladder ----

def test_membership_ladder_and_heal():
    t = [0.0]
    m = podnet.PodMembership(
        suspect_s=1.0, dead_s=2.0, lease_s=1.0, clock=lambda: t[0]
    )
    m.register("a")
    m.observe("a")
    t[0] = 1.2
    assert ("a", "alive", "suspect") in m.tick()
    # heal from suspect: nothing lost
    m.observe("a")
    assert m.state_of("a") == "alive"
    t[0] = 3.5
    events = m.tick()
    assert ("a", "alive", "suspect") in events
    assert ("a", "suspect", "dead") in events
    # the lease holds the re-home back...
    assert m.lease_expired() == []
    # ...and a late heartbeat inside the lease heals without a re-home
    m.observe("a")
    assert m.state_of("a") == "alive"
    t[0] = 6.0
    m.tick()
    t[0] = 7.1
    assert m.lease_expired() == ["a"]
    assert m.lease_expired() == []   # consumed exactly once
    snap = m.snapshot()
    assert snap["a"]["lease_fired"] is True
    m.observe("a")                   # the healed host re-registers
    assert m.state_of("a") == "alive"
    assert m.snapshot()["a"]["lease_fired"] is False


def test_heartbeat_loss_fault_drops_beats():
    t = [0.0]
    m = podnet.PodMembership(
        suspect_s=1.0, dead_s=2.0, lease_s=0.5, clock=lambda: t[0]
    )
    m.register("a")
    faults.inject("heartbeat_loss", times=3)
    t[0] = 1.5
    for _ in range(3):
        assert m.observe("a") is False   # dropped
    assert m.tick() and m.state_of("a") == "suspect"
    assert faults.fired("heartbeat_loss") == 3
    assert m.observe("a") is True        # budget exhausted: delivered
    assert m.state_of("a") == "alive"
    assert m.snapshot()["a"]["heartbeats_lost"] == 3


# ---- partition chaos: lease-gated re-home, token identity ----

def test_partition_mid_decode_rehomes_after_lease(
    make_fleet, control,
):
    full, cont = control
    fleet = make_fleet(
        n=2, env={"ROOM_TPU_POD_MEMBERSHIP": "1"},
    )
    streamed, victim = _stream_partial(fleet, "s", len(full), 3)
    n = len(streamed)
    # a fresh heartbeat right before the partition: the detector walks
    # the ladder from NOW, not from the pre-jit-compile registration
    fleet.pod._last_beat = 0.0
    fleet.supervise()
    fleet.pod.partition(victim.rid)
    # suspicion first: no re-home before the DEAD + lease deadline
    assert _supervise_until(
        fleet,
        lambda: fleet.pod.membership.state_of(victim.rid) == "suspect",
    )
    assert fleet._handle(victim.rid).state == "serving"
    assert fleet.fleet_stats()["pod"]["lease_rehomes"] == 0
    # then death + lease expiry drives the replica_crash re-home
    assert _supervise_until(
        fleet, lambda: fleet._handle(victim.rid).state == "dead",
    )
    st = fleet.fleet_stats()
    assert st["pod"]["lease_rehomes"] == 1
    assert st["sessions_rehomed"] >= 1
    t2 = fleet.submit(
        [], session_id="s",
        sampling=_greedy(len(full) - n),
    )
    fleet.run_until_idle()
    assert streamed + list(t2.new_tokens) == full
    # the record's ownership generation advanced at the transfer
    assert fleet._records["s"].fence >= 1


def test_partition_during_inflight_ship_aborts_and_rehomes(
    make_fleet, control,
):
    full, cont = control
    fleet = make_fleet(
        n=2, roles=("prefill", "decode"),
        env={"ROOM_TPU_POD_MEMBERSHIP": "1"},
    )
    fleet.pod.tick()
    t1 = fleet.submit(LONG_PROMPT, session_id="s",
                      sampling=_greedy(len(full)))
    donor = fleet._handle(fleet._records["s"].rid)
    assert donor.role == "prefill"
    # freeze the donor's engine behind a fake loop thread so the ship
    # export QUEUES instead of applying inline -> ship stays in flight
    for _ in range(3000):
        donor.engine.step()
        if t1.done.is_set():
            break
    assert t1.done.is_set()
    assert list(t1.new_tokens) == full

    class FakeAliveThread:
        @staticmethod
        def is_alive():
            return True

    donor.engine._loop_thread = FakeAliveThread()
    fleet.disagg.advance()
    rec = fleet._records["s"]
    assert rec.ship_state == "exporting"
    donor.engine._loop_thread = None
    fleet.pod.partition(donor.rid)
    assert _supervise_until(
        fleet, lambda: fleet._handle(donor.rid).state == "dead",
    )
    rec = fleet._records["s"]
    assert rec.ship_state is None          # aborted, not leaked
    assert rec.rid and rec.rid != donor.rid
    t2 = fleet.submit(CONT, session_id="s",
                      sampling=_greedy(len(cont)))
    fleet.run_until_idle()
    assert list(t2.new_tokens) == cont


# ---- fencing: the healed host cannot fork a session ----

def test_stale_fence_export_refused_over_wire(make_fleet, control):
    full, cont = control
    fleet = make_fleet(
        n=3, roles=("prefill", "decode", "decode"),
        env={"ROOM_TPU_DISAGG_WIRE": "loopback"},
    )
    t1 = fleet.submit(LONG_PROMPT, session_id="s",
                      sampling=_greedy(len(full)))
    fleet.run_until_idle()
    assert list(t1.new_tokens) == full
    rec = fleet._records["s"]
    # the ship moved the session to a decode replica and advanced the
    # fence; capture the PRE-transfer generation a partitioned host
    # would still hold
    assert rec.fence >= 1
    stale_fence = rec.fence - 1
    owner_rid = rec.rid
    owner = fleet._handle(owner_rid)
    # a healed host replays its stale export over the real wire
    stale_entry = {
        "id": "s",
        "history": [int(t) for t in (LONG_PROMPT + full)[:-1]],
        "pending": int(full[-1]),
        "length": len(LONG_PROMPT) + len(full) - 1,
        "generation": 0,
        "fence": stale_fence,
        "kv": None,
    }
    other = next(
        h for h in fleet.replicas
        if h.role == "decode" and h.rid != owner_rid
    )
    with pytest.raises(multihost.KVWireRefused, match="stale fence"):
        multihost.kv_wire_send(
            fleet.disagg._wire_server.address, stale_entry,
            target_rid=other.rid,
        )
    assert fleet.fleet_stats()["fence_refusals"] >= 1
    # no fork: the session exists on exactly its owner, and its
    # continuation is token-identical
    assert "s" not in other.engine.sessions
    assert "s" in owner.engine.sessions
    t2 = fleet.submit(CONT, session_id="s",
                      sampling=_greedy(len(cont)))
    fleet.run_until_idle()
    assert list(t2.new_tokens) == cont
    # a CURRENT-fence frame is not refused by the fence gate
    fresh = dict(stale_entry)
    fresh["fence"] = fleet._records["s"].fence
    fresh["id"] = "s"
    reply = multihost.kv_wire_send(
        fleet.disagg._wire_server.address, fresh,
        target_rid=fleet._records["s"].rid,
    )
    assert reply.get("ok")
    fleet.disagg.close()


def test_inflight_ship_superseded_by_rehome_is_discarded(
    make_fleet, control,
):
    """A re-home that lands while an export is in flight advances the
    fence; the ship's dispatch then refuses its own stale entry."""
    full, _ = control
    fleet = make_fleet(n=2, roles=("prefill", "decode"))
    t1 = fleet.submit(LONG_PROMPT, session_id="s",
                      sampling=_greedy(4))
    donor = fleet._handle(fleet._records["s"].rid)
    for _ in range(3000):
        donor.engine.step()
        if t1.done.is_set():
            break

    class FakeAliveThread:
        @staticmethod
        def is_alive():
            return True

    donor.engine._loop_thread = FakeAliveThread()
    fleet.disagg.advance()
    rec = fleet._records["s"]
    assert rec.ship_state == "exporting"
    # a concurrent failover advances the ownership generation
    with fleet._lock:
        rec.fence += 1
    donor.engine._loop_thread = None
    donor.engine._drain_ships()
    before = fleet.fleet_stats()["fence_refusals"]
    fleet.disagg.advance()
    rec = fleet._records["s"]
    assert rec.ship_state is None
    assert fleet.fleet_stats()["fence_refusals"] == before + 1


# ---- wire retry / backoff / breaker ----

def _echo_server(tmp_path):
    got: list = []

    def on_entry(entry, fingerprint, target_rid):
        got.append(entry)
        return {"ok": True, "adopted": False}

    srv = multihost.KVWireServer(str(tmp_path / "wire-in"), on_entry)
    return srv, got


def test_wire_retry_absorbs_transient_partition(
    tmp_path, monkeypatch,
):
    monkeypatch.setenv("ROOM_TPU_WIRE_BACKOFF_S", "0.001")
    srv, got = _echo_server(tmp_path)
    try:
        faults.inject("wire_partition", times=1)
        entry = {"id": "x", "history": [1, 2], "pending": 3,
                 "length": 2, "generation": 0, "kv": None}
        reply = multihost.kv_wire_send(srv.address, entry, retries=3)
        assert reply.get("ok")
        assert faults.fired("wire_partition") == 1
        assert len(got) == 1
        assert podnet.breaker_for(srv.address).state == "closed"
    finally:
        srv.close()


def test_wire_exhaustion_opens_breaker_and_fails_fast(
    tmp_path, monkeypatch,
):
    monkeypatch.setenv("ROOM_TPU_WIRE_BACKOFF_S", "0.001")
    monkeypatch.setenv("ROOM_TPU_WIRE_BREAKER_FAILS", "3")
    monkeypatch.setenv("ROOM_TPU_WIRE_BREAKER_COOLDOWN_S", "60")
    srv, got = _echo_server(tmp_path)
    try:
        faults.inject("wire_partition")   # every attempt fails
        entry = {"id": "x", "history": [1], "pending": 2,
                 "length": 1, "generation": 0, "kv": None}
        with pytest.raises(multihost.KVWireError, match="exhausted"):
            multihost.kv_wire_send(srv.address, entry, retries=3)
        assert podnet.breaker_for(srv.address).state == "open"
        # the open breaker refuses BEFORE any socket work
        with pytest.raises(multihost.KVWireError,
                           match="circuit open"):
            multihost.kv_wire_send(srv.address, entry, retries=3)
        assert not got
    finally:
        srv.close()


def test_ship_degrades_to_mirror_reprefill_on_wire_exhaustion(
    make_fleet, control,
):
    """Acceptance (d): kv_wire_send exhausts its retry budget into the
    documented re-prefill degradation — zero durable-token loss,
    token-identical continuation."""
    full, cont = control
    fleet = make_fleet(
        n=2, roles=("prefill", "decode"),
        env={
            "ROOM_TPU_DISAGG_WIRE": "loopback",
            "ROOM_TPU_WIRE_RETRIES": "2",
        },
    )
    faults.inject("wire_partition")   # every attempt, every send
    t1 = fleet.submit(LONG_PROMPT, session_id="s",
                      sampling=_greedy(len(full)))
    fleet.run_until_idle()
    assert list(t1.new_tokens) == full
    st = fleet.fleet_stats()["disagg"]
    assert st["wire_errors"] >= 1
    assert st["ships_reprefill"] >= 1
    assert faults.fired("wire_partition") >= 2   # retries consumed
    faults.clear()
    t2 = fleet.submit(CONT, session_id="s",
                      sampling=_greedy(len(cont)))
    fleet.run_until_idle()
    assert list(t2.new_tokens) == cont
    fleet.disagg.close()


def test_wire_heartbeats_ride_the_rtkw_wire(make_fleet):
    fleet = make_fleet(
        n=2, roles=("prefill", "decode"),
        env={
            "ROOM_TPU_DISAGG_WIRE": "loopback",
            "ROOM_TPU_POD_MEMBERSHIP": "1",
        },
    )
    try:
        fleet.supervise()
        pod = fleet.fleet_stats()["pod"]
        assert pod["heartbeats_wire"] >= 2
        wire = fleet.fleet_stats()["disagg"]["wire_server"]
        assert wire["control_frames"] >= 2
        states = {m["state"] for m in pod["members"].values()}
        assert states == {"alive"}
    finally:
        fleet.disagg.close()


def test_dead_listener_does_not_kill_healthy_replicas(make_fleet):
    """A wire-listener-only failure must not escalate to a fleet-wide
    kill: in-process members fall back to the direct observe (the
    wire loss stays visible in heartbeats_lost)."""
    fleet = make_fleet(
        n=2, roles=("prefill", "decode"),
        env={
            "ROOM_TPU_DISAGG_WIRE": "loopback",
            "ROOM_TPU_POD_MEMBERSHIP": "1",
            "ROOM_TPU_WIRE_RETRIES": "1",
        },
    )
    try:
        fleet.supervise()
        fleet.disagg._wire_server.close()   # the listener dies
        deadline = time.monotonic() + 1.0   # >> dead_s + lease_s
        while time.monotonic() < deadline:
            fleet.supervise()
            time.sleep(0.02)
        pod = fleet.fleet_stats()["pod"]
        assert pod["heartbeats_lost"] >= 1
        assert all(
            m["state"] == "alive" for m in pod["members"].values()
        ), pod
        assert pod["lease_rehomes"] == 0
        assert all(h.state == "serving" for h in fleet.replicas)
    finally:
        fleet.disagg.close()


# ---- crash-durable router mirror ----

def test_router_restart_recovers_mid_stream_from_journal(
    make_fleet, control,
):
    """Acceptance (c): a router process restart (no drain — the crash
    case) rebuilds its mirror from the journal and the mid-stream
    session resumes token-identically."""
    full, cont = control
    env = {"ROOM_TPU_POD_MIRROR": "1"}
    fleet1 = make_fleet(n=1, roles=("mixed",), env=env)
    streamed, handle = _stream_partial(fleet1, "s", len(full), 3)
    n = len(streamed)
    # router process "crashes": no drain, no manifest — the journal is
    # all that survives
    del fleet1, handle
    fleet2 = make_fleet(n=1, roles=("mixed",))
    st = fleet2.fleet_stats()
    assert st["mirror_restored"] == 1
    assert st["mirror"]["journal"]["replayed_sessions"] == 1
    t2 = fleet2.submit(
        [], session_id="s", sampling=_greedy(len(full) - n),
    )
    fleet2.run_until_idle()
    assert streamed + list(t2.new_tokens) == full
    # and the NEXT turn keeps flowing through the rebuilt mirror
    t3 = fleet2.submit(CONT, session_id="s",
                       sampling=_greedy(len(cont)))
    fleet2.run_until_idle()
    assert list(t3.new_tokens) == cont


def test_clean_drain_clears_journal_no_double_restore(
    make_fleet, control,
):
    full, _ = control
    env = {"ROOM_TPU_POD_MIRROR": "1", "ROOM_TPU_LIFECYCLE": "1"}
    fleet1 = make_fleet(n=1, roles=("mixed",), env=env)
    t1 = fleet1.submit(LONG_PROMPT, session_id="s",
                       sampling=_greedy(len(full)))
    fleet1.run_until_idle()
    assert list(t1.new_tokens) == full
    summary = fleet1.drain()
    assert summary["manifest_written"]
    fleet2 = make_fleet(n=1, roles=("mixed",))
    # the manifest is the restart authority; the consumed journal must
    # not resurrect a second copy
    assert fleet2.fleet_stats()["mirror_restored"] == 0
    restored = fleet2.restore_from_manifest()
    assert restored["resumed"] + restored["reprefill"] >= 1


def test_mirror_journal_io_fault_degrades_never_breaks_serving(
    make_fleet, control,
):
    full, _ = control
    fleet = make_fleet(
        n=1, roles=("mixed",), env={"ROOM_TPU_POD_MIRROR": "1"},
    )
    faults.inject("mirror_journal_io", probability=0.5, seed=7)
    t1 = fleet.submit(LONG_PROMPT, session_id="s",
                      sampling=_greedy(len(full)))
    fleet.run_until_idle()
    # live serving is untouched by journal failures
    assert list(t1.new_tokens) == full
    assert faults.fired("mirror_journal_io") >= 1
    stats = fleet.mirror_journal.stats()
    assert stats["errors"] >= 1
    faults.clear()
    # a replay over the holey journal either restores the session
    # complete or refuses it — never a partial/forked mirror
    state = fleet.mirror_journal.replay()
    if "s" in state and state["s"]["complete"]:
        assert state["s"]["tokens"] == LONG_PROMPT + full


def test_cap_evicted_mirror_never_resumes_from_journal(
    make_fleet, control,
):
    """A cap-evicted mirror keeps streaming durable tokens the
    journal no longer sees — replaying its truncated prefix after a
    router crash would fork the session. The eviction must drop the
    journal's claim."""
    full, _ = control
    fleet = make_fleet(
        n=1, roles=("mixed",),
        env={
            "ROOM_TPU_POD_MIRROR": "1",
            "ROOM_TPU_FLEET_MIRROR_TOKENS": "4",
        },
    )
    t1 = fleet.submit(LONG_PROMPT, session_id="s",
                      sampling=_greedy(len(full)))
    fleet.run_until_idle()
    assert list(t1.new_tokens) == full
    assert fleet.fleet_stats()["mirror"]["evictions"] >= 1
    state = fleet.mirror_journal.replay()
    assert "s" not in state or not state["s"]["tokens"]
    # and a rebuilt router must NOT restore it from the journal
    fleet2 = make_fleet(n=1, roles=("mixed",))
    assert fleet2.fleet_stats()["mirror_restored"] == 0


def test_journal_compaction_preserves_replay(make_fleet, control):
    full, _ = control
    fleet = make_fleet(
        n=1, roles=("mixed",), env={"ROOM_TPU_POD_MIRROR": "1"},
    )
    t1 = fleet.submit(LONG_PROMPT, session_id="s",
                      sampling=_greedy(len(full)))
    fleet.run_until_idle()
    assert fleet.mirror_journal.compact(
        fleet._mirror_snapshot_sessions()
    )
    state = fleet.mirror_journal.replay()
    assert state["s"]["complete"]
    assert state["s"]["tokens"] == LONG_PROMPT + full
    assert state["s"]["rid"] == fleet._records["s"].rid


# ---- wire server satellites ----

def test_wire_in_orphan_sweep_dead_pid(tmp_path):
    wire_dir = tmp_path / "wire-in"
    wire_dir.mkdir()
    dead = wire_dir / "pid999999-wire1-kv.kvspool"
    dead.write_bytes(b"orphaned payload")
    live = wire_dir / f"pid{os.getpid()}-wire2-kv.kvspool"
    live.write_bytes(b"live payload")
    srv = multihost.KVWireServer(
        str(wire_dir), lambda e, f, t: {"ok": True}
    )
    try:
        assert not dead.exists()      # dead-PID payload swept at boot
        assert live.exists()          # live sibling's file untouched
        assert srv.stats()["orphans_swept"] == 1
    finally:
        srv.close()


def test_wedged_peer_does_not_hold_the_acceptor(tmp_path):
    srv, got = _echo_server(tmp_path)
    wedged = socket.create_connection(srv.address, timeout=5.0)
    try:
        wedged.sendall(b"RT")   # partial magic, then silence
        t0 = time.monotonic()
        # no on_control wired here: the prompt REFUSAL is the proof —
        # the frame was read and answered on its own handler thread
        # while the wedged peer still held a connection open
        with pytest.raises(multihost.KVWireRefused,
                           match="no control frames"):
            multihost.wire_send_control(
                srv.address, {"kind": "heartbeat", "member": "m0"},
                retries=1,
            )
        elapsed = time.monotonic() - t0
        assert elapsed < multihost.wire_timeout_s() / 2
        st = srv.stats()
        assert st["open_handlers"] >= 1
        assert st["accept_alive"]
    finally:
        wedged.close()
        srv.close()


def test_failed_accept_join_is_reported_not_silent(tmp_path):
    srv, _ = _echo_server(tmp_path)

    class WedgedThread:
        @staticmethod
        def join(timeout=None):
            pass              # "join" that never succeeds

        @staticmethod
        def is_alive():
            return True

    real = srv._thread
    srv._thread = WedgedThread()
    srv.close()
    assert srv.stats()["accept_join_failed"] == 1
    assert srv.stats()["accept_alive"]
    srv._thread = real
    real.join(timeout=5.0)


def test_saturated_receiver_is_retryable_not_a_refusal(tmp_path):
    """Backpressure must feed the retry budget and the breaker as a
    FAILURE — a saturated receiver is not an application refusal a
    heartbeat or shipment should give up on."""
    srv, _ = _echo_server(tmp_path)
    srv.max_handlers = 0   # every slot "busy": instant saturation
    try:
        with pytest.raises(multihost.KVWireError,
                           match="backpressure") as ei:
            multihost.wire_send_control(
                srv.address, {"kind": "heartbeat", "member": "m"},
                retries=2,
            )
        assert not isinstance(ei.value, multihost.KVWireRefused)
        snap = podnet.breaker_for(srv.address).snapshot()
        assert snap["consecutive_failures"] == 2   # both attempts
        assert srv.stats()["handlers_capped"] == 2
    finally:
        srv.max_handlers = 16
        srv.close()


def test_journal_compact_callable_never_loses_racing_appends(
    tmp_path,
):
    """The fleet's callable-compaction form: appends racing the
    snapshot/swap park in memory and land in the new journal — a
    replay sees every token exactly once (overlaps absorbed)."""
    j = podnet.MirrorJournal(str(tmp_path), batch=1, compact_lines=4)
    j.record_place("s", "r0", 1, 0)
    j.append_tokens("s", [1, 2, 3], 0)

    def sessions():
        # an append lands mid-snapshot-build: the snapshot below
        # already covers token 4, and its journal line is parked
        j.append_tokens("s", [4], 3)
        return [{"sid": "s", "rid": "r0", "fence": 1, "gen": 0,
                 "tokens": [1, 2, 3, 4]}]

    assert j.compact(sessions)
    j.append_tokens("s", [5], 4)
    state = j.replay()
    assert state["s"]["complete"]
    assert state["s"]["tokens"] == [1, 2, 3, 4, 5]


def test_control_frame_with_payload_is_refused(tmp_path):
    srv, _ = _echo_server(tmp_path)
    try:
        import json
        import struct

        header = json.dumps(
            {"control": {"kind": "heartbeat", "member": "x"}}
        ).encode()
        with socket.create_connection(srv.address, timeout=5.0) as c:
            c.sendall(
                multihost.WIRE_MAGIC
                + struct.pack("<I", multihost.WIRE_VERSION)
                + struct.pack("<Q", len(header)) + header
                + struct.pack("<Q", 4) + b"XXXX"
            )
            reply = multihost._recv_json(c)
        assert reply["ok"] is False
        assert "control frame with payload" in reply["error"]
    finally:
        srv.close()
