"""Contacts subsystem: email code verification state machine, telegram
deep-link flow + webhook confirm, keeper email, route surface
(reference behaviors: src/server/routes/contacts.ts,
keeper-email.ts)."""

import json
import os
import time

import pytest

from room_tpu.db import Database
from room_tpu.server import contacts
from room_tpu.server.contacts import (
    ApiError, check_telegram_verification, confirm_telegram_verification,
    contacts_status, disconnect_telegram, hash_email_code,
    issue_email_verification, send_keeper_email,
    start_telegram_verification, verify_email_code,
)


@pytest.fixture
def db(tmp_path, monkeypatch):
    monkeypatch.setenv("ROOM_TPU_DATA_DIR", str(tmp_path / "data"))
    monkeypatch.setenv("ROOM_TPU_EMAIL_OUTBOX", str(tmp_path / "outbox"))
    return Database(":memory:")


def _outbox(tmp_path) -> list[dict]:
    out = []
    box = tmp_path / "outbox"
    if box.is_dir():
        for p in sorted(box.iterdir()):
            out.append(json.loads(p.read_text()))
    return out


def _sent_code(tmp_path) -> str:
    mails = _outbox(tmp_path)
    assert mails, "no email delivered"
    import re

    m = re.search(r"\b(\d{6})\b", mails[-1]["body"])
    assert m, mails[-1]
    return m.group(1)


def test_email_verification_happy_path(db, tmp_path):
    out = issue_email_verification(db, "keeper@example.com")
    assert out["sentTo"] == "keeper@example.com"
    code = _sent_code(tmp_path)
    result = verify_email_code(db, code)
    assert result["email"] == "keeper@example.com"
    st = contacts_status(db)
    assert st["email"]["verified"] is True
    assert st["email"]["address"] == "keeper@example.com"
    assert st["email"]["pendingCode"] is False


def test_email_wrong_code_and_expiry(db, tmp_path):
    issue_email_verification(db, "k@example.com")
    with pytest.raises(ApiError, match="Invalid verification code"):
        wrong = "000000" if _sent_code(tmp_path) != "000000" else "111111"
        verify_email_code(db, wrong)
    # expire the code
    from room_tpu.core.messages import set_setting

    set_setting(db, contacts.K_EMAIL_CODE_EXPIRES,
                str(time.time() - 1))
    with pytest.raises(ApiError, match="expired"):
        verify_email_code(db, _sent_code(tmp_path))
    # expired code was cleared -> "no pending"
    with pytest.raises(ApiError, match="No pending"):
        verify_email_code(db, "123456")


def test_email_resend_cooldown_and_rate_window(db, tmp_path):
    issue_email_verification(db, "k@example.com")
    with pytest.raises(ApiError) as exc:
        issue_email_verification(db, "k@example.com")
    assert exc.value.status == 429
    assert exc.value.retry_after_s is not None
    # hourly cap: wind the cooldown back each time but keep the window
    from room_tpu.core.messages import set_setting

    for _ in range(contacts.EMAIL_MAX_SENDS_PER_HOUR - 1):
        set_setting(db, contacts.K_EMAIL_LAST_SENT,
                    str(time.time() - 61))
        issue_email_verification(db, "k@example.com")
    set_setting(db, contacts.K_EMAIL_LAST_SENT, str(time.time() - 61))
    with pytest.raises(ApiError, match="Too many"):
        issue_email_verification(db, "k@example.com")


def test_email_no_transport_fails_closed(db, monkeypatch):
    monkeypatch.delenv("ROOM_TPU_EMAIL_OUTBOX")
    with pytest.raises(ApiError) as exc:
        issue_email_verification(db, "k@example.com")
    assert exc.value.status == 502
    # nothing was persisted: no pending code
    assert contacts_status(db)["email"]["pendingCode"] is False


def test_code_hash_is_keyed_per_install(db):
    h1 = hash_email_code(db, "a@b.co", "123456")
    h2 = hash_email_code(db, "a@b.co", "123457")
    assert h1 != h2 and len(h1) == 64


def test_telegram_flow_webhook_confirm(db):
    out = start_telegram_verification(db)
    assert out["pending"] and "t.me/" in out["deepLink"]
    token = out["deepLink"].split("start=tv1_")[1]
    assert check_telegram_verification(db)["status"] == "pending"

    assert not confirm_telegram_verification(db, "wrong-token", "99")
    assert confirm_telegram_verification(
        db, token, "42", username="keeper", first_name="Kay"
    )
    st = check_telegram_verification(db)
    assert st["status"] == "verified"
    assert st["telegram"]["id"] == "42"
    assert contacts_status(db)["telegram"]["connected"] is True

    disconnect_telegram(db)
    assert contacts_status(db)["telegram"]["connected"] is False
    assert check_telegram_verification(db)["status"] == "not_pending"


def test_telegram_expiry(db):
    from room_tpu.core.messages import set_setting

    start_telegram_verification(db)
    set_setting(db, contacts.K_TG_PENDING_EXPIRES,
                str(time.time() - 1))
    assert check_telegram_verification(db)["status"] == "expired"
    # and confirm after expiry fails
    assert not confirm_telegram_verification(db, "anything", "7")


def test_send_keeper_email_admin_requires_verified(db, tmp_path):
    assert send_keeper_email(db, "admin", "hello") is False
    issue_email_verification(db, "keeper@example.com")
    verify_email_code(db, _sent_code(tmp_path))
    assert send_keeper_email(db, "admin", "hello keeper") is True
    mails = _outbox(tmp_path)
    # same-millisecond writes make file order nondeterministic: match
    # by content
    assert any(
        m["to"] == "keeper@example.com" and m["body"] == "hello keeper"
        for m in mails
    )
    msg = db.query_one(
        "SELECT * FROM clerk_messages ORDER BY id DESC LIMIT 1"
    )
    assert msg["source"] == "email"


def test_contact_routes_and_webhook(db, tmp_path, monkeypatch):
    from tests.test_server import req

    from room_tpu.server.http import ApiServer

    server = ApiServer(db)
    server.start()
    try:
        status, out = req(server, "GET", "/api/contacts/status")
        assert status == 200
        assert out["data"]["email"]["verified"] is False

        status, out = req(server, "POST", "/api/contacts/email/start",
                          {"email": "not-an-email"})
        assert status == 400

        status, out = req(server, "POST", "/api/contacts/email/start",
                          {"email": "K@Example.com"})
        assert status == 200 and out["data"]["sentTo"] == "k@example.com"
        status, out = req(server, "POST", "/api/contacts/email/verify",
                          {"code": _sent_code(tmp_path)})
        assert status == 200 and out["data"]["email"] == "k@example.com"
        # idempotent start on verified email
        status, out = req(server, "POST", "/api/contacts/email/start",
                          {"email": "k@example.com"})
        assert status == 200 and out["data"]["alreadyVerified"] is True

        status, out = req(server, "POST",
                          "/api/contacts/telegram/start", {})
        assert status == 200
        token = out["data"]["deepLink"].split("start=tv1_")[1]
        # webhook confirm rides the pre-auth tokened path
        import urllib.request

        r = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/api/hooks/telegram/{token}",
            data=json.dumps({"id": "777", "username": "kp"}).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(r, timeout=10) as resp:
            assert resp.status == 200
        status, out = req(server, "POST",
                          "/api/contacts/telegram/check", {})
        assert out["data"]["status"] == "verified"
        status, out = req(server, "POST",
                          "/api/contacts/telegram/disconnect", {})
        assert out["data"]["ok"] is True
    finally:
        server.stop()
