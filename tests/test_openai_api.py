"""OpenAI-compatible /v1 surface: the drop-in equivalent of the Ollama
endpoint the reference points OpenAI-style clients at
(src/shared/local-model.ts:3-5, agent-executor.ts:327-338)."""

import json
import time
import urllib.request

import pytest

from room_tpu.db import Database
from room_tpu.providers.tpu import reset_model_hosts
from room_tpu.server.http import ApiServer


@pytest.fixture()
def server(tmp_path, monkeypatch):
    monkeypatch.setenv("ROOM_TPU_DATA_DIR", str(tmp_path / "data"))
    db = Database(":memory:")
    srv = ApiServer(db)
    srv.start()
    reset_model_hosts()
    yield srv
    reset_model_hosts()
    srv.stop()


def call(server, method, path, body=None, token=True, raw=False):
    headers = {"Content-Type": "application/json"}
    if token:
        headers["Authorization"] = f"Bearer {server.tokens['agent']}"
    r = urllib.request.Request(
        f"http://127.0.0.1:{server.port}{path}",
        data=json.dumps(body).encode() if body is not None else None,
        headers=headers, method=method,
    )
    try:
        with urllib.request.urlopen(r, timeout=300) as resp:
            data = resp.read()
            return resp.status, data if raw else json.loads(data)
    except urllib.error.HTTPError as e:
        data = e.read()
        return e.code, data if raw else json.loads(data)


def test_v1_models_lists_tpu_models(server):
    status, out = call(server, "GET", "/v1/models")
    assert status == 200
    assert out["object"] == "list"
    ids = {m["id"] for m in out["data"]}
    assert "tpu:qwen3-coder-30b" in ids and "tpu:tiny-moe" in ids
    tiny = next(m for m in out["data"] if m["id"] == "tpu:tiny-moe")
    assert tiny["ready"] is True


def test_v1_requires_auth(server):
    status, out = call(server, "GET", "/v1/models", token=False)
    assert status == 401
    # even pre-handler rejections carry the OpenAI error object
    assert out["error"]["type"] == "authentication_error"
    assert isinstance(out["error"]["message"], str)


def test_v1_chat_completion(server):
    status, out = call(server, "POST", "/v1/chat/completions", {
        "model": "tpu:tiny-moe",
        "messages": [
            {"role": "system", "content": "you are terse"},
            {"role": "user", "content": "say something"},
        ],
        "max_tokens": 6,
        "temperature": 0,
    })
    assert status == 200, out
    # OpenAI wire shape, not the internal {status,data} envelope
    assert out["object"] == "chat.completion"
    assert out["id"].startswith("chatcmpl-")
    choice = out["choices"][0]
    assert choice["message"]["role"] == "assistant"
    assert isinstance(choice["message"]["content"], str)
    assert choice["finish_reason"] in ("stop", "length")
    u = out["usage"]
    assert u["prompt_tokens"] > 0 and 1 <= u["completion_tokens"] <= 6
    assert u["total_tokens"] == u["prompt_tokens"] + u["completion_tokens"]


def test_v1_penalties_accepted_and_validated(server):
    body = {
        "model": "tpu:tiny-moe",
        "messages": [{"role": "user", "content": "no repeats"}],
        "max_tokens": 5, "temperature": 0,
        "presence_penalty": 1.5, "frequency_penalty": 0.5,
    }
    status, out = call(server, "POST", "/v1/chat/completions", body)
    assert status == 200, out
    assert out["choices"][0]["finish_reason"] in ("stop", "length")

    status, out = call(server, "POST", "/v1/chat/completions",
                       {**body, "presence_penalty": 2.5})
    assert status == 400
    assert "presence_penalty" in out["error"]["message"]
    status, out = call(server, "POST", "/v1/chat/completions",
                       {**body, "frequency_penalty": -3})
    assert status == 400
    assert "frequency_penalty" in out["error"]["message"]


def test_v1_stop_sequence_caps(server):
    body = {
        "model": "tpu:tiny-moe",
        "messages": [{"role": "user", "content": "x"}],
        "max_tokens": 3, "temperature": 0,
    }
    status, out = call(server, "POST", "/v1/chat/completions",
                       {**body, "stop": ["a", "b", "c", "d", "e"]})
    assert status == 400
    assert "4 stop sequences" in out["error"]["message"]
    status, out = call(server, "POST", "/v1/chat/completions",
                       {**body, "stop": "q" * 65})
    assert status == 400
    assert "64 bytes" in out["error"]["message"]


def test_v1_chat_unknown_model_openai_error_shape(server):
    status, out = call(server, "POST", "/v1/chat/completions", {
        "model": "gpt-4o", "messages": [{"role": "user", "content": "x"}],
    })
    assert status == 404
    assert out["error"]["message"].startswith("unknown model")
    assert out["error"]["type"] == "invalid_request_error"


def test_v1_chat_validates_messages(server):
    status, out = call(server, "POST", "/v1/chat/completions",
                       {"model": "tpu:tiny-moe"})
    assert status == 400
    assert "messages" in out["error"]["message"]


def test_v1_chat_streaming_sse(server):
    status, body = call(server, "POST", "/v1/chat/completions", {
        "model": "tpu:tiny-moe",
        "messages": [{"role": "user", "content": "stream it"}],
        "max_tokens": 5, "temperature": 0, "stream": True,
    }, raw=True)
    assert status == 200
    events = [
        line[len("data: "):]
        for line in body.decode().splitlines()
        if line.startswith("data: ")
    ]
    assert events[-1] == "[DONE]"
    chunks = [json.loads(e) for e in events[:-1]]
    assert chunks[0]["choices"][0]["delta"]["role"] == "assistant"
    assert all(c["object"] == "chat.completion.chunk" for c in chunks)
    # a finish_reason arrives on the last content-bearing chunk
    assert chunks[-1]["choices"][0]["finish_reason"] in (
        "stop", "length"
    )
    # streamed content concatenates to the non-streamed completion
    text = "".join(
        c["choices"][0]["delta"].get("content") or "" for c in chunks
    )
    _, full = call(server, "POST", "/v1/chat/completions", {
        "model": "tpu:tiny-moe",
        "messages": [{"role": "user", "content": "stream it"}],
        "max_tokens": 5, "temperature": 0,
    })
    assert text == full["choices"][0]["message"]["content"]


def test_v1_null_params_use_defaults(server):
    """OpenAI clients serialize unset knobs as JSON null."""
    status, out = call(server, "POST", "/v1/chat/completions", {
        "model": "tpu:tiny-moe",
        "messages": [{"role": "user", "content": "hello"}],
        "temperature": None, "top_p": None, "max_tokens": 4,
    })
    assert status == 200, out
    assert out["choices"][0]["message"]["content"] is not None


def test_v1_no_chat_scaffolding_in_content(server):
    """Stop tokens (<|im_end|>) must never reach the client, streamed
    or not."""
    body = {
        "model": "tpu:tiny-moe",
        "messages": [{"role": "user", "content": "talk"}],
        "max_tokens": 8, "temperature": 0,
    }
    _, out = call(server, "POST", "/v1/chat/completions", body)
    assert "<|im_end|>" not in (out["choices"][0]["message"]["content"]
                                or "")
    _, raw = call(server, "POST", "/v1/chat/completions",
                  {**body, "stream": True}, raw=True)
    assert b"<|im_end|>" not in raw


class _ScriptedEngine:
    """Engine stand-in that streams a fixed reply (tool call included)
    so the route's streaming/tool plumbing is testable independently of
    what a random-weight model happens to emit."""

    def __init__(self, text):
        import threading

        from room_tpu.serving import ByteTokenizer

        self.tokenizer = ByteTokenizer()
        self.stop_token_ids = {self.tokenizer.IM_END}
        self.sessions = {}
        self.released = []
        self._text = text
        self._threading = threading

    def submit(self, prompt_tokens, *, sampling=None, on_token=None,
               session_id=None, stop_strings=None):
        th = self._threading

        class Turn:
            pass

        turn = Turn()
        turn.session_id = session_id or "scripted"
        turn.new_tokens = []
        turn.finish_reason = None
        turn.stop_hit = None
        turn.error = None
        turn.done = th.Event()
        ids = self.tokenizer.encode(self._text)

        def run():
            for t in ids:
                turn.new_tokens.append(t)
                if on_token:
                    on_token(t)
            turn.finish_reason = "tool_call"
            turn.done.set()

        th.Thread(target=run, daemon=True).start()
        return turn

    def release_session(self, sid):
        self.released.append(sid)


def test_v1_streaming_tool_call_never_leaks_xml(server, monkeypatch):
    eng = _ScriptedEngine(
        'Checking. <tool_call>\n{"name": "get_weather", '
        '"arguments": {"city": "Oslo"}}\n</tool_call>'
    )

    class Host:
        def engine(self):
            return eng

    import room_tpu.providers.tpu as tpu_mod

    monkeypatch.setattr(tpu_mod, "get_model_host", lambda name: Host())

    status, body = call(server, "POST", "/v1/chat/completions", {
        "model": "tpu:tiny-moe",
        "messages": [{"role": "user", "content": "weather in Oslo?"}],
        "stream": True,
        "tools": [{"type": "function",
                   "function": {"name": "get_weather"}}],
    }, raw=True)
    assert status == 200
    events = [
        json.loads(line[len("data: "):])
        for line in body.decode().splitlines()
        if line.startswith("data: ") and line != "data: [DONE]"
    ]
    content = "".join(
        e["choices"][0]["delta"].get("content") or ""
        for e in events if "choices" in e
    )
    assert "<tool_call>" not in content and "get_weather" not in content
    assert content.strip() == "Checking."
    tool_chunks = [
        e for e in events
        if "choices" in e and e["choices"][0]["delta"].get("tool_calls")
    ]
    assert tool_chunks, events
    fn = tool_chunks[0]["choices"][0]["delta"]["tool_calls"][0]["function"]
    assert fn["name"] == "get_weather"
    assert json.loads(fn["arguments"]) == {"city": "Oslo"}
    finish = [e for e in events if "choices" in e and
              e["choices"][0]["finish_reason"]]
    assert finish[-1]["choices"][0]["finish_reason"] == "tool_calls"
    assert eng.released  # session freed after the stream


def test_v1_embeddings(server):
    """The 384-d on-mesh encoder behind the OpenAI embeddings shape."""
    status, out = call(server, "POST", "/v1/embeddings", {
        "input": ["alpha beta", "gamma"],
    })
    assert status == 200, out
    from room_tpu.serving.embed_service import get_embed_host

    assert out["object"] == "list" and len(out["data"]) == 2
    v0 = out["data"][0]["embedding"]
    # 384-d with the real checkpoint; the hermetic tiny encoder's dim
    # otherwise — the route reports whichever is loaded
    assert len(v0) == get_embed_host().dim
    assert out["model"] == f"room-embed-{get_embed_host().dim}"
    # unit-normalized (cosine-ready), deterministic
    import math
    assert abs(math.sqrt(sum(x * x for x in v0)) - 1.0) < 1e-3
    _, again = call(server, "POST", "/v1/embeddings",
                    {"input": "alpha beta"})
    v1 = again["data"][0]["embedding"]
    # deterministic up to batch-shape float noise
    assert max(abs(a - b) for a, b in zip(v0, v1)) < 1e-5

    status, out = call(server, "POST", "/v1/embeddings", {})
    assert status == 400 and "input" in out["error"]["message"]


def test_v1_sessions_released_after_turn(server):
    from room_tpu.providers.tpu import get_model_host

    for _ in range(3):
        status, _ = call(server, "POST", "/v1/chat/completions", {
            "model": "tpu:tiny-moe",
            "messages": [{"role": "user", "content": "hi"}],
            "max_tokens": 3,
        })
        assert status == 200
    eng = get_model_host("tiny-moe")._engine
    # releases apply on the engine thread (concurrency contract): give
    # its loop a moment to drain the release queue
    deadline = time.time() + 10
    while time.time() < deadline and eng.sessions:
        time.sleep(0.02)
    assert len(eng.sessions) == 0


def test_v1_stop_sequence_nonstream(server, monkeypatch):
    """A custom stop string ends generation and is excluded from the
    reply (OpenAI `stop` semantics; the reference's Ollama daemon
    honored these natively)."""
    eng = _ScriptedEngine("alpha STOPWORD omega never-seen")
    # scripted engine ignores stops itself; emulate the real engine's
    # behavior by exposing stop_hit through a subclassed submit
    real_submit = eng.submit

    def submit(prompt_tokens, **kw):
        turn = real_submit(prompt_tokens, **kw)
        turn.done.wait(5)
        if kw.get("stop_strings"):
            text = eng.tokenizer.decode(turn.new_tokens)
            for s in kw["stop_strings"]:
                if s in text:
                    turn.stop_hit = s
                    turn.finish_reason = "stop"
        return turn

    eng.submit = submit

    class Host:
        def engine(self):
            return eng

    import room_tpu.providers.tpu as tpu_mod

    monkeypatch.setattr(tpu_mod, "get_model_host", lambda name: Host())
    status, out = call(server, "POST", "/v1/chat/completions", {
        "model": "tpu:tiny-moe",
        "messages": [{"role": "user", "content": "go"}],
        "stop": "STOPWORD",
    })
    assert status == 200
    content = out["choices"][0]["message"]["content"]
    assert "STOPWORD" not in content
    assert content.startswith("alpha")
    assert "omega" not in content


def test_v1_stop_sequence_streaming_never_leaks(server, monkeypatch):
    """Streaming must hold back any suffix that could grow into a stop
    sequence and never deliver the sequence or what follows."""
    eng = _ScriptedEngine("one two STOPWORD three four")

    class Host:
        def engine(self):
            return eng

    import room_tpu.providers.tpu as tpu_mod

    monkeypatch.setattr(tpu_mod, "get_model_host", lambda name: Host())
    status, body = call(server, "POST", "/v1/chat/completions", {
        "model": "tpu:tiny-moe",
        "messages": [{"role": "user", "content": "go"}],
        "stream": True,
        "stop": ["STOPWORD"],
    }, raw=True)
    assert status == 200
    events = [
        json.loads(line[len("data: "):])
        for line in body.decode().splitlines()
        if line.startswith("data: ") and line != "data: [DONE]"
    ]
    content = "".join(
        e["choices"][0]["delta"].get("content") or ""
        for e in events if "choices" in e
    )
    assert "STOPWORD" not in content
    assert "three" not in content
    assert content.startswith("one two")


def test_engine_stop_strings_end_generation(server):
    """Real engine path: stop_strings finish the turn with reason
    'stop' and record which string fired, even when the string spans
    token boundaries."""
    import jax

    from room_tpu.models import qwen3
    from room_tpu.models.config import tiny_moe
    from room_tpu.serving import SamplingParams, ServingEngine

    cfg = tiny_moe()
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    eng = ServingEngine(cfg, params, max_batch=1, page_size=8,
                        n_pages=64)
    # byte tokenizer: every decoded token is one char, so pick a stop
    # string the random model will hit quickly (any single byte it
    # emits early)
    probe = eng.submit([1, 2, 3], sampling=SamplingParams(
        temperature=0.0, max_new_tokens=8))
    eng.run_until_idle()
    assert probe.new_tokens
    decoded = eng.tokenizer.decode(probe.new_tokens)
    assert decoded, "greedy run emitted no decodable text"
    stop_char = decoded[:2] if len(decoded) >= 2 else decoded
    eng.release_session(probe.session_id)

    t = eng.submit([1, 2, 3], sampling=SamplingParams(
        temperature=0.0, max_new_tokens=64),
        stop_strings=[stop_char])
    eng.run_until_idle()
    assert t.finish_reason == "stop"
    assert t.stop_hit == stop_char
    assert len(t.new_tokens) < 64
