"""Update checker / staged auto-update / restart + MCP auto-registration
(reference behaviors: src/server/updateChecker.ts, autoUpdate.ts,
index.ts:526-576 restart endpoints, index.ts:729-864 registerMcpGlobally).
All network is stubbed with a local HTTP server (zero egress image)."""

import hashlib
import io
import json
import os
import tarfile
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from room_tpu import __version__
from room_tpu.server import updater
from room_tpu.server.updater import (
    UpdateChecker, get_ready_update_version, init_boot_health_check,
    parse_semver, promote_staged_update, semver_gt,
)


# ---- semver ----

def test_semver():
    assert parse_semver("v1.2.3") == (1, 2, 3)
    assert parse_semver("1.2.3-rc1") == (1, 2, 3)
    assert parse_semver("nope") is None
    assert semver_gt("1.2.10", "1.2.9")
    assert not semver_gt("1.2.3", "1.2.3")
    assert not semver_gt("garbage", "1.0.0")


def test_github_release_pick():
    releases = [
        {"tag_name": "v2.0.0", "prerelease": True, "assets": []},
        {"tag_name": "v1.4.0-test", "assets": []},
        {"tag_name": "v1.3.0", "html_url": "u3", "assets": [
            {"name": "room-tpu-update-1.3.0.tar.gz",
             "browser_download_url": "http://x/b.tar.gz"},
            {"name": "installer.pkg", "browser_download_url": "p"},
        ]},
        {"tag_name": "v1.2.0", "html_url": "u2", "assets": []},
    ]
    info = UpdateChecker._parse_github(releases)
    assert info["latestVersion"] == "1.3.0"
    assert info["updateBundle"] == "http://x/b.tar.gz"


# ---- bundle fixture server ----

NEXT_VERSION = "99.0.0"


def _make_bundle() -> bytes:
    app_js = b"console.log('new version')\n"
    version_json = json.dumps({
        "version": NEXT_VERSION,
        "checksums": {
            "app.js": hashlib.sha256(app_js).hexdigest(),
        },
    }).encode()
    buf = io.BytesIO()
    with tarfile.open(fileobj=buf, mode="w:gz") as tf:
        for name, data in (("version.json", version_json),
                           ("app.js", app_js)):
            ti = tarfile.TarInfo(name)
            ti.size = len(data)
            tf.addfile(ti, io.BytesIO(data))
    return buf.getvalue()


@pytest.fixture
def update_source(tmp_path, monkeypatch):
    monkeypatch.setenv("ROOM_TPU_DATA_DIR", str(tmp_path / "data"))
    bundle = _make_bundle()
    corrupt = {"on": False}

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            if self.path == "/release.json":
                body = json.dumps({
                    "version": NEXT_VERSION,
                    "updateBundleUrl":
                        f"http://127.0.0.1:{srv.server_address[1]}"
                        "/bundle.tar.gz",
                    "releaseUrl": "http://example/release",
                }).encode()
                self.send_response(200)
            elif self.path == "/bundle.tar.gz":
                body = bundle
                if corrupt["on"]:
                    # flip payload bytes so a checksum must fail
                    raw = bytearray(_make_bundle_with(
                        b"console.log('evil')\n"
                    ))
                    body = bytes(raw)
                self.send_response(200)
            else:
                self.send_response(404)
                body = b"{}"
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    def _make_bundle_with(app_js: bytes) -> bytes:
        version_json = json.dumps({
            "version": NEXT_VERSION,
            "checksums": {
                "app.js": hashlib.sha256(b"different").hexdigest(),
            },
        }).encode()
        buf = io.BytesIO()
        with tarfile.open(fileobj=buf, mode="w:gz") as tf:
            for name, data in (("version.json", version_json),
                               ("app.js", app_js)):
                ti = tarfile.TarInfo(name)
                ti.size = len(data)
                tf.addfile(ti, io.BytesIO(data))
        return buf.getvalue()

    srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    url = f"http://127.0.0.1:{srv.server_address[1]}/release.json"
    monkeypatch.setenv("ROOM_TPU_UPDATE_SOURCE_URL", url)
    yield {"corrupt": corrupt, "port": srv.server_address[1]}
    srv.shutdown()
    srv.server_close()


def test_check_download_stage_promote(update_source):
    ready_events = []
    checker = UpdateChecker(on_ready_update=ready_events.append)
    checker.force_check()
    assert checker.cached["latestVersion"] == NEXT_VERSION
    assert checker.diagnostics["updateSource"] == "cloud"
    assert checker.auto_status == {
        "state": "ready", "version": NEXT_VERSION,
    }
    assert ready_events == [NEXT_VERSION]
    assert get_ready_update_version() == NEXT_VERSION
    # staged content verified and present
    assert os.path.exists(
        os.path.join(updater.staging_dir(), "app.js")
    )
    # second check is a no-op re-stage (already ready)
    checker.force_check()
    assert ready_events == [NEXT_VERSION]

    version = promote_staged_update()
    assert version == NEXT_VERSION
    assert os.path.exists(os.path.join(updater.app_dir(), "app.js"))
    assert get_ready_update_version() is None  # staging gone


def test_checksum_mismatch_rejected(update_source):
    update_source["corrupt"]["on"] = True
    checker = UpdateChecker()
    checker.force_check()
    assert checker.auto_status["state"] == "error"
    assert "checksum" in checker.auto_status["error"].lower()
    assert get_ready_update_version() is None


def test_backoff_on_failures(tmp_path, monkeypatch):
    monkeypatch.setenv("ROOM_TPU_DATA_DIR", str(tmp_path))
    monkeypatch.setenv(
        "ROOM_TPU_UPDATE_SOURCE_URL", "http://127.0.0.1:1/x"
    )
    checker = UpdateChecker()
    checker.force_check()   # failure 1: no backoff yet
    assert checker.diagnostics["consecutiveFailures"] == 1
    assert checker.diagnostics["nextCheckAt"] is None
    checker.force_check()   # failure 2: 30s backoff armed
    assert checker.diagnostics["consecutiveFailures"] == 2
    assert checker.diagnostics["nextCheckAt"] > time.time()
    before = checker.diagnostics["consecutiveFailures"]
    checker.force_check()   # inside backoff: skipped
    assert checker.diagnostics["consecutiveFailures"] == before
    checker.force_check(ignore_backoff=True)  # forced through
    assert checker.diagnostics["consecutiveFailures"] == before + 1


# ---- crash rollback ----

def _write_user_app(tmp_path, version):
    target = updater.app_dir()
    os.makedirs(target, exist_ok=True)
    with open(os.path.join(target, "version.json"), "w") as f:
        json.dump({"version": version}, f)
    return target


def test_boot_health_crash_rollback(tmp_path, monkeypatch):
    monkeypatch.setenv("ROOM_TPU_DATA_DIR", str(tmp_path))
    target = _write_user_app(tmp_path, "99.0.0")
    # boot 1: arms the marker
    init_boot_health_check(grace_s=9999)
    assert os.path.exists(os.path.join(target, ".booting"))
    # each boot that finds a live marker is a crash; third strike rolls
    # the user-space update back
    init_boot_health_check(grace_s=9999)   # crash 1
    assert os.path.isdir(target)
    init_boot_health_check(grace_s=9999)   # crash 2
    assert os.path.isdir(target)
    init_boot_health_check(grace_s=9999)   # crash 3: rollback
    assert not os.path.isdir(target)


def test_boot_health_clears_marker_after_grace(tmp_path, monkeypatch):
    monkeypatch.setenv("ROOM_TPU_DATA_DIR", str(tmp_path))
    target = _write_user_app(tmp_path, "99.0.0")
    init_boot_health_check(grace_s=0.1)
    time.sleep(0.4)
    assert not os.path.exists(os.path.join(target, ".booting"))
    assert os.path.isdir(target)


def test_boot_health_cleans_stale_update(tmp_path, monkeypatch):
    monkeypatch.setenv("ROOM_TPU_DATA_DIR", str(tmp_path))
    target = _write_user_app(tmp_path, "0.0.1")  # older than current
    init_boot_health_check()
    assert not os.path.isdir(target)


# ---- restart endpoints ----

def test_restart_endpoints(tmp_path, monkeypatch, update_source):
    from room_tpu.db import Database
    from room_tpu.server.http import ApiServer
    from room_tpu.server.updater import set_restart_hook

    restarted = []
    set_restart_hook(lambda: restarted.append(True))
    try:
        db = Database(":memory:")
        srv = ApiServer(db)
        srv.start()
        try:
            def post(path):
                r = urllib.request.Request(
                    f"http://127.0.0.1:{srv.port}{path}",
                    method="POST", data=b"{}",
                    headers={"Content-Type": "application/json"},
                )
                try:
                    with urllib.request.urlopen(r, timeout=5) as resp:
                        return resp.status, json.loads(resp.read())
                except urllib.error.HTTPError as e:
                    return e.code, json.loads(e.read() or b"{}")

            # nothing staged yet
            status, out = post("/api/server/update-restart")
            assert status == 404

            checker = UpdateChecker()
            checker.force_check()
            status, out = post("/api/server/update-restart")
            assert status == 202 and out["version"] == NEXT_VERSION
            assert os.path.exists(
                os.path.join(updater.app_dir(), "app.js")
            )

            status, out = post("/api/server/restart")
            assert status == 202 and out["restarting"] is True
            time.sleep(0.4)
            assert len(restarted) >= 2
        finally:
            srv.stop()
    finally:
        set_restart_hook(None)


# ---- update status routes ----

def test_update_routes(update_source):
    from tests.test_server import req

    from room_tpu.db import Database
    from room_tpu.server.http import ApiServer

    updater.reset_update_checker()
    db = Database(":memory:")
    srv = ApiServer(db)
    srv.start()
    try:
        status, out = req(srv, "GET", "/api/update")
        assert status == 200
        assert out["data"]["currentVersion"] == __version__
        status, out = req(srv, "POST", "/api/update/check", {})
        assert status == 200
        assert out["data"]["updateInfo"]["latestVersion"] == NEXT_VERSION
        assert out["data"]["autoUpdate"]["state"] == "ready"
    finally:
        srv.stop()
        updater.reset_update_checker()


# ---- MCP auto-registration ----

def test_register_mcp_globally(tmp_path):
    from room_tpu.mcp.autoregister import register_mcp_globally

    home = tmp_path / "home"
    (home / ".claude").mkdir(parents=True)
    (home / ".claude.json").write_text(
        json.dumps({"mcpServers": {"other": {"command": "x"}}})
    )
    (home / ".claude" / "settings.json").write_text(
        json.dumps({"permissions": {"allow": ["Bash(ls:*)"]}})
    )
    (home / ".cursor").mkdir()
    (home / ".cursor" / "mcp.json").write_text(
        json.dumps({"mcpServers": {}})
    )
    (home / ".codeium").mkdir()  # windsurf dir but NO config file
    (home / ".codex").mkdir()
    (home / ".codex" / "config.toml").write_text(
        "[mcp_servers.room_tpu]\ncommand = 'stale'\n\n"
        "[mcp_servers.other]\ncommand = 'keep'\n"
    )
    # windsurf NOT installed: no config dir

    out = register_mcp_globally("/data/db.sqlite", home=str(home))
    assert out["claude-code"] is True
    assert out["claude-code-permissions"] is True
    assert out["cursor"] is True
    assert out["codex"] is True
    assert out["windsurf"] is False  # absent config untouched
    assert not (home / ".codeium" / "windsurf").exists()

    cc = json.loads((home / ".claude.json").read_text())
    assert "room_tpu" in cc["mcpServers"]
    assert cc["mcpServers"]["other"] == {"command": "x"}  # preserved
    assert cc["mcpServers"]["room_tpu"]["env"]["ROOM_TPU_DB_PATH"] == \
        "/data/db.sqlite"

    perms = json.loads(
        (home / ".claude" / "settings.json").read_text()
    )["permissions"]["allow"]
    assert "mcp__room_tpu__*" in perms and "Bash(ls:*)" in perms

    cursor = json.loads((home / ".cursor" / "mcp.json").read_text())
    assert "room_tpu" in cursor["mcpServers"]

    toml = (home / ".codex" / "config.toml").read_text()
    assert "command = 'stale'" not in toml  # old section replaced
    assert "[mcp_servers.other]" in toml    # others preserved
    assert "[mcp_servers.room_tpu]" in toml

    # idempotent permissions patch
    out2 = register_mcp_globally("/data/db.sqlite", home=str(home))
    assert out2["claude-code-permissions"] is False
    perms2 = json.loads(
        (home / ".claude" / "settings.json").read_text()
    )["permissions"]["allow"]
    assert perms2.count("mcp__room_tpu__*") == 1


def test_register_mcp_never_rewrites_unparseable_config(tmp_path):
    """An unparseable config (possibly mid-write by the client) must be
    left untouched — rewriting would destroy the user's whole file."""
    from room_tpu.mcp.autoregister import patch_mcp_config

    cfg = tmp_path / "broken.json"
    cfg.write_text("{truncated mid-write")
    assert patch_mcp_config(str(cfg), {"command": "x"}) is False
    assert cfg.read_text() == "{truncated mid-write"
    # non-dict JSON likewise untouched
    cfg.write_text("[1, 2, 3]")
    assert patch_mcp_config(str(cfg), {"command": "x"}) is False
    assert cfg.read_text() == "[1, 2, 3]"


def test_scratch_stage_never_looks_ready(tmp_path, monkeypatch):
    """A crash mid-download/mid-verify leaves only the .tmp scratch
    tree, which get_ready_update_version must ignore."""
    monkeypatch.setenv("ROOM_TPU_DATA_DIR", str(tmp_path))
    scratch = updater.staging_dir() + ".tmp"
    os.makedirs(scratch, exist_ok=True)
    with open(os.path.join(scratch, "version.json"), "w") as f:
        json.dump({"version": "99.0.0"}, f)
    assert get_ready_update_version() is None


# ---- CLI update / uninstall ----

def test_cli_update_command(update_source, capsys):
    from room_tpu.cli.main import main

    updater.reset_update_checker()
    assert main(["update"]) == 0
    out = capsys.readouterr().out
    assert NEXT_VERSION in out and "staged" in out
    assert main(["update", "--apply"]) == 0
    out = capsys.readouterr().out
    assert "promoted" in out
    assert os.path.exists(os.path.join(updater.app_dir(), "app.js"))


def test_cli_uninstall_requires_confirmation(tmp_path, monkeypatch,
                                             capsys):
    from room_tpu.cli.main import main

    monkeypatch.setenv("ROOM_TPU_DATA_DIR", str(tmp_path / "d"))
    os.makedirs(tmp_path / "d", exist_ok=True)
    (tmp_path / "d" / "room.db").write_text("x")
    assert main(["uninstall"]) == 2       # refuses without --yes
    assert (tmp_path / "d").exists()
    assert main(["uninstall", "--yes"]) == 0
    assert not (tmp_path / "d").exists()


# ---- release pipeline: the bundle CI actually builds round-trips ----

def test_built_bundle_round_trips_check_stage_promote(
    tmp_path, monkeypatch
):
    """scripts/make_bundle.py output (the artifact release.yml attaches
    to a tag) must round-trip through the updater's own
    check -> download -> checksum-verify -> stage -> promote path
    (VERDICT r2 #9: nothing in-tree produced the bundle the updater
    consumes)."""
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from scripts.make_bundle import build_bundle, sha256_file

    monkeypatch.setenv("ROOM_TPU_DATA_DIR", str(tmp_path / "data"))
    version = "99.1.0"
    bundle_path = build_bundle(version, str(tmp_path / "dist"))
    assert os.path.basename(bundle_path) == \
        f"room-tpu-update-{version}.tar.gz"
    with open(bundle_path, "rb") as f:
        bundle_bytes = f.read()

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            if self.path == "/release.json":
                body = json.dumps({
                    "version": version,
                    "updateBundleUrl":
                        f"http://127.0.0.1:{srv.server_address[1]}"
                        "/bundle.tar.gz",
                    "releaseUrl": "http://example/release",
                }).encode()
            else:
                body = bundle_bytes
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        monkeypatch.setenv(
            "ROOM_TPU_UPDATE_SOURCE_URL",
            f"http://127.0.0.1:{srv.server_address[1]}/release.json",
        )
        checker = UpdateChecker()
        checker.force_check()
        assert checker.auto_status == {
            "state": "ready", "version": version,
        }, checker.auto_status
        assert get_ready_update_version() == version

        assert promote_staged_update() == version
        app = updater.app_dir()
        # the promoted tree is the real package: version manifest +
        # every checksummed file present and intact
        with open(os.path.join(app, "version.json")) as f:
            manifest = json.load(f)
        assert manifest["version"] == version
        assert "room_tpu/serving/engine.py" in manifest["checksums"]
        assert "ui/panels.js" in manifest["checksums"]
        assert "bench.py" in manifest["checksums"]
        for rel, want in manifest["checksums"].items():
            assert sha256_file(os.path.join(app, rel)) == want, rel
    finally:
        srv.shutdown()
        srv.server_close()


def test_cli_status_against_live_server(tmp_path, monkeypatch, capsys):
    """`room-tpu status` reads api.port/api.token from the data dir and
    prints the live /api/status payload (reference: cli status)."""
    monkeypatch.setenv("ROOM_TPU_DATA_DIR", str(tmp_path))
    from room_tpu.db import Database
    from room_tpu.server.http import ApiServer
    from room_tpu.cli.main import main

    db = Database(":memory:")
    srv = ApiServer(db)
    srv.start()
    try:
        assert main(["status"]) == 0
        out = capsys.readouterr().out
        data = json.loads(out)
        assert {"version", "platform", "devices"} <= set(data)
    finally:
        srv.stop()
        db.close()


def test_cli_status_unreachable(tmp_path, monkeypatch, capsys):
    monkeypatch.setenv("ROOM_TPU_DATA_DIR", str(tmp_path / "empty"))
    from room_tpu.cli.main import main

    assert main(["status"]) == 1
    assert "not reachable" in capsys.readouterr().err
