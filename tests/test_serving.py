"""Serving-engine tests: paged KV correctness vs dense cache, page table
accounting, continuous batching, tool-call parking + resume, tokenizer."""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from room_tpu.models import qwen3, tiny_moe
from room_tpu.serving import (
    ByteTokenizer, PageTable, SamplingParams, ServingEngine,
    extract_tool_call, init_page_cache, make_paged_kv_hook, render_chat,
    sample_batched,
)


# ---- paged KV vs dense cache ----

def test_paged_matches_dense_cache():
    cfg = tiny_moe()
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    b, s = 2, 6
    tokens = jax.random.randint(jax.random.PRNGKey(1), (b, s), 0,
                                cfg.vocab_size)

    dense = qwen3.init_kv_cache(cfg, b, 32)
    want, _ = qwen3.forward(params, cfg, tokens, None, dense)

    page_size = 4
    cache = init_page_cache(cfg, n_pages=16, page_size=page_size)
    # seq 0 gets pages [1,2], seq 1 gets [3,4] (page 0 = scratch)
    tables = jnp.array([[1, 2, 0, 0], [3, 4, 0, 0]], jnp.int32)
    lengths = jnp.zeros((b,), jnp.int32)
    hook = make_paged_kv_hook(tables, lengths, page_size)
    positions = jnp.broadcast_to(jnp.arange(s)[None], (b, s))
    got, cache = qwen3.forward(
        params, cfg, tokens, positions, cache, kv_hook=hook
    )
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

    # now decode one token against the filled pages and compare
    dense2, _ = qwen3.forward(params, cfg, tokens, None, dense)
    next_tok = jnp.array([7, 9], jnp.int32)
    dcache = qwen3.init_kv_cache(cfg, b, 32)
    _, dcache = qwen3.forward(params, cfg, tokens, None, dcache)
    want_step, _ = qwen3.decode_step(params, cfg, next_tok, dcache)

    hook2 = make_paged_kv_hook(
        tables, jnp.full((b,), s, jnp.int32), page_size
    )
    got_step, _ = qwen3.forward(
        params, cfg, next_tok[:, None],
        jnp.full((b, 1), s, jnp.int32), cache, kv_hook=hook2,
    )
    np.testing.assert_allclose(
        got_step[:, 0], want_step, rtol=2e-4, atol=2e-4
    )


def test_bounded_gather_matches_full_capacity():
    """active_pages bounds the XLA gather to the batch's reach; results
    must be identical to the full-capacity gather (VERDICT r2 #2:
    prefill cost scales with session length, not table capacity)."""
    cfg = tiny_moe()
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    b, s, page_size = 2, 6, 4
    tokens = jax.random.randint(jax.random.PRNGKey(1), (b, s), 0,
                                cfg.vocab_size)
    # wide table (16 slots) but sequences only ever reach 2 pages + the
    # chunk: active_pages=4 must cover prefix+chunk exactly
    tables = jnp.zeros((b, 16), jnp.int32)
    tables = tables.at[0, :4].set(jnp.array([1, 2, 5, 6]))
    tables = tables.at[1, :4].set(jnp.array([3, 4, 7, 8]))

    def run(active_pages):
        cache = init_page_cache(cfg, n_pages=16, page_size=page_size)
        lengths = jnp.zeros((b,), jnp.int32)
        hook = make_paged_kv_hook(tables, lengths, page_size)
        positions = jnp.broadcast_to(jnp.arange(s)[None], (b, s))
        _, cache = qwen3.forward(
            params, cfg, tokens, positions, cache, kv_hook=hook
        )
        # continuation chunk at length s: takes the gather path
        hook2 = make_paged_kv_hook(
            tables, jnp.full((b,), s, jnp.int32), page_size,
            active_pages=active_pages,
        )
        cont = jax.random.randint(jax.random.PRNGKey(2), (b, 3), 0,
                                  cfg.vocab_size)
        pos2 = s + jnp.broadcast_to(jnp.arange(3)[None], (b, 3))
        out, _ = qwen3.forward(
            params, cfg, cont, pos2, cache, kv_hook=hook2
        )
        return out

    np.testing.assert_allclose(run(None), run(4), rtol=1e-5, atol=1e-5)


def test_pages_bucket_arithmetic():
    eng = ServingEngine.__new__(ServingEngine)
    eng.page_size = 32
    eng.max_pages_per_seq = 64
    assert eng._pages_bucket(1) == 1
    assert eng._pages_bucket(32) == 1
    assert eng._pages_bucket(33) == 2
    assert eng._pages_bucket(200) == 8       # 7 pages -> pow2
    # at/beyond capacity: None (no slicing, full table)
    assert eng._pages_bucket(64 * 32) is None
    assert eng._pages_bucket(10 ** 6) is None


def test_engine_concurrency_stress(engine_setup):
    """Concurrency contract (VERDICT r2 #4): client threads submitting
    and releasing against a running serve_forever engine never corrupt
    page accounting — all mutation lands on the engine thread; releases
    route through the command queue. Closes to zero leaked pages."""
    import threading

    cfg, params = engine_setup
    eng = ServingEngine(cfg, params, max_batch=4, page_size=8,
                        n_pages=96)
    stop = threading.Event()
    loop = threading.Thread(
        target=eng.serve_forever, args=(stop,), daemon=True
    )
    loop.start()
    errors: list[Exception] = []

    def client(tid: int) -> None:
        try:
            rng = np.random.default_rng(tid)
            for r in range(5):
                sid = f"stress-{tid}-{r}"
                toks = rng.integers(
                    0, cfg.vocab_size, size=5
                ).tolist()
                turn = eng.submit(
                    toks, session_id=sid,
                    sampling=SamplingParams(
                        max_new_tokens=3, temperature=0.0
                    ),
                )
                assert turn.done.wait(300), "turn timed out"
                eng.release_session(sid)
        except Exception as e:  # pragma: no cover - failure path
            errors.append(e)

    threads = [
        threading.Thread(target=client, args=(i,)) for i in range(6)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(360)
        assert not t.is_alive(), "client thread hung"
    stop.set()
    loop.join(60)
    assert not loop.is_alive()
    assert not errors, errors
    # every session was released: the pool must close to full (page 0
    # stays reserved as the engine's scratch page)
    assert eng.page_table.free_pages == eng.page_table.n_pages - 1
    assert not eng.sessions
    assert eng.stats()["turns_completed"] == 30


def test_page_table_accounting():
    pt = PageTable(n_pages=8, page_size=4)
    pages = pt.ensure_capacity("a", 10)  # 3 pages
    assert len(pages) == 3 and pt.free_pages == 5
    pages2 = pt.ensure_capacity("a", 12)  # still 3 pages
    assert pages2 == pages
    pt.ensure_capacity("b", 17)          # 5 pages
    assert pt.free_pages == 0
    with pytest.raises(MemoryError):
        pt.ensure_capacity("c", 1)
    assert pt.release("a") == 3
    assert pt.free_pages == 3


# ---- engine ----

@pytest.fixture(scope="module")
def engine_setup():
    cfg = tiny_moe()
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def make_engine(cfg, params, **kw):
    kw.setdefault("max_batch", 4)
    kw.setdefault("page_size", 8)
    kw.setdefault("n_pages", 64)
    return ServingEngine(cfg, params, **kw)


def test_engine_single_turn_greedy(engine_setup):
    cfg, params = engine_setup
    eng = make_engine(cfg, params)
    turn = eng.submit(
        [1, 2, 3],
        sampling=SamplingParams(temperature=0.0, max_new_tokens=8),
    )
    eng.run_until_idle()
    assert turn.finish_reason in ("stop", "length")
    assert 1 <= len(turn.new_tokens) <= 8
    st = eng.stats()
    assert st["turns_completed"] == 1


def test_penalties_prevent_repeats(engine_setup):
    """A huge frequency penalty makes every generated token of a request
    unique (each sampled token's count immediately knocks it out of the
    greedy argmax) — proving the count array resets at admission, rides
    the decode scan, and reaches the logits before sampling."""
    cfg, params = engine_setup
    eng = make_engine(cfg, params)
    sp = SamplingParams(temperature=0.0, max_new_tokens=6,
                        frequency_penalty=1e9)
    t1 = eng.submit([1, 2, 3], sampling=sp)
    eng.run_until_idle()
    body = t1.new_tokens[:-1] if t1.finish_reason == "stop" \
        else t1.new_tokens
    assert len(set(body)) == len(body), body

    # second request on a fresh session: counts must reset (its first
    # token may repeat tokens from request one)
    t2 = eng.submit([1, 2, 3], sampling=sp)
    eng.run_until_idle()
    assert t2.new_tokens[0] == t1.new_tokens[0]

    # unpenalized turns are unaffected by batchmates with penalties
    eng2 = make_engine(cfg, params)
    plain_sp = SamplingParams(temperature=0.0, max_new_tokens=6)
    alone = eng2.submit([9, 8, 7], sampling=plain_sp)
    eng2.run_until_idle()
    eng3 = make_engine(cfg, params)
    pair = [eng3.submit([9, 8, 7], sampling=plain_sp),
            eng3.submit([1, 2, 3], sampling=sp)]
    eng3.run_until_idle()
    assert pair[0].new_tokens == alone.new_tokens


def test_apply_penalties_math():
    from room_tpu.serving.sampler import apply_penalties

    logits = jnp.zeros((2, 5), jnp.float32)
    counts = jnp.array([[0, 1, 3, 0, 0], [0, 0, 0, 0, 0]], jnp.int32)
    out = apply_penalties(
        logits, counts,
        jnp.array([0.5, 0.5]), jnp.array([0.25, 0.25]),
    )
    np.testing.assert_allclose(
        out[0], [0.0, -0.75, -1.25, 0.0, 0.0], atol=1e-6
    )
    np.testing.assert_allclose(out[1], np.zeros(5), atol=1e-6)


def test_engine_batched_turns_match_sequential(engine_setup):
    """Turns decoded together must equal turns decoded alone (batching
    must not change results) — greedy for determinism."""
    cfg, params = engine_setup
    sp = SamplingParams(temperature=0.0, max_new_tokens=6)

    eng1 = make_engine(cfg, params)
    prompts = [[1, 2, 3], [9, 8, 7, 6], [42]]
    alone = []
    for p in prompts:
        t = eng1.submit(p, sampling=sp)
        eng1.run_until_idle()
        alone.append(t.new_tokens)

    eng2 = make_engine(cfg, params)
    turns = [eng2.submit(p, sampling=sp) for p in prompts]
    eng2.run_until_idle()
    together = [t.new_tokens for t in turns]
    assert alone == together


def test_engine_on_mesh_matches_single_device(engine_setup):
    """The serving engine on an 8-device dp/ep/tp mesh (sharded params +
    sharded page pool + dp-sharded decode batch) generates the same
    tokens as the unsharded engine — multi-chip serving is a placement
    detail, not a numerics change."""
    from room_tpu.parallel import (
        MeshSpec, decoder_param_specs, make_mesh, shard_pytree,
    )

    cfg, params = engine_setup
    sp = SamplingParams(temperature=0.0, max_new_tokens=6)
    prompts = [[1, 2, 3], [9, 8, 7, 6], [42], [5, 5]]

    eng1 = make_engine(cfg, params)
    base = [eng1.submit(p, sampling=sp) for p in prompts]
    eng1.run_until_idle()

    mesh = make_mesh(MeshSpec(dp=2, ep=2, tp=2))
    sharded = shard_pytree(params, decoder_param_specs(cfg), mesh)
    eng2 = make_engine(cfg, sharded, mesh=mesh)
    assert eng2._dp_size == 2  # max_batch=4 splits across dp
    got = [eng2.submit(p, sampling=sp) for p in prompts]
    eng2.run_until_idle()

    assert [t.new_tokens for t in base] == [t.new_tokens for t in got]
    # the pool actually lives sharded on the mesh
    shard_mesh = eng2.cache["k_pages"].sharding.mesh
    assert shard_mesh.shape == mesh.shape


def test_hetero_disjoint_submeshes(engine_setup):
    """Hetero-swarm placement (BASELINE config #5): two engines on
    disjoint device windows of one pod — params and KV pools must land
    on non-overlapping device sets, and each engine's tokens must match
    its unsharded twin."""
    from room_tpu.parallel import (
        MeshSpec, decoder_param_specs, make_submesh, parse_mesh_spec,
        shard_pytree,
    )

    cfg, params = engine_setup
    sp = SamplingParams(temperature=0.0, max_new_tokens=5)
    prompts = [[1, 2, 3], [9, 8, 7, 6]]

    spec_a, start_a = parse_mesh_spec("1,2,2@0")
    spec_b, start_b = parse_mesh_spec("1,1,4@4")
    assert (spec_a, start_a) == (MeshSpec(1, 2, 2), 0)
    assert (spec_b, start_b) == (MeshSpec(1, 1, 4), 4)
    mesh_a = make_submesh(spec_a, start_a)
    mesh_b = make_submesh(spec_b, start_b)
    devs_a = {d.id for d in mesh_a.devices.flat}
    devs_b = {d.id for d in mesh_b.devices.flat}
    assert not (devs_a & devs_b)

    eng0 = make_engine(cfg, params)
    want = [eng0.submit(p, sampling=sp) for p in prompts]
    eng0.run_until_idle()
    want = [t.new_tokens for t in want]

    for mesh, devs in ((mesh_a, devs_a), (mesh_b, devs_b)):
        sharded = shard_pytree(params, decoder_param_specs(cfg), mesh)
        eng = make_engine(cfg, sharded, mesh=mesh)
        got = [eng.submit(p, sampling=sp) for p in prompts]
        eng.run_until_idle()
        assert [t.new_tokens for t in got] == want
        pool_devs = {
            d.id for d in eng.cache["k_pages"].sharding.device_set
        }
        assert pool_devs <= devs

    # a window past the device count must refuse, not wrap
    with pytest.raises(ValueError):
        make_submesh(MeshSpec(1, 1, 4), 6)


def test_hetero_two_models_concurrent_turns(engine_setup):
    """VERDICT r4 #4: a 72b-shape queen (tiny-dense: qkv-bias, no
    qk-norm) and a 30b-shape worker (tiny-moe) serve CONCURRENT turns
    on disjoint submeshes of one pod, each token-identical to its own
    unsharded engine."""
    import threading

    import jax

    from room_tpu.models import qwen3
    from room_tpu.models.config import tiny_dense
    from room_tpu.parallel import (
        MeshSpec, decoder_param_specs, make_submesh, shard_pytree,
    )

    worker_cfg, worker_params = engine_setup
    queen_cfg = tiny_dense()
    queen_params = qwen3.init_params(queen_cfg, jax.random.PRNGKey(7))
    sp = SamplingParams(temperature=0.0, max_new_tokens=5)
    prompts = [[1, 2, 3], [9, 8, 7, 6]]

    sub_a = make_submesh(MeshSpec(1, 1, 4), 0)
    sub_b = make_submesh(MeshSpec(1, 1, 4), 4)

    def serve(cfg_, params_, mesh, out, key):
        p = shard_pytree(params_, decoder_param_specs(cfg_), mesh) \
            if mesh is not None else params_
        eng = make_engine(cfg_, p, mesh=mesh)
        turns = [eng.submit(pp, sampling=sp) for pp in prompts]
        eng.run_until_idle()
        out[key] = [t.new_tokens for t in turns]

    want: dict = {}
    serve(queen_cfg, queen_params, None, want, "queen")
    serve(worker_cfg, worker_params, None, want, "worker")
    assert want["queen"] != want["worker"]  # non-vacuous check

    got: dict = {}
    ts = [
        threading.Thread(target=serve, args=(
            queen_cfg, queen_params, sub_a, got, "queen")),
        threading.Thread(target=serve, args=(
            worker_cfg, worker_params, sub_b, got, "worker")),
    ]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert got["queen"] == want["queen"]
    assert got["worker"] == want["worker"]


def test_mesh_env_per_model_override(monkeypatch):
    """ROOM_TPU_MESH_<SLUG> wins over the global ROOM_TPU_MESH, slugged
    from the model name (dots/dashes -> underscores)."""
    from room_tpu.providers.tpu import mesh_env_for

    import os

    monkeypatch.delenv("ROOM_TPU_MESH", raising=False)
    for key in [k for k in os.environ if k.startswith("ROOM_TPU_MESH_")]:
        monkeypatch.delenv(key, raising=False)
    assert mesh_env_for("tiny-moe") is None
    monkeypatch.setenv("ROOM_TPU_MESH", "2,2,2")
    assert mesh_env_for("tiny-moe") == "2,2,2"
    monkeypatch.setenv("ROOM_TPU_MESH_QWEN2_5_72B", "1,1,4@0")
    assert mesh_env_for("qwen2.5-72b") == "1,1,4@0"
    assert mesh_env_for("qwen3-coder-30b") == "2,2,2"


def test_eviction_oversubscribed_pool(engine_setup):
    """12 sessions against a pool that holds ~3: LRU eviction must keep
    admission moving and every turn must complete (no MemoryError
    turns)."""
    cfg, params = engine_setup
    # 17 pages * page_size 4 = 68 tokens; each session buckets to 16
    # tokens (4 pages) -> ~4 resident; 12 sessions ~= 3-4x oversubscribed
    eng = make_engine(cfg, params, max_batch=2, page_size=4, n_pages=17)
    sp = SamplingParams(temperature=0.0, max_new_tokens=4)
    turns = [
        eng.submit([i + 1, i + 2, i + 3], session_id=f"s{i}", sampling=sp)
        for i in range(12)
    ]
    eng.run_until_idle()
    assert all(t.finish_reason in ("stop", "length") for t in turns), [
        (t.finish_reason, t.error) for t in turns
    ]
    assert eng.stats()["evictions"] > 0


def test_evicted_session_resumes_identically(engine_setup):
    """A session whose pages were evicted re-prefills from its host-side
    history on resume and generates exactly the tokens it would have
    with resident KV."""
    cfg, params = engine_setup
    sp = SamplingParams(temperature=0.0, max_new_tokens=5)

    def run(n_pages):
        eng = make_engine(
            cfg, params, max_batch=1, page_size=4, n_pages=n_pages
        )
        t1 = eng.submit([1, 2, 3], session_id="keep", sampling=sp)
        eng.run_until_idle()
        # fill the pool with other sessions so "keep" gets evicted in
        # the small-pool engine (and stays resident in the big one)
        for i in range(3):
            eng.submit([50 + i], session_id=f"fill{i}", sampling=sp)
            eng.run_until_idle()
        t2 = eng.submit([7, 8], session_id="keep", sampling=sp)
        eng.run_until_idle()
        assert t1.finish_reason in ("stop", "length")
        assert t2.finish_reason in ("stop", "length"), t2.error
        return t1.new_tokens, t2.new_tokens, eng.stats()["evictions"]

    small = run(n_pages=9)    # scratch + 8 usable -> 2 resident sessions
    big = run(n_pages=64)     # everything stays resident
    assert small[2] > 0 and big[2] == 0  # eviction happened only in small
    assert small[0] == big[0]
    assert small[1] == big[1]


def test_engine_more_turns_than_slots(engine_setup):
    cfg, params = engine_setup
    eng = make_engine(cfg, params, max_batch=2)
    sp = SamplingParams(temperature=0.0, max_new_tokens=4)
    turns = [eng.submit([i + 1, i + 2], sampling=sp) for i in range(5)]
    eng.run_until_idle()
    assert all(t.finish_reason in ("stop", "length") for t in turns)
    assert eng.stats()["turns_completed"] == 5


def test_engine_session_resume_matches_uninterrupted(engine_setup):
    """Park/resume correctness: decoding [a] then resuming with [b] must
    equal decoding with the dense-cache model over the same token
    stream."""
    cfg, params = engine_setup
    sp = SamplingParams(temperature=0.0, max_new_tokens=3)

    eng = make_engine(cfg, params)
    t1 = eng.submit([5, 6, 7], session_id="sess", sampling=sp)
    eng.run_until_idle()
    assert t1.finish_reason == "length"
    t2 = eng.submit([11, 12], session_id="sess", sampling=sp)
    eng.run_until_idle()

    # uninterrupted reference on the dense cache path
    stream = [5, 6, 7] + t1.new_tokens + [11, 12]
    cache = qwen3.init_kv_cache(cfg, 1, 64)
    logits, cache = qwen3.forward(
        params, cfg, jnp.asarray([stream], jnp.int32), None, cache
    )
    toks = []
    tok = jnp.argmax(logits[:, -1], -1)
    for _ in range(3):
        toks.append(int(tok[0]))
        lg, cache = qwen3.decode_step(params, cfg, tok, cache)
        tok = jnp.argmax(lg, -1)
    assert t2.new_tokens == toks


def test_engine_tool_call_parks_session(engine_setup):
    cfg, params = engine_setup
    tok = ByteTokenizer()
    eng = make_engine(cfg, params)
    # craft a prompt whose continuation we control by seeding new_tokens:
    # simulate by submitting and letting it hit max tokens, then verify
    # parked resume keeps pages
    sp = SamplingParams(temperature=0.0, max_new_tokens=2)
    t = eng.submit([1, 2], session_id="park-me", sampling=sp)
    eng.run_until_idle()
    pages_before = eng.page_table.pages_of("park-me")
    assert pages_before  # retained after turn end
    eng.release_session("park-me")
    assert eng.page_table.pages_of("park-me") == []


def test_engine_rejects_oversized_prompt(engine_setup):
    cfg, params = engine_setup
    eng = make_engine(cfg, params, n_pages=16, page_size=8,
                      max_seq_len=64)
    t = eng.submit(list(range(100)),
                   sampling=SamplingParams(max_new_tokens=4))
    eng.run_until_idle()
    assert t.finish_reason == "error"
    assert "exceed" in t.error or "too long" in t.error


def test_sample_batched_greedy_rows():
    logits = jnp.array([[0.0, 5.0, 1.0], [3.0, 0.0, 0.1]])
    toks = sample_batched(
        logits, jax.random.PRNGKey(0),
        jnp.array([0.0, 0.0]), jnp.array([1.0, 1.0]),
        jnp.array([0, 0], dtype=jnp.int32),
    )
    assert toks.tolist() == [1, 0]


def test_sample_batched_per_row_top_k():
    # row 0: top_k=0 (full vocab) — must still be able to sample any
    # token even when batched with a narrow top_k row. Make the
    # non-argmax tokens dominate collectively: near-uniform logits.
    logits = jnp.array([
        [1.0, 1.01, 1.0, 1.0, 1.0, 1.0, 1.0, 1.0],
        [9.0, 0.0, 0.0, 0.0, 0.0, 0.0, 0.0, 0.0],
    ])
    top_k = jnp.array([0, 1], dtype=jnp.int32)
    seen0 = set()
    for seed in range(64):
        toks = sample_batched(
            logits, jax.random.PRNGKey(seed),
            jnp.array([1.0, 1.0]), jnp.array([1.0, 1.0]), top_k,
        )
        seen0.add(int(toks[0]))
        assert int(toks[1]) == 0  # top_k=1 row is pinned to argmax
    # full-vocab row reached tokens outside any widened top-k window
    assert len(seen0) > 4


# ---- tokenizer + chat template ----

def test_byte_tokenizer_roundtrip():
    tok = ByteTokenizer()
    text = "hello <|im_start|>user\nhi<|im_end|> <tool_call>{}</tool_call>"
    ids = tok.encode(text)
    assert tok.decode(ids) == text
    assert ByteTokenizer.IM_START in ids and ByteTokenizer.TOOL_END in ids


def test_render_chat_and_tool_extraction():
    msgs = [
        {"role": "system", "content": "be brief"},
        {"role": "user", "content": "list files"},
    ]
    tools = [{"name": "ls", "parameters": {}}]
    text = render_chat(msgs, tools)
    assert text.endswith("<|im_start|>assistant\n")
    assert '"name": "ls"' in text
    assert "# Tools" in text and "<tools>" in text  # Qwen3 template shape

    call = extract_tool_call(
        'thinking... <tool_call>{"name": "ls", "arguments": {"d": "."}}'
        "</tool_call> done"
    )
    assert call == {"name": "ls", "arguments": {"d": "."}}
    assert extract_tool_call("no call here") is None
    assert extract_tool_call("<tool_call>not json</tool_call>") is None


def test_freed_slot_does_not_corrupt_reallocated_pages(engine_setup):
    """A finished turn's slot must stop writing KV through its old block
    table once the pages are reallocated (regression: stale slot tables)."""
    cfg, params = engine_setup
    sp = SamplingParams(temperature=0.0, max_new_tokens=4)

    eng = make_engine(cfg, params, max_batch=2)
    a = eng.submit([3, 1, 4, 1, 5], session_id="a", sampling=sp)
    eng.run_until_idle()
    eng.release_session("a")           # a's pages return to the pool

    # b likely reuses a's pages; c keeps the engine decoding afterwards
    b = eng.submit([2, 7, 1, 8], session_id="b", sampling=sp)
    c = eng.submit([1, 6, 1, 8], session_id="c",
                   sampling=SamplingParams(temperature=0.0,
                                           max_new_tokens=8))
    eng.run_until_idle()

    # reference: same token streams on a fresh engine
    eng2 = make_engine(cfg, params, max_batch=2)
    b2 = eng2.submit([2, 7, 1, 8], session_id="b", sampling=sp)
    c2 = eng2.submit([1, 6, 1, 8], session_id="c",
                     sampling=SamplingParams(temperature=0.0,
                                             max_new_tokens=8))
    eng2.run_until_idle()
    assert b.new_tokens == b2.new_tokens
    assert c.new_tokens == c2.new_tokens


def test_release_active_session_is_deferred(engine_setup):
    cfg, params = engine_setup
    eng = make_engine(cfg, params)
    t = eng.submit([1, 2, 3], session_id="live",
                   sampling=SamplingParams(temperature=0.0,
                                           max_new_tokens=6))
    eng._admit()                        # session now active in a slot
    eng.release_session("live")        # must defer, not free live pages
    assert eng.page_table.pages_of("live")
    eng.run_until_idle()
    assert eng.page_table.pages_of("live") == []
    assert t.finish_reason in ("stop", "length")


def test_deferred_release_consumed_atomically_with_finish(engine_setup):
    """lockmap regression (lock-guarded-write on _deferred_release):
    _finish_turn and the admission sweep used to check-then-discard
    the deferral set WITHOUT the engine lock, racing the cross-thread
    add in release_session (under the lock). Both consumers now take
    the lock, so a deferral is consumed exactly once: the session is
    fully released at finish, nothing lingers in the deferral set, and
    a release landing from another thread mid-stream still converges
    to a released session."""
    cfg, params = engine_setup
    eng = make_engine(cfg, params)
    t = eng.submit([1, 2, 3], session_id="live",
                   sampling=SamplingParams(temperature=0.0,
                                           max_new_tokens=6))
    eng._admit()
    releaser = threading.Thread(
        target=eng.release_session, args=("live",))
    releaser.start()
    releaser.join()
    assert "live" in eng._deferred_release
    eng.run_until_idle()
    assert t.finish_reason in ("stop", "length")
    assert eng._deferred_release == set()
    assert eng.page_table.pages_of("live") == []
    assert "live" not in eng.sessions


def test_resume_near_capacity_rejected_cleanly(engine_setup):
    cfg, params = engine_setup
    eng = make_engine(cfg, params, n_pages=32, page_size=8,
                      max_seq_len=32)
    sp = SamplingParams(temperature=0.0, max_new_tokens=2)
    t1 = eng.submit(list(range(1, 25)), session_id="s", sampling=sp)
    eng.run_until_idle()
    assert t1.finish_reason in ("stop", "length")
    # resume would pad past the block table; engine must reject, not crash
    t2 = eng.submit([1, 2, 3, 4], session_id="s", sampling=sp)
    eng.run_until_idle()
    assert t2.finish_reason == "error"
    assert "capacity" in t2.error or "exceed" in t2.error


def test_prefill_bucket_clamped_to_capacity(engine_setup):
    """A prompt whose bucket would exceed page capacity gets a clamped
    page-aligned prefill instead of a rejection (regression)."""
    cfg, params = engine_setup
    # capacity = 8 pages x 8 = 64 usable; a 40-token prompt buckets to 64
    eng = make_engine(cfg, params, n_pages=16, page_size=8,
                      max_seq_len=64)
    t = eng.submit(list(range(1, 41)),
                   sampling=SamplingParams(temperature=0.0,
                                           max_new_tokens=4))
    eng.run_until_idle()
    assert t.finish_reason in ("stop", "length"), t.error


def test_chunked_decode_matches_single_step(engine_setup, monkeypatch):
    """ROOM_TPU_DECODE_CHUNK=4 must produce the same greedy stream as
    chunk=1, including turns that stop mid-chunk."""
    cfg, params = engine_setup
    sp = SamplingParams(temperature=0.0, max_new_tokens=7)  # 7 % 4 != 0

    monkeypatch.setenv("ROOM_TPU_DECODE_CHUNK", "1")
    e1 = make_engine(cfg, params)
    a = e1.submit([4, 8, 15], session_id="s", sampling=sp)
    e1.run_until_idle()
    # resume after a mid-chunk-style stop: continuation must align
    a2 = e1.submit([16, 23], session_id="s", sampling=sp)
    e1.run_until_idle()

    monkeypatch.setenv("ROOM_TPU_DECODE_CHUNK", "4")
    e2 = make_engine(cfg, params)
    b = e2.submit([4, 8, 15], session_id="s", sampling=sp)
    e2.run_until_idle()
    b2 = e2.submit([16, 23], session_id="s", sampling=sp)
    e2.run_until_idle()

    assert a.new_tokens == b.new_tokens
    assert a2.new_tokens == b2.new_tokens
    # chunked run used ~1/4 the host round-trips
    assert e2.stats()["decode_steps"] < e1.stats()["decode_steps"]


def test_chunked_decode_at_capacity_edge(engine_setup, monkeypatch):
    """A turn whose budget ends near max_seq_len must complete under a
    large decode chunk (regression: capacity over-ensure crash)."""
    cfg, params = engine_setup
    monkeypatch.setenv("ROOM_TPU_DECODE_CHUNK", "16")
    # capacity 64; prompt 56 + 8 new tokens == 64 exactly
    eng = make_engine(cfg, params, n_pages=32, page_size=16,
                      max_seq_len=64)
    t = eng.submit(list(range(1, 57)),
                   sampling=SamplingParams(temperature=0.0,
                                           max_new_tokens=8))
    eng.run_until_idle()
    assert t.finish_reason in ("stop", "length"), t.error
    assert len(t.new_tokens) <= 8

    # and the stream matches chunk=1 on the same inputs
    monkeypatch.setenv("ROOM_TPU_DECODE_CHUNK", "1")
    eng2 = make_engine(cfg, params, n_pages=32, page_size=16,
                       max_seq_len=64)
    t2 = eng2.submit(list(range(1, 57)),
                     sampling=SamplingParams(temperature=0.0,
                                             max_new_tokens=8))
    eng2.run_until_idle()
    assert t.new_tokens == t2.new_tokens


def test_chunked_decode_finishes_under_pool_pressure(engine_setup,
                                                     monkeypatch):
    """A turn with few tokens left and room in its current page must
    finish even when the pool is empty (regression: chunk over-ensure)."""
    cfg, params = engine_setup
    monkeypatch.setenv("ROOM_TPU_DECODE_CHUNK", "8")
    # pool: scratch + 2 usable pages of 16 = 32 tokens capacity
    eng = make_engine(cfg, params, n_pages=3, page_size=16,
                      max_seq_len=32)
    t = eng.submit([5, 4, 3],
                   sampling=SamplingParams(temperature=0.0,
                                           max_new_tokens=4))
    eng.run_until_idle()
    assert t.finish_reason in ("stop", "length"), t.error


def test_batched_prefill_matches_sequential(engine_setup):
    """Simultaneously queued same-bucket turns prefill together; greedy
    results must equal one-at-a-time admission."""
    cfg, params = engine_setup
    sp = SamplingParams(temperature=0.0, max_new_tokens=5)
    prompts = [[3, 1, 4], [1, 5, 9, 2], [6, 5], [3, 5, 8, 9, 7]]

    eng_seq = make_engine(cfg, params, max_batch=1)  # forces 1-by-1
    seq = []
    for p in prompts:
        t = eng_seq.submit(p, sampling=sp)
        eng_seq.run_until_idle()
        seq.append(t.new_tokens)

    eng_bat = make_engine(cfg, params, max_batch=4)
    turns = [eng_bat.submit(p, sampling=sp) for p in prompts]
    eng_bat.run_until_idle()
    assert [t.new_tokens for t in turns] == seq
    # all four shared one grouped prefill (bucket 16 x batch 4)
    phases = eng_bat.stats()["phases"]
    assert any("x4" in k for k in phases), phases


def test_pallas_prefill_probe_gates_kernel(monkeypatch):
    """The S>1 prefill kernel only routes traffic after a one-shot
    compile + numerics smoke (ADVICE r3): a kernel that fails to lower
    OR returns wrong numbers pins the engine to the XLA gather."""
    from room_tpu.ops import paged_attention as pa
    from room_tpu.serving import kv_pages

    real = pa.paged_attention_prefill

    # lowering failure -> fallback (the real CPU pallas error also
    # lands here)
    monkeypatch.setattr(kv_pages, "_PREFILL_PROBE", {})

    def boom(*a, **k):
        raise RuntimeError("mosaic lowering failed")

    monkeypatch.setattr(pa, "paged_attention_prefill", boom)
    assert kv_pages.pallas_prefill_ok(4, 2, 64, 8) is False

    # compiles but wrong numerics -> fallback
    monkeypatch.setattr(kv_pages, "_PREFILL_PROBE", {})
    monkeypatch.setattr(
        pa, "paged_attention_prefill",
        lambda q, *a, **k: jnp.zeros_like(q),
    )
    assert kv_pages.pallas_prefill_ok(4, 2, 64, 8) is False

    # the real kernel (interpret mode stands in for hardware) passes
    # the numerics check -> kernel allowed
    monkeypatch.setattr(kv_pages, "_PREFILL_PROBE", {})
    monkeypatch.setattr(
        pa, "paged_attention_prefill",
        lambda *a, **k: real(*a, **{**k, "interpret": True}),
    )
    assert kv_pages.pallas_prefill_ok(4, 2, 64, 8) is True

    # env force wins in both directions, no probe
    monkeypatch.setattr(kv_pages, "_PREFILL_PROBE", {})
    monkeypatch.setenv("ROOM_TPU_PREFILL_KERNEL", "off")
    assert kv_pages.pallas_prefill_ok(32, 4, 128, 16) is False
    monkeypatch.setenv("ROOM_TPU_PREFILL_KERNEL", "on")
    assert kv_pages.pallas_prefill_ok(32, 4, 128, 16) is True
