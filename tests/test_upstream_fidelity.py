"""Upstream-architecture fidelity: REAL transformers checkpoints (tiny,
generated in-test on CPU torch) → ``save_pretrained`` safetensors →
``utils/convert.py`` → forward logits and *served* greedy tokens
cross-checked against the transformers reference implementation.

This proves convert→serve fidelity on upstream tensor names/layouts and
upstream *math* (rope, GQA, qk-norm, MoE routing, BERT pooling), not
just against the in-house init tree (VERDICT r2 #3; the reference pins
exact model behavior: src/shared/local-model.ts:3-5)."""

import dataclasses

import jax
import numpy as np
import pytest

from room_tpu.models import qwen3
from room_tpu.models.config import DecoderConfig, EncoderConfig
from room_tpu.serving import SamplingParams, ServingEngine
from room_tpu.utils.convert import convert_hf_decoder, convert_hf_encoder

torch = pytest.importorskip("torch")


def _f32_tree(params):
    return jax.tree.map(lambda x: np.asarray(x, np.float32), params)


def _served_greedy(cfg, params, prompt, n_new, eos_id):
    eng = ServingEngine(
        cfg, _f32_tree(params), max_batch=2, page_size=8, n_pages=32,
        stop_token_ids=[eos_id],
    )
    turn = eng.submit(
        list(prompt),
        sampling=SamplingParams(temperature=0.0, max_new_tokens=n_new),
    )
    eng.run_until_idle()
    return turn.new_tokens


def test_qwen2_checkpoint_logits_and_served_tokens(tmp_path):
    """Qwen2 architecture (the qwen2.5-72b queen family: GQA + qkv bias,
    no qk-norm)."""
    from transformers import Qwen2Config, Qwen2ForCausalLM

    hf_cfg = Qwen2Config(
        vocab_size=128, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=256,
        rope_theta=10000.0, rms_norm_eps=1e-6,
        tie_word_embeddings=False, eos_token_id=127, bos_token_id=126,
    )
    torch.manual_seed(0)
    model = Qwen2ForCausalLM(hf_cfg).eval()
    model.save_pretrained(str(tmp_path))

    has_bias = model.model.layers[0].self_attn.q_proj.bias is not None
    cfg = DecoderConfig(
        name="hf-qwen2-tiny", vocab_size=128, hidden=64, n_layers=2,
        n_heads=4, n_kv_heads=2, head_dim=16, intermediate=96,
        rope_theta=10000.0, rms_eps=1e-6, qkv_bias=has_bias,
        qk_norm=False, dtype="float32", max_seq_len=256,
    )
    params = convert_hf_decoder(str(tmp_path), cfg, dtype="float32")

    prompt = [3, 17, 42, 9, 88, 5]
    ids = torch.tensor([prompt])
    with torch.no_grad():
        want = model(ids).logits.numpy()[0]
    got, _ = qwen3.forward(
        _f32_tree(params), cfg, np.asarray([prompt], np.int32)
    )
    np.testing.assert_allclose(
        np.asarray(got)[0], want, rtol=2e-3, atol=2e-3
    )

    with torch.no_grad():
        hf_out = model.generate(
            ids, max_new_tokens=8, do_sample=False,
            eos_token_id=127, pad_token_id=0,
        )[0].tolist()[len(prompt):]
    served = _served_greedy(cfg, params, prompt, 8, eos_id=127)
    assert served[: len(hf_out)] == hf_out


def test_qwen3moe_checkpoint_logits_and_served_tokens(tmp_path):
    """Qwen3-MoE architecture — the qwen3-coder-30b flagship family:
    GQA + per-head qk RMSNorm + softmax-topk expert routing."""
    from transformers import Qwen3MoeConfig, Qwen3MoeForCausalLM

    hf_cfg = Qwen3MoeConfig(
        vocab_size=128, hidden_size=64, intermediate_size=96,
        moe_intermediate_size=32, num_experts=8, num_experts_per_tok=2,
        decoder_sparse_step=1, mlp_only_layers=[],
        norm_topk_prob=True, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, head_dim=16,
        max_position_embeddings=256, rope_theta=10000.0,
        rms_norm_eps=1e-6, tie_word_embeddings=False,
        eos_token_id=127, bos_token_id=126,
        router_aux_loss_coef=0.0,
    )
    torch.manual_seed(1)
    model = Qwen3MoeForCausalLM(hf_cfg).eval()
    model.save_pretrained(str(tmp_path))

    cfg = DecoderConfig(
        name="hf-qwen3moe-tiny", vocab_size=128, hidden=64, n_layers=2,
        n_heads=4, n_kv_heads=2, head_dim=16, intermediate=0,
        rope_theta=10000.0, rms_eps=1e-6, qkv_bias=False, qk_norm=True,
        n_experts=8, top_k=2, moe_intermediate=32, norm_topk_prob=True,
        dtype="float32", max_seq_len=256,
    )
    params = convert_hf_decoder(str(tmp_path), cfg, dtype="float32")

    prompt = [11, 4, 99, 23, 56]
    ids = torch.tensor([prompt])
    with torch.no_grad():
        want = model(ids).logits.numpy()[0]
    got, _ = qwen3.forward(
        _f32_tree(params), cfg, np.asarray([prompt], np.int32)
    )
    np.testing.assert_allclose(
        np.asarray(got)[0], want, rtol=2e-3, atol=2e-3
    )

    with torch.no_grad():
        hf_out = model.generate(
            ids, max_new_tokens=8, do_sample=False,
            eos_token_id=127, pad_token_id=0,
        )[0].tolist()[len(prompt):]
    served = _served_greedy(cfg, params, prompt, 8, eos_id=127)
    assert served[: len(hf_out)] == hf_out


def test_bert_checkpoint_embeddings_match_transformers(tmp_path):
    """BERT/MiniLM encoder family (the 384-d memory embedder): converted
    weights must reproduce transformers' mean-pooled, L2-normalized
    sentence vectors (the all-MiniLM-L6-v2 recipe the reference ran via
    ONNX; src/shared/embeddings.ts:33-100)."""
    from transformers import BertConfig, BertModel

    from room_tpu.models import embedder

    hf_cfg = BertConfig(
        vocab_size=100, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=64, type_vocab_size=2,
        hidden_act="gelu", layer_norm_eps=1e-12,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
    )
    torch.manual_seed(2)
    model = BertModel(hf_cfg).eval()
    model.save_pretrained(str(tmp_path))

    cfg = EncoderConfig(
        name="hf-bert-tiny", vocab_size=100, hidden=32, n_layers=2,
        n_heads=4, intermediate=64, max_positions=64,
        layer_norm_eps=1e-12,
    )
    params = convert_hf_encoder(str(tmp_path), cfg)

    tokens = np.array([[5, 6, 7, 8, 9], [11, 12, 13, 0, 0]], np.int32)
    mask = np.array(
        [[1, 1, 1, 1, 1], [1, 1, 1, 0, 0]], np.float32
    )
    with torch.no_grad():
        hidden = model(
            input_ids=torch.tensor(tokens, dtype=torch.long),
            attention_mask=torch.tensor(mask, dtype=torch.long),
        ).last_hidden_state.numpy()
    m = mask[:, :, None]
    pooled = (hidden * m).sum(1) / np.maximum(m.sum(1), 1e-9)
    want = pooled / np.maximum(
        np.linalg.norm(pooled, axis=-1, keepdims=True), 1e-9
    )

    cfg32 = dataclasses.replace(cfg, dtype="float32")
    got = embedder.encode(params, cfg32, tokens, mask)
    np.testing.assert_allclose(
        np.asarray(got), want, rtol=2e-4, atol=2e-4
    )
