"""Multi-step decode pipeline (docs/serving.md).

Greedy decode must be byte-identical across dispatch-window depths
(ROOM_TPU_DECODE_STEPS_PER_DISPATCH in {1, 2, 4}) through every
disruptive path the engine has — mid-window stops, park+requeue,
prefix-cache hits, KV-offload hibernate/restore — because the window
only changes WHEN the host learns about tokens, never which tokens the
model samples. A decode_window fault must fail exactly the turns in the
faulted window and leak no KV pages. Quick tier: runs in the ci.yml
chaos job.
"""

import threading

import jax
import numpy as np
import pytest

from room_tpu.models import qwen3, tiny_moe
from room_tpu.serving import SamplingParams, ServingEngine, faults

STEPS = (1, 2, 4)


@pytest.fixture(scope="module")
def model():
    cfg = tiny_moe()
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture()
def build(model, monkeypatch):
    cfg, params = model

    def make(steps, **kw):
        monkeypatch.setenv(
            "ROOM_TPU_DECODE_STEPS_PER_DISPATCH", str(steps)
        )
        kw.setdefault("max_batch", 4)
        kw.setdefault("page_size", 8)
        kw.setdefault("n_pages", 96)
        return ServingEngine(cfg, params, **kw)

    return make


def _greedy(n=8):
    return SamplingParams(temperature=0.0, max_new_tokens=n)


def test_identity_mid_window_stop_and_resume(build):
    """A turn whose budget (7) never lands on a window boundary (2, 4)
    stops mid-window; the trimmed stream and the parked session's
    resume must match the step-at-a-time engine exactly."""
    streams = {}
    for steps in STEPS:
        eng = build(steps)
        sp = _greedy(7)
        a = eng.submit([4, 8, 15], session_id="s", sampling=sp)
        eng.run_until_idle()
        b = eng.submit([16, 23], session_id="s", sampling=sp)
        eng.run_until_idle()
        streams[steps] = (a.new_tokens, b.new_tokens)
        if steps > 1:
            assert eng.stats()["decode_windows"] >= 1
    assert streams[2] == streams[1]
    assert streams[4] == streams[1]


def test_identity_across_park_requeue(build):
    """The stall watchdog parks+requeues mid-stream; the pipeline's
    late-reconciled park (discovered one window after dispatch) must
    still resume from the pending token with zero divergence."""
    base = None
    for steps in STEPS:
        eng = build(steps)
        clean = eng.submit([9, 8, 7], sampling=_greedy(12))
        eng.run_until_idle()
        if base is None:
            base = clean.new_tokens
        assert clean.new_tokens == base
        eng.step_stall_s = 0.05
        faults.inject("decode_stall", latency_s=0.2, times=2)
        turn = eng.submit([9, 8, 7], sampling=_greedy(12))
        eng.run_until_idle()
        faults.clear()
        eng.step_stall_s = 120.0
        assert turn.finish_reason in ("stop", "length")
        assert turn.requeues >= 1 and turn.disrupted
        assert turn.new_tokens == base, f"steps={steps}"


def test_identity_prefix_cache_hit(build):
    """The second session references the first's cached page-aligned
    prefix instead of re-prefilling; its stream must be window-depth
    invariant."""
    prefix = list(range(1, 25))          # 24 tokens = 3 aligned pages
    base = None
    for steps in STEPS:
        eng = build(steps)
        t1 = eng.submit(prefix + [31, 32, 33], sampling=_greedy(6))
        eng.run_until_idle()             # registers the prefix
        t2 = eng.submit(prefix + [41, 42], sampling=_greedy(6))
        eng.run_until_idle()             # block-table hit
        assert eng.stats()["prefix_hits"] >= 1
        got = (t1.new_tokens, t2.new_tokens)
        if base is None:
            base = got
        assert got == base, f"steps={steps}"


def test_identity_kv_offload_restore(build):
    """Hibernate a parked session to the host tier and resume it: the
    restored-KV continuation must match across window depths."""
    base = None
    for steps in STEPS:
        eng = build(steps, offload=True)
        t1 = eng.submit(list(range(1, 20)), session_id="h",
                        sampling=_greedy(6))
        eng.run_until_idle()
        assert eng.offload_session("h")
        t2 = eng.submit([5, 6, 7], session_id="h", sampling=_greedy(6))
        eng.run_until_idle()
        assert eng.stats()["offload_restores"] >= 1
        got = (t1.new_tokens, t2.new_tokens)
        if base is None:
            base = got
        assert got == base, f"steps={steps}"


def test_pipeline_serve_forever_matches_sync(build):
    """The threaded loop (with its shutdown flush) produces the same
    streams as the synchronous legacy engine."""
    prompts = [[3, 1, 4], [1, 5, 9], [2, 6]]
    eng_sync = build(1)
    want = []
    for p in prompts:
        t = eng_sync.submit(p, sampling=_greedy(8))
        eng_sync.run_until_idle()
        want.append(t.new_tokens)

    eng = build(4)
    stop = threading.Event()
    loop = threading.Thread(
        target=eng.serve_forever, args=(stop,), daemon=True
    )
    loop.start()
    turns = [eng.submit(p, sampling=_greedy(8)) for p in prompts]
    for t in turns:
        assert t.done.wait(300), "turn timed out"
    stop.set()
    loop.join(60)
    assert not loop.is_alive()
    assert [t.new_tokens for t in turns] == want


def test_identity_penalized_rows(build):
    """Penalty counts ride the scan carry on device; a penalized turn's
    stream must be window-depth invariant too (the count array must not
    absorb pad tokens from masked lanes or overshoot double-counts)."""
    sp = SamplingParams(temperature=0.0, max_new_tokens=9,
                        frequency_penalty=1e9)
    base = None
    for steps in STEPS:
        eng = build(steps)
        t = eng.submit([1, 2, 3], sampling=sp)
        eng.run_until_idle()
        if base is None:
            base = t.new_tokens
        assert t.new_tokens == base, f"steps={steps}"
        body = t.new_tokens[:-1] if t.finish_reason == "stop" \
            else t.new_tokens
        assert len(set(body)) == len(body)


def test_decode_window_fault_fails_only_window(build, monkeypatch):
    """An injected decode_window fault fails exactly the turns in the
    faulted window: queued turns still complete, the engine stays
    healthy (no crash-supervisor reset), and no KV page leaks."""
    monkeypatch.setenv("ROOM_TPU_PREFIX_CACHE_PAGES", "0")
    eng = build(4, max_batch=2)
    faults.inject("decode_window", times=1, transient=False)
    turns = [
        eng.submit([i + 1, i + 2, i + 3], session_id=f"w{i}",
                   sampling=_greedy(6))
        for i in range(4)
    ]
    eng.run_until_idle()
    failed = [t for t in turns if t.finish_reason == "error"]
    ok = [t for t in turns if t.finish_reason in ("stop", "length")]
    assert len(failed) == 2 and len(ok) == 2
    assert all("decode_window" in (t.error or "") for t in failed)
    st = eng.stats()
    assert st["window_faults"] == 1
    assert st["healthy"] is True and st["engine_crashes"] == 0
    for i in range(4):
        eng.release_session(f"w{i}")
    assert eng.page_table.free_pages == eng.page_table.n_pages - 1
    assert not eng.sessions


def test_degraded_reservation_parks_instead_of_corrupting(build,
                                                          monkeypatch):
    """Pool pressure can grant a window a single token of KV headroom
    while the scan still runs `steps` steps: the drain must accept only
    the durably-written tokens and park+requeue on the last one (the
    pending-token contract) — never book tokens whose KV landed on the
    scratch page. Stream stays identical to the legacy engine."""
    monkeypatch.setenv("ROOM_TPU_PREFIX_CACHE_PAGES", "0")
    e1 = build(1)
    base = e1.submit([7, 7, 3], sampling=_greedy(12))
    e1.run_until_idle()

    eng = build(4)
    turn = eng.submit([7, 7, 3], sampling=_greedy(12))
    eng.step()                    # admit + dispatch window 1
    # the next window's reservation hits an allocation failure, no
    # relief available -> _reserve_slot degrades to a 1-token grant
    faults.inject("kv_alloc", times=1)
    eng.run_until_idle()
    faults.clear()
    assert turn.finish_reason in ("stop", "length")
    assert turn.requeues >= 1 and turn.disrupted
    assert turn.new_tokens == base.new_tokens


def test_spec_no_draft_does_not_disable_pipeline(build):
    """spec_tokens>0 on non-repetitive traffic (nothing draftable) must
    not flush the pipeline every iteration: the empty-draft probe arms
    the spec cooldown, and dispatch windows keep rolling between
    probes."""
    eng = build(4, spec_tokens=4)
    t = eng.submit(list(range(1, 9)), sampling=_greedy(24))
    eng.run_until_idle()
    st = eng.stats()
    assert t.finish_reason in ("stop", "length")
    # windows actually dispatched (the pipeline ran) even though the
    # spec gate kept probing and never found a draft
    assert st["decode_windows"] >= 2, st
    assert st["steps_per_dispatch"] == 4


def test_decode_window_fault_preserves_previous_window_tokens(
        build, monkeypatch):
    """A fault discovered at dispatch k must not discard window k-1's
    already-computed tokens: the previous window drains to the stream
    (callbacks, history, length) BEFORE the faulted window's turn
    fails — the fault's blast radius is exactly one window."""
    monkeypatch.setenv("ROOM_TPU_PREFIX_CACHE_PAGES", "0")
    eng = build(4)
    got = []
    turn = eng.submit([1, 2, 3], sampling=_greedy(64),
                      on_token=got.append)
    eng.step()                 # admit + dispatch window 1 (in flight)
    assert eng.stats()["decode_windows"] == 1
    faults.inject("decode_window", times=1, transient=False)
    eng.step()                 # window 2 faults; window 1 drains first
    assert turn.finish_reason == "error"
    assert "decode_window" in turn.error
    # the prefill token + all 4 tokens window 1 really computed
    assert len(turn.new_tokens) == 5
    assert got == turn.new_tokens
    eng.release_session(turn.session_id)
    assert eng.page_table.free_pages == eng.page_table.n_pages - 1


def test_pipeline_stats_surface(build):
    eng = build(4)
    t = eng.submit([1, 2, 3], sampling=_greedy(9))
    eng.run_until_idle()
    st = eng.stats()
    assert t.finish_reason in ("stop", "length")
    assert st["steps_per_dispatch"] == 4
    assert st["decode_windows"] >= 2
    assert st["host_stall_ms"] > 0.0
    assert st["overshoot_tokens"] >= 0


def test_greedy_argmax_tie_break_index_ordered():
    """The stable greedy rule: lowest index wins inside the tie band,
    the true argmax wins outside it — so cross-mesh reduction-order
    noise can never flip a near-tie two different ways (ROADMAP
    CPU-mesh determinism item)."""
    import jax.numpy as jnp

    from room_tpu.serving.sampler import GREEDY_TIE_EPS, greedy_argmax

    logits = jnp.asarray([
        [0.0, 1.0, 1.0, 0.5],
        [0.0, 1.0 - GREEDY_TIE_EPS / 2, 1.0, 0.5],
        [0.0, 1.0 - GREEDY_TIE_EPS * 4, 1.0, 0.5],
    ], jnp.float32)
    got = np.asarray(greedy_argmax(logits))
    assert got.tolist() == [1, 1, 2]
