"""Durable process lifecycle suite (docs/lifecycle.md).

Pins the restart contract end to end on the CPU backend:

- a drained-then-restarted engine resumes a parked greedy session with
  output TOKEN-IDENTICAL to an uninterrupted run — through the
  byte-exact KV spool (manifest + checksummed spool file adopted into
  the offload store) AND through every degraded fallback (no offload
  store, corrupt manifest, truncated spool, model-config mismatch):
  the fallbacks re-prefill from the manifest's token history, trading
  compute, never correctness;
- SIGTERM mid-decode-window loses no durably-streamed tokens: the
  shutdown flush books the in-flight window, the drain parks on the
  last sampled token, and the resumed stream continues exactly where
  the interrupted one stopped;
- the drain is BOUNDED: a wedged offload_io/shutdown_io fault or a
  blown ROOM_TPU_DRAIN_DEADLINE_S abandons remaining KV copies to the
  manifest's intent record instead of blocking the exit;
- spool hygiene: orphan files from dead processes are swept
  (age-thresholded, manifest-referenced files protected);
- the clean-shutdown marker round-trips, and its absence routes the
  next boot through journal crash recovery (docs/swarm_recovery.md).
"""

import json
import os
import threading
import time

import jax
import numpy as np
import pytest

from room_tpu.models import qwen3, tiny_moe
from room_tpu.serving import (
    SamplingParams, ServingEngine, faults, lifecycle,
)
from room_tpu.serving.kv_offload import TieredKVStore, _write_spool


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture(scope="module")
def model():
    cfg = tiny_moe()
    params = qwen3.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


@pytest.fixture()
def make_engine(model, monkeypatch, tmp_path):
    """Engine factory: prefix cache off so every session's KV is
    spoolable (shared prefixes legitimately re-prefill), offload spool
    under tmp_path, and NO stop tokens — greedy streams always run to
    their budget, so mid-stream interruption points are controllable."""
    monkeypatch.setenv("ROOM_TPU_PREFIX_CACHE_PAGES", "0")
    monkeypatch.setenv("ROOM_TPU_OFFLOAD_DIR", str(tmp_path / "spool"))
    cfg, params = model

    def build(**kw):
        kw.setdefault("max_batch", 4)
        kw.setdefault("page_size", 8)
        kw.setdefault("n_pages", 96)
        kw.setdefault("offload", True)
        kw.setdefault("stop_token_ids", [])
        return ServingEngine(cfg, params, **kw)

    return build


@pytest.fixture()
def lc_dir(tmp_path):
    return str(tmp_path / "lifecycle")


def _greedy(n=8):
    return SamplingParams(temperature=0.0, max_new_tokens=n)


PROMPT = list(range(1, 20))
CONT = [7, 7, 7]


def _control_streams(make_engine, n1=8, n2=8):
    """Uninterrupted two-turn reference streams."""
    ctrl = make_engine(offload=False)
    c1 = ctrl.submit(PROMPT, session_id="s", sampling=_greedy(n1))
    ctrl.run_until_idle()
    c2 = ctrl.submit(CONT, session_id="s", sampling=_greedy(n2))
    ctrl.run_until_idle()
    return c1.new_tokens, c2.new_tokens


# ---- drain manifest shape ----

def test_drain_writes_versioned_checksummed_manifest(make_engine, lc_dir):
    eng = make_engine()
    eng.submit(PROMPT, session_id="s", sampling=_greedy())
    eng.run_until_idle()
    summary = eng.drain(lc_dir)
    assert summary["manifest_written"] and summary["sessions_spooled"] == 1
    assert eng.lifecycle_phase == "draining"

    with open(os.path.join(lc_dir, lifecycle.MANIFEST_NAME)) as f:
        m = json.load(f)
    assert m["version"] == lifecycle.MANIFEST_VERSION
    assert m["generation"] == 1
    assert m["fingerprint"]["page_size"] == 8
    (entry,) = m["sessions"]
    sess_len = entry["length"]
    assert entry["id"] == "s"
    assert len(entry["history"]) == sess_len
    assert entry["pending"] is not None
    assert entry["generation"] == 1
    kv = entry["kv"]
    path = os.path.join(lc_dir, kv["file"])
    assert os.path.getsize(path) == kv["nbytes"]
    assert lifecycle.file_sha256(path) == kv["sha256"]
    assert kv["own_tokens"] == sess_len

    # a second drain bumps the manifest generation (rolling restarts
    # can tell stale state from fresh)
    eng2 = make_engine()
    eng2.submit(PROMPT, session_id="t", sampling=_greedy())
    eng2.run_until_idle()
    eng2.drain(lc_dir)
    with open(os.path.join(lc_dir, lifecycle.MANIFEST_NAME)) as f:
        assert json.load(f)["generation"] == 2

    # ... and the counter survives the restore CONSUMING the manifest
    # (the per-dir state sidecar carries it), so it genuinely counts
    # rolling restarts instead of resetting to 1 each cycle
    eng3 = make_engine()
    eng3.restore_from_manifest(lc_dir)
    assert not os.path.exists(
        os.path.join(lc_dir, lifecycle.MANIFEST_NAME)
    )
    eng3.drain(lc_dir)
    with open(os.path.join(lc_dir, lifecycle.MANIFEST_NAME)) as f:
        assert json.load(f)["generation"] == 3


def test_submit_while_draining_sheds_with_503_contract(make_engine):
    eng = make_engine()
    eng.begin_drain()
    t = eng.submit(PROMPT, session_id="x", sampling=_greedy())
    assert t.done.is_set() and t.shed and t.finish_reason == "error"
    # routes map turn.shed to 503 + Retry-After (PR 1 ladder plumbing)


# ---- THE acceptance canary: warm restart token identity ----

def test_drain_restart_resumes_token_identical(make_engine, lc_dir):
    c1, c2 = _control_streams(make_engine)

    a = make_engine()
    t1 = a.submit(PROMPT, session_id="s", sampling=_greedy())
    a.run_until_idle()
    assert t1.new_tokens == c1
    assert a.drain(lc_dir)["sessions_spooled"] == 1

    b = make_engine()
    restored = b.restore_from_manifest(lc_dir)
    assert restored == {"resumed": 1, "reprefill": 0, "skipped": 0,
                        "manifest": True}
    assert b.sessions["s"].parked
    t2 = b.submit(CONT, session_id="s", sampling=_greedy())
    b.run_until_idle()
    st = b.stats()
    assert st["offload_restores"] == 1, \
        "warm restart must restore spooled KV, not re-prefill"
    assert st["lifecycle"]["sessions_resumed"] == 1
    assert t2.new_tokens == c2, (
        "restart round trip changed the greedy stream"
    )
    # consumed: a second boot must not resurrect stale sessions
    assert not os.path.exists(
        os.path.join(lc_dir, lifecycle.MANIFEST_NAME)
    )


def test_restart_without_offload_store_reprefills_identical(
    make_engine, lc_dir,
):
    _, c2 = _control_streams(make_engine)
    a = make_engine()
    a.submit(PROMPT, session_id="s", sampling=_greedy())
    a.run_until_idle()
    a.drain(lc_dir)

    b = make_engine(offload=False)   # no store to adopt KV into
    restored = b.restore_from_manifest(lc_dir)
    assert restored["resumed"] == 0 and restored["reprefill"] == 1
    t2 = b.submit(CONT, session_id="s", sampling=_greedy())
    b.run_until_idle()
    assert b.stats()["lifecycle"]["sessions_reprefill"] == 1
    assert t2.new_tokens == c2


def test_sigterm_mid_decode_window_loses_no_streamed_tokens(
    make_engine, lc_dir,
):
    """Acceptance: interrupt serve_forever mid-stream (pipelined
    dispatch windows in flight), drain, restart, resume — the streamed
    prefix plus the resumed stream equals the uninterrupted run."""
    budget = 32
    ctrl = make_engine(offload=False)
    full = ctrl.submit(PROMPT, session_id="s", sampling=_greedy(budget))
    ctrl.run_until_idle()
    assert len(full.new_tokens) == budget   # no stop tokens configured

    eng = make_engine()
    eng.steps_per_dispatch = 4
    stop = threading.Event()
    seen: list[int] = []

    def on_token(tok):
        seen.append(tok)
        if len(seen) == 3:
            stop.set()   # SIGTERM lands mid-window

    t1 = eng.submit(PROMPT, session_id="s",
                    sampling=_greedy(budget), on_token=on_token)
    thread = threading.Thread(target=eng.serve_forever, args=(stop,))
    thread.start()
    thread.join(timeout=60)
    assert not thread.is_alive()
    summary = eng.drain(lc_dir)
    assert summary["sessions_total"] == 1
    # the shutdown flush booked every dispatched token: the failed
    # turn's stream IS the durable prefix
    assert t1.shed and t1.new_tokens == seen
    assert 3 <= len(seen) < budget, "interruption must be mid-stream"
    assert seen == full.new_tokens[: len(seen)]

    b = make_engine()
    b.steps_per_dispatch = 4
    assert b.restore_from_manifest(lc_dir)["manifest"]
    t2 = b.submit([], session_id="s",
                  sampling=_greedy(budget - len(seen)))
    b.run_until_idle()
    assert seen + t2.new_tokens == full.new_tokens, (
        "restart dropped or duplicated streamed tokens"
    )


def test_disk_cap_overflow_keeps_warmest_and_counts_honestly(
    make_engine, lc_dir, monkeypatch,
):
    """Review hardening: when the manifest's spooled bytes exceed the
    restoring engine's disk cap, the WARMEST session must keep its
    byte-exact KV (adoption runs coldest-first so the overflow evicts
    cold entries) and the resumed/reprefill counts must reflect what
    actually survived — never claim warmth the store no longer holds.
    Both sessions stay token-identical either way."""
    ctrl = make_engine(offload=False)
    expect = {}
    for sid in ("cold", "warm"):
        ctrl.submit(PROMPT, session_id=sid, sampling=_greedy())
        ctrl.run_until_idle()
        t = ctrl.submit(CONT, session_id=sid, sampling=_greedy())
        ctrl.run_until_idle()
        expect[sid] = t.new_tokens

    eng = make_engine()
    for sid in ("cold", "warm"):   # "warm" submitted last = warmest
        eng.submit(PROMPT, session_id=sid, sampling=_greedy())
        eng.run_until_idle()
    summary = eng.drain(lc_dir)
    assert summary["sessions_spooled"] == 2
    sizes = [os.path.getsize(os.path.join(lc_dir, f))
             for f in os.listdir(lc_dir) if f.endswith(".kvspool")]
    assert len(sizes) == 2 and sizes[0] == sizes[1]
    # cap fits exactly one spool
    monkeypatch.setenv("ROOM_TPU_OFFLOAD_DISK_MB",
                       str(sizes[0] * 1.5 / (1024 * 1024)))
    b = make_engine()
    restored = b.restore_from_manifest(lc_dir)
    assert restored == {"resumed": 1, "reprefill": 1, "skipped": 0,
                        "manifest": True}
    assert b.offload_store.has("warm"), \
        "disk-cap overflow must evict the coldest session, not the warmest"
    assert not b.offload_store.has("cold")
    for sid in ("cold", "warm"):
        t = b.submit(CONT, session_id=sid, sampling=_greedy())
        b.run_until_idle()
        assert t.new_tokens == expect[sid], sid


def test_unquiesced_drain_spools_nothing_but_keeps_history(
    make_engine, lc_dir,
):
    """Review hardening: when the serve thread failed to join
    (ModelHost.shutdown's wedged path), drain must not flush the
    pipeline or gather KV from under a possibly-live loop —
    ``drain(deadline_s=0, flush=False)`` records history-only entries
    and the restart re-prefills token-identical."""
    _, c2 = _control_streams(make_engine)
    eng = make_engine()
    eng.submit(PROMPT, session_id="s", sampling=_greedy())
    eng.run_until_idle()
    summary = eng.drain(lc_dir, deadline_s=0.0, flush=False)
    assert summary["sessions_spooled"] == 0
    assert summary["sessions_abandoned"] == 1
    assert summary["manifest_written"]
    assert not [f for f in os.listdir(lc_dir)
                if f.endswith(".kvspool")], \
        "no KV may be gathered from an unquiesced engine"

    b = make_engine()
    assert b.restore_from_manifest(lc_dir)["reprefill"] == 1
    t2 = b.submit(CONT, session_id="s", sampling=_greedy())
    b.run_until_idle()
    assert t2.new_tokens == c2


def test_restore_entry_keeps_live_drain_visible_throughout(
    make_engine, lc_dir, monkeypatch,
):
    """lockmap regression (lock-guarded-write on lifecycle_phase): the
    restore entry path used to snapshot the phase and write 'warming'
    WITHOUT the engine lock — an engine already draining had its phase
    overwritten for the whole restore (re-opening the draining check
    at admission) and a begin_drain landing inside the unlocked window
    was clobbered outright. Restore on a draining engine must leave
    'draining' visible at every point of the scan and at exit."""
    eng = make_engine()
    eng.begin_drain()
    assert eng.lifecycle_phase == "draining"
    seen = []
    orig = eng._restore_dir

    def spy(d, summary, adopted):
        seen.append(eng.lifecycle_phase)
        return orig(d, summary, adopted)

    monkeypatch.setattr(eng, "_restore_dir", spy)
    eng.restore_from_manifest(lc_dir)
    assert seen and all(p == "draining" for p in seen), seen
    assert eng.lifecycle_phase == "draining"


def test_drain_byte_copies_disk_tier_spool(
    make_engine, lc_dir, monkeypatch,
):
    """A disk-tier hibernated session drains via a streaming byte copy
    (the file is already in spool format — no parse, no full-KV RAM
    residency inside the deadline) and still restores
    token-identical."""
    monkeypatch.setenv("ROOM_TPU_OFFLOAD_HOST_MB", "0")  # disk tier
    _, c2 = _control_streams(make_engine)
    eng = make_engine()
    eng.submit(PROMPT, session_id="s", sampling=_greedy())
    eng.run_until_idle()
    eng.sessions["s"].parked = True      # as a </tool_call> stop does
    assert eng.offload_session("s")
    assert eng.offload_store.tier_of("s") == "disk"
    assert eng.offload_store.spool_copy_source("s") is not None
    summary = eng.drain(lc_dir)
    assert summary["sessions_spooled"] == 1

    b = make_engine()
    assert b.restore_from_manifest(lc_dir)["resumed"] == 1
    t2 = b.submit(CONT, session_id="s", sampling=_greedy())
    b.run_until_idle()
    assert t2.new_tokens == c2


def test_release_during_drain_defers_until_after_spool(
    make_engine, lc_dir,
):
    """Review hardening: HTTP route threads are still finishing (the
    API stops after the drain — that's where the 503s come from) and
    their finally-blocks call release_session. During drain() the
    drain thread claims loop-thread ownership, so a racing release
    defers to the command queue instead of popping self.sessions out
    from under the spool loop; it applies on the way out."""
    eng = make_engine()
    eng.submit(PROMPT, session_id="s", sampling=_greedy())
    eng.run_until_idle()
    gate = threading.Event()
    entered = threading.Event()
    real = eng._spool_session_kv

    def slow_spool(sess, d):
        entered.set()
        gate.wait(10)
        return real(sess, d)

    eng._spool_session_kv = slow_spool
    out: dict = {}
    th = threading.Thread(
        target=lambda: out.update(s=eng.drain(lc_dir))
    )
    th.start()
    assert entered.wait(10), "drain never reached the spool loop"
    eng.release_session("s")   # a route thread's finally, mid-drain
    assert "s" in eng.sessions, "release must defer during the drain"
    gate.set()
    th.join(20)
    assert not th.is_alive()
    assert out["s"]["manifest_written"]
    assert "s" not in eng.sessions, \
        "the deferred release applies before the manifest lands"
    with open(os.path.join(lc_dir, lifecycle.MANIFEST_NAME)) as f:
        m = json.load(f)
    assert [e["id"] for e in m["sessions"]] == [], \
        "a released session must not be resurrected by the next boot"
    assert out["s"]["sessions_spooled"] == 0


def test_graceful_stop_marks_clean_with_lifecycle_disabled(
    tmp_path, monkeypatch,
):
    """Review hardening: ROOM_TPU_LIFECYCLE=0 disables drains and
    manifests, but the boot-side marker check runs unconditionally —
    so the graceful path must still stamp the marker, or every clean
    stop of a lifecycle-disabled deployment reads as a crash."""
    import room_tpu.providers.tpu as tpu_mod
    from room_tpu.db import Database
    from room_tpu.server import runtime as rt_mod
    from room_tpu.server.app import start_server

    monkeypatch.setenv("ROOM_TPU_LIFECYCLE", "0")
    monkeypatch.setenv("ROOM_TPU_LIFECYCLE_DIR",
                       str(tmp_path / "root"))
    monkeypatch.setenv("ROOM_TPU_MCP_AUTOREGISTER", "0")
    rt_mod._runtime = None
    app = start_server(port=0, db=Database(":memory:"))
    try:
        app.stop(graceful=True)
    finally:
        rt_mod._runtime = None
        tpu_mod.reset_model_hosts()
    assert lifecycle.consume_clean_marker() == "clean"


def test_drain_window_blocks_cold_engine_builds():
    """Review hardening: between begin_drain_model_hosts and process
    exit, a straggler request must get a ProviderError (routes map it
    to 503 + Retry-After) instead of cold-building a fresh engine —
    that engine's restore would consume the manifest the drain just
    wrote and then die un-drained at exit behind a clean marker."""
    from room_tpu.providers import tpu as tpu_provider
    from room_tpu.providers.base import ProviderError

    tpu_provider.reset_model_hosts()
    tpu_provider.begin_drain_model_hosts()
    try:
        with pytest.raises(ProviderError, match="draining"):
            tpu_provider.get_model_host("tiny-moe").engine()
    finally:
        tpu_provider.reset_model_hosts()
    assert not tpu_provider._draining


def test_second_signal_escalates_past_wedged_drain(tmp_path):
    """Review hardening: the SIGTERM handler takes the graceful path
    once; a second signal while the drain is wedged must restore the
    default disposition and kill the process — an operator's repeated
    Ctrl-C can never be swallowed forever."""
    import signal
    import subprocess
    import sys

    code = (
        "import sys, time\n"
        f"sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})\n"
        "from room_tpu.server.runtime import "
        "install_lifecycle_signal_handlers\n"
        "install_lifecycle_signal_handlers(lambda: time.sleep(120))\n"
        "print('ready', flush=True)\n"
        "time.sleep(120)\n"
    )
    env = dict(os.environ, PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu")
    p = subprocess.Popen(
        [sys.executable, "-c", code],
        stdout=subprocess.PIPE, text=True, env=env,
    )
    try:
        assert p.stdout.readline().strip() == "ready"
        p.send_signal(signal.SIGTERM)   # enters the wedged drain
        time.sleep(0.5)
        p.send_signal(signal.SIGTERM)   # must escalate
        rc = p.wait(timeout=20)
    finally:
        p.kill()
    assert rc == -signal.SIGTERM, (
        f"second SIGTERM did not terminate the process (rc={rc})"
    )


# ---- degraded fallbacks: never a crash, always the full history ----

def test_corrupt_manifest_cold_starts_cleanly(make_engine, lc_dir):
    os.makedirs(lc_dir)
    with open(os.path.join(lc_dir, lifecycle.MANIFEST_NAME), "w") as f:
        f.write('{"version": 1, "sessions": [{"id":')   # truncated
    b = make_engine()
    restored = b.restore_from_manifest(lc_dir)
    assert restored == {"resumed": 0, "reprefill": 0, "skipped": 0,
                        "manifest": False}
    assert b.stats()["lifecycle"]["manifest_errors"] == 1
    assert b.sessions == {} and b.lifecycle_phase == "serving"


def test_truncated_spool_file_falls_back_to_reprefill(
    make_engine, lc_dir,
):
    _, c2 = _control_streams(make_engine)
    a = make_engine()
    a.submit(PROMPT, session_id="s", sampling=_greedy())
    a.run_until_idle()
    a.drain(lc_dir)
    with open(os.path.join(lc_dir, lifecycle.MANIFEST_NAME)) as f:
        kv = json.load(f)["sessions"][0]["kv"]
    with open(os.path.join(lc_dir, kv["file"]), "r+b") as f:
        f.truncate(kv["nbytes"] // 2)   # size validation now fails

    b = make_engine()
    restored = b.restore_from_manifest(lc_dir)
    assert restored["resumed"] == 0 and restored["reprefill"] == 1
    t2 = b.submit(CONT, session_id="s", sampling=_greedy())
    b.run_until_idle()
    assert t2.new_tokens == c2


def test_bitflipped_spool_caught_lazily_stays_token_identical(
    make_engine, lc_dir,
):
    """The manifest's sha256 is verified at the first spool READ, not
    at boot (restore stays a metadata scan): a same-size corruption is
    adopted, then the first restore attempt fails the checksum and
    degrades to the re-prefill miss path — output still
    token-identical."""
    _, c2 = _control_streams(make_engine)
    a = make_engine()
    a.submit(PROMPT, session_id="s", sampling=_greedy())
    a.run_until_idle()
    a.drain(lc_dir)
    with open(os.path.join(lc_dir, lifecycle.MANIFEST_NAME)) as f:
        kv = json.load(f)["sessions"][0]["kv"]
    with open(os.path.join(lc_dir, kv["file"]), "r+b") as f:
        f.seek(kv["nbytes"] - 8)
        (b0,) = f.read(1)
        f.seek(kv["nbytes"] - 8)
        f.write(bytes([b0 ^ 0xFF]))   # size unchanged: boot can't see it

    b = make_engine()
    restored = b.restore_from_manifest(lc_dir)
    assert restored["resumed"] == 1, "lazy check: adoption succeeds"
    t2 = b.submit(CONT, session_id="s", sampling=_greedy())
    b.run_until_idle()
    assert t2.new_tokens == c2
    assert b.offload_store._stats["spool_errors"] >= 1, \
        "the corruption must be caught at first read"
    assert not b.offload_store.has("s")


def test_model_config_mismatch_falls_back_to_reprefill(
    make_engine, lc_dir,
):
    _, c2 = _control_streams(make_engine)
    a = make_engine()
    a.submit(PROMPT, session_id="s", sampling=_greedy())
    a.run_until_idle()
    a.drain(lc_dir)

    # page geometry changed across the restart: the spooled pages no
    # longer line up — KV must be rejected, history must still resume
    b = make_engine(page_size=16, n_pages=48)
    restored = b.restore_from_manifest(lc_dir)
    assert restored["resumed"] == 0 and restored["reprefill"] == 1
    t2 = b.submit(CONT, session_id="s", sampling=_greedy())
    b.run_until_idle()
    assert t2.new_tokens == c2


# ---- bounded drain ----

def test_wedged_offload_io_cannot_stall_shutdown(
    make_engine, lc_dir, monkeypatch,
):
    """Satellite: a wedged KV copy (permanent offload_io with latency)
    burns at most one firing before the deadline abandons the rest —
    the drain returns promptly and every session's history still rides
    the manifest, so the restart loses nothing but warmth."""
    monkeypatch.setenv("ROOM_TPU_DRAIN_DEADLINE_S", "0.2")
    _, c2 = _control_streams(make_engine)
    eng = make_engine()
    for i, sid in enumerate(("s", "t", "u")):
        eng.submit(PROMPT, session_id=sid, sampling=_greedy())
        eng.run_until_idle()
    faults.inject("offload_io", latency_s=0.5, transient=False)
    t0 = time.monotonic()
    summary = eng.drain(lc_dir)
    elapsed = time.monotonic() - t0
    faults.clear()
    assert elapsed < 3.0, f"drain stalled for {elapsed:.1f}s"
    assert summary["sessions_total"] == 3
    assert summary["sessions_spooled"] == 0
    assert summary["sessions_fallback"] + \
        summary["sessions_abandoned"] == 3
    assert summary["sessions_abandoned"] >= 1
    assert summary["manifest_written"]
    with open(os.path.join(lc_dir, lifecycle.MANIFEST_NAME)) as f:
        m = json.load(f)
    assert {e["id"] for e in m["sessions"]} == {"s", "t", "u"}
    assert set(m["abandoned"]) <= {"s", "t", "u"}

    b = make_engine()
    restored = b.restore_from_manifest(lc_dir)
    assert restored["reprefill"] == 3
    t2 = b.submit(CONT, session_id="s", sampling=_greedy())
    b.run_until_idle()
    assert t2.new_tokens == c2


def test_shutdown_io_chaos_burst(make_engine, tmp_path):
    """Manifest/spool I/O failing 50% of the time across repeated
    drain->restore->continue rounds: phases always settle, streams stay
    greedy-identical whether each round restored KV or re-prefilled,
    and nothing ever raises out of the lifecycle layer."""
    rounds = 4
    ctrl = make_engine(offload=False)
    ctrl.submit(PROMPT, session_id="s", sampling=_greedy())
    ctrl.run_until_idle()
    expected = []
    for i in range(rounds):
        c = ctrl.submit(CONT, session_id="s", sampling=_greedy())
        ctrl.run_until_idle()
        expected.append(c.new_tokens)

    eng = make_engine()
    eng.submit(PROMPT, session_id="s", sampling=_greedy())
    eng.run_until_idle()
    faults.inject("shutdown_io", probability=0.5, seed=7)
    for i in range(rounds):
        d = str(tmp_path / f"burst{i}")
        eng.drain(d)
        eng = make_engine()
        eng.restore_from_manifest(d)
        if "s" not in eng.sessions:
            # the manifest write itself failed this round: warmth is
            # lost, correctness of LATER rounds can't be compared —
            # rebuild the session from scratch and keep hammering
            faults.clear(); eng.submit(PROMPT, session_id="s",
                                       sampling=_greedy())
            eng.run_until_idle()
            for c in expected[:i + 1]:
                t = eng.submit(CONT, session_id="s",
                               sampling=_greedy())
                eng.run_until_idle()
                assert t.new_tokens == c
            faults.inject("shutdown_io", probability=0.5, seed=7 + i)
            continue
        t = eng.submit(CONT, session_id="s", sampling=_greedy())
        eng.run_until_idle()
        assert t.new_tokens == expected[i], f"round {i} diverged"
        assert eng.lifecycle_phase == "serving"
        assert eng.healthy
    faults.clear()


# ---- spool hygiene ----

def test_orphan_spool_sweep_is_age_thresholded_and_manifest_aware(
    tmp_path,
):
    d = str(tmp_path)
    old = os.path.join(d, "dead.kvspool")
    partial = os.path.join(d, "crashed.kvspool.tmp")
    fresh = os.path.join(d, "fresh.kvspool")
    kept = os.path.join(d, "kept.kvspool")
    for p in (old, partial, fresh, kept):
        with open(p, "wb") as f:
            f.write(b"x")
    stale_t = time.time() - 7200
    os.utime(old, (stale_t, stale_t))
    os.utime(partial, (stale_t, stale_t))
    os.utime(kept, (stale_t, stale_t))
    with open(os.path.join(d, lifecycle.MANIFEST_NAME), "w") as f:
        json.dump({"version": 1, "sessions": [
            {"id": "k", "history": [1], "kv": {"file": "kept.kvspool"}},
        ]}, f)

    removed = lifecycle.sweep_orphans(d, max_age_s=3600)
    assert removed == 2
    assert not os.path.exists(old), "aged orphan must be swept"
    assert not os.path.exists(partial), \
        "crash-interrupted .tmp partials must be swept too"
    assert os.path.exists(fresh), "fresh files survive (racing drain)"
    assert os.path.exists(kept), "manifest-referenced files survive"


def test_sweep_protects_everything_when_manifest_unreadable(tmp_path):
    """Review hardening: a manifest that is PRESENT but unreadable
    (transient I/O error, armed shutdown_io fault) has an unknown
    protected set — the sweep must delete NOTHING rather than destroy
    still-referenced warm-restart data. 'A failed read cold-starts',
    it never destroys."""
    d = str(tmp_path)
    spool = os.path.join(d, "referenced.kvspool")
    with open(spool, "wb") as f:
        f.write(b"x")
    stale_t = time.time() - 7200
    os.utime(spool, (stale_t, stale_t))
    with open(os.path.join(d, lifecycle.MANIFEST_NAME), "w") as f:
        f.write("{not json")

    assert lifecycle.sweep_orphans(d, max_age_s=3600) == 0
    assert os.path.exists(spool)

    # same protection when the read fails via the fault point
    with open(os.path.join(d, lifecycle.MANIFEST_NAME), "w") as f:
        json.dump({"version": 1, "sessions": []}, f)
    faults.inject("shutdown_io", times=1)
    assert lifecycle.sweep_orphans(d, max_age_s=3600) == 0
    faults.clear()
    assert os.path.exists(spool)

    # a READABLE manifest that no longer references the file sweeps it
    assert lifecycle.sweep_orphans(d, max_age_s=3600) == 1
    assert not os.path.exists(spool)


def test_sweep_skips_live_pid_owned_spools(tmp_path):
    """A SHARED offload dir holds live sibling engines' hibernated
    sessions: the boot sweep must never delete a PID-tagged spool whose
    owner process is still alive, whatever its age — while a dead
    owner's files sweep normally past the threshold."""
    import subprocess
    import sys

    d = str(tmp_path)
    live = os.path.join(d, f"pid{os.getpid()}-aaaa.kvspool")
    child = subprocess.Popen([sys.executable, "-c", "pass"])
    child.wait()
    dead = os.path.join(d, f"pid{child.pid}-bbbb.kvspool")
    untagged = os.path.join(d, "legacy.kvspool")
    for p in (live, dead, untagged):
        with open(p, "wb") as f:
            f.write(b"x")
        stale_t = time.time() - 7200
        os.utime(p, (stale_t, stale_t))

    assert lifecycle.spool_owner_pid(live) == os.getpid()
    assert lifecycle.spool_owner_pid(untagged) is None
    removed = lifecycle.sweep_orphans(d, max_age_s=3600)
    assert removed == 2
    assert os.path.exists(live), "live sibling's spool must survive"
    assert not os.path.exists(dead), "dead owner's spool is swept"
    assert not os.path.exists(untagged), "untagged aged file is swept"


def test_store_init_sweeps_shared_spool_dir(tmp_path, monkeypatch):
    """Satellite: a durable (shared) ROOM_TPU_OFFLOAD_DIR no longer
    leaks dead processes' spool files forever — store construction
    sweeps aged orphans."""
    d = tmp_path / "spool"
    d.mkdir()
    orphan = d / "leak.kvspool"
    _write_spool(str(orphan), {"k": np.zeros((1, 4))})
    stale_t = time.time() - 7200
    os.utime(orphan, (stale_t, stale_t))
    monkeypatch.setenv("ROOM_TPU_SPOOL_SWEEP_AGE_S", "3600")
    TieredKVStore(spool_dir=str(d))
    assert not orphan.exists()


def test_adopt_retags_spool_with_owner_pid(tmp_path):
    """Review hardening: a drain spool keeps its untagged name through
    adoption unless adopt() re-tags it — and untagged files in a shared
    engine dir are only age-protected, so a blue/green sibling's boot
    sweep could delete a live engine's disk-tier session after the age
    threshold. adopt() must rename the file to pid<self>-<name>."""
    d = tmp_path / "engines"
    d.mkdir()
    path = d / "abcd.kvspool"
    _write_spool(str(path), {"k": np.ones((2, 4), np.float32)})
    nbytes = path.stat().st_size
    store = TieredKVStore(spool_dir=str(tmp_path / "spool"))
    assert store.adopt("s", str(path), 8, 1, nbytes)
    tagged = d / f"pid{os.getpid()}-abcd.kvspool"
    assert tagged.exists() and not path.exists()
    stale_t = time.time() - 7200
    os.utime(tagged, (stale_t, stale_t))
    # no manifest protects it any more — only the live PID tag does
    assert lifecycle.sweep_orphans(str(d), max_age_s=0.0) == 0
    assert tagged.exists()


def test_marker_withheld_when_drain_fails(tmp_path, monkeypatch):
    """Review hardening: a graceful stop whose engine drain did NOT
    land its manifest must not stamp the shutdown clean — the next
    boot has real losses to report, not a green pill."""
    import room_tpu.providers.tpu as tpu_mod
    from room_tpu.db import Database
    from room_tpu.server import runtime as rt_mod
    from room_tpu.server.app import start_server

    root = str(tmp_path / "root")
    monkeypatch.setenv("ROOM_TPU_LIFECYCLE_DIR", root)
    monkeypatch.setenv("ROOM_TPU_MCP_AUTOREGISTER", "0")
    monkeypatch.setattr(
        tpu_mod, "drain_model_hosts",
        lambda: {"tiny-moe": {"manifest_written": False,
                              "error": "drain failed"}},
    )
    rt_mod._runtime = None
    app = start_server(port=0, db=Database(":memory:"))
    try:
        app.stop(graceful=True)
    finally:
        rt_mod._runtime = None
        # the monkeypatched drain skipped the real host teardown; clear
        # the module _draining flag so later tests can cold-build
        tpu_mod.reset_model_hosts()
    assert not os.path.exists(
        os.path.join(root, lifecycle.MARKER_NAME)
    ), "a failed drain must withhold the clean-shutdown marker"
    assert lifecycle.consume_clean_marker() == "crash"


def test_same_process_restart_after_graceful_stop(tmp_path, monkeypatch):
    """Review hardening: a graceful stop must leave the module state
    restartable — the build bar lifts once teardown completes (so a
    same-process start_server() can cold-build engines again) and the
    new incarnation must not report the previous server's drain
    summary in /api/tpu/health as its own."""
    import room_tpu.providers.tpu as tpu_mod
    from room_tpu.db import Database
    from room_tpu.server import runtime as rt_mod
    from room_tpu.server.app import start_server

    monkeypatch.setenv("ROOM_TPU_LIFECYCLE_DIR", str(tmp_path / "root"))
    monkeypatch.setenv("ROOM_TPU_MCP_AUTOREGISTER", "0")
    rt_mod._runtime = None
    app = start_server(port=0, db=Database(":memory:"))
    try:
        app.stop(graceful=True)
    finally:
        rt_mod._runtime = None
    assert not tpu_mod._draining, \
        "graceful stop must re-open engine builds once torn down"
    assert rt_mod.lifecycle_snapshot()["drain"] is not None
    # second incarnation, same process: boot reads the clean marker
    # and starts with fresh drain telemetry
    app2 = start_server(port=0, db=Database(":memory:"))
    try:
        snap = rt_mod.lifecycle_snapshot()
        assert snap["last_shutdown"] == "clean"
        assert snap["drain"] is None
        assert snap["drain_ms"] is None
    finally:
        app2.stop()
        rt_mod._runtime = None
        tpu_mod.reset_model_hosts()


# ---- clean-shutdown marker + journal crash recovery ----

def test_clean_marker_roundtrip(tmp_path, monkeypatch):
    root = str(tmp_path / "root")
    monkeypatch.setenv("ROOM_TPU_LIFECYCLE_DIR", root)
    assert lifecycle.consume_clean_marker() == "first_boot"
    lifecycle.record_boot()
    assert lifecycle.write_clean_marker()
    assert lifecycle.consume_clean_marker() == "clean"
    # marker is consume-once: the NEXT boot without a fresh marker is
    # a crash (prior state exists)
    assert lifecycle.consume_clean_marker() == "crash"


def test_marker_write_survives_shutdown_io_fault(tmp_path, monkeypatch):
    monkeypatch.setenv("ROOM_TPU_LIFECYCLE_DIR", str(tmp_path / "r"))
    faults.inject("shutdown_io", times=1)
    assert lifecycle.write_clean_marker() is False   # degraded, no raise
    assert lifecycle.write_clean_marker() is True


def test_crash_boot_routes_through_journal_recovery(db, tmp_path,
                                                    monkeypatch):
    """Crash (no marker) -> the journal path recovers interrupted
    work; clean drain (marker) -> recovery finds nothing. The swarm
    side of the restart contract (docs/swarm_recovery.md)."""
    from room_tpu.core import journal, rooms, workers

    monkeypatch.setenv("ROOM_TPU_LIFECYCLE_DIR", str(tmp_path / "lr"))
    room = rooms.create_room(db, "hive", worker_model="echo",
                             create_wallet=False)
    queen = workers.get_worker(db, room["queen_worker_id"])
    cycle_id = db.insert(
        "INSERT INTO worker_cycles(worker_id, room_id, status) "
        "VALUES (?,?,'running')",
        (queen["id"], room["id"]),
    )
    journal.record_started(db, "cycle", cycle_id, room_id=room["id"],
                           worker_id=queen["id"])
    lifecycle.record_boot()            # a previous life existed…
    assert lifecycle.consume_clean_marker() == "crash"   # …no marker
    summary = journal.recover(db)
    assert summary["cycles"] == 1
    row = db.query_one("SELECT status, error_message FROM "
                       "worker_cycles WHERE id=?", (cycle_id,))
    assert row["status"] == "error"
    assert "recovered" in row["error_message"]
    # the clean path: marker present, nothing open, recovery is a no-op
    lifecycle.write_clean_marker()
    assert lifecycle.consume_clean_marker() == "clean"
    assert journal.recover(db) == {"cycles": 0, "task_runs": 0,
                                   "effects_flagged": 0, "closed": 0}


def test_health_route_reports_lifecycle(make_engine, monkeypatch):
    """/api/tpu/health carries the process phase + per-engine
    lifecycle blocks the TPU panel renders."""
    import room_tpu.providers.tpu as tpu_mod
    from room_tpu.server.router import RequestContext, Router
    from room_tpu.server.routes import register_all_routes
    from room_tpu.server.runtime import set_lifecycle_phase

    eng = make_engine()
    eng.submit(PROMPT, session_id="s", sampling=_greedy())
    eng.run_until_idle()

    class FakeHost:
        _engine = eng

        @staticmethod
        def is_healthy():
            return True

    monkeypatch.setattr(tpu_mod, "_hosts", {"tiny-moe": FakeHost()})
    set_lifecycle_phase("serving")
    router = Router()
    register_all_routes(router)
    handler, params = router.match("GET", "/api/tpu/health")
    out = handler(RequestContext(
        method="GET", path="/api/tpu/health", params=params, query={},
        body=None,
    ))
    data = out["data"]
    assert data["lifecycle"]["phase"] == "serving"
    assert "last_shutdown" in data["lifecycle"]
    row = data["engines"]["tiny-moe"]
    assert row["lifecycle"]["phase"] == "serving"
    assert "sessions_resumed" in row["lifecycle"]
    assert "drain_ms" in row["lifecycle"]
