"""Aux subsystem tests: templates, prompt sync, identity, watches,
telemetry, supervisor, tpu manager, commentary, notifications, native
top-k."""

import os
import signal
import subprocess
import time

import numpy as np
import pytest

from room_tpu.core import (
    escalations, memory, messages, rooms, supervisor, telemetry, watches,
    workers,
)
from room_tpu.core.identity import (
    build_register_calldata, get_identity, metadata_data_uri,
    register_room_identity,
)
from room_tpu.core.prompt_sync import (
    export_worker_prompts, import_worker_prompts,
)
from room_tpu.core.templates import (
    ROOM_TEMPLATES, WORKER_TEMPLATES, instantiate_room_template,
)
from room_tpu.providers import get_model_provider, reset_provider_cache
from room_tpu.server.commentary import CommentaryEngine
from room_tpu.server.notifications import collect_pending, relay_pending
from room_tpu.server.tpu_manager import (
    apply_tpu_model_to_all, get_tpu_status, model_weight_bytes,
)
from room_tpu.utils.native import native_available, topk_cosine


# ---- templates ----

def test_room_template_builds_full_team(db):
    room = instantiate_room_template(db, "saas-builder",
                                     worker_model="echo")
    team = workers.list_room_workers(db, room["id"])
    # queen + 4 template workers
    assert len(team) == 5
    roles = {w["role"] for w in team}
    assert {"queen", "researcher", "executor", "guardian"} <= roles


def test_unknown_template_raises(db):
    with pytest.raises(KeyError):
        instantiate_room_template(db, "nope")
    assert len(ROOM_TEMPLATES) >= 3 and len(WORKER_TEMPLATES) >= 6


# ---- prompt sync ----

def test_prompt_export_import_roundtrip(db, tmp_path, monkeypatch):
    monkeypatch.setenv("ROOM_TPU_DATA_DIR", str(tmp_path))
    room = rooms.create_room(db, "r", create_wallet=False)
    paths = export_worker_prompts(db, room["id"])
    assert len(paths) == 1 and os.path.exists(paths[0])

    # edit the file (newer mtime than the DB row) and re-import
    with open(paths[0]) as f:
        text = f.read()
    assert text.startswith("---\n")
    new_text = text.rsplit("---\n", 1)[0] + "---\n\nEDITED PROMPT\n"
    time.sleep(0.01)
    with open(paths[0], "w") as f:
        f.write(new_text)
    future = time.time() + 5
    os.utime(paths[0], (future, future))
    out = import_worker_prompts(db, room["id"])
    assert out["applied"], out
    queen = workers.get_worker(db, room["queen_worker_id"])
    assert queen["system_prompt"] == "EDITED PROMPT"

    # stale file (older than db) is skipped unless forced
    past = time.time() - 3600
    os.utime(paths[0], (past, past))
    with open(paths[0], "w") as f:
        f.write(text.rsplit("---\n", 1)[0] + "---\n\nSTALE\n")
    os.utime(paths[0], (past, past))
    out = import_worker_prompts(db, room["id"])
    assert not out["applied"]
    out = import_worker_prompts(db, room["id"], force=True)
    assert out["applied"]


# ---- identity ----

def test_identity_calldata_and_metadata(db):
    room = rooms.create_room(db, "chainy", goal="do things")
    ident = get_identity(db, room["id"])
    assert ident["address"].startswith("0x")
    assert not ident["registered"]
    out = register_room_identity(db, room["id"], dry_run=True)
    tx = out["tx"]
    assert tx["data"].startswith("0x")
    uri = metadata_data_uri(out["metadata"])
    assert uri.startswith("data:application/json;base64,")
    # calldata embeds the uri
    assert uri.encode().hex() in tx["data"]


# ---- watches ----

def test_watch_path_validation():
    assert watches.validate_watch_path("~/projects") is None
    assert watches.validate_watch_path("/tmp/x") is None
    assert watches.validate_watch_path("/etc/passwd") is not None
    assert watches.validate_watch_path("~/.ssh/id_rsa") is not None


def test_watch_fires_task_on_change(db, tmp_path):
    target = tmp_path / "watched.txt"
    target.write_text("v1")
    # tmp_path is under /tmp on this host
    wid = watches.create_watch(
        db, str(target), "summarize the change",
    )
    rt = watches.WatchRuntime(db)
    assert rt.poll_once() == 0        # baseline pass
    time.sleep(0.01)
    target.write_text("v2 changed")
    assert rt.poll_once() == 1
    w = db.query_one("SELECT * FROM watches WHERE id=?", (wid,))
    assert w["trigger_count"] == 1
    task = db.query_one("SELECT * FROM tasks ORDER BY id DESC LIMIT 1")
    assert "watched.txt" in task["name"]
    assert rt.poll_once() == 0        # no re-fire without change


# ---- telemetry ----

def test_telemetry_disabled_without_token(db, monkeypatch):
    monkeypatch.delenv("ROOM_TPU_TELEMETRY_TOKEN", raising=False)
    assert not telemetry.telemetry_enabled()
    assert not telemetry.submit_crash_report(db, ValueError("x"))
    assert not telemetry.submit_heartbeat(db)
    assert len(telemetry.get_machine_id()) == 12


# ---- supervisor ----

def test_supervisor_tree_kill():
    # parent shell that spawns a child sleep
    proc = supervisor.spawn_managed(
        ["/bin/sh", "-c", "sleep 300 & wait"], label="test-tree"
    )
    time.sleep(0.3)
    descendants = supervisor._descendants(proc.pid)
    assert descendants, "child sleep not found"
    n = supervisor.terminate_managed_processes(grace_s=1.0)
    assert n == 1
    time.sleep(0.2)
    assert not supervisor._alive(proc.pid)
    assert proc.pid not in supervisor.managed_processes()
    proc.wait(timeout=5)


# ---- tpu manager ----

def test_tpu_status_gate(monkeypatch):
    monkeypatch.delenv("ROOM_TPU_CKPT_DIR", raising=False)
    monkeypatch.delenv("ROOM_TPU_ALLOW_RANDOM_INIT", raising=False)
    st = get_tpu_status("qwen3-coder-30b")
    names = {c["name"] for c in st["checks"]}
    assert {"accelerator", "hbm", "disk", "weights"} <= names
    weights = next(c for c in st["checks"] if c["name"] == "weights")
    assert not weights["ok"]          # fail-closed without a checkpoint
    st2 = get_tpu_status("tiny-moe")
    assert st2["ready"]
    # the 30B MoE weight estimate lands in a sane range (50-70 GB bf16)
    gb = model_weight_bytes("qwen3-coder-30b") / 1e9
    assert 40 < gb < 80


def test_capacity_planner_refuses_72b_bf16(monkeypatch):
    """BASELINE config #5 arithmetic (VERDICT r2 #6): qwen2.5-72b bf16
    is ~145 GB of weights — it must NEVER be placed on 4 (or even 8)
    v5e chips at bf16; int8 fits a v5e-8 submesh."""
    from room_tpu.server.tpu_manager import plan_mesh, plan_placement

    p4 = plan_placement("qwen2.5-72b", 4, "bf16", hbm_per_chip_gb=16.0)
    assert not p4["fits"]
    assert p4["weight_gb"] > 120
    # int8 doesn't rescue 4 chips either: the suggestion is more chips
    assert p4["suggestion"].startswith("chips>=")

    p8 = plan_placement(
        "qwen2.5-72b", 8, "bf16",
        kv_tokens=65_536, hbm_per_chip_gb=16.0,
    )
    assert not p8["fits"]
    assert p8["suggestion"] == "int8"
    assert plan_placement(
        "qwen2.5-72b", 8, "int8",
        kv_tokens=65_536, hbm_per_chip_gb=16.0,
    )["fits"]

    # the 30B worker at bf16 fits 8 chips with a large page pool
    assert plan_placement(
        "qwen3-coder-30b", 8, "bf16", hbm_per_chip_gb=16.0
    )["fits"]

    # hetero pod: 72b-int8 queen + 30b-bf16 workers on disjoint
    # submeshes of a 16-chip pod
    mesh = plan_mesh(
        [
            {"model": "qwen2.5-72b", "chips": 8, "quant": "int8",
             "kv_tokens": 65_536},
            {"model": "qwen3-coder-30b", "chips": 8},
        ],
        total_chips=16, hbm_per_chip_gb=16.0,
    )
    assert mesh["ok"] and mesh["chips_used"] == 16
    # same placements on one v5e-8: refused (submeshes exceed the pod)
    assert not plan_mesh(
        [
            {"model": "qwen2.5-72b", "chips": 8, "quant": "int8",
             "kv_tokens": 65_536},
            {"model": "qwen3-coder-30b", "chips": 8},
        ],
        total_chips=8, hbm_per_chip_gb=16.0,
    )["ok"]

    with pytest.raises(ValueError):
        plan_placement("qwen2.5-72b", 8, "fp4")
    with pytest.raises(ValueError):
        plan_placement("nonexistent-model", 8)


def test_apply_tpu_model_to_all(db):
    r1 = rooms.create_room(db, "a", create_wallet=False)
    r2 = rooms.create_room(db, "b", create_wallet=False)
    out = apply_tpu_model_to_all(db, "qwen3-coder-30b")
    assert out["rooms"] == 2 and out["queens"] == 2
    assert rooms.get_room(db, r1["id"])["worker_model"] == \
        "tpu:qwen3-coder-30b"
    assert messages.get_setting(db, "clerk_model") == \
        "tpu:qwen3-coder-30b"


# ---- commentary + notifications ----

def test_commentary_narrates_buffered_events(db):
    reset_provider_cache()
    echo = get_model_provider("echo")
    echo.responses.append("The queen delegates — the hive is buzzing!")
    engine = CommentaryEngine(db, model="echo")
    engine._on_event(type("E", (), {
        "type": "cycle:log", "channel": "cycle:1",
        "data": {"entry_type": "assistant", "content": "planning..."},
    })())
    text = engine.narrate_once()
    assert text == "The queen delegates — the hive is buzzing!"
    row = db.query_one(
        "SELECT * FROM clerk_messages WHERE role='commentary'"
    )
    assert row is not None
    usage = db.query_one(
        "SELECT * FROM clerk_usage WHERE source='commentary'"
    )
    assert usage is not None
    # empty buffer -> no narration
    assert engine.narrate_once() is None


def test_notification_digest_and_cursors(db):
    room = rooms.create_room(db, "hive", create_wallet=False)
    escalations.create_escalation(db, room["id"], "need budget approval")
    messages.add_chat_message(db, room["id"], "assistant",
                              "weekly summary ready")
    digest = relay_pending(db)
    assert "escalation" in digest and "weekly summary" in digest
    # cursors advanced: nothing new -> no digest
    assert relay_pending(db) is None
    escalations.create_escalation(db, room["id"], "urgent: prod down")
    pending = collect_pending(db)
    assert pending["urgent"]
    assert relay_pending(db) is not None


# ---- native ----

def test_native_topk_matches_numpy():
    rng = np.random.default_rng(3)
    M = rng.standard_normal((500, 64)).astype(np.float32)
    q = rng.standard_normal(64).astype(np.float32)
    idx, scores = topk_cosine(M, q, 7)
    mn = M / np.linalg.norm(M, axis=1, keepdims=True)
    ref = np.argsort(-(mn @ (q / np.linalg.norm(q))))[:7]
    assert list(idx) == list(ref)
    assert native_available() in (True, False)  # works either way
    # degenerate cases
    i2, s2 = topk_cosine(np.zeros((0, 8), np.float32), q[:8], 3)
    assert len(i2) == 0


def test_watch_denies_nested_protected_paths():
    assert watches.validate_watch_path(
        "~/.config/gcloud/application_default_credentials.json"
    ) is not None
    assert watches.validate_watch_path("~/.ssh/config") is not None
    assert watches.validate_watch_path("~/.config/someapp/ok.txt") is None


def test_provision_dedupes_running_sessions():
    from room_tpu.server import tpu_manager

    a = tpu_manager.start_provision_session("tiny-dense")
    b = tpu_manager.start_provision_session("tiny-dense")
    assert a == b  # second request joins the running session
    for _ in range(200):
        s = tpu_manager.get_provision_session(a)
        if s["status"] != "running":
            break
        time.sleep(0.1)
    from room_tpu.providers.tpu import reset_model_hosts
    reset_model_hosts()
