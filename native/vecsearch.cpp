// Native vector-search core — the in-tree equivalent of the reference's
// sqlite-vec C extension (reference dependency: vec_distance_cosine used
// by src/shared/db-queries.ts:995-1010). Serves the host-side recall
// path when the device index is cold; the TPU path lives in
// room_tpu/serving/embed_service.py.
//
// Build: make -C native   (g++ -O3 -march=native -shared -fPIC)
// Bind:  ctypes from room_tpu/utils/native.py — no pybind11 needed.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <vector>

extern "C" {

// Cosine top-k: matrix [n, d] row-major, query [d]. Writes k indices and
// scores (descending). Returns the number of results (<= k).
int topk_cosine(const float* matrix, int64_t n, int64_t d,
                const float* query, int k,
                int32_t* out_idx, float* out_score) {
    if (n <= 0 || d <= 0 || k <= 0) return 0;

    double qnorm = 0.0;
    for (int64_t j = 0; j < d; ++j) qnorm += (double)query[j] * query[j];
    qnorm = std::sqrt(qnorm) + 1e-9;

    struct Hit { float score; int32_t idx; };
    std::vector<Hit> hits;
    hits.reserve(n);

    for (int64_t i = 0; i < n; ++i) {
        const float* row = matrix + i * d;
        double dot = 0.0, rnorm = 0.0;
        // simple loops: -O3 -march=native auto-vectorizes these
        for (int64_t j = 0; j < d; ++j) {
            dot += (double)row[j] * query[j];
            rnorm += (double)row[j] * row[j];
        }
        float score = (float)(dot / ((std::sqrt(rnorm) + 1e-9) * qnorm));
        hits.push_back({score, (int32_t)i});
    }

    int kk = (int)std::min<int64_t>(k, n);
    std::partial_sort(
        hits.begin(), hits.begin() + kk, hits.end(),
        [](const Hit& a, const Hit& b) { return a.score > b.score; });
    for (int i = 0; i < kk; ++i) {
        out_idx[i] = hits[i].idx;
        out_score[i] = hits[i].score;
    }
    return kk;
}

// Batched float32 blob pack/unpack helpers (BLOB <-> contiguous matrix).
void unpack_blobs(const uint8_t* blob, int64_t n, int64_t d,
                  float* out) {
    std::memcpy(out, blob, (size_t)n * d * sizeof(float));
}

int version() { return 1; }

}  // extern "C"
