# room-tpu server image (reference analogue: .github/workflows/docker.yml
# image). CPU base works everywhere; on TPU VMs the host-provided libtpu
# is picked up automatically by jax[tpu].
FROM python:3.12-slim

RUN apt-get update && apt-get install -y --no-install-recommends \
        g++ make curl \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /app
COPY setup.py pyproject.toml* README.md ./
COPY room_tpu ./room_tpu
COPY native ./native
COPY ui ./ui
COPY bench.py ./

# jax pinned CPU by default; install `jax[tpu]` in TPU deployments
RUN pip install --no-cache-dir \
        "jax>=0.9" optax orbax-checkpoint transformers safetensors \
        ml_dtypes cryptography \
    && pip install --no-cache-dir -e . \
    && make -C native

ENV ROOM_TPU_DATA_DIR=/data \
    ROOM_TPU_BIND_HOST=0.0.0.0 \
    ROOM_TPU_DEPLOYMENT_MODE=cloud
VOLUME /data
EXPOSE 3700

HEALTHCHECK --interval=30s --timeout=5s \
    CMD curl -fs http://127.0.0.1:3700/api/auth/handshake || exit 1

CMD ["python", "-m", "room_tpu", "serve", "--port", "3700"]
