#!/bin/sh
# room-tpu installer (reference analogue: the project's install.sh /
# platform installers): installs the package into the current Python
# environment, builds the native helpers, and registers the MCP server
# with installed AI clients on first `serve`.
set -eu

PYTHON="${PYTHON:-python3}"

echo "==> checking python"
"$PYTHON" -c 'import sys; assert sys.version_info >= (3, 10), \
    "python 3.10+ required"'

echo "==> installing room-tpu"
"$PYTHON" -m pip install -e .

echo "==> building native helpers"
if command -v make >/dev/null 2>&1 && command -v g++ >/dev/null 2>&1; then
    make -C native || echo "   (native build failed — the pure-JAX \
fallbacks will be used)"
else
    echo "   (make/g++ not found — skipping; pure-JAX fallbacks used)"
fi

echo "==> done"
echo "    start the server:  room-tpu serve"
echo "    open the dashboard: http://127.0.0.1:3700/"
echo "    TPU deployments:    pip install 'jax[tpu]' first"
