/* room_tpu service worker (reference: the SPA's PWA layer + the
   update-restart cleanup in its UI tests): cache-first for the static
   bundle, never caching /api or /ws, with the active server version
   PERSISTED in a meta cache — in-memory SW globals die whenever the
   browser reaps the idle worker, and lookups must only ever hit the
   current version's cache or an update-restart would serve the old
   bundle forever. */
"use strict";

const STATIC = ["/", "/app.js", "/panels.js", "/style.css",
                "/manifest.json", "/icon.svg"];
const META = "room-tpu-meta";

async function currentCacheName() {
  const meta = await caches.open(META);
  const hit = await meta.match("/__version");
  const v = hit ? await hit.text() : "v1";
  return "room-tpu-static-" + v;
}

async function setVersion(version) {
  const meta = await caches.open(META);
  await meta.put("/__version", new Response(String(version)));
  const keep = new Set([META, await currentCacheName()]);
  const keys = await caches.keys();
  await Promise.all(
    keys.filter((k) => !keep.has(k)).map((k) => caches.delete(k))
  );
}

self.addEventListener("install", (e) => {
  e.waitUntil(
    currentCacheName()
      .then((name) => caches.open(name))
      .then((c) => c.addAll(STATIC))
      .then(() => self.skipWaiting())
  );
});

self.addEventListener("activate", (e) => {
  e.waitUntil(
    currentCacheName().then(async (name) => {
      const keep = new Set([META, name]);
      const keys = await caches.keys();
      await Promise.all(
        keys.filter((k) => !keep.has(k)).map((k) => caches.delete(k))
      );
      await self.clients.claim();
    })
  );
});

self.addEventListener("message", (e) => {
  if (e.data && e.data.type === "version") {
    e.waitUntil
      ? e.waitUntil(setVersion(e.data.version))
      : setVersion(e.data.version);
  }
});

self.addEventListener("fetch", (e) => {
  const url = new URL(e.request.url);
  if (url.origin !== self.location.origin ||
      url.pathname.startsWith("/api") || url.pathname === "/ws" ||
      e.request.method !== "GET") {
    return; // live data / foreign origins never come from cache
  }
  e.respondWith((async () => {
    const cache = await caches.open(await currentCacheName());
    const hit = await cache.match(e.request);
    if (hit) {
      return hit;
    }
    const resp = await fetch(e.request);
    if (resp.ok && STATIC.includes(url.pathname)) {
      cache.put(e.request, resp.clone());
    }
    return resp;
  })());
});
